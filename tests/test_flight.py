"""Flight recorder, anomaly/straggler detection, SLO rules, and the XLA
retrace watchdog — including the end-to-end incident drill (fault-injected
stall → SLO breach → straggler flag on tracker /metrics → incident bundle
with a loadable Chrome trace naming the breached rule)."""

import json
import os
import socket
import time
import urllib.error
import urllib.request

import pytest

from dmlc_core_tpu.telemetry import anomaly, exposition, flight
from dmlc_core_tpu.telemetry import trace as teltrace
from dmlc_core_tpu.telemetry import xla_introspect
from dmlc_core_tpu.utils.faults import fault_point, inject_faults
from dmlc_core_tpu.utils.metrics import MetricsRegistry, metrics


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    """Fresh spans, fresh registry state for the names these tests touch,
    and a disarmed, rate-limit-free global recorder."""
    teltrace.recorder.clear()
    monkeypatch.setattr(flight.flight_recorder, "_dir", None)
    monkeypatch.setattr(flight.flight_recorder, "_min_interval", 0.0)
    monkeypatch.setattr(flight.flight_recorder, "_last_dump",
                        -float("inf"))
    flight.flight_recorder._snaps.clear()
    flight.flight_recorder._notes.clear()
    metrics.reset()
    yield
    teltrace.recorder.clear()
    metrics.reset()


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _assert_chrome_trace_valid(doc):
    """Schema-validate a Chrome trace-event JSON object (the contract
    Perfetto/chrome://tracing loads)."""
    assert isinstance(doc, dict)
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "b", "e", "i", "M")
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float))
            assert ev["dur"] >= 0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_incident_bundle_schema_golden(tmp_path):
    """The on-disk bundle layout and incident JSON schema are the
    operator contract — pin them."""
    rec = flight.FlightRecorder()
    rec._min_interval = 0.0
    rec.note("fault_injected", site="x.y")
    metrics.counter("drill.work").add(1)
    # two passes of the time-machine sampler bracket the second counter
    # bump so the bundle's timeline slice deterministically has series
    # (counter → rate needs a baseline sample plus a delta)
    from dmlc_core_tpu.telemetry import timeseries
    timeseries.history.sample_once()
    rec.note_snapshot()
    metrics.counter("drill.work").add(3)
    with teltrace.span("drill.step"):
        pass
    timeseries.history.sample_once()
    path = rec.arm(str(tmp_path)).dump("unit_test", why="golden")
    assert path is not None and os.path.isdir(path)
    assert sorted(os.listdir(path)) == ["critical_path.txt",
                                        "diagnosis.json", "diagnosis.txt",
                                        "incident.json", "log_tail.txt",
                                        "profile.txt", "timeline.json",
                                        "trace.json"]
    doc = json.load(open(os.path.join(path, "incident.json")))
    for key in ("schema", "reason", "detail", "ts", "pid", "host", "rank",
                "slo_spec", "fault_spec", "metrics", "metrics_delta",
                "notes", "span_count", "files"):
        assert key in doc, key
    assert doc["schema"] == flight.INCIDENT_SCHEMA == "dmlc.flight.incident/1"
    assert doc["reason"] == "unit_test"
    assert doc["detail"] == {"why": "golden"}
    assert doc["notes"][0]["kind"] == "fault_injected"
    assert doc["metrics"]["drill.work"]["value"] == 4
    # counter moved since the ring snapshot → it shows in the delta
    assert doc["metrics_delta"]["deltas"]["drill.work"] == 3
    # the incident carries the stacks that were running when it fired
    assert doc["files"]["profile"] == "profile.txt"
    # the time-machine evidence rides every bundle with data to show
    assert doc["files"]["timeline"] == "timeline.json"
    assert doc["files"]["critical_path"] == "critical_path.txt"
    # every bundle answers "what broke?" with the ranked suspect report
    assert doc["files"]["diagnosis"] == "diagnosis.json"
    assert doc["files"]["diagnosis_text"] == "diagnosis.txt"
    ddoc = json.load(open(os.path.join(path, "diagnosis.json")))
    assert ddoc["schema"] == "dmlc.diagnosis/1"
    tl = json.load(open(os.path.join(path, "timeline.json")))
    assert "drill.work.rate" in tl["series"]
    cp = open(os.path.join(path, "critical_path.txt")).read()
    assert "drill.step" in cp
    prof = open(os.path.join(path, "profile.txt")).read()
    assert prof.strip(), "collapsed-stack profile must be non-empty"
    _assert_chrome_trace_valid(
        json.load(open(os.path.join(path, "trace.json"))))


def test_dump_unarmed_is_none_and_rate_limited(tmp_path):
    rec = flight.FlightRecorder()
    assert rec.dump("nope") is None          # not armed → no-op
    rec.arm(str(tmp_path))
    rec._min_interval = 3600.0
    assert rec.dump("first") is not None
    assert rec.dump("suppressed") is None    # within the window
    assert rec.dump("forced", force=True) is not None


def test_note_ring_is_bounded():
    rec = flight.FlightRecorder(note_capacity=8)
    for i in range(50):
        rec.note("n", i=i)
    notes = rec.notes()
    assert len(notes) == 8 and notes[-1]["i"] == 49


def test_injected_error_fault_leaves_flight_evidence(tmp_path):
    """utils.faults → flight: an injected ERROR notes + dumps a bundle
    (the chaos run's evidence trail matches a real incident's)."""
    flight.flight_recorder.arm(str(tmp_path))
    with inject_faults("drill.boom:error=1"):
        with pytest.raises(Exception):
            fault_point("drill.boom")
    kinds = [n["kind"] for n in flight.flight_recorder.notes()]
    assert "fault_injected" in kinds
    assert any(d.startswith("incident-") for d in os.listdir(tmp_path))


# ---------------------------------------------------------------------------
# stall detection
# ---------------------------------------------------------------------------

def test_stall_detector_flags_outlier():
    det = anomaly.StallDetector("unit.stage", z_threshold=6.0,
                                min_samples=16, rel_floor=0.5)
    for _ in range(30):
        det.observe(0.010)
    z = det.observe(0.200)                  # 20x the typical duration
    assert z > 6.0
    assert metrics.counter("anomaly.stalls.unit.stage").value == 1
    assert any(n["kind"] == "stage_stall"
               for n in flight.flight_recorder.notes())


def test_stall_detector_quiet_stream_no_false_positive():
    det = anomaly.StallDetector("unit.quiet", z_threshold=6.0,
                                min_samples=16, rel_floor=0.5)
    for i in range(200):
        det.observe(0.010 + (i % 7) * 1e-4)     # ±7% jitter
    assert metrics.counter("anomaly.stalls.unit.quiet").value == 0


# ---------------------------------------------------------------------------
# straggler board
# ---------------------------------------------------------------------------

def _stage_state(count, total_sec):
    return {"train.step": {"type": "stage", "count": count,
                           "total_sec": total_sec}}


def test_straggler_board_flags_synthetic_slow_rank():
    board = anomaly.StragglerBoard(z_threshold=4.0, min_ranks=3)
    # 4 ranks, 3 pushes each: ranks 0-2 do 10ms steps, rank 3 does 100ms
    for push in range(1, 4):
        for rank in range(4):
            per = 0.100 if rank == 3 else 0.010
            board.update(rank, _stage_state(push * 50, push * 50 * per))
    assert board.suspects() == ["3"]
    snap = board.snapshot()
    assert snap["stragglers"] == ["3"]
    assert snap["stages"]["train.step"]["3"]["straggler"] is True
    assert snap["stages"]["train.step"]["0"]["straggler"] is False
    rows = dict((labels["rank"], s) for labels, s in board.series())
    assert rows["3"]["straggler_suspect"]["value"] == 1
    assert rows["0"]["straggler_suspect"]["value"] == 0


def test_straggler_board_counter_reset_safe():
    """A restarted rank (counters reset to 0) must not produce a negative
    increment or a bogus flag."""
    board = anomaly.StragglerBoard(min_ranks=3)
    for rank in range(3):
        board.update(rank, _stage_state(100, 1.0))
        board.update(rank, _stage_state(200, 2.0))
    board.update(0, _stage_state(10, 0.1))      # rank 0 restarted
    assert board.suspects() == []


def test_straggler_board_needs_min_ranks():
    board = anomaly.StragglerBoard(min_ranks=3)
    for push in range(1, 3):
        board.update(0, _stage_state(push * 10, push * 0.1))
        board.update(1, _stage_state(push * 10, push * 1.0))
    assert board.evaluate() == {}               # 2 ranks < min_ranks


# ---------------------------------------------------------------------------
# SLO grammar + monitor
# ---------------------------------------------------------------------------

def test_slo_spec_parsing():
    rules = anomaly.parse_slo_spec(
        "serving.latency_s:field=p99:max=50ms,"
        "q.depth:max=192,rate:min=1.5:for=3")
    assert [r.metric for r in rules] == ["serving.latency_s", "q.depth",
                                        "rate"]
    assert rules[0].max_v == pytest.approx(0.05)
    assert rules[0].field == "p99"
    assert rules[2].min_v == 1.5 and rules[2].for_count == 3


@pytest.mark.parametrize("bad", [
    "", "   ", ":max=1", "m:max", "m:nope=1", "m:field=p99",
    "m:max=abc", "m:max=1:for=x",
])
def test_slo_spec_bad_specs_raise(bad):
    with pytest.raises(anomaly.SloSpecError):
        anomaly.parse_slo_spec(bad)


def test_slo_env_unset_is_exact_noop(monkeypatch):
    monkeypatch.delenv("DMLC_SLO_SPEC", raising=False)
    assert anomaly.maybe_monitor_from_env() is None


def test_slo_default_fields_by_type():
    reg = MetricsRegistry()
    reg.gauge("g").set(7)
    h = reg.histogram("h")
    for v in [1.0] * 100:
        h.observe(v)
    snap = reg.snapshot()
    assert anomaly.SloRule("g", None, 5.0, None, 1).check(snap) is not None
    assert anomaly.SloRule("h", None, 0.5, None, 1).check(snap) is not None
    # absent metric is NOT a breach
    assert anomaly.SloRule("missing", None, 0.0, None, 1).check(snap) is None


def test_slo_for_requires_consecutive_breaches():
    reg = MetricsRegistry()
    rule = anomaly.SloRule("g", None, 10.0, None, 3)
    reg.gauge("g").set(99)
    assert rule.check(reg.snapshot()) is None       # 1st
    assert rule.check(reg.snapshot()) is None       # 2nd
    reg.gauge("g").set(0)
    assert rule.check(reg.snapshot()) is None       # reset
    reg.gauge("g").set(99)
    assert rule.check(reg.snapshot()) is None
    assert rule.check(reg.snapshot()) is None
    fired = rule.check(reg.snapshot())              # 3rd consecutive
    assert fired is not None and fired["consecutive"] == 3


def test_slo_monitor_breach_sets_gauge_and_dumps(tmp_path):
    flight.flight_recorder.arm(str(tmp_path))
    reg = MetricsRegistry()
    reg.gauge("q.depth").set(500)
    mon = anomaly.SloMonitor(anomaly.parse_slo_spec("q.depth:max=100"),
                             registry=reg, interval_s=3600,
                             spec="q.depth:max=100")
    fired = mon.evaluate_once()
    assert len(fired) == 1 and fired[0]["rule"].startswith("q.depth")
    assert reg.gauge("slo.active_breaches").value == 1
    assert reg.counter("slo.breaches").value == 1
    bundles = [d for d in os.listdir(tmp_path) if "slo_breach" in d]
    assert bundles
    doc = json.load(open(os.path.join(tmp_path, bundles[0],
                                      "incident.json")))
    assert doc["detail"]["breaches"][0]["rule"].startswith("q.depth")
    # recovery clears the gauge
    reg.gauge("q.depth").set(1)
    assert mon.evaluate_once() == []
    assert reg.gauge("slo.active_breaches").value == 0


def test_serving_health_degrades_on_slo_breach():
    """An otherwise-healthy server reports degraded while a rule is
    breached (the load-balancer drain signal)."""
    jax = pytest.importorskip("jax")
    from dmlc_core_tpu.models.cli import MODEL_REGISTRY, TrainParams
    from dmlc_core_tpu.serving import InferenceEngine, PredictionServer

    p = TrainParams()
    p.init({"data": "x", "model": "logreg", "features": "64", "task": "binary"})
    model = MODEL_REGISTRY["logreg"](p)
    engine = InferenceEngine(model, model.init(jax.random.PRNGKey(0)))
    srv = PredictionServer(engine, warmup=False)
    try:
        assert srv.health == "ok"
        metrics.gauge("slo.active_breaches").set(2)
        assert srv.health == "degraded"
        assert metrics.gauge("serving.server.health").value == 1
        metrics.gauge("slo.active_breaches").set(0)
        assert srv.health == "ok"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# retrace watchdog
# ---------------------------------------------------------------------------

def test_watchdog_alerts_on_compile_after_steady():
    reg = MetricsRegistry()
    wd = xla_introspect.RetraceWatchdog(registry=reg)
    assert wd.note_compile("r8x512", 1.2) is False      # cold: expected
    wd.mark_steady()
    assert not wd.alerted
    assert wd.note_compile("r8x512", 1.3) is True       # retrace!
    assert wd.alerted
    assert reg.counter("xla.retrace_alerts").value == 1
    assert reg.gauge("xla.retrace_alert").value == 1
    assert reg.counter("xla.compiles").value == 2
    wd.reset_alert()
    assert not wd.alerted and reg.gauge("xla.retrace_alert").value == 0


def test_watchdog_begin_warmup_reopens_compile_window():
    """A checkpoint hot-reload re-warms a fresh engine; those compiles
    are declared, not retraces — only post-window compiles alert."""
    reg = MetricsRegistry()
    wd = xla_introspect.RetraceWatchdog(registry=reg)
    wd.note_compile("r8x512", 1.0)
    wd.mark_steady()
    wd.begin_warmup()
    assert wd.note_compile("r8x512", 1.0) is False
    wd.mark_steady()
    assert wd.note_compile("r8x512", 1.0) is True


def test_watchdog_ladder_miss_alert():
    """The satellite case: a request falling off the no-retrace ladder
    raises the alert and leaves flight evidence."""
    from dmlc_core_tpu.serving.engine import BucketLadder, RequestTooLarge
    ladder = BucketLadder([(8, 512)])
    with pytest.raises(RequestTooLarge):
        try:
            ladder.select(1000, 1 << 20)
        except RequestTooLarge as e:
            xla_introspect.watchdog.note_ladder_miss(str(e))
            raise
    assert metrics.counter("xla.ladder_misses").value == 1
    assert metrics.gauge("xla.retrace_alert").value == 1
    assert any(n["kind"] == "ladder_miss"
               for n in flight.flight_recorder.notes())
    xla_introspect.watchdog.reset_alert()


def test_engine_predict_too_large_counts_ladder_miss():
    jax = pytest.importorskip("jax")
    import numpy as np

    from dmlc_core_tpu.models.cli import MODEL_REGISTRY, TrainParams
    from dmlc_core_tpu.serving import InferenceEngine
    from dmlc_core_tpu.serving.engine import BucketLadder, RequestTooLarge

    p = TrainParams()
    p.init({"data": "x", "model": "logreg", "features": "64", "task": "binary"})
    model = MODEL_REGISTRY["logreg"](p)
    engine = InferenceEngine(model, model.init(jax.random.PRNGKey(0)),
                             buckets=BucketLadder([(4, 64)]))
    before = metrics.counter("xla.ladder_misses").value
    ids = np.zeros(1000, np.int32)
    with pytest.raises(RequestTooLarge):
        engine.predict(ids, np.zeros(1000, np.float32),
                       np.arange(0, 1001, 100, dtype=np.int64)[:11])
    assert metrics.counter("xla.ladder_misses").value == before + 1
    xla_introspect.watchdog.reset_alert()


def test_sample_memory_without_jax_is_quiet(monkeypatch):
    """sample_memory never raises; with JAX importable it sets the
    live-buffer gauge, without it it returns False."""
    reg = MetricsRegistry()
    assert xla_introspect.sample_memory(reg) in (True, False)


# ---------------------------------------------------------------------------
# exposition endpoints
# ---------------------------------------------------------------------------

def test_flight_endpoint_returns_bundle(tmp_path):
    flight.flight_recorder.arm(str(tmp_path))
    flight.flight_recorder.note("unit", marker="endpoint-test")
    srv = exposition.TelemetryServer(port=0).start()
    try:
        code, body = _get(f"http://127.0.0.1:{srv.port}/flight")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == "dmlc.flight.incident/1"
        assert doc["reason"] == "endpoint"
        assert any(n["kind"] == "unit" for n in doc["notes"])
        assert doc["dumped_to"].startswith(str(tmp_path))
    finally:
        srv.stop()


def test_stragglers_endpoint_worker_404_tracker_json():
    srv = exposition.TelemetryServer(port=0).start()
    try:
        code, _ = _get(f"http://127.0.0.1:{srv.port}/stragglers")
        assert code == 404                  # workers have no fleet view
    finally:
        srv.stop()
    board = anomaly.StragglerBoard()
    srv = exposition.TelemetryServer(port=0,
                                     stragglers_fn=board.snapshot).start()
    try:
        code, body = _get(f"http://127.0.0.1:{srv.port}/stragglers")
        assert code == 200
        doc = json.loads(body)
        assert doc["stragglers"] == [] and "z_threshold" in doc
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the end-to-end incident drill
# ---------------------------------------------------------------------------

def test_end_to_end_incident_drill(tmp_path, monkeypatch):
    """The acceptance drill, in one flow:

    1. a ``DMLC_FAULT_SPEC``-injected stall hits a monitored stage;
    2. the stall flags ``anomaly.stalls.*`` which breaches a
       ``DMLC_SLO_SPEC`` rule;
    3. the tracker ``/metrics`` flags the straggling rank that the same
       stall would produce fleet-side;
    4. the flight recorder emits a bundle whose Chrome trace is
       schema-valid and whose incident JSON names the breached rule.
    """
    from dmlc_core_tpu.parallel.tracker import RabitTracker, send_json

    monkeypatch.setenv("DMLC_SLO_SPEC",
                       "anomaly.stalls.drill.stage:max=0")
    monkeypatch.setenv("DMLC_FAULT_SPEC",
                       "drill.stage:latency=80ms:lp=1:after=30")
    flight.maybe_arm_from_env()             # unset FLIGHT_DIR → still None
    flight.flight_recorder.arm(str(tmp_path))

    # (1)+(2) — the stalled stage, under a span so the trace has content
    det = anomaly.StallDetector("drill.stage", z_threshold=6.0,
                                min_samples=16, rel_floor=0.5)
    with teltrace.span("drill.run"):
        for _ in range(32):
            t0 = time.monotonic()
            with teltrace.span("drill.stage.step"):
                fault_point("drill.stage")  # 31st+ call sleeps 80ms
            det.observe(time.monotonic() - t0)
    assert metrics.counter("anomaly.stalls.drill.stage").value >= 1

    mon = anomaly.maybe_monitor_from_env(autostart=False)
    assert mon is not None                  # spec set → monitor exists
    fired = mon.evaluate_once()
    assert len(fired) == 1
    assert fired[0]["rule"].startswith("anomaly.stalls.drill.stage")
    assert metrics.gauge("slo.active_breaches").value == 1

    # (3) — fleet side: the same slow stage, pushed rank-tagged
    t = RabitTracker(num_workers=4, host_ip="127.0.0.1", telemetry_port=0)
    t.start()
    try:
        def push(rank, count, total):
            s = socket.create_connection((t.host_ip, t.port), timeout=5)
            try:
                send_json(s, {"cmd": "telemetry", "jobid": f"j{rank}",
                              "rank": rank,
                              "state": {"drill.stage": {
                                  "type": "stage", "count": count,
                                  "total_sec": total}}})
            finally:
                s.close()

        for step in range(1, 4):
            for rank in range(4):
                per = 0.120 if rank == 2 else 0.012    # rank 2 straggles
                push(rank, step * 40, step * 40 * per)
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and len(t.telemetry_states()) < 4):
            time.sleep(0.02)
        assert t.straggler_board.suspects() == ["2"]
        code, body = _get(f"http://127.0.0.1:{t.telemetry.port}/metrics")
        assert code == 200
        assert 'dmlc_straggler_suspect{rank="2"} 1' in body.splitlines()
        assert 'dmlc_straggler_suspect{rank="0"} 0' in body.splitlines()
        code, body = _get(
            f"http://127.0.0.1:{t.telemetry.port}/stragglers")
        assert code == 200 and json.loads(body)["stragglers"] == ["2"]
    finally:
        t.stop()

    # (4) — the evidence: bundle on disk names the rule, trace loads
    bundles = sorted(d for d in os.listdir(tmp_path)
                     if "slo_breach" in d)
    assert bundles, f"no slo_breach bundle in {os.listdir(tmp_path)}"
    bundle = os.path.join(str(tmp_path), bundles[-1])
    doc = json.load(open(os.path.join(bundle, "incident.json")))
    assert doc["schema"] == "dmlc.flight.incident/1"
    assert doc["reason"] == "slo_breach"
    assert (doc["detail"]["breaches"][0]["rule"]
            .startswith("anomaly.stalls.drill.stage"))
    assert doc["slo_spec"] == "anomaly.stalls.drill.stage:max=0"
    assert doc["fault_spec"] == "drill.stage:latency=80ms:lp=1:after=30"
    assert any(n["kind"] == "stage_stall" for n in doc["notes"])
    trace_doc = json.load(open(os.path.join(bundle, "trace.json")))
    _assert_chrome_trace_valid(trace_doc)
    names = {ev["name"] for ev in trace_doc["traceEvents"]}
    assert "drill.stage.step" in names
    assert os.path.getsize(os.path.join(bundle, "log_tail.txt")) > 0
