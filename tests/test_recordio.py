"""RecordIO property tests — mirrors reference ``test/recordio_test.cc``:
random payloads with deliberately embedded magic words must round-trip
byte-exactly through writer → reader, chunk reader, and the partitioned
InputSplit across all nsplit values."""

import io
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import (KMAGIC, RecordIOChunkReader, RecordIOReader,
                              RecordIOWriter, create_input_split)

MAGIC = struct.pack("<I", KMAGIC)


def gen_records(rng, n, magic_rate=0.3):
    """Random payloads, ~magic_rate of them with embedded magic words at
    assorted alignments (the reference fuzz embeds kMagic deliberately,
    recordio_test.cc:26-47)."""
    recs = []
    for i in range(n):
        size = int(rng.integers(0, 200))
        data = bytes(rng.integers(0, 256, size, dtype=np.uint8))
        if rng.random() < magic_rate and size >= 8:
            k = int(rng.integers(0, size - 4))
            data = data[:k] + MAGIC + data[k + 4:]
            if rng.random() < 0.5:
                a = (int(rng.integers(0, size // 4)) * 4) % max(size - 4, 1)
                data = data[:a] + MAGIC + data[a + 4:]
        recs.append(data)
    return recs


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(42)
    return gen_records(rng, 500)


def test_writer_reader_roundtrip(corpus):
    buf = io.BytesIO()
    w = RecordIOWriter(buf)
    for r in corpus:
        w.write_record(r)
    assert w.except_counter > 0  # the fuzz did embed aligned magic
    buf.seek(0)
    got = list(RecordIOReader(buf))
    assert got == corpus


def test_chunk_reader_all_parts(corpus):
    buf = io.BytesIO()
    w = RecordIOWriter(buf)
    for r in corpus:
        w.write_record(r)
    blob = buf.getvalue()
    for nparts in (1, 2, 3, 7):
        got = []
        for k in range(nparts):
            got.extend(RecordIOChunkReader(blob, k, nparts))
        assert got == corpus, f"nparts={nparts}"


def test_input_split_partition_union(corpus, tmp_path):
    path = tmp_path / "data.rec"
    with open(path, "wb") as f:
        w = RecordIOWriter(f)
        for r in corpus:
            w.write_record(r)
    for nparts in (1, 2, 5, 8):
        got = []
        for k in range(nparts):
            with create_input_split(str(path), k, nparts, "recordio",
                                    threaded=False) as split:
                part = list(split)
            got.extend(part)
        assert got == corpus, f"nparts={nparts}"


def test_input_split_multifile(corpus, tmp_path):
    # records spread over 3 files; union across parts must equal the corpus
    third = len(corpus) // 3
    paths = []
    for i in range(3):
        p = tmp_path / f"part{i}.rec"
        with open(p, "wb") as f:
            w = RecordIOWriter(f)
            for r in corpus[i * third: (i + 1) * third if i < 2 else len(corpus)]:
                w.write_record(r)
        paths.append(str(p))
    uri = ";".join(paths)
    for nparts in (1, 4):
        got = []
        for k in range(nparts):
            with create_input_split(uri, k, nparts, "recordio",
                                    threaded=False) as split:
                got.extend(split)
        assert got == corpus


def test_empty_records_roundtrip():
    buf = io.BytesIO()
    w = RecordIOWriter(buf)
    recs = [b"", b"a", b"", MAGIC, MAGIC * 3]
    for r in recs:
        w.write_record(r)
    buf.seek(0)
    assert list(RecordIOReader(buf)) == recs


def test_threaded_recordio_split(corpus, tmp_path):
    path = tmp_path / "data.rec"
    with open(path, "wb") as f:
        w = RecordIOWriter(f)
        for r in corpus:
            w.write_record(r)
    with create_input_split(str(path), 0, 1, "recordio", threaded=True) as split:
        assert list(split) == corpus
        split.before_first()
        assert list(split) == corpus
