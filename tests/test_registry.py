"""Registry tests (reference: ``test/unittest/unittest_registry.cc``)."""

import pytest

from dmlc_core_tpu.utils import DMLCError, Registry


def test_register_and_find():
    reg = Registry.get("TestTree")

    @reg.register("binary", description="binary tree")
    def make_binary():
        return "binary-tree"

    entry = reg.find("binary")
    assert entry is not None
    assert entry() == "binary-tree"
    assert entry.description == "binary tree"
    assert reg.find("missing") is None
    with pytest.raises(KeyError):
        reg["missing"]
    reg.remove("binary")


def test_alias_and_duplicate():
    reg = Registry.get("TestAlias")

    @reg.register("adam")
    def make_adam():
        return 1

    reg.add_alias("adam", "adamw-ish")
    assert reg["adamw-ish"]() == 1
    with pytest.raises(DMLCError):
        @reg.register("adam")
        def make_adam2():
            return 2
    reg.remove("adam")
    reg.remove("adamw-ish")


def test_singleton_per_name():
    assert Registry.get("A1") is Registry.get("A1")
    assert Registry.get("A1") is not Registry.get("A2")


def test_entry_metadata():
    reg = Registry.get("TestMeta")
    e = reg.register_entry(
        __import__("dmlc_core_tpu.utils.registry", fromlist=["RegistryEntry"])
        .RegistryEntry("thing", lambda: 3))
    e.describe("a thing").add_argument("x", "int", "the x").set_return_type("int")
    assert e.arguments[0]["name"] == "x"
    reg.remove("thing")
