"""Disaggregated ingest service: N workers parse+pack partitions and
stream fused wire frames; the trainer-side loader decodes to device
batches.  Union-of-parts, epoch reconnect, compact wire fidelity, and
mid-stream worker death are all covered."""

import socket
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.pipeline import RemoteIngestLoader, serve_ingest  # noqa: E402


from conftest import free_port as _free_port  # noqa: E402  (shared helper)


@pytest.fixture()
def libsvm_file(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "svc.libsvm"
    with open(path, "w") as f:
        for r in range(600):            # label = row id: the union key
            k = int(rng.integers(1, 6))
            idx = np.sort(rng.choice(5000, size=k, replace=False))
            f.write(f"{r} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    return str(path), 600


def _start_workers(uri, nparts, ports, max_epochs, **kw):
    from conftest import start_ingest_worker
    for part, port in enumerate(ports):
        start_ingest_worker(uri, part, nparts, port=port,
                            max_epochs=max_epochs, **kw)


def _collect_rows(loader):
    seen = []
    nb = 0
    for b in loader:
        w = np.asarray(b["weights"]) > 0
        seen.extend(np.asarray(b["labels"])[w].astype(int).tolist())
        nb += 1
    return seen, nb


def test_two_workers_union_equals_file_two_epochs(libsvm_file):
    uri, nrows = libsvm_file
    ports = [_free_port(), _free_port()]
    _start_workers(f"file://{uri}", 2, ports, max_epochs=2)
    loader = RemoteIngestLoader([("127.0.0.1", p) for p in ports],
                                batch_rows=64)
    try:
        seen, nb = _collect_rows(loader)
        assert sorted(seen) == list(range(nrows)), len(seen)
        assert nb >= 2                   # frames from both workers
        loader.before_first()            # epoch 2: reconnects
        seen2, _ = _collect_rows(loader)
        assert sorted(seen2) == list(range(nrows))
    finally:
        loader.close()


def test_compact_wire_over_the_network(libsvm_file):
    """Worker packs the v3 compact layout; the decoded device batches must
    equal the plain-wire ones value-for-value."""
    from dmlc_core_tpu import native
    if not native.has_compact():
        pytest.skip("native compact packer unavailable")
    uri, nrows = libsvm_file

    def run(compact):
        port = _free_port()
        _start_workers(f"file://{uri}", 1, [port], max_epochs=1,
                       wire_compact=compact)
        loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64)
        try:
            rows = {}
            for b in loader:
                ids = np.asarray(b["ids"])
                vals = np.asarray(b["vals"])
                segs = np.asarray(b["segments"])
                labels = np.asarray(b["labels"])
                for r in range(64):
                    m = segs == r
                    if m.any():
                        rows[int(labels[r])] = (ids[m].tolist(),
                                                np.round(vals[m], 6).tolist())
            return rows
        finally:
            loader.close()

    plain = run(False)
    compact = run(True)
    assert plain.keys() == compact.keys() and len(plain) == nrows
    for k in plain:
        assert plain[k][0] == compact[k][0]
        np.testing.assert_allclose(plain[k][1], compact[k][1], rtol=1e-6)


def test_worker_death_raises_loudly(libsvm_file):
    """A worker that dies mid-stream must surface an error, not silently
    truncate the epoch (the service-level analog of the partition
    union guarantee)."""
    uri, _ = libsvm_file
    port = _free_port()

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)

    def half_worker():
        conn, _ = srv.accept()
        import struct
        # one well-formed header promising a frame, then vanish
        conn.sendall(struct.pack("<QII", 100, 100, 0xFFFFFFFF))
        conn.sendall(b"\x00" * 40)       # partial payload
        conn.close()

    threading.Thread(target=half_worker, daemon=True).start()
    loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64,
                                connect_timeout=10.0)
    try:
        with pytest.raises(Exception, match="mid-frame|mid-stream|reader"):
            for _ in loader:
                pass
    finally:
        loader.close()
        srv.close()


def test_batch_rows_mismatch_raises(libsvm_file):
    uri, _ = libsvm_file
    port = _free_port()
    _start_workers(f"file://{uri}", 1, [port], max_epochs=1)
    loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=32)
    try:
        with pytest.raises(Exception, match="batch_rows"):
            for _ in loader:
                pass
    finally:
        loader.close()


def test_early_close_frees_worker_for_next_connection(libsvm_file):
    """Abandoning an epoch mid-stream must cancel the readers so the
    worker can serve the next connection promptly."""
    uri, nrows = libsvm_file
    port = _free_port()
    _start_workers(f"file://{uri}", 1, [port], max_epochs=2)
    loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64)
    first = loader.next_batch()
    assert first is not None
    loader.close()                       # mid-epoch abandon
    loader2 = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64,
                                 connect_timeout=30.0)
    try:
        seen, _ = _collect_rows(loader2)
        assert sorted(seen) == list(range(nrows))
    finally:
        loader2.close()
