"""The README quick-start must stay executable: extract its first python
code block verbatim, substitute the s3 URI for a generated local corpus,
and run it — documentation that rots fails CI (reference analog: the
csv test's dump-for-diffing discipline applied to our front door)."""

import os
import re

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_readme_quickstart_runs(tmp_path):
    readme = open(os.path.join(REPO, "README.md")).read()
    blocks = re.findall(r"```python\n(.*?)```", readme, re.S)
    assert blocks, "README lost its python quick-start block"
    code = blocks[0]
    assert "s3://bucket/train.libsvm" in code, \
        "quick-start URI changed — update this test's substitution"
    rng = np.random.default_rng(0)
    path = tmp_path / "qs.libsvm"
    with open(path, "w") as f:
        for i in range(600):
            idx = np.sort(rng.choice(1 << 16, 6, replace=False))
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    # every substitution must MATCH — a silent no-op would run the
    # full-size model in CI (or a dead URI)
    subs = {"s3://bucket/train.libsvm": f"file://{path}",
            "batch_rows=4096, nnz_cap=131072": "batch_rows=128, nnz_cap=2048",
            "num_features=1 << 20": "num_features=1 << 16"}
    for old, new in subs.items():
        assert old in code, f"quick-start changed ({old!r}) — update test"
        code = code.replace(old, new)
    ns: dict = {}
    exec(compile(code, "README.quickstart", "exec"), ns)  # noqa: S102
    assert "loss" in ns and float(ns["loss"]) > 0

    # the fused k-step block must stay executable too (same substitution
    # discipline; it builds its own loader so it runs standalone after
    # the quick-start's namespace)
    assert len(blocks) >= 2, "README lost its FusedTrainer block"
    code2 = blocks[1]
    for old, new in subs.items():
        if old in code2:
            code2 = code2.replace(old, new)
    assert "FusedTrainer" in code2
    exec(compile(code2, "README.fused", "exec"), ns)  # noqa: S102
    assert float(ns["loss"]) > 0
    assert ns["trainer"].steps > 0
