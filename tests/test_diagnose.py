"""Automated incident diagnosis (r20): per-analyzer unit tests on
synthetic populations (wide-event differencing, timeline lead/lag,
critical-path diff, fleet attribution + corroboration), the merged
ranking and breach auto-scoping, the strictly-monotonic ``/events``
cursor with per-reader ``missed`` accounting, profile diffing on
``/profile?diff=1``, the ``/diagnose`` endpoint over a real socket, and
the 3-replica chaos drill that proves end-to-end attribution: inject
20 ms on one replica, breach, and the ranked report names that replica
and the leading series with zero human input."""

import json
import math
import os
import time
import urllib.error
import urllib.request

import pytest

import dmlc_core_tpu.telemetry.diagnose as diagnose
from dmlc_core_tpu.telemetry import exposition, profiling, slo
from dmlc_core_tpu.telemetry import timeseries as ts
from dmlc_core_tpu.telemetry import trace as teltrace
from dmlc_core_tpu.telemetry.wide_events import WideEventLog, wide_log
from dmlc_core_tpu.utils.metrics import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: fixed synthetic epoch (multiple of every tier step used below)
T0 = 1_000_000.0


@pytest.fixture(autouse=True)
def _clean_diagnosis(monkeypatch):
    monkeypatch.setattr(diagnose, "_last_breach", None)
    monkeypatch.setattr(diagnose, "_last_doc", None)
    monkeypatch.setattr(profiling, "_baseline", None)
    yield


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _empty_store():
    return ts.HistoryStore(snapshot_fn=lambda: {}, tiers=[(1.0, 16)])


def _engine(**kw):
    kw.setdefault("events_fn", lambda: [])
    kw.setdefault("history", _empty_store())
    kw.setdefault("records_fn", lambda: [])
    return diagnose.DiagnosisEngine(**kw)


# ---------------------------------------------------------------------------
# analyzer 1: wide-event dimension differencing
# ---------------------------------------------------------------------------

def test_robust_slow_threshold_splits_bimodal_window():
    durs = [1.0] * 50 + [20.0] * 10
    thr = diagnose._robust_slow_ms(durs)
    assert 1.0 < thr < 20.0     # between the modes, not inside either


def _route_events(n=60, bad_replica="10.0.0.9:7013", bad_every=3,
                  outcome="UNAVAILABLE", ts_at=T0 - 5.0):
    evs = []
    for i in range(n):
        bad = i % bad_every == 0
        evs.append({"kind": "serving.route", "seq": i + 1, "ts": ts_at,
                    "model": "m", "trace_id": f"{i:016x}",
                    "replica": bad_replica if bad
                    else f"10.0.0.{i % 2}:7011",
                    "outcome": outcome if bad else "OK",
                    "dur_ms": 25.0 if bad else 1.0})
    return evs


def test_wide_event_differencing_ranks_bad_dimension():
    evs = _route_events()
    eng = _engine(events_fn=lambda: evs)
    doc = eng._diff_wide_events(T0 - 60, T0, top=5, slow_ms=0.0)
    assert doc["in_window"] == 60 and doc["bad"] == 20
    top2 = {(s["field"], s["value"]) for s in doc["suspects"][:2]}
    assert ("replica", "10.0.0.9:7013") in top2
    assert ("outcome", "UNAVAILABLE") in top2
    rep = next(s for s in doc["suspects"] if s["field"] == "replica")
    assert rep["bad_frac"] == 1.0 and rep["base_frac"] == 0.0
    # measures and per-event identities are never differenced — that is
    # the cardinality alarm BubbleUp-style analysis exists to avoid
    banned = diagnose.MEASURE_FIELDS | diagnose.IDENTITY_FIELDS
    assert all(s["field"] not in banned for s in doc["suspects"])


def test_wide_event_differencing_slowness_without_errors():
    # every outcome is OK: the adaptive dur_ms threshold alone must
    # isolate the slow replica's requests as the bad population
    evs = _route_events(outcome="OK")
    eng = _engine(events_fn=lambda: evs)
    doc = eng._diff_wide_events(T0 - 60, T0, top=5, slow_ms=0.0)
    assert doc["bad"] == 20 and doc["slow_ms"] is not None
    top = doc["suspects"][0]
    assert (top["field"], top["value"]) == ("replica", "10.0.0.9:7013")


def test_wide_event_differencing_empty_windows():
    eng = _engine(events_fn=lambda: [])
    doc = eng._diff_wide_events(T0 - 60, T0, top=5, slow_ms=0.0)
    assert doc == {"events": 0, "in_window": 0, "bad": 0, "baseline": 0,
                   "slow_ms": None, "suspects": []}
    # out-of-window events are baseline only, never bad
    evs = _route_events(ts_at=T0 - 500.0)
    doc = _engine(events_fn=lambda: evs)._diff_wide_events(
        T0 - 60, T0, top=5, slow_ms=0.0)
    assert doc["in_window"] == 0 and doc["bad"] == 0


# ---------------------------------------------------------------------------
# analyzer 2: timeline lead/lag correlation
# ---------------------------------------------------------------------------

def test_onset_detection_and_frozen_baseline():
    flat = [(T0 + i, 1.0) for i in range(20)]
    assert diagnose.DiagnosisEngine._onset(flat) == (None, 0.0)
    step = flat[:10] + [(T0 + 10 + i, 50.0) for i in range(5)]
    onset, mag = diagnose.DiagnosisEngine._onset(step)
    assert onset == T0 + 10 and mag > 3.0
    # the stat freezes at onset: a series that keeps climbing measures
    # against the pre-deviation baseline, so its magnitude only grows
    climb = flat[:10] + [(T0 + 10 + i, 50.0 * (i + 1)) for i in range(5)]
    _, mag2 = diagnose.DiagnosisEngine._onset(climb)
    assert mag2 > mag
    assert mag2 <= diagnose._Z_CAP


def test_timeline_leaders_only_and_self_series_excluded():
    vals = {"cause": 1.0, "victim": 2.0, "effect": 3.0, "flat": 4.0,
            "slo.decoy": 5.0}

    def snap():
        return {k: {"type": "gauge", "value": v} for k, v in vals.items()}

    store = ts.HistoryStore(snapshot_fn=snap, tiers=[(1.0, 64)])
    for i in range(30):
        if i == 6:
            vals["slo.decoy"] = 500.0   # earliest mover, but self-series
        if i == 10:
            vals["cause"] = 50.0        # the upstream cause
        if i == 15:
            vals["victim"] = 80.0       # the breached series
        if i == 25:
            vals["effect"] = 90.0       # moved after the breach: effect
        store.sample_once(now=T0 + i)
    eng = _engine(history=store)
    doc = eng._correlate_timeline(T0 + 20, T0 + 30, top=5,
                                  breach_series="victim")
    assert doc["breach_onset"] == T0 + 15
    names = [s["series"] for s in doc["suspects"]]
    assert names == ["cause"]
    s = doc["suspects"][0]
    assert s["lead_s"] == 5.0 and s["magnitude"] > 3.0
    # no breach series given → window start is the reference onset
    doc = eng._correlate_timeline(T0 + 20, T0 + 30, top=5,
                                  breach_series=None)
    assert doc["breach_onset"] == T0 + 20
    assert "cause" in [s["series"] for s in doc["suspects"]]


# ---------------------------------------------------------------------------
# analyzer 3: critical-path regression diff
# ---------------------------------------------------------------------------

def _span(name, i, ts_s, dur_us):
    return {"kind": "span", "name": name, "trace_id": f"t{i}",
            "span_id": f"s{i}", "parent_id": None,
            "ts_us": int(ts_s * 1e6), "dur_us": int(dur_us)}


def test_critical_path_diff_ranks_grown_span():
    records = []
    for i in range(5):      # baseline: db dominates the critical path
        records.append(_span("db", f"b{i}", T0 - 100 - i, 1000))
    for i in range(3):      # incident: lock_wait displaces it
        records.append(_span("db", f"i{i}", T0 - 10 - i, 1000))
        records.append(_span("lock_wait", f"j{i}", T0 - 10 - i, 5000))
    eng = _engine(records_fn=lambda: records)
    doc = eng._diff_critical_path(T0 - 30, T0, top=5)
    assert doc["incident_spans"] == 6 and doc["baseline_spans"] == 5
    assert not doc["baseline_missing"]
    top = doc["suspects"][0]
    assert top["span"] == "lock_wait" and top["score"] > 0
    assert top["share_baseline"] == 0.0
    # db shrank: a regression diff only surfaces what grew
    assert all(s["span"] != "db" for s in doc["suspects"])
    # no incident spans → empty verdict, no division by zero
    assert eng._diff_critical_path(T0 + 50, T0 + 60, top=5)[
        "suspects"] == []


# ---------------------------------------------------------------------------
# analyzer 4 + merger: fleet attribution, corroboration, ranking
# ---------------------------------------------------------------------------

def test_fleet_attribution_corroborated_by_wide_events():
    evs = _route_events()        # bad replica 10.0.0.9:7013
    fleet = {"replicas": {"job:3": {"addr": "10.0.0.9:7013",
                                    "alive": True, "straggler": True},
                          "job:1": {"addr": "10.0.0.0:7011",
                                    "alive": True}},
             "workers": {"w:9": {"addr": "10.0.0.8:9000",
                                 "alive": False}}}
    stragglers = {"stages": {"step": {"2": {"straggler": True,
                                            "z": 7.5},
                                      "0": {"straggler": False,
                                            "z": 0.1}}}}
    eng = _engine(events_fn=lambda: evs, fleet_fn=lambda: fleet,
                  stragglers_fn=lambda: stragglers)
    doc = eng.run(since=T0 - 60, until=T0, top=8)
    fl = doc["analyzers"]["fleet"]
    assert set(fl["sources"]) == {"stragglers", "fleet"}
    reasons = {(s["entity"], s["id"]): s["reason"]
               for s in fl["suspects"]}
    assert reasons[("rank", "2")] == "straggler"
    assert reasons[("worker", "w:9")] == "dead"
    assert reasons[("replica", "job:3")] == "straggler"
    # the fleet row whose addr the wide-event verdict also names is
    # corroborated and boosted — two analyzers agreeing beats either
    rep = next(s for s in doc["suspects"]
               if s["subject"] == "replica job:3")
    # raw 6.0 against the dead worker's peak 10.0 → 0.6, +0.25 boost
    assert rep["corroborated"] and rep["score"] == pytest.approx(0.85)
    # the boost lifts it past the rank straggler's higher raw z (0.75)
    rank2 = next(s for s in doc["suspects"] if s["subject"] == "rank 2")
    assert rep["rank"] < rank2["rank"]
    assert not any(s.get("corroborated") for s in doc["suspects"]
                   if s["subject"] != "replica job:3")
    # ranks are 1..N in score order
    assert [s["rank"] for s in doc["suspects"]] == list(
        range(1, len(doc["suspects"]) + 1))


def test_run_document_schema_metrics_and_text():
    runs0 = metrics.counter("telemetry.diagnose.runs").value
    eng = _engine(events_fn=lambda: _route_events())
    doc = eng.run(since=T0 - 60, until=T0, top=3)
    assert doc["schema"] == diagnose.DIAGNOSIS_SCHEMA
    assert doc["window"]["since"] == T0 - 60
    assert doc["trigger"] == {"kind": "explicit"}
    assert len(doc["suspects"]) <= 3 and doc["wall_ms"] >= 0
    assert metrics.counter("telemetry.diagnose.runs").value == runs0 + 1
    assert metrics.gauge("telemetry.diagnose.suspects").value == \
        len(doc["suspects"])
    text = diagnose.render_text(doc)
    assert "ranked suspects" in text and "replica=10.0.0.9:7013" in text
    # a quiet window renders too (the empty report is still a report)
    quiet = _engine().run(since=T0 - 60, until=T0)
    assert "(none — quiet window)" in diagnose.render_text(quiet)


def test_endpoint_doc_scopes_to_recent_breach(monkeypatch):
    evs = _route_events(ts_at=time.time())
    eng = _engine(events_fn=lambda: evs)
    # explicit window wins: trigger is explicit, window is since..until
    doc = eng.endpoint_doc(since_s=10.0)
    assert doc["trigger"]["kind"] == "explicit"
    assert abs((doc["window"]["until"] - doc["window"]["since"]) - 10.0) \
        < 1e-6
    # a fresh breach scopes a bare call
    breach = {"rule": "r:burn", "series": "x.p99", "window_s": 30.0}
    monkeypatch.setattr(diagnose, "_last_breach", (breach, time.time()))
    doc = eng.endpoint_doc()
    assert doc["trigger"]["kind"] == "breach"
    assert doc["trigger"]["breach"]["rule"] == "r:burn"
    assert abs((doc["window"]["until"] - doc["window"]["since"]) - 30.0) \
        < 1e-6
    # a stale breach (older than 2x its window) no longer scopes it
    monkeypatch.setattr(diagnose, "_last_breach",
                        (breach, time.time() - 1000.0))
    assert eng.endpoint_doc()["trigger"]["kind"] == "explicit"


def test_on_breach_and_incident_diagnosis_gating(monkeypatch):
    evs = _route_events(ts_at=time.time())
    eng = _engine(events_fn=lambda: evs)
    monkeypatch.setattr(diagnose, "_default_engine", eng)
    breach = {"rule": "r:burn", "series": "x.p99", "window_s": 30.0}
    doc = diagnose.on_breach(breach)
    assert doc is not None and doc["trigger"]["kind"] == "breach"
    # the flight hook reuses the breach-scoped verdict while fresh
    assert diagnose.incident_diagnosis() is doc
    # master gate: automatic paths opt out entirely
    monkeypatch.setenv("DMLC_DIAGNOSE", "0")
    assert diagnose.on_breach(breach) is None
    assert diagnose.incident_diagnosis() is None
    monkeypatch.delenv("DMLC_DIAGNOSE")
    monkeypatch.setenv("DMLC_DIAGNOSE_ON_BREACH", "0")
    monkeypatch.setattr(diagnose, "_last_breach", None)
    assert diagnose.on_breach(breach) is None
    # ... but on-demand diagnosis still works
    assert diagnose.incident_diagnosis() is not None


# ---------------------------------------------------------------------------
# satellite: strictly-monotonic /events cursor with missed accounting
# ---------------------------------------------------------------------------

def test_events_cursor_monotonic_seq_and_missed_counts():
    log = WideEventLog(capacity=4, path=None)
    for i in range(10):
        log.emit("serving.route", req_id=i)
    doc = log.doc(0)
    assert doc["last_seq"] == 10 and doc["dropped"] == 6
    assert [e["seq"] for e in doc["events"]] == [7, 8, 9, 10]
    assert doc["missed"] == 6            # seqs 1..6 overflowed the ring
    # a reader resuming inside the ring sees a gap-free continuation
    doc = log.doc(6)
    assert doc["missed"] == 0
    assert [e["seq"] for e in doc["events"]] == [7, 8, 9, 10]
    assert log.doc(8)["missed"] == 0
    # a reader that fell behind the ring learns exactly how far
    assert log.doc(3)["missed"] == 3     # 4..6 gone, 7..10 served
    # reset clears the buffer but seq NEVER restarts: cursors stay
    # strictly monotonic and cleared events are reported as missed
    log.reset(capacity=4)
    assert log.doc(10)["missed"] == 0    # caught-up reader: no loss
    assert log.doc(4)["missed"] == 6     # 5..10 cleared by the reset
    ev = log.emit("serving.route", req_id=99)
    assert ev["seq"] == 11               # continues, not restarts
    doc = log.doc(5)
    assert doc["missed"] == 5            # 6..10 gone across the reset
    assert [e["seq"] for e in doc["events"]] == [11]
    assert doc["dropped"] == 0           # dropped is since-reset overflow


# ---------------------------------------------------------------------------
# satellite: profile diffing
# ---------------------------------------------------------------------------

def test_diff_collapsed_share_shift():
    base = "main;a;db 50\nmain;a;cache 50\n"
    inc = "main;a;db 90\nmain;a;cache 10\n"
    out = profiling.diff_collapsed(base, inc)
    lines = out.splitlines()
    assert lines[0].startswith("main;a;db 90 +40.0% ")
    assert "(baseline 50.0% -> incident 90.0%)" in lines[0]
    assert lines[1].startswith("main;a;cache 10 -40.0% ")
    # a stack that vanished still shows (what grew displaced something)
    out = profiling.diff_collapsed("gone 10\nmain 10\n", "main 20\n")
    assert any(ln.startswith("gone 0 -50.0%") for ln in out.splitlines())
    # no baseline → annotated passthrough, never empty
    out = profiling.diff_collapsed("", inc)
    assert all(ln.endswith("(no baseline)") for ln in out.splitlines())


def test_incident_profile_diff_requires_baseline():
    assert profiling.baseline() is None
    assert profiling.incident_profile_diff("main 10\n") == ""
    profiling.record_baseline("")            # empty scrape never arms
    assert profiling.baseline() is None
    profiling.record_baseline("main 10\n", ts=T0)
    text, ts_rec = profiling.baseline()
    assert text == "main 10\n" and ts_rec == T0
    out = profiling.incident_profile_diff("main 30\n")
    assert out.startswith("# profile diff: baseline @ ")
    assert "main 30" in out
    assert profiling.incident_profile_diff("") == ""


# ---------------------------------------------------------------------------
# endpoints over a real socket
# ---------------------------------------------------------------------------

def test_profile_and_diagnose_endpoints():
    # materialized once: events stamped after run() captures its window
    # would fall outside it
    evs = _route_events(ts_at=time.time())
    eng = _engine(events_fn=lambda: evs)
    srv = exposition.TelemetryServer(
        port=0, host="127.0.0.1",
        profile_fn=lambda seconds: "main;work 10\n",
        diagnose_fn=eng.endpoint_doc).start()
    url = f"http://127.0.0.1:{srv.port}"
    try:
        # diff before any baseline scrape is an explicit 404, not junk
        code, body = _get(f"{url}/profile?diff=1")
        assert code == 404 and "no baseline" in body
        # a plain scrape serves AND records the baseline
        code, body = _get(f"{url}/profile")
        assert code == 200 and body == "main;work 10\n"
        assert profiling.baseline() is not None
        code, body = _get(f"{url}/profile?diff=1")
        assert code == 200 and body.startswith("# profile diff:")
        # /diagnose: explicit window, top clamp, text rendering
        code, body = _get(f"{url}/diagnose?since=60&top=2")
        doc = json.loads(body)
        assert code == 200
        assert doc["schema"] == diagnose.DIAGNOSIS_SCHEMA
        assert len(doc["suspects"]) <= 2
        assert doc["suspects"][0]["subject"] in (
            "replica=10.0.0.9:7013", "outcome=UNAVAILABLE")
        code, body = _get(f"{url}/diagnose?since=5m&format=text")
        assert code == 200 and "ranked suspects" in body
    finally:
        srv.stop()


def test_diagnose_endpoint_in_inventory():
    from dmlc_core_tpu.analysis.inventory import load
    inv = load(os.path.join(REPO, "docs", "inventory.json"))
    assert "/diagnose" in inv["endpoints"]
    assert "/diagnose" in exposition._ROUTES


# ---------------------------------------------------------------------------
# e2e chaos drill: slow replica → breach → ranked attribution → bundle
# ---------------------------------------------------------------------------

def test_chaos_drill_slow_replica_diagnosed(tmp_path, monkeypatch):
    pytest.importorskip("jax")
    import numpy as np
    from dmlc_core_tpu.models import SparseLogReg
    from dmlc_core_tpu.serving import (BucketLadder, InferenceEngine,
                                       PredictClient, PredictionServer,
                                       ReplicaAgent, ReplicaRegistry,
                                       ServingRouter)
    from dmlc_core_tpu.telemetry import flight
    from dmlc_core_tpu.utils import clear_faults, fault_point, inject_faults
    import jax.numpy as jnp

    F = 5000

    def _mk_engine():
        model = SparseLogReg(num_features=F)
        params = {"w": jnp.ones((F,), jnp.float32),
                  "b": jnp.float32(0.0)}
        return InferenceEngine(model, params,
                               buckets=BucketLadder([(16, 512)]))

    monkeypatch.setenv("DMLC_TIMELINE", "0")
    wide_log.reset()
    teltrace.recorder.clear()
    clear_faults()
    metrics.gauge("drill20.upstream_queue").set(0.05)

    reg = ReplicaRegistry(heartbeat_timeout_s=2.0).start()
    pairs = []
    for _ in range(3):
        srv = PredictionServer(_mk_engine(), metrics_port=0).start()
        ag = ReplicaAgent(srv, reg.address, interval_s=0.1).start()
        pairs.append((srv, ag))
    router = ServingRouter(registry=reg.address, sync_s=0.1,
                           health_poll_s=0.1).start()
    cli = PredictClient(router.host, router.port, model_id="default")

    slow_srv = pairs[0][0]
    orig_predict = slow_srv.engine.predict

    def slow_predict(*a, **kw):
        fault_point("drill20.replica.slow")
        return orig_predict(*a, **kw)

    monkeypatch.setattr(slow_srv.engine, "predict", slow_predict)
    hist = metrics.histogram("drill20.client_lat_s")
    rng = np.random.default_rng(7)

    def _load(n):
        for _ in range(n):
            counts = rng.integers(1, 17, size=4)
            ids = rng.integers(0, F, size=int(counts.sum())) \
                .astype(np.int32)
            vals = rng.random(len(ids), dtype=np.float32)
            row_ptr = np.concatenate([[0], np.cumsum(counts)]) \
                .astype(np.int32)
            t0 = time.perf_counter()
            cli.predict(ids, vals, row_ptr, timeout=10.0)
            hist.observe(time.perf_counter() - t0)

    fleet_up = True

    def _stop_fleet():
        nonlocal fleet_up
        if not fleet_up:
            return
        fleet_up = False
        cli.close()
        router.stop()
        for srv, ag in pairs:
            ag.stop()
            srv.stop()
        reg.stop()

    flight.flight_recorder.arm(str(tmp_path))
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(
                router.fleet_snapshot()["replicas"]) < 3:
            time.sleep(0.05)
        assert len(router.fleet_snapshot()["replicas"]) == 3

        _load(45)                       # healthy traffic, all replicas
        snap_healthy = metrics.snapshot()
        metrics.gauge("drill20.upstream_queue").set(100.0)
        with inject_faults("drill20.replica.slow:latency=20ms"):
            _load(45)                   # ~1/3 land on the slow replica
        snap_incident = metrics.snapshot()
        # the leading cause moves two synthetic ticks before the
        # latency does (a phase-snapshot copy with only the gauge up)
        snap_mid = dict(snap_healthy)
        snap_mid["drill20.upstream_queue"] = {"type": "gauge",
                                              "value": 100.0}

        # fleet down BEFORE the synthetic-clock sampling: nothing but
        # the recorded phase snapshots feeds the timeline, so onsets
        # are deterministic (no live heartbeat counters mid-sampling)
        _stop_fleet()

        phase = {"i": 0}

        def snap_fn():
            i = phase["i"]
            phase["i"] += 1
            if i < 8:
                return snap_healthy
            if i < 10:
                return snap_mid
            return snap_incident

        # tier 0 must span the analyzer's full lookback (breach window
        # + 300s baseline): query() serves whole windows from the
        # finest covering tier, and a coarser ring fed only 32 synthetic
        # ticks would hold too few points for onset detection
        store = ts.HistoryStore(snapshot_fn=snap_fn,
                                tiers=[(1.0, 400)])
        monkeypatch.setattr(ts, "history", store)
        base = math.floor((time.time() - 32) / 10.0) * 10.0
        for i in range(32):
            store.sample_once(now=base + i)

        plain, burn = slo.parse_slo_spec(
            "drill20.client_lat_s:field=p99:max=10ms:budget=0.01"
            ":fast=20s/2:slow=2m/2")
        mon = slo.BurnRateMonitor(plain, burn, history=store)
        fired = mon.evaluate_once()
        assert fired and fired[0]["series"] == "drill20.client_lat_s.p99"

        # the breach hook ran the diagnosis with zero human input
        doc = diagnose._last_doc
        assert doc is not None and doc["trigger"]["kind"] == "breach"
        bad = f":{slow_srv.port}"
        top3 = [s["subject"] for s in doc["suspects"][:3]]
        assert any(s.startswith("replica=") and s.endswith(bad)
                   for s in top3), top3
        assert "drill20.upstream_queue" in top3, top3

        # the breach's flight bundle carries the same verdict
        bundles = sorted(tmp_path.glob("incident-*"))
        assert bundles, "SLO breach must dump a flight bundle"
        bundle = bundles[-1]
        incident = json.loads((bundle / "incident.json").read_text())
        assert incident["files"]["diagnosis"] == "diagnosis.json"
        assert incident["files"]["diagnosis_text"] == "diagnosis.txt"
        bdoc = json.loads((bundle / "diagnosis.json").read_text())
        assert bdoc["suspects"] == doc["suspects"]
        assert (bundle / "diagnosis.txt").read_text() \
            .startswith("diagnosis @")

        # /diagnose on a live exporter auto-scopes to the same breach
        tsrv = exposition.TelemetryServer(port=0,
                                          host="127.0.0.1").start()
        try:
            url = f"http://127.0.0.1:{tsrv.port}"
            code, body = _get(f"{url}/diagnose")
            assert code == 200
            edoc = json.loads(body)
            assert edoc["schema"] == diagnose.DIAGNOSIS_SCHEMA
            assert edoc["trigger"]["kind"] == "breach"
            subs = [s["subject"] for s in edoc["suspects"][:3]]
            assert any(s.startswith("replica=") and s.endswith(bad)
                       for s in subs), subs
            assert "drill20.upstream_queue" in subs, subs
            code, body = _get(f"{url}/diagnose?format=text")
            assert code == 200 and "ranked suspects" in body
        finally:
            tsrv.stop()
    finally:
        _stop_fleet()
        flight.flight_recorder.disarm()
        clear_faults()
        metrics.gauge("slo.active_breaches").set(0)
        wide_log.reset()
