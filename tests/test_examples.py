"""Integration tests running the shipped examples through the real
launcher — the full stack in one shot: tracker rendezvous, partitioned
ingest, tree allreduce, identical replicas."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_distributed_logreg_example(tmp_path):
    data = tmp_path / "d.libsvm"
    import random
    rnd = random.Random(0)
    with open(data, "w") as f:
        for _ in range(1500):
            y = rnd.randint(0, 1)
            f.write(f"{y} {1 if y else 2}:1.0 {rnd.randint(3, 500)}:0.3\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3",
         "--env", f"PYTHONPATH={REPO}",
         "--", sys.executable,
         os.path.join(REPO, "examples", "distributed_logreg.py"), str(data)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO, "EPOCHS": "2"})
    assert out.returncode == 0, out.stderr[-3000:]
    assert out.stderr.count("all workers agree") == 3
    assert "all 3 processes exited cleanly" in out.stderr
