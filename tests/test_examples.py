"""Integration tests running the shipped examples through the real
launcher — the full stack in one shot: tracker rendezvous, partitioned
ingest, tree allreduce, identical replicas."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))



def _write_synth_libsvm(path, seed: int, rows: int = 600,
                        libfm: bool = False) -> None:
    """Shared synthetic corpus for the example integration tests (one
    place to tweak row count / id range / nnz shape for all of them)."""
    import random
    rnd = random.Random(seed)
    with open(path, "w") as f:
        for _ in range(rows):
            k = rnd.randint(1, 6)
            if libfm:
                ent = " ".join(f"{rnd.randint(0, 4)}:{rnd.randint(0, 200)}:"
                               f"{rnd.random():.3f}" for _ in range(k))
            else:
                ent = " ".join(f"{rnd.randint(0, 255)}:{rnd.random():.3f}"
                               for _ in range(k))
            f.write(f"{rnd.randint(0, 1)} {ent}\n")

def test_distributed_logreg_example(tmp_path):
    data = tmp_path / "d.libsvm"
    import random
    rnd = random.Random(0)
    with open(data, "w") as f:
        for _ in range(1500):
            y = rnd.randint(0, 1)
            f.write(f"{y} {1 if y else 2}:1.0 {rnd.randint(3, 500)}:0.3\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3",
         "--env", f"PYTHONPATH={REPO}",
         "--", sys.executable,
         os.path.join(REPO, "examples", "distributed_logreg.py"), str(data)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO, "EPOCHS": "2"})
    assert out.returncode == 0, out.stderr[-3000:]
    assert out.stderr.count("all workers agree") == 3
    assert "all 3 processes exited cleanly" in out.stderr


def test_failure_injection_worker_crash_and_recover(tmp_path):
    """Fault injection (SURVEY §5): one worker crashes on its first
    attempt; the launcher retry loop restarts it with DMLC_NUM_ATTEMPT=1,
    the cohort assembles with the reborn worker, and the job completes."""
    script = tmp_path / "flaky_worker.py"
    script.write_text(
        "import os, sys\n"
        "import numpy as np\n"
        "from dmlc_core_tpu.parallel import RabitContext\n"
        "tid = os.environ['DMLC_TASK_ID']\n"
        "att = int(os.environ.get('DMLC_NUM_ATTEMPT', '0'))\n"
        "if tid == '1' and att == 0:\n"
        "    print('INJECTED-CRASH', flush=True)\n"
        "    sys.exit(1)\n"
        "ctx = RabitContext.from_env()\n"
        "out = ctx.allreduce(np.array([float(ctx.rank)]))\n"
        "assert out[0] == sum(range(ctx.world_size))\n"
        "print(f'SURVIVED rank {ctx.rank} attempt {att}', flush=True)\n"
        "ctx.shutdown()\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3",
         "--env", f"PYTHONPATH={REPO}",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO})
    assert out.returncode == 0, out.stderr[-3000:]
    assert "INJECTED-CRASH" in out.stdout
    assert out.stdout.count("SURVIVED") == 3
    assert "attempt 1" in out.stdout          # the reborn worker


def test_failure_injection_midjob_crash_and_second_allreduce(tmp_path):
    """Mid-job elastic recovery (VERDICT r1 #3): a worker crashes AFTER a
    successful allreduce.  Survivors hold sockets to the dead incarnation;
    the tracker's reset_links push makes them drop stale links, re-link with
    the reborn worker (which fast-forwards via the rabit checkpoint), and the
    cohort completes a SECOND allreduce (reference link re-brokering,
    `tracker/dmlc_tracker/tracker.py:80-135,279-291`)."""
    script = tmp_path / "midjob_worker.py"
    script.write_text(
        "import os, sys\n"
        "import numpy as np\n"
        "from dmlc_core_tpu.parallel import RabitContext\n"
        "tid = os.environ['DMLC_TASK_ID']\n"
        "att = int(os.environ.get('DMLC_NUM_ATTEMPT', '0'))\n"
        "ctx = RabitContext.from_env()\n"
        "state = ctx.load_checkpoint() if att > 0 else None\n"
        "if state is None:\n"
        "    out1 = ctx.allreduce(np.array([float(ctx.rank + 1)]))\n"
        "    assert out1[0] == sum(range(1, ctx.world_size + 1)), out1\n"
        "    ctx.checkpoint({'out1': float(out1[0])})\n"
        "    if tid == '1' and att == 0:\n"
        "        print('MIDJOB-CRASH', flush=True)\n"
        "        os._exit(1)\n"
        "else:\n"
        "    out1 = np.array([state['out1']])\n"
        "out2 = ctx.allreduce(np.array([out1[0] * (ctx.rank + 1)]))\n"
        "expected = out1[0] * sum(r + 1 for r in range(ctx.world_size))\n"
        "assert out2[0] == expected, (out2, expected)\n"
        "print(f'SECOND-OK rank {ctx.rank} attempt {att}', flush=True)\n"
        "ctx.shutdown()\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3",
         "--env", f"PYTHONPATH={REPO}",
         "--env", f"DMLC_CHECKPOINT_DIR={tmp_path}",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO})
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    assert "MIDJOB-CRASH" in out.stdout
    assert out.stdout.count("SECOND-OK") == 3
    assert "attempt 1" in out.stdout          # the reborn worker finished


def test_checkpoint_resume_after_midjob_kill_converges(tmp_path):
    """VERDICT r2 #9 e2e: a worker is killed mid-job (survivors are already
    blocked inside the next allreduce), the launcher restarts it, it resumes
    from its durable CheckpointManager state (not from step 0), and the
    cohort converges to the optimum."""
    script = tmp_path / "train_resume.py"
    script.write_text(
        "import os, sys\n"
        "import numpy as np\n"
        "from dmlc_core_tpu.parallel import RabitContext\n"
        "from dmlc_core_tpu.utils.checkpoint import CheckpointManager\n"
        "ctx = RabitContext.from_env()\n"
        "att = int(os.environ.get('DMLC_NUM_ATTEMPT', '0'))\n"
        "mgr = CheckpointManager(\n"
        "    os.environ['CKPT_DIR'] + f'/rank{ctx.rank}', max_to_keep=2)\n"
        "start, w = 0, np.zeros(1)\n"
        "if att > 0 and mgr.latest_step is not None:\n"
        "    s, state = mgr.restore()\n"
        "    start, w = s + 1, state['w']\n"
        "    ctx.resume_seq(state['seq'])\n"
        "    print(f'RESUMED rank {ctx.rank} from step {s}', flush=True)\n"
        "target = 3.0\n"
        "for step in range(start, 10):\n"
        "    g = ctx.allreduce(w - target) / ctx.world_size\n"
        "    w = w - 0.5 * g\n"
        "    mgr.save(step, {'w': w, 'seq': ctx.seq})\n"
        "    if ctx.rank == 1 and att == 0 and step == 5:\n"
        "        print('KILLED-MIDJOB', flush=True)\n"
        "        os._exit(1)\n"
        "final = ctx.allreduce(w) / ctx.world_size\n"
        "assert abs(final[0] - target) < 0.1, final\n"
        "print(f'CONVERGED rank {ctx.rank} attempt {att} '\n"
        "      f'{float(final[0])}', flush=True)\n"
        "ctx.shutdown()\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3", "--max-attempts", "4",
         "--env", f"PYTHONPATH={REPO}",
         "--env", f"CKPT_DIR={tmp_path}",
         "--env", "DMLC_RECOVER_TIMEOUT=30",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO})
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    assert "KILLED-MIDJOB" in out.stdout
    assert out.stdout.count("CONVERGED") == 3
    assert "RESUMED rank 1" in out.stdout


def test_train_ffm_example(tmp_path):
    """The FFM example end-to-end on a small libfm file (single process)."""
    data = tmp_path / "t.libfm"
    _write_synth_libsvm(data, seed=0, libfm=True)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_ffm.py"),
         f"file://{data}", "--features", "256", "--fields", "5",
         "--batch-rows", "128", "--nnz-cap", "2048"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done:" in out.stdout


def test_failure_injection_two_crashes_wide_cohort(tmp_path):
    """Two workers of an 8-wide cohort crash on their first attempt; both
    are reborn by the retry loop, the tree topology assembles with all 8,
    and the allreduce is correct — elastic recovery beyond the minimal
    3-worker case."""
    script = tmp_path / "wide_worker.py"
    script.write_text(
        "import os, sys\n"
        "import numpy as np\n"
        "from dmlc_core_tpu.parallel import RabitContext\n"
        "tid = os.environ['DMLC_TASK_ID']\n"
        "att = int(os.environ.get('DMLC_NUM_ATTEMPT', '0'))\n"
        "if tid in ('2', '5') and att == 0:\n"
        "    print(f'INJECTED-CRASH {tid}', flush=True)\n"
        "    sys.exit(1)\n"
        "ctx = RabitContext.from_env()\n"
        "out = ctx.allreduce(np.array([float(ctx.rank + 1)]))\n"
        "assert out[0] == sum(range(1, ctx.world_size + 1)), out\n"
        "print(f'SURVIVED rank {ctx.rank} attempt {att}', flush=True)\n"
        "ctx.shutdown()\n")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "8",
         "--env", f"PYTHONPATH={REPO}",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=480,
        env={**os.environ, "PYTHONPATH": REPO})
    assert out.returncode == 0, out.stderr[-3000:]
    assert out.stdout.count("INJECTED-CRASH") == 2
    assert out.stdout.count("SURVIVED") == 8
    assert out.stdout.count("attempt 1") == 2   # both reborn workers


def test_train_dcn_example(tmp_path):
    """examples/train_dcn.py runs the full ladder (URI → parse → device
    batches → jitted DCN step → checkpoint) as a user would invoke it."""
    data = tmp_path / "d.libsvm"
    _write_synth_libsvm(data, seed=1)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_dcn.py"),
         f"file://{data}", "--features", "256", "--dim", "8",
         "--layers", "2", "--batch-rows", "128", "--nnz-cap", "2048",
         "--ckpt-dir", str(tmp_path / "ck")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done:" in out.stdout


def test_train_fm_example(tmp_path):
    """examples/train_fm.py — the original quick-start ladder — runs as a
    user invokes it (every shipped example has an integration test)."""
    data = tmp_path / "f.libsvm"
    _write_synth_libsvm(data, seed=2)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_fm.py"),
         f"file://{data}", "--features", "256", "--dim", "4",
         "--batch-rows", "128", "--nnz-cap", "2048",
         "--ckpt-dir", str(tmp_path / "ck")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done:" in out.stdout


def test_mesh_train_fm_example(tmp_path):
    """examples/mesh_train_fm.py on the 8-device virtual mesh (dp=4,mp=2):
    sharded ingest + dim-sharded table through the example's own CLI."""
    data = tmp_path / "m.libsvm"
    _write_synth_libsvm(data, seed=3)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "mesh_train_fm.py"),
         f"file://{data}", "--features", "256", "--dim", "8",
         "--mesh", "dp=4,mp=2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done:" in out.stdout


def test_example_elastic_train_survives_crash(tmp_path):
    """examples/elastic_train.py: rank 2 crashes mid-job, the --elastic
    launcher respawns it, the cohort rebuilds the jax mesh at generation
    1, and training completes on every rank."""
    import subprocess
    import sys

    import numpy as np

    rng = np.random.default_rng(0)
    data = tmp_path / "el.libsvm"
    with open(data, "w") as f:
        for i in range(900):
            idx = np.sort(rng.choice(200, size=6, replace=False))
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    env = {**os.environ, "PYTHONPATH": REPO,
           "DMLC_CHECKPOINT_DIR": str(tmp_path), "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "DMLC_CONNECT_TIMEOUT": "120", "DMLC_RECOVER_TIMEOUT": "300"}
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "tpu", "-n", "3", "--elastic", "--max-attempts", "2",
         "--host-ip", "127.0.0.1", "--env", f"PYTHONPATH={REPO}",
         "--env", "JAX_PLATFORMS=cpu",
         "--env", "XLA_FLAGS=--xla_force_host_platform_device_count=1",
         "--", sys.executable,
         os.path.join(REPO, "examples", "elastic_train.py"),
         f"file://{data}", "--epochs", "3", "--features", "256",
         "--crash-rank", "2", "--crash-epoch", "1"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2500:])
    assert "CRASHING at epoch 1" in out.stdout
    assert "reborn (attempt 1), resuming at epoch 1" in out.stdout
    assert "mesh rebuilt -> gen 1" in out.stdout
    for i in range(3):
        assert f"rank {i} DONE gen=1" in out.stdout, out.stdout[-2000:]
