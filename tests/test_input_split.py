"""InputSplit partition-coverage tests — mirrors reference
``split_repeat_read_test.cc`` / ``split_test.cc``: for every nsplit, the
concatenation of all partitions equals the whole file's records, each exactly
once."""

import os
import random

import pytest

from dmlc_core_tpu.io import (URI, URISpec, create_input_split, expand_uris,
                              open_stream)
from dmlc_core_tpu.utils import DMLCError


def write_lines(path, lines, newline=b"\n"):
    with open(path, "wb") as f:
        for ln in lines:
            f.write(ln + newline)


@pytest.fixture()
def text_corpus(tmp_path):
    rng = random.Random(7)
    lines = [("line%06d:" % i + "x" * rng.randrange(0, 120)).encode()
             for i in range(2000)]
    path = tmp_path / "data.txt"
    write_lines(path, lines)
    return str(path), lines


def test_line_partition_union(text_corpus):
    path, lines = text_corpus
    for nparts in (1, 2, 3, 5, 16):
        got = []
        for k in range(nparts):
            with create_input_split(path, k, nparts, "text",
                                    threaded=False) as s:
                got.extend(s)
        assert got == lines, f"nparts={nparts}"


def test_line_partition_union_no_trailing_newline(tmp_path):
    lines = [b"aaa", b"bb", b"cccc", b"d"]
    path = tmp_path / "nofinalnl.txt"
    with open(path, "wb") as f:
        f.write(b"\n".join(lines))  # no trailing newline
    for nparts in (1, 2, 3, 4):
        got = []
        for k in range(nparts):
            with create_input_split(str(path), k, nparts, "text",
                                    threaded=False) as s:
                got.extend(s)
        assert got == lines


def test_crlf_and_empty_lines(tmp_path):
    raw = b"a\r\nb\n\nc\r\rd\ne\n"
    path = tmp_path / "crlf.txt"
    with open(path, "wb") as f:
        f.write(raw)
    expected = [b"a", b"b", b"c", b"d", b"e"]
    for nparts in (1, 2, 3):
        got = []
        for k in range(nparts):
            with create_input_split(str(path), k, nparts, "text",
                                    threaded=False) as s:
                got.extend(s)
        assert got == expected


def test_multifile_and_wildcard(tmp_path):
    all_lines = []
    for i in range(4):
        lines = [f"f{i}l{j}".encode() for j in range(50)]
        write_lines(tmp_path / f"part-{i}.txt", lines)
        all_lines.extend(lines)
    # wildcard
    got = []
    for k in range(3):
        with create_input_split(str(tmp_path / "part-*.txt"), k, 3, "text",
                                threaded=False) as s:
            got.extend(s)
    assert got == all_lines
    # directory expansion
    with create_input_split(str(tmp_path), 0, 1, "text", threaded=False) as s:
        assert list(s) == all_lines
    # ';' separated
    uri = ";".join(str(tmp_path / f"part-{i}.txt") for i in range(4))
    with create_input_split(uri, 0, 1, "text", threaded=False) as s:
        assert list(s) == all_lines


def test_more_parts_than_records(tmp_path):
    lines = [b"only", b"three", b"lines"]
    path = tmp_path / "tiny.txt"
    write_lines(path, lines)
    got = []
    for k in range(10):
        with create_input_split(str(path), k, 10, "text", threaded=False) as s:
            got.extend(s)
    assert got == lines


def test_chunk_iteration_covers_all(text_corpus):
    path, lines = text_corpus
    total = b"".join(ln + b"\n" for ln in lines)
    got = b""
    for k in range(4):
        with create_input_split(path, k, 4, "text", threaded=False) as s:
            s.hint_chunk_size(4096)
            while True:
                c = s.next_chunk()
                if c is None:
                    break
                got += c
    assert got == total


def test_reset_partition_and_before_first(text_corpus):
    path, lines = text_corpus
    with create_input_split(path, 0, 2, "text", threaded=False) as s:
        first = list(s)
        s.before_first()
        assert list(s) == first
        s.reset_partition(1, 2)
        second = list(s)
        assert first + second == lines


def test_shuffle_covers_all_and_reorders(text_corpus):
    path, lines = text_corpus
    with create_input_split(path, 0, 1, "text", shuffle=True,
                            num_shuffle_parts=8, shuffle_seed=3,
                            threaded=False) as s:
        ep1 = list(s)
        s.before_first()
        ep2 = list(s)
    assert sorted(ep1) == sorted(lines)
    assert sorted(ep2) == sorted(lines)
    assert ep1 != lines  # sub-part order shuffled
    assert ep1 != ep2    # reshuffled per epoch


def test_cached_split(tmp_path, text_corpus):
    path, lines = text_corpus
    cache = tmp_path / "c.cache"
    uri = f"{path}#{cache}"
    with create_input_split(uri, 0, 1, "text") as s:
        ep1 = list(s)
        s.before_first()
        ep2 = list(s)  # replayed from cache
    assert ep1 == lines and ep2 == lines
    assert os.path.exists(str(cache) + ".done")
    # second instance reads only the cache
    with create_input_split(uri, 0, 1, "text") as s:
        assert list(s) == lines


def test_uri_spec():
    spec = URISpec("hdfs://nn/data.txt?format=libsvm&x=1#cachef", 2, 4)
    assert spec.uri == "hdfs://nn/data.txt"
    assert spec.args == {"format": "libsvm", "x": "1"}
    assert spec.cache_file == "cachef.split4.part2"
    u = URI("s3://bucket/key/a.txt")
    assert (u.scheme, u.host, u.name) == ("s3", "bucket", "/key/a.txt")
    u2 = URI("/local/path.txt")
    assert u2.protocol == "" and u2.name == "/local/path.txt"


def test_expand_errors(tmp_path):
    with pytest.raises(DMLCError):
        expand_uris(str(tmp_path / "missing-*.txt"))
    with pytest.raises(DMLCError):
        create_input_split(str(tmp_path / "nope.txt"), 0, 1, "text")


def test_shuffle_with_threaded_wrapper(text_corpus):
    # regression: shuffle=True with the default threaded=True must work
    path, lines = text_corpus
    with create_input_split(path, 0, 1, "text", shuffle=True,
                            shuffle_seed=2) as s:
        ep1 = list(s)
        s.before_first()
        ep2 = list(s)
    assert sorted(ep1) == sorted(lines) == sorted(ep2)
    assert ep1 != ep2


def test_threaded_equals_unthreaded(text_corpus):
    path, lines = text_corpus
    with create_input_split(path, 1, 3, "text", threaded=True) as t, \
         create_input_split(path, 1, 3, "text", threaded=False) as u:
        assert list(t) == list(u)


def test_before_first_mid_stream(text_corpus):
    """Reference split_repeat_read_test.cc: read PART of the stream, reset,
    and the re-read must reproduce the records byte-for-byte — a reset
    must clear the overflow/partial-record carry, not splice it into the
    next epoch.  Covered for plain, threaded, and shuffled splits."""
    path, lines = text_corpus
    for kw in ({}, {"threaded": True},
               {"shuffle": True, "num_shuffle_parts": 4, "shuffle_seed": 7}):
        with create_input_split(path, 0, 1, "text",
                                **({"threaded": False} | kw)) as s:
            seen = []
            for rec in s:
                seen.append(rec)
                if len(seen) == max(3, len(lines) // 3):
                    break               # mid-stream: carry likely nonempty
            s.before_first()
            replay = list(s)
        if kw.get("shuffle"):
            assert sorted(replay) == sorted(lines), kw
        else:
            assert replay == lines, kw
            assert replay[:len(seen)] == seen, kw
