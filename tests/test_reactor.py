"""Event-driven connection fabric (r19): the ``transport.reactor``
primitives (timer wheel, frame reassembly under adversarial chunking,
idle reaping, EMFILE backoff, executor handoff / loop-lag honesty) and
the ported tiers — byte-identical wire vs the threaded router, legacy
clients against a reactor router, the dispatcher's JSON-line RPC plane,
and the SIGKILL chaos drill with router-less client failover."""

import errno
import os
import random
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.models import SparseLogReg  # noqa: E402
from dmlc_core_tpu.serving import (  # noqa: E402
    BucketLadder, InferenceEngine, PredictClient, PredictionServer,
    ServingRouter)
from dmlc_core_tpu.serving.server import (  # noqa: E402
    HELLO_REQ_ID, REQ_HEADER, RSP_HEADER, STATUS_BAD_REQUEST, STATUS_OK)
from dmlc_core_tpu.transport.listener import (  # noqa: E402
    FD_EXHAUSTION_ERRNOS, Listener, accept_once)
from dmlc_core_tpu.transport.reactor import (  # noqa: E402
    FrameAssembler, Reactor, TimerWheel)
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

F = 1000
LEN = struct.Struct("<I")               # toy [u32 length][payload] wire


def _counter(name):
    return metrics.counter(name).value


def _wait_for(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _engine(w_scale=1.0):
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.full((F,), w_scale, jnp.float32),
              "b": jnp.float32(0.0)}
    return InferenceEngine(model, params,
                           buckets=BucketLadder([(16, 512)]))


def _req(rng, rows=4, nnz_per_row=8):
    counts = rng.integers(1, nnz_per_row + 1, size=rows)
    ids = rng.integers(0, F, size=int(counts.sum())).astype(np.int32)
    vals = rng.random(len(ids), dtype=np.float32)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    return ids, vals, row_ptr


def _ref_scores(w_scale, ids, vals, row_ptr):
    return np.array([w_scale * float(vals[row_ptr[r]:row_ptr[r + 1]].sum())
                     for r in range(len(row_ptr) - 1)])


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _echo_reactor(idle_s=0.0):
    """Reactor serving the toy length-prefixed echo protocol; returns
    ``(reactor, listener, frames)`` — frames collects reassembled
    payloads in arrival order."""
    r = Reactor("test-echo", executor_workers=1).start()
    lst = Listener("127.0.0.1", 0)
    frames = []

    def on_frame(conn, header, payload):
        frames.append(bytes(payload))
        conn.write(header + payload)

    def on_accept(sock, _addr):
        asm = FrameAssembler(LEN.size,
                             lambda c, h: LEN.unpack(h)[0], on_frame)
        conn = r.add_connection(sock, lambda c, v: asm.feed(c, v),
                                idle_s=idle_s)
        conn.data = asm

    r.add_listener(lst.sock, on_accept)
    return r, lst, frames


# ---------------------------------------------------------------------------
# timer wheel
# ---------------------------------------------------------------------------

def test_timer_wheel_fires_cancels_and_reports_lag():
    w = TimerWheel(granularity_s=0.05)
    fired = []
    now = 100.0
    w.schedule(now, 0.10, lambda: fired.append("a"))
    t_b = w.schedule(now, 0.10, lambda: fired.append("b"))
    w.schedule(now, 0.30, lambda: fired.append("c"))
    t_b.cancel()
    assert w.next_deadline() == pytest.approx(0.05 * int(100.10 / 0.05))

    # a's slot has fully elapsed at +0.2; c's has not
    n, lag = w.fire_due(now + 0.20)
    assert fired == ["a"] and n == 1
    assert lag == pytest.approx(0.10, abs=0.051)

    # firing late reports the delay — this is the loop-lag ground truth
    n, lag = w.fire_due(now + 1.00)
    assert fired == ["a", "c"] and n == 1
    assert lag == pytest.approx(0.70, abs=0.051)
    assert w.next_deadline() is None


# ---------------------------------------------------------------------------
# frame reassembly under adversarial chunking
# ---------------------------------------------------------------------------

def test_frame_reassembly_trickle_coalesced_torn():
    """Echo fuzz: the same frame set arrives as a 1-byte trickle, as one
    coalesced blob, and in random torn chunks (headers split across
    reads) — reassembly and the echoed byte stream must be exact."""
    rng = random.Random(19)
    payloads = [b"", b"x", bytes(rng.getrandbits(8) for _ in range(3)),
                rng.randbytes(257), rng.randbytes(70000),  # > scratch
                rng.randbytes(1)]
    stream = b"".join(LEN.pack(len(p)) + p for p in payloads)

    def chunkings():
        yield [stream[i:i + 1] for i in range(len(stream))
               ] if len(stream) < 4096 else None     # trickle (bounded)
        yield [stream]                               # fully coalesced
        for _ in range(3):                           # random torn cuts
            cuts = sorted(rng.sample(range(1, len(stream)),
                                     k=min(40, len(stream) - 1)))
            yield [stream[a:b] for a, b in
                   zip([0] + cuts, cuts + [len(stream)])]

    r, lst, frames = _echo_reactor()
    try:
        for chunks in chunkings():
            if chunks is None:
                # trickle the header-heavy prefix only — full 70 KB
                # 1-byte trickle is pointlessly slow
                head = stream[:600]
                chunks = [head[i:i + 1] for i in range(len(head))] \
                    + [stream[600:]]
            del frames[:]
            cli = socket.create_connection((lst.host, lst.port),
                                           timeout=10)
            try:
                for ch in chunks:
                    cli.sendall(ch)
                echoed = _recv_exact(cli, len(stream))
            finally:
                cli.close()
            assert echoed == stream
            assert frames == payloads
    finally:
        lst.close()
        r.stop()


# ---------------------------------------------------------------------------
# idle reaping
# ---------------------------------------------------------------------------

def test_idle_connections_reaped_active_ones_kept():
    # generous idle_s: on a loaded 1-core CI host a keepalive sleep can
    # stretch well past a tight deadline and reap the chatty conn too
    r, lst, _frames = _echo_reactor(idle_s=1.0)
    before = _counter("transport.reactor.idle_reaped")
    try:
        silent = socket.create_connection((lst.host, lst.port), timeout=10)
        chatty = socket.create_connection((lst.host, lst.port), timeout=10)
        silent.settimeout(10.0)
        chatty.settimeout(10.0)
        # traffic every 0.2 s keeps chatty alive well past the deadline
        end = time.monotonic() + 3.0
        while time.monotonic() < end:
            chatty.sendall(LEN.pack(2) + b"hi")
            assert _recv_exact(chatty, LEN.size + 2) is not None
            time.sleep(0.2)
        assert silent.recv(1) == b""        # reaped: EOF
        assert _counter("transport.reactor.idle_reaped") > before
        chatty.sendall(LEN.pack(2) + b"yo")
        assert _recv_exact(chatty, LEN.size + 2) == LEN.pack(2) + b"yo"
        silent.close()
        chatty.close()
    finally:
        lst.close()
        r.stop()


# ---------------------------------------------------------------------------
# EMFILE backoff: reactor accept path and threaded accept_once
# ---------------------------------------------------------------------------

class _FlakyListener:
    """Wraps a real listener; the first ``fails`` accepts raise EMFILE
    (selectors only needs ``fileno()``, so the wrapper registers fine)."""

    def __init__(self, inner, fails):
        self.inner = inner
        self.fails = fails

    def fileno(self):
        return self.inner.sock.fileno()

    def setblocking(self, flag):
        self.inner.sock.setblocking(flag)

    def accept(self):
        if self.fails > 0:
            self.fails -= 1
            raise OSError(errno.EMFILE, "too many open files")
        return self.inner.sock.accept()


def test_reactor_emfile_backoff_rearms_and_recovers():
    r = Reactor("test-emfile", executor_workers=1).start()
    lst = Listener("127.0.0.1", 0)
    flaky = _FlakyListener(lst, fails=2)
    before = _counter("transport.reactor.emfile_backoffs")

    def on_accept(sock, _addr):
        asm = FrameAssembler(LEN.size, lambda c, h: LEN.unpack(h)[0],
                             lambda c, h, p: c.write(h + p))
        conn = r.add_connection(sock, lambda c, v: asm.feed(c, v))
        conn.data = asm

    r.add_listener(flaky, on_accept)
    try:
        cli = socket.create_connection((lst.host, lst.port), timeout=10)
        cli.settimeout(10.0)
        # both EMFILE rounds unregister + re-arm after a jittered pause;
        # the third readiness event accepts for real and echo works
        cli.sendall(LEN.pack(4) + b"ping")
        assert _recv_exact(cli, LEN.size + 4) == LEN.pack(4) + b"ping"
        assert _counter("transport.reactor.emfile_backoffs") - before == 2
        assert flaky.fails == 0
        cli.close()
    finally:
        lst.close()
        r.stop()


def test_accept_once_retries_fd_exhaustion_then_accepts():
    a, b = socket.socketpair()

    class _Srv:
        calls = 0

        def accept(self):
            self.calls += 1
            if self.calls == 1:
                raise OSError(errno.ENFILE, "file table overflow")
            return a, ("peer", 0)

    before = _counter("transport.accept_backoffs")
    got = accept_once(_Srv())
    assert got is not None and got[0] is a
    assert _counter("transport.accept_backoffs") - before == 1

    class _Closed:
        def accept(self):
            raise OSError(errno.EBADF, "closed")         # shutdown path

    assert accept_once(_Closed()) is None
    assert errno.EMFILE in FD_EXHAUSTION_ERRNOS
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# executor handoff + loop-lag honesty
# ---------------------------------------------------------------------------

def test_executor_results_hop_back_to_loop():
    r = Reactor("test-exec", executor_workers=1).start()
    done = threading.Event()
    seen = {}

    def on_done(res, exc):
        seen["res"], seen["exc"], seen["on_loop"] = res, exc, r.in_loop()
        done.set()

    try:
        r.executor.submit(lambda: 40 + 2, on_done)
        assert done.wait(5.0)
        assert seen == {"res": 42, "exc": None, "on_loop": True}

        done.clear()
        r.executor.submit(lambda: 1 / 0, on_done)
        assert done.wait(5.0)
        assert isinstance(seen["exc"], ZeroDivisionError)
    finally:
        r.stop()


def test_loop_lag_visible_under_executor_saturation():
    """Flood a 1-worker executor from the loop: the bounded queue fills,
    overflow runs inline on the loop thread, and the heartbeat timer's
    fire-time slip surfaces on ``transport.reactor.loop_lag_ms`` —
    saturation is visible, never a silent deadlock."""
    r = Reactor("test-lag", executor_workers=1)
    inline_before = _counter("transport.reactor.executor_inline")
    r.start()
    gauge = metrics.gauge("transport.reactor.loop_lag_ms")

    def flood():
        for _ in range(40):
            r.executor.submit(lambda: time.sleep(0.02))

    try:
        r.call_soon(flood)
        peak, deadline = 0.0, time.monotonic() + 3.0
        while time.monotonic() < deadline:
            peak = max(peak, gauge.value)
            time.sleep(0.01)
        assert _counter("transport.reactor.executor_inline") > inline_before
        assert peak >= 50.0, f"loop lag never surfaced (peak {peak} ms)"
        # and the loop survived the abuse
        pong = threading.Event()
        r.call_soon(pong.set)
        assert pong.wait(5.0)
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# ported tiers: wire equivalence + legacy interop
# ---------------------------------------------------------------------------

def _raw_response(host, port, frame, status):
    """Send one raw request frame, return the full response bytes."""
    with socket.create_connection((host, port), timeout=10) as s:
        s.settimeout(10.0)
        s.sendall(frame)
        head = _recv_exact(s, RSP_HEADER.size)
        assert head is not None
        req_id, st, n = RSP_HEADER.unpack(head)
        assert st == status
        body = _recv_exact(s, 4 * n if st == STATUS_OK else n)
        assert body is not None
        return head + body


def test_wire_byte_identical_threaded_vs_reactor_router():
    """The port's core promise: the reactor router emits the exact bytes
    the threaded router does — OK scores and BAD_REQUEST rejects — for
    identical request frames against the same replica."""
    srv = PredictionServer(_engine(2.0), metrics_port=0).start()
    threaded = ServingRouter(replicas=[(srv.host, srv.port)],
                             reactor=False).start()
    reactor = ServingRouter(replicas=[(srv.host, srv.port)],
                            reactor=True).start()
    try:
        rng = np.random.default_rng(7)
        ids, vals, row_ptr = _req(rng, rows=3)
        rows, nnz = len(row_ptr) - 1, len(ids)
        ok_frame = REQ_HEADER.pack(77, 0, 0, rows, nnz) \
            + row_ptr.tobytes() + ids.tobytes() + vals.tobytes()
        # hello preamble + request: model routing is part of the wire
        blob = b"default"
        hello_ok = REQ_HEADER.pack(HELLO_REQ_ID, 0, 0, 0, len(blob)) \
            + blob + ok_frame
        # header validation rejects before reading any tail
        bad_frame = REQ_HEADER.pack(78, 0, 0, (1 << 20) + 1, 4)

        for frame, status in ((ok_frame, STATUS_OK),
                              (hello_ok, STATUS_OK),
                              (bad_frame, STATUS_BAD_REQUEST)):
            a = _raw_response(threaded.host, threaded.port, frame, status)
            b = _raw_response(reactor.host, reactor.port, frame, status)
            assert a == b, f"wire divergence for status={status}"

        scores = np.frombuffer(
            _raw_response(reactor.host, reactor.port, ok_frame,
                          STATUS_OK)[RSP_HEADER.size:], np.float32)
        np.testing.assert_allclose(
            scores, _ref_scores(2.0, ids, vals, row_ptr), rtol=1e-5)
    finally:
        reactor.stop()
        threaded.stop()
        srv.stop()


def test_legacy_client_unmodified_against_reactor_router():
    """PredictClient predates the reactor and must not notice it —
    pipelined predicts, hello model routing, clean close."""
    srv = PredictionServer(_engine(1.5), metrics_port=0).start()
    router = ServingRouter(replicas=[(srv.host, srv.port)],
                           reactor=True).start()
    cli = PredictClient(router.host, router.port, model_id="default")
    try:
        rng = np.random.default_rng(3)
        futs, refs = [], []
        for _ in range(16):                       # pipelined, no waits
            ids, vals, row_ptr = _req(rng)
            futs.append(cli.submit(ids, vals, row_ptr))
            refs.append(_ref_scores(1.5, ids, vals, row_ptr))
        for fut, ref in zip(futs, refs):
            np.testing.assert_allclose(fut.result(timeout=30), ref,
                                       rtol=1e-5)
    finally:
        cli.close()
        router.stop()
        srv.stop()


def test_dispatcher_reactor_rpc_plane():
    """JSON-line RPCs against the reactor-backed dispatcher: a trickled
    request parses, junk gets an error reply, and a line that never
    terminates is killed at the 4 MB bound instead of buffered forever."""
    from dmlc_core_tpu.pipeline.data_service.dispatcher import Dispatcher

    d = Dispatcher(port=0, reactor=True)
    d.start()
    try:
        # one-byte trickle of a valid command
        msg = b'{"cmd": "list_workers"}\n'
        with socket.create_connection((d.host, d.port), timeout=10) as s:
            s.settimeout(10.0)
            for i in range(len(msg)):
                s.sendall(msg[i:i + 1])
            reply = s.makefile("r").readline()
        assert "workers" in reply and "error" not in reply

        with socket.create_connection((d.host, d.port), timeout=10) as s:
            s.settimeout(10.0)
            s.sendall(b"this is not json\n")
            reply = s.makefile("r").readline()
        assert "error" in reply

        # unterminated line: the reactor kills the connection at the
        # bound — recv sees EOF/RST, never an unbounded buffer
        with socket.create_connection((d.host, d.port), timeout=10) as s:
            s.settimeout(10.0)
            try:
                s.sendall(b"x" * ((4 << 20) + (64 << 10)))
                assert s.recv(1) == b""
            except OSError:
                pass                               # RST also acceptable
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# chaos drill: SIGKILL the reactor router mid-load
# ---------------------------------------------------------------------------

def test_chaos_sigkill_reactor_router_client_fails_over():
    """Run a reactor-mode router as a real OS process, SIGKILL it with
    requests in flight, and require the stock client's endpoint sweep to
    land every request on the direct replica — correct scores for all,
    no duplicates (each future settles exactly once), failovers
    counted."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    srv = PredictionServer(_engine(1.0), metrics_port=0).start()
    env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu",
           "DMLC_SERVE_REACTOR": "1"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "dmlc_core_tpu.serving.fleet.router",
         f"replicas={srv.host}:{srv.port}", "host=127.0.0.1", "port=0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        bufsize=1, env=env)
    cli = None
    try:
        line = proc.stdout.readline()
        assert line.startswith("routing on "), (line, proc.stderr.read())
        rhost, rport = line.split()[-1].rsplit(":", 1)

        before = _counter("serving.client.failovers")
        cli = PredictClient(rhost, int(rport),
                            endpoints=[(srv.host, srv.port)])
        rng = np.random.default_rng(11)
        reqs = [_req(rng) for _ in range(24)]
        futs = []
        for i, (ids, vals, row_ptr) in enumerate(reqs):
            futs.append(cli.submit(ids, vals, row_ptr))
            if i == 7:                     # kill with futures in flight
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
        results = [f.result(timeout=60) for f in futs]

        # exactly one settled result per request, all correct — replayed
        # frames may score twice server-side, but the client surfaces
        # each exactly once
        assert len(results) == len(reqs)
        for got, (ids, vals, row_ptr) in zip(results, reqs):
            np.testing.assert_allclose(
                got, _ref_scores(1.0, ids, vals, row_ptr), rtol=1e-5)
        assert _counter("serving.client.failovers") > before
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
        proc.stderr.close()
        if cli is not None:
            cli.close()
        srv.stop()
