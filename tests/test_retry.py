"""Unit tests for the unified resilience layer: Deadline budgets,
RetryPolicy (full-jitter backoff, retryable predicate, server-directed
Retry-After floor), CircuitBreaker state machine, and the deterministic
fault-injection plan language (``DMLC_FAULT_SPEC``)."""

import time

import pytest

from dmlc_core_tpu.utils import (
    CircuitBreaker, CircuitOpen, Deadline, DeadlineExpired, FaultInjected,
    FaultSpecError, RetriesExhausted, RetryPolicy, clear_faults, fault_point,
    inject_faults, install_faults)
from dmlc_core_tpu.utils.faults import _parse_duration, active_spec
from dmlc_core_tpu.utils.metrics import metrics


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------

def test_deadline_remaining_and_clamp():
    clk = FakeClock()
    dl = Deadline(10.0, clock=clk)
    assert dl.remaining() == pytest.approx(10.0)
    assert dl.clamp(3.0) == pytest.approx(3.0)
    assert dl.clamp(30.0) == pytest.approx(10.0)
    clk.advance(9.5)
    assert dl.clamp(3.0) == pytest.approx(0.5)
    assert not dl.expired()
    clk.advance(1.0)
    assert dl.expired()
    assert dl.clamp(3.0) == 0.0
    with pytest.raises(DeadlineExpired):
        dl.check("unit test")


def test_deadline_unbounded_never_expires():
    dl = Deadline(None)
    assert dl.remaining() == float("inf")
    assert not dl.expired()
    assert dl.clamp(7.0) == 7.0
    dl.check()                              # no raise
    assert not Deadline.unbounded().expired()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    sleeps = []
    policy = RetryPolicy(max_attempts=5, base_delay_s=0.01, seed=0,
                         name="ut.transient", sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert policy.call(flaky) == "ok"
    assert calls["n"] == 3
    assert len(sleeps) == 2                 # one sleep per retry


def test_retry_counts_total_attempts_and_chains_cause():
    policy = RetryPolicy(max_attempts=3, seed=0, name="ut.exhaust",
                         sleep=lambda s: None)
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError("still down")

    before = metrics.counter("retry.ut.exhaust.exhausted").value
    with pytest.raises(RetriesExhausted) as ei:
        policy.call(always_fails)
    assert calls["n"] == 3                  # max_attempts is TOTAL tries
    assert isinstance(ei.value.__cause__, OSError)
    assert metrics.counter("retry.ut.exhaust.exhausted").value == before + 1


def test_retry_non_retryable_raises_immediately():
    policy = RetryPolicy(max_attempts=5, sleep=lambda s: None)
    calls = {"n": 0}

    def typo():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        policy.call(typo)
    assert calls["n"] == 1


def test_retry_custom_retryable_predicate():
    class Shed(Exception):
        pass

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.0,
                         retryable=lambda e: isinstance(e, Shed),
                         sleep=lambda s: None)
    calls = {"n": 0}

    def shed_twice():
        calls["n"] += 1
        if calls["n"] < 3:
            raise Shed()
        return calls["n"]

    assert policy.call(shed_twice) == 3


def test_retry_backoff_full_jitter_bounds_and_determinism():
    p1 = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, seed=42)
    p2 = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, seed=42)
    for attempt in range(1, 10):
        cap = min(1.0, 0.1 * 2.0 ** (attempt - 1))
        d1 = p1.backoff_s(attempt)
        assert 0.0 <= d1 <= cap
        assert d1 == p2.backoff_s(attempt)   # same seed → same schedule


def test_retry_deadline_stops_the_schedule():
    clk = FakeClock()
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clk.advance(max(s, 0.3))            # attempts burn wall clock too

    policy = RetryPolicy(max_attempts=100, base_delay_s=0.05,
                         name="ut.deadline", sleep=fake_sleep)
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(DeadlineExpired) as ei:
        policy.call(always_fails, deadline=Deadline(1.0, clock=clk))
    assert isinstance(ei.value.__cause__, OSError)
    assert calls["n"] < 100                 # budget, not attempt cap, ended it
    # every sleep was clamped to the remaining budget
    assert all(s <= 1.0 for s in sleeps)


def test_retry_honors_retry_after_hint_clamped_by_deadline():
    class Overloaded(OSError):
        def __init__(self, retry_after_s):
            super().__init__("429")
            self.retry_after_s = retry_after_s

    sleeps = []
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                         sleep=sleeps.append)
    calls = {"n": 0}

    def server_says_wait():
        calls["n"] += 1
        if calls["n"] == 1:
            raise Overloaded(5.0)
        return "ok"

    assert policy.call(server_says_wait) == "ok"
    assert sleeps == [5.0]                  # hint raised the backoff floor

    sleeps.clear()
    calls["n"] = 0
    clk = FakeClock()
    assert policy.call(server_says_wait,
                       deadline=Deadline(0.5, clock=clk)) == "ok"
    assert sleeps == [0.5]                  # hostile hint capped at budget


def test_retry_on_retry_callback_sees_each_failure():
    seen = []
    policy = RetryPolicy(max_attempts=4, base_delay_s=0.0,
                         sleep=lambda s: None)
    calls = {"n": 0}

    def fails_twice():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(f"boom {calls['n']}")
        return "ok"

    policy.call(fails_twice,
                on_retry=lambda a, e: seen.append((a, str(e))))
    assert seen == [(1, "boom 1"), (2, "boom 2")]


def test_retry_from_env_and_explicit_kwargs_win(monkeypatch):
    monkeypatch.setenv("UT_RETRIES", "7")
    monkeypatch.setenv("UT_BACKOFF_BASE", "0.25")
    monkeypatch.setenv("UT_BACKOFF_MAX", "3.5")
    monkeypatch.setenv("UT_DEADLINE", "9")
    p = RetryPolicy.from_env("UT")
    assert p.max_attempts == 7
    assert p.base_delay_s == 0.25
    assert p.max_delay_s == 3.5
    assert p.deadline_s == 9
    assert p.name == "ut"
    # explicit kwargs beat the env
    p2 = RetryPolicy.from_env("UT", max_attempts=2, name="mine")
    assert p2.max_attempts == 2 and p2.name == "mine"
    # DEADLINE=0 means unbounded
    monkeypatch.setenv("UT_DEADLINE", "0")
    assert RetryPolicy.from_env("UT").deadline_s is None


def test_retry_counter_increments():
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0,
                         name="ut.counted", sleep=lambda s: None)
    before = metrics.counter("retry.ut.counted.retries").value
    calls = {"n": 0}

    def fails_once():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("x")
        return "ok"

    policy.call(fails_once)
    assert metrics.counter("retry.ut.counted.retries").value == before + 1


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

def test_breaker_opens_after_threshold_and_fast_fails():
    clk = FakeClock()
    br = CircuitBreaker("ut.open", failure_threshold=3, cooldown_s=10.0,
                        clock=clk)
    opens_before = metrics.counter("circuit.ut.open.opens").value
    for _ in range(2):
        br.allow()
        br.record_failure()
    assert br.state == "closed"             # under threshold
    br.allow()
    br.record_failure()                     # third consecutive: opens
    assert br.state == "open"
    assert metrics.counter("circuit.ut.open.opens").value == opens_before + 1
    ff_before = metrics.counter("circuit.ut.open.fast_fails").value
    with pytest.raises(CircuitOpen):
        br.allow()
    assert metrics.counter(
        "circuit.ut.open.fast_fails").value == ff_before + 1


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker("ut.streak", failure_threshold=3,
                        cooldown_s=10.0, clock=FakeClock())
    for _ in range(2):
        br.record_failure()
    br.record_success()
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed"             # streak broken, never opened


def test_breaker_half_open_admits_single_probe():
    clk = FakeClock()
    br = CircuitBreaker("ut.probe", failure_threshold=1, cooldown_s=5.0,
                        clock=clk)
    br.record_failure()
    assert br.state == "open"
    clk.advance(5.0)
    assert br.state == "half_open"
    br.allow()                              # this caller is THE probe
    with pytest.raises(CircuitOpen):
        br.allow()                          # everyone else keeps failing fast
    br.record_success()                     # probe succeeded
    assert br.state == "closed"
    br.allow()


def test_breaker_failed_probe_restarts_cooldown():
    clk = FakeClock()
    br = CircuitBreaker("ut.reprobe", failure_threshold=1, cooldown_s=5.0,
                        clock=clk)
    br.record_failure()
    clk.advance(5.0)
    br.allow()                              # probe
    br.record_failure()                     # probe failed
    assert br.state == "open"               # cooldown restarted
    clk.advance(4.9)
    with pytest.raises(CircuitOpen):
        br.allow()
    clk.advance(0.2)
    br.allow()                              # next probe window


def test_breaker_call_wrapper_records_outcomes():
    clk = FakeClock()
    br = CircuitBreaker("ut.wrap", failure_threshold=2, cooldown_s=5.0,
                        clock=clk)
    with pytest.raises(OSError):
        br.call(lambda: (_ for _ in ()).throw(OSError("x")))
    with pytest.raises(OSError):
        br.call(lambda: (_ for _ in ()).throw(OSError("x")))
    with pytest.raises(CircuitOpen):
        br.call(lambda: "never runs")
    clk.advance(5.0)
    assert br.call(lambda: "ok") == "ok"    # probe succeeds, re-closes
    assert br.state == "closed"


def test_breaker_from_env(monkeypatch):
    monkeypatch.setenv("UT_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("UT_BREAKER_COOLDOWN", "1.5")
    br = CircuitBreaker.from_env("UT")
    assert br.failure_threshold == 2
    assert br.cooldown_s == 1.5


# ---------------------------------------------------------------------------
# fault-injection plan language
# ---------------------------------------------------------------------------

def test_fault_spec_parse_errors_are_loud():
    for bad in ["", ":error=1", "site:error", "site:error=x",
                "site:latency=4q", "site:bogus=1", "site:times=maybe"]:
        with pytest.raises(FaultSpecError):
            install_faults(bad)


def test_parse_duration_forms():
    assert _parse_duration("50ms") == pytest.approx(0.05)
    assert _parse_duration("0.2s") == pytest.approx(0.2)
    assert _parse_duration("3") == pytest.approx(3.0)
    with pytest.raises(FaultSpecError):
        _parse_duration("fast")


def test_fault_point_noop_when_nothing_installed():
    clear_faults()
    assert active_spec() is None
    snap_before = {k: v for k, v in metrics.snapshot().items()
                   if k.startswith("faults.")}
    for _ in range(100):
        fault_point("ut.some.site")         # must not raise, sleep, or count
    snap_after = {k: v for k, v in metrics.snapshot().items()
                  if k.startswith("faults.")}
    assert snap_before == snap_after


def test_fault_error_with_times_and_after():
    fired = 0
    with inject_faults("ut.kill:error=1:times=2:after=3"):
        for i in range(10):
            try:
                fault_point("ut.kill")
            except FaultInjected as e:
                assert isinstance(e, OSError)   # composes with retry layers
                fired += 1
                # calls are 1-based: after=3 skips 1..3, times=2 arms 4..5
                assert i in (3, 4)
    assert fired == 2


def test_fault_seeded_probability_is_deterministic():
    def schedule():
        hits = []
        with inject_faults("ut.p:error=0.5:seed=123"):
            for i in range(40):
                try:
                    fault_point("ut.p")
                except FaultInjected:
                    hits.append(i)
        return hits

    a, b = schedule(), schedule()
    assert a == b                           # identical replayed schedule
    assert 0 < len(a) < 40                  # actually probabilistic


def test_fault_latency_sleeps_and_counts():
    before = metrics.counter("faults.ut.slow.delays").value
    with inject_faults("ut.slow:latency=30ms"):
        t0 = time.monotonic()
        fault_point("ut.slow")
        assert time.monotonic() - t0 >= 0.025
    assert metrics.counter("faults.ut.slow.delays").value == before + 1


def test_fault_prefix_glob_matches():
    with inject_faults("ingest.*:error=1:times=1"):
        with pytest.raises(FaultInjected):
            fault_point("ingest.recv")
        fault_point("serving.server.admit")   # different prefix: untouched


def test_fault_env_var_drives_probes(monkeypatch):
    monkeypatch.setenv("DMLC_FAULT_SPEC", "ut.env:error=1:times=1")
    with pytest.raises(FaultInjected):
        fault_point("ut.env")
    fault_point("ut.env")                   # times=1: healed
    monkeypatch.delenv("DMLC_FAULT_SPEC")
    fault_point("ut.env")                   # env cleared: exact no-op again
    assert active_spec() is None


def test_fault_install_wins_over_env(monkeypatch):
    monkeypatch.setenv("DMLC_FAULT_SPEC", "ut.a:error=1")
    install_faults("ut.b:error=1:times=1")
    fault_point("ut.a")                     # env plan is shadowed
    with pytest.raises(FaultInjected):
        fault_point("ut.b")
    clear_faults()


def test_fault_error_counter_increments():
    before = metrics.counter("faults.ut.ctr.errors").value
    with inject_faults("ut.ctr:error=1:times=3"):
        for _ in range(5):
            try:
                fault_point("ut.ctr")
            except FaultInjected:
                pass
    assert metrics.counter("faults.ut.ctr.errors").value == before + 3
