"""dmlc-train CLI: config file + CLI overrides through the Parameter
system, model selection through the registry, training, AUC, checkpoint —
the reference ecosystem's xgboost-style UX composed from config.h +
parameter.h + registry.h counterparts."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.models.cli", *args],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})


@pytest.fixture()
def libsvm_file(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "t.libsvm"
    w = rng.standard_normal(50)
    with open(path, "w") as f:
        for _ in range(800):
            idx = np.sort(rng.choice(50, size=8, replace=False))
            x = rng.random(8)
            y = int((w[idx] * x).sum() > 0)
            f.write(f"{y} " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(idx, x)) + "\n")
    return str(path)


def test_cli_config_file_with_overrides(libsvm_file, tmp_path):
    conf = tmp_path / "train.conf"
    conf.write_text(
        "# xgboost-style config\n"
        f"data = {libsvm_file}\n"
        "model = logreg\n"
        "features = 64\n"
        "epochs = 1\n"
        "batch_rows = 128\n"
        "nnz_cap = 2048\n"
        "lr = 0.1\n"
        "log_every = 0\n")
    ckpt = tmp_path / "ck"
    # CLI overrides the file's model and adds a checkpoint dir
    out = _run([str(conf), "model=fm", "dim=4", f"ckpt_dir={ckpt}",
                "epochs=2"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained fm:" in out.stdout
    assert "train AUC" in out.stdout
    auc = float(out.stdout.split("train AUC")[1].split()[0])
    assert auc > 0.7, out.stdout
    assert "checkpoint step" in out.stdout
    assert (ckpt / "MANIFEST.json").exists() or any(ckpt.iterdir())


def test_cli_ffm_on_libfm(libsvm_file, tmp_path):
    rng = np.random.default_rng(1)
    path = tmp_path / "t.libfm"
    with open(path, "w") as f:
        for _ in range(400):
            k = int(rng.integers(1, 5))
            ent = " ".join(f"{int(rng.integers(0, 5))}:"
                           f"{int(rng.integers(0, 100))}:"
                           f"{rng.random():.3f}" for _ in range(k))
            f.write(f"{int(rng.integers(0, 2))} {ent}\n")
    out = _run([f"data={path}", "model=ffm", "features=128", "fields=5",
                "dim=3", "batch_rows=128", "nnz_cap=2048", "log_every=0"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained ffm:" in out.stdout


def test_cli_errors_loudly(libsvm_file):
    # unknown key lists candidates
    out = _run([f"data={libsvm_file}", "modle=fm"])
    assert out.returncode == 2
    assert "unknown parameter 'modle'" in out.stderr
    assert "model" in out.stderr            # candidates listed
    # enum violation
    out = _run([f"data={libsvm_file}", "model=resnet"])
    assert out.returncode == 2
    # missing required
    out = _run(["model=fm"])
    assert out.returncode == 2
    assert "data" in out.stderr


def test_cli_help_prints_docstring():
    out = _run(["--help"])
    assert out.returncode == 0
    assert "Parameters of TrainParams" in out.stdout
    assert "batch_rows" in out.stdout


def test_cli_malformed_config_and_suffix_resolution(tmp_path):
    bad = tmp_path / "bad.conf"
    bad.write_text("model\n")          # missing '='
    out = _run([str(bad)])
    assert out.returncode == 2
    assert "dmlc-train:" in out.stderr and "Traceback" not in out.stderr

    # .csv suffix resolves the parser without an explicit format=
    rng = np.random.default_rng(2)
    path = tmp_path / "t.csv"
    with open(path, "w") as f:
        for _ in range(300):
            row = rng.random(6)
            f.write(f"{int(rng.integers(0, 2))}," +
                    ",".join(f"{v:.3f}" for v in row) + "\n")
    out = _run([f"data={path}?label_column=0", "model=logreg",
                "features=16", "batch_rows=64", "nnz_cap=1024",
                "log_every=0"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained logreg:" in out.stdout


def test_cli_resume_continues_from_checkpoint(libsvm_file, tmp_path):
    ckpt = tmp_path / "ck"
    common = [f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
              "batch_rows=128", "nnz_cap=2048", "lr=0.05",
              f"ckpt_dir={ckpt}", "log_every=0", "eval_auc=0"]
    a = _run(common)
    assert a.returncode == 0, a.stderr[-2000:]
    loss_a = float(a.stdout.split("final loss")[1].split()[0])
    b = _run(common + ["resume=1"])
    assert b.returncode == 0, b.stderr[-2000:]
    assert "resumed from step" in b.stdout
    loss_b = float(b.stdout.split("final loss")[1].split()[0])
    assert loss_b < loss_a, (loss_a, loss_b)   # training actually continued
    # resume without ckpt_dir is a loud config error
    c = _run([f"data={libsvm_file}", "resume=1"])
    assert c.returncode == 2


def test_cli_resume_restores_optimizer_state(libsvm_file, tmp_path):
    """resume must restore Adam moments, not just params (ADVICE r3):
    the checkpoint carries opt_state, the resumed run reports a clean
    resume, and a legacy params-only checkpoint resumes with a loud
    moments-reset warning instead of failing."""
    ckpt = tmp_path / "ck"
    common = [f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
              "batch_rows=128", "nnz_cap=2048", "lr=0.05",
              f"ckpt_dir={ckpt}", "log_every=0", "eval_auc=0"]
    assert _run(common).returncode == 0
    # the saved state itself carries opt_state
    from dmlc_core_tpu.utils import CheckpointManager
    _, state = CheckpointManager(str(ckpt)).restore()
    assert "opt_state" in state and "params" in state
    b = _run(common + ["resume=1"])
    assert b.returncode == 0, b.stderr[-2000:]
    assert "resumed from step" in b.stdout
    assert "moments reset" not in b.stdout

    # legacy params-only checkpoint: resumes, warns, still trains
    legacy = tmp_path / "ck_legacy"
    mgr = CheckpointManager(str(legacy))
    mgr.save(7, {"params": state["params"]}, meta={"model": "fm"})
    c = _run([f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
              "batch_rows=128", "nnz_cap=2048", "lr=0.05",
              f"ckpt_dir={legacy}", "log_every=0", "eval_auc=0",
              "resume=1"])
    assert c.returncode == 0, c.stderr[-2000:]
    assert "moments reset" in c.stdout


def test_cli_predict_keeps_weight_zero_rows(libsvm_file, tmp_path):
    """Predict output is one score per INPUT row: rows with an explicit
    weight of 0 (libsvm 'label:weight' head) must not be dropped — padding
    is identified by row count, not by weight (ADVICE r3)."""
    rng = np.random.default_rng(5)
    path = tmp_path / "w0.libsvm"
    nrows = 137                       # not a batch multiple → padded tail
    with open(path, "w") as f:
        for i in range(nrows):
            idx = np.sort(rng.choice(50, size=4, replace=False))
            x = rng.random(4)
            w = 0 if i % 3 == 0 else 1   # a third of rows weigh 0
            f.write(f"{i % 2}:{w} " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(idx, x)) + "\n")
    ckpt = tmp_path / "ck"
    assert _run([f"data={libsvm_file}", "model=logreg", "features=64",
                 "batch_rows=64", "nnz_cap=1024", f"ckpt_dir={ckpt}",
                 "log_every=0", "eval_auc=0"]).returncode == 0
    pred = tmp_path / "scores.txt"
    out = _run([f"data={path}", "mode=predict", "model=logreg",
                "features=64", "batch_rows=64", "nnz_cap=1024",
                f"ckpt_dir={ckpt}", f"output=file://{pred}"])
    assert out.returncode == 0, out.stderr[-2000:]
    scores = pred.read_text().split()
    assert len(scores) == nrows, (len(scores), nrows)


def test_cli_predict_mode_roundtrip(libsvm_file, tmp_path):
    """train → checkpoint → predict: one score per row, informative AUC,
    and a model-name mismatch against the checkpoint meta fails loudly."""
    ckpt = tmp_path / "ck"
    common = [f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
              "batch_rows=128", "nnz_cap=2048", "lr=0.1", "epochs=3",
              f"ckpt_dir={ckpt}", "log_every=0", "eval_auc=0"]
    assert _run(common).returncode == 0
    pred = tmp_path / "scores.txt"
    out = _run([f"data={libsvm_file}", "mode=predict", "model=fm",
                "features=64", "dim=4", "batch_rows=128", "nnz_cap=2048",
                f"ckpt_dir={ckpt}", f"output=file://{pred}"])
    assert out.returncode == 0, out.stderr[-2000:]
    scores = [float(x) for x in pred.read_text().split()]
    labels = [int(line.split()[0]) for line in
              open(libsvm_file).read().splitlines()]
    assert len(scores) == len(labels)
    assert all(0.0 <= s <= 1.0 for s in scores)      # sigmoid applied
    # scores must actually rank the labels (train AUC >~ chance)
    import numpy as _np
    s, y = _np.asarray(scores), _np.asarray(labels)
    pos, neg = s[y == 1], s[y == 0]
    auc = (pos[:, None] > neg[None, :]).mean()
    assert auc > 0.7, auc

    # mismatched model name vs checkpoint meta
    bad = _run([f"data={libsvm_file}", "mode=predict", "model=logreg",
                "features=64", "batch_rows=128", "nnz_cap=2048",
                f"ckpt_dir={ckpt}", f"output=file://{pred}"])
    assert bad.returncode == 2
    assert "trained as 'fm'" in bad.stderr
    # missing output
    bad2 = _run([f"data={libsvm_file}", "mode=predict",
                 f"ckpt_dir={ckpt}"])
    assert bad2.returncode == 2


def test_cli_trains_from_ingest_workers(libsvm_file, tmp_path):
    """workers= routes the CLI through the disaggregated ingest service."""
    from conftest import start_ingest_worker

    port = start_ingest_worker(f"file://{libsvm_file}", 0, 1,
                               batch_rows=128, nnz_cap=2048, max_epochs=4)
    out = _run([f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
                f"workers=127.0.0.1:{port}", "batch_rows=128",
                "nnz_cap=2048", "epochs=2", "log_every=0", "eval_auc=0"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained fm:" in out.stdout


def test_cli_valid_watchlist(libsvm_file, tmp_path):
    out = _run([f"data={libsvm_file}", f"valid={libsvm_file}", "model=fm",
                "features=64", "dim=4", "batch_rows=128", "nnz_cap=2048",
                "lr=0.1", "epochs=2", "log_every=0", "eval_auc=0"])
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if "valid acc" in ln]
    assert len(lines) == 2                       # once per epoch
    assert "auc" in lines[-1]
    final_auc = float(lines[-1].split("auc")[1])
    assert final_auc > 0.7, lines


def test_cli_periodic_async_checkpoints(libsvm_file, tmp_path):
    """ckpt_every=N async-saves during training (overlapping the loop),
    waits before exit, and resume from a mid-train checkpoint works."""
    ckpt = tmp_path / "ck"
    out = _run([f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
                "batch_rows=128", "nnz_cap=2048", "lr=0.05",
                f"ckpt_dir={ckpt}", "ckpt_every=3", "log_every=0",
                "eval_auc=0"])
    assert out.returncode == 0, out.stderr[-2000:]
    import sys as _sys
    from dmlc_core_tpu.utils import CheckpointManager
    mgr = CheckpointManager(str(ckpt))
    # 800 rows / 128 = 7 steps: every-3 saves at 3,6 + final at 7; bounded
    # retention (3) keeps them all
    assert mgr.steps == [3, 6, 7], mgr.steps
    step, st = mgr.restore(6)
    assert step == 6 and "opt_state" in st


def test_cli_trains_dcn(libsvm_file, tmp_path):
    """model=dcn end-to-end through dmlc-train: the registry-derived enum
    accepts it and the cross network trains to a meaningful AUC on the
    linear-signal corpus."""
    ckpt = tmp_path / "ck"
    out = _run([f"data={libsvm_file}", "model=dcn", "features=64", "dim=8",
                "layers=2", "epochs=3", "batch_rows=128", "nnz_cap=2048",
                "lr=0.05", "log_every=0", f"ckpt_dir={ckpt}"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trained dcn:" in out.stdout
    assert "train AUC" in out.stdout, out.stdout
    auc = float(out.stdout.split("train AUC")[1].split()[0])
    assert auc > 0.7, out.stdout


def test_cli_kstep_fused_matches_per_step(libsvm_file, tmp_path):
    """kstep=N routes training through the fused k-step dispatch; the SGD
    trajectory (and so the final loss/AUC) matches the per-step loop, and
    periodic checkpointing still fires at the group-crossed cadence."""
    ck = tmp_path / "ck_fused"
    base = [f"data={libsvm_file}", "model=fm", "features=64", "dim=4",
            "epochs=2", "batch_rows=128", "nnz_cap=2048", "lr=0.05",
            "log_every=0", "seed=3"]
    out1 = _run(base)
    out4 = _run(base + ["kstep=4", f"ckpt_dir={ck}", "ckpt_every=3"])
    assert out1.returncode == 0, out1.stderr[-2000:]
    assert out4.returncode == 0, out4.stderr[-2000:]
    loss1 = float(out1.stdout.split("final loss")[1].split()[0])
    loss4 = float(out4.stdout.split("final loss")[1].split()[0])
    assert abs(loss1 - loss4) < 1e-4, (loss1, loss4)
    auc1 = float(out1.stdout.split("train AUC")[1].split()[0])
    auc4 = float(out4.stdout.split("train AUC")[1].split()[0])
    assert abs(auc1 - auc4) < 1e-3, (auc1, auc4)
    assert "checkpoint step" in out4.stdout
    assert any(ck.iterdir())
    # both ran the same number of steps (2 epochs x ceil(800/128))
    steps1 = out1.stdout.split("trained fm:")[1].split()[0]
    steps4 = out4.stdout.split("trained fm:")[1].split()[0]
    assert steps1 == steps4 == "14", (steps1, steps4)


def test_cli_kstep_with_ingest_workers(libsvm_file, tmp_path):
    """kstep=N composes with workers= : remote wire frames feed the fused
    k-step trainer directly (no per-frame transfer stage), and the final
    loss matches the per-step remote run's trajectory."""
    from conftest import start_ingest_worker

    def start_worker():
        return start_ingest_worker(f"file://{libsvm_file}", 0, 1,
                                   batch_rows=128, nnz_cap=2048,
                                   max_epochs=2)

    base = ["model=fm", "features=64", "dim=4", "batch_rows=128",
            "nnz_cap=2048", "epochs=1", "log_every=0", "eval_auc=0",
            "lr=0.05", "seed=3", f"data={libsvm_file}"]
    out1 = _run(base + [f"workers=127.0.0.1:{start_worker()}"])
    out4 = _run(base + [f"workers=127.0.0.1:{start_worker()}", "kstep=4"])
    assert out1.returncode == 0, out1.stderr[-2000:]
    assert out4.returncode == 0, out4.stderr[-2000:]
    loss1 = float(out1.stdout.split("final loss")[1].split()[0])
    loss4 = float(out4.stdout.split("final loss")[1].split()[0])
    assert abs(loss1 - loss4) < 1e-4, (loss1, loss4)
    steps1 = out1.stdout.split("trained fm:")[1].split()[0]
    steps4 = out4.stdout.split("trained fm:")[1].split()[0]
    assert steps1 == steps4 == "7", (steps1, steps4)
