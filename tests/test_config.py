"""Config parser tests (reference: ``test/unittest/unittest_config.cc``)."""

import pytest

from dmlc_core_tpu.utils import Config, DMLCError


def test_basic_parse():
    cfg = Config("lr = 0.1\nbatch=32  # trailing comment\n# full comment\nname = net1\n")
    assert cfg["lr"] == "0.1"
    assert cfg["batch"] == "32"
    assert cfg["name"] == "net1"
    assert "missing" not in cfg
    with pytest.raises(KeyError):
        cfg.get_param("missing")


def test_quoted_strings_and_escapes():
    cfg = Config('msg = "hello world"\npath = "a\\tb\\nc"\nq = "say \\"hi\\""\n')
    assert cfg["msg"] == "hello world"
    assert cfg["path"] == "a\tb\nc"
    assert cfg["q"] == 'say "hi"'


def test_multi_value_mode():
    text = "eval = a\neval = b\n"
    single = Config(text)
    assert single.get_all("eval") == ["b"]  # overwrite
    multi = Config(text, multi_value=True)
    assert multi.get_all("eval") == ["a", "b"]
    assert multi["eval"] == "b"  # latest


def test_order_preserved_and_proto_string():
    cfg = Config(multi_value=True)
    cfg.set_param("b", 2)
    cfg.set_param("a", "x y")
    cfg.set_param("flag", True)
    proto = cfg.to_proto_string()
    assert proto.splitlines() == ['b = 2', 'a = "x y"', 'flag = true']
    # round trip
    cfg2 = Config(proto, multi_value=True)
    assert cfg2.items() == cfg.items()


def test_errors():
    with pytest.raises(DMLCError):
        Config("key value\n")  # missing '='
    with pytest.raises(DMLCError):
        Config('a = "unterminated\n')
    with pytest.raises(DMLCError):
        Config("a =\n")  # missing value
