"""Production tracing at scale (ISSUE 18): tail-based sampling units
(hash floor, debug bit, token bucket, trace buffer), metric exemplars
through the OpenMetrics exposition, wide-event audit ring + /events,
the span-ring eviction counter, router hedge/failover span events, and
the cross-tier chaos drills (serving router→replica and data-service
consumer→worker→dispatcher) — all on CPU."""

import json
import re
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from dmlc_core_tpu.telemetry import exposition
from dmlc_core_tpu.telemetry import sampling as telsampling
from dmlc_core_tpu.telemetry import trace as teltrace
from dmlc_core_tpu.telemetry import wide_events
from dmlc_core_tpu.telemetry.sampling import (
    DEBUG_BIT, TailSampler, TraceBuffer, _TokenBucket, debug_trace_id,
    hash_keep, is_debug, mark_debug, _mix)
from dmlc_core_tpu.telemetry.wide_events import FIELDS, wide_event, wide_log
from dmlc_core_tpu.utils import clear_faults, inject_faults
from dmlc_core_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telsampling.uninstall()
    teltrace.recorder.clear()
    clear_faults()
    yield
    telsampling.uninstall()
    teltrace.recorder.clear()
    clear_faults()


def _c(name):
    return metrics.counter(name).value


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def _wait_for(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _rec(name, tid, *, span_id="s1", parent_id=None, dur_us=1000,
         status="OK", error=None, kind="span"):
    attrs = {"status": status}
    if error is not None:
        attrs["error"] = error
    return {"kind": kind, "name": name,
            "trace_id": teltrace.format_id(tid), "span_id": span_id,
            "parent_id": parent_id, "dur_us": dur_us, "attrs": attrs,
            "events": []}


def _feed(sampler, tid, **kw):
    """One single-span trace through the sampler's hook surface."""
    sampler.on_start(tid)
    sampler.on_end(tid, _rec(kw.pop("name", "op"), tid, **kw))


def _sampler(**kw):
    """A NOT-installed sampler over a private recorder (unit tests)."""
    kw.setdefault("floor", 0.0)
    kw.setdefault("keep_per_s", 0.0)
    kw.setdefault("keep_slow_ms", 1e9)
    kw.setdefault("decide_timeout_s", 60.0)
    kw.setdefault("recorder", teltrace.SpanRecorder(capacity=4096))
    return TailSampler(**kw)


# ---------------------------------------------------------------------------
# hash floor + debug bit + token bucket
# ---------------------------------------------------------------------------

def test_mix_and_hash_keep_deterministic():
    ids = [teltrace.new_trace_id() for _ in range(4000)]
    assert all(_mix(i) == _mix(i) for i in ids[:32])
    # shortcuts: 1.0 keeps everything, 0.0 keeps nothing
    assert all(hash_keep(i, 1.0) for i in ids[:32])
    assert not any(hash_keep(i, 0.0) for i in ids[:32])
    # the floor is a rate: ~25% of random ids land under 0.25
    frac = sum(hash_keep(i, 0.25) for i in ids) / len(ids)
    assert 0.20 < frac < 0.30
    # the debug bit is masked out of the hash, so a debug-marked id
    # lands on the same side of the floor on every tier
    for i in ids[:64]:
        assert hash_keep(i, 0.25) == hash_keep(i | DEBUG_BIT, 0.25)


def test_debug_bit_marking():
    # new_trace_id mints 63-bit ids — bit 63 is never set by accident
    assert all(teltrace.new_trace_id() < DEBUG_BIT for _ in range(256))
    ctx = teltrace.TraceContext(teltrace.new_trace_id(),
                                teltrace.new_trace_id())
    marked = mark_debug(ctx)
    assert is_debug(marked.trace_id) and not is_debug(ctx.trace_id)
    assert marked.span_id == ctx.span_id
    assert (marked.trace_id & ~DEBUG_BIT) == ctx.trace_id
    assert is_debug(debug_trace_id())


def test_token_bucket_force_and_debt():
    b = _TokenBucket(2.0)               # burst = max(1, rate) = 2
    t = b._t                            # anchor the injected clock
    assert b.take(now=t) and b.take(now=t)
    assert not b.take(now=t)            # budget spent
    assert b.take(force=True, now=t)    # forced keep always passes...
    # ...but debits into debt: one second refills 2 tokens, only one of
    # which is spendable (the other paid the debt back)
    assert b.take(now=t + 1.0)
    assert not b.take(now=t + 1.0)
    assert _TokenBucket(0.0).take()     # rate <= 0 = unlimited


# ---------------------------------------------------------------------------
# TraceBuffer
# ---------------------------------------------------------------------------

def test_buffer_decides_when_local_refcount_hits_zero():
    done = []
    b = TraceBuffer(lambda g, timed_out: done.append((g, timed_out)),
                    max_spans=64, decide_timeout_s=60.0)
    tid = teltrace.new_trace_id()
    b.on_start(tid)
    b.on_start(tid)                     # nested child
    b.on_end(tid, _rec("child", tid, span_id="c"))
    assert not done                     # root still open
    assert b.attach(tid, _rec("ev", tid, kind="event"))
    b.on_end(tid, _rec("root", tid, span_id="r"))
    assert len(done) == 1
    g, timed_out = done[0]
    assert not timed_out
    assert [r["name"] for r in g.records] == ["child", "ev", "root"]
    assert len(b) == 0
    # no group open → attach refuses, caller falls back to the verdict
    assert not b.attach(tid, _rec("late", tid, kind="event"))


def test_buffer_unknown_span_is_its_own_group():
    """A span whose start predates the sampler decides immediately as a
    single-record group (sampler installed mid-span)."""
    done = []
    b = TraceBuffer(lambda g, timed_out: done.append(g))
    tid = teltrace.new_trace_id()
    b.on_end(tid, _rec("orphan", tid))
    assert len(done) == 1 and len(done[0].records) == 1


def test_buffer_timeout_flush_counts():
    done = []
    b = TraceBuffer(lambda g, timed_out: done.append((g, timed_out)),
                    decide_timeout_s=0.05)
    tid = teltrace.new_trace_id()
    b.on_start(tid)
    b.on_start(tid)
    b.on_end(tid, _rec("child", tid))   # root never ends locally
    t0 = _c("telemetry.sampling.timeouts")
    assert b.flush_expired(now=time.monotonic() + 10.0) == 1
    assert done and done[0][1] is True
    assert _c("telemetry.sampling.timeouts") - t0 == 1


def test_buffer_overflow_evicts_oldest_whole_trace():
    done = []
    b = TraceBuffer(lambda g, timed_out: done.append(g),
                    max_spans=4, decide_timeout_s=60.0)
    t1, t2 = teltrace.new_trace_id(), teltrace.new_trace_id()
    o0 = _c("telemetry.sampling.overflow")
    for _ in range(4):
        b.on_start(t1)
    for i in range(3):
        b.on_end(t1, _rec(f"a{i}", t1, span_id=f"a{i}"))
    for _ in range(3):
        b.on_start(t2)
    b.on_end(t2, _rec("b0", t2, span_id="b0"))
    assert not done                     # 4 buffered spans: at capacity
    b.on_end(t2, _rec("b1", t2, span_id="b1"))   # 5th: evict oldest
    assert _c("telemetry.sampling.overflow") - o0 == 1
    assert len(done) == 1 and done[0].trace_id == t1
    assert len(done[0].records) == 3    # the whole trace, not one span


# ---------------------------------------------------------------------------
# TailSampler verdicts
# ---------------------------------------------------------------------------

def test_error_trace_kept_despite_zero_floor():
    s = _sampler()
    k0, e0 = _c("telemetry.sampling.kept"), _c("telemetry.sampling.keep_error")
    tid = teltrace.new_trace_id()
    _feed(s, tid, status="OVERLOADED")
    assert s.verdict(tid) is True
    assert [r["trace_id"] for r in s.recorder.snapshot()] == \
        [teltrace.format_id(tid)]
    assert _c("telemetry.sampling.kept") - k0 == 1
    assert _c("telemetry.sampling.keep_error") - e0 == 1
    # an attrs["error"] marker is an error trace too
    tid2 = teltrace.new_trace_id()
    _feed(s, tid2, error="ValueError: boom")
    assert s.verdict(tid2) is True


def test_healthy_trace_dropped_and_counted():
    s = _sampler()
    d0 = _c("telemetry.sampling.dropped")
    ds0 = _c("telemetry.sampling.dropped_spans")
    tid = teltrace.new_trace_id()
    _feed(s, tid)
    assert s.verdict(tid) is False
    assert len(s.recorder) == 0
    assert _c("telemetry.sampling.dropped") - d0 == 1
    assert _c("telemetry.sampling.dropped_spans") - ds0 == 1


def test_slow_keep_explicit_threshold():
    s = _sampler(keep_slow_ms=50.0)
    sl0 = _c("telemetry.sampling.keep_slow")
    fast, slow = teltrace.new_trace_id(), teltrace.new_trace_id()
    _feed(s, fast, dur_us=10_000)       # 10ms < 50ms
    _feed(s, slow, dur_us=100_000)      # 100ms > 50ms
    assert s.verdict(fast) is False
    assert s.verdict(slow) is True
    assert _c("telemetry.sampling.keep_slow") - sl0 == 1


def test_adaptive_slow_threshold_from_live_p95():
    s = _sampler(keep_slow_ms=0.0)      # 0 = adaptive
    name = "adaptive.op.r18"
    for _ in range(60):                 # build the p95 (needs >= 50 obs)
        _feed(s, teltrace.new_trace_id(), dur_us=10_000, name=name)
    s._thr_cache.clear()                # drop the 1s TTL cache: the
    sl0 = _c("telemetry.sampling.keep_slow")   # 60 feeds ran within it
    outlier = teltrace.new_trace_id()
    _feed(s, outlier, dur_us=200_000, name=name)   # 200ms vs p95 ~10ms
    assert s.verdict(outlier) is True
    assert _c("telemetry.sampling.keep_slow") - sl0 == 1


def test_floor_keep_matches_hash_and_caches_verdict():
    s = _sampler(floor=0.5)
    f0 = _c("telemetry.sampling.keep_floor")
    ids = [teltrace.new_trace_id() for _ in range(64)]
    for tid in ids:
        _feed(s, tid)
    for tid in ids:
        assert s.verdict(tid) == hash_keep(tid, 0.5)
    kept = sum(1 for tid in ids if s.verdict(tid))
    assert _c("telemetry.sampling.keep_floor") - f0 == kept
    assert 0 < kept < len(ids)


def test_debug_bit_forces_keep():
    s = _sampler()
    db0 = _c("telemetry.sampling.keep_debug")
    tid = debug_trace_id()
    _feed(s, tid)
    assert s.verdict(tid) is True
    assert _c("telemetry.sampling.keep_debug") - db0 == 1


def test_slo_breach_keeps_trace():
    g = metrics.gauge("slo.active_breaches")
    g.set(1)
    try:
        s = _sampler()
        s0 = _c("telemetry.sampling.keep_slo")
        tid = teltrace.new_trace_id()
        _feed(s, tid)
        assert s.verdict(tid) is True
        assert _c("telemetry.sampling.keep_slo") - s0 == 1
    finally:
        g.set(0)


def test_token_bucket_caps_floor_keeps_not_error_keeps():
    s = _sampler(floor=1.0, keep_per_s=2.0)   # burst 2
    k0, t0 = _c("telemetry.sampling.kept"), _c("telemetry.sampling.throttled")
    for _ in range(30):
        _feed(s, teltrace.new_trace_id())
    kept = _c("telemetry.sampling.kept") - k0
    assert kept <= 4                    # burst + at most a refill tick
    assert _c("telemetry.sampling.throttled") - t0 >= 26
    # error keeps force through an empty bucket
    for _ in range(5):
        _feed(s, teltrace.new_trace_id(), status="FAILED")
    assert _c("telemetry.sampling.kept") - k0 == kept + 5


def test_sticky_verdicts_route_late_spans():
    s = _sampler()
    kept_tid, drop_tid = teltrace.new_trace_id(), teltrace.new_trace_id()
    _feed(s, kept_tid, status="FAILED")            # kept
    _feed(s, drop_tid)                             # dropped
    n = len(s.recorder)
    k0 = _c("telemetry.sampling.kept")
    ds0 = _c("telemetry.sampling.dropped_spans")
    # a late span of a kept trace records directly — no fresh decision
    s.on_start(kept_tid)
    s.on_end(kept_tid, _rec("late", kept_tid, span_id="late"))
    assert len(s.recorder) == n + 1
    assert _c("telemetry.sampling.kept") == k0
    # a late span of a dropped trace is dropped and counted
    s.on_start(drop_tid)
    s.on_end(drop_tid, _rec("late2", drop_tid, span_id="late2"))
    assert len(s.recorder) == n + 1
    assert _c("telemetry.sampling.dropped_spans") - ds0 == 1
    # standalone events follow the verdict; untraced events always land
    s.on_event(drop_tid, _rec("ev", drop_tid, kind="event"))
    assert len(s.recorder) == n + 1
    s.on_event(None, _rec("untraced", 1, kind="event"))
    assert len(s.recorder) == n + 2


def test_was_kept_lookup_and_module_level():
    assert telsampling.was_kept("deadbeefdeadbeef") is None  # no sampler
    s = telsampling.install(_sampler())
    try:
        tid = teltrace.new_trace_id()
        hexid = teltrace.format_id(tid)
        assert telsampling.was_kept(hexid) is None     # undecided
        _feed(s, tid, status="FAILED")
        assert telsampling.was_kept(hexid) is True
        assert s.was_kept("not-hex") is None
        assert s.was_kept(None) is None
    finally:
        telsampling.uninstall()


def test_flush_decides_pending_groups():
    s = _sampler()
    tid = teltrace.new_trace_id()
    s.on_start(tid)
    s.on_start(tid)
    s.on_end(tid, _rec("child", tid, status="FAILED"))
    assert s.verdict(tid) is None
    s.flush()
    assert s.verdict(tid) is True


def test_maybe_install_from_env_gates_on_knob(monkeypatch):
    monkeypatch.delenv("DMLC_TRACE_SAMPLE", raising=False)
    assert telsampling.maybe_install_from_env() is None
    assert telsampling.get_sampler() is None
    monkeypatch.setenv("DMLC_TRACE_SAMPLE", "0.25")
    try:
        s = telsampling.maybe_install_from_env()
        assert s is not None and s.floor == 0.25
        assert telsampling.get_sampler() is s
        # idempotent: a second tier's startup reuses the installed one
        assert telsampling.maybe_install_from_env() is s
    finally:
        telsampling.uninstall()


# ---------------------------------------------------------------------------
# exemplars + OpenMetrics exposition
# ---------------------------------------------------------------------------

def test_histogram_retains_exemplar_from_active_trace():
    h = metrics.histogram("test.exemplar.capture_ms")
    with teltrace.span("exemplar-op") as s:
        h.observe(42.0)
    snap = h.snapshot()
    (ex,) = snap["exemplars"]
    assert ex["value"] == 42.0
    assert ex["trace_id"] == teltrace.format_id(s.trace_id)
    assert ex["ts"] > 0
    # untraced observations never attach exemplars
    h2 = metrics.histogram("test.exemplar.untraced_ms")
    h2.observe(1.0)
    assert "exemplars" not in h2.snapshot()


def test_openmetrics_renders_only_kept_trace_exemplars():
    s = telsampling.install(_sampler())
    h = metrics.histogram("test.exemplar.filter_ms")
    with pytest.raises(ValueError):
        with teltrace.span("ex-err-op") as sp_err:
            h.observe(5.0)
            raise ValueError("boom")
    kept_hex = teltrace.format_id(sp_err.trace_id)
    with teltrace.span("ex-ok-op") as sp_ok:
        h.observe(500.0)
    dropped_hex = teltrace.format_id(sp_ok.trace_id)
    assert s.verdict(sp_err.trace_id) is True
    assert s.verdict(sp_ok.trace_id) is False
    text = exposition.render_openmetrics(metrics.snapshot())
    assert text.endswith("# EOF\n")
    assert "# TYPE dmlc_test_exemplar_filter_ms histogram" in text
    buckets = [ln for ln in text.splitlines()
               if ln.startswith("dmlc_test_exemplar_filter_ms_bucket")]
    assert any('le="+Inf"' in ln for ln in buckets)
    # exemplar syntax per OpenMetrics: `... # {trace_id="..."} value ts`
    ex_lines = [ln for ln in buckets if " # {" in ln]
    assert ex_lines
    for ln in ex_lines:
        assert re.search(r'# \{trace_id="[0-9a-f]{16}"\} \S+ \S+$', ln)
    assert kept_hex in text             # followable into /spans
    assert dropped_hex not in text      # dropped trace never referenced


def test_exporter_openmetrics_timeline_analyze_exemplars():
    h = metrics.histogram("test.exemplar.endpoint_ms")
    with teltrace.span("endpoint-op") as sp:
        h.observe(7.0)
    hexid = teltrace.format_id(sp.trace_id)
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, ctype, body = _get(base + "/metrics?format=openmetrics")
        assert code == 200 and "openmetrics-text" in ctype
        assert body.endswith("# EOF\n")
        assert f'# {{trace_id="{hexid}"}}' in body
        # /timeline and /analyze bridge aggregates to concrete traces
        code, _, body = _get(base + "/timeline")
        exs = json.loads(body)["exemplars"]
        assert any(e["trace_id"] == hexid
                   for e in exs["test.exemplar.endpoint_ms"])
        code, _, body = _get(base + "/analyze")
        exs = json.loads(body)["exemplars"]
        assert any(e["trace_id"] == hexid
                   for e in exs["test.exemplar.endpoint_ms"])
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# wide events
# ---------------------------------------------------------------------------

def test_wide_event_closed_vocabulary_and_seq():
    wide_log.reset(capacity=4)
    try:
        u0 = _c("telemetry.wide_events.unknown_fields")
        e0 = _c("telemetry.wide_events.emitted")
        ev = wide_event("serving.request", model="m", bogus=1, rows=4)
        assert "bogus" not in ev
        assert set(ev) <= FIELDS
        assert _c("telemetry.wide_events.unknown_fields") - u0 == 1
        ev2 = wide_event("serving.request", model="m")
        assert ev2["seq"] == ev["seq"] + 1
        assert _c("telemetry.wide_events.emitted") - e0 == 2
        assert wide_log.snapshot(since=ev["seq"]) == [ev2]
        # ring overflow is counted, never silent
        for _ in range(6):
            wide_event("serving.request", model="m")
        doc = wide_log.doc()
        assert len(doc["events"]) == 4
        assert doc["dropped"] >= 2
        assert doc["schema"] == "dmlc.telemetry.wide_events/1"
    finally:
        wide_log.reset()


def test_wide_event_stamps_trace_identity_and_verdict():
    wide_log.reset()
    s = telsampling.install(_sampler())
    try:
        with teltrace.span("we-op") as sp:
            ev = wide_event("serving.request", model="m")
        assert ev["trace_id"] == teltrace.format_id(sp.trace_id)
        assert ev.get("debug") is False
        ctx = mark_debug(teltrace.TraceContext(teltrace.new_trace_id(),
                                               teltrace.new_trace_id()))
        with teltrace.activate(ctx):
            ev = wide_event("serving.request", model="m")
        assert ev["debug"] is True
        # a decided trace's verdict rides along as `sampled`
        tid = teltrace.new_trace_id()
        _feed(s, tid, status="FAILED")
        ev = wide_event("serving.request", model="m",
                        trace_id=teltrace.format_id(tid))
        assert ev["sampled"] is True
    finally:
        telsampling.uninstall()
        wide_log.reset()


def test_wide_event_file_mirror(tmp_path):
    path = tmp_path / "audit.jsonl"
    wide_log.reset(capacity=64, path=str(path))
    try:
        wide_event("serving.request", model="m", rows=4, outcome="OK")
        wide_event("data_service.lease", worker="w0", part=1,
                   outcome="OK")
        lines = path.read_text().splitlines()
        assert len(lines) == 2
        docs = [json.loads(ln) for ln in lines]
        assert [d["kind"] for d in docs] == ["serving.request",
                                             "data_service.lease"]
        for d in docs:
            assert set(d) <= FIELDS
    finally:
        wide_log.reset()


def test_events_endpoint_serves_since_cursor():
    wide_log.reset()
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        evs = [wide_event("serving.request", model="m", req_id=i)
               for i in range(3)]
        base = f"http://127.0.0.1:{srv.port}"
        code, _, body = _get(base + "/events")
        doc = json.loads(body)
        assert code == 200
        assert [e["req_id"] for e in doc["events"]] == [0, 1, 2]
        assert doc["last_seq"] == evs[-1]["seq"]
        code, _, body = _get(base + f"/events?since={evs[1]['seq']}")
        doc = json.loads(body)
        assert [e["req_id"] for e in doc["events"]] == [2]
    finally:
        srv.stop()
        wide_log.reset()


# ---------------------------------------------------------------------------
# satellite: span-ring eviction is visible
# ---------------------------------------------------------------------------

def test_recorder_eviction_bumps_drop_counter():
    d0 = _c("telemetry.spans_dropped")
    r = teltrace.SpanRecorder(capacity=2)
    for i in range(5):
        r.record({"name": str(i)})
    assert r.dropped == 3
    assert _c("telemetry.spans_dropped") - d0 == 3
    r.clear()
    assert r.dropped == 0


def test_spans_endpoint_stamps_dropped_count():
    with teltrace.span("spans-dropped-probe"):
        pass
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        code, _, body = _get(f"http://127.0.0.1:{srv.port}/spans")
        doc = json.loads(body)
        assert code == 200
        assert doc["dropped"] == teltrace.recorder.dropped
        assert isinstance(doc["dropped"], int)
        assert any(s["name"] == "spans-dropped-probe"
                   for s in doc["spans"])
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# satellite: router hedge/failover span events
# ---------------------------------------------------------------------------

def test_router_failover_events_reparent_under_request_span(monkeypatch):
    pytest.importorskip("jax")
    from dmlc_core_tpu.serving import ServingRouter
    from dmlc_core_tpu.serving.fleet import router as router_mod

    monkeypatch.setenv("DMLC_ROUTER_RETRIES", "4")
    r = ServingRouter(replicas=[("127.0.0.1", 1), ("127.0.0.1", 2)])
    try:
        a = r._replicas["127.0.0.1:1"]
        b = r._replicas["127.0.0.1:2"]
        span = teltrace.start_span("serving.router.request", req_id=1)
        pend = router_mod._Pending(
            1, SimpleNamespace(model_id="default"), 1, span.trace_id,
            span.context.span_id, 4, 16, b"", span)
        dispatched = []
        monkeypatch.setattr(r, "_pick", lambda model, tried: b)
        monkeypatch.setattr(
            r, "_dispatch", lambda p, rep: dispatched.append(p) or True)
        # a status-triggered resubmit is a hedge; conn loss a failover
        assert r._try_failover(pend, a, reason="OVERLOADED")
        assert r._try_failover(pend, a, reason="conn_lost",
                               already_released=True)
        assert pend.hedges == 1 and pend.failovers == 1
        assert [e["name"] for e in span.events] == ["hedge", "failover"]
        for e in span.events:
            assert e["attrs"]["frm"] == "127.0.0.1:1"
            assert e["attrs"]["to"] == "127.0.0.1:2"
        # the replacement attempt reuses the original pend and its span:
        # every attempt re-parents under the one router request span
        assert all(p is pend and p.span is span for p in dispatched)
        span.end(status="OK")
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# serving fleet harness (drill + end-to-end hedge)
# ---------------------------------------------------------------------------

F = 5000


def _fleet_stack(n, monkeypatch):
    jnp = pytest.importorskip("jax").numpy
    from dmlc_core_tpu.models import SparseLogReg
    from dmlc_core_tpu.serving import (BucketLadder, InferenceEngine,
                                       PredictClient, PredictionServer,
                                       ReplicaAgent, ReplicaRegistry,
                                       ServingRouter)

    def engine():
        model = SparseLogReg(num_features=F)
        params = {"w": jnp.full((F,), 1.0, jnp.float32),
                  "b": jnp.float32(0.0)}
        return InferenceEngine(model, params,
                               buckets=BucketLadder([(16, 512)]))

    monkeypatch.setenv("DMLC_ROUTER_RETRIES", "4")
    reg = ReplicaRegistry(heartbeat_timeout_s=2.0).start()
    pairs = []
    for _ in range(n):
        srv = PredictionServer(engine(), metrics_port=0).start()
        ag = ReplicaAgent(srv, reg.address, interval_s=0.1).start()
        pairs.append((srv, ag))
    router = ServingRouter(registry=reg.address, sync_s=0.1,
                           health_poll_s=0.1).start()
    cli = PredictClient(router.host, router.port, model_id="default")
    return reg, pairs, router, cli


def _fleet_teardown(reg, pairs, router, cli):
    cli.close()
    router.stop()
    for srv, ag in pairs:
        ag.stop()
        srv.stop()
    reg.stop()


def _predict_req(rng, cli, rows=4, nnz_per_row=16):
    counts = rng.integers(1, nnz_per_row + 1, size=rows)
    ids = rng.integers(0, F, size=int(counts.sum())).astype(np.int32)
    vals = rng.random(len(ids), dtype=np.float32)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    return cli.predict(ids, vals, row_ptr, timeout=15.0)


def test_router_hedge_keeps_both_attempts_in_one_trace(monkeypatch):
    """The injected shed and its hedged resubmit are ONE trace: both
    replica attempts parent under the single router request span, which
    carries the hedge event with endpoint labels."""
    reg, pairs, router, cli = _fleet_stack(2, monkeypatch)
    try:
        rng = np.random.default_rng(0)
        with inject_faults("serving.server.admit:error=1.0:times=1"):
            _predict_req(rng, cli)

        def hedged_router_span():
            return next(
                (r for r in teltrace.recorder.snapshot()
                 if r["name"] == "serving.router.request"
                 and any(e["name"] == "hedge" for e in r["events"])),
                None)

        assert _wait_for(lambda: hedged_router_span() is not None)
        rt = hedged_router_span()
        ev = next(e for e in rt["events"] if e["name"] == "hedge")
        assert ev["attrs"]["frm"] and ev["attrs"]["to"]
        assert ev["attrs"]["frm"] != ev["attrs"]["to"]
        assert ev["attrs"]["reason"] == "OVERLOADED"
        assert _wait_for(lambda: len(
            [r for r in teltrace.recorder.snapshot()
             if r["name"] == "serving.server.request"
             and r["trace_id"] == rt["trace_id"]]) == 2)
        servers = [r for r in teltrace.recorder.snapshot()
                   if r["name"] == "serving.server.request"
                   and r["trace_id"] == rt["trace_id"]]
        assert {s["attrs"]["status"] for s in servers} == \
            {"OVERLOADED", "OK"}
        assert all(s["parent_id"] == rt["span_id"] for s in servers)
    finally:
        _fleet_teardown(reg, pairs, router, cli)


# ---------------------------------------------------------------------------
# satellite: chaos drills
# ---------------------------------------------------------------------------

def test_drill_serving_error_trace_kept_complete_on_all_tiers(monkeypatch):
    """Router→replica drill: with a zero hash floor only the injected
    error trace survives, and it survives COMPLETE — client, router,
    both replica attempts, engine — while healthy traffic is dropped."""
    reg, pairs, router, cli = _fleet_stack(2, monkeypatch)
    sampler = telsampling.install(_sampler(recorder=teltrace.recorder))
    try:
        rng = np.random.default_rng(1)
        e0 = _c("telemetry.sampling.keep_error")
        d0 = _c("telemetry.sampling.dropped")
        with inject_faults("serving.server.admit:error=1.0:times=1"):
            _predict_req(rng, cli)
        for _ in range(11):
            _predict_req(rng, cli)
        assert _wait_for(
            lambda: _c("telemetry.sampling.keep_error") - e0 >= 1)
        assert _wait_for(
            lambda: _c("telemetry.sampling.dropped") - d0 >= 11)
        recs = teltrace.recorder.snapshot()
        err_tids = {r["trace_id"] for r in recs
                    if r["name"] == "serving.server.request"
                    and r["attrs"].get("status") == "OVERLOADED"}
        assert len(err_tids) == 1
        (etid,) = err_tids
        names = {r["name"] for r in recs if r["trace_id"] == etid}
        assert {"serving.client.predict", "serving.router.request",
                "serving.server.request",
                "serving.engine.forward"} <= names
        assert telsampling.was_kept(etid) is True
        # NOTHING from the 11 healthy traces leaked into the ring
        client_tids = {r["trace_id"] for r in recs
                       if r["name"] == "serving.client.predict"}
        assert client_tids == {etid}
    finally:
        telsampling.uninstall()
        _fleet_teardown(reg, pairs, router, cli)


def test_drill_hash_floor_verdicts_agree_across_tiers():
    """Three tiers (router / replica / worker), three INDEPENDENT
    samplers, zero coordination: identical keep sets at the hash floor,
    100% of error traces and 100% of slow traces kept on every tier."""
    tiers = ("router", "replica", "worker")

    def trio(**kw):
        return {t: _sampler(**dict(kw)) for t in tiers}

    # hash floor: same verdict everywhere, ~floor keep rate
    floored = trio(floor=0.25)
    ids = [teltrace.new_trace_id() for _ in range(600)]
    for tid in ids:
        for t in tiers:
            _feed(floored[t], tid, name=f"{t}.op", span_id=f"{t}-span")
    kept = {t: {tid for tid in ids if floored[t].verdict(tid)}
            for t in tiers}
    assert kept["router"] == kept["replica"] == kept["worker"]
    assert all(hash_keep(tid, 0.25) for tid in kept["router"])
    assert 0.15 < len(kept["router"]) / len(ids) < 0.35
    # kept traces are complete per tier; dropped ones leave nothing
    for t in tiers:
        ring = {int(r["trace_id"], 16)
                for r in floored[t].recorder.snapshot()}
        assert ring == kept[t]
    # error and slow traces: kept on every tier regardless of the floor
    drilled = trio(floor=0.0, keep_slow_ms=50.0)
    err_ids = [teltrace.new_trace_id() for _ in range(20)]
    slow_ids = [teltrace.new_trace_id() for _ in range(40)]
    fast_ids = [teltrace.new_trace_id() for _ in range(40)]
    for t in tiers:
        for tid in err_ids:
            _feed(drilled[t], tid, status="FAILED", name=f"{t}.op")
        for tid in slow_ids:
            _feed(drilled[t], tid, dur_us=200_000, name=f"{t}.op")
        for tid in fast_ids:
            _feed(drilled[t], tid, dur_us=1_000, name=f"{t}.op")
    for t in tiers:
        assert all(drilled[t].verdict(tid) for tid in err_ids)    # 100%
        n_slow = sum(1 for tid in slow_ids if drilled[t].verdict(tid))
        assert n_slow >= 0.95 * len(slow_ids)                     # >=95%
        assert not any(drilled[t].verdict(tid) for tid in fast_ids)


def test_drill_data_service_error_trace_kept_complete(tmp_path):
    """Consumer→worker→dispatcher drill: an errored consumer epoch is
    kept with all three tiers' spans in one trace (plus its lease wide
    event); a healthy epoch at a zero floor is dropped on all tiers."""
    pytest.importorskip("jax")
    from dmlc_core_tpu.pipeline.data_service import (
        DataServiceLoader, DataServiceWorker, Dispatcher)

    rng = np.random.default_rng(7)
    path = tmp_path / "drill.libsvm"
    with open(path, "w") as f:
        for i in range(120):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    spec = {"uri": str(path), "fmt": "libsvm", "num_parts": 2,
            "batch_rows": 32, "nnz_cap": 1024}

    def drain_epoch():
        ldr = DataServiceLoader(d.address, spec)
        try:
            for _kind, buf, _meta, _rows in ldr:
                ldr.recycle(buf)
        finally:
            ldr.close()

    wide_log.reset()
    sampler = telsampling.install(_sampler(recorder=teltrace.recorder))
    try:
        with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
            d.start()
            with DataServiceWorker(d.address) as w:
                w.start()
                e0 = _c("telemetry.sampling.keep_error")
                with pytest.raises(RuntimeError):
                    with teltrace.span("consumer.epoch") as root:
                        err_tid = root.trace_id
                        drain_epoch()
                        raise RuntimeError("injected: epoch audit")
                assert _wait_for(
                    lambda: sampler.verdict(err_tid) is not None)
                assert sampler.verdict(err_tid) is True
                assert _c("telemetry.sampling.keep_error") - e0 >= 1
                hexid = teltrace.format_id(err_tid)
                names = {r["name"]
                         for r in teltrace.recorder.snapshot()
                         if r["trace_id"] == hexid}
                # all three tiers present in the one kept trace
                assert {"consumer.epoch",                 # consumer
                        "data_service.client.stream",
                        "data_service.serve_shard",       # worker
                        "data_service.dispatcher.rpc",    # dispatcher
                        } <= names
                # the lease audit line references the same trace
                leases = [e for e in wide_log.snapshot()
                          if e["kind"] == "data_service.lease"
                          and e.get("trace_id") == hexid]
                assert leases
                assert all(e["outcome"] == "OK" for e in leases)
                # a healthy epoch at floor 0 drops on every tier
                with teltrace.span("consumer.epoch") as root2:
                    ok_tid = root2.trace_id
                    drain_epoch()
                assert _wait_for(
                    lambda: sampler.verdict(ok_tid) is not None)
                assert sampler.verdict(ok_tid) is False
                ok_hex = teltrace.format_id(ok_tid)
                assert not any(r["trace_id"] == ok_hex
                               for r in teltrace.recorder.snapshot())
    finally:
        telsampling.uninstall()
        wide_log.reset()
