"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over a 'pp'
mesh axis equals sequential stage composition — forward AND gradients —
and composes with a dp axis on a 2-D mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from dmlc_core_tpu.parallel.pipeline import (  # noqa: E402
    make_pipeline, split_microbatches, stack_stage_params, stage_sharding)


def _stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _sequential(stacked, xs):
    def apply_all(x):
        for s in range(stacked["w"].shape[0]):
            x = _stage({"w": stacked["w"][s], "b": stacked["b"][s]}, x)
        return x
    return jnp.stack([apply_all(xs[m]) for m in range(xs.shape[0])])


def _make_params(rng, S, F):
    per = [{"w": jnp.asarray(rng.standard_normal((F, F)) / np.sqrt(F),
                             jnp.float32),
            "b": jnp.asarray(rng.standard_normal(F) * 0.1, jnp.float32)}
           for _ in range(S)]
    return stack_stage_params(per)


@pytest.mark.parametrize("S,M", [(4, 6), (8, 8), (2, 1)])
def test_pipeline_matches_sequential(S, M):
    devices = jax.devices()
    if len(devices) < S:
        pytest.skip(f"needs {S} devices")
    mesh = Mesh(np.array(devices[:S]), ("pp",))
    rng = np.random.default_rng(0)
    F, mb = 16, 4
    stacked = _make_params(rng, S, F)
    stacked = jax.device_put(stacked, stage_sharding(mesh, "pp"))
    xs = jnp.asarray(rng.standard_normal((M, mb, F)), jnp.float32)

    run = make_pipeline(mesh, "pp", _stage)
    got = run(stacked, xs)
    np.testing.assert_allclose(got, _sequential(stacked, xs),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential():
    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devices[:4]), ("pp",))
    rng = np.random.default_rng(1)
    F, M, mb = 8, 5, 2
    stacked = _make_params(rng, 4, F)
    xs = jnp.asarray(rng.standard_normal((M, mb, F)), jnp.float32)
    run = make_pipeline(mesh, "pp", _stage)

    g_pipe = jax.grad(lambda p: jnp.sum(run(p, xs) ** 2))(
        jax.device_put(stacked, stage_sharding(mesh, "pp")))
    g_seq = jax.grad(lambda p: jnp.sum(_sequential(p, xs) ** 2))(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=2e-4, atol=2e-4)


def test_pipeline_composes_with_dp():
    """2-D mesh (dp=2, pp=4): batch sharded over dp, stages over pp."""
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "pp"))
    rng = np.random.default_rng(2)
    F, M, mb = 8, 4, 4
    stacked = _make_params(rng, 4, F)
    xs = jnp.asarray(rng.standard_normal((M, mb, F)), jnp.float32)

    run = make_pipeline(mesh, "pp", _stage)
    stacked_sh = jax.device_put(
        stacked, NamedSharding(mesh, P("pp")))
    xs_sh = jax.device_put(xs, NamedSharding(mesh, P(None, "dp")))
    got = run(stacked_sh, xs_sh)
    np.testing.assert_allclose(got, _sequential(stacked, xs),
                               rtol=2e-5, atol=2e-5)


def test_split_microbatches_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    xs = split_microbatches(x, 3)
    assert xs.shape == (3, 4, 2)
    np.testing.assert_array_equal(np.asarray(xs).reshape(12, 2),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        split_microbatches(x, 5)
