"""Data-plane flight deck: cross-tier trace propagation, the
lease-lifecycle ledger behind ``/leases``, the ``/fleet`` console,
incident profiling, and the client's resilience gauges.

The e2e trace tests run dispatcher, workers, and consumer in one process
(threads + real sockets), so the process-global span recorder sees all
three tiers — exactly the merged view a Perfetto export renders."""

import json
import os
import time
import urllib.error
import urllib.request
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.pipeline.data_service import (  # noqa: E402
    DataServiceLoader, DataServiceWorker, Dispatcher, dispatcher_rpc)
from dmlc_core_tpu.pipeline.device_loader import (  # noqa: E402
    _fused_words_meta, _put_fused_buf)
from dmlc_core_tpu.telemetry import flight  # noqa: E402
from dmlc_core_tpu.telemetry import profiling  # noqa: E402
from dmlc_core_tpu.telemetry import trace as teltrace  # noqa: E402
from dmlc_core_tpu.utils import clear_faults  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

from conftest import free_port  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


ROWS = 200
BATCH_ROWS = 32
NNZ_CAP = 1024


def _libsvm(tmp_path, rows=ROWS):
    rng = np.random.default_rng(11)
    path = tmp_path / "deck.libsvm"
    with open(path, "w") as f:
        for i in range(rows):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    return str(path)


def _spec(uri, num_parts):
    return {"uri": uri, "fmt": "libsvm", "num_parts": num_parts,
            "batch_rows": BATCH_ROWS, "nnz_cap": NNZ_CAP}


def _drain_labels(loader):
    labels = Counter()
    for kind, buf, meta, _rows in loader:
        assert kind == "fused"
        out = _put_fused_buf(
            np.asarray(buf)[: _fused_words_meta(BATCH_ROWS, int(meta))],
            BATCH_ROWS, int(meta))
        labels.update(int(x) for x in np.asarray(out["labels"])
                      if int(x) > 0)
        loader.recycle(buf)
    return labels


def _wait_for(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _spans_by_name(name):
    return [r for r in teltrace.recorder.snapshot()
            if r.get("kind") == "span" and r.get("name") == name]


# ---------------------------------------------------------------------------
# tentpole: one trace id across consumer → worker → dispatcher
# ---------------------------------------------------------------------------

def test_one_trace_spans_all_three_tiers(tmp_path):
    """A traced consumer epoch produces spans on every tier sharing ONE
    trace id: the client stream readers, the workers' serve/parse/pack
    spans, the dispatcher's RPC handling, and the lease-grant decision."""
    uri = _libsvm(tmp_path)
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        workers = [DataServiceWorker(d.address).start() for _ in range(2)]
        try:
            with teltrace.span("test.flight_deck.epoch") as root:
                root_tid = teltrace.format_id(root.trace_id)
                ldr = DataServiceLoader(d.address, _spec(uri, 3))
                labels = _drain_labels(ldr)
                ldr.close()
            assert set(labels) == set(range(1, ROWS + 1))
            # worker-side spans are recorded when the serving thread
            # unwinds — poll briefly instead of racing it
            cross_tier = ("data_service.client.stream",
                          "data_service.serve_stream",
                          "data_service.serve_shard",
                          "data_service.dispatcher.rpc",
                          "data_service.lease_grant")
            for name in cross_tier:
                assert _wait_for(
                    lambda n=name: any(s["trace_id"] == root_tid
                                       for s in _spans_by_name(n)),
                    timeout=5.0), \
                    f"no {name} span joined trace {root_tid}"
            # the dispatcher span is parented to the remote caller, not
            # floating: every one in this trace names a parent
            for s in _spans_by_name("data_service.dispatcher.rpc"):
                if s["trace_id"] == root_tid:
                    assert s["parent_id"] is not None
        finally:
            for w in workers:
                w.stop()


def test_untraced_rpc_stays_untraced():
    """A zero/absent trace id on the wire must NOT grow spans on the
    server: the dispatcher handles the command untraced."""
    assert teltrace.from_wire(0, 0) is None
    assert teltrace.from_wire(None, None) is None
    assert teltrace.from_wire("junk", 3) is None
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        teltrace.recorder.clear()
        assert teltrace.current() is None       # this caller is untraced
        dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": "u1",
                                   "host": "127.0.0.1", "port": 1})
        dispatcher_rpc(d.address, {"cmd": "heartbeat", "jobid": "u1"})
        assert _spans_by_name("data_service.dispatcher.rpc") == []
        assert _spans_by_name("data_service.lease_grant") == []


# ---------------------------------------------------------------------------
# tentpole: lease-lifecycle ledger + /leases
# ---------------------------------------------------------------------------

def test_lease_ledger_records_lifecycle_and_serves_endpoint(tmp_path):
    uri = _libsvm(tmp_path)
    with Dispatcher(lease_ttl_s=0.3, heartbeat_timeout_s=60.0,
                    telemetry_port=0) as d:
        d.start()
        dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": "w1",
                                   "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 1)})["key"]
        dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                   "jobid": "w1"})
        # TTL lapses → expired + regranted land in the ledger
        assert _wait_for(lambda: any(
            e["event"] == "regranted"
            for e in d.ledger_snapshot()["events"]), timeout=5.0)
        lease = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                           "jobid": "w1"})["lease"]
        # the resurrected epoch-1 completion is ledgered as stale
        dispatcher_rpc(d.address, {"cmd": "complete_lease", "key": key,
                                   "part": 0, "lease_epoch": 1,
                                   "jobid": "w1"})
        dispatcher_rpc(d.address, {"cmd": "complete_lease", "key": key,
                                   "part": 0,
                                   "lease_epoch": lease["lease_epoch"],
                                   "jobid": "w1"})
        events = [e["event"] for e in d.ledger_snapshot()["events"]]
        for ev in ("granted", "expired", "regranted", "stale_completion",
                   "completed"):
            assert ev in events, (ev, events)
        # order: the first grant precedes its expiry precedes the regrant
        assert events.index("granted") < events.index("expired") \
            < events.index("regranted")
        # a fresh pass is one epoch_started marker
        dispatcher_rpc(d.address, {"cmd": "start_epoch", "key": key})
        assert any(e["event"] == "epoch_started" and e["epoch"] == 2
                   for e in d.ledger_snapshot()["events"])
        # the HTTP view serves the same schema
        code, body = _get(
            f"http://127.0.0.1:{d.telemetry.port}/leases")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == "dmlc.data_service.leases/1"
        assert doc["leases"][key][0]["state"] == "pending"   # re-armed
        assert len(doc["events"]) == len(events) + 1


def test_leases_endpoint_is_dispatcher_only():
    from dmlc_core_tpu.telemetry.exposition import TelemetryServer
    srv = TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        code, _ = _get(f"http://127.0.0.1:{srv.port}/leases")
        assert code == 404
        code, _ = _get(f"http://127.0.0.1:{srv.port}/fleet")
        assert code == 404
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# tentpole: /fleet console
# ---------------------------------------------------------------------------

def test_fleet_console_reflects_worker_death_and_rates(tmp_path):
    uri = _libsvm(tmp_path)
    with Dispatcher(lease_ttl_s=30.0, heartbeat_timeout_s=0.4,
                    telemetry_port=0) as d:
        d.start()
        for w in ("alive-1", "doomed-2"):
            dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": w,
                                       "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 2)})["key"]
        dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                   "jobid": "alive-1"})
        dispatcher_rpc(d.address, {"cmd": "consumer_stats", "key": key,
                                   "backlog": 3, "batches": 17})
        # beat only alive-1 (with a metric push) past the silent
        # worker's timeout; /fleet must flip doomed-2 within one window
        state = {"data_service.worker.bytes":
                 {"type": "throughput", "total": 5_000_000,
                  "rate": 2.5e6, "windowed_rate": 2.5e6},
                 "data_service.worker.shards":
                 {"type": "counter", "value": 4}}
        deadline = time.monotonic() + 1.2       # 3x the 0.4s timeout
        while time.monotonic() < deadline:
            dispatcher_rpc(d.address, {"cmd": "heartbeat",
                                       "jobid": "alive-1", "state": state})
            time.sleep(0.1)
        code, body = _get(f"http://127.0.0.1:{d.telemetry.port}/fleet")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == "dmlc.data_service.fleet/1"
        w1, w2 = doc["workers"]["alive-1"], doc["workers"]["doomed-2"]
        assert w1["alive"] is True and w2["alive"] is False
        assert w1["heartbeat_age_s"] < w2["heartbeat_age_s"]
        assert w1["mb_s"] == pytest.approx(2.5)
        assert w1["shards"] == 4
        assert w1["live_leases"] == 1
        assert doc["consumers"][key]["backlog"] == 3
        assert doc["consumers"][key]["batches"] == 17
        assert doc["datasets"][key]["granted"] == 1
        # the zero-dependency boards render the same facts
        code, text = _get(
            f"http://127.0.0.1:{d.telemetry.port}/fleet?format=text")
        assert code == 200
        assert "alive-1" in text and "DEAD" in text
        code, html = _get(
            f"http://127.0.0.1:{d.telemetry.port}/fleet?format=html")
        assert code == 200
        assert html.startswith("<!doctype html>") or "<pre>" in html


# ---------------------------------------------------------------------------
# tentpole: incident profiling
# ---------------------------------------------------------------------------

def test_sampling_profiler_collapsed_output():
    s0 = metrics.counter("profile.samples").value
    prof = profiling.SamplingProfiler(hz=200)
    prof.sample_once()                      # deterministic single sample
    out = prof.collapsed()
    assert out.strip()
    # collapsed-stack grammar: "frame;frame;... count" per line,
    # root-first labels of module:function form
    line = out.splitlines()[0]
    stack, count = line.rsplit(" ", 1)
    assert int(count) >= 1
    assert ";" in stack or ":" in stack
    # this very test function is on some sampled thread's stack
    assert "test_sampling_profiler_collapsed_output" in out
    assert metrics.counter("profile.samples").value > s0


def test_profile_for_window_and_endpoint():
    out = profiling.profile_for(0.15)
    assert out.strip(), "a window over a live interpreter has samples"
    from dmlc_core_tpu.telemetry.exposition import TelemetryServer
    srv = TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        code, body = _get(
            f"http://127.0.0.1:{srv.port}/profile?seconds=0.1")
        assert code == 200
        assert body.strip()
        # malformed query degrades to the default window, not a 500
        code, _ = _get(
            f"http://127.0.0.1:{srv.port}/profile?seconds=bogus&x=1")
        assert code == 200
    finally:
        srv.stop()


def test_incident_profile_env_gates(monkeypatch):
    monkeypatch.setenv("DMLC_FLIGHT_PROFILE_S", "0")
    assert profiling.incident_profile() == ""
    monkeypatch.setenv("DMLC_FLIGHT_PROFILE_S", "0.05")
    assert profiling.incident_profile().strip()


def test_flight_bundle_carries_ledger_and_profile(tmp_path):
    """An incident bundle dumped while a dispatcher lives in-process
    carries the lease ledger (contributor section) and a non-empty
    collapsed-stack profile."""
    uri = _libsvm(tmp_path)
    with Dispatcher(lease_ttl_s=30.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": "w1",
                                   "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 1)})["key"]
        dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                   "jobid": "w1"})
        rec = flight.FlightRecorder()
        rec._min_interval = 0.0
        path = rec.arm(str(tmp_path)).dump("deck_drill")
        assert path is not None
        doc = json.load(open(os.path.join(path, "incident.json")))
        assert doc["lease_ledger"]["schema"] == "dmlc.data_service.leases/1"
        assert any(e["event"] == "granted"
                   for e in doc["lease_ledger"]["events"])
        assert doc["files"]["profile"] == "profile.txt"
        prof = open(os.path.join(path, "profile.txt")).read()
        assert prof.strip()
    # after stop() the contributor is gone: bundles elsewhere never see
    # a dead dispatcher's ledger
    rec2 = flight.FlightRecorder()
    assert "lease_ledger" not in rec2.bundle("post_stop")


# ---------------------------------------------------------------------------
# satellite: client resilience gauges
# ---------------------------------------------------------------------------

def test_client_breaker_state_exposed_as_gauges(tmp_path, monkeypatch):
    """A ghost fleet member (registered, never serving) trips its
    per-worker breaker; the loader publishes that as gauges while the
    epoch completes off the living worker."""
    monkeypatch.setenv("DMLC_DATA_CLIENT_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("DMLC_DATA_CLIENT_RETRIES", "3")
    monkeypatch.setenv("DMLC_DATA_CLIENT_BACKOFF_BASE", "0.01")
    monkeypatch.setenv("DMLC_DATA_CLIENT_BACKOFF_MAX", "0.05")
    uri = _libsvm(tmp_path)
    r0 = metrics.counter("data_service.client.redials").value
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        dispatcher_rpc(d.address, {"cmd": "register_worker",
                                   "jobid": "ghost", "host": "127.0.0.1",
                                   "port": free_port()})   # nobody listens
        with DataServiceWorker(d.address) as w:
            w.start()
            ldr = DataServiceLoader(d.address, _spec(uri, 2))
            labels = _drain_labels(ldr)
            ldr.close()
    assert set(labels) == set(range(1, ROWS + 1))
    assert metrics.gauge(
        "data_service.client.breaker_open.ghost").value == 1.0
    assert metrics.gauge("data_service.client.breakers_open").value >= 1.0
    assert metrics.counter("data_service.client.redials").value > r0


# ---------------------------------------------------------------------------
# acceptance: chaos run — death mid-epoch, one merged trace, full bundle
# ---------------------------------------------------------------------------

def test_chaos_death_merged_trace_and_bundle(tmp_path, monkeypatch):
    """The ISSUE's acceptance drill: a worker is killed mid-epoch by
    DMLC_FAULT_SPEC; the (shared) trace shows the re-granted lease served
    under the same consumer trace id by a survivor, /fleet flips the dead
    worker, and the incident bundle carries ledger + profile."""
    uri = _libsvm(tmp_path)
    monkeypatch.setenv("DMLC_FAULT_SPEC",
                       "data_service.lease:error=1:times=1:after=1")
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=0.5,
                    telemetry_port=0) as d:
        d.start()
        workers = [DataServiceWorker(d.address,
                                     heartbeat_interval_s=0.1).start()
                   for _ in range(2)]
        try:
            with teltrace.span("test.chaos.epoch") as root:
                root_tid = teltrace.format_id(root.trace_id)
                ldr = DataServiceLoader(d.address, _spec(uri, 4))
                labels = _drain_labels(ldr)
                ldr.close()
            assert set(labels) == set(range(1, ROWS + 1))
            # the ledger shows the death → regrant → completion arc
            events = d.ledger_snapshot()["events"]
            kinds = [e["event"] for e in events]
            assert "worker_died" in kinds or "failed" in kinds, kinds
            assert "regranted" in kinds
            regrant = next(e for e in events if e["event"] == "regranted")
            done = [e for e in events if e["event"] == "completed"
                    and e["part"] == regrant["part"]
                    and e["lease_epoch"] > 1]
            assert done, "re-granted shard never completed by a survivor"
            # the re-granted lease's grant decision is in the SAME trace
            grants = [s for s in _spans_by_name("data_service.lease_grant")
                      if s["trace_id"] == root_tid
                      and s["attrs"].get("part") == regrant["part"]
                      and s["attrs"].get("lease_epoch") > 1]
            assert grants, "regrant not visible in the consumer's trace"
            # /fleet flips the killed worker within a heartbeat timeout
            def one_dead():
                doc = json.loads(_get(
                    f"http://127.0.0.1:{d.telemetry.port}/fleet")[1])
                return sum(0 if w["alive"] else 1
                           for w in doc["workers"].values()) >= 1
            assert _wait_for(one_dead, timeout=5.0)
            # incident bundle: ledger section + non-empty profile
            rec = flight.FlightRecorder()
            rec._min_interval = 0.0
            path = rec.arm(str(tmp_path)).dump("chaos_drill")
            doc = json.load(open(os.path.join(path, "incident.json")))
            assert any(e["event"] == "regranted"
                       for e in doc["lease_ledger"]["events"])
            assert open(os.path.join(path, "profile.txt")).read().strip()
        finally:
            for w in workers:
                w.kill()
