"""Data service v2: durable dispatcher (journal + SIGKILL failover),
multi-consumer shared epochs, snapshot jobs riding the lease machinery,
the fleet autoscaler policy, and heartbeat jitter.

The journal tests drive :func:`replay_state` as a pure function over
every record prefix (the property the write-ahead design promises); the
chaos drill runs the dispatcher as a *subprocess*, SIGKILLs it
mid-epoch with three workers and two consumers sharing one job, and
proves row + frame-sha1 parity against the single-host ground truth —
zero duplicate frames across the restart."""

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.parallel.tracker import jittered  # noqa: E402
from dmlc_core_tpu.pipeline.data_service import (  # noqa: E402
    DataServiceLoader, DataServiceWorker, Dispatcher, DispatchJournal,
    FleetAutoscaler, dispatcher_rpc, materialize_dataset, replay_state)
from dmlc_core_tpu.pipeline.data_service.snapshot import (  # noqa: E402
    cached_spec, snapshot_spec)
from dmlc_core_tpu.pipeline.device_loader import (  # noqa: E402
    DeviceLoader, _fused_words_meta, _put_fused_buf)
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

ROWS = 400
BATCH_ROWS = 32
NNZ_CAP = 1024
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return metrics.counter(name).value


def _libsvm(tmp_path, rows=ROWS):
    rng = np.random.default_rng(11)
    path = tmp_path / "ds2.libsvm"
    with open(path, "w") as f:
        for i in range(rows):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    return str(path)


def _spec(uri, num_parts):
    return {"uri": uri, "fmt": "libsvm", "num_parts": num_parts,
            "batch_rows": BATCH_ROWS, "nnz_cap": NNZ_CAP}


def _frame_digest(buf, meta):
    words = _fused_words_meta(BATCH_ROWS, int(meta))
    return hashlib.sha1(np.asarray(buf)[:words].tobytes()).hexdigest()


def _drain(loader, per_frame_sleep=0.0, on_frame=None):
    """(label multiset, frame-digest multiset) for one epoch."""
    labels, digests = Counter(), Counter()
    for kind, buf, meta, _rows in loader:
        assert kind == "fused"
        digests[_frame_digest(buf, meta)] += 1
        out = _put_fused_buf(
            np.asarray(buf)[: _fused_words_meta(BATCH_ROWS, int(meta))],
            BATCH_ROWS, int(meta))
        labels.update(int(x) for x in np.asarray(out["labels"])
                      if int(x) > 0)
        loader.recycle(buf)
        if on_frame is not None:
            on_frame()
        if per_frame_sleep:
            time.sleep(per_frame_sleep)
    return labels, digests


def _single_host_baseline(uri, num_parts):
    labels, digests = Counter(), Counter()
    for part in range(num_parts):
        loader = DeviceLoader(
            create_parser(uri, part, num_parts, "libsvm", nthreads=1,
                          threaded=False),
            batch_rows=BATCH_ROWS, nnz_cap=NNZ_CAP, emit="host")
        try:
            for kind, buf, meta, _rows in loader:
                digests[_frame_digest(buf, meta)] += 1
                out = _put_fused_buf(
                    np.asarray(buf)[: _fused_words_meta(BATCH_ROWS,
                                                        int(meta))],
                    BATCH_ROWS, int(meta))
                labels.update(int(x) for x in np.asarray(out["labels"])
                              if int(x) > 0)
        finally:
            loader.close()
    return labels, digests


# ---------------------------------------------------------------------------
# journal: prefix-replay property + in-process restart
# ---------------------------------------------------------------------------

def _assert_consistent(state):
    """The invariants every replayed prefix must satisfy: only legal
    lease states, a GRANTED lease always names a worker inside a live
    (>= 1) epoch, lease_epochs at least 1."""
    for key, ds in state["datasets"].items():
        assert int(ds["epoch"]) >= 1, (key, ds["epoch"])
        for ls in ds["leases"]:
            assert ls["state"] in ("pending", "granted", "completed")
            assert int(ls["lease_epoch"]) >= 1
            if ls["state"] == "granted":
                assert ls["worker"], (key, ls)


def test_any_journal_prefix_replays_consistent(tmp_path):
    """Write-ahead property: a crash can truncate the log after ANY
    record, so every prefix must replay to a consistent lease table with
    per-part monotone lease_epochs."""
    uri = _libsvm(tmp_path)
    prefix = str(tmp_path / "jr" / "dispatch")
    with Dispatcher(lease_ttl_s=0.3, heartbeat_timeout_s=60.0,
                    journal=prefix) as d:
        d.start()
        for w in ("w1", "w2"):
            dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": w,
                                       "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 3)})["key"]
        dispatcher_rpc(d.address, {"cmd": "start_epoch", "key": key})
        l0 = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                        "jobid": "w1"})["lease"]
        dispatcher_rpc(d.address, {"cmd": "complete_lease", "key": key,
                                   "part": l0["part"],
                                   "lease_epoch": l0["lease_epoch"],
                                   "jobid": "w1"})
        # a grant left to expire: the TTL sweep regrants (lease_epoch
        # bump) — the record mix now covers grant/complete/regrant
        dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                   "jobid": "w2"})
        deadline = time.monotonic() + 5.0
        while d.dataset_status(key)["regrants"] < 1:
            assert time.monotonic() < deadline, d.dataset_status(key)
            time.sleep(0.05)
        l2 = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                        "jobid": "w1"})["lease"]
        dispatcher_rpc(d.address, {"cmd": "complete_lease", "key": key,
                                   "part": l2["part"],
                                   "lease_epoch": l2["lease_epoch"],
                                   "jobid": "w1"})
        # journal BEFORE the clean stop compacts it away
        snap, records = DispatchJournal(prefix).load()
    assert len(records) >= 6                      # a real record mix
    last_epochs = {}
    for k in range(len(records) + 1):
        state = replay_state(snap, records[:k])
        _assert_consistent(state)
        for dkey, ds in state["datasets"].items():
            for ls in ds["leases"]:
                slot = (dkey, ls["part"])
                prev = last_epochs.get(slot, 1)
                assert int(ls["lease_epoch"]) >= prev, (slot, k)
                last_epochs[slot] = int(ls["lease_epoch"])
    # full replay matches what the dispatcher knew
    full = replay_state(snap, records)
    ds = full["datasets"][key]
    states = Counter(ls["state"] for ls in ds["leases"])
    assert states["completed"] == 2
    assert set(full["workers"]) == {"w1", "w2"}


def test_restart_resumes_mid_epoch_and_ledger_survives(tmp_path):
    """A restarted dispatcher picks the epoch up where the old one
    died: completed parts stay completed, the remaining part is granted
    under its journaled lease_epoch, stale completions stay rejected,
    and the /leases event ring carries pre-restart history."""
    uri = _libsvm(tmp_path)
    prefix = str(tmp_path / "jr2" / "dispatch")
    d = Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0,
                   journal=prefix)
    d.start()
    dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": "w1",
                               "host": "127.0.0.1", "port": 1})
    key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                     "spec": _spec(uri, 2)})["key"]
    dispatcher_rpc(d.address, {"cmd": "start_epoch", "key": key})
    l0 = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                    "jobid": "w1"})["lease"]
    dispatcher_rpc(d.address, {"cmd": "complete_lease", "key": key,
                               "part": l0["part"],
                               "lease_epoch": l0["lease_epoch"],
                               "jobid": "w1"})
    l1 = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                    "jobid": "w1"})["lease"]
    # crash: no stop(), no compaction — the log alone must carry it
    d._stop_ev.set()
    try:
        d._srv.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    d._srv.close()
    d._journal.close()

    d2 = Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0,
                    journal=prefix)
    d2.start()
    try:
        st = d2.dataset_status(key)
        assert st["epoch"] == 1 and st["completed"] == 1
        # the replayed grant kept its worker + lease_epoch: the old
        # completion lands, a stale one bounces
        stale = dispatcher_rpc(d2.address, {"cmd": "complete_lease",
                                            "key": key, "part": l1["part"],
                                            "lease_epoch":
                                                l1["lease_epoch"] - 1,
                                            "jobid": "w1"})
        assert stale == {"ok": False, "stale": True}
        ok = dispatcher_rpc(d2.address, {"cmd": "complete_lease",
                                         "key": key, "part": l1["part"],
                                         "lease_epoch": l1["lease_epoch"],
                                         "jobid": "w1"})
        assert ok["ok"] is True
        assert d2.dataset_status(key)["completed"] == 2
        # ledger continuity: events appended by the DEAD dispatcher are
        # visible through the restarted one's /leases body
        events = d2.ledger_snapshot()["events"]
        kinds = Counter(e.get("event") for e in events)
        assert kinds["granted"] >= 2 and kinds["completed"] >= 2
    finally:
        d2.stop()


# ---------------------------------------------------------------------------
# chaos drill: SIGKILL the dispatcher mid-epoch, 3 workers, 2 consumers
# ---------------------------------------------------------------------------

def _spawn_dispatcher(port, journal):
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "dmlc_core_tpu.pipeline.data_service.dispatcher",
         f"port={port}", f"journal={journal}"],
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    line = proc.stdout.readline()
    assert line, "dispatcher subprocess died before binding"
    return proc, int(json.loads(line)["port"])


def test_dispatcher_sigkilled_mid_epoch_epoch_completes_exactly_once(
        tmp_path, monkeypatch):
    """The acceptance drill: journaled dispatcher subprocess, three
    workers, two consumers sharing one job; SIGKILL the dispatcher after
    the consumers have frames in hand, restart it on the same port +
    journal, and the epoch completes with row and frame-sha1 parity
    against the single-host ground truth — zero duplicate frames."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 6)
    # out-retry the dead window: default policies give up in ~a second
    # and the breaker would blacklist innocent workers whose completions
    # bounce off a dead control plane
    monkeypatch.setenv("DMLC_DATA_CLIENT_RETRIES", "40")
    monkeypatch.setenv("DMLC_DATA_CLIENT_BREAKER_THRESHOLD", "1000")
    monkeypatch.setenv("DMLC_DS_CTRL_RETRIES", "40")
    journal = str(tmp_path / "chaos" / "dispatch")
    disp, port = _spawn_dispatcher(0, journal)
    addr = ("127.0.0.1", port)
    workers = [DataServiceWorker(addr, heartbeat_interval_s=0.2).start()
               for _ in range(3)]
    frames_seen = threading.Event()
    registered = {"c1": threading.Event(), "c2": threading.Event()}
    total = [0]

    def _on_frame():
        # the kill waits for BOTH consumers registered (a loader
        # constructed into the dead window would fail registration,
        # which is not this drill) plus frames actually in flight
        total[0] += 1
        if (total[0] >= 2 and registered["c1"].is_set()
                and registered["c2"].is_set()):
            frames_seen.set()

    results = {}

    def _consume(tag):
        ldr = DataServiceLoader(addr, _spec(uri, 6))
        registered[tag].set()
        try:
            results[tag] = _drain(ldr, per_frame_sleep=0.05,
                                  on_frame=_on_frame)
        finally:
            ldr.close()

    threads = [threading.Thread(target=_consume, args=(t,))
               for t in ("c1", "c2")]
    try:
        for t in threads:
            t.start()
        assert frames_seen.wait(timeout=60.0), "no frames before the kill"
        os.kill(disp.pid, signal.SIGKILL)   # mid-epoch: leases in flight
        disp.wait()
        disp, port2 = _spawn_dispatcher(port, journal)
        assert port2 == port
        for t in threads:
            t.join(timeout=180.0)
            assert not t.is_alive(), "consumer stuck after failover"
    finally:
        for w in workers:
            w.kill()
        disp.kill()
        disp.wait()
    assert set(results) == {"c1", "c2"}
    labels = results["c1"][0] + results["c2"][0]
    digests = results["c1"][1] + results["c2"][1]
    assert labels == base_labels          # every row exactly once
    assert digests == base_digests        # every frame exactly once
    assert max(digests.values()) == 1     # zero duplicate frames


# ---------------------------------------------------------------------------
# multi-consumer shared epochs
# ---------------------------------------------------------------------------

def test_two_consumers_share_one_job_union_covers_dataset_once(tmp_path):
    """Shared mode (the default): two loaders naming the same spec join
    one epoch and split its shards — the union covers the dataset
    exactly once, no frame delivered to both."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 4)
    with Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        assert d.sharing == "shared"
        workers = [DataServiceWorker(d.address,
                                     heartbeat_interval_s=0.2).start()
                   for _ in range(2)]
        results = {}

        def _consume(tag):
            ldr = DataServiceLoader(d.address, _spec(uri, 4))
            try:
                results[tag] = _drain(ldr, per_frame_sleep=0.02)
            finally:
                ldr.close()

        threads = [threading.Thread(target=_consume, args=(t,))
                   for t in ("c1", "c2")]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
                assert not t.is_alive()
        finally:
            for w in workers:
                w.kill()
    labels = results["c1"][0] + results["c2"][0]
    digests = results["c1"][1] + results["c2"][1]
    assert labels == base_labels
    assert digests == base_digests
    assert max(digests.values()) == 1


def test_isolated_sharing_escape_hatch(tmp_path, monkeypatch):
    """``DMLC_DS_SHARING=isolated`` restores the seed semantics: each
    start_epoch owns the whole dataset."""
    monkeypatch.setenv("DMLC_DS_SHARING", "isolated")
    uri = _libsvm(tmp_path)
    base_labels, _ = _single_host_baseline(uri, 2)
    with Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        assert d.sharing == "isolated"
        assert d.fleet_snapshot()["sharing"] == "isolated"
        with DataServiceWorker(d.address) as w:
            w.start()
            for _ in range(2):      # two full epochs, one consumer each
                ldr = DataServiceLoader(d.address, _spec(uri, 2))
                labels, _d = _drain(ldr)
                ldr.close()
                assert labels == base_labels


# ---------------------------------------------------------------------------
# snapshot jobs + shared packed-page cache
# ---------------------------------------------------------------------------

def test_snapshot_materializes_pages_and_cached_consumer_rides_them(
        tmp_path):
    """A ``snapshot`` job materializes every part to page files through
    the normal lease machinery; a consumer registering the cached spec
    is then served from the validated pages (parse-free) with full
    frame parity, and the registry advertises the build."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    out_dir = str(tmp_path / "pages")
    serves0 = _counter("data_service.worker.page_serves")
    with Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            produced = materialize_dataset(d.address, _spec(uri, 2),
                                           out_dir)
            assert sorted(produced) == [0, 1]
            for part, path in produced.items():
                assert os.path.exists(path), (part, path)
            # epoch 1 over the cached spec rides the materialized page
            # files (parse-free) and registers them under the consumer
            # key; epoch 2 is then served build-once/serve-many from the
            # registry
            for epoch in (1, 2):
                ldr = DataServiceLoader(d.address,
                                        cached_spec(_spec(uri, 2),
                                                    out_dir))
                labels, digests = _drain(ldr)
                ldr.close()
                assert labels == base_labels, epoch
                assert digests == base_digests, epoch
            assert _counter("data_service.worker.page_serves") > serves0
            assert d.fleet_snapshot()["pages"]     # registry non-empty


def test_snapshot_spec_is_its_own_registry_namespace(tmp_path):
    """The snapshot variant of a spec must not collide with the plain
    dataset's registry entry (first-registration-wins would otherwise
    hand plain consumers a frame-less job)."""
    uri = _libsvm(tmp_path)
    with Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        plain = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                           "spec": _spec(uri, 2)})["key"]
        snap = dispatcher_rpc(
            d.address,
            {"cmd": "register_dataset",
             "spec": snapshot_spec(_spec(uri, 2),
                                   str(tmp_path / "p"))})["key"]
        assert plain != snap


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

def test_autoscaler_decide_policy():
    decide = FleetAutoscaler.decide
    up = decide({"workers": 0, "pending": 0, "granted": 0, "backlog": 0},
                1, 4)
    assert up["action"] == "up" and "floor" in up["reason"]
    up = decide({"workers": 0, "pending": 3, "granted": 0, "backlog": 0},
                0, 4)
    assert up["action"] == "up" and "pending" in up["reason"]
    up = decide({"workers": 1, "pending": 0, "granted": 2, "backlog": 9,
                 "backlog_high": 8, "burn_mb_s": 12.5}, 0, 4)
    assert up["action"] == "up" and "12.5" in up["reason"]
    down = decide({"workers": 2, "pending": 0, "granted": 0, "backlog": 0,
                   "backlog_low": 1}, 0, 4)
    assert down["action"] == "down"
    # in-band: work outstanding, backlog tolerable → hold
    assert decide({"workers": 2, "pending": 1, "granted": 1, "backlog": 3,
                   "backlog_high": 8, "backlog_low": 1}, 0, 4) is None
    # at the ceiling: backlog pressure cannot scale past max
    assert decide({"workers": 4, "pending": 5, "granted": 0,
                   "backlog": 50, "backlog_high": 8}, 0, 4) is None


def test_autoscaler_step_spawns_drains_and_journals_scale_events(tmp_path):
    """One step under the floor spawns (via the injected effect), the
    action lands in the lease ledger and /fleet, and stop() drains every
    worker the scaler owns — and only those."""
    spawned, drained = [], []
    with Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        scaler = FleetAutoscaler(
            d, min_workers=1, max_workers=2, interval_s=60.0,
            cooldown_s=5.0,
            spawn_fn=lambda addr: spawned.append(addr) or f"h{len(spawned)}",
            drain_fn=drained.append)
        assert scaler.step(now=100.0) == "up"
        assert spawned == [d.address]
        assert scaler.step(now=101.0) is None       # cooldown holds
        fleet = d.fleet_snapshot()
        assert fleet["autoscale"]["owned"] == 1
        assert fleet["autoscale"]["last_action"] == "up"
        events = [e for e in d.ledger_snapshot()["events"]
                  if str(e.get("event", "")).startswith("scale_")]
        assert events and events[-1]["event"] == "scale_up"
        scaler.stop()
        assert drained == ["h1"]
    ups = _counter("data_service.autoscale.ups")
    assert ups >= 1


# ---------------------------------------------------------------------------
# heartbeat jitter
# ---------------------------------------------------------------------------

def test_heartbeat_jitter_spreads_within_bounds(monkeypatch):
    samples = [jittered(10.0) for _ in range(200)]
    assert all(8.0 <= s <= 12.0 for s in samples)
    assert len({round(s, 6) for s in samples}) > 10   # actually spread
    monkeypatch.setenv("DMLC_HEARTBEAT_JITTER", "0")
    assert jittered(10.0) == 10.0
    monkeypatch.setenv("DMLC_HEARTBEAT_JITTER", "5")  # capped at ±90%
    assert all(jittered(10.0) >= 1.0 - 1e-9 for _ in range(50))
