"""Ragged CSR path (ISSUE 6): ops equivalence vs the padded path,
nnz-budget packing that never truncates, the capacity-ladder serving
engine, and the best_fit golden sweep — all on the CPU/XLA fallback
(bit-identical by construction) plus interpret-mode Pallas (allclose)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.data.row_block import RowBlock  # noqa: E402
from dmlc_core_tpu.ops import csr, ragged_csr  # noqa: E402
from dmlc_core_tpu.pipeline import packing  # noqa: E402
from dmlc_core_tpu.pipeline.device_loader import DeviceLoader  # noqa: E402
from dmlc_core_tpu.serving.engine import (  # noqa: E402
    BucketLadder, InferenceEngine, RequestTooLarge)
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

F = 700          # feature space
D = 16           # embedding width


def _block(rng, rows, max_k, *, empty_every=0, giant=None):
    """Random CSR RowBlock; ``empty_every``: every Nth row empty;
    ``giant``: (row, count) forcing one huge row."""
    counts = rng.integers(1, max_k + 1, rows).astype(np.int64)
    if empty_every:
        counts[::empty_every] = 0
    if giant is not None:
        counts[giant[0]] = giant[1]
    nnz = int(counts.sum())
    return RowBlock(
        offsets=np.concatenate([[0], np.cumsum(counts)]).astype(np.uint64),
        indices=rng.integers(0, F, nnz).astype(np.uint64),
        values=rng.normal(size=nnz).astype(np.float32),
        labels=rng.integers(0, 2, rows).astype(np.float32))


def _poison_tails(batch):
    """Overwrite everything past nnz_used with hostile garbage — any
    consumer that reads past the prefix words will fail loudly."""
    k = int(batch["nnz_used"])
    batch = dict(batch)
    for key, bad in (("ids", 2**31 - 1), ("vals", np.nan),
                     ("segments", -1)):
        arr = batch[key].copy()
        arr[k:] = bad
        batch[key] = arr
    return batch


# ---------------------------------------------------------------------------
# satellite: ragged-vs-padded numerical equivalence sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fill_pct", [1, 10, 50, 100])
@pytest.mark.parametrize("shape", ["plain", "empty_rows", "giant_row"])
def test_equivalence_sweep(fill_pct, shape):
    """pack_flat + padded ops == pack_ragged + ragged ops, bit-identical
    on the XLA fallback, across fill levels 1%–100%, rows with zero
    values, and a single row holding (almost) the whole budget."""
    rng = np.random.default_rng(fill_pct * 7 + len(shape))
    rows, cap = 24, 512
    target = max(rows, cap * fill_pct // 100)
    max_k = max(1, target // rows)
    kw = {}
    if shape == "empty_rows":
        kw["empty_every"] = 3
    elif shape == "giant_row":
        # one row takes the whole budget minus one slot per other row
        max_k = 1
        kw["giant"] = (5, max(1, target - (rows - 1)))
    blk = _block(rng, rows, max_k, **kw)

    padded = packing.pack_flat(blk, rows, cap)
    rag = _poison_tails(packing.pack_ragged(blk, rows, cap))
    nnz_used = jnp.int32(int(rag["nnz_used"]))
    w = jnp.asarray(rng.normal(size=F).astype(np.float32))
    table = jnp.asarray(rng.normal(size=(F, D)).astype(np.float32))

    ref_mv = csr.csr_dense_matvec(
        jnp.asarray(padded["ids"]), jnp.asarray(padded["vals"]),
        jnp.asarray(padded["segments"]), w, rows)
    got_mv = ragged_csr.ragged_dense_matvec(
        jnp.asarray(rag["ids"]), jnp.asarray(rag["vals"]),
        jnp.asarray(rag["segments"]), nnz_used, w, rows)
    assert np.array_equal(np.asarray(got_mv), np.asarray(ref_mv))

    ref_es = csr.csr_embed_sum(
        jnp.asarray(padded["ids"]), jnp.asarray(padded["vals"]),
        jnp.asarray(padded["segments"]), table, rows)
    got_es = ragged_csr.ragged_embed_sum(
        jnp.asarray(rag["ids"]), jnp.asarray(rag["vals"]),
        jnp.asarray(rag["segments"]), nnz_used, table, rows,
        engine="xla")
    assert np.array_equal(np.asarray(got_es), np.asarray(ref_es))

    ref_fm = csr.fm_pairwise(
        jnp.asarray(padded["ids"]), jnp.asarray(padded["vals"]),
        jnp.asarray(padded["segments"]), table, rows)
    got_fm = ragged_csr.ragged_fm_pairwise(
        jnp.asarray(rag["ids"]), jnp.asarray(rag["vals"]),
        jnp.asarray(rag["segments"]), nnz_used, table, rows,
        engine="xla")
    assert np.array_equal(np.asarray(got_fm), np.asarray(ref_fm))


def test_ragged_segment_sum_tolerates_garbage_tails():
    rng = np.random.default_rng(0)
    cap, rows, used = 64, 5, 23
    data = rng.normal(size=(cap, 3)).astype(np.float32)
    segs = np.full(cap, -9, np.int32)        # hostile tail
    segs[:used] = rng.integers(0, rows, used)
    data[used:] = np.nan
    ref = np.zeros((rows, 3), np.float32)
    for i in range(used):
        ref[segs[i]] += data[i]
    got = ragged_csr.ragged_segment_sum(jnp.asarray(data),
                                        jnp.asarray(segs),
                                        jnp.int32(used), rows)
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5)


def test_pallas_interpret_matches_xla():
    """The predicated DMA-ring kernels (interpret mode off-TPU) agree
    with the masked XLA reference; allclose, not bit-identical — the
    kernel accumulates in gather order per chunk."""
    rng = np.random.default_rng(2)
    rows, cap, width = 6, 48, 128
    counts = rng.integers(0, 9, rows)
    nnz = int(counts.sum())
    ids = np.full(cap, 3, np.int32)
    vals = rng.normal(size=cap).astype(np.float32)
    segs = np.full(cap, 2, np.int32)
    ids[:nnz] = rng.integers(0, F, nnz)
    segs[:nnz] = np.repeat(np.arange(rows), counts)
    table = rng.normal(size=(F, width)).astype(np.float32)

    ref = ragged_csr._embed_sum_xla(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(segs),
        jnp.int32(nnz), jnp.asarray(table), rows)
    out = ragged_csr._gather_pallas(
        jnp.asarray(ids), jnp.asarray(segs), jnp.asarray(vals),
        jnp.int32(nnz), jnp.asarray(table), rows, fm=False,
        interpret=True)[:rows]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)

    ref_fm = ragged_csr._fm_pairwise_xla(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(segs),
        jnp.int32(nnz), jnp.asarray(table), rows)
    s1, s2 = ragged_csr._gather_pallas(
        jnp.asarray(ids), jnp.asarray(segs), jnp.asarray(vals),
        jnp.int32(nnz), jnp.asarray(table), rows, fm=True,
        interpret=True)
    got_fm = 0.5 * jnp.sum(s1[:rows] * s1[:rows] - s2[:rows], axis=-1)
    np.testing.assert_allclose(np.asarray(got_fm), np.asarray(ref_fm),
                               atol=1e-4)

    # zero fill: output must be exactly zero, no DMA ran
    out0 = ragged_csr._gather_pallas(
        jnp.asarray(ids), jnp.asarray(segs), jnp.asarray(vals),
        jnp.int32(0), jnp.asarray(table), rows, fm=False,
        interpret=True)[:rows]
    assert (np.asarray(out0) == 0).all()


def test_mask_batch_matches_padded_model_forward():
    """mask_batch turns a garbage-tailed ragged batch into the padded
    convention: a zoo model's forward is bit-identical on both."""
    from dmlc_core_tpu.models import SparseLogReg
    rng = np.random.default_rng(3)
    rows, cap = 16, 256
    blk = _block(rng, rows, 8)
    padded = packing.pack_flat(blk, rows, cap)
    rag = _poison_tails(packing.pack_ragged(blk, rows, cap))
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.arange(F, dtype=jnp.float32) / F,
              "b": jnp.float32(0.5)}
    ref = model.forward(params, {k: jnp.asarray(v)
                                 for k, v in padded.items()})
    masked = ragged_csr.mask_batch({k: jnp.asarray(v)
                                    for k, v in rag.items()})
    got = model.forward(params, masked)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# pack_ragged / ragged_slices: budget cuts, never truncate
# ---------------------------------------------------------------------------

def test_ragged_slices_cover_exactly_once_within_budget():
    rng = np.random.default_rng(4)
    blk = _block(rng, 100, 12, empty_every=7)
    rows = nnz = 0
    prev_end = 0
    for s in packing.ragged_slices(blk, batch_rows=16, nnz_cap=64):
        o = s.offsets.astype(np.int64)
        snnz = int(o[-1] - o[0])
        assert s.size <= 16 and snnz <= 64
        rows += s.size
        nnz += snnz
        prev_end += s.size
    assert rows == blk.size
    assert nnz == int(blk.offsets[-1] - blk.offsets[0])


def test_ragged_never_truncates_giant_row_raises():
    rng = np.random.default_rng(5)
    blk = _block(rng, 3, 4, giant=(1, 200))
    with pytest.raises(ValueError, match="never truncates"):
        list(packing.ragged_slices(blk, batch_rows=8, nnz_cap=64))
    with pytest.raises(ValueError, match="never truncates"):
        packing.pack_ragged(blk, 8, 64)


def test_pack_ragged_prefix_equals_pack_flat():
    rng = np.random.default_rng(6)
    blk = _block(rng, 10, 6)
    flat = packing.pack_flat(blk, 16, 128)
    rag = packing.pack_ragged(blk, 16, 128)
    k = int(rag["nnz_used"])
    assert int(rag["rows_used"]) == blk.size
    for key in ("ids", "vals", "segments"):
        assert np.array_equal(rag[key][:k], flat[key][:k])
    assert np.array_equal(rag["row_ptr"], flat["row_ptr"])
    assert np.array_equal(rag["labels"], flat["labels"])
    assert np.array_equal(rag["weights"], flat["weights"])


def test_pack_flat_truncation_is_surfaced():
    """Satellite: silent pack_flat truncation now bumps the
    pipeline.pack.* counters (and logs, rate-limited)."""
    rng = np.random.default_rng(7)
    blk = _block(rng, 20, 10)
    total = int(blk.offsets[-1])
    v0 = metrics.counter("pipeline.pack.truncated_values").value
    r0 = metrics.counter("pipeline.pack.truncated_rows").value
    stats = packing.PackStats()
    packing.pack_flat(blk, 20, total // 2, stats=stats)
    dv = metrics.counter("pipeline.pack.truncated_values").value - v0
    dr = metrics.counter("pipeline.pack.truncated_rows").value - r0
    assert dv == stats.truncated_values > 0
    assert dr == stats.truncated_rows > 0
    assert stats.padding_ratio > 0


def test_device_loader_ragged_end_to_end():
    """Ragged loader: every row exactly once, in order, within budget,
    prefix words on every batch, padding_ratio 1.0."""
    rng = np.random.default_rng(8)
    blocks = [_block(rng, 30, 9, empty_every=5) for _ in range(4)]

    class Src:
        def __iter__(self):
            return iter(blocks)

        def before_first(self):
            pass

    dl = DeviceLoader(Src(), batch_rows=16, nnz_cap=64, ragged=True)
    rows = nnz = 0
    labels = []
    for b in dl:
        ru, nu = int(b["rows_used"]), int(b["nnz_used"])
        assert ru <= 16 and nu <= 64
        assert b["ids"].shape == (64,) and b["labels"].shape == (16,)
        rows += ru
        nnz += nu
        labels.append(np.asarray(b["labels"])[:ru])
    dl.close()
    assert rows == sum(b.size for b in blocks)
    assert nnz == sum(int(b.offsets[-1]) for b in blocks)
    assert np.array_equal(np.concatenate(labels),
                          np.concatenate([b.labels for b in blocks]))
    assert dl.stats.padding_ratio == 1.0


def test_device_loader_ragged_fingerprint_field():
    """The pack-config fingerprint carries the ragged flag, so pages
    written by a padded loader can never serve a ragged one (PR-4 cache
    invalidation contract)."""
    rng = np.random.default_rng(9)

    class Src:
        def __iter__(self):
            return iter([_block(rng, 8, 4)])

        def before_first(self):
            pass

    dl = DeviceLoader(Src(), batch_rows=8, nnz_cap=64, ragged=True)
    try:
        import inspect

        from dmlc_core_tpu.pipeline import fingerprint as fp

        # the shared builder carries the flag...
        assert '"ragged"' in inspect.getsource(fp.pack_fingerprint)
        # ...and the loader threads its own setting into it
        src = inspect.getsource(type(dl)._cache_fingerprint)
        assert "ragged=self.ragged" in src
        assert dl.ragged is True
    finally:
        dl.close()
    with pytest.raises(Exception):
        DeviceLoader(Src(), batch_rows=8, nnz_cap=64, ragged=True,
                     layout="rowmajor")


# ---------------------------------------------------------------------------
# serving: best_fit golden sweep + ragged capacity engine
# ---------------------------------------------------------------------------

def test_best_fit_bisect_matches_linear_sweep():
    """Golden selection sweep (satellite): the bisect early-exit picks
    the same bucket as the full linear scan for every (rows, nnz)."""
    for lad in (BucketLadder.default(),
                BucketLadder.ragged_default(),
                BucketLadder([(8, 64), (8, 512), (32, 512),
                              (128, 4096), (7, 333)])):
        for rows in range(1, lad.max_rows + 2, 3):
            for nnz in range(1, lad.max_nnz + 2,
                             max(1, lad.max_nnz // 97)):
                ref = next((b for b in lad.buckets
                            if b.rows >= rows and b.nnz >= nnz), None)
                try:
                    got = lad.best_fit(rows, nnz)
                except RequestTooLarge:
                    got = None
                assert got == ref, (rows, nnz, got, ref)


def test_ragged_default_ladder_is_small():
    assert len(BucketLadder.ragged_default()) <= 3
    assert len(BucketLadder.ragged_default()) < len(BucketLadder.default())


def _fm_engines(ladder):
    from dmlc_core_tpu.models import FactorizationMachine
    model = FactorizationMachine(num_features=F, dim=8)
    params = model.init(jax.random.PRNGKey(0))
    pad = InferenceEngine(model, params, postprocess="sigmoid",
                          buckets=BucketLadder(list(ladder)))
    rag = InferenceEngine(model, params, postprocess="sigmoid",
                          ragged=True, buckets=BucketLadder(list(ladder)))
    return pad, rag


def test_ragged_engine_scores_bit_identical():
    pad, rag = _fm_engines([(8, 128), (32, 512)])
    rng = np.random.default_rng(10)
    for rows, k in [(1, 4), (8, 15), (30, 16), (32, 16), (3, 1)]:
        counts = rng.integers(1, k + 1, rows)
        ids = rng.integers(0, F, int(counts.sum())).astype(np.int32)
        vals = rng.random(len(ids), dtype=np.float32)
        rp = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        assert np.array_equal(pad.predict(ids, vals, rp),
                              rag.predict(ids, vals, rp))


def test_ragged_engine_compile_count_flat_under_mixed_traffic():
    """The no-retrace proof for the capacity ladder: warmup compiles
    every tier, then maximally mixed (rows, nnz) traffic adds ZERO
    compiles and no watchdog alert — one executable per capacity serves
    every fill level."""
    from dmlc_core_tpu.telemetry import xla_introspect
    _, rag = _fm_engines([(8, 128), (32, 512)])
    xla_introspect.watchdog.reset_alert()
    rag.warmup_all()
    assert rag.compile_count == len(rag.ladder) == 2
    rng = np.random.default_rng(11)
    for _ in range(40):
        rows = int(rng.integers(1, 33))
        counts = rng.integers(1, 17, rows)
        nnz = int(counts.sum())
        if nnz > 512:
            continue
        ids = rng.integers(0, F, nnz).astype(np.int32)
        vals = rng.random(nnz, dtype=np.float32)
        rp = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        rag.predict(ids, vals, rp)
    assert rag.compile_count == 2          # steady state: zero retraces
    assert not xla_introspect.watchdog.alerted


def test_ragged_engine_env_pin_roundtrip(monkeypatch):
    """DMLC_RAGGED_ENGINE pins the ops dispatch; bogus values raise."""
    monkeypatch.setenv("DMLC_RAGGED_ENGINE", "xla")
    out = ragged_csr.ragged_embed_sum(
        jnp.zeros(8, jnp.int32), jnp.ones(8, jnp.float32),
        jnp.zeros(8, jnp.int32), jnp.int32(4),
        jnp.ones((4, 8), jnp.float32), 2)
    assert out.shape == (2, 8)
    monkeypatch.setenv("DMLC_RAGGED_ENGINE", "bogus")
    with pytest.raises(ValueError, match="unknown ragged engine"):
        ragged_csr.ragged_embed_sum(
            jnp.zeros(8, jnp.int32), jnp.ones(8, jnp.float32),
            jnp.zeros(8, jnp.int32), jnp.int32(4),
            jnp.ones((4, 8), jnp.float32), 2)
