"""Remote filesystem tests against in-process fake servers — the moral
equivalent of the reference's S3 soak test (`test/README.md:1-30`) without
cloud credentials: exercises ranged reads, restart-on-seek, SigV4 signing,
multipart upload, ListObjectsV2, WebHDFS, and partition-correct InputSplit
over HTTP."""

import hashlib
import io
import json
import os
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_core_tpu.io import (
    URI,
    RangedReadStream,
    S3FileSystem,
    WebHDFSFileSystem,
    create_input_split,
    get_filesystem,
    open_seek_stream_for_read,
    open_stream,
    sign_v4,
)


# ---------------------------------------------------------------------------
# fake servers
# ---------------------------------------------------------------------------

class _RangeHTTPHandler(BaseHTTPRequestHandler):
    """Static file server with Range support; records request count."""
    files = {}        # path -> bytes
    requests = []

    def log_message(self, *a):
        pass

    def _body(self):
        data = self.files.get(self.path.split("?")[0])
        return data

    def do_HEAD(self):
        data = self._body()
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        type(self).requests.append((self.command, self.path,
                                    self.headers.get("Range")))
        data = self._body()
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo, hi = rng[6:].split("-")
            lo = int(lo)
            hi = min(int(hi), len(data) - 1) if hi else len(data) - 1
            if lo >= len(data):
                self.send_response(416)
                self.end_headers()
                return
            part = data[lo:hi + 1]
            self.send_response(206)
            self.send_header("Content-Range",
                             f"bytes {lo}-{hi}/{len(data)}")
            self.send_header("Content-Length", str(len(part)))
            self.end_headers()
            self.wfile.write(part)
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)


class _FakeS3Handler(BaseHTTPRequestHandler):
    """Minimal S3: GET/HEAD object (+Range), PUT object, multipart upload,
    ListObjectsV2. Verifies every request carries a SigV4 Authorization.

    Fault injection (VERDICT r4 #10): push op names onto ``fail_next``
    ("initiate" | "part" | "complete") and the NEXT matching request is
    severed after its body is read — the request reached the server, the
    response never arrives, exactly a connection dropped mid-upload."""
    objects = {}          # "bucket/key" -> bytes
    uploads = {}          # upload_id -> {part_no: bytes}
    auth_seen = []
    next_upload = [0]
    fail_next = []        # queue of ops to sever
    part_attempts = []    # part numbers as the server saw them, in order

    def log_message(self, *a):
        pass

    def _record_auth(self):
        type(self).auth_seen.append(self.headers.get("Authorization", ""))

    def _maybe_drop(self, op: str) -> bool:
        if type(self).fail_next and type(self).fail_next[0] == op:
            type(self).fail_next.pop(0)
            self.close_connection = True
            try:                      # sever with zero response bytes
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return True
        return False

    def _obj_key(self):
        return urllib.parse.unquote(self.path.split("?")[0].lstrip("/"))

    def _query(self):
        qs = urllib.parse.urlparse(self.path).query
        return dict(urllib.parse.parse_qsl(qs, keep_blank_values=True))

    def do_HEAD(self):
        self._record_auth()
        data = self.objects.get(self._obj_key())
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        self._record_auth()
        q = self._query()
        if q.get("list-type") == "2":
            bucket = self._obj_key().split("/")[0]
            prefix = q.get("prefix", "")
            delim = q.get("delimiter", "")
            keys, prefixes = [], set()
            for full, data in sorted(self.objects.items()):
                b, k = full.split("/", 1)
                if b != bucket or not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim)[0] + delim)
                else:
                    keys.append((k, len(data)))
            xml = ["<ListBucketResult>"]
            for k, sz in keys:
                xml.append(f"<Contents><Key>{k}</Key><Size>{sz}</Size></Contents>")
            for p in sorted(prefixes):
                xml.append(f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>")
            xml.append("</ListBucketResult>")
            body = "".join(xml).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = self.objects.get(self._obj_key())
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo, hi = rng[6:].split("-")
            lo, hi = int(lo), min(int(hi), len(data) - 1)
            part = data[lo:hi + 1]
            self.send_response(206)
            self.send_header("Content-Range", f"bytes {lo}-{hi}/{len(data)}")
            self.send_header("Content-Length", str(len(part)))
            self.end_headers()
            self.wfile.write(part)
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    def do_PUT(self):
        self._record_auth()
        q = self._query()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if "partNumber" in q:
            type(self).part_attempts.append(int(q["partNumber"]))
            if self._maybe_drop("part"):
                return                # body consumed, response severed
            up = self.uploads.setdefault(q["uploadId"], {})
            up[int(q["partNumber"])] = body
            etag = hashlib.md5(body).hexdigest()
            self.send_response(200)
            self.send_header("ETag", f'"{etag}"')
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.objects[self._obj_key()] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        self._record_auth()
        q = self._query()
        if "uploadId" in q:             # AbortMultipartUpload
            self.uploads.pop(q["uploadId"], None)
        else:
            self.objects.pop(self._obj_key(), None)
        self.send_response(204)         # S3 DeleteObject is idempotent
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_POST(self):
        self._record_auth()
        q = self._query()
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        if "uploads" in q:
            if self._maybe_drop("initiate"):
                return
            self.next_upload[0] += 1
            uid = f"upload-{self.next_upload[0]}"
            self.uploads[uid] = {}
            body = (f"<InitiateMultipartUploadResult><UploadId>{uid}"
                    f"</UploadId></InitiateMultipartUploadResult>").encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if "uploadId" in q:
            if self._maybe_drop("complete"):
                return
            parts = self.uploads.pop(q["uploadId"], {})
            data = b"".join(parts[i] for i in sorted(parts))
            self.objects[self._obj_key()] = data
            body = b"<CompleteMultipartUploadResult/>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_error(400)


class _FakeWebHDFSHandler(BaseHTTPRequestHandler):
    """Namenode that answers OPEN/CREATE/APPEND with a datanode Location
    JSON (the real two-step WebHDFS protocol); /data/ paths play the
    datanode role."""
    files = {}       # "/path" -> bytes
    data_requests = []  # (method, path) seen by the fake datanode
    namenode_queries = []  # (method, query dict) seen by the fake namenode

    def log_message(self, *a):
        pass

    def _port(self):
        return self.server.server_address[1]

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        if parsed.path.startswith("/data"):     # datanode read
            path = parsed.path[len("/data"):]
            data = self.files.get(path, b"")
            off = int(q.get("offset", 0))
            ln = int(q.get("length", len(data)))
            body = data[off:off + ln]
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        path = parsed.path[len("/webhdfs/v1"):]
        self.namenode_queries.append(("GET", q))
        op = q.get("op")
        if op == "GETFILESTATUS":
            if path not in self.files:
                self.send_error(404)
                return
            body = json.dumps({"FileStatus": {
                "length": len(self.files[path]), "type": "FILE"}}).encode()
        elif op == "LISTSTATUS":
            sts = [{"pathSuffix": p.rsplit("/", 1)[-1], "length": len(d),
                    "type": "FILE"}
                   for p, d in sorted(self.files.items())
                   if p.rsplit("/", 1)[0] == path.rstrip("/")]
            body = json.dumps({"FileStatuses": {"FileStatus": sts}}).encode()
        elif op == "OPEN":
            if path not in self.files:
                self.send_error(404)
                return
            # namenode: hand back the datanode URL, NOT the data
            loc = (f"http://127.0.0.1:{self._port()}/data{path}?"
                   f"offset={q.get('offset', 0)}&length={q.get('length', 0)}")
            body = json.dumps({"Location": loc}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        else:
            self.send_error(400)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        parsed = urllib.parse.urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if parsed.path.startswith("/data"):     # datanode write
            self.data_requests.append(("PUT", parsed.path[len("/data"):]))
            self.files[parsed.path[len("/data"):]] = body
            self.send_response(201)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        # namenode: RENAME is answered inline; CREATE points at the datanode
        path = parsed.path[len("/webhdfs/v1"):]
        q = dict(urllib.parse.parse_qsl(parsed.query))
        self.namenode_queries.append(("PUT", q))
        if q.get("op") == "RENAME":
            dest = q.get("destination", "")
            ok = path in self.files
            if ok:
                self.files[dest] = self.files.pop(path)
            resp = json.dumps({"boolean": ok}).encode()
            self.send_response(200 if ok else 404)
            self.send_header("Content-Length", str(len(resp)))
            self.end_headers()
            self.wfile.write(resp)
            return
        loc = f"http://127.0.0.1:{self._port()}/data{path}"
        resp = json.dumps({"Location": loc}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)

    def do_DELETE(self):
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path[len("/webhdfs/v1"):]
        existed = self.files.pop(path, None) is not None
        resp = json.dumps({"boolean": existed}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if parsed.path.startswith("/data"):     # datanode append
            path = parsed.path[len("/data"):]
            self.data_requests.append(("POST", path))
            self.files[path] = self.files.get(path, b"") + body
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        # namenode APPEND: point at the datanode
        path = parsed.path[len("/webhdfs/v1"):]
        loc = f"http://127.0.0.1:{self._port()}/data{path}"
        resp = json.dumps({"Location": loc}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)


@pytest.fixture
def http_server():
    _RangeHTTPHandler.files = {}
    _RangeHTTPHandler.requests = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _RangeHTTPHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv, _RangeHTTPHandler
    srv.shutdown()


@pytest.fixture
def s3_server(monkeypatch):
    _FakeS3Handler.objects = {}
    _FakeS3Handler.uploads = {}
    _FakeS3Handler.auth_seen = []
    _FakeS3Handler.fail_next = []
    _FakeS3Handler.part_attempts = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    monkeypatch.setenv("DMLC_S3_ENDPOINT",
                       f"http://127.0.0.1:{srv.server_address[1]}")
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDEXAMPLE")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secretsecret")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    yield srv, _FakeS3Handler
    srv.shutdown()


@pytest.fixture
def hdfs_server():
    _FakeWebHDFSHandler.files = {}
    _FakeWebHDFSHandler.data_requests = []
    _FakeWebHDFSHandler.namenode_queries = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeWebHDFSHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv, _FakeWebHDFSHandler
    srv.shutdown()


# ---------------------------------------------------------------------------
# RangedReadStream (CURLReadStreamBase semantics)
# ---------------------------------------------------------------------------

def test_ranged_stream_sequential_read(http_server):
    srv, h = http_server
    data = bytes(range(256)) * 100
    h.files["/blob"] = data
    s = RangedReadStream("http", f"127.0.0.1:{srv.server_address[1]}",
                         "/blob", buffer_size=1000)
    assert s.read(10) == data[:10]
    assert s.read() == data[10:]
    assert s.read(5) == b""


def test_ranged_stream_seek_tell_restart(http_server):
    srv, h = http_server
    data = os.urandom(50000)
    h.files["/blob"] = data
    s = RangedReadStream("http", f"127.0.0.1:{srv.server_address[1]}",
                         "/blob", buffer_size=4096)
    s.read(100)
    n_before = len(h.requests)
    # in-buffer seek: no new request
    s.seek(2000)
    assert s.read(96) == data[2000:2096]
    assert len(h.requests) == n_before
    # out-of-buffer seek: restart-on-seek issues a fresh ranged GET
    s.seek(40000)
    assert s.read(100) == data[40000:40100]
    assert len(h.requests) == n_before + 1
    assert s.tell() == 40100
    # SEEK_END
    s.seek(-10, os.SEEK_END)
    assert s.read() == data[-10:]


def test_ranged_stream_via_open_stream(http_server):
    srv, h = http_server
    h.files["/f.txt"] = b"hello remote world"
    url = f"http://127.0.0.1:{srv.server_address[1]}/f.txt"
    with open_seek_stream_for_read(url) as s:
        assert s.read() == b"hello remote world"
    info = get_filesystem(URI(url)).get_path_info(URI(url))
    assert info.size == 18


def test_http_404(http_server):
    srv, h = http_server
    from dmlc_core_tpu.utils import DMLCError
    url = f"http://127.0.0.1:{srv.server_address[1]}/nope"
    with pytest.raises(DMLCError):
        open_seek_stream_for_read(url).read()


def test_input_split_partition_union_over_http(http_server):
    """Partition correctness over a remote stream: union of all parts ==
    whole file (the reference's split_repeat_read_test over HTTP)."""
    srv, h = http_server
    lines = [f"{i} {i%7+1}:0.5".encode() for i in range(500)]
    h.files["/data.libsvm"] = b"\n".join(lines) + b"\n"
    url = f"http://127.0.0.1:{srv.server_address[1]}/data.libsvm"
    got = []
    nsplit = 4
    for k in range(nsplit):
        sp = create_input_split(url, k, nsplit, "text", threaded=False)
        while True:
            rec = sp.next_record()
            if rec is None:
                break
            got.append(bytes(rec))
        sp.close()
    assert sorted(got) == sorted(lines)


# ---------------------------------------------------------------------------
# SigV4
# ---------------------------------------------------------------------------

def test_sign_v4_official_test_vector():
    """AWS sigv4 test-suite vector ``get-vanilla-query-order-key-case``."""
    import datetime
    now = datetime.datetime(2015, 8, 30, 12, 36, 0,
                            tzinfo=datetime.timezone.utc)
    headers = sign_v4(
        "GET", "example.amazonaws.com", "/",
        {"Param2": "value2", "Param1": "value1"}, {},
        hashlib.sha256(b"").hexdigest(),
        "us-east-1", "service", "AKIDEXAMPLE",
        "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY", now=now,
        include_content_sha256=False)
    assert headers["Authorization"].endswith(
        "Signature=b97d918cfa904a5beff61c982a1b6f458b799221646efd99d3219ec94cdf2500")
    assert "SignedHeaders=host;x-amz-date" in headers["Authorization"]


def test_sign_v4_session_token_included():
    headers = sign_v4("GET", "h", "/", {}, {}, "e3b0", "us-east-1", "s3",
                      "ak", "sk", session_token="tok")
    assert headers["x-amz-security-token"] == "tok"
    assert "x-amz-security-token" in headers["Authorization"]


# ---------------------------------------------------------------------------
# S3 filesystem against the fake server
# ---------------------------------------------------------------------------

def test_s3_read_write_roundtrip_small(s3_server):
    data = b"small object contents"
    with open_stream("s3://bkt/dir/obj.bin", "w") as w:
        w.write(data)
    assert _FakeS3Handler.objects["bkt/dir/obj.bin"] == data
    with open_seek_stream_for_read("s3://bkt/dir/obj.bin") as r:
        assert r.read() == data
    # every request was signed
    assert all(a.startswith("AWS4-HMAC-SHA256") for a in _FakeS3Handler.auth_seen)


def test_s3_multipart_upload(s3_server, monkeypatch):
    from dmlc_core_tpu.io import remote_filesys
    fs = remote_filesys.S3FileSystem(part_size=1024)  # tiny parts for test
    data = os.urandom(10 * 1024 + 123)
    w = fs.open(URI("s3://bkt/big.bin"), "w")
    for i in range(0, len(data), 700):   # odd write sizes
        w.write(data[i:i + 700])
    w.close()
    assert _FakeS3Handler.objects["bkt/big.bin"] == data
    assert _FakeS3Handler.uploads == {}  # upload completed and cleaned


def test_s3_multipart_part_retry_on_dropped_connection(s3_server):
    """VERDICT r4 #10 (write-side restart-on-seek): a connection severed
    mid-UploadPart is retried — same partNumber+uploadId, so the re-PUT
    replaces the part idempotently — and the final object is bit-exact."""
    from dmlc_core_tpu.io import remote_filesys
    fs = remote_filesys.S3FileSystem(part_size=1024)
    data = os.urandom(5 * 1024 + 77)
    _FakeS3Handler.fail_next = ["part"]        # sever the FIRST part PUT
    with fs.open(URI("s3://bkt/retry.bin"), "w") as w:
        w.write(data)
    assert _FakeS3Handler.objects["bkt/retry.bin"] == data
    assert _FakeS3Handler.uploads == {}
    # part 1 reached the server twice (drop + retry); each part exactly
    # once thereafter — no duplicated or skipped part numbers
    assert _FakeS3Handler.part_attempts[:2] == [1, 1]
    assert _FakeS3Handler.part_attempts[2:] == sorted(
        set(_FakeS3Handler.part_attempts[2:]))
    assert _FakeS3Handler.fail_next == []      # the fault actually fired


def test_s3_multipart_initiate_retry_on_dropped_connection(s3_server):
    """A severed InitiateMultipartUpload retries (the lost response only
    orphans an upload id server-side) and the write still publishes."""
    from dmlc_core_tpu.io import remote_filesys
    fs = remote_filesys.S3FileSystem(part_size=1024)
    data = os.urandom(3 * 1024)
    _FakeS3Handler.fail_next = ["initiate"]
    with fs.open(URI("s3://bkt/init.bin"), "w") as w:
        w.write(data)
    assert _FakeS3Handler.objects["bkt/init.bin"] == data
    assert _FakeS3Handler.fail_next == []


def test_s3_multipart_complete_fault_surfaces(s3_server):
    """CompleteMultipartUpload is deliberately single-shot (a blind
    re-send after server-side success errors NoSuchUpload): a severed
    complete must surface as an error, never a silent fake success."""
    from dmlc_core_tpu.io import remote_filesys
    from dmlc_core_tpu.utils.logging import DMLCError
    fs = remote_filesys.S3FileSystem(part_size=1024)
    _FakeS3Handler.fail_next = ["complete"]
    w = fs.open(URI("s3://bkt/cmpl.bin"), "w")
    w.write(os.urandom(2048))
    with pytest.raises(DMLCError):
        w.close()
    assert "bkt/cmpl.bin" not in _FakeS3Handler.objects


def test_s3_abort_cleans_up_upload(s3_server):
    """abort() mid-write: AbortMultipartUpload removes the pending parts
    server-side and the object is never published (the crash-path analog
    of the checkpoint atomic-publish discipline)."""
    from dmlc_core_tpu.io import remote_filesys
    fs = remote_filesys.S3FileSystem(part_size=1024)
    w = fs.open(URI("s3://bkt/aborted.bin"), "w")
    w.write(os.urandom(4096))          # at least one part uploaded
    assert _FakeS3Handler.uploads      # upload open, parts pending
    w.abort()
    assert _FakeS3Handler.uploads == {}            # parts discarded
    assert "bkt/aborted.bin" not in _FakeS3Handler.objects


def test_s3_abort_after_part_fault(s3_server):
    """Error path end-to-end: if a part ultimately fails (all retries
    severed), the caller aborts; no object appears and the upload is
    cleaned."""
    from dmlc_core_tpu.io import remote_filesys
    from dmlc_core_tpu.utils.logging import DMLCError
    fs = remote_filesys.S3FileSystem(part_size=1024)
    # sever the same part PUT more times than _MAX_RETRY allows
    _FakeS3Handler.fail_next = ["part"] * 5
    w = fs.open(URI("s3://bkt/doomed.bin"), "w")
    with pytest.raises(DMLCError):
        w.write(os.urandom(8 * 1024))
    w.abort()
    assert _FakeS3Handler.uploads == {}
    assert "bkt/doomed.bin" not in _FakeS3Handler.objects


def test_s3_seek_read(s3_server):
    data = os.urandom(100000)
    _FakeS3Handler.objects["bkt/r.bin"] = data
    s = open_seek_stream_for_read("s3://bkt/r.bin")
    s.seek(50000)
    assert s.read(100) == data[50000:50100]
    s.seek(0)
    assert s.read(10) == data[:10]


def test_s3_list_and_path_info(s3_server):
    _FakeS3Handler.objects.update({
        "bkt/d/a.txt": b"aa", "bkt/d/b.txt": b"bbb", "bkt/d/sub/c.txt": b"c",
        "bkt/other.txt": b"x"})
    fs = get_filesystem(URI("s3://bkt/d"))
    infos = fs.list_directory(URI("s3://bkt/d"))
    names = sorted(i.path for i in infos)
    assert names == ["s3://bkt/d/a.txt", "s3://bkt/d/b.txt", "s3://bkt/d/sub"]
    assert [i.type for i in sorted(infos, key=lambda i: i.path)] == \
        ["file", "file", "dir"]
    info = fs.get_path_info(URI("s3://bkt/d/a.txt"))
    assert info.size == 2 and info.type == "file"
    assert fs.get_path_info(URI("s3://bkt/d")).type == "dir"


def test_s3_input_split_end_to_end(s3_server):
    lines = [f"{i%2} {i%11+1}:1.5".encode() for i in range(300)]
    _FakeS3Handler.objects["bkt/train.libsvm"] = b"\n".join(lines) + b"\n"
    got = []
    for k in range(3):
        sp = create_input_split("s3://bkt/train.libsvm", k, 3, "text",
                                threaded=False)
        while True:
            rec = sp.next_record()
            if rec is None:
                break
            got.append(bytes(rec))
        sp.close()
    assert sorted(got) == sorted(lines)


# ---------------------------------------------------------------------------
# WebHDFS
# ---------------------------------------------------------------------------

def test_webhdfs_read_seek_list(hdfs_server):
    srv, h = hdfs_server
    data = os.urandom(30000)
    h.files["/user/x/part-0"] = data
    h.files["/user/x/part-1"] = b"small"
    host = f"127.0.0.1:{srv.server_address[1]}"
    uri = f"hdfs://{host}/user/x/part-0"
    s = open_seek_stream_for_read(uri)
    assert s.read(100) == data[:100]
    s.seek(20000)
    assert s.read(50) == data[20000:20050]
    fs = get_filesystem(URI(uri))
    infos = fs.list_directory(URI(f"hdfs://{host}/user/x"))
    assert sorted(i.path.rsplit("/", 1)[-1] for i in infos) == \
        ["part-0", "part-1"]
    assert fs.get_path_info(URI(uri)).size == len(data)


def test_s3_special_char_key(s3_server):
    """Keys needing percent-encoding must sign and transfer correctly."""
    data = b"odd key bytes"
    with open_stream("s3://bkt/dir/my file+x.txt", "w") as w:
        w.write(data)
    assert _FakeS3Handler.objects["bkt/dir/my file+x.txt"] == data
    with open_seek_stream_for_read("s3://bkt/dir/my file+x.txt") as r:
        assert r.read() == data


def test_s3_bucket_root_is_dir(s3_server):
    _FakeS3Handler.objects["bkt/x.txt"] = b"x"
    fs = get_filesystem(URI("s3://bkt/"))
    assert fs.get_path_info(URI("s3://bkt/")).type == "dir"


def test_s3_endpoint_without_scheme(monkeypatch):
    from dmlc_core_tpu.io.remote_filesys import _S3Config
    monkeypatch.setenv("DMLC_S3_ENDPOINT", "localhost:9000")
    scheme, netloc, prefix = _S3Config().resolve("bkt")
    assert (scheme, netloc, prefix) == ("http", "localhost:9000", "/bkt")


def test_webhdfs_streaming_write_appends(hdfs_server, monkeypatch):
    """A write of 2.5 parts must stream as CREATE + APPENDs (>1 datanode
    data request), never buffering the whole object (hdfs_filesys.cc:56-75
    streams via hdfsWrite)."""
    srv, h = hdfs_server
    host = f"127.0.0.1:{srv.server_address[1]}"
    monkeypatch.setenv("DMLC_WEBHDFS_PART_SIZE", "1024")
    from dmlc_core_tpu.io import open_stream
    payload = bytes(range(256)) * 10  # 2560 bytes = 2.5 parts
    with open_stream(f"hdfs://{host}/out/big.bin", "w") as w:
        mv = memoryview(payload)
        for off in range(0, len(payload), 700):  # odd-sized writes
            w.write(mv[off:off + 700])
    assert h.files["/out/big.bin"] == payload
    reqs = [r for r in h.data_requests if r[1] == "/out/big.bin"]
    assert len(reqs) == 3                      # 1024 + 1024 + 512
    assert reqs[0][0] == "PUT" and {r[0] for r in reqs[1:]} == {"POST"}


def test_webhdfs_delegation_token(hdfs_server, monkeypatch):
    """DMLC_WEBHDFS_TOKEN rides every namenode request as ``delegation=``
    and suppresses ``user.name`` (Hadoop rejects both together) — the
    kerberized-cluster path: fetch the token out-of-band, export it."""
    srv, h = hdfs_server
    host = f"127.0.0.1:{srv.server_address[1]}"
    monkeypatch.setenv("HADOOP_USER_NAME", "alice")     # must be overridden
    monkeypatch.setenv("DMLC_WEBHDFS_TOKEN", "HAAEdG9r")
    h.files["/secure/f.bin"] = b"secret bytes"
    s = open_seek_stream_for_read(f"hdfs://{host}/secure/f.bin")
    assert s.read() == b"secret bytes"
    with open_stream(f"hdfs://{host}/secure/out.bin", "w") as w:
        w.write(b"tokenized write")
    assert h.files["/secure/out.bin"] == b"tokenized write"
    assert h.namenode_queries, "fake namenode saw no requests"
    for method, q in h.namenode_queries:
        assert q.get("delegation") == "HAAEdG9r", (method, q)
        assert "user.name" not in q, (method, q)
    # without the token, user.name comes back
    monkeypatch.delenv("DMLC_WEBHDFS_TOKEN")
    h.namenode_queries.clear()
    get_filesystem(URI(f"hdfs://{host}/secure/f.bin")).get_path_info(
        URI(f"hdfs://{host}/secure/f.bin"))
    assert all(q.get("user.name") == "alice" and "delegation" not in q
               for _, q in h.namenode_queries)


def test_webhdfs_write(hdfs_server):
    srv, h = hdfs_server
    host = f"127.0.0.1:{srv.server_address[1]}"
    with open_stream(f"hdfs://{host}/out/result.bin", "w") as w:
        w.write(b"written via webhdfs")
    assert h.files["/out/result.bin"] == b"written via webhdfs"


# ---------------------------------------------------------------------------
# fs CLI (reference filesys_test.cc ls/cat/cp driver)
# ---------------------------------------------------------------------------

def test_fscli_ls_cat_cp_stat(tmp_path, capsys, s3_server):
    from dmlc_core_tpu.io.fscli import main
    src = tmp_path / "in.txt"
    src.write_bytes(b"cli payload " * 100)

    assert main(["stat", f"file://{src}"]) == 0
    out = capsys.readouterr().out
    assert f"file {src.stat().st_size}" in out

    assert main(["ls", f"file://{tmp_path}"]) == 0
    assert "in.txt" in capsys.readouterr().out

    # cp local -> s3 (multipart machinery), then cat s3 back
    assert main(["cp", f"file://{src}", "s3://bkt/out.txt"]) == 0
    assert _FakeS3Handler.objects["bkt/out.txt"] == src.read_bytes()
    assert main(["cat", "s3://bkt/out.txt"]) == 0

    # bad URI → rc 1, no traceback
    assert main(["stat", "file:///definitely/not/there"]) == 1


def test_checkpoint_manager_over_s3(s3_server):
    """VERDICT r2 #9: CheckpointManager against an object store — save,
    retention pruning via DELETE, manifest round-trip, restore latest."""
    import numpy as np
    from dmlc_core_tpu.utils.checkpoint import CheckpointManager
    srv, h = s3_server
    mgr = CheckpointManager("s3://ckpts/run1", max_to_keep=2)
    for step in range(4):
        mgr.save(step, {"w": np.full(8, float(step), np.float32)},
                 meta={"loss": 1.0 / (step + 1)})
    assert mgr.steps == [2, 3]
    assert "ckpts/run1/ckpt-0.bin" not in h.objects       # pruned via DELETE
    assert "ckpts/run1/ckpt-3.bin" in h.objects
    assert "ckpts/run1/MANIFEST.json" in h.objects
    step, state = mgr.restore()
    assert step == 3
    np.testing.assert_array_equal(state["w"], np.full(8, 3.0, np.float32))
    assert mgr.meta(3)["loss"] == 0.25
    # a second manager over the same prefix sees the same history
    mgr2 = CheckpointManager("s3://ckpts/run1", max_to_keep=2)
    assert mgr2.latest_step == 3


def test_checkpoint_manager_over_webhdfs_rename_publish(hdfs_server):
    """hdfs:// checkpoints publish via write-to-temp + RENAME (appends are
    visible mid-write on WebHDFS, so direct writes would expose partials)."""
    import numpy as np
    from dmlc_core_tpu.utils.checkpoint import CheckpointManager
    srv, h = hdfs_server
    host = f"127.0.0.1:{srv.server_address[1]}"
    mgr = CheckpointManager(f"hdfs://{host}/ck/run", max_to_keep=2)
    for step in range(3):
        mgr.save(step, {"w": np.full(4, float(step), np.float32)})
    assert mgr.steps == [1, 2]
    step, state = mgr.restore()
    assert step == 2
    np.testing.assert_array_equal(state["w"], np.full(4, 2.0, np.float32))
    # no temp objects left behind, pruned step deleted
    assert set(h.files) == {"/ck/run/ckpt-1.bin", "/ck/run/ckpt-2.bin",
                            "/ck/run/MANIFEST.json"}


def test_fscli_pack_unpack_roundtrip(tmp_path, capsys):
    """text → .rec → text roundtrip through the CLI, including lines that
    embed the recordio magic bytes (the codec's escape path)."""
    from dmlc_core_tpu.io.fscli import main
    import struct as _struct
    src = tmp_path / "in.txt"
    magic = _struct.pack("<I", 0xced7230a)
    lines = [b"hello world", b"", b"x" * 5000, magic + b"embedded" + magic,
             "unicode-é".encode()]
    src.write_bytes(b"\n".join(lines) + b"\n")
    rec = tmp_path / "out.rec"
    txt = tmp_path / "back.txt"
    assert main(["pack", f"file://{src}", f"file://{rec}"]) == 0
    assert main(["unpack", f"file://{rec}", f"file://{txt}"]) == 0
    assert txt.read_bytes() == src.read_bytes()


# ---------------------------------------------------------------------------
# 429 rate limiting + Retry-After (the shared retry machinery end-to-end)
# ---------------------------------------------------------------------------

class _RateLimitHandler(_RangeHTTPHandler):
    """Range server that answers each queued GET with 429; the header value
    queued in ``limit_next`` (or None for no header) rides as Retry-After."""
    limit_next = []

    def do_GET(self):
        if type(self).limit_next:
            ra = type(self).limit_next.pop(0)
            self.send_response(429)
            if ra is not None:
                self.send_header("Retry-After", ra)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        super().do_GET()


@pytest.fixture
def ratelimit_server():
    _RateLimitHandler.files = {}
    _RateLimitHandler.requests = []
    _RateLimitHandler.limit_next = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _RateLimitHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv, _RateLimitHandler
    srv.shutdown()


def test_http_429_retried_and_retry_after_honored(ratelimit_server):
    """One 429 with ``Retry-After: 1`` then 200: the request succeeds and
    the server-directed pause is respected as a backoff floor."""
    import time as _t
    from dmlc_core_tpu.io.remote_filesys import _http_request
    srv, h = ratelimit_server
    h.files["/obj"] = b"rate limited payload"
    h.limit_next = ["1"]
    t0 = _t.monotonic()
    status, _, data = _http_request(
        "http", f"127.0.0.1:{srv.server_address[1]}", "GET", "/obj", {})
    assert status == 200 and data == b"rate limited payload"
    assert _t.monotonic() - t0 >= 0.9, \
        "Retry-After must raise the backoff floor"
    assert h.limit_next == []


def test_http_429_retry_after_capped_by_deadline(ratelimit_server):
    """A huge ``Retry-After: 30`` must not out-wait the I/O deadline: the
    sleep is clamped to the remaining budget and the final 429 comes back
    as a STATUS (caller contract), promptly."""
    import time as _t
    from dmlc_core_tpu.io.remote_filesys import _http_request
    from dmlc_core_tpu.utils.retry import Deadline
    srv, h = ratelimit_server
    h.files["/obj"] = b"x"
    h.limit_next = ["30"] * 10
    t0 = _t.monotonic()
    status, _, _ = _http_request(
        "http", f"127.0.0.1:{srv.server_address[1]}", "GET", "/obj", {},
        deadline=Deadline(0.5))
    assert status == 429
    assert _t.monotonic() - t0 < 5.0


def test_ranged_read_recovers_from_429(ratelimit_server):
    """End-to-end: a ranged stream read rides over a transient 429."""
    srv, h = ratelimit_server
    data = os.urandom(5000)
    h.files["/blob"] = data
    h.limit_next = ["0.05"]
    url = f"http://127.0.0.1:{srv.server_address[1]}/blob"
    with open_seek_stream_for_read(url) as s:
        assert s.read() == data


def test_parse_retry_after_both_rfc_forms():
    import datetime
    import email.utils
    from dmlc_core_tpu.io.remote_filesys import _parse_retry_after
    assert _parse_retry_after({}) is None
    assert _parse_retry_after({"retry-after": "7"}) == 7.0
    assert _parse_retry_after({"retry-after": "-3"}) == 0.0
    future = datetime.datetime.now(datetime.timezone.utc) \
        + datetime.timedelta(seconds=60)
    got = _parse_retry_after({"retry-after": email.utils.format_datetime(future)})
    assert got is not None and 50.0 <= got <= 61.0
    assert _parse_retry_after({"retry-after": "not a date"}) is None
