"""Indexed recordio split tests: record-count partitioning, per-epoch shuffle,
index building (reference src/io/indexed_recordio_split.cc behaviors)."""

import numpy as np
import pytest

from dmlc_core_tpu.io import (RecordIOWriter, create_input_split,
                              write_recordio_index)
from dmlc_core_tpu.io.single_file_split import SingleFileSplit


@pytest.fixture()
def indexed(tmp_path):
    rng = np.random.default_rng(1)
    recs = [bytes(rng.integers(0, 256, int(rng.integers(1, 100)),
                               dtype=np.uint8)) for _ in range(97)]
    rec_path = str(tmp_path / "d.rec")
    idx_path = str(tmp_path / "d.idx")
    with open(rec_path, "wb") as f:
        w = RecordIOWriter(f)
        for r in recs:
            w.write_record(r)
    n = write_recordio_index(rec_path, idx_path)
    assert n == len(recs)
    return rec_path, idx_path, recs


def test_partition_by_record_count(indexed):
    rec_path, idx_path, recs = indexed
    for nparts in (1, 2, 5):
        got = []
        sizes = []
        for k in range(nparts):
            with create_input_split(rec_path, k, nparts, "indexed_recordio",
                                    index_uri=idx_path) as s:
                part = list(iter(s.next_record, None))
            sizes.append(len(part))
            got.extend(part)
        assert got == recs
        # record-count balance: parts differ by at most 1 batch step
        assert max(sizes) - min(sizes) <= (len(recs) + nparts - 1) // nparts


def test_shuffle_per_epoch(indexed):
    rec_path, idx_path, recs = indexed
    with create_input_split(rec_path, 0, 1, "indexed_recordio",
                            index_uri=idx_path, shuffle=True,
                            shuffle_seed=5) as s:
        ep1 = list(iter(s.next_record, None))
        s.before_first()
        ep2 = list(iter(s.next_record, None))
    assert sorted(ep1) == sorted(recs)
    assert ep1 != recs and ep1 != ep2


def test_next_batch_and_chunk(indexed):
    rec_path, idx_path, recs = indexed
    with create_input_split(rec_path, 0, 1, "indexed_recordio",
                            index_uri=idx_path, batch_size=10) as s:
        batches = []
        while True:
            b = s.next_batch()
            if b is None:
                break
            batches.append(b)
    assert [r for b in batches for r in b] == recs
    assert all(len(b) <= 10 for b in batches)


def test_single_file_split(tmp_path):
    lines = [b"alpha", b"beta", b"gamma"]
    p = tmp_path / "f.txt"
    p.write_bytes(b"\n".join(lines) + b"\n")
    s = SingleFileSplit(str(p))
    assert list(iter(s.next_record, None)) == lines
    s.before_first()
    assert list(iter(s.next_record, None)) == lines
    s.close()
