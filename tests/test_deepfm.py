"""DeepFM: layout parity, pipelined-tower parity over a 'pp' mesh, and
learning a nonlinearity the plain FM cannot express."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dmlc_core_tpu.models import DeepFM, FactorizationMachine, \
    make_train_step  # noqa: E402


def _flat_batch(rng, B, F, cap):
    ids, vals, segs = [], [], []
    for r in range(B):
        k = int(rng.integers(1, 5))
        for i in rng.choice(F, size=k, replace=False):
            ids.append(int(i)), vals.append(float(rng.random()) + 0.1)
            segs.append(r)
    pad = cap - len(ids)
    return {"ids": jnp.asarray(ids + [0] * pad, jnp.int32),
            "vals": jnp.asarray(vals + [0.0] * pad, jnp.float32),
            "segments": jnp.asarray(segs + [B] * pad, jnp.int32),
            "labels": jnp.asarray(rng.integers(0, 2, B), jnp.float32),
            "weights": jnp.ones((B,), jnp.float32)}


def _rowmajor_of(flat, B, K):
    ids = np.zeros((B, K), np.int32)
    vals = np.zeros((B, K), np.float32)
    fill = np.zeros(B, np.int32)
    segs = np.asarray(flat["segments"])
    fi = np.asarray(flat["ids"])
    fv = np.asarray(flat["vals"])
    for j in range(len(fi)):
        r = int(segs[j])
        if r < B and fv[j] != 0:
            ids[r, fill[r]], vals[r, fill[r]] = fi[j], fv[j]
            fill[r] += 1
    return {"ids": jnp.asarray(ids), "vals": jnp.asarray(vals),
            "labels": flat["labels"], "weights": flat["weights"]}


def test_deepfm_layouts_agree():
    rng = np.random.default_rng(0)
    B, F = 16, 40
    flat = _flat_batch(rng, B, F, cap=128)
    rm = _rowmajor_of(flat, B, K=8)
    model = DeepFM(num_features=F, dim=8, layers=2, engine="xla")
    params = model.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(model.forward(params, flat),
                               model.forward(params, rm),
                               rtol=2e-5, atol=2e-5)


def test_deepfm_pipelined_tower_matches_sequential():
    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs 4 devices")
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devices[:4]), ("pp",))
    rng = np.random.default_rng(1)
    B, F = 16, 40
    flat = _flat_batch(rng, B, F, cap=128)
    model = DeepFM(num_features=F, dim=8, layers=4, engine="xla")
    params = model.init(jax.random.PRNGKey(0))
    pp = model.with_pipelined_tower(mesh, "pp", microbatches=4)
    np.testing.assert_allclose(pp.forward(params, flat),
                               model.forward(params, flat),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError):
        DeepFM(num_features=F, dim=8, layers=3).with_pipelined_tower(
            mesh, "pp")


def test_deepfm_beats_fm_on_nonlinear_target():
    """Labels depend on a threshold of the embedding sum — representable by
    the tanh tower, not by FM's quadratic form.  DeepFM must reach a lower
    train loss than FM with the same budget."""
    optax = pytest.importorskip("optax")
    rng = np.random.default_rng(2)
    B, F = 256, 30
    flat = _flat_batch(rng, B, F, cap=1280)
    # nonlinear target: parity of the number of active features in a group
    segs = np.asarray(flat["segments"])
    ids = np.asarray(flat["ids"])
    labels = np.zeros(B, np.float32)
    for r in range(B):
        m = (segs == r)
        labels[r] = float((ids[m] < 15).sum() % 2)
    flat["labels"] = jnp.asarray(labels)

    def fit(model, steps=150, lr=0.05):
        params = model.init(jax.random.PRNGKey(3))
        opt = optax.adam(lr)
        state = opt.init(params)
        step = make_train_step(model, opt)
        loss = None
        for _ in range(steps):
            params, state, loss = step(params, state, flat)
        return float(loss)

    fm_loss = fit(FactorizationMachine(num_features=F, dim=8, engine="xla"))
    deep_loss = fit(DeepFM(num_features=F, dim=8, layers=2, engine="xla"))
    assert deep_loss < fm_loss * 0.9, (fm_loss, deep_loss)
