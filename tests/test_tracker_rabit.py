"""Tracker + rabit client tests: topology properties, full local rendezvous
with tree collectives over real sockets, recover re-registration, and the
local launcher end-to-end (the reference validates distributed behavior with
--cluster local the same way, SURVEY §4)."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from dmlc_core_tpu.parallel import (RabitContext, RabitTracker, compute_ring,
                                    compute_tree)


@pytest.mark.parametrize("world", [1, 2, 3, 5, 8, 16])
def test_tree_and_ring_properties(world):
    tree = compute_tree(world)
    # connected binary tree: world-1 edges, each node ≤3 neighbors
    edges = sum(len(v) for v in tree.values())
    assert edges == 2 * (world - 1)
    assert all(len(v) <= 3 for v in tree.values())
    ring = compute_ring(world)
    assert sorted(ring) == list(range(world))
    # DFS pre-order: every rank appears after its tree parent (recovery data
    # flows with tree locality; ring links are brokered as extra connections,
    # like the reference's assign_rank sends both tree and ring neighbors)
    pos = {r: i for i, r in enumerate(ring)}
    for r in range(1, world):
        assert pos[r] > pos[(r - 1) // 2]


def _run_cohort(world, fn):
    """Spin a tracker + world thread-workers; fn(ctx, results, rank)."""
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    results = [None] * world
    errors = []

    def worker(i):
        try:
            ctx = RabitContext(env["DMLC_TRACKER_URI"],
                               int(env["DMLC_TRACKER_PORT"]),
                               jobid=f"w{i}")
            fn(ctx, results, i)
            ctx.shutdown()
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    tracker.join(timeout=30)
    assert not errors, errors
    return results


@pytest.mark.parametrize("world", [1, 2, 4, 7])
def test_allreduce_sum_and_max(world):
    def fn(ctx, results, i):
        contrib = np.arange(4, dtype=np.float64) + ctx.rank
        s = ctx.allreduce(contrib, "sum")
        m = ctx.allreduce(contrib, "max")
        results[i] = (ctx.rank, s, m)

    results = _run_cohort(world, fn)
    expect_sum = sum(np.arange(4) + r for r in range(world))
    expect_max = np.arange(4) + (world - 1)
    for rank, s, m in results:
        np.testing.assert_allclose(s, expect_sum)
        np.testing.assert_allclose(m, expect_max)


@pytest.mark.parametrize("root", [0, 1, 3])
def test_broadcast_any_root(root):
    world = 4

    def fn(ctx, results, i):
        payload = {"cfg": "v1", "root": ctx.rank} if ctx.rank == root else None
        out = ctx.broadcast(payload, root=root)
        results[i] = out

    results = _run_cohort(world, fn)
    for out in results:
        assert out == {"cfg": "v1", "root": root}


def test_allgather():
    world = 4

    def fn(ctx, results, i):
        out = ctx.allgather(np.array([ctx.rank * 10.0]))
        results[i] = out

    results = _run_cohort(world, fn)
    for out in results:
        np.testing.assert_allclose(out.ravel(), [0, 10, 20, 30])


def test_recover_keeps_rank():
    world = 3
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    ranks = {}
    ready = threading.Barrier(world)

    def worker(i):
        ctx = RabitContext(env["DMLC_TRACKER_URI"],
                           int(env["DMLC_TRACKER_PORT"]), jobid=f"w{i}")
        ranks[i] = ctx.rank
        ready.wait()
        ctx.shutdown()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # simulate restart of worker 1: recover must return the same rank
    # (links are not dialed — the old cohort is gone; a real elastic rejoin
    # would find live peers at the refreshed addresses)
    ctx = RabitContext(env["DMLC_TRACKER_URI"],
                       int(env["DMLC_TRACKER_PORT"]), jobid="w1",
                       recover=True, connect_links=False)
    assert ctx.rank == ranks[1]
    ctx.shutdown()
    tracker.stop()


WORKER_SCRIPT = r"""
import numpy as np
from dmlc_core_tpu.parallel import RabitContext
with RabitContext.from_env() as rc:
    out = rc.allreduce(np.array([float(rc.rank + 1)]))
    assert out[0] == sum(range(1, rc.world_size + 1)), out
    rc.tracker_print(f"rank {rc.rank} ok")
"""


def test_local_launcher_end_to_end(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    env = os.environ.copy()
    # the package is run from the repo, not installed: workers need it on path
    env["PYTHONPATH"] = "/root/repo" + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    rc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3", "--host-ip", "127.0.0.1",
         sys.executable, str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=120,
        env=env)
    assert rc.returncode == 0, rc.stderr


def test_pstracker_env_and_scheduler_spawn():
    """PSTracker parity (reference tracker.py:336-386): scheduler process
    gets DMLC_ROLE=scheduler + PS root env; workers get the same env."""
    import subprocess
    import sys
    from dmlc_core_tpu.parallel.tracker import PSTracker
    t = PSTracker(host_ip="127.0.0.1",
                  pscmd=[sys.executable, "-c",
                         "import os; "
                         "assert os.environ['DMLC_ROLE']=='scheduler'; "
                         "assert os.environ['DMLC_PS_ROOT_URI']=='127.0.0.1'; "
                         "assert int(os.environ['DMLC_PS_ROOT_PORT'])>0"])
    env = t.worker_envs()
    assert env["DMLC_PS_ROOT_URI"] == "127.0.0.1"
    assert int(env["DMLC_PS_ROOT_PORT"]) >= 9100
    t.start()
    assert t.join() == 0
    t.stop()
