"""Tracker + rabit client tests: topology properties, full local rendezvous
with tree collectives over real sockets, recover re-registration, and the
local launcher end-to-end (the reference validates distributed behavior with
--cluster local the same way, SURVEY §4)."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from dmlc_core_tpu.parallel import (RabitContext, RabitTracker, compute_ring,
                                    compute_tree)


def _jax_cpu_multiprocess() -> bool:
    """jax < 0.5 CPU backends refuse multi-process computations outright
    ("Multiprocess computations aren't implemented on the CPU backend") —
    the elastic-rejoin tests need them to run their 3-process cohorts."""
    import jax
    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True
    return (major, minor) >= (0, 5)


needs_multiprocess_cpu = pytest.mark.skipif(
    not _jax_cpu_multiprocess(),
    reason="this jax's CPU backend lacks multi-process collectives")


@pytest.mark.parametrize("world", [1, 2, 3, 5, 8, 16])
def test_tree_and_ring_properties(world):
    tree = compute_tree(world)
    # connected binary tree: world-1 edges, each node ≤3 neighbors
    edges = sum(len(v) for v in tree.values())
    assert edges == 2 * (world - 1)
    assert all(len(v) <= 3 for v in tree.values())
    ring = compute_ring(world)
    assert sorted(ring) == list(range(world))
    # DFS pre-order: every rank appears after its tree parent (recovery data
    # flows with tree locality; ring links are brokered as extra connections,
    # like the reference's assign_rank sends both tree and ring neighbors)
    pos = {r: i for i, r in enumerate(ring)}
    for r in range(1, world):
        assert pos[r] > pos[(r - 1) // 2]


def _run_cohort(world, fn):
    """Spin a tracker + world thread-workers; fn(ctx, results, rank)."""
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    results = [None] * world
    errors = []

    def worker(i):
        try:
            ctx = RabitContext(env["DMLC_TRACKER_URI"],
                               int(env["DMLC_TRACKER_PORT"]),
                               jobid=f"w{i}")
            fn(ctx, results, i)
            ctx.shutdown()
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    tracker.join(timeout=30)
    assert not errors, errors
    return results


@pytest.mark.parametrize("world", [1, 2, 4, 7])
def test_allreduce_sum_and_max(world):
    def fn(ctx, results, i):
        contrib = np.arange(4, dtype=np.float64) + ctx.rank
        s = ctx.allreduce(contrib, "sum")
        m = ctx.allreduce(contrib, "max")
        results[i] = (ctx.rank, s, m)

    results = _run_cohort(world, fn)
    expect_sum = sum(np.arange(4) + r for r in range(world))
    expect_max = np.arange(4) + (world - 1)
    for rank, s, m in results:
        np.testing.assert_allclose(s, expect_sum)
        np.testing.assert_allclose(m, expect_max)


@pytest.mark.parametrize("root", [0, 1, 3])
def test_broadcast_any_root(root):
    world = 4

    def fn(ctx, results, i):
        payload = {"cfg": "v1", "root": ctx.rank} if ctx.rank == root else None
        out = ctx.broadcast(payload, root=root)
        results[i] = out

    results = _run_cohort(world, fn)
    for out in results:
        assert out == {"cfg": "v1", "root": root}


def test_allgather():
    world = 4

    def fn(ctx, results, i):
        out = ctx.allgather(np.array([ctx.rank * 10.0]))
        results[i] = out

    results = _run_cohort(world, fn)
    for out in results:
        np.testing.assert_allclose(out.ravel(), [0, 10, 20, 30])


def test_recover_keeps_rank():
    world = 3
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    ranks = {}
    ready = threading.Barrier(world)

    def worker(i):
        ctx = RabitContext(env["DMLC_TRACKER_URI"],
                           int(env["DMLC_TRACKER_PORT"]), jobid=f"w{i}")
        ranks[i] = ctx.rank
        ready.wait()
        ctx.shutdown()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # simulate restart of worker 1: recover must return the same rank
    # (links are not dialed — the old cohort is gone; a real elastic rejoin
    # would find live peers at the refreshed addresses)
    ctx = RabitContext(env["DMLC_TRACKER_URI"],
                       int(env["DMLC_TRACKER_PORT"]), jobid="w1",
                       recover=True, connect_links=False)
    assert ctx.rank == ranks[1]
    ctx.shutdown()
    tracker.stop()


WORKER_SCRIPT = r"""
import numpy as np
from dmlc_core_tpu.parallel import RabitContext
with RabitContext.from_env() as rc:
    out = rc.allreduce(np.array([float(rc.rank + 1)]))
    assert out[0] == sum(range(1, rc.world_size + 1)), out
    rc.tracker_print(f"rank {rc.rank} ok")
"""


def test_local_launcher_end_to_end(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    env = os.environ.copy()
    # the package is run from the repo, not installed: workers need it on path
    env["PYTHONPATH"] = "/root/repo" + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    rc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "local", "-n", "3", "--host-ip", "127.0.0.1",
         sys.executable, str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=120,
        env=env)
    assert rc.returncode == 0, rc.stderr


def test_pstracker_env_and_scheduler_spawn():
    """PSTracker parity (reference tracker.py:336-386): scheduler process
    gets DMLC_ROLE=scheduler + PS root env; workers get the same env."""
    import subprocess
    import sys
    from dmlc_core_tpu.parallel.tracker import PSTracker
    t = PSTracker(host_ip="127.0.0.1",
                  pscmd=[sys.executable, "-c",
                         "import os; "
                         "assert os.environ['DMLC_ROLE']=='scheduler'; "
                         "assert os.environ['DMLC_PS_ROOT_URI']=='127.0.0.1'; "
                         "assert int(os.environ['DMLC_PS_ROOT_PORT'])>0"])
    env = t.worker_envs()
    assert env["DMLC_PS_ROOT_URI"] == "127.0.0.1"
    assert int(env["DMLC_PS_ROOT_PORT"]) >= 9100
    t.start()
    assert t.join() == 0
    t.stop()


ELASTIC_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
# OVERRIDE (not append): under pytest the parent env carries conftest's
# device_count=8 flag; 8 virtual devices per process would make a
# 24-device mesh whose dp axis cannot divide this worker's tiny arrays
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge
xla_bridge._backend_factories.pop("axon", None)
import numpy as np
from dmlc_core_tpu.parallel import ElasticJaxMesh, RabitContext

attempt = int(os.environ.get("DMLC_NUM_ATTEMPT", "0"))
ctx = RabitContext.from_env()
if attempt > 0:
    # reference LoadCheckPoint contract: restoring fast-forwards the rabit
    # seq so the reborn worker's control-plane frames align with survivors
    state = ctx.load_checkpoint()
    assert state == {"phase": 1}, state
mesh = ElasticJaxMesh(ctx)          # base port from DMLC_ELASTIC_BASE_PORT
mesh.initialize()
from jax.experimental import multihost_utils
if attempt == 0:
    assert mesh.generation == 0
    g = multihost_utils.process_allgather(
        np.array([float(ctx.rank + 1)], np.float32))
    assert float(g.sum()) == 6.0, g
    # one control-plane collective so seq alignment is actually exercised
    rows = ctx.allreduce(np.array([100.0], np.float32))
    assert float(rows[0]) == 300.0
    ctx.checkpoint({"phase": 1})
    if ctx.rank == 2:
        print("DYING", ctx.rank, flush=True)
        os._exit(7)                      # crash: no shutdown, no goodbye
    changed = mesh.resync()              # sync point: survivors rebuild
    assert changed, "survivors must observe the bumped generation"
assert mesh.generation == 1, mesh.generation
# post-rejoin reduction over the REBUILT jax mesh: value read-back proves
# the generation-1 collective is correct on every process
g2 = multihost_utils.process_allgather(
    np.array([10.0 * (ctx.rank + 1)], np.float32))
assert float(g2.sum()) == 60.0, g2
import jax.numpy as jnp
total = float(jax.jit(jnp.sum)(
    multihost_utils.host_local_array_to_global_array(
        np.full((2, 2), float(ctx.rank + 1), np.float32),
        jax.sharding.Mesh(np.array(jax.devices()), ("dp",)),
        jax.sharding.PartitionSpec("dp"))))
assert total == 2 * 2 * 6.0, total
print("ELASTIC-OK", ctx.rank, mesh.generation, flush=True)
mesh.close()
ctx.shutdown()
'''


@needs_multiprocess_cpu
def test_elastic_jax_mesh_rejoin_after_kill(tmp_path):
    """VERDICT r4 #9 (SURVEY §7 hard part (c)): kill one jax.distributed
    process mid-job; the launcher respawns it (DMLC_NUM_ATTEMPT=1), the
    cohort agrees a new mesh generation over the rabit control plane, every
    process re-initializes, and a post-rejoin psum/allgather is correct."""
    import socket as _socket
    import subprocess
    import sys

    # two consecutive free ports: generation 0 and the post-rejoin gen 1
    for _ in range(20):
        s0, s1 = _socket.socket(), _socket.socket()
        try:
            s0.bind(("127.0.0.1", 0))
            p = s0.getsockname()[1]
            s1.bind(("127.0.0.1", p + 1))
            break
        except OSError:
            continue
        finally:
            s0.close()
            s1.close()
    script = tmp_path / "elastic_worker.py"
    script.write_text(ELASTIC_WORKER)
    tracker = RabitTracker(num_workers=3, host_ip="127.0.0.1")
    tracker.start()
    # generous timeouts: this 1-core host time-slices these 3 jax
    # processes against whatever else runs (harvest probes, CI); the
    # budgets only bound the failure case — a healthy run takes ~2 min
    base_env = {**os.environ, **tracker.worker_envs(),
                "PYTHONPATH": "/root/repo",
                "DMLC_ELASTIC_BASE_PORT": str(p),
                "DMLC_CHECKPOINT_DIR": str(tmp_path),
                "DMLC_CONNECT_TIMEOUT": "120",
                "DMLC_RECOVER_TIMEOUT": "300"}

    def spawn(rank, att):
        env = dict(base_env, DMLC_TASK_ID=str(rank),
                   DMLC_NUM_ATTEMPT=str(att))
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
    procs = {i: spawn(i, 0) for i in range(3)}
    try:
        assert procs[2].wait(timeout=300) == 7      # crashed as scripted
        procs[2] = spawn(2, 1)                      # launcher-style retry
        outs = {}
        for i, pr in procs.items():
            out, _ = pr.communicate(timeout=480)
            outs[i] = out
            assert pr.returncode == 0, (i, out[-2000:])
        for i in range(3):
            assert f"ELASTIC-OK {i} 1" in outs[i], outs[i][-1500:]
    finally:
        for pr in procs.values():
            if pr.poll() is None:
                pr.kill()
        tracker.stop()


@needs_multiprocess_cpu
def test_elastic_rejoin_through_tpu_launcher(tmp_path):
    """The launcher half of elastic rejoin: `--cluster tpu --max-attempts 2`
    respawns the crashed rank with DMLC_NUM_ATTEMPT=1 itself (no manual
    respawn), the cohort resyncs to generation 1, and the job exits 0."""
    import subprocess
    import sys

    script = tmp_path / "elastic_worker.py"
    script.write_text(ELASTIC_WORKER)
    env = {**os.environ, "PYTHONPATH": "/root/repo",
           "DMLC_CHECKPOINT_DIR": str(tmp_path),
           "DMLC_CONNECT_TIMEOUT": "120", "DMLC_RECOVER_TIMEOUT": "300"}
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "tpu", "-n", "3", "--max-attempts", "2",
         "--elastic", "--host-ip", "127.0.0.1",
         "--env", "PYTHONPATH=/root/repo",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd="/root/repo")
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-2500:])
    for i in range(3):
        assert f"ELASTIC-OK {i} 1" in out.stdout, out.stdout[-2000:]


def test_tpu_launcher_without_elastic_fails_fast(tmp_path):
    """Without --elastic a crashed tpu worker is NOT respawned: plain
    jax.distributed cannot admit a reborn process, so retry would hang —
    the launcher must surface the failure immediately instead."""
    import subprocess
    import sys
    import time as _t

    script = tmp_path / "crash.py"
    script.write_text(
        "import os, sys\n"
        "assert os.environ.get('DMLC_NUM_ATTEMPT', '0') == '0', "
        "'non-elastic job must never see a retry attempt'\n"
        "sys.exit(3 if os.environ['DMLC_TASK_ID'] == '1' else 0)\n")
    t0 = _t.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "tpu", "-n", "2", "--max-attempts", "3",
         "--host-ip", "127.0.0.1", "--env", "PYTHONPATH=/root/repo",
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "PYTHONPATH": "/root/repo"}, cwd="/root/repo")
    assert out.returncode == 3, (out.stdout[-800:], out.stderr[-1500:])
    assert _t.monotonic() - t0 < 120


# ---------------------------------------------------------------------------
# resilience knobs: peer recv timeout + heartbeat liveness
# ---------------------------------------------------------------------------

def _solo_ctx(**kw):
    """1-worker cohort: tracker + registered context (caller tears down)."""
    tracker = RabitTracker(num_workers=1, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    ctx = RabitContext(env["DMLC_TRACKER_URI"],
                       int(env["DMLC_TRACKER_PORT"]), jobid="w0",
                       heartbeat_interval=0, **kw)
    return tracker, ctx


def test_peer_recv_timeout_defaults_to_twice_recover_timeout(monkeypatch):
    monkeypatch.delenv("DMLC_PEER_RECV_TIMEOUT", raising=False)
    tracker, ctx = _solo_ctx(recover_timeout=45.0)
    try:
        assert ctx.peer_recv_timeout == 90.0
    finally:
        ctx.shutdown()
        tracker.stop()


@pytest.mark.parametrize("raw", ["0", "-3"])
def test_peer_recv_timeout_nonpositive_means_unbounded(monkeypatch, raw):
    monkeypatch.setenv("DMLC_PEER_RECV_TIMEOUT", raw)
    tracker, ctx = _solo_ctx()
    try:
        assert ctx.peer_recv_timeout is None
    finally:
        ctx.shutdown()
        tracker.stop()


def test_peer_recv_timeout_malformed_falls_back_to_default(monkeypatch):
    """An env typo must not crash worker boot — it logs and uses the
    default."""
    monkeypatch.setenv("DMLC_PEER_RECV_TIMEOUT", "garbage")
    tracker, ctx = _solo_ctx(recover_timeout=30.0)
    try:
        assert ctx.peer_recv_timeout == 60.0
    finally:
        ctx.shutdown()
        tracker.stop()


def test_tracker_declares_silent_worker_dead_and_resets_survivors():
    """Liveness: a worker that stops beating past DMLC_HEARTBEAT_TIMEOUT
    is declared dead exactly once, the dead-worker counter ticks, and the
    survivors get a reset_links push (generation bump) so their next
    collective re-rendezvouses instead of hanging on the corpse."""
    import time as _t

    from dmlc_core_tpu.utils.metrics import metrics

    dead0 = metrics.counter("tracker.dead_workers").value
    tracker = RabitTracker(num_workers=2, host_ip="127.0.0.1",
                           heartbeat_timeout_s=0.6)
    tracker.start()
    env = tracker.worker_envs()
    ctxs = {}
    errors = []

    def worker(i):
        try:
            ctxs[i] = RabitContext(env["DMLC_TRACKER_URI"],
                                   int(env["DMLC_TRACKER_PORT"]),
                                   jobid=f"w{i}", heartbeat_interval=0.1)
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    survivor = ctxs[0] if ctxs[1].rank != 0 else ctxs[1]
    silent = ctxs[1] if survivor is ctxs[0] else ctxs[0]
    try:
        silent._hb_stop.set()           # worker falls silent, stays alive
        give_up = _t.monotonic() + 10
        while _t.monotonic() < give_up:
            if (metrics.counter("tracker.dead_workers").value > dead0
                    and survivor._target_gen >= 1):
                break
            _t.sleep(0.05)
        assert metrics.counter("tracker.dead_workers").value == dead0 + 1
        assert survivor._target_gen >= 1, \
            "survivor never saw the tracker's reset_links push"
    finally:
        for c in ctxs.values():
            c.shutdown()
        tracker.stop()
