"""dmlclint: golden good/bad snippets per rule, suppressions, CLI.

Each bad snippet is shaped like the historical bug that motivated its
rule (see docs/analysis.md) — the test suite is the rule's spec.
"""

import json
import os
import textwrap

import pytest

from dmlc_core_tpu.analysis.core import lint_paths
from dmlc_core_tpu.analysis.lint import main as lint_main
from dmlc_core_tpu.analysis import inventory as inv


def _lint_snippet(tmp_path, source, rules=None, rel="mod.py"):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    findings, stats, ctx = lint_paths([str(p)], rules=rules,
                                      repo_root=str(tmp_path))
    return findings, stats, ctx


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- env-discipline ---------------------------------------------------------

def test_env_raw_reads_flagged(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import os
        a = os.environ["DMLC_FOO"]
        b = os.environ.get("DMLC_BAR")
        c = os.getenv("DMLC_BAZ", "1")
        d = os.environ.get("PATH")          # non-DMLC: fine
    """, rules=["env-discipline"])
    assert len(findings) == 3
    assert _rules(findings) == ["env-discipline"]
    assert sorted(f.line for f in findings) == [2, 3, 4]


def test_env_module_constant_indirection(tmp_path):
    # anomaly.py idiom: ENV_VAR = "DMLC_SLO_SPEC"; os.environ.get(ENV_VAR)
    findings, _, _ = _lint_snippet(tmp_path, """\
        import os
        KEY = "DMLC_INDIRECT"
        v = os.environ.get(KEY)
    """, rules=["env-discipline"])
    assert len(findings) == 1


def test_env_helpers_are_clean_and_noted(tmp_path):
    findings, _, ctx = _lint_snippet(tmp_path, """\
        from dmlc_core_tpu.utils.parameter import env_int, get_env
        a = get_env("DMLC_GOOD", "x")
        b = env_int("DMLC_ALSO_GOOD", 3)
    """, rules=["env-discipline"])
    assert findings == []
    assert set(ctx.knob_sites) == {"DMLC_GOOD", "DMLC_ALSO_GOOD"}


def test_env_parameter_module_exempt(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import os
        raw = os.environ.get("DMLC_INSIDE_HELPER")
    """, rules=["env-discipline"], rel="utils/parameter.py")
    assert findings == []


# -- metric-vocabulary ------------------------------------------------------

def test_metric_grammar(tmp_path):
    findings, _, ctx = _lint_snippet(tmp_path, """\
        from dmlc_core_tpu.utils.metrics import metrics
        metrics.counter("serving.good_name")
        metrics.counter("BadName")
        metrics.gauge("nodots")
        name = "dynamic." + "x"
        metrics.counter(name)               # dynamic: skipped
    """, rules=["metric-vocabulary"])
    assert len(findings) == 2
    assert sorted(f.line for f in findings) == [3, 4]
    assert "serving.good_name" in ctx.metric_sites


def _fake_repo(tmp_path, doc, code):
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(doc)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(textwrap.dedent(code))
    return pkg


DOC = """\
## Metric catalog

| Name | Type | Meaning |
|---|---|---|
| `app.{hits,misses}` | counter | cache traffic |
| `app.latency_s` | histogram | request wall time |
| `anomaly.stall.<stage>` | gauge | per-stage stalls |

| File | Contents |
|---|---|
| `incident.json` | not a metric — must not be parsed as one |
"""


def test_metric_doc_two_way_check(tmp_path):
    pkg = _fake_repo(tmp_path, DOC, """\
        from dmlc_core_tpu.utils.metrics import metrics
        metrics.counter("app.hits")
        metrics.counter("app.misses")
        metrics.gauge("anomaly.stall.parse")
        metrics.counter("app.undocumented")
    """)
    findings, _, _ = lint_paths([str(pkg)], rules=["metric-vocabulary"],
                                repo_root=str(tmp_path))
    msgs = [f.message for f in findings]
    # app.undocumented missing a row; app.latency_s documented but gone
    assert any("app.undocumented" in m for m in msgs)
    assert any("app.latency_s" in m for m in msgs)
    # braces and wildcards cover; the File table never leaks stale rows
    assert not any("app.hits" in m for m in msgs)
    assert not any("anomaly.stall" in m for m in msgs)
    assert not any("incident.json" in m for m in msgs)
    assert len(findings) == 2


# -- span-vocabulary --------------------------------------------------------

def test_span_grammar(tmp_path):
    findings, _, ctx = _lint_snippet(tmp_path, """\
        from dmlc_core_tpu.telemetry import trace as teltrace
        with teltrace.span("data_service.serve_stream"):
            pass
        teltrace.start_span("reshard")          # single segment: legal
        teltrace.span("Bad Name")
        name = "dyn." + "x"
        teltrace.span(name)                     # dynamic: skipped
        "abc".split("b")[0].span if False else None
    """, rules=["span-vocabulary"])
    assert [f.line for f in findings] == [5]
    assert "data_service.serve_stream" in ctx.span_sites
    assert "reshard" in ctx.span_sites


SPAN_DOC = """\
## Span catalog

| Span | Emitted by | Meaning |
|---|---|---|
| `app.{serve,drain}` | worker | epoch phases |
| `app.old_phase` | worker | retired phase |
| `app.rpc.<cmd>` | server | per-command handling |

| Name | Type | Meaning |
|---|---|---|
| `app.latency_s` | histogram | must not leak into the span table |
"""


def test_span_doc_two_way_check(tmp_path):
    pkg = _fake_repo(tmp_path, SPAN_DOC, """\
        from dmlc_core_tpu.telemetry import trace as teltrace
        teltrace.span("app.serve")
        teltrace.span("app.drain")
        teltrace.start_span("app.rpc.heartbeat")
        teltrace.span("app.undocumented")
    """)
    findings, _, _ = lint_paths([str(pkg)], rules=["span-vocabulary"],
                                repo_root=str(tmp_path))
    msgs = [f.message for f in findings]
    # app.undocumented missing a row; app.old_phase documented but gone
    assert any("app.undocumented" in m for m in msgs)
    assert any("app.old_phase" in m for m in msgs)
    # braces and wildcards cover; the metric table never leaks spans
    assert not any("app.serve" in m for m in msgs)
    assert not any("app.rpc" in m for m in msgs)
    assert not any("app.latency_s" in m for m in msgs)
    assert len(findings) == 2


# -- endpoint-vocabulary ----------------------------------------------------

ENDPOINT_DOC = """\
## Endpoints

| Endpoint | Where | Meaning |
|---|---|---|
| `/metrics` | everywhere | Prometheus text |
| `/timeline?metric=&since=` | everywhere | history store |
| `/stale` | nowhere | retired long ago |

| Name | Type | Meaning |
|---|---|---|
| `app.latency_s` | histogram | must not leak into the endpoint table |
"""


def test_endpoint_grammar(tmp_path):
    findings, _, ctx = _lint_snippet(tmp_path, """\
        _ROUTES = {}
        def _endpoint(path):
            def deco(fn):
                _ROUTES[path] = fn.__name__
                return fn
            return deco
        @_endpoint("/metrics")
        def a(q): pass
        @_endpoint("/BadPath")
        def b(q): pass
        @_endpoint("/two/segments")
        def c(q): pass
    """, rules=["endpoint-vocabulary"])
    msgs = [f.message for f in findings]
    assert any("/BadPath" in m for m in msgs)
    assert any("/two/segments" in m for m in msgs)
    assert not any("'/metrics'" in m for m in msgs)
    assert "/metrics" in ctx.endpoint_sites   # noted for the inventory


def test_endpoint_doc_two_way_check(tmp_path):
    pkg = _fake_repo(tmp_path, ENDPOINT_DOC, """\
        def _endpoint(path):
            def deco(fn):
                return fn
            return deco
        @_endpoint("/metrics")
        def a(q): pass
        @_endpoint("/timeline")
        def t(q): pass
        @_endpoint("/undocumented")
        def u(q): pass
    """)
    findings, _, _ = lint_paths([str(pkg)], rules=["endpoint-vocabulary"],
                                repo_root=str(tmp_path))
    msgs = [f.message for f in findings]
    # /undocumented missing a row; /stale documented but unregistered
    assert any("/undocumented" in m for m in msgs)
    assert any("/stale" in m for m in msgs)
    # query-string doc rows cover their path; the metric table never
    # leaks into the endpoint vocabulary
    assert not any("'/metrics'" in m or "'/timeline'" in m for m in msgs)
    assert len(findings) == 2


# -- lock-discipline --------------------------------------------------------

def test_lock_mixed_guard_flagged(tmp_path):
    # the rabit-shaped bug: mutated under the lock in one method, bare in
    # another (init is exempt — construction has no concurrency yet)
    findings, _, _ = _lint_snippet(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._gen = 0

            def safe(self):
                with self._lock:
                    self._items.append(1)
                    self._gen = 1

            def racy(self):
                self._items.append(2)
                self._gen = 2
    """, rules=["lock-discipline"])
    assert len(findings) == 2
    assert all("without the lock" in f.message for f in findings)
    assert sorted(f.line for f in findings) == [15, 16]


def test_lock_clean_and_locked_convention(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def _bump_locked(self):
                # *_locked: caller holds the lock by convention
                self._n += 1
    """, rules=["lock-discipline"])
    assert findings == []


# -- durable-state ----------------------------------------------------------

def test_durable_delete_flagged_and_journaled(tmp_path):
    # the r17 extension: `del` on a durable table is a mutation too — a
    # replay that misses the removal resurrects the entry
    findings, _, _ = _lint_snippet(tmp_path, """\
        class Reg:
            _DURABLE_STATE = ("_active",)

            def forget(self, k):
                del self._active[k]

            def finish(self, k):
                self._jlog("gone", k=k)
                del self._active[k]

            def _restore_state(self, st):
                del self._active["replayed"]    # replay applies, exempt
    """, rules=["durable-state"])
    assert len(findings) == 1
    assert findings[0].line == 5
    assert "without journaling" in findings[0].message


# -- atomic-write -----------------------------------------------------------

def test_atomic_write_flagged_and_fixed(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import json, os

        def bad(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)

        def good(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)

        def read_only(path):
            with open(path) as f:
                return f.read()
    """, rules=["atomic-write"])
    assert len(findings) == 1
    assert findings[0].line == 4


# -- retrace-hazard ---------------------------------------------------------

def test_retrace_hazards(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import jax

        @jax.jit
        def bad(x, n):
            if n > 0:            # traced branch
                return x + int(n)    # and a concretizing cast
            return x

        @jax.jit
        def shape_ok(x):
            if x.shape[0] > 8:   # static at trace time
                return x[:8]
            return x

        def by_name(x, flag):
            if flag:
                return x * 2
            return x

        fast = jax.jit(by_name, static_argnames=("flag",))
    """, rules=["retrace-hazard"])
    assert len(findings) == 2
    assert sorted(f.line for f in findings) == [5, 6]


def test_retrace_partial_static(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def topk(x, k):
            if k > 16:           # static: fine
                k = 16
            return x[:k]
    """, rules=["retrace-hazard"])
    assert findings == []


# -- thread-hygiene ---------------------------------------------------------

def test_thread_hygiene(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import threading

        def fire_and_forget(fn):
            threading.Thread(target=fn).start()      # bad: no join path

        def daemonized(fn):
            threading.Thread(target=fn, daemon=True).start()

        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def stop(self):
                self._t.join()

            def _run(self):
                try:
                    pass
                except:          # bad: bare except
                    pass
    """, rules=["thread-hygiene"])
    assert len(findings) == 2
    kinds = sorted(f.message.split(" ")[0] for f in findings)
    assert any("bare" in f.message for f in findings)
    assert any("non-daemon" in f.message for f in findings)


# -- suppressions -----------------------------------------------------------

def test_suppression_same_line_and_next_line(tmp_path):
    findings, stats, _ = _lint_snippet(tmp_path, """\
        import os
        a = os.environ["DMLC_A"]  # dmlclint: disable=env-discipline -- why
        # dmlclint: disable-next-line=env-discipline -- bootstrap
        b = os.environ["DMLC_B"]
        c = os.environ["DMLC_C"]
    """, rules=["env-discipline"])
    assert len(findings) == 1 and findings[0].line == 5
    assert stats["suppressed"] == 2


def test_suppression_file_level_and_all(tmp_path):
    findings, stats, _ = _lint_snippet(tmp_path, """\
        # dmlclint: disable-file=env-discipline -- legacy module
        import os
        a = os.environ["DMLC_A"]
        b = os.environ["DMLC_B"]
    """, rules=["env-discipline"])
    assert findings == []
    assert stats["suppressed"] == 2
    findings, _, _ = _lint_snippet(tmp_path, """\
        import os
        a = os.environ["DMLC_A"]  # dmlclint: disable=all
    """, rules=["env-discipline"], rel="other.py")
    assert findings == []


def test_suppression_wrong_rule_does_not_hide(tmp_path):
    findings, _, _ = _lint_snippet(tmp_path, """\
        import os
        a = os.environ["DMLC_A"]  # dmlclint: disable=atomic-write
    """, rules=["env-discipline"])
    assert len(findings) == 1


# -- inventory + CLI --------------------------------------------------------

def test_inventory_round_trip(tmp_path):
    _, _, ctx = _lint_snippet(tmp_path, """\
        from dmlc_core_tpu.utils.parameter import get_env
        from dmlc_core_tpu.utils.metrics import metrics
        a = get_env("DMLC_KNOB", "x")
        metrics.counter("sub.metric")
    """)
    path = str(tmp_path / "inventory.json")
    inv.write(ctx, path)
    doc = inv.load(path)
    assert doc["schema"] == inv.SCHEMA
    assert doc["knobs"]["DMLC_KNOB"] == ["mod.py"]
    assert doc["metrics"]["sub.metric"] == ["mod.py"]


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text('import os\nx = os.environ["DMLC_X"]\n')
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint_main([str(good), "--repo-root", str(tmp_path)]) == 0
    capsys.readouterr()
    assert lint_main([str(bad), "--json",
                      "--repo-root", str(tmp_path)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "dmlc.lint.report/1"
    assert doc["findings"][0]["rule"] == "env-discipline"


def test_cli_lists_all_builtin_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("env-discipline", "metric-vocabulary", "span-vocabulary",
                 "endpoint-vocabulary", "lock-discipline", "atomic-write",
                 "retrace-hazard", "thread-hygiene", "durable-state"):
        assert rule in out


def test_repo_tree_is_clean():
    """The acceptance bar: the swept package lints clean."""
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dmlc_core_tpu")
    findings, stats, _ = lint_paths(
        [pkg], repo_root=os.path.dirname(pkg))
    assert findings == [], [repr(f) for f in findings[:10]]
