"""RowBlockIter tests: in-memory materialization, disk cache build + replay
(reference basic_row_iter.h / disk_row_iter.h behaviors)."""

import os

import numpy as np
import pytest

from dmlc_core_tpu.data import (BasicRowIter, DiskRowIter, create_parser,
                                create_row_block_iter)


@pytest.fixture()
def libsvm_file(tmp_path):
    rng = np.random.default_rng(3)
    lines = []
    for i in range(2000):
        n = int(rng.integers(1, 8))
        idx = sorted(rng.choice(500, size=n, replace=False).tolist())
        lines.append(f"{i % 2} " + " ".join(f"{j}:{(j % 7) + 0.5}" for j in idx))
    path = tmp_path / "a1a-like.libsvm"
    path.write_text("\n".join(lines) + "\n")
    return str(path), lines


def test_basic_row_iter(libsvm_file):
    path, lines = libsvm_file
    it = create_row_block_iter(path)
    assert isinstance(it, BasicRowIter)
    blocks = list(it)
    assert len(blocks) == 1 and blocks[0].size == 2000
    assert it.num_col == blocks[0].max_index + 1
    # epochs repeat
    it.before_first()
    again = list(it)
    assert again[0].size == 2000


def test_basic_row_iter_partitioned(libsvm_file):
    path, lines = libsvm_file
    sizes = []
    for k in range(3):
        it = create_row_block_iter(path, k, 3)
        sizes.append(sum(b.size for b in it))
    assert sum(sizes) == 2000


def test_disk_row_iter_build_and_replay(libsvm_file, tmp_path):
    path, lines = libsvm_file
    cache = str(tmp_path / "rows.cache")
    uri = f"{path}#{cache}"
    with create_row_block_iter(uri) as it:
        assert isinstance(it, DiskRowIter)
        rows1 = sum(b.size for b in it)
        it.before_first()
        rows2 = sum(b.size for b in it)
        ncol = it.num_col
    assert rows1 == rows2 == 2000
    assert os.path.exists(cache) and os.path.exists(cache + ".meta")
    # fresh instance: replays cache without re-parsing (source could vanish)
    os.rename(path, path + ".gone")
    try:
        with create_row_block_iter(uri) as it2:
            assert sum(b.size for b in it2) == 2000
            assert it2.num_col == ncol
    finally:
        os.rename(path + ".gone", path)


def test_disk_iter_small_pages(libsvm_file, tmp_path):
    path, _ = libsvm_file
    parser = create_parser(path)
    it = DiskRowIter(parser, str(tmp_path / "p.cache"), page_size=16 << 10)
    blocks = list(it)
    assert len(blocks) > 1  # multiple pages
    assert sum(b.size for b in blocks) == 2000
    it.close()


def test_disk_cache_iter_feeds_device_loader(tmp_path):
    """#cache RowBlockIter as a DeviceLoader source across two epochs —
    the reference's disk_row_iter → consumer composition on the device
    path, with the second epoch served purely from the cache."""
    import numpy as np
    from dmlc_core_tpu.pipeline import DeviceLoader

    rng = np.random.default_rng(0)
    path = tmp_path / "d.libsvm"
    with open(path, "w") as f:
        for r in range(300):
            idx = np.sort(rng.choice(500, size=4, replace=False))
            f.write(f"{r} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    cache = tmp_path / "rows.cache"
    it = create_row_block_iter(f"file://{path}#{cache}", 0, 1, "libsvm")
    loader = DeviceLoader(it, batch_rows=64, nnz_cap=1024)
    try:
        def labels_of():
            seen = []
            for b in loader:
                w = np.asarray(b["weights"]) > 0
                seen.extend(np.asarray(b["labels"])[w].astype(int).tolist())
            return sorted(seen)
        assert labels_of() == list(range(300))
        path.unlink()                   # second epoch must come from cache
        loader.before_first()
        assert labels_of() == list(range(300))
    finally:
        loader.close()
