"""FTRL-Proximal optimizer tests: math vs hand-rolled numpy reference,
sparsity behavior, end-to-end training, checkpoint round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dmlc_core_tpu.models.ftrl import FTRLState, ftrl


def _numpy_ftrl_step(g, z, n, w, alpha, beta, l1, l2):
    sigma = (np.sqrt(n + g * g) - np.sqrt(n)) / alpha
    z = z + g - sigma * w
    n = n + g * g
    denom = (beta + np.sqrt(n)) / alpha + l2
    w_new = np.where(np.abs(z) > l1,
                     -(z - np.sign(z) * l1) / denom, 0.0)
    return w_new, z, n


def test_matches_numpy_reference():
    rng = np.random.default_rng(0)
    alpha, beta, l1, l2 = 0.1, 1.0, 0.5, 0.25
    opt = ftrl(alpha, beta, l1, l2)
    w = rng.standard_normal(32).astype(np.float32)
    params = {"w": jnp.asarray(w)}
    state = opt.init(params)
    zn = np.zeros_like(w)
    nn = np.zeros_like(w)
    wn = w.copy()
    for step in range(5):
        g = rng.standard_normal(32).astype(np.float32)
        updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
        params = optax.apply_updates(params, updates)
        wn, zn, nn = _numpy_ftrl_step(g, zn, nn, wn, alpha, beta, l1, l2)
        np.testing.assert_allclose(np.asarray(params["w"]), wn,
                                   rtol=1e-5, atol=1e-6)


def test_l1_produces_exact_zeros():
    opt = ftrl(alpha=0.1, l1=10.0)       # aggressive threshold
    params = {"w": jnp.zeros(16)}
    state = opt.init(params)
    g = jnp.asarray(np.random.default_rng(1).standard_normal(16) * 0.01)
    updates, state = opt.update({"w": g}, state, params)
    params = optax.apply_updates(params, updates)
    # tiny gradients never cross |z| > l1: all weights exactly zero
    assert np.all(np.asarray(params["w"]) == 0.0)


def test_requires_params():
    opt = ftrl()
    state = opt.init({"w": jnp.zeros(4)})
    with pytest.raises(ValueError, match="requires params"):
        opt.update({"w": jnp.ones(4)}, state, None)


def test_trains_logreg_end_to_end(tmp_path):
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import SparseLogReg
    from dmlc_core_tpu.models.train import make_train_step
    from dmlc_core_tpu.pipeline.device_loader import DeviceLoader

    path = tmp_path / "f.libsvm"
    rng = np.random.default_rng(0)
    # learnable signal: label correlates with feature 1 vs 2
    with open(path, "w") as f:
        for _ in range(2000):
            y = int(rng.random() < 0.5)
            feat = 1 if y else 2
            f.write(f"{y} {feat}:1.0 {int(rng.integers(3, 20))}:0.3\n")

    model = SparseLogReg(num_features=32)
    opt = ftrl(alpha=0.5, l1=0.01, l2=0.01)
    step = make_train_step(model, opt)
    params = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    losses = []
    for _epoch in range(3):
        loader = DeviceLoader(create_parser(f"file://{path}", 0, 1, "libsvm"),
                              batch_rows=256, nnz_cap=1024)
        for batch in loader:
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        loader.close()
    assert losses[-1] < losses[0] * 0.8     # it learned
    w = np.asarray(params["w"])
    assert w[1] > 0 > w[2]                   # the signal features
    assert np.mean(w == 0.0) > 0.3           # L1 sparsity on the rest


def test_ftrl_state_checkpoints_with_template(tmp_path):
    import io
    from dmlc_core_tpu.utils.checkpoint import load_pytree, save_pytree
    opt = ftrl()
    params = {"w": jnp.arange(4, dtype=jnp.float32)}
    state = opt.init(params)
    updates, state = opt.update({"w": jnp.ones(4)}, state, params)
    buf = io.BytesIO()
    save_pytree(buf, state)
    buf.seek(0)
    restored = load_pytree(buf, template=opt.init(params))
    assert isinstance(restored, FTRLState)
    np.testing.assert_array_equal(np.asarray(restored.n["w"]),
                                  np.asarray(state.n["w"]))
    np.testing.assert_array_equal(np.asarray(restored.z["w"]),
                                  np.asarray(state.z["w"]))


def test_tuple_params_pytree():
    """Params pytrees containing tuples must update correctly (regression:
    an is_leaf=tuple extraction trick silently corrupted these)."""
    opt = ftrl(alpha=0.1, l1=0.0, l2=0.0)
    params = (jnp.ones(3), {"nested": (jnp.zeros(2), jnp.full(2, 2.0))})
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    updates, state = opt.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    # same structure back
    assert jax.tree_util.tree_structure(new) == \
        jax.tree_util.tree_structure(params)
    # every leaf moved opposite the (positive) gradient
    for leaf in jax.tree_util.tree_leaves(new):
        assert np.all(np.asarray(leaf) <= np.asarray(
            jax.tree_util.tree_leaves(params)[0]).max() + 1e-6)
    # and z accumulated on every leaf
    for z in jax.tree_util.tree_leaves(state.z):
        assert np.any(np.asarray(z) != 0)
