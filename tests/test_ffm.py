"""FieldAwareFM: field-bucket formulation vs brute-force pair loop, both
batch layouts, end-to-end from libfm text through DeviceLoader(fields=True).
Reference parity: the libfm field coordinate (`src/data/libfm_parser.h:36-93`,
`include/dmlc/data.h:168`) finally has an in-framework consumer."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dmlc_core_tpu.models import FieldAwareFM, make_train_step  # noqa: E402


def brute_ffm(w0, w, v, rows):
    """rows: list of [(idx, field, val), ...] per example."""
    out = []
    for row in rows:
        y = w0 + sum(w[i] * x for i, _, x in row)
        for a in range(len(row)):
            for b in range(a + 1, len(row)):
                ia, fa, xa = row[a]
                ib, fb, xb = row[b]
                y += float(np.dot(v[ia, fb], v[ib, fa])) * xa * xb
        out.append(y)
    return np.array(out, np.float32)


def make_case(rng, B, kmax, F, nf):
    rows = []
    for _ in range(B):
        k = int(rng.integers(1, kmax + 1))
        idx = rng.choice(F, size=k, replace=False)
        rows.append([(int(i), int(rng.integers(0, nf)),
                      float(rng.random()) + 0.1) for i in idx])
    return rows


def to_rowmajor(rows, B, K):
    ids = np.zeros((B, K), np.int32)
    vals = np.zeros((B, K), np.float32)
    fields = np.zeros((B, K), np.int32)
    for r, row in enumerate(rows):
        for c, (i, f, x) in enumerate(row):
            ids[r, c], fields[r, c], vals[r, c] = i, f, x
    return {"ids": jnp.asarray(ids), "vals": jnp.asarray(vals),
            "fields": jnp.asarray(fields),
            "labels": jnp.zeros((B,), jnp.float32),
            "weights": jnp.ones((B,), jnp.float32)}


def to_flat(rows, B, cap):
    ids, vals, fields, segs = [], [], [], []
    for r, row in enumerate(rows):
        for i, f, x in row:
            ids.append(i), fields.append(f), vals.append(x), segs.append(r)
    pad = cap - len(ids)
    ids += [0] * pad
    vals += [0.0] * pad
    fields += [0] * pad
    segs += [B] * pad          # scratch row
    return {"ids": jnp.asarray(ids, jnp.int32),
            "vals": jnp.asarray(vals, jnp.float32),
            "fields": jnp.asarray(fields, jnp.int32),
            "segments": jnp.asarray(segs, jnp.int32),
            "labels": jnp.zeros((B,), jnp.float32),
            "weights": jnp.ones((B,), jnp.float32)}


def test_ffm_matches_bruteforce_both_layouts():
    rng = np.random.default_rng(7)
    B, K, F, nf, d = 6, 5, 37, 4, 3
    rows = make_case(rng, B, K, F, nf)
    model = FieldAwareFM(num_features=F, num_fields=nf, dim=d)
    params = model.init(jax.random.PRNGKey(0))
    params["w"] = jnp.asarray(rng.standard_normal(F), jnp.float32)
    params["w0"] = jnp.asarray(0.3, jnp.float32)

    expect = brute_ffm(float(params["w0"]), np.asarray(params["w"]),
                       np.asarray(params["v"]), rows)
    got_rm = model.forward(params, to_rowmajor(rows, B, K))
    got_fl = model.forward(params, to_flat(rows, B, cap=64))
    np.testing.assert_allclose(got_rm, expect, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(got_fl, expect, rtol=2e-4, atol=2e-4)


def test_ffm_field_clip_and_missing_fields():
    model = FieldAwareFM(num_features=10, num_fields=2, dim=2)
    params = model.init(jax.random.PRNGKey(0))
    batch = to_rowmajor([[(1, 5, 1.0), (2, 0, 1.0)]], 1, 2)  # field 5 ≥ nf
    out = model.forward(params, batch)          # clipped, not out-of-bounds
    assert np.isfinite(float(out[0]))
    with pytest.raises(KeyError):
        bad = {k: v for k, v in batch.items() if k != "fields"}
        model.forward(params, bad)


def test_ffm_trains_on_separable_fields():
    """Loss decreases and grads flow through v on a field-XOR-ish task a
    plain FM cannot represent with dim this small."""
    optax = pytest.importorskip("optax")
    rng = np.random.default_rng(0)
    B, K, F, nf, d = 64, 2, 20, 3, 4
    rows, labels = [], []
    for _ in range(B):
        i, j = rng.choice(F, size=2, replace=False)
        fi, fj = int(rng.integers(0, nf)), int(rng.integers(0, nf))
        rows.append([(int(i), fi, 1.0), (int(j), fj, 1.0)])
        labels.append(1.0 if (fi + fj) % 2 == 0 else 0.0)
    batch = to_rowmajor(rows, B, K)
    batch["labels"] = jnp.asarray(labels, jnp.float32)

    model = FieldAwareFM(num_features=F, num_fields=nf, dim=d,
                         init_scale=0.1)
    params = model.init(jax.random.PRNGKey(1))
    opt = optax.adam(0.05)
    state = opt.init(params)
    step = make_train_step(model, opt)
    first = None
    for _ in range(60):
        params, state, loss = step(params, state, batch)
        first = float(loss) if first is None else first
    assert float(loss) < first * 0.7, (first, float(loss))


def test_ffm_sharded_step_matches_single_device(tmp_path):
    """dp×mp mesh: FFM train losses equal the single-device run and the
    3-D factor table really shards its trailing dim over 'mp'."""
    optax = pytest.importorskip("optax")
    from jax.sharding import Mesh, PartitionSpec as P
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import (batch_sharding, param_shardings,
                                      shard_params)
    from dmlc_core_tpu.pipeline import DeviceLoader

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    mesh = Mesh(np.array(devices).reshape(4, 2), ("dp", "mp"))

    rng = np.random.default_rng(5)
    path = str(tmp_path / "s.libfm")
    with open(path, "w") as fh:
        for r in range(256):
            k = int(rng.integers(1, 5))
            idx = rng.choice(64, size=k, replace=False)
            ent = " ".join(f"{int(rng.integers(0, 3))}:{i}:"
                           f"{rng.random():.4f}" for i in idx)
            fh.write(f"{r % 2} {ent}\n")

    model = FieldAwareFM(num_features=64, num_fields=3, dim=4)
    opt = optax.sgd(0.1)

    def run(mesh_arg):
        loader = DeviceLoader(create_parser(path, 0, 1, "libfm"),
                              batch_rows=64, nnz_cap=512, fields=True,
                              sharding=batch_sharding(mesh_arg))
        params = model.init(jax.random.PRNGKey(0))
        params = shard_params(params,
                              param_shardings(model, params, mesh_arg))
        state = opt.init(params)
        from dmlc_core_tpu.models import make_train_step
        step = make_train_step(model, opt, mesh_arg, donate=False)
        losses = []
        for batch in loader:
            params, state, loss = step(params, state, batch)
            losses.append(float(loss))
        loader.close()
        return losses, params

    losses_single, _ = run(None)
    losses_mesh, params_mesh = run(mesh)
    np.testing.assert_allclose(losses_single, losses_mesh,
                               rtol=2e-4, atol=2e-5)
    assert params_mesh["v"].sharding.spec == P(None, None, "mp")


def test_ffm_end_to_end_from_libfm_text(tmp_path):
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader

    rng = np.random.default_rng(3)
    path = tmp_path / "t.libfm"
    lines, truth = [], []
    for r in range(23):
        k = int(rng.integers(1, 6))
        idx = rng.choice(100, size=k, replace=False)
        ent = [(int(f), int(i), round(float(x), 4))
               for f, i, x in zip(rng.integers(0, 5, k), idx, rng.random(k))]
        lines.append(f"{r % 2} " + " ".join(
            f"{f}:{i}:{x}" for f, i, x in ent))
        truth.append(sorted((i, f, np.float32(x)) for f, i, x in ent))
    path.write_text("\n".join(lines) + "\n")

    for layout in ("flat", "rowmajor"):
        loader = DeviceLoader(
            create_parser(f"file://{path}", 0, 1, "libfm"),
            batch_rows=8, nnz_cap=64, layout=layout, fields=True)
        got = []
        for batch in loader:
            assert "fields" in batch
            ids = np.asarray(batch["ids"])
            vals = np.asarray(batch["vals"])
            fields = np.asarray(batch["fields"])
            if layout == "flat":
                segs = np.asarray(batch["segments"])
                for r in range(int(np.asarray(batch["labels"]).shape[0])):
                    m = segs == r
                    if m.any():
                        got.append(sorted(
                            zip(ids[m].tolist(), fields[m].tolist(),
                                vals[m].tolist())))
            else:
                for r in range(ids.shape[0]):
                    m = vals[r] != 0
                    if m.any():
                        got.append(sorted(
                            zip(ids[r][m].tolist(), fields[r][m].tolist(),
                                vals[r][m].tolist())))
        loader.close()
        got = got[:len(truth)]
        assert len(got) == len(truth)
        for g, t in zip(got, truth):
            assert [(i, f) for i, f, _ in g] == [(i, f) for i, f, _ in t]
            np.testing.assert_allclose([x for _, _, x in g],
                                       [x for _, _, x in t], rtol=1e-5)
