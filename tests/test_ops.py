"""Device op tests: CSR primitives vs dense references; Pallas kernel
(interpret mode) vs XLA reference."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dmlc_core_tpu.ops import csr_dense_matvec, csr_embed_sum, fm_pairwise  # noqa: E402


def make_batch(rng, B=6, F=40, max_nnz=5, pad=7):
    rows = []
    ids, vals, segs = [], [], []
    dense = np.zeros((B, F), np.float32)
    for b in range(B):
        n = int(rng.integers(1, max_nnz))
        idx = rng.choice(F, n, replace=False)
        v = rng.random(n).astype(np.float32)
        dense[b, idx] = v
        ids.extend(idx.tolist())
        vals.extend(v.tolist())
        segs.extend([b] * n)
    target = len(ids) + pad
    while len(ids) < target:
        ids.append(0)
        vals.append(0.0)
        segs.append(B)
    return (jnp.array(ids, jnp.int32), jnp.array(vals, jnp.float32),
            jnp.array(segs, jnp.int32), dense)


def test_csr_dense_matvec_matches_dense():
    rng = np.random.default_rng(0)
    ids, vals, segs, dense = make_batch(rng)
    w = jnp.array(rng.random(40), jnp.float32)
    out = csr_dense_matvec(ids, vals, segs, w, dense.shape[0])
    np.testing.assert_allclose(out, dense @ np.asarray(w), rtol=1e-5)


def test_csr_embed_sum_matches_dense():
    rng = np.random.default_rng(1)
    ids, vals, segs, dense = make_batch(rng)
    table = jnp.array(rng.random((40, 8)), jnp.float32)
    out = csr_embed_sum(ids, vals, segs, table, dense.shape[0])
    np.testing.assert_allclose(out, dense @ np.asarray(table), rtol=1e-5)


def test_fm_pairwise_matches_bruteforce():
    rng = np.random.default_rng(2)
    ids, vals, segs, dense = make_batch(rng)
    table = np.asarray(rng.random((40, 8)), np.float32)
    out = fm_pairwise(ids, vals, segs, jnp.array(table), dense.shape[0])
    # brute force: sum_{i<j} <v_i, v_j> x_i x_j
    expect = []
    for b in range(dense.shape[0]):
        s = 0.0
        nz = np.nonzero(dense[b])[0]
        for ii in range(len(nz)):
            for jj in range(ii + 1, len(nz)):
                i, j = nz[ii], nz[jj]
                s += float(table[i] @ table[j]) * dense[b, i] * dense[b, j]
        expect.append(s)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_pallas_embed_bag_interpret_matches_reference():
    from dmlc_core_tpu.ops.pallas_embed import (embed_bag_pallas,
                                                embed_bag_reference)
    rng = np.random.default_rng(3)
    B, K, F, D = 4, 8, 64, 128
    ids = jnp.array(rng.integers(0, F, (B, K)), jnp.int32)
    vals = jnp.array(rng.random((B, K)), jnp.float32)
    table = jnp.array(rng.random((F, D)), jnp.float32)
    ref = embed_bag_reference(ids, vals, table)
    out = embed_bag_pallas(ids, vals, table, interpret=True)
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_engine_dispatch_deterministic(monkeypatch):
    """Default dispatch is a pure function of shape (ADVICE r3: every host
    on a shared mesh must pick the same engine) and, post-TPU_MICRO_r04,
    always XLA: on-hardware timing showed the DMA kernel loses at every
    shape that has ever run (latency-bound 512B fetches), so pallas is
    opt-in via DMLC_EMBED_ENGINE=pallas or DMLC_EMBED_AUTOTUNE=1."""
    from dmlc_core_tpu.ops import pallas_embed as pe

    monkeypatch.delenv("DMLC_EMBED_AUTOTUNE", raising=False)
    for shape in ((1024, 32, 64), (1024, 32, 8), (8, 32, 512)):
        assert pe._pallas_profitable(*shape, fused=False) is False
        # same inputs, same verdict — repeat-call determinism
        assert pe._pallas_profitable(*shape, fused=False) is False


def test_pallas_embed_chunked_matches_reference(monkeypatch):
    """Batches whose flat ids/vals exceed the SMEM scalar-prefetch budget
    split into independent row-chunk pallas_calls (TPU_MICRO_r04: 1MB+
    scalar operands are a hard Mosaic OOM on v5e).  Force a tiny cap so
    the chunk path runs at test scale; a non-multiple tail chunk included."""
    from dmlc_core_tpu.ops import pallas_embed as pe

    monkeypatch.setenv("DMLC_PALLAS_SMEM_SCALARS", "64")   # → 8-row chunks
    rng = np.random.default_rng(5)
    B, K, F, D = 44, 8, 64, 128          # 5 full chunks + 4-row tail
    assert pe._chunk_rows(K) == 8
    ids = jnp.array(rng.integers(0, F, (B, K)), jnp.int32)
    vals = jnp.array(rng.random((B, K)), jnp.float32)
    table = jnp.array(rng.random((F, D)), jnp.float32)
    ref = pe.embed_bag_reference(ids, vals, table)
    out = pe.embed_bag_pallas(ids, vals, table, interpret=True)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    s1, s2 = pe.fm_terms_pallas(ids, vals, table, interpret=True)
    g = table[ids]
    np.testing.assert_allclose(
        s1, jnp.einsum("bk,bkd->bd", vals, g), rtol=1e-5)
    np.testing.assert_allclose(
        s2, jnp.einsum("bk,bkd->bd", vals * vals, g * g), rtol=1e-5)


def test_engine_env_pin(monkeypatch):
    """DMLC_EMBED_ENGINE pins the engine regardless of auto heuristics —
    the multi-host escape hatch."""
    from dmlc_core_tpu.ops import pallas_embed as pe

    monkeypatch.setenv("DMLC_EMBED_ENGINE", "xla")
    assert pe._resolve_engine("auto", 512) == "xla"
    assert pe._resolve_engine("pallas", 512) == "xla"   # pin beats explicit
    monkeypatch.setenv("DMLC_EMBED_ENGINE", "bogus")
    import pytest as _pytest
    with _pytest.raises(ValueError):
        pe._resolve_engine("auto", 512)


def test_engine_autotune_logic(monkeypatch):
    """Opt-in timed autotune (DMLC_EMBED_AUTOTUNE=1): picks by measured
    time, caches per shape, and a kernel failure degrades to XLA instead of
    raising — exercised on CPU since the real gate only opens on TPU."""
    from dmlc_core_tpu.ops import pallas_embed as pe

    pe._engine_time_cache.clear()
    # kernel raises (CPU without interpret) → False, no exception
    assert pe._pallas_faster_timed(64, 4, 8, fused=False) is False
    assert pe._engine_time_cache[(4, 8, False)] is False

    # substitute engines with controllable speeds: pallas wins.  The slow
    # engine must be slow when COMPILED (the autotuner jits the xla side),
    # so it carries real FLOPs, not a python sleep that traces away.
    def fast(ids, vals, table):
        return jnp.zeros((ids.shape[0], table.shape[1]), jnp.float32)

    def slow(ids, vals, table, square=False):
        x = jnp.ones((400, 400), jnp.float32)
        for _ in range(30):
            x = (x @ x) * 1e-3
        return jnp.zeros((ids.shape[0], table.shape[1]),
                         jnp.float32) + x[0, 0]

    monkeypatch.setattr(pe, "embed_bag_pallas", fast)
    monkeypatch.setattr(pe, "embed_bag_reference", slow)
    pe._engine_time_cache.clear()
    assert pe._pallas_faster_timed(64, 5, 8, fused=False) is True
    # cached: flipping the implementations does not change the verdict
    monkeypatch.setattr(pe, "embed_bag_pallas", slow)
    assert pe._pallas_faster_timed(64, 5, 8, fused=False) is True
    # DMLC_EMBED_AUTOTUNE=1 routes _pallas_profitable through the timer
    monkeypatch.setenv("DMLC_EMBED_AUTOTUNE", "1")
    assert pe._pallas_profitable(64, 5, 8, fused=False) is True
    pe._engine_time_cache.clear()
