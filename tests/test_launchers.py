"""Launcher backend tests: command generation for YARN/Mesos (dry-run) and
env-contract correctness of the generated wrapper scripts."""

import os
import subprocess

from dmlc_core_tpu.parallel.launcher.mesos import build_mesos_commands
from dmlc_core_tpu.parallel.launcher.opts import get_opts
from dmlc_core_tpu.parallel.launcher.yarn import build_yarn_command

ENVS = {"DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091"}
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _args(cluster, extra=()):
    return get_opts([
        "--cluster", cluster, "-n", "3", "-s", "1", "--jobname", "jobx",
        *extra, "--", "python", "train.py", "--lr", "0.1"])


def test_yarn_command_shape():
    args = _args("yarn", ["--yarn-queue", "prod", "--worker-memory-mb",
                          "2048", "--worker-cores", "4"])
    cmd = build_yarn_command(args, ENVS)
    joined = " ".join(cmd)
    assert "distributedshell.Client" in joined
    assert "-num_containers 4" in joined          # 3 workers + 1 server
    assert "-container_memory 2048" in joined
    assert "-container_vcores 4" in joined
    assert "-queue prod" in joined
    assert "-appname jobx" in joined
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read()
    assert "export DMLC_TRACKER_URI=10.0.0.1" in body
    assert "export DMLC_NUM_WORKER=3" in body
    assert "export DMLC_NUM_SERVER=1" in body
    assert "DMLC_MAX_ATTEMPT" in body
    assert 'DMLC_NUM_ATTEMPT="$attempt" python train.py --lr 0.1' in body
    os.unlink(script)


def test_yarn_wrapper_rank_and_role():
    """Execute the wrapper with a faked CONTAINER_ID: container 2 (first
    task container after the AM) must get DMLC_TASK_ID=0 → server role."""
    args = _args("yarn")
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read().replace(
        "python train.py --lr 0.1",
        'echo "$DMLC_TASK_ID $DMLC_ROLE"; true')
    open(script, "w").write(body)
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000002"},
        capture_output=True, text=True)
    assert out.stdout.split() == ["0", "server"]
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000005"},
        capture_output=True, text=True)
    assert out.stdout.split() == ["3", "worker"]
    os.unlink(script)


def test_mesos_commands_one_per_task():
    """Everything must be inlined in --command: mesos-execute does not ship
    local files to agents, so no path on the submit host may appear."""
    args = _args("mesos", ["--mesos-master", "master:5050"])
    cmds = build_mesos_commands(args, ENVS)
    assert len(cmds) == 4
    for tid, c in enumerate(cmds):
        assert c[0] == "mesos-execute"
        assert f"--master=master:5050" in c
        assert f"--name=jobx-task-{tid}" in c
        inline = next(a for a in c if a.startswith("--command=")).split("=", 1)[1]
        assert "/tmp/" not in inline          # self-contained, nothing to ship
        assert f"export DMLC_TASK_ID={tid}" in inline
        role = "server" if tid < 1 else "worker"
        assert f"export DMLC_ROLE={role}" in inline
        assert "export DMLC_TRACKER_URI=10.0.0.1" in inline
        assert "python train.py --lr 0.1" in inline
        # the inline command must execute (with retry machinery): stub the
        # worker with a child shell (env-prefix vars are only visible to
        # the child process, not to same-line expansion)
        out = subprocess.run(
            ["bash", "-c", inline.replace(
                "python train.py --lr 0.1",
                "sh -c 'echo \"$DMLC_TASK_ID $DMLC_ROLE $DMLC_NUM_ATTEMPT\"'")],
            capture_output=True, text=True)
        assert out.stdout.split() == [str(tid), role, "0"]


def test_yarn_out_of_range_container_fails_fast():
    """An out-of-range container id must fail with a clear message, not
    join the cohort with a bogus rank."""
    args = _args("yarn")
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000099"},
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "outside cohort" in out.stderr
    os.unlink(script)


def test_wrapper_retry_loop_drives_recover_protocol():
    """The wrapper must rerun a failing worker with DMLC_NUM_ATTEMPT
    incremented (what flips the rabit client into `recover` mode) up to
    DMLC_MAX_ATTEMPT, keeping the task id stable."""
    args = get_opts(["--cluster", "yarn", "-n", "2", "--max-attempts", "3",
                     "--", "bash", "-c",
                     'echo "att=$DMLC_NUM_ATTEMPT id=$DMLC_TASK_ID"; '
                     '[ "$DMLC_NUM_ATTEMPT" -ge 2 ]'])
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000002"},
        capture_output=True, text=True)
    assert out.returncode == 0
    assert out.stdout.splitlines() == [
        "att=0 id=0", "att=1 id=0", "att=2 id=0"]
    os.unlink(script)


def test_wrapper_retry_exhaustion_propagates_rc():
    args = get_opts(["--cluster", "yarn", "-n", "1", "--max-attempts", "2",
                     "--", "bash", "-c", "exit 7"])
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000002"},
        capture_output=True, text=True)
    assert out.returncode == 7
    os.unlink(script)


def test_submit_dry_run_all_clusters():
    """--dry-run must not launch anything on ANY backend: tracker boots,
    submission is previewed, rc 0, no scheduler binaries needed."""
    from dmlc_core_tpu.parallel.launcher.submit import submit
    for cluster in ["yarn", "mesos", "slurm", "sge", "mpi", "local"]:
        rc = submit(["--cluster", cluster, "-n", "2", "--dry-run",
                     "--", "definitely-not-a-real-binary"])
        assert rc == 0, cluster


def test_bootstrap_fixup_env():
    from dmlc_core_tpu.parallel.launcher.bootstrap import fixup_env
    # slurm rank → task id → role + jax contract: jax process ids are the
    # WORKER-relative index (global ids 0..ns-1 are servers, which do not
    # join the jax process group)
    e = fixup_env({"SLURM_PROCID": "3", "DMLC_NUM_SERVER": "2",
                   "DMLC_NUM_WORKER": "6"})
    assert e["DMLC_TASK_ID"] == "3"
    assert e["DMLC_ROLE"] == "worker"
    assert e["JAX_PROCESS_ID"] == "1"       # 3 - 2 servers
    assert e["JAX_NUM_PROCESSES"] == "6"
    # first worker (task id == ns) must be jax process 0 (the coordinator)
    e = fixup_env({"SLURM_PROCID": "2", "DMLC_NUM_SERVER": "2",
                   "DMLC_NUM_WORKER": "6"})
    assert e["JAX_PROCESS_ID"] == "0"
    # sge is 1-based; servers get no jax process id
    e = fixup_env({"SGE_TASK_ID": "1", "DMLC_NUM_SERVER": "2"})
    assert e["DMLC_TASK_ID"] == "0"
    assert e["DMLC_ROLE"] == "server"
    assert "JAX_PROCESS_ID" not in e
    # SGE non-array jobs export the literal 'undefined': must not crash
    e = fixup_env({"SGE_TASK_ID": "undefined"})
    assert "DMLC_TASK_ID" not in e
    # explicit values never overwritten
    e = fixup_env({"DMLC_TASK_ID": "7", "SLURM_PROCID": "1",
                   "DMLC_ROLE": "worker"})
    assert e["DMLC_TASK_ID"] == "7"


def test_bootstrap_unpack_and_exec(tmp_path):
    import subprocess
    import sys
    import zipfile
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with zipfile.ZipFile(tmp_path / "bundle.zip", "w") as z:
        z.writestr("inner.txt", "shipped")
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.bootstrap",
         "--", sys.executable, "-c",
         "import os; print(os.environ['DMLC_ROLE'], "
         "open('bundle/inner.txt').read())"],
        cwd=tmp_path, capture_output=True, text=True,
        env={**os.environ, "SLURM_PROCID": "0",
             "DMLC_NUM_SERVER": "0", "DMLC_NUM_WORKER": "1",
             "PYTHONPATH": repo})
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "worker shipped"


def test_ps_mode_exports_scheduler_env(tmp_path):
    """-s N must hand every process the PS rendezvous env (reference
    starts PSTracker whenever nserver > 0)."""
    import sys
    from dmlc_core_tpu.parallel.launcher.submit import submit
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "assert os.environ['DMLC_PS_ROOT_URI']\n"
        "assert int(os.environ['DMLC_PS_ROOT_PORT']) > 0\n")
    rc = submit(["--cluster", "local", "-n", "2", "-s", "1",
                 "--host-ip", "127.0.0.1",
                 "--env", f"PYTHONPATH={os.path.dirname(os.path.dirname(os.path.abspath(__file__)))}",
                 "--", sys.executable, str(probe)])
    assert rc == 0


def test_ps_mode_end_to_end_rendezvous(tmp_path):
    """-s N launches the user command as the SCHEDULER (DMLC_ROLE=scheduler,
    ADVICE r1): server+worker connect to DMLC_PS_ROOT_URI/PORT and the
    scheduler actually listens there (reference local.py:72 passes the job
    command as pscmd; tracker.py:410-425 spawns it)."""
    import sys
    from dmlc_core_tpu.parallel.launcher.submit import submit
    prog = tmp_path / "ps_prog.py"
    marker = tmp_path / "sched_done.txt"
    prog.write_text(
        "import os, socket, time, sys\n"
        "role = os.environ['DMLC_ROLE']\n"
        "uri = os.environ['DMLC_PS_ROOT_URI']\n"
        "port = int(os.environ['DMLC_PS_ROOT_PORT'])\n"
        "if role == 'scheduler':\n"
        "    s = socket.socket()\n"
        "    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
        "    s.bind((uri, port)); s.listen(8)\n"
        "    n = int(os.environ['DMLC_NUM_WORKER']) + int(os.environ['DMLC_NUM_SERVER'])\n"
        "    for _ in range(n):\n"
        "        c, _ = s.accept(); c.sendall(b'ok'); c.close()\n"
        f"    open({str(marker)!r}, 'w').write('done')\n"
        "else:\n"
        "    deadline = time.time() + 30\n"
        "    while True:\n"
        "        try:\n"
        "            c = socket.create_connection((uri, port), timeout=5)\n"
        "            break\n"
        "        except OSError:\n"
        "            if time.time() > deadline: raise\n"
        "            time.sleep(0.2)\n"
        "    assert c.recv(2) == b'ok'\n")
    rc = submit(["--cluster", "local", "-n", "1", "-s", "1",
                 "--host-ip", "127.0.0.1",
                 "--env", f"PYTHONPATH={os.path.dirname(os.path.dirname(os.path.abspath(__file__)))}",
                 "--", sys.executable, str(prog)])
    assert rc == 0
    # scheduler saw both role processes connect before workers exited
    deadline = __import__('time').time() + 10
    while not marker.exists() and __import__('time').time() < deadline:
        __import__('time').sleep(0.1)
    assert marker.exists()


def test_jax_distributed_multiprocess_train(tmp_path):
    """VERDICT r1 #6: drive the REAL jax.distributed coordination path —
    2 processes through `--cluster tpu` (initialize_jax_from_env), each
    parsing its own partition (part_index = process_index, the reference's
    ResetPartition contract), then a global-mesh reduction over all
    simulated devices."""
    import subprocess
    import sys
    data = tmp_path / "d.libsvm"
    with open(data, "w") as f:
        for i in range(400):
            f.write(f"{i % 2} {1 + i % 7}:1.0 {10 + i % 11}:0.5\n")
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax._src import xla_bridge\n"
        "xla_bridge._backend_factories.pop('axon', None)\n"
        "from dmlc_core_tpu.parallel.launcher.tpu import initialize_jax_from_env\n"
        "initialize_jax_from_env()\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "assert len(jax.devices()) == 4, jax.devices()\n"
        "import numpy as np, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental import multihost_utils\n"
        "from dmlc_core_tpu.data import create_parser\n"
        f"parser = create_parser({str(data)!r}, jax.process_index(), 2,\n"
        "                       'libsvm', threaded=False)\n"
        "rows = sum(c.get_block().size for c in parser)\n"
        "parser.close()\n"
        "per_proc = multihost_utils.process_allgather(np.array([rows], np.float32))\n"
        "assert float(per_proc.sum()) == 400.0, per_proc\n"
        "mesh = Mesh(np.array(jax.devices()), ('dp',))\n"
        "local = np.full((2, 4), float(jax.process_index() + 1), np.float32)\n"
        "garr = multihost_utils.host_local_array_to_global_array(\n"
        "    local, mesh, P('dp'))\n"
        "total = jax.jit(lambda x: jnp.sum(x))(garr)\n"
        "assert float(total) == 2 * 4 * (1 + 2), total\n"
        "print('JAXDIST-OK', jax.process_index(), rows, flush=True)\n")
    env = {**os.environ, "PYTHONPATH": os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))}
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.parallel.launcher.submit",
         "--cluster", "tpu", "-n", "2", "--host-ip", "127.0.0.1",
         "--env", f"PYTHONPATH={env['PYTHONPATH']}",
         "--", sys.executable, str(worker)],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-3000:])
    assert out.stdout.count("JAXDIST-OK") == 2


def test_max_attempts_exhaustion_aborts_job(tmp_path):
    """VERDICT r1 #9: a task that keeps failing exhausts --max-attempts and
    the JOB aborts with its return code (the reference AM's maxNumAttempt →
    abortJob flow, ApplicationMaster.java:73-74,508)."""
    import sys
    from dmlc_core_tpu.parallel.launcher.submit import submit
    prog = tmp_path / "always_fail.py"
    counter = tmp_path / "attempts.txt"
    prog.write_text(
        "import os, sys\n"
        f"with open({str(counter)!r}, 'a') as f:\n"
        "    f.write(os.environ.get('DMLC_NUM_ATTEMPT', '?') + '\\n')\n"
        "sys.exit(9)\n")
    rc = submit(["--cluster", "local", "-n", "1", "--host-ip", "127.0.0.1",
                 "--max-attempts", "3",
                 "--env", f"PYTHONPATH={os.path.dirname(os.path.dirname(os.path.abspath(__file__)))}",
                 "--", sys.executable, str(prog)])
    assert rc == 9
    attempts = counter.read_text().split()
    assert attempts == ["0", "1", "2"]          # exactly max-attempts tries


# ---------------------------------------------------------------------------
# opts parity additions (reference opts.py:85-124) + file shipping
# ---------------------------------------------------------------------------

def test_opts_memory_forms_and_server_resources():
    args = _args("local", ["--worker-memory", "2g", "--server-memory",
                           "512m", "--server-cores", "3"])
    assert args.worker_memory_mb == 2048
    assert args.server_memory_mb == 512
    assert args.server_cores == 3
    from dmlc_core_tpu.parallel.launcher.wrapper import job_env
    env = job_env(args, ENVS, "slurm")
    assert env["DMLC_SERVER_CORES"] == "3"
    assert env["DMLC_SERVER_MEMORY_MB"] == "512"
    assert env["DMLC_WORKER_MEMORY_MB"] == "2048"


def test_opts_generic_queue_and_slurm_nodes():
    """Reference opts parity: --queue maps onto each backend's queue
    unless given explicitly; --slurm-worker/server-nodes pin srun -N."""
    args = _args("slurm", ["--queue", "prod", "--slurm-worker-nodes", "3",
                           "--slurm-server-nodes", "1", "--yarn-app-dir",
                           "/stage/app"])
    assert args.sge_queue == "prod"
    assert args.yarn_queue == "prod"
    assert args.slurm_partition == "prod"
    assert args.extra_env["DMLC_YARN_APP_DIR"] == "/stage/app"
    args2 = _args("sge", ["--queue", "prod", "--sge-queue", "special"])
    assert args2.sge_queue == "special"  # explicit wins

    import dmlc_core_tpu.parallel.launcher.batch as batch
    seen = {}
    orig = batch._launch
    batch._launch = lambda a, cmd, label, script: seen.update(cmd=cmd) or 0
    try:
        batch.submit_slurm(args, dict(ENVS))
    finally:
        batch._launch = orig
    cmd = seen["cmd"]
    assert cmd[cmd.index("-N") + 1] == "4"
    assert cmd[cmd.index("-p") + 1] == "prod"


def test_opts_sge_log_dir_forwarded(tmp_path):
    import dmlc_core_tpu.parallel.launcher.batch as batch
    args = _args("sge", ["--sge-log-dir", str(tmp_path), "--dry-run"])
    seen = {}
    orig = batch._launch

    def grab(args_, cmd, label, script):
        seen["cmd"] = cmd
        return orig(args_, cmd, label, script)

    batch._launch, _ = grab, None
    try:
        assert batch.submit_sge(args, ENVS) == 0
    finally:
        batch._launch = orig
    joined = " ".join(seen["cmd"])
    assert f"-o {tmp_path}" in joined and f"-e {tmp_path}" in joined


def test_file_cache_resolve_rewrites_only_cwd_files(tmp_path, monkeypatch):
    import sys
    monkeypatch.chdir(tmp_path)
    (tmp_path / "train.py").write_text("print('hi')")
    from dmlc_core_tpu.parallel.launcher.filecache import resolve
    files, archives, cmds = resolve(
        [sys.executable, "train.py", "--lr", "0.1"], [], [])
    # the interpreter lives outside cwd: runs in place, NOT shipped
    assert cmds == [sys.executable, "./train.py", "--lr", "0.1"]
    assert files == [str(tmp_path / "train.py")]


def test_shipped_file_readable_in_worker_cwd_local(tmp_path, monkeypatch):
    """VERDICT r2 #5: a --files shipped data file must be readable from the
    worker's cwd on the local backend."""
    import sys
    from dmlc_core_tpu.parallel.launcher.submit import submit
    monkeypatch.chdir(tmp_path)
    (tmp_path / "data.txt").write_text("hello-cache")
    rc = submit([
        "--cluster", "local", "-n", "2", "--files", "data.txt", "--",
        sys.executable, "-c",
        "import sys; sys.exit(0 if open('data.txt').read()=='hello-cache'"
        " else 3)"])
    assert rc == 0


def test_shipped_file_readable_in_worker_cwd_ssh(tmp_path, monkeypatch):
    """Same guarantee on the ssh backend, with ssh/rsync faked to run
    locally (the transfer + remote-cd protocol is what's under test)."""
    import stat
    import sys
    from dmlc_core_tpu.parallel.launcher.submit import submit
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    # fake ssh: exec the remote command locally; fake rsync: local copy
    # with the host: prefix stripped
    (bin_dir / "ssh").write_text(
        "#!/bin/bash\n"
        'while [[ "$1" == -* ]]; do [[ "$1" == -o || "$1" == -p ]] && '
        "shift; shift; done\n"
        'shift\nexec bash -c "$*"\n')
    (bin_dir / "rsync").write_text(
        "#!/bin/bash\nargs=()\n"
        'for a in "$@"; do case "$a" in -*) ;; *) args+=("$a");; esac; '
        "done\n"
        'unset "args[0]" 2>/dev/null\n'   # drop the -e value ("ssh -p 22")
        'args=("${args[@]}")\n'
        'dest="${args[-1]#*:}"\nunset "args[-1]"\n'
        'exec cp -f "${args[@]}" "$dest"\n')
    for f in bin_dir.iterdir():
        f.chmod(f.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.chdir(tmp_path)
    (tmp_path / "data.txt").write_text("hello-ssh")
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("127.0.0.1\n")
    rc = submit([
        "--cluster", "ssh", "-n", "1", "--host-file", str(hosts),
        "--jobname", f"t{os.getpid()}", "--files", "data.txt", "--",
        sys.executable, "-c",
        "import sys; sys.exit(0 if open('data.txt').read()=='hello-ssh'"
        " else 3)"])
    assert rc == 0


def test_yarn_ships_cache_via_shell_files(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "data.txt").write_text("x")
    (tmp_path / "libs.zip").write_bytes(b"PK\x05\x06" + b"\x00" * 18)
    args = get_opts(["--cluster", "yarn", "-n", "1", "--files", "data.txt",
                     "--archives", "libs.zip", "--",
                     "python", "-c", "pass"])
    cmd = build_yarn_command(args, ENVS)
    joined = " ".join(cmd)
    assert "-shell_files" in joined
    assert str(tmp_path / "data.txt") in joined
    # cwd-mode wrapper: archives extracted in place, no cp/mktemp staging
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read()
    os.unlink(script)
    assert "unzip -oq ./libs.zip -d ." in body
    assert "mktemp" not in body


def test_batch_wrapper_stages_and_cds(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "w.bin").write_text("x")
    args = get_opts(["--cluster", "slurm", "-n", "1", "--files", "w.bin",
                     "--", "python", "-c", "pass"])
    from dmlc_core_tpu.parallel.launcher.wrapper import wrapper_body
    body = wrapper_body(args, ENVS, "slurm", 'export DMLC_TASK_ID=0')
    assert "mktemp -d" in body
    assert f"cp -f {tmp_path}/w.bin" in body
    assert 'cd "$DMLC_STAGE_DIR"' in body


# ---------------------------------------------------------------------------
# node-replacement failure domain (reference ApplicationMaster.java:73-74,
# 508, 535-563: blacklist + container replacement + maxNumAttempt abort)
# ---------------------------------------------------------------------------

def test_host_pool_blacklist_and_exhaustion():
    from dmlc_core_tpu.parallel.launcher.ssh import HostPool
    from dmlc_core_tpu.utils import DMLCError
    import pytest
    a, b = ("h1", 22), ("h2", 22)
    pool = HostPool([a, b], fail_limit=2)
    assert pool.assign() in (a, b)
    assert not pool.record_failure(a)          # 1st failure: kept
    assert pool.record_failure(a)              # 2nd: blacklisted
    assert pool.blacklisted == {a}
    assert pool.assign() == b and pool.assign() == b
    assert pool.record_failure(b, unreachable=True)   # 255 → immediate
    with pytest.raises(DMLCError):
        pool.assign()


def _fake_ssh_bin(tmp_path, dead_host="deadhost"):
    """ssh/rsync fakes: remote commands run locally; ssh to ``dead_host``
    fails with 255 (connection refused), emulating a dead node."""
    import stat
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir(exist_ok=True)
    (bin_dir / "ssh").write_text(
        "#!/bin/bash\n"
        'while [[ "$1" == -* ]]; do [[ "$1" == -o || "$1" == -p ]] && '
        "shift; shift; done\n"
        'host="$1"; shift\n'
        f'[[ "$host" == {dead_host} ]] && exit 255\n'
        'exec bash -c "$*"\n')
    (bin_dir / "rsync").write_text(
        "#!/bin/bash\nargs=()\n"
        'for a in "$@"; do case "$a" in -*) ;; *) args+=("$a");; esac; '
        "done\n"
        'unset "args[0]" 2>/dev/null\n'
        'args=("${args[@]}")\n'
        'dest="${args[-1]}"\n'
        f'[[ "$dest" == {dead_host}:* ]] && exit 255\n'
        'dest="${dest#*:}"\nunset "args[-1]"\n'
        'exec cp -f "${args[@]}" "$dest"\n')
    for f in bin_dir.iterdir():
        f.chmod(f.stat().st_mode | stat.S_IXUSR)
    return bin_dir


def test_dead_host_replaced_and_job_finishes(tmp_path, monkeypatch):
    """VERDICT r2 #4: one of two hosts is dead; the task scheduled there is
    blacklisted off it and rescheduled onto the live host, the 2-worker
    cohort assembles, an allreduce completes, the job exits 0."""
    from dmlc_core_tpu.parallel.launcher.submit import submit
    monkeypatch.setenv("PATH",
                       f"{_fake_ssh_bin(tmp_path)}:{os.environ['PATH']}")
    monkeypatch.chdir(tmp_path)
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("deadhost\n127.0.0.1\n")
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "import numpy as np\n"
        "from dmlc_core_tpu.parallel import RabitContext\n"
        "ctx = RabitContext.from_env()\n"
        "out = ctx.allreduce(np.array([1.0]))\n"
        "assert out[0] == ctx.world_size\n"
        "print('REPLACED-OK rank', ctx.rank, 'attempt',\n"
        "      os.environ.get('DMLC_NUM_ATTEMPT'), flush=True)\n"
        "ctx.shutdown()\n")
    import sys as _sys
    rc = submit([
        "--cluster", "ssh", "-n", "2", "--host-file", str(hosts),
        "--host-ip", "127.0.0.1", "--max-attempts", "3",
        "--env", f"PYTHONPATH={REPO}", "--",
        _sys.executable, str(script)])
    assert rc == 0


def test_yarn_app_level_reacquire(tmp_path, monkeypatch):
    """Node-death handling (VERDICT r3 #8): a FAILED app is resubmitted
    with fresh containers, bounded by DMLC_YARN_APP_ATTEMPTS, with RM REST
    diagnostics logged when the endpoint answers; a 0-rc app submits once."""
    import http.server
    import threading

    from dmlc_core_tpu.parallel.launcher.yarn import rm_app_report, submit_yarn

    # fake hadoop CLI: fails (rc 1) until the count file reaches 3
    count = tmp_path / "count"
    count.write_text("0")
    fake = tmp_path / "hadoop"
    fake.write_text(
        "#!/bin/bash\n"
        f"n=$(cat {count}); n=$((n+1)); echo $n >{count}\n"
        "echo 'Submitted application application_1700000000001_0042'\n"
        f"if [ \"$n\" -lt 3 ]; then exit 1; fi\n"
        "exit 0\n")
    fake.chmod(0o755)
    monkeypatch.setenv("HADOOP_HOME", "")
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

    # stub RM REST endpoint serving diagnostics for the failed app
    class RM(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            assert self.path.endswith(
                "/ws/v1/cluster/apps/application_1700000000001_0042")
            body = (b'{"app": {"state": "FINISHED", "finalStatus": "FAILED",'
                    b' "diagnostics": "Container released on a *lost* node"}}')
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), RM)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        rm = f"http://127.0.0.1:{srv.server_address[1]}"
        monkeypatch.setenv("DMLC_YARN_RM_HTTP", rm)
        rep = rm_app_report("application_1700000000001_0042")
        assert rep["finalStatus"] == "FAILED" and "lost" in rep["diagnostics"]

        monkeypatch.setenv("DMLC_YARN_APP_ATTEMPTS", "3")
        args = _args("yarn")
        assert submit_yarn(args, ENVS) == 0
        assert count.read_text().strip() == "3"   # 2 failures + 1 success

        # bounded: attempts exhausted -> nonzero rc, submission count capped
        count.write_text("-10")                   # needs 13 runs to succeed
        monkeypatch.setenv("DMLC_YARN_APP_ATTEMPTS", "2")
        assert submit_yarn(args, ENVS) != 0
        assert count.read_text().strip() == "-8"  # exactly 2 submissions

        # rc 0 first time: exactly one submission
        count.write_text("99")
        monkeypatch.setenv("DMLC_YARN_APP_ATTEMPTS", "3")
        assert submit_yarn(args, ENVS) == 0
        assert count.read_text().strip() == "100"
    finally:
        srv.shutdown()

    # unreachable RM endpoint degrades to {}
    monkeypatch.setenv("DMLC_YARN_RM_HTTP", "http://127.0.0.1:1")
    assert rm_app_report("application_1_1") == {}


# ---------------------------------------------------------------------------
# container-granularity YARN supervision (VERDICT r4 #8): fake RM proving a
# container death retries ONLY its own task's app
# ---------------------------------------------------------------------------

def _fake_rm():
    """In-process RM REST stub for the per-task app supervisor.  Outcomes
    are scripted per (task_id, attempt): submitting an app immediately
    assigns its final report, so the supervisor's poll loop is
    deterministic."""
    import http.server
    import json as _json
    import re
    import threading

    class RM(http.server.BaseHTTPRequestHandler):
        apps = {}           # app_id -> report dict
        payloads = []       # every submitted payload, in order
        kills = []
        next_id = [0]
        outcomes = {}       # (task_id, attempt) -> (state, final, node)
        default = ("FINISHED", "SUCCEEDED", "goodnode")

        def log_message(self, *a):
            pass

        def _send(self, obj, code=200):
            body = _json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            ln = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(ln)
            if self.path.endswith("/new-application"):
                self.next_id[0] += 1
                self._send({"application-id":
                            f"application_1_{self.next_id[0]:04d}"})
                return
            payload = _json.loads(body)
            type(self).payloads.append(payload)
            env = {e["key"]: e["value"] for e in
                   payload["am-container-spec"]["environment"]["entry"]}
            key = (env["DMLC_TASK_ID"], env["DMLC_NUM_ATTEMPT"])
            state, final, node = self.outcomes.get(key, self.default)
            self.apps[payload["application-id"]] = {
                "state": state, "finalStatus": final,
                "amHostHttpAddress": f"{node}:8042",
                "diagnostics": f"scripted outcome for task/attempt {key}"}
            self._send({}, 202)

        def do_GET(self):
            app_id = self.path.rsplit("/", 1)[-1]
            rep = self.apps.get(app_id)
            self._send({"app": rep} if rep else {}, 200 if rep else 404)

        def do_PUT(self):
            m = re.search(r"/apps/([^/]+)/state", self.path)
            ln = int(self.headers.get("Content-Length", 0))
            self.rfile.read(ln)
            type(self).kills.append(m.group(1))
            self.apps[m.group(1)] = {"state": "KILLED",
                                     "finalStatus": "KILLED",
                                     "amHostHttpAddress": "x:1"}
            self._send({})

    RM.apps, RM.payloads, RM.kills, RM.outcomes = {}, [], [], {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), RM)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, RM


def test_yarn_rest_container_death_retries_only_that_task():
    """A failed container (== its single-container app) is retried with a
    bumped DMLC_NUM_ATTEMPT while every OTHER task's app is untouched — the
    reference AM's container re-request semantics (ApplicationMaster.java:
    535-563) without restarting the whole job; the failing node enters the
    supervisor blacklist and rides the retry's env."""
    from dmlc_core_tpu.parallel.launcher.yarn_am import (
        TaskSpec, TaskSupervisor, YarnRestClient)

    srv, RM = _fake_rm()
    try:
        RM.outcomes[("1", "0")] = ("FINISHED", "FAILED", "badnode")
        client = YarnRestClient(f"http://127.0.0.1:{srv.server_address[1]}")
        tasks = [TaskSpec(i, "run-task") for i in range(3)]
        sup = TaskSupervisor(client, tasks, max_attempts=3,
                             node_fail_limit=1, poll_s=0,
                             sleep=lambda s: None)
        assert sup.run() == 0
        by_task = {}
        for p in RM.payloads:
            env = {e["key"]: e["value"] for e in
                   p["am-container-spec"]["environment"]["entry"]}
            by_task.setdefault(env["DMLC_TASK_ID"], []).append(env)
        # tasks 0/2: exactly one submission each — no whole-job restart
        assert len(by_task["0"]) == 1 and len(by_task["2"]) == 1
        # task 1: original + retry, attempt env bumped for recover
        assert [e["DMLC_NUM_ATTEMPT"] for e in by_task["1"]] == ["0", "1"]
        # the retry carries the blacklisted node (wrapper fails fast on it)
        assert by_task["1"][1]["DMLC_BLACKLISTED_NODES"] == "badnode"
        assert RM.kills == []
        assert sup.blacklist == {"badnode"}
    finally:
        srv.shutdown()


def test_yarn_rest_abort_after_max_attempts_kills_cohort():
    """One task exhausting max_attempts aborts the job (reference :508):
    still-running task apps are killed, rc is nonzero, and the doomed task
    was submitted exactly max_attempts times."""
    from dmlc_core_tpu.parallel.launcher.yarn_am import (
        TaskSpec, TaskSupervisor, YarnRestClient)

    srv, RM = _fake_rm()
    try:
        for a in range(5):
            RM.outcomes[("0", str(a))] = ("FINISHED", "FAILED", f"n{a}")
        # task 1 never finishes: stays RUNNING so the abort must kill it
        RM.outcomes[("1", "0")] = ("RUNNING", "UNDEFINED", "n9")
        client = YarnRestClient(f"http://127.0.0.1:{srv.server_address[1]}")
        sup = TaskSupervisor(client, [TaskSpec(0, "x"), TaskSpec(1, "x")],
                             max_attempts=2, node_fail_limit=3, poll_s=0,
                             sleep=lambda s: None)
        assert sup.run() == 1
        task0_subs = [p for p in RM.payloads
                      if any(e["key"] == "DMLC_TASK_ID"
                             and e["value"] == "0"
                             for e in p["am-container-spec"]
                             ["environment"]["entry"])]
        assert len(task0_subs) == 2          # exactly max_attempts
        assert len(RM.kills) == 1            # task 1's app, and only it
    finally:
        srv.shutdown()


def test_yarn_rest_mode_end_to_end_via_submit(monkeypatch):
    """DMLC_YARN_MODE=rest routes submit_yarn through the supervisor: one
    app per task (workers + servers), each command shipping the shared
    wrapper inline, all-success returns 0."""
    from dmlc_core_tpu.parallel.launcher.yarn import submit_yarn

    srv, RM = _fake_rm()
    try:
        monkeypatch.setenv("DMLC_YARN_MODE", "rest")
        monkeypatch.setenv(
            "DMLC_YARN_RM_HTTP", f"http://127.0.0.1:{srv.server_address[1]}")
        args = _args("yarn")                 # 3 workers + 1 server
        assert submit_yarn(args, ENVS) == 0
        assert len(RM.payloads) == 4
        for p in RM.payloads:
            assert "base64 -d" in p["am-container-spec"]["commands"]["command"]
        # server task (id 0) gets server resources, worker tasks worker's
        ids = sorted(int(e["value"])
                     for p in RM.payloads
                     for e in p["am-container-spec"]["environment"]["entry"]
                     if e["key"] == "DMLC_TASK_ID")
        assert ids == [0, 1, 2, 3]
    finally:
        srv.shutdown()
