"""Launcher backend tests: command generation for YARN/Mesos (dry-run) and
env-contract correctness of the generated wrapper scripts."""

import os
import subprocess

from dmlc_core_tpu.parallel.launcher.mesos import build_mesos_commands
from dmlc_core_tpu.parallel.launcher.opts import get_opts
from dmlc_core_tpu.parallel.launcher.yarn import build_yarn_command

ENVS = {"DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091"}


def _args(cluster, extra=()):
    return get_opts([
        "--cluster", cluster, "-n", "3", "-s", "1", "--jobname", "jobx",
        *extra, "--", "python", "train.py", "--lr", "0.1"])


def test_yarn_command_shape():
    args = _args("yarn", ["--yarn-queue", "prod", "--worker-memory-mb",
                          "2048", "--worker-cores", "4"])
    cmd = build_yarn_command(args, ENVS)
    joined = " ".join(cmd)
    assert "distributedshell.Client" in joined
    assert "-num_containers 4" in joined          # 3 workers + 1 server
    assert "-container_memory 2048" in joined
    assert "-container_vcores 4" in joined
    assert "-queue prod" in joined
    assert "-appname jobx" in joined
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read()
    assert "export DMLC_TRACKER_URI=10.0.0.1" in body
    assert "export DMLC_NUM_WORKER=3" in body
    assert "export DMLC_NUM_SERVER=1" in body
    assert "DMLC_MAX_ATTEMPT" in body
    assert "exec python train.py --lr 0.1" in body
    os.unlink(script)


def test_yarn_wrapper_rank_and_role():
    """Execute the wrapper with a faked CONTAINER_ID: container 2 (first
    task container after the AM) must get DMLC_TASK_ID=0 → server role."""
    args = _args("yarn")
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read().replace(
        "exec python train.py --lr 0.1",
        'echo "$DMLC_TASK_ID $DMLC_ROLE"')
    open(script, "w").write(body)
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000002"},
        capture_output=True, text=True)
    assert out.stdout.split() == ["0", "server"]
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000005"},
        capture_output=True, text=True)
    assert out.stdout.split() == ["3", "worker"]
    os.unlink(script)


def test_mesos_commands_one_per_task():
    """Everything must be inlined in --command: mesos-execute does not ship
    local files to agents, so no path on the submit host may appear."""
    args = _args("mesos", ["--mesos-master", "master:5050"])
    cmds = build_mesos_commands(args, ENVS)
    assert len(cmds) == 4
    for tid, c in enumerate(cmds):
        assert c[0] == "mesos-execute"
        assert f"--master=master:5050" in c
        assert f"--name=jobx-task-{tid}" in c
        inline = next(a for a in c if a.startswith("--command=")).split("=", 1)[1]
        assert "/tmp/" not in inline          # self-contained, nothing to ship
        assert f"export DMLC_TASK_ID={tid}" in inline
        role = "server" if tid < 1 else "worker"
        assert f"export DMLC_ROLE={role}" in inline
        assert "export DMLC_TRACKER_URI=10.0.0.1" in inline
        assert inline.endswith("exec python train.py --lr 0.1")
        # the inline command must execute: run it with a stub
        out = subprocess.run(
            ["bash", "-c", inline.replace("exec python train.py --lr 0.1",
                                          'echo "$DMLC_TASK_ID $DMLC_ROLE"')],
            capture_output=True, text=True)
        assert out.stdout.split() == [str(tid), role]


def test_yarn_restarted_container_recovers_via_tracker():
    """Out-of-range container id (YARN restart) must clear DMLC_TASK_ID and
    flag DMLC_RECOVER so the tracker assigns the orphaned rank."""
    args = _args("yarn")
    cmd = build_yarn_command(args, ENVS)
    script = cmd[cmd.index("-shell_script") + 1]
    body = open(script).read().replace(
        "exec python train.py --lr 0.1",
        'echo "id=${DMLC_TASK_ID:-unset} role=$DMLC_ROLE rec=${DMLC_RECOVER:-0}"')
    open(script, "w").write(body)
    out = subprocess.run(
        ["bash", script],
        env={**os.environ,
             "CONTAINER_ID": "container_1700000000001_0001_01_000099"},
        capture_output=True, text=True)
    assert out.stdout.split() == ["id=unset", "role=worker", "rec=1"]
    os.unlink(script)


def test_submit_dry_run_all_clusters():
    """--dry-run must not launch anything on ANY backend: tracker boots,
    submission is previewed, rc 0, no scheduler binaries needed."""
    from dmlc_core_tpu.parallel.launcher.submit import submit
    for cluster in ["yarn", "mesos", "slurm", "sge", "mpi", "local"]:
        rc = submit(["--cluster", cluster, "-n", "2", "--dry-run",
                     "--", "definitely-not-a-real-binary"])
        assert rc == 0, cluster
