"""Ring attention (sequence parallelism) correctness on the 8-device virtual
CPU mesh: exact match vs single-device attention, causal and non-causal."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from dmlc_core_tpu.ops.ring_attention import (make_ring_attention,  # noqa: E402
                                              reference_attention)


def make_qkv(rng, B=2, T=32, H=2, D=16):
    return [jnp.array(rng.standard_normal((B, T, H, D)), jnp.float32)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(np.array(devices), ("sp",))
    rng = np.random.default_rng(0)
    q, k, v = make_qkv(rng)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = make_ring_attention(mesh, "sp", causal=causal)
    out = fn(qs, ks, vs)
    expect = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)
    # output keeps the sequence sharding (no gather to one device)
    assert out.sharding.spec == P(None, "sp", None, None)


def test_single_device_ring_degenerates():
    # world=1: ring attention is just flash-style blockwise attention
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    rng = np.random.default_rng(1)
    q, k, v = make_qkv(rng, T=8)
    fn = make_ring_attention(mesh, "sp", causal=True)
    out = fn(q, k, v)
    expect = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_gradients_match_dense():
    """Reverse-mode through the ppermute ring (scan + online softmax)
    equals dense-attention grads."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    import numpy as _np
    mesh = Mesh(_np.array(jax.devices()[:8]), ("sp",))
    rng = _np.random.default_rng(4)
    shp = (2, 32, 4, 16)
    q, k, v = (jnp.asarray(rng.standard_normal(shp), jnp.float32)
               for _ in range(3))
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = make_ring_attention(mesh, "sp", causal=True)
    g = jax.grad(lambda a, b, c: jnp.sum(fn(a, b, c) ** 2),
                 argnums=(0, 1, 2))(qs, ks, vs)
    gr = jax.grad(
        lambda a, b, c: jnp.sum(
            reference_attention(a, b, c, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(_np.asarray(a), _np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
