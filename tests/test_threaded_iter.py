"""ThreadedIter semantics tests — mirrors reference
``test/unittest/unittest_threaditer.cc`` coverage: basic streaming, recycling,
BeforeFirst reset races, mid-stream destruction, producer error propagation."""

import threading
import time

import pytest

from dmlc_core_tpu.utils import DMLCError, ThreadedIter


def make_counter_iter(n, capacity=4, delay=0.0):
    state = {"i": 0}

    def next_fn(cell):
        if state["i"] >= n:
            return None
        if delay:
            time.sleep(delay)
        v = state["i"]
        state["i"] += 1
        # reuse the recycled cell when present (zero-alloc steady state)
        if cell is not None:
            cell[0] = v
            return cell
        return [v]

    def beforefirst():
        state["i"] = 0

    it = ThreadedIter(max_capacity=capacity)
    it.init(next_fn, beforefirst)
    return it


def test_basic_stream():
    with make_counter_iter(100) as it:
        got = [x[0] for x in it]
        assert got == list(range(100))
        assert it.next() is None  # stays ended


def test_recycling_reuses_cells():
    with make_counter_iter(50, capacity=2) as it:
        seen_ids = set()
        out = []
        while True:
            item = it.next()
            if item is None:
                break
            out.append(item[0])
            seen_ids.add(id(item))
            it.recycle(item)
        assert out == list(range(50))
        # with recycling and capacity 2 the number of distinct cells stays small
        assert len(seen_ids) <= 8


def test_before_first_restarts_epoch():
    with make_counter_iter(10) as it:
        first = [x[0] for x in it]
        it.before_first()
        second = [x[0] for x in it]
        assert first == second == list(range(10))


def test_before_first_mid_stream():
    # reference unittest_threaditer.cc exercises reset while producer active
    with make_counter_iter(1000, capacity=4) as it:
        for _ in range(5):
            assert it.next() is not None
        it.before_first()
        vals = [x[0] for x in it]
        assert vals == list(range(1000))


def test_destroy_mid_stream():
    it = make_counter_iter(10**9, capacity=2, delay=0.001)
    assert it.next() is not None
    it.destroy()  # must not hang with a full queue / busy producer
    # destroying twice is fine
    it.destroy()


def test_producer_exception_propagates():
    def next_fn(cell):
        raise ValueError("boom")

    it = ThreadedIter(max_capacity=2)
    it.init(next_fn)
    with pytest.raises(DMLCError, match="boom"):
        it.next()
    it.destroy()


def test_exception_then_reset_recovers():
    state = {"fail": True, "i": 0}

    def next_fn(cell):
        if state["fail"]:
            raise ValueError("first epoch fails")
        if state["i"] >= 3:
            return None
        state["i"] += 1
        return state["i"]

    def beforefirst():
        state["fail"] = False
        state["i"] = 0

    it = ThreadedIter(max_capacity=2)
    it.init(next_fn, beforefirst)
    with pytest.raises(DMLCError):
        it.next()
    it.before_first()
    assert [x for x in it] == [1, 2, 3]
    it.destroy()


def test_backpressure_bounded_queue():
    produced = []

    def next_fn(cell):
        produced.append(len(produced))
        return produced[-1]

    it = ThreadedIter(max_capacity=3)
    it.init(next_fn)
    time.sleep(0.2)  # let the producer run against a full queue
    assert len(produced) <= 5  # capacity + in-flight, never unbounded
    it.destroy()


def test_from_iterable_factory():
    it = ThreadedIter.from_iterable_factory(lambda: iter(range(7)), max_capacity=2)
    assert list(it) == list(range(7))
    it.before_first()
    assert list(it) == list(range(7))
    it.destroy()
