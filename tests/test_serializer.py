"""Serializer round-trip tests via in-memory streams
(reference: ``test/unittest/unittest_serializer.cc:12-25``)."""

import io

import numpy as np
import pytest

from dmlc_core_tpu.utils import serializer as ser
from dmlc_core_tpu.utils import DMLCError


def roundtrip(obj):
    buf = io.BytesIO()
    ser.save(buf, obj)
    buf.seek(0)
    out = ser.load(buf)
    assert buf.read() == b""  # fully consumed
    return out


@pytest.mark.parametrize("obj", [
    None, True, False, 0, -1, 2**40, 3.25, float("inf"),
    "", "héllo", b"\x00\xff\x01", [1, 2, 3], (4, "x"), {1, 2, 3},
    {"a": 1, "b": [1.5, None]}, [[{"k": (1, 2)}], {"s": {3}}],
])
def test_scalar_container_roundtrip(obj):
    assert roundtrip(obj) == obj


def test_numpy_roundtrip():
    for arr in [np.arange(12, dtype=np.float32).reshape(3, 4),
                np.array([], dtype=np.uint64),
                np.random.default_rng(0).random((5, 7)),
                np.array([[1, 2], [3, 4]], dtype=np.int8)]:
        out = roundtrip(arr)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_nested_mixed():
    obj = {"arrays": [np.ones(3, np.float32)], "meta": {"n": 3, "ok": True}}
    out = roundtrip({"arrays": obj["arrays"], "meta": obj["meta"]})
    np.testing.assert_array_equal(out["arrays"][0], obj["arrays"][0])
    assert out["meta"] == obj["meta"]


class Point:
    """Serializable class (reference Serializable io.h:112)."""

    def __init__(self, x=0, y=0):
        self.x, self.y = x, y

    def save(self, s):
        ser.write_int64(s, self.x)
        ser.write_int64(s, self.y)

    def load(self, s):
        self.x = ser.read_int64(s)
        self.y = ser.read_int64(s)


def test_saveload_class():
    buf = io.BytesIO()
    ser.save(buf, Point(3, -4))
    buf.seek(0)
    p = ser.load(buf, Point())
    assert (p.x, p.y) == (3, -4)
    buf.seek(0)
    with pytest.raises(DMLCError):
        ser.load(buf)  # needs target instance


def test_truncated_stream_raises():
    buf = io.BytesIO()
    ser.save(buf, [1, 2, 3])
    data = buf.getvalue()[:-3]
    with pytest.raises(DMLCError):
        ser.load(io.BytesIO(data))


def test_scalar_helpers():
    buf = io.BytesIO()
    ser.write_uint32(buf, 7)
    ser.write_uint64(buf, 2**63)
    ser.write_int64(buf, -5)
    ser.write_float64(buf, 1.5)
    ser.write_string(buf, "abc")
    buf.seek(0)
    assert ser.read_uint32(buf) == 7
    assert ser.read_uint64(buf) == 2**63
    assert ser.read_int64(buf) == -5
    assert ser.read_float64(buf) == 1.5
    assert ser.read_string(buf) == "abc"
