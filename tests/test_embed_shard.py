"""Sharded embedding-table subsystem: partition math, deduped lookup,
sparse update, replica failover, collective-flush determinism over a
real tracker, and the chaos proof — kill one rank mid-epoch and the run
stays bit-identical to a no-kill run with zero checkpoint reads."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.embed import ShardedEmbeddingTable  # noqa: E402
from dmlc_core_tpu.ops.ragged_csr import (ragged_embed_grad,  # noqa: E402
                                          ragged_embed_sum)
from dmlc_core_tpu.parallel import (RabitContext, RabitTracker,  # noqa: E402
                                    row_owners, row_partition)
from dmlc_core_tpu.pipeline.packing import dedup_ids  # noqa: E402
from dmlc_core_tpu.utils import DMLCError  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

from conftest import free_port  # noqa: E402


def _counter(name):
    return metrics.counter(name).value


def _ragged(ids, vals, segments, rows, nnz_cap=0):
    """Build a ragged batch dict (the ``pack_ragged`` contract) from live
    arrays; tails past ``nnz_used`` are zero-filled (garbage by contract,
    the masked kernels never read them)."""
    nnz = len(ids)
    cap = max(nnz, nnz_cap)
    out = {"ids": np.zeros(cap, np.int32), "vals": np.zeros(cap, np.float32),
           "segments": np.zeros(cap, np.int32),
           "row_ptr": np.zeros(rows + 1, np.int32),
           "labels": np.zeros(rows, np.float32),
           "weights": np.ones(rows, np.float32),
           "nnz_used": np.int32(nnz), "rows_used": np.int32(rows)}
    out["ids"][:nnz] = ids
    out["vals"][:nnz] = vals
    out["segments"][:nnz] = segments
    return out


def _identity_batch(num_rows):
    """One table row per output row with weight 1.0 — lookup returns the
    table itself."""
    return _ragged(np.arange(num_rows), np.ones(num_rows),
                   np.arange(num_rows), num_rows)


# ---------------------------------------------------------------------------
# pure partition math
# ---------------------------------------------------------------------------

def test_row_owners_inverts_row_partition():
    for n in (1, 2, 7, 48, 1000):
        for p in (1, 2, 3, 5, 13):
            parts = row_partition(n, p)
            rows = np.arange(n, dtype=np.int64)
            owners = row_owners(n, p, rows)
            for r, (s, e) in enumerate(parts):
                assert (owners[s:e] == r).all(), (n, p, r)
    # parts > n_rows: trailing empty ranges own nothing
    owners = row_owners(2, 4, np.array([0, 1]))
    assert owners.tolist() == [0, 1]
    with pytest.raises(DMLCError):
        row_owners(10, 2, np.array([10]))
    with pytest.raises(DMLCError):
        row_owners(10, 2, np.array([-1]))


def test_holders_and_replica_clamp():
    t = ShardedEmbeddingTable(48, 4, rank=1, world=3, replicas=1)
    assert t.holders_of(0) == [0, 1]
    assert t.holders_of(2) == [2, 0]
    # replicas clamp to world-1; holders list never wraps past the world
    t5 = ShardedEmbeddingTable(48, 4, rank=0, world=3, replicas=5)
    assert t5.replicas == 2
    assert t5.holders_of(1) == [1, 2, 0]
    solo = ShardedEmbeddingTable(8, 2, replicas=3)
    assert solo.replicas == 0 and solo.holders_of(0) == [0]


def test_reference_rows_is_shard_union_and_resize_stable():
    ref = ShardedEmbeddingTable.reference_rows(100, 3, seed=5)
    assert ref.shape == (100, 3)
    for world in (1, 2, 3, 7):
        got = np.concatenate([
            ShardedEmbeddingTable(100, 3, rank=r, world=world, seed=5,
                                  replicas=0).read_block(s, e)
            for r, (s, e) in enumerate(row_partition(100, world))
            if s < e])
        # any cohort layout materializes the SAME table bit-for-bit
        assert got.tobytes() == ref.tobytes(), world


def test_dedup_ids_contract():
    ids = np.array([7, 3, 7, 7, 3, 9, 999], np.int32)   # 999 is dead tail
    uniq, pos = dedup_ids(ids, nnz_used=6)
    assert uniq.tolist() == [3, 7, 9] and uniq.dtype == np.int64
    assert (uniq[pos] == ids[:6].astype(np.int64)).all()
    assert pos.dtype == np.int32
    u0, p0 = dedup_ids(np.array([], np.int32), 0)
    assert u0.size == 0 and p0.size == 0


# ---------------------------------------------------------------------------
# single-host numerics (world == 1: the train_fm/train_dcn migration mode)
# ---------------------------------------------------------------------------

def test_lookup_matches_dense_reference():
    rng = np.random.default_rng(3)
    n, d, rows, nnz = 64, 4, 6, 40
    t = ShardedEmbeddingTable(n, d, seed=1)
    ref = ShardedEmbeddingTable.reference_rows(n, d, seed=1)
    ids = rng.integers(0, n, nnz)
    vals = rng.random(nnz).astype(np.float32)
    segs = np.sort(rng.integers(0, rows - 2, nnz))   # last 2 rows padded
    pooled = t.lookup(_ragged(ids, vals, segs, rows, nnz_cap=64))
    want = np.zeros((rows, d), np.float32)
    for i in range(nnz):
        want[segs[i]] += vals[i] * ref[ids[i]]
    np.testing.assert_allclose(pooled, want, rtol=1e-5, atol=1e-6)
    assert (pooled[-2:] == 0).all()                  # padded rows exact 0


def test_backward_flush_applies_sgd():
    n, d, rows = 32, 4, 4
    t = ShardedEmbeddingTable(n, d, seed=2, lr=0.5)
    ref = ShardedEmbeddingTable.reference_rows(n, d, seed=2)
    # row 5 appears twice with vals 2 and 3 in segments 0 and 1
    batch = _ragged(np.array([5, 5, 9]), np.array([2.0, 3.0, 1.0]),
                    np.array([0, 1, 2]), rows)
    t.lookup(batch)
    g = np.zeros((rows, d), np.float32)
    g[0], g[1], g[2] = 1.0, 10.0, 7.0
    assert t.backward(batch, g) == 2                 # unique rows {5, 9}
    assert t.flush_direct() == 2
    np.testing.assert_allclose(
        t.read_block(5, 6)[0], ref[5] - 0.5 * (2.0 * g[0] + 3.0 * g[1]),
        rtol=1e-5)
    np.testing.assert_allclose(t.read_block(9, 10)[0],
                               ref[9] - 0.5 * 7.0, rtol=1e-5)
    assert t.read_block(6, 7)[0].tobytes() == ref[6].tobytes()  # untouched


def test_ragged_embed_grad_matches_autodiff():
    rng = np.random.default_rng(11)
    n, d, rows, nnz = 16, 3, 5, 20
    ids = rng.integers(0, n, nnz).astype(np.int32)
    vals = rng.random(nnz).astype(np.float32)
    segs = np.sort(rng.integers(0, rows, nnz)).astype(np.int32)
    table = rng.random((n, d)).astype(np.float32)
    g_rows = rng.random((rows, d)).astype(np.float32)
    live = np.int32(nnz - 4)                          # mask a tail

    def pooled_sum(tab):
        out = ragged_embed_sum(ids, vals, segs, live, tab, num_rows=rows,
                               engine="xla")
        return (out * g_rows).sum()

    want = jax.grad(pooled_sum)(table)
    got = ragged_embed_grad(ids, vals, segs, live, g_rows,
                            num_table_rows=n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# exchange plane: two tables in one process wired by address injection
# ---------------------------------------------------------------------------

def test_remote_lookup_cache_and_eviction():
    n, d = 32, 4
    ta = ShardedEmbeddingTable(n, d, rank=0, world=2, replicas=0,
                               serve=True, cache_rows=8)
    tb = ShardedEmbeddingTable(n, d, rank=1, world=2, replicas=0,
                               serve=True)
    try:
        ta.set_addresses({1: ("127.0.0.1", tb.server.port)})
        assert ta.addresses[1][1] == tb.server.port
        ref = ShardedEmbeddingTable.reference_rows(n, d)
        remote = np.arange(16, 32)
        batch = _ragged(remote, np.ones(16), np.arange(16), 16)
        misses0, hits0 = _counter("embed.cache_misses"), _counter(
            "embed.cache_hits")
        np.testing.assert_allclose(ta.lookup(batch), ref[16:32], rtol=1e-5)
        assert _counter("embed.cache_misses") == misses0 + 16
        # LRU keeps only cache_rows=8 of them: a re-lookup hits 8
        np.testing.assert_allclose(ta.lookup(batch), ref[16:32], rtol=1e-5)
        assert _counter("embed.cache_hits") == hits0 + 8
        # a local apply invalidates the cache (rows may be stale)
        ta.apply_update(np.array([0]), np.ones((1, d), np.float32))
        np.testing.assert_allclose(ta.lookup(batch), ref[16:32], rtol=1e-5)
        assert _counter("embed.cache_hits") == hits0 + 8
    finally:
        ta.close()
        tb.close()


def test_replica_failover_when_primary_dies():
    n, d, world = 48, 4, 3
    tables = [ShardedEmbeddingTable(n, d, rank=r, world=world, replicas=1,
                                    serve=True, cache_rows=0)
              for r in range(world)]
    try:
        addrs = {r: ("127.0.0.1", t.server.port)
                 for r, t in enumerate(tables)}
        for t in tables:
            t.set_addresses(addrs)
        ref = ShardedEmbeddingTable.reference_rows(n, d)
        s1, e1 = tables[0].partition[1]
        shard1 = np.arange(s1, e1)
        batch = _ragged(shard1, np.ones(len(shard1)),
                        np.arange(len(shard1)), len(shard1))
        tables[1].close()                 # primary of shard 1 dies
        fo0 = _counter("embed.failovers")
        # rank 0 fails over to shard 1's replica holder (rank 2)
        np.testing.assert_allclose(tables[0].lookup(batch), ref[s1:e1],
                                   rtol=1e-5)
        assert _counter("embed.failovers") > fo0
        # rank 2 holds the replica locally — no wire at all
        np.testing.assert_allclose(tables[2].lookup(batch), ref[s1:e1],
                                   rtol=1e-5)
        # all holders down -> a clear error, not a hang
        tables[2].close()
        with pytest.raises(DMLCError, match="no live holder"):
            tables[0].lookup(batch)
    finally:
        for t in tables:
            t.close()


def test_snapshot_budget_and_plan(monkeypatch):
    t = ShardedEmbeddingTable(64, 8, rank=0, world=2, replicas=1)
    assert t.plan(t.leaf, (64, 8)) == t.partition[0]
    assert t.plan("dense/w1", (3, 3)) is None
    snap = t.build_snapshot()
    # primary + replica blocks ride as ranged pieces of ONE leaf
    assert sorted(s for s, _, _ in snap.pieces[t.leaf]) == [0, 32]
    monkeypatch.setenv("DMLC_RESHARD_MAX_BYTES", "64")
    skipped0 = _counter("reshard.snapshot_skipped")
    assert t.build_snapshot() is None
    assert _counter("reshard.snapshot_skipped") == skipped0 + 1


def test_adopt_restored_keeps_wanted_replicas():
    t = ShardedEmbeddingTable(48, 4, rank=0, world=3, replicas=1, seed=9)
    ref = ShardedEmbeddingTable.reference_rows(48, 4, seed=9)
    s, e = t.partition[0]
    rs, re_ = t.partition[2]              # rank 0 replicates shard 2
    fresh = ref[s:e] + 1.0
    t.adopt_restored({t.leaf: fresh})
    np.testing.assert_allclose(t.read_block(s, e), fresh)
    # the replica of shard 2 survived the restore (post-flush bit-equal)
    assert t.read_block(rs, re_).tobytes() == ref[rs:re_].tobytes()
    assert t.rebuild_replicas() == 0      # nothing missing to refetch


# ---------------------------------------------------------------------------
# real tracker cohort: remote lookup + collective flush determinism
# ---------------------------------------------------------------------------

def _cohort(world, fn, timeout=90):
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    results, errors = [None] * world, [None] * world

    def worker(i):
        ctx = None
        try:
            ctx = RabitContext(env["DMLC_TRACKER_URI"],
                               int(env["DMLC_TRACKER_PORT"]), jobid=f"w{i}")
            results[ctx.rank] = fn(ctx, ctx.rank)
        except Exception as e:  # noqa: BLE001
            errors[i] = e
        finally:
            if ctx is not None:
                try:
                    ctx.shutdown()
                except Exception:  # noqa: BLE001
                    pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    tracker.join(timeout=30)
    return results, [e for e in errors if e is not None]


def test_cohort_lookup_flush_bit_identical():
    """3 ranks over a real tracker: every rank looks up the WHOLE table
    (two thirds remote), contributes rank-dependent grads, and after one
    collective flush all ranks observe a bit-identical table equal to
    rank-ordered SGD."""
    n, d, world, lr = 48, 4, 3, 0.5
    ref = ShardedEmbeddingTable.reference_rows(n, d, seed=4)

    def fn(ctx, rank):
        t = ShardedEmbeddingTable(n, d, rank=rank, world=world, seed=4,
                                  lr=lr, replicas=1, serve=True)
        try:
            t.sync_addresses(ctx)
            full = _identity_batch(n)
            pooled = t.lookup(full)
            np.testing.assert_allclose(pooled, ref, rtol=1e-5, atol=1e-6)
            g = np.full((n, d), float(rank + 1), np.float32)
            t.backward(full, g)
            t.flush(ctx)
            after = t.lookup(full)        # cache was dropped by the apply
            ctx.allreduce(np.zeros(1, np.float32), "sum")  # pre-close sync
            return after.tobytes(), t.resident_bytes
        finally:
            t.close()

    results, errors = _cohort(world, fn)
    assert not errors, errors
    blobs = {r[0] for r in results}
    assert len(blobs) == 1                # bit-identical across ranks
    after = np.frombuffer(results[0][0], np.float32).reshape(n, d)
    # rank-ordered applies: ref - lr*1 - lr*2 - lr*3 per component
    np.testing.assert_allclose(after, ref - lr * 6.0, rtol=1e-5, atol=1e-5)
    # replication: each rank resides 2/3 of the table, not all of it
    total = ref.nbytes
    for _, resident in results:
        assert resident == total * 2 // 3


# ---------------------------------------------------------------------------
# chaos: kill one rank mid-run; bit-consistent with the no-kill run
# ---------------------------------------------------------------------------

def _libsvm(tmp_path, rows=300):
    rng = np.random.default_rng(0)
    path = tmp_path / "embed.libsvm"
    with open(path, "w") as f:
        for r in range(rows):
            k = int(rng.integers(1, 5))
            idx = np.sort(rng.choice(3000, size=k, replace=False))
            f.write(f"{r % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    return str(path)


def _run_embed_cohort(uri, tmp_path, tag, kill):
    """Run examples/train_embed_shard.py as a 3-rank subprocess cohort;
    when ``kill``, rank 2 dies entering epoch 1 (after epoch 0 is synced
    and checkpointed) and is respawned with a bumped attempt."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    world = 3
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    tenv = tracker.worker_envs()
    ckpt = tmp_path / f"ckpt_{tag}"
    ckpt.mkdir()
    base = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu",
            "DMLC_TRACKER_URI": tenv["DMLC_TRACKER_URI"],
            "DMLC_TRACKER_PORT": str(tenv["DMLC_TRACKER_PORT"]),
            "DMLC_ELASTIC_BASE_PORT": str(free_port()),
            "DMLC_ELASTIC_DATA_PLANE": "0",
            "DMLC_CHECKPOINT_DIR": str(ckpt),
            "DMLC_CONNECT_TIMEOUT": "120", "DMLC_RECOVER_TIMEOUT": "300"}
    base.pop("DMLC_FAULT_SPEC", None)
    cmd = [sys.executable,
           os.path.join(repo, "examples", "train_embed_shard.py"),
           f"file://{uri}", "--epochs", "3", "--features", "512",
           "--dim", "8", "--batch-rows", "64"]

    def spawn(i, attempt, fault=None):
        env = dict(base, DMLC_TASK_ID=f"e{i}",
                   DMLC_NUM_ATTEMPT=str(attempt))
        if fault:
            env["DMLC_FAULT_SPEC"] = fault
        return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [spawn(i, 0, "embed.epoch:error=1.0:times=1:after=1"
                   if (kill and i == 2) else None) for i in range(world)]
    outs = []
    if kill:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and procs[2].poll() is None:
            time.sleep(0.2)
        crash_out, crash_err = procs[2].communicate()
        assert procs[2].returncode == 7, \
            f"victim rc={procs[2].returncode}: {crash_err[-2000:]}"
        assert "CRASHING at epoch 1" in crash_out
        outs.append(crash_out)
        procs = [procs[0], procs[1], spawn(2, 1)]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-2000:]
        outs.append(out)
    tracker.join(timeout=30)
    return [json.loads(ln[6:]) for out in outs
            for ln in out.splitlines() if ln.startswith("EPOCH ")]


def test_embed_chaos_kill_is_bit_consistent(tmp_path):
    """THE subsystem proof: killing a rank between epochs changes NOTHING
    observable.  The reborn rank recomputes its join epoch from the rabit
    position checkpoint + remote lookups (survivor replicas serve its
    shard), the resharder moves its shard back without reading any
    checkpoint, and every (rank, epoch) loss and state digest is
    bit-equal to the same cohort run without the kill."""
    uri = _libsvm(tmp_path)
    nk = {(r["rank"], r["epoch"]): r
          for r in _run_embed_cohort(uri, tmp_path, "nk", kill=False)}
    kk = {(r["rank"], r["epoch"]): r
          for r in _run_embed_cohort(uri, tmp_path, "k", kill=True)}
    keys = [(r, e) for r in range(3) for e in range(3)]
    assert sorted(nk) == sorted(kk) == keys   # every epoch exactly once
    for key in keys:
        assert nk[key]["loss"] == kk[key]["loss"], key
        assert nk[key]["digest"] == kk[key]["digest"], key
    for r in kk.values():
        assert r["from_ckpt"] == 0            # zero checkpoint reads, ever
        # no rank ever resides the whole 512x8xf32 table
        assert 0 < r["resident"] < 512 * 8 * 4
    # the kill epoch rebuilt the mesh and moved the shard from peers
    reborn = kk[(2, 1)]
    assert reborn["rebuilt"] and reborn["gen"] == 1
    assert reborn["from_peers"] >= 1 and reborn["bytes_moved"] > 0
    assert not nk[(2, 1)]["rebuilt"]
