"""JSON reader/writer tests — mirrors reference ``unittest_json.cc`` shape:
round-trips of STL-like compositions, struct helper contract, any maps."""

import io

import pytest

from dmlc_core_tpu.utils.json import (
    AnyValue,
    JSONError,
    JSONObjectReadHelper,
    JSONReader,
    JSONWriter,
    json_dumps,
    json_loads,
    read_any,
    register_any_type,
)


def test_scalar_roundtrip():
    for v in [0, 1, -3, 3.5, True, False, None, "hello", 'quote " slash \\']:
        assert json_loads(json_dumps(v)) == v


def test_nested_composition_roundtrip():
    v = {"a": [1, 2, 3], "b": {"x": [1.5, -2.5], "y": "str"},
         "c": [], "d": {}, "e": [[1], [2, 3]]}
    assert json_loads(json_dumps(v)) == v


def test_output_is_valid_stdlib_json():
    import json as stdjson
    v = {"k": [1, {"n": None, "b": True}], "s": "line\nbreak"}
    assert stdjson.loads(json_dumps(v)) == v


def test_reads_stdlib_output():
    import json as stdjson
    v = {"k": [1, 2], "nested": {"a": "b"}, "f": 1.25}
    assert json_loads(stdjson.dumps(v)) == v


def test_streaming_cursor_api():
    r = JSONReader('{"one": 1, "arr": [10, 20]}')
    r.begin_object()
    assert r.next_object_item() == "one"
    assert r.read_int() == 1
    assert r.next_object_item() == "arr"
    vals = []
    r.begin_array()
    while r.next_array_item():
        vals.append(r.read_int())
    assert vals == [10, 20]
    assert r.next_object_item() is None


def test_writer_streaming_api():
    w = JSONWriter()
    w.begin_object()
    w.write_object_keyvalue("a", 1)
    w.write_object_keyvalue("b", [True, None])
    w.end_object()
    assert json_loads(w.getvalue()) == {"a": 1, "b": [True, None]}


def test_error_has_line_number():
    with pytest.raises(JSONError, match="Line 2"):
        json_loads('{"a": 1,\n "b": }')


def test_unterminated_string():
    with pytest.raises(JSONError):
        json_loads('"abc')


def test_helper_required_and_unknown_fields():
    h = JSONObjectReadHelper()
    h.declare_field("name", lambda r: r.read_string())
    h.declare_optional_field("count", lambda r: r.read_int(), default=7)
    out = h.read_all_fields(JSONReader('{"name": "x"}'))
    assert out == {"name": "x", "count": 7}

    h2 = JSONObjectReadHelper()
    h2.declare_field("name")
    with pytest.raises(JSONError, match="missing required"):
        h2.read_all_fields(JSONReader("{}"))

    h3 = JSONObjectReadHelper()
    h3.declare_field("name")
    with pytest.raises(JSONError, match="unknown field"):
        h3.read_all_fields(JSONReader('{"name": "x", "bogus": 1}'))


def test_any_map_roundtrip():
    register_any_type("int", int, int)
    register_any_type("strlist", list, list)
    w = JSONWriter()
    w.begin_object()
    w.write_object_keyvalue("n", AnyValue("int", 42))
    w.write_object_keyvalue("l", AnyValue("strlist", ["a", "b"]))
    w.end_object()

    r = JSONReader(w.getvalue())
    r.begin_object()
    out = {}
    while True:
        k = r.next_object_item()
        if k is None:
            break
        out[k] = read_any(r)
    assert out["n"] == AnyValue("int", 42)
    assert out["l"] == AnyValue("strlist", ["a", "b"])


def test_unregistered_any_rejected():
    with pytest.raises(JSONError, match="not registered"):
        json_dumps(AnyValue("nope_never_registered", 1))


def test_reader_from_stream_object():
    r = JSONReader(io.StringIO('[1, "two", [3]]'))
    assert r.read() == [1, "two", [3]]


def test_unicode_escape():
    assert json_loads('"\\u0041\\u00e9"') == "Aé"


def test_surrogate_pair_from_stdlib():
    import json as stdjson
    s = "emoji \U0001f600 end"
    assert json_loads(stdjson.dumps(s)) == s


def test_large_int_exact_roundtrip():
    for v in [10**17 + 1, 2**63 - 1, -(2**62 + 3)]:
        assert json_loads(json_dumps(v)) == v
        r = JSONReader(json_dumps(v))
        assert r.read_int() == v


def test_control_chars_valid_json():
    import json as stdjson
    s = "bs\b ff\f bell\x07"
    assert stdjson.loads(json_dumps(s)) == s
    assert json_loads(json_dumps(s)) == s


def test_nonfinite_float_rejected():
    for v in [float("nan"), float("inf"), float("-inf")]:
        with pytest.raises(JSONError, match="non-finite"):
            json_dumps(v)


def test_helper_reuse_no_stale_values():
    h = JSONObjectReadHelper()
    h.declare_field("name", lambda r: r.read_string())
    h.declare_optional_field("count", lambda r: r.read_int(), default=7)
    assert h.read_all_fields(JSONReader('{"name": "a", "count": 5}')) == \
        {"name": "a", "count": 5}
    # second record omits count: default must apply, not the stale 5
    assert h.read_all_fields(JSONReader('{"name": "b"}')) == \
        {"name": "b", "count": 7}


def test_write_json_streaming_hook():
    class Point:
        def __init__(self, x, y):
            self.x, self.y = x, y

        def write_json(self, w):
            w.begin_object()
            w.write_object_keyvalue("x", self.x)
            w.write_object_keyvalue("y", self.y)
            w.end_object()

    assert json_loads(json_dumps(Point(1, 2))) == {"x": 1, "y": 2}


def test_read_any_exported_from_package():
    from dmlc_core_tpu import utils
    assert hasattr(utils, "read_any")
