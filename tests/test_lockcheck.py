"""lockcheck: runtime lock-order checker + regression tests for the
real ordering bugs the ISSUE 9 sweep fixed (rabit topology races,
ingest frame-holder publication).
"""

import ast
import os
import threading
import time

import pytest

from dmlc_core_tpu.utils import lockcheck


@pytest.fixture(autouse=True)
def _clean_slate():
    # snapshot/restore rather than plain reset: under a DMLC_LOCKCHECK=1
    # tier-1 run the checker state belongs to the whole process, and this
    # module's synthetic inversions must not leak into (or wipe) it
    with lockcheck._meta:
        saved = (dict(lockcheck._graph), dict(lockcheck._names),
                 list(lockcheck._inversions), list(lockcheck._long_holds),
                 set(lockcheck._reported_pairs))
    lockcheck.reset()
    yield
    with lockcheck._meta:
        lockcheck._graph.clear()
        lockcheck._graph.update(saved[0])
        lockcheck._names.clear()
        lockcheck._names.update(saved[1])
        lockcheck._inversions[:] = saved[2]
        lockcheck._long_holds[:] = saved[3]
        lockcheck._reported_pairs.clear()
        lockcheck._reported_pairs.update(saved[4])
    assert lockcheck._held() == [], "test leaked a held-lock entry"


def _run(*fns):
    threads = [threading.Thread(target=fn, daemon=True) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()


# -- inversion detection ----------------------------------------------------

def test_two_thread_inversion_detected():
    a = lockcheck.make_lock("lock-a")
    b = lockcheck.make_lock("lock-b")
    first_done = threading.Event()

    def t1():                      # establishes the a → b ordering
        with a:
            with b:
                pass
        first_done.set()

    def t2():                      # then inverts it: b → a
        first_done.wait(10)
        with b:
            with a:
                pass

    _run(t1, t2)
    rep = lockcheck.report()
    assert len(rep["inversions"]) == 1
    inv = rep["inversions"][0]
    assert inv["held"] == "lock-b"
    assert inv["acquiring"] == "lock-a"
    assert "test_lockcheck.py" in inv["site"]


def test_consistent_ordering_is_clean():
    a = lockcheck.make_lock("ordered-a")
    b = lockcheck.make_lock("ordered-b")

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    _run(worker, worker)
    rep = lockcheck.report()
    assert rep["inversions"] == []
    assert rep["edges"] >= 1       # a → b was learned


def test_inversion_reported_once_per_pair():
    a = lockcheck.make_lock("dedup-a")
    b = lockcheck.make_lock("dedup-b")
    with a:
        with b:
            pass
    for _ in range(3):
        with b:
            with a:
                pass
    assert len(lockcheck.report()["inversions"]) == 1


def test_three_lock_transitive_cycle():
    # a→b and b→c recorded; acquiring a while holding c closes the cycle
    a = lockcheck.make_lock("tri-a")
    b = lockcheck.make_lock("tri-b")
    c = lockcheck.make_lock("tri-c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    inv = lockcheck.report()["inversions"]
    assert len(inv) == 1
    assert inv[0]["held"] == "tri-c" and inv[0]["acquiring"] == "tri-a"


def test_long_hold_flagged(monkeypatch):
    monkeypatch.setenv("DMLC_LOCKCHECK_HOLD_S", "0.01")
    slow = lockcheck.make_lock("slow-lock")
    with slow:
        time.sleep(0.05)
    holds = lockcheck.report()["long_holds"]
    assert any(h["lock"] == "slow-lock" and h["hold_s"] >= 0.01
               for h in holds)


# -- lock protocol compatibility -------------------------------------------

def test_rlock_reentrancy():
    rl = lockcheck.make_rlock("re-lock")
    with rl:
        with rl:
            assert rl._is_owned()
    assert not rl._is_owned()
    assert lockcheck.report()["inversions"] == []


def test_condition_on_instrumented_lock():
    lk = lockcheck.make_lock("cond-lock")
    cv = threading.Condition(lk)
    box = []

    def consumer():
        with cv:
            while not box:
                cv.wait(timeout=10)
            box.append("seen")

    def producer():
        time.sleep(0.02)
        with cv:
            box.append("item")
            cv.notify()

    _run(consumer, producer)
    assert box == ["item", "seen"]
    assert lockcheck.report()["inversions"] == []


def test_nonblocking_acquire_failure_records_nothing():
    lk = lockcheck.make_lock("try-lock")
    grabbed = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            grabbed.set()
            release.wait(10)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    grabbed.wait(10)
    assert lk.acquire(blocking=False) is False
    release.set()
    t.join(10)
    assert lockcheck._held() == []


# -- factory scoping --------------------------------------------------------

@pytest.fixture
def shim():
    was = lockcheck.installed()
    lockcheck.install()
    yield
    if not was:
        lockcheck.uninstall()


def test_factory_shims_package_code_only(shim):
    # this test file lives outside the package: stays raw
    assert not isinstance(threading.Lock(), lockcheck.InstrumentedLock)
    # same call compiled under a package filename: instrumented + named
    fake = os.path.join(lockcheck._PKG_DIR, "pipeline",
                        "_lockcheck_probe.py")
    ns = {}
    exec(compile("import threading\nmade = threading.Lock()\n",
                 fake, "exec"), ns)
    assert isinstance(ns["made"], lockcheck.InstrumentedLock)
    assert "pipeline/_lockcheck_probe.py:2" in ns["made"].name
    # Event() allocates its lock inside threading.py: stays raw
    ev = threading.Event()
    assert not isinstance(ev._cond._lock, lockcheck.InstrumentedLock)
    # the reporting plane stays raw too — instrumenting metrics' own
    # locks would self-deadlock snapshot() when hold_s is observed
    fake_metrics = os.path.join(lockcheck._PKG_DIR, "utils", "metrics.py")
    ns2 = {}
    exec(compile("import threading\nmade = threading.Lock()\n",
                 fake_metrics, "exec"), ns2)
    assert not isinstance(ns2["made"], lockcheck.InstrumentedLock)


def test_package_queue_works_under_shim(shim):
    from dmlc_core_tpu.utils.concurrency import ConcurrentBlockingQueue
    q = ConcurrentBlockingQueue(max_size=8)
    got = []

    def pusher():
        for i in range(32):
            q.push(i)

    def popper():
        for _ in range(32):
            got.append(q.pop(timeout=10))

    _run(pusher, popper)
    assert sorted(got) == list(range(32))
    assert lockcheck.report()["inversions"] == []


def test_install_uninstall_idempotent():
    was = lockcheck.installed()
    lockcheck.install()
    lockcheck.install()
    assert lockcheck.installed()
    if not was:
        lockcheck.uninstall()
        assert not lockcheck.installed()
        assert threading.Lock is lockcheck._REAL_LOCK


def test_enabled_parses_env(monkeypatch):
    monkeypatch.setenv("DMLC_LOCKCHECK", "1")
    assert lockcheck.enabled()
    monkeypatch.setenv("DMLC_LOCKCHECK", "0")
    assert not lockcheck.enabled()
    monkeypatch.delenv("DMLC_LOCKCHECK")
    assert not lockcheck.enabled()


# -- regressions for the real ordering bugs the sweep fixed -----------------

def _bare_rabit_ctx():
    from dmlc_core_tpu.parallel.rabit import RabitContext
    ctx = RabitContext.__new__(RabitContext)
    ctx._peer_lock = threading.Lock()
    ctx._target_gen = 0
    ctx._addresses = {}
    return ctx


def test_rabit_topology_never_rolls_back():
    # the bug: _register wrote _target_gen/_addresses bare, so a
    # reset_links push racing ahead of the registration reply was
    # clobbered with the stale pre-reset topology
    ctx = _bare_rabit_ctx()
    ctx._target_gen = 5                         # pushed by reset_links
    ctx._addresses = {0: ("pushed-host", 9000)}
    ctx._apply_topology(3, {0: ("stale-host", 1), 1: ("filler", 2)})
    assert ctx._target_gen == 5
    assert ctx._addresses[0] == ("pushed-host", 9000)   # kept
    assert ctx._addresses[1] == ("filler", 2)           # gap filled
    ctx._apply_topology(7, {0: ("new-host", 3)})
    assert ctx._target_gen == 7
    assert ctx._addresses == {0: ("new-host", 3)}


def test_rabit_topology_applied_under_peer_lock():
    ctx = _bare_rabit_ctx()

    class Probe:
        entered = 0

        def __enter__(self):
            Probe.entered += 1

        def __exit__(self, *exc):
            pass

    ctx._peer_lock = Probe()
    ctx._apply_topology(1, {0: ("h", 1)})
    assert Probe.entered == 1
    assert ctx._addresses == {0: ("h", 1)}


def test_ingest_frame_holder_published_under_gen_lock():
    # structural regression: every _frame_holder write outside __init__
    # must sit inside `with self._gen_lock:` (readers swap the holder's
    # state from the restart path under that lock)
    import dmlc_core_tpu.pipeline.ingest_service as mod
    src = os.path.abspath(mod.__file__)
    with open(src, encoding="utf-8") as f:
        tree = ast.parse(f.read())

    def is_self_attr(node, attr):
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    def walk(node, fn_name, under_lock, bad):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_name, under_lock = node.name, False
        if isinstance(node, ast.With):
            if any(is_self_attr(item.context_expr, "_gen_lock")
                   for item in node.items):
                under_lock = True
        if isinstance(node, ast.Assign) and fn_name != "__init__":
            for tgt in node.targets:
                if is_self_attr(tgt, "_frame_holder") and not under_lock:
                    bad.append((fn_name, node.lineno))
        for child in ast.iter_child_nodes(node):
            walk(child, fn_name, under_lock, bad)

    bad = []
    walk(tree, "<module>", False, bad)
    assert bad == [], f"_frame_holder written without _gen_lock: {bad}"
