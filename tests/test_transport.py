"""Transport overhaul (PR 15): zero-copy UNIX lanes, vectored wire
sends, negotiated compression, fd-passing, and the planned reshard
round schedule.

Wire-compatibility is the hard invariant: every lane and codec must
deliver frames byte-identical to the single-host baseline, and peers
from BEFORE the negotiation existed must interoperate with peers from
after — proven here with a hand-rolled legacy client and a hand-rolled
legacy worker speaking the seed framing verbatim."""

import hashlib
import json
import socket
import struct
import threading
import time
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu import transport  # noqa: E402
from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.pipeline.data_service import (  # noqa: E402
    DataServiceLoader, DataServiceWorker, Dispatcher, dispatcher_rpc)
from dmlc_core_tpu.pipeline.data_service.worker import (  # noqa: E402
    CTRL_SHARD_BEGIN, CTRL_SHARD_END)
from dmlc_core_tpu.pipeline.device_loader import (  # noqa: E402
    DeviceLoader, _fused_words_meta, _put_fused_buf)
from dmlc_core_tpu.pipeline.ingest_service import _recv_exact  # noqa: E402
from dmlc_core_tpu.transport import (  # noqa: E402
    FRAME, NO_ROWS, FrameWriter, Transfer, available_codecs, choose_codec,
    negotiate_reply, plan_rounds)
from dmlc_core_tpu.transport.frames import CTRL_TRANSPORT  # noqa: E402
from dmlc_core_tpu.utils import clear_faults, inject_faults  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


def _counter(name):
    return metrics.counter(name).value


ROWS = 300
BATCH_ROWS = 32
NNZ_CAP = 1024


def _libsvm(tmp_path, rows=ROWS):
    rng = np.random.default_rng(11)
    path = tmp_path / "tp.libsvm"
    with open(path, "w") as f:
        for i in range(rows):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    return str(path)


def _spec(uri, num_parts, **extra):
    spec = {"uri": uri, "fmt": "libsvm", "num_parts": num_parts,
            "batch_rows": BATCH_ROWS, "nnz_cap": NNZ_CAP}
    spec.update(extra)
    return spec


def _frame_digest(buf, meta):
    words = _fused_words_meta(BATCH_ROWS, int(meta))
    return hashlib.sha1(np.asarray(buf)[:words].tobytes()).hexdigest()


def _drain(loader):
    labels, digests = Counter(), Counter()
    for kind, buf, meta, _rows in loader:
        assert kind == "fused"
        digests[_frame_digest(buf, meta)] += 1
        out = _put_fused_buf(
            np.asarray(buf)[: _fused_words_meta(BATCH_ROWS, int(meta))],
            BATCH_ROWS, int(meta))
        labels.update(int(x) for x in np.asarray(out["labels"])
                      if int(x) > 0)
        loader.recycle(buf)
    return labels, digests


def _single_host_baseline(uri, num_parts):
    labels, digests = Counter(), Counter()
    for part in range(num_parts):
        loader = DeviceLoader(
            create_parser(uri, part, num_parts, "libsvm", nthreads=1,
                          threaded=False),
            batch_rows=BATCH_ROWS, nnz_cap=NNZ_CAP, emit="host")
        try:
            for kind, buf, meta, _rows in loader:
                digests[_frame_digest(buf, meta)] += 1
                out = _put_fused_buf(
                    np.asarray(buf)[: _fused_words_meta(BATCH_ROWS,
                                                        int(meta))],
                    BATCH_ROWS, int(meta))
                labels.update(int(x) for x in np.asarray(out["labels"])
                              if int(x) > 0)
        finally:
            loader.close()
    return labels, digests


def _fleet_epoch(tmp_path, num_parts=2, workers=1, epochs=1, spec_extra=None,
                 key_out=None):
    """One dispatcher + N workers + one consumer; returns the per-epoch
    (labels, digests) list."""
    uri = _libsvm(tmp_path)
    out = []
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        ws = [DataServiceWorker(d.address).start() for _ in range(workers)]
        try:
            ldr = DataServiceLoader(
                d.address, _spec(uri, num_parts, **(spec_extra or {})))
            try:
                for _ in range(epochs):
                    out.append(_drain(ldr))
            finally:
                ldr.close()
        finally:
            for w in ws:
                w.kill()
    if key_out is not None:
        key_out.append(uri)
    return out


# ---------------------------------------------------------------------------
# units: round planner
# ---------------------------------------------------------------------------

def test_plan_rounds_balances_holders_and_bounds_bytes():
    """First-fit-decreasing: the 300-byte transfer fills round 0 alone
    (budget 350 leaves no room for a 100), then the five 100-byte pulls
    from one holder pack two per round under the per-holder slot cap."""
    ts = [Transfer("p", i, i + 1, 0, (), nbytes=100, tag=i)
          for i in range(5)]
    ts.append(Transfer("q", 0, 3, 1, (), nbytes=300, tag=9))
    rounds = plan_rounds(ts, max_bytes=350, per_holder=2)
    shape = [sorted(t.tag for t in rnd) for rnd in rounds]
    assert shape == [[9], [0, 1], [2, 3], [4]]
    for rnd in rounds:
        assert sum(t.nbytes for t in rnd) <= 350


def test_plan_rounds_oversize_and_unbounded():
    """A transfer bigger than the budget still ships — alone in its own
    round; with no byte bound only the holder cap splits rounds."""
    big = Transfer("x", 0, 10, 0, (), nbytes=10_000, tag="big")
    small = Transfer("y", 0, 1, 0, (), nbytes=10, tag="small")
    rounds = plan_rounds([big, small], max_bytes=100, per_holder=4)
    assert [t.tag for t in rounds[0]] == ["big"]
    assert [t.tag for t in rounds[1]] == ["small"]
    # unbounded bytes, per_holder=1: one transfer per round per holder
    rounds = plan_rounds([big, small], max_bytes=None, per_holder=1)
    assert [len(r) for r in rounds] == [1, 1]
    # fully unbounded: everything in one round
    rounds = plan_rounds([big, small], max_bytes=None, per_holder=0)
    assert [len(r) for r in rounds] == [2]


def test_plan_rounds_deterministic_under_input_order():
    """The plan is a pure function of the transfer set — every cohort
    member computes the same schedule without communicating."""
    ts = [Transfer(f"p{i % 3}", i, i + 2, i % 4, (), nbytes=50 + 13 * i,
                   tag=i) for i in range(12)]
    a = plan_rounds(list(ts), max_bytes=200, per_holder=2)
    b = plan_rounds(list(reversed(ts)), max_bytes=200, per_holder=2)
    assert [[t.tag for t in r] for r in a] == [[t.tag for t in r] for r in b]


def test_remap_deltas_excludes_resident_rows():
    from dmlc_core_tpu.parallel.mesh import remap_deltas, remap_rows
    # 3 -> 2 shrink over 10 rows: each survivor keeps its resident rows
    assert remap_deltas(10, 3, 2) == [[(1, 4, 5)], [(2, 7, 10)]]
    # identity resize moves nothing
    assert remap_deltas(10, 3, 3) == [[], [], []]
    # deltas are always a subset of the full feed map
    for new_rank, (full, delta) in enumerate(zip(remap_rows(10, 2, 3),
                                                 remap_deltas(10, 2, 3))):
        assert set(delta) <= set(full)


# ---------------------------------------------------------------------------
# units: codec negotiation + frame writer
# ---------------------------------------------------------------------------

def test_choose_codec_and_negotiate_fallback():
    assert "zlib" in available_codecs()     # stdlib floor, always present
    assert choose_codec(["zlib"], ["zlib"], ["zlib"]) == "zlib"
    # peer lacks the wanted codec: fall back to UNCOMPRESSED, never to a
    # codec the caller didn't ask for
    f0 = _counter("transport.codec_fallbacks")
    assert choose_codec(["zstd"], ["zlib"], ["zlib"]) is None
    neg = negotiate_reply({"codecs": ["zlib"], "want": "zstd",
                           "lane": "tcp", "fdpass": False},
                          uds=False, fdpass_ok=False)
    assert neg["compress"] is None and neg["fdpass"] is False
    assert _counter("transport.codec_fallbacks") - f0 >= 1
    # no wish at all: no fallback counted, no compression
    neg = negotiate_reply({"codecs": ["zlib"], "want": None,
                           "lane": "tcp", "fdpass": False},
                          uds=False, fdpass_ok=False)
    assert neg["compress"] is None


def test_frame_writer_vectored_send_is_byte_identical():
    """A queued control frame + data frame leave in ONE sendmsg whose
    bytes equal the seed's sequential sendall layout exactly."""
    a, b = socket.socketpair()
    c0 = _counter("transport.frames_coalesced")
    try:
        w = FrameWriter(a)
        payload = np.arange(64, dtype=np.uint32).tobytes()
        w.control(3, CTRL_SHARD_BEGIN, 7)
        w.send_frame(123, 64, 5, payload)
        w.control(3, CTRL_SHARD_END, 1)
        w.control(0, 0, 0)
        w.flush()
        expect = (FRAME.pack(3, CTRL_SHARD_BEGIN, 7)
                  + FRAME.pack(123, 64, 5) + payload
                  + FRAME.pack(3, CTRL_SHARD_END, 1)
                  + FRAME.pack(0, 0, 0))
        got = _recv_exact(b, len(expect))
        assert bytes(got) == expect
    finally:
        a.close()
        b.close()
    assert _counter("transport.frames_coalesced") - c0 >= 4


def test_frame_writer_compression_roundtrip():
    """Compressed data frames keep the UNCOMPRESSED word count in the
    header and carry a trailing u32 wire length; clen=0 marks a frame
    that didn't shrink and rides raw."""
    import zlib
    a, b = socket.socketpair()
    try:
        w = FrameWriter(a, compress="zlib")
        payload = np.zeros(256, dtype=np.uint32).tobytes()   # compresses
        w.send_frame(9, 256, NO_ROWS, payload)
        hdr = _recv_exact(b, FRAME.size)
        meta, words, rows = FRAME.unpack(bytes(hdr))
        assert (meta, words, rows) == (9, 256, NO_ROWS)
        (clen,) = struct.unpack("<I", bytes(_recv_exact(b, 4)))
        assert 0 < clen < len(payload)
        assert zlib.decompress(bytes(_recv_exact(b, clen))) == payload
    finally:
        a.close()
        b.close()
    with pytest.raises(ValueError):
        FrameWriter(None, compress="not-a-codec")


def test_sock_buf_knob_applied(monkeypatch):
    from dmlc_core_tpu.parallel.reshard import _apply_sock_buf
    monkeypatch.setenv("DMLC_SOCK_BUF_KB", "256")
    s = socket.socket()
    try:
        _apply_sock_buf(s)
        # kernels report >= the requested size (linux doubles it)
        assert s.getsockopt(socket.SOL_SOCKET,
                            socket.SO_SNDBUF) >= 256 * 1024
        assert s.getsockopt(socket.SOL_SOCKET,
                            socket.SO_RCVBUF) >= 256 * 1024
    finally:
        s.close()


# ---------------------------------------------------------------------------
# lanes: UNIX vs TCP byte-identical, chaos fallback, fd-passing
# ---------------------------------------------------------------------------

def test_uds_lane_matches_tcp_byte_identical(tmp_path, monkeypatch):
    """The same dataset over the TCP path and over the colocated UNIX
    lane: labels exactly once and frames byte-identical both ways."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            assert w.uds_path is not None    # lane bound by default
            tcp0 = _counter("transport.lane.tcp")
            monkeypatch.setenv("DMLC_TRANSPORT_LANE", "0")
            ldr = DataServiceLoader(d.address, _spec(uri, 2))
            labels, digests = _drain(ldr)
            ldr.close()
            assert labels == base_labels and digests == base_digests
            assert _counter("transport.lane.tcp") - tcp0 >= 1
            uds0 = _counter("transport.lane.uds")
            monkeypatch.delenv("DMLC_TRANSPORT_LANE")
            ldr = DataServiceLoader(d.address, _spec(uri, 2))
            labels, digests = _drain(ldr)
            ldr.close()
            assert labels == base_labels and digests == base_digests
            assert _counter("transport.lane.uds") - uds0 >= 1


def test_wire_compression_negotiated_and_fallback(tmp_path, monkeypatch):
    """DMLC_WIRE_COMPRESS=zlib streams compressed frames that decompress
    to the exact baseline; asking for a codec this host lacks degrades
    to uncompressed (counted), never to a broken stream."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    monkeypatch.setenv("DMLC_TRANSPORT_LANE", "0")   # exercise TCP framing
    monkeypatch.setenv("DMLC_WIRE_COMPRESS", "zlib")
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            ldr = DataServiceLoader(d.address, _spec(uri, 2))
            labels, digests = _drain(ldr)
            ldr.close()
            assert labels == base_labels and digests == base_digests
            ratio = metrics.gauge("transport.compress_ratio").value
            assert 0 < ratio < 1.0       # sparse int frames shrink
            if "zstd" not in available_codecs():
                f0 = _counter("transport.codec_fallbacks")
                monkeypatch.setenv("DMLC_WIRE_COMPRESS", "zstd")
                ldr = DataServiceLoader(d.address, _spec(uri, 2))
                labels, digests = _drain(ldr)
                ldr.close()
                assert labels == base_labels and digests == base_digests
                assert _counter("transport.codec_fallbacks") - f0 >= 1


def test_fdpass_shard_crosses_as_descriptor(tmp_path):
    """A page-cache-backed shard on a UNIX lane crosses as ONE
    SCM_RIGHTS descriptor: epoch 2 is served from the cache the worker
    built in epoch 1, zero payload bytes on the wire, frames still
    byte-identical and exactly-once."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 1)
    cache = str(tmp_path / "shard0.pages")
    z0 = _counter("transport.bytes_zero_copy")
    s0 = _counter("data_service.worker.fdpass_shards")
    dup0 = _counter("data_service.client.dup_frames")
    epochs = _fleet_epoch(tmp_path, num_parts=1, epochs=2,
                          spec_extra={"cache": cache})
    for labels, digests in epochs:
        assert labels == base_labels
        assert digests == base_digests
    assert _counter("data_service.worker.fdpass_shards") - s0 >= 1
    assert _counter("transport.bytes_zero_copy") - z0 > 0
    assert _counter("data_service.client.dup_frames") - dup0 == 0


def test_lane_fault_mid_epoch_falls_back_to_tcp(tmp_path):
    """Chaos: the UNIX lane dies mid-epoch (DMLC_FAULT_SPEC).  The
    consumer marks the lane down, redials over TCP, and the exactly-once
    ledger holds — every row once, every frame byte-identical."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    fb0 = _counter("transport.lane_fallbacks")
    f0 = _counter("faults.transport.lane.errors")
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            with inject_faults("transport.lane:error=1:times=1:after=3"):
                ldr = DataServiceLoader(d.address, _spec(uri, 2))
                labels, digests = _drain(ldr)
                ldr.close()
    assert _counter("faults.transport.lane.errors") - f0 == 1
    assert labels == base_labels          # every row exactly once
    assert digests == base_digests        # every frame byte-identical
    assert _counter("transport.lane_fallbacks") - fb0 >= 1


# ---------------------------------------------------------------------------
# mixed-version interop: the negotiation must be invisible to old peers
# ---------------------------------------------------------------------------

def test_legacy_client_against_new_worker(tmp_path):
    """A consumer from before the negotiation existed: raw hello with NO
    "transport" key.  The new worker must serve the seed framing
    verbatim — no CTRL_TRANSPORT frame, no compression, no trailers."""
    from dmlc_core_tpu.parallel.tracker import send_json
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            key = dispatcher_rpc(d.address, {
                "cmd": "register_dataset", "spec": _spec(uri, 2)})["key"]
            listing = dispatcher_rpc(d.address, {"cmd": "list_workers"})
            (jobid, addr), = listing["workers"].items()
            labels, digests = Counter(), Counter()
            with socket.create_connection(tuple(addr), timeout=10) as s:
                s.settimeout(30.0)
                send_json(s, {"key": key, "epoch": 0})   # seed-era hello
                while True:
                    meta, words, rows = FRAME.unpack(
                        bytes(_recv_exact(s, FRAME.size)))
                    assert words != CTRL_TRANSPORT, \
                        "negotiation reply leaked to a legacy consumer"
                    if words == 0:
                        break
                    if words in (CTRL_SHARD_BEGIN, CTRL_SHARD_END):
                        continue
                    buf = np.frombuffer(
                        bytes(_recv_exact(s, words * 4)), dtype=np.uint32)
                    digests[_frame_digest(buf, meta)] += 1
                    out = _put_fused_buf(buf, BATCH_ROWS, int(meta))
                    labels.update(int(x) for x in np.asarray(out["labels"])
                                  if int(x) > 0)
    assert labels == base_labels
    assert digests == base_digests


def test_new_client_against_legacy_worker(tmp_path):
    """A worker from before the negotiation existed: ignores the
    "transport" hello key, never replies CTRL_TRANSPORT, streams seed
    framing with raw sendall.  The new consumer must accept it as-is."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    stop = threading.Event()

    def legacy_worker(dispatcher_addr):
        """The seed-era serve loop, hand-rolled: JSON hello in, struct
        frames out via plain sendall, leases via dispatcher RPC."""
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                req = json.loads(conn.makefile("r").readline())
                key = req["key"]
                assert "transport" in req     # new hello carries the offer
                while not stop.is_set():
                    reply = dispatcher_rpc(dispatcher_addr, {
                        "cmd": "next_lease", "key": key,
                        "jobid": "legacy-w"})
                    if reply.get("status") == "done":
                        conn.sendall(FRAME.pack(0, 0, 0))
                        break
                    lease = reply.get("lease")
                    if lease is None:
                        time.sleep(0.05)
                        continue
                    part = int(lease["part"])
                    epoch_id = int(lease["lease_epoch"])
                    spec = lease["spec"]
                    loader = DeviceLoader(
                        create_parser(str(spec["uri"]), part,
                                      int(spec["num_parts"]),
                                      str(spec["fmt"]), nthreads=1,
                                      threaded=False),
                        batch_rows=int(spec["batch_rows"]),
                        nnz_cap=int(spec["nnz_cap"]), emit="host")
                    conn.sendall(FRAME.pack(part, CTRL_SHARD_BEGIN,
                                            epoch_id))
                    frames = 0
                    try:
                        for _kind, buf, meta, rows in loader:
                            words = _fused_words_meta(
                                int(spec["batch_rows"]), int(meta))
                            conn.sendall(FRAME.pack(
                                int(meta), words,
                                NO_ROWS if rows is None else int(rows)))
                            conn.sendall(memoryview(
                                np.asarray(buf)[:words]).cast("B"))
                            frames += 1
                    finally:
                        loader.close()
                    conn.sendall(FRAME.pack(part, CTRL_SHARD_END, frames))
                    dispatcher_rpc(dispatcher_addr, {
                        "cmd": "complete_lease", "key": key, "part": part,
                        "lease_epoch": epoch_id, "jobid": "legacy-w"})

    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=60.0) as d:
        d.start()
        dispatcher_rpc(d.address, {"cmd": "register_worker",
                                   "jobid": "legacy-w",
                                   "host": "127.0.0.1", "port": port})
        t = threading.Thread(target=legacy_worker, args=(d.address,),
                             daemon=True)
        t.start()
        try:
            ldr = DataServiceLoader(d.address, _spec(uri, 2))
            labels, digests = _drain(ldr)
            ldr.close()
        finally:
            stop.set()
            srv.close()
            t.join(timeout=10.0)
    assert labels == base_labels
    assert digests == base_digests
