"""Packing + DeviceLoader tests: fixed shapes, padding/truncation accounting,
epoch resets, row conservation."""

import os

import numpy as np
import pytest

from dmlc_core_tpu.data import RowBlockContainer, create_parser
from dmlc_core_tpu.pipeline import (DeviceLoader, PackStats, batch_slices,
                                    pack_flat, pack_rowmajor)


def block_of(rows):
    c = RowBlockContainer()
    for label, idx, vals in rows:
        c.push_row(label, idx, vals)
    return c.get_block()


def test_pack_flat_shapes_and_padding():
    blk = block_of([(1.0, [3, 7], [0.5, 1.5]), (0.0, [2], [2.0])])
    out = pack_flat(blk, batch_rows=4, nnz_cap=8)
    assert out["ids"].shape == (8,) and out["labels"].shape == (4,)
    np.testing.assert_array_equal(out["ids"][:3], [3, 7, 2])
    np.testing.assert_array_equal(out["segments"][:3], [0, 0, 1])
    np.testing.assert_array_equal(out["segments"][3:], [4, 4, 4, 4, 4])
    np.testing.assert_array_equal(out["weights"], [1, 1, 0, 0])
    assert out["vals"][3:].sum() == 0


def test_pack_flat_truncation():
    blk = block_of([(1.0, list(range(10)), [1.0] * 10),
                    (0.0, list(range(10, 16)), [1.0] * 6)])
    stats = PackStats()
    out = pack_flat(blk, batch_rows=2, nnz_cap=8, stats=stats)
    assert stats.truncated_values == 8
    # both rows keep some values
    assert (out["segments"] == 0).sum() > 0
    assert (out["segments"] == 1).sum() > 0


def test_waterfill_minimal_truncation():
    from dmlc_core_tpu.pipeline.packing import _waterfill
    # skewed rows: short rows keep everything, only the minimum is dropped
    keep = _waterfill(np.array([1, 12]), 10)
    assert keep.sum() == 10 and keep.tolist() == [1, 9]
    keep = _waterfill(np.array([2, 3, 10]), 9)
    assert keep.sum() == 9 and keep.tolist() == [2, 3, 4]
    keep = _waterfill(np.array([5, 5, 5]), 9)
    assert keep.sum() == 9
    assert _waterfill(np.array([2, 2]), 10).tolist() == [2, 2]  # no-op
    assert _waterfill(np.array([4, 4]), 1).sum() == 1


def test_pack_rowmajor():
    blk = block_of([(1.0, [3, 7, 9], None), (0.0, [2], [2.0])])
    out = pack_rowmajor(blk, batch_rows=3, k_cap=2)
    assert out["ids"].shape == (3, 2)
    np.testing.assert_array_equal(out["ids"][0], [3, 7])   # truncated to k_cap
    np.testing.assert_array_equal(out["vals"][0], [1, 1])  # implicit 1.0
    np.testing.assert_array_equal(out["ids"][1], [2, 0])
    np.testing.assert_array_equal(out["weights"], [1, 1, 0])


def test_batch_slices():
    blk = block_of([(float(i), [i], [1.0]) for i in range(10)])
    pieces = list(batch_slices(blk, 4))
    assert [p.size for p in pieces] == [4, 4, 2]
    assert pieces[2].labels.tolist() == [8.0, 9.0]


@pytest.fixture()
def libsvm_file(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "d.libsvm"
    with open(path, "w") as f:
        for i in range(1037):  # deliberately not a multiple of batch size
            n = int(rng.integers(1, 6))
            idx = sorted(rng.choice(100, n, replace=False).tolist())
            f.write(f"{i % 2} " + " ".join(f"{j}:1" for j in idx) + "\n")
    return str(path)


def test_device_loader_row_conservation(libsvm_file):
    with DeviceLoader(create_parser(libsvm_file), batch_rows=128,
                      nnz_cap=1024) as loader:
        batches = list(loader)
        rows = sum(int(np.asarray(b["weights"]).sum()) for b in batches)
        assert rows == 1037
        assert all(b["labels"].shape == (128,) for b in batches)
        # epochs
        loader.before_first()
        rows2 = sum(int(np.asarray(b["weights"]).sum()) for b in loader)
        assert rows2 == 1037
    assert loader.stats.rows >= 1037


def test_device_loader_transfer_pool_ordered(libsvm_file):
    """put_threads>1 (the multi-stream transfer pool for high-latency h2d
    links) must yield the exact same batch sequence as the single-thread
    path: same order, same contents, same epoch-reset behavior."""
    def collect(pt):
        with DeviceLoader(create_parser(libsvm_file), batch_rows=128,
                          nnz_cap=1024, put_threads=pt) as loader:
            first = [np.asarray(b["labels"]) for b in loader]
            loader.before_first()
            second = [np.asarray(b["labels"]) for b in loader]
        return first, second

    ref1, ref2 = collect(1)
    pool1, pool2 = collect(3)
    assert len(pool1) == len(ref1)
    for a, b in zip(ref1, pool1):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(ref2, pool2):
        np.testing.assert_array_equal(a, b)


def test_transfer_pool_error_propagates(libsvm_file, monkeypatch):
    from dmlc_core_tpu.utils.logging import DMLCError

    def failing(self, item, sync=True):
        raise RuntimeError("injected transfer failure")

    monkeypatch.setattr(DeviceLoader, "_transfer_item", failing)
    loader = DeviceLoader(create_parser(libsvm_file), batch_rows=128,
                          nnz_cap=1024, put_threads=2)
    with pytest.raises(DMLCError, match="injected transfer failure"):
        for _ in loader:
            pass
    loader.close()


def _loader_batches(path, wire_compact, batch_rows=128, nnz_cap=1024):
    with DeviceLoader(create_parser(path), batch_rows=batch_rows,
                      nnz_cap=nnz_cap, wire_compact=wire_compact) as loader:
        return [{k: np.asarray(v) for k, v in b.items()} for b in loader]


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k], err_msg=k)


def test_wire_compact_matches_plain(libsvm_file):
    """The v3 compact wire layout (bit-packed ids + dict-coded values) must
    reconstruct bit-identical batches to the plain v2 layout.  This file has
    small ids (8-bit width) and a 2-entry value dictionary (all 1.0)."""
    from dmlc_core_tpu import native
    if not native.has_compact():
        pytest.skip("native compact packer unavailable")
    _assert_batches_equal(_loader_batches(libsvm_file, False),
                          _loader_batches(libsvm_file, True))


def test_wire_compact_variants(tmp_path):
    """Compact-wire regimes beyond the easy case: (a) high-cardinality
    values forcing the raw-f32 dictionary fallback, (b) 20-bit ids, and
    (c) a near-int32-max id forcing the 32-bit width bucket — all must
    round-trip bit-exactly, including the flushed partial batch."""
    from dmlc_core_tpu import native
    if not native.has_compact():
        pytest.skip("native compact packer unavailable")
    rng = np.random.default_rng(7)
    path = tmp_path / "v.libsvm"
    with open(path, "w") as f:
        for i in range(600):
            n = int(rng.integers(3, 9))
            idx = sorted(rng.choice(1 << 20, n, replace=False).tolist())
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.6f}" for j in idx) + "\n")
        # one giant id → this batch's ids bucket to the full 32-bit width
        f.write("1 2147483646:0.5\n")
    _assert_batches_equal(_loader_batches(str(path), False),
                          _loader_batches(str(path), True))


def test_device_loader_drop_remainder(libsvm_file):
    with DeviceLoader(create_parser(libsvm_file), batch_rows=128,
                      nnz_cap=1024, drop_remainder=True) as loader:
        batches = list(loader)
    assert len(batches) == 1037 // 128
    for b in batches:
        assert int(np.asarray(b["weights"]).sum()) == 128


def test_device_loader_rowmajor_layout(libsvm_file):
    with DeviceLoader(create_parser(libsvm_file), batch_rows=64, nnz_cap=8,
                      layout="rowmajor") as loader:
        b = loader.next_batch()
        assert b["ids"].shape == (64, 8)
        assert b["vals"].shape == (64, 8)


def test_fused_h2d_matches_per_array(tmp_path):
    """The single-transfer fused path (v2 layout: row_ptr shipped, segments
    reconstructed on device by searchsorted) must produce bitwise-identical
    batch contents to the packed host arrays."""
    import numpy as np
    from dmlc_core_tpu.pipeline.device_loader import _fused_put
    rows, nnz = 64, 256
    rng = np.random.default_rng(0)
    rows_spec = []
    for i in range(50):                      # partial batch: 50 < 64 rows
        n = int(rng.integers(0, 6))          # includes empty rows
        idx = sorted(rng.choice(1000, n, replace=False).tolist())
        rows_spec.append((float(i % 2), idx, rng.random(n).astype(np.float32)))
    host = pack_flat(block_of(rows_spec), batch_rows=rows, nnz_cap=nnz)
    fused = _fused_put(host, rows, nnz)
    for k, v in host.items():
        np.testing.assert_array_equal(np.asarray(fused[k]), v, err_msg=k)
        assert fused[k].dtype == v.dtype, k


def test_ids_overflow_raises_and_id_mod_hashes():
    """VERDICT r1 #5: ids past int32 must raise, not wrap; id_mod gives the
    documented feature-hashing remap (reference keeps uint64 ids first-class,
    src/data.cc:131-147)."""
    from dmlc_core_tpu.utils import IdOverflowError
    big = np.uint64(2**33 + 5)
    blk = block_of([(1.0, np.array([1, big], np.uint64), [0.5, 1.5])])
    with pytest.raises(IdOverflowError):
        pack_flat(blk, batch_rows=2, nnz_cap=8)
    with pytest.raises(IdOverflowError):
        pack_rowmajor(blk, batch_rows=2, k_cap=8)
    out = pack_flat(blk, batch_rows=2, nnz_cap=8, id_mod=1000)
    np.testing.assert_array_equal(out["ids"][:2], [1, int(big) % 1000])


def test_native_packer_overflow_and_id_mod():
    from dmlc_core_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    from dmlc_core_tpu.utils import IdOverflowError
    big = np.uint64(2**33 + 5)
    blk = block_of([(1.0, np.array([1, big], np.uint64), [0.5, 1.5])])
    p = native.Packer(2, 8)
    with pytest.raises(IdOverflowError):
        list(p.feed(blk))
    p.close()
    p = native.Packer(2, 8, id_mod=1000)
    assert list(p.feed(blk)) == []          # one row: stays in carry
    buf, nnz_b = p.flush()
    assert nnz_b >= 2
    np.testing.assert_array_equal(buf[:2], [1, int(big) % 1000])
    p.close()


def test_native_packer_matches_python_pack(libsvm_file):
    """The native fused packer and the python pack path must produce
    identical device batches when no early-close pressure exists."""
    from dmlc_core_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    parser = create_parser(libsvm_file, threaded=False)
    blocks = [c.get_block() for c in parser]
    parser.close()
    rows_cap, nnz_cap = 256, 8192
    p = native.Packer(rows_cap, nnz_cap)
    fused = []
    for blk in blocks:
        fused.extend(p.feed(blk))
    tail = p.flush()
    if tail is not None:
        fused.append(tail)
    # python reference: accumulate blocks then pack slice by slice
    acc = RowBlockContainer()
    for blk in blocks:
        acc.push_block(blk)
    whole = acc.get_block()
    expect = []
    for s in batch_slices(whole, rows_cap):
        expect.append(pack_flat(s, rows_cap, nnz_cap))
    assert len(fused) == len(expect)
    for (buf, B), host in zip(fused, expect):
        # v2 layout: ids[B] | vals[B] | row_ptr[rows+1] | labels | weights;
        # B <= nnz_cap is the bucketed actual nnz, python pads to nnz_cap
        assert B <= nnz_cap
        np.testing.assert_array_equal(buf[:B], host["ids"][:B])
        assert not host["ids"][B:].any()
        np.testing.assert_array_equal(
            buf[B:2 * B].view(np.float32), host["vals"][:B])
        assert not host["vals"][B:].any()
        rp = buf[2 * B:2 * B + rows_cap + 1]
        np.testing.assert_array_equal(rp, host["row_ptr"])
        np.testing.assert_array_equal(
            buf[2 * B + rows_cap + 1:2 * B + 2 * rows_cap + 1]
            .view(np.float32), host["labels"])
        np.testing.assert_array_equal(
            buf[2 * B + 2 * rows_cap + 1:2 * B + 3 * rows_cap + 1]
            .view(np.float32), host["weights"])


def test_packer_early_close_on_nnz_pressure():
    """A batch closes early (padded) when the next row would overflow
    nnz_cap — no values are lost, unlike per-slice truncation."""
    from dmlc_core_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    rows = [(float(i), np.arange(5, dtype=np.uint64), None) for i in range(4)]
    blk = block_of(rows)
    p = native.Packer(4, 12)            # 2 rows of 5 fit per batch (10 <= 12)
    bufs = list(p.feed(blk))
    tail = p.flush()
    assert len(bufs) == 1 and tail is not None
    assert bufs[0][1] >= 10             # bucket covers the 10 staged values
    st = p.stats()
    assert st["rows"] == 4 and st["truncated_values"] == 0
    p.close()


def test_pack_roundtrip_fuzz():
    """Property fuzz (the reference's recordio-fuzz idea applied to the
    pack layer): random ragged CSR blocks — including empty rows, dense
    rows, valueless features and fields — must reconstruct exactly from
    BOTH packed layouts when nothing is truncated."""
    import numpy as np
    from dmlc_core_tpu.data.row_block import RowBlockContainer
    from dmlc_core_tpu.pipeline.packing import pack_flat, pack_rowmajor

    rng = np.random.default_rng(0)
    for trial in range(25):
        n = int(rng.integers(1, 40))
        c = RowBlockContainer()
        truth = []
        with_fields = bool(trial % 2)
        for r in range(n):
            k = int(rng.integers(0, 12))       # empty rows included
            idx = np.sort(rng.choice(10_000, size=k, replace=False))
            vals = rng.random(k).astype(np.float32)
            fields = (rng.integers(0, 7, k).astype(np.uint32)
                      if with_fields else None)
            c.push_row(float(r % 3), idx.astype(np.uint64), vals,
                       weight=1.0 + r,
                       fields=fields)
            truth.append((idx, vals, fields))
        blk = c.get_block()
        cap = int(blk.offsets[-1]) + 5
        rows_cap = n + int(rng.integers(0, 4))

        flat = pack_flat(blk, rows_cap, cap, want_fields=with_fields)
        for r, (idx, vals, fields) in enumerate(truth):
            m = flat["segments"] == r
            assert m.sum() == len(idx), (trial, r)
            np.testing.assert_array_equal(flat["ids"][m], idx)
            np.testing.assert_allclose(flat["vals"][m], vals, rtol=1e-6)
            if with_fields:
                np.testing.assert_array_equal(flat["fields"][m], fields)
            assert flat["labels"][r] == float(r % 3)
            assert flat["weights"][r] == 1.0 + r
        # padding rows weigh zero — silent-loss guard for the loss masks
        assert (flat["weights"][n:] == 0).all()

        kmax = max((len(t[0]) for t in truth), default=1) or 1
        rm = pack_rowmajor(blk, rows_cap, kmax, want_fields=with_fields)
        for r, (idx, vals, fields) in enumerate(truth):
            got = rm["vals"][r][rm["vals"][r] != 0]
            keep = vals != 0          # rowmajor padding is val==0
            np.testing.assert_allclose(np.sort(got), np.sort(vals[keep]),
                                       rtol=1e-6)
            gi = rm["ids"][r][:len(idx)]
            np.testing.assert_array_equal(gi, idx)
            if with_fields:
                np.testing.assert_array_equal(rm["fields"][r][:len(idx)],
                                              fields)


def test_wire_compact_property_fuzz(tmp_path):
    """Hypothesis-style generative sweep of the compact codec's regime
    space: id widths 1..31 bits, value cardinalities from binary to
    unbounded, row counts hitting every flush path — plain and compact
    wire must agree bit-exactly in all of them."""
    import itertools
    from dmlc_core_tpu import native
    if not native.has_compact():
        pytest.skip("native compact packer unavailable")
    rng = np.random.default_rng(11)
    id_spaces = [2, 1 << 7, 1 << 13, 1 << 20, (1 << 31) - 2]
    val_modes = ["binary", "quantized", "continuous"]
    rowcounts = [1, 127, 128, 300]
    for trial, (ids_hi, vmode, nrows) in enumerate(
            itertools.product(id_spaces, val_modes, rowcounts)):
        path = tmp_path / f"f{trial}.libsvm"
        with open(path, "w") as f:
            for r in range(nrows):
                n = int(rng.integers(1, 7))
                hi = min(ids_hi, 1 << 20)  # choice() cost; top id forced:
                idx = sorted(set(rng.integers(0, hi, n).tolist()))
                if r == 0 and ids_hi > hi:
                    idx = sorted(set(idx + [ids_hi - 1]))
                if vmode == "binary":
                    toks = [f"{j}:1" for j in idx]
                elif vmode == "quantized":
                    toks = [f"{j}:{rng.integers(0, 16) * 0.25}"
                            for j in idx]
                else:
                    toks = [f"{j}:{rng.random():.7f}" for j in idx]
                f.write(f"{r % 2} " + " ".join(toks) + "\n")
        _assert_batches_equal(_loader_batches(str(path), False),
                              _loader_batches(str(path), True))


def test_wire_compact_with_transfer_pool(libsvm_file):
    """The bench probes compact × put_threads on the chip; the combination
    (pool recycling + compact buffers) must agree with the plain single-
    thread path batch-for-batch."""
    from dmlc_core_tpu import native
    if not native.has_compact():
        pytest.skip("native compact packer unavailable")
    plain = _loader_batches(libsvm_file, False)
    with DeviceLoader(create_parser(libsvm_file), batch_rows=128,
                      nnz_cap=1024, wire_compact=True,
                      put_threads=4) as loader:
        pooled = [{k: np.asarray(v) for k, v in b.items()} for b in loader]
    _assert_batches_equal(plain, pooled)


def test_python_pack_preserves_row_order_across_blocks(monkeypatch):
    """Cross-block carry must not permute rows (code-review r4): once a
    partial tail is pending, later full slices may NOT jump ahead of it —
    predict's one-score-per-input-row contract depends on batch order ==
    input order.  Forced onto the python pack path (the native packer
    streams in order by construction)."""
    from dmlc_core_tpu import native
    monkeypatch.setattr(native, "has_packer", lambda: False)

    # blocks sized so tails interleave with full slices: 36-row tail, then
    # a block large enough to yield full slices while the carry is pending
    sizes = [100, 200, 37, 64, 99]
    blocks, label = [], 0
    for sz in sizes:
        c = RowBlockContainer()
        for _ in range(sz):
            c.push_row(float(label), [label % 50], [1.0])
            label += 1
        blocks.append(c.get_block())

    loader = DeviceLoader(iter(blocks), batch_rows=64, nnz_cap=256)
    seen = []
    try:
        for batch in loader:
            w = np.asarray(batch["weights"])
            seen.extend(np.asarray(batch["labels"])[w > 0].tolist())
    finally:
        loader.close()
    assert seen == [float(i) for i in range(sum(sizes))]


def test_streampack_matches_two_stage(tmp_path, monkeypatch):
    """The fused native parse→pack fast path (SpPacker: text → wire in one
    C++ pass) must produce the SAME device batch stream as the two-stage
    parse→Packer path, on messy input (label:weight heads, implicit-1.0
    tokens, blank/bad lines) across multiple chunks and both wire
    layouts."""
    from dmlc_core_tpu import native
    if not native.has_sppack():
        pytest.skip("native sppack not built")

    rng = np.random.default_rng(11)
    path = tmp_path / "m.libsvm"
    with open(path, "w") as f:
        for i in range(4000):
            n = int(rng.integers(1, 10))
            idx = np.sort(rng.choice(50_000, size=n, replace=False))
            toks = [f"{j}" if rng.random() < 0.3 else
                    f"{j}:{rng.random():.4f}" for j in idx]
            head = f"{i % 2}" if i % 5 else f"{i % 2}:{rng.random():.2f}"
            f.write(head + " " + " ".join(toks) + "\n")
            if i == 777:
                f.write("\n")            # blank line
            if i == 1234:
                f.write("1 5:xx 9:1\n")  # bad token mid-row

    from dmlc_core_tpu.data import create_parser

    def collect(streampack: bool, compact: bool):
        monkeypatch.setenv("DMLC_STREAMPACK", "1" if streampack else "0")
        loader = DeviceLoader(
            create_parser(f"file://{path}", 0, 1, "libsvm", nthreads=1,
                          threaded=False),
            batch_rows=512, nnz_cap=8192, wire_compact=compact)
        if streampack:
            assert loader._use_streampack()
        else:
            assert not loader._use_streampack()
        out = []
        try:
            for b in loader:
                out.append({k: np.asarray(v) for k, v in b.items()})
        finally:
            loader.close()
        return out, loader.stats.rows

    for compact in (False, True):
        a, rows_a = collect(True, compact)
        b, rows_b = collect(False, compact)
        assert rows_a == rows_b
        assert len(a) == len(b), (compact, len(a), len(b))
        for i, (x, y) in enumerate(zip(a, b)):
            assert x.keys() == y.keys()
            for k in x:
                np.testing.assert_array_equal(x[k], y[k], err_msg=f"{i}/{k}")


@pytest.mark.parametrize("fmt", ["libfm", "csv"])
def test_streampack_matches_two_stage_other_formats(tmp_path, monkeypatch,
                                                    fmt):
    """libfm (field dropped — fused wire carries none) and csv (column
    position = feature id, bad rows dropped whole) through the fused path
    must match the two-stage path batch-for-batch."""
    from dmlc_core_tpu import native
    if not native.has_sppack():
        pytest.skip("native sppack not built")

    rng = np.random.default_rng(13)
    if fmt == "libfm":
        path = tmp_path / "m.libfm"
        with open(path, "w") as f:
            for i in range(2000):
                n = int(rng.integers(1, 7))
                ent = " ".join(
                    f"{int(rng.integers(0, 9))}:{int(rng.integers(0, 9999))}"
                    f":{rng.random():.3f}" for _ in range(n))
                f.write(f"{i % 2} {ent}\n")
            f.write("1 3:5\n")            # malformed libfm token (2-part)
        uri = f"file://{path}"
    else:
        path = tmp_path / "m.csv"
        with open(path, "w") as f:
            for i in range(2000):
                row = rng.random(7)
                f.write(f"{i % 2}," +
                        ",".join(f"{v:.4f}" for v in row) + "\n")
            f.write("1,0.5,oops,0.25,1,2,3,4\n")   # bad cell → row dropped
            f.write("0,,0.5,,1,2,3,4\n")           # empty cells → 0.0
        uri = f"file://{path}?label_column=0"

    from dmlc_core_tpu.data import create_parser

    def collect(streampack: bool):
        monkeypatch.setenv("DMLC_STREAMPACK", "1" if streampack else "0")
        loader = DeviceLoader(
            create_parser(uri, 0, 1, fmt, nthreads=1, threaded=False),
            batch_rows=256, nnz_cap=4096)
        assert loader._use_streampack() == streampack
        out = []
        try:
            for b in loader:
                out.append({k: np.asarray(v) for k, v in b.items()})
        finally:
            loader.close()
        return out

    a, b = collect(True), collect(False)
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        for k in x:
            np.testing.assert_array_equal(x[k], y[k], err_msg=f"{i}/{k}")


def test_streampack_with_cache_sugar(tmp_path, monkeypatch):
    """#cachefile URI sugar replays CHUNKS from the cache file on epoch 2;
    the fused streampack path consumes chunks directly from the split, so
    replay must deliver identical batches even after the source file is
    deleted (the CachedInputSplit contract)."""
    from dmlc_core_tpu import native
    if not native.has_sppack():
        pytest.skip("native sppack not built")
    rng = np.random.default_rng(17)
    src = tmp_path / "c.libsvm"
    with open(src, "w") as f:
        for i in range(800):
            idx = np.sort(rng.choice(999, size=4, replace=False))
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    cache = tmp_path / "cc"
    from dmlc_core_tpu.data import create_parser
    loader = DeviceLoader(
        create_parser(f"file://{src}#{cache}", 0, 1, "libsvm", nthreads=1,
                      threaded=False),
        batch_rows=256, nnz_cap=4096)
    assert loader._use_streampack()
    try:
        ep1 = [np.asarray(b["labels"]) for b in loader]
        os.remove(src)                       # epoch 2 must come from cache
        loader.before_first()
        ep2 = [np.asarray(b["labels"]) for b in loader]
    finally:
        loader.close()
    assert len(ep1) == len(ep2) == 4
    for a, b in zip(ep1, ep2):
        np.testing.assert_array_equal(a, b)


def test_tuned_config_roundtrip_and_resolve(tmp_path, monkeypatch):
    """VERDICT r4 #2: the probe's winner persists per-platform and the
    loader's "auto" knobs resolve through it — explicit values always win,
    cpu never inherits link tuning (no link to tune)."""
    from dmlc_core_tpu.pipeline import tuned

    monkeypatch.setenv("DMLC_TUNED_CONFIG", str(tmp_path / "tuned.json"))
    assert tuned.load_tuned("tpu") is None
    # untuned defaults
    assert tuned.resolve("tpu", "auto", "auto") == (1, True)
    assert tuned.resolve("cpu", "auto", "auto") == (1, False)
    tuned.save_tuned({"platform": "tpu", "put_threads": 4,
                      "wire_compact": False, "batch_rows": 49152,
                      "nnz_cap": 1572864, "mbps": 72.3})
    tuned.save_tuned({"platform": "cpu", "put_threads": 2,
                      "wire_compact": True})
    # per-platform entries don't clobber each other
    assert tuned.load_tuned("tpu")["batch_rows"] == 49152
    assert tuned.load_tuned("cpu")["put_threads"] == 2
    # auto inherits the persisted winner (tpu); cpu stays untuned-by-design
    # (no link: extra put threads only time-slice the core, compact wire
    # costs host cycles with nothing to save — even a cpu file entry is
    # deliberately ignored)
    assert tuned.resolve("tpu", "auto", "auto") == (4, False)
    assert tuned.resolve("cpu", "auto", "auto") == (1, False)
    # explicit values pass through
    assert tuned.resolve("tpu", 2, True) == (2, True)
    # corrupt file degrades to defaults
    (tmp_path / "tuned.json").write_text("{not json")
    assert tuned.load_tuned("tpu") is None
    assert tuned.resolve("tpu", "auto", "auto") == (1, True)
