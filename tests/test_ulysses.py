"""Ulysses all-to-all sequence parallelism vs dense reference on the
virtual 8-device CPU mesh (conftest sets the XLA device-count flag)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_core_tpu.ops.ring_attention import reference_attention
from dmlc_core_tpu.ops.ulysses import make_ulysses_attention


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _qkv(rng, b, t, h, d):
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_ulysses_matches_dense(n_dev, causal):
    if len(jax.devices()) < n_dev:
        pytest.skip("needs virtual device mesh")
    mesh = _mesh(n_dev)
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 64, 8, 16
    q, k, v = _qkv(rng, b, t, h, d)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = make_ulysses_attention(mesh, "sp", causal=causal)
    out = fn(qs, ks, vs)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # output keeps the sequence sharding
    assert out.sharding.spec == P(None, "sp", None, None)


def test_ulysses_rejects_indivisible_heads():
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    mesh = _mesh(4)
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 1, 32, 6, 8)        # 6 heads % 4 devices != 0
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = make_ulysses_attention(mesh, "sp")
    with pytest.raises(ValueError, match="divisible"):
        fn(qs, ks, vs)


def test_ulysses_and_ring_agree():
    """Both SP strategies must compute the same attention."""
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    from dmlc_core_tpu.ops.ring_attention import make_ring_attention
    mesh = _mesh(4)
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, 2, 32, 4, 8)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out_u = make_ulysses_attention(mesh, "sp", causal=True)(qs, ks, vs)
    out_r = make_ring_attention(mesh, "sp", causal=True)(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_gradients_match_dense(causal):
    """Long-context is a TRAINING feature: grads through the all-to-all
    resharding must equal dense-attention grads (custom_vjp built from
    forward-direction collectives — all_to_all's autodiff transpose
    mislowers under this shard_map configuration)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = _mesh(8)
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, 2, 32, 8, 16)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = make_ulysses_attention(mesh, "sp", causal=causal)
    g = jax.grad(lambda a, b, c: jnp.sum(fn(a, b, c) ** 2),
                 argnums=(0, 1, 2))(qs, ks, vs)
    gr = jax.grad(
        lambda a, b, c: jnp.sum(
            reference_attention(a, b, c, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
