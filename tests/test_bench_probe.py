"""The root bench's multi-combo probe control flow (put_threads × compact
× batch shape, screen-then-confirm) — exercised on the CPU backend via
platform_override so a regression can't hide until the driver's one TPU
run."""

import importlib.util
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench_mod(tmp_path_factory):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    # small corpus: the probe runs ~20 passes over it
    data = tmp_path_factory.mktemp("bench") / "probe.libsvm"
    rng = np.random.default_rng(0)
    with open(data, "w") as f:
        for r in range(4000):
            idx = np.sort(rng.choice(50_000, size=12, replace=False))
            f.write(f"{r % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    mod.DATA = str(data)
    return mod


def test_probe_flow_tpu_configspace_on_cpu(bench_mod, capfd):
    mean, runs, (pt, cm, rows), platform = bench_mod.measure_ours(
        platform_override="tpu")
    err = capfd.readouterr().err
    assert platform == "tpu"
    # tpu mode runs 5 timed pairs (drift-bounding, bench.py) vs cpu's 3
    assert len(runs) == 5 and all(r > 0 for r in runs)
    assert mean > 0
    # the full config space was screened: 3 pt × 2 compact × 3 shapes
    assert "config probe:" in err
    probe_line = [ln for ln in err.splitlines() if "config probe:" in ln][0]
    assert probe_line.count("pt=") >= 18, probe_line
    for frag in ("rows=16384", "rows=49152", "rows=147456",
                 "compact=1", "compact=0"):
        assert frag in probe_line, (frag, probe_line)
    # the winner is one of the probed configs
    assert pt in (1, 2, 4) and cm in (True, False)
    assert rows in (16384, 49152, 147456)


def test_probe_flow_pinned_by_env(bench_mod, capfd, monkeypatch):
    monkeypatch.setenv("DMLC_BENCH_PUT_THREADS", "1")
    monkeypatch.setenv("DMLC_BENCH_COMPACT", "0")
    monkeypatch.setenv("DMLC_BENCH_ROWS", "8192")
    monkeypatch.setenv("DMLC_BENCH_NNZ", "131072")
    mean, runs, (pt, cm, rows), _ = bench_mod.measure_ours(
        platform_override="tpu")
    err = capfd.readouterr().err
    assert "config probe:" not in err       # single pinned combo, no probe
    assert (pt, cm, rows) == (1, False, 8192)
    assert mean > 0


def test_harvest_commit_suite_merge():
    """Suite artifacts from different grant windows merge per-config: a
    measured entry never loses to a later error/skip entry, fresher
    measured entries win, extra top-level keys survive, and an
    unparseable source leaves the existing artifact untouched."""
    spec = importlib.util.spec_from_file_location(
        "harvest_commit_under_test",
        os.path.join(REPO, "benchmarks", "harvest_commit.py"))
    hc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hc)
    old = {"provenance": "window1", "platform": "tpu", "results": [
        {"metric": "libsvm", "value": 300.0, "platform": "tpu"},
        {"metric": "csv", "value": 400.0, "platform": "host"}]}
    new = {"platform": "cpu", "results": [
        {"metric": "libsvm", "error": "timeout"},          # must NOT win
        {"metric": "csv", "value": 430.0, "platform": "host"},  # fresher
        {"metric": "fm_train", "value": 7000, "platform": "tpu"}]}
    m = hc._merge_suite(old, new)
    assert m["provenance"] == "window1"
    assert m["platform"] == "tpu"
    by = {r["metric"]: r for r in m["results"]}
    assert by["libsvm"]["value"] == 300.0 and "error" not in by["libsvm"]
    assert by["csv"]["value"] == 430.0
    assert by["fm_train"]["value"] == 7000
    # order: old configs first, new appended
    assert [r["metric"] for r in m["results"]] == ["libsvm", "csv",
                                                   "fm_train"]
    # unparseable/mid-rewrite source: old artifact returned unchanged
    assert hc._merge_suite(old, {"error": "JSONDecodeError"}) is old
    # malformed old: fresh artifact wins wholesale
    assert hc._merge_suite({}, new) is new
    # an error entry may land where nothing was measured before
    m2 = hc._merge_suite({"platform": "tpu", "results": []}, new)
    assert "error" in {r["metric"]: r for r in m2["results"]}["libsvm"]


def test_suite_error_rows_use_headline_metric_keys():
    """Error/skip rows must carry the config's HEADLINE metric name, not
    the config name: the merge pairs rows by metric key, so a "libfm"
    error row beside a measured "libfm_ingest_to_device" row would never
    be suppressed by the measured entry (observed in the r04 artifact).
    METRIC_OF is derived from the registry, so the real risk is a
    registered name drifting from what the config fn emits — cross-check
    the cheap host-only config end-to-end."""
    import benchmarks.bench_suite as bs

    assert set(bs.METRIC_OF) == set(bs.ALL)
    r = bs.bench_stream()
    assert r["metric"] == bs.METRIC_OF["stream"]


def test_suite_priority_env_reorders_without_forking_registry(monkeypatch):
    """DMLC_SUITE_PRIORITY puts listed configs first and keeps the rest in
    default order, so a harvest knob can't silently drop configs added to
    the registry later; unknown names fail loudly; explicit argv wins."""
    import benchmarks.bench_suite as bs

    default = [n for n in bs.ALL if n not in bs.DEFAULT_SKIP]
    monkeypatch.delenv("DMLC_SUITE_PRIORITY", raising=False)
    assert bs.resolve_picks([]) == default
    monkeypatch.setenv("DMLC_SUITE_PRIORITY", "allreduce,ingest_scale")
    got = bs.resolve_picks([])
    assert got[:2] == ["allreduce", "ingest_scale"]
    assert sorted(got) == sorted(default)          # nothing dropped/added
    assert [p for p in got[2:]] == [p for p in default
                                    if p not in got[:2]]  # rest keep order
    assert bs.resolve_picks(["csv"]) == ["csv"]    # argv wins verbatim
    monkeypatch.setenv("DMLC_SUITE_PRIORITY", "nonesuch")
    import pytest as _pytest
    with _pytest.raises(SystemExit):
        bs.resolve_picks([])


def test_suite_hang_isolation(tmp_path):
    """A wedged config child (simulated 1h sleep — the r3 tunnel wedge) is
    killed by the per-config timeout and the NEXT config still runs and
    lands in the artifact (VERDICT r3 #6)."""
    import json
    import subprocess

    out = tmp_path / "suite.json"
    env = {**os.environ, "DMLC_SUITE_TEST_HANG": "1",
           "DMLC_SUITE_CONFIG_TIMEOUT": "10",
           "DMLC_BENCH_SUITE_OUT": str(out),
           "DMLC_BENCH_MB": "2", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO}
    env.pop("DMLC_REQUIRE_TPU", None)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_suite.py"),
         "_hang", "stream"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-2000:]
    data = json.loads(out.read_text())
    assert len(data["results"]) == 2
    hang, stream = data["results"]
    assert hang["metric"] == "_hang" and "timeout" in hang["error"]
    assert "error" not in stream and stream.get("unit") == "MB/s"


def test_consume_batch_completion_accumulator(bench_mod):
    """The timed-ingest completion proof: every batch folds one element
    into an on-device accumulator, and prove_consumed forces a VALUE read
    — the only sync the tunnel runtime cannot fake (docs/perf.md
    'Benchmarking against a tunnel runtime')."""
    import jax.numpy as jnp

    acc = None
    total = 0.0
    for i in range(5):
        batch = {"vals": jnp.full((3, 4), float(i + 1))}
        acc = bench_mod.consume_batch(acc, batch)
        total += float(i + 1)
    assert float(acc) == total          # first element of each batch
    bench_mod.prove_consumed(acc)       # must not raise
    bench_mod.prove_consumed(None)      # empty stream: no-op


def test_probe_fast_fail_grant_check(bench_mod, capfd, monkeypatch):
    """VERDICT r4 #5: a driver run against a dead/absent tunnel must fall
    back in minutes, not ~20.  With the backend pinned to cpu the tiny-put
    grant check reports 'cpu' immediately; probe_tpu must return False
    WITHOUT ever reaching the patient full probe (whose 600 s budget is
    the thing the fast-fail protects)."""
    monkeypatch.delenv("DMLC_FORCE_CPU", raising=False)
    # tiny budget: the probe child either reports platform=cpu instantly
    # or hangs on a dead/queued tunnel claim — both must resolve to False
    # within the fast-fail window, never reaching the patient full probe
    monkeypatch.setenv("DMLC_TPU_PROBE_FAST_S", "5")
    monkeypatch.setenv("DMLC_TPU_PROBE_FAST_TOTAL_S", "8")
    import time as _t
    t0 = _t.monotonic()
    assert bench_mod.probe_tpu() is False
    err = capfd.readouterr().err
    assert "grant-check" in err
    assert "[full" not in err           # fast-fail short-circuited
    assert _t.monotonic() - t0 < 60


def test_probe_fast_fail_disabled_env(bench_mod, capfd, monkeypatch):
    """DMLC_TPU_PROBE_FAST_S=0 skips stage 1 (harvest-loop mode keeps its
    own patient budget via DMLC_TPU_PROBE_S)."""
    monkeypatch.delenv("DMLC_FORCE_CPU", raising=False)
    monkeypatch.setenv("DMLC_TPU_PROBE_FAST_S", "0")
    monkeypatch.setenv("DMLC_TPU_PROBE_S", "5")
    assert bench_mod.probe_tpu() is False
    err = capfd.readouterr().err
    assert "grant-check" not in err
    assert "[full" in err


def test_measure_link_verified_cpu(bench_mod):
    """The link probe must survive any backend (it is optional context in
    the bench JSON): on CPU it measures host 'puts' and returns > 0; it
    must never raise."""
    mbps = bench_mod.measure_link_verified(mb=1, reps=2)
    assert mbps > 0


def test_train_configs_registered_with_metric_keys():
    """deepfm_train/ffm_train joined the registry (VERDICT r3 #3): their
    error rows must pair with measured rows across harvest windows, which
    the merge does by metric key."""
    import benchmarks.bench_suite as bs

    assert bs.METRIC_OF["deepfm_train"] == "deepfm_train_stream"
    assert bs.METRIC_OF["ffm_train"] == "ffm_train_stream"
    # never accidentally host-only or cpu-mesh: these need the chip
    assert "deepfm_train" not in bs.HOST_ONLY | bs.CPU_MESH
    assert "ffm_train" not in bs.HOST_ONLY | bs.CPU_MESH


def test_cache_config_registered_host_only():
    """cache_build_replay reproduces the reference's disk_row_iter
    self-report (BASELINE.md instrumentation table); it is pure host/disk
    and must never wait on a tunnel probe."""
    import benchmarks.bench_suite as bs

    assert bs.METRIC_OF["cache"] == "cache_build_replay"
    assert "cache" in bs.HOST_ONLY


def test_probe_deadline_truncates_screen(bench_mod, capfd, monkeypatch):
    """DMLC_BENCH_DEADLINE_S bounds the config screen: the driver runs
    bench.py under a finite timeout, and a truncated probe that proceeds
    with best-so-far beats a killed process falling back to CPU numbers.
    With an already-expired deadline the probe screens nothing, falls to
    the default config, and the timed runs still complete."""
    monkeypatch.setenv("DMLC_BENCH_DEADLINE_S", "0")
    mean, runs, (pt, cm, rows), platform = bench_mod.measure_ours(
        platform_override="tpu")
    err = capfd.readouterr().err
    assert "probe deadline hit" in err
    assert "no combos screened" in err
    # past-deadline runs degrade from 5 timed pairs to 3: measured pairs
    # inside the driver's budget beat a killed process with no JSON
    assert "3 pairs instead of 5" in err
    assert mean > 0 and len(runs) == 3
    # fallback = best-guess-first combo (pt=4, compact first on "tpu"),
    # not a hardcoded worst guess
    assert (pt, cm) == (4, True)


def test_integrity_config_bit_exact_on_cpu():
    """The integrity config's checksum compare must pass on the local
    backend (whose futures are truthful): a failure here means the
    checksum plumbing itself is wrong, not the transport."""
    import benchmarks.bench_suite as bs

    assert bs.METRIC_OF["integrity"] == "ingest_integrity"
    r = bs.bench_integrity()
    assert r["value"] == 1.0, r.get("paths")
    for name in ("libsvm_compact", "libfm_fields", "libsvm_rowmajor"):
        sub = r["paths"][name]
        assert sub["ok"], (name, sub.get("mismatch"))
        # host-derived in every path (rowmajor included) — a degenerate
        # zero-feature corpus would make the checksums vacuous
        assert sub["rows"] > 0 and sub["nnz"] > 0


def test_allreduce_multidevice_branch_on_virtual_mesh():
    """bench_allreduce's n>1 branch (feedback-chained, RTT-corrected bus
    bandwidth) executes on the 8-device virtual host mesh — the branch
    only real multi-chip runs would otherwise reach, rewritten in r4 and
    unexercised until this test."""
    import json
    import subprocess

    code = (
        "import os, json\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=8'\n"
        "os.environ['DMLC_BENCH_MB'] = '2'\n"
        "import benchmarks.bench_suite as bs\n"
        "print(json.dumps(bs.bench_allreduce()))\n")
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=600, env={**os.environ, "PYTHONPATH": REPO}, cwd=REPO)
    assert p.returncode == 0, p.stderr[-2000:]
    r = json.loads(p.stdout.strip().splitlines()[-1])
    assert r["metric"] == "allreduce_bus_bw"
    assert r["devices"] == 8
    assert r["value"] > 0 and r["rtt_ms"] >= 0


def test_harvest_priority_default_matches_registry(monkeypatch):
    """harvest_run.sh's DMLC_SUITE_PRIORITY default must name only
    registered configs: resolve_picks SystemExits on unknown names, which
    inside a granted window would kill the whole suite step.  The string
    lives in shell, the registry in python — this test is the drift
    guard (the string changed three times in r4 alone)."""
    import re

    import benchmarks.bench_suite as bs

    sh = open(os.path.join(REPO, "benchmarks", "harvest_run.sh")).read()
    m = re.search(r"DMLC_SUITE_PRIORITY:-([a-z0-9_,]+)", sh)
    assert m, "priority default not found in harvest_run.sh"
    names = m.group(1).split(",")
    unknown = [n for n in names if n not in bs.ALL]
    assert not unknown, f"harvest_run.sh priority names unknown: {unknown}"
    # and the env path actually accepts it end-to-end
    monkeypatch.setenv("DMLC_SUITE_PRIORITY", m.group(1))
    got = bs.resolve_picks([])
    assert got[:len(names)] == names


def test_tpu_micro_wire_builder_roundtrips_decoder():
    """The wire-decode fusion bench's v3 buffer builder must round-trip
    through the REAL decoder and drive the fused consume jit on CPU — a
    builder bug must surface here, not during a scarce grant window."""
    import jax
    import numpy as np

    from benchmarks.tpu_micro import build_v3_buffer
    from dmlc_core_tpu.ops.csr import fm_pairwise
    from dmlc_core_tpu.pipeline.device_loader import make_decoder

    rows, nnz, w = 64, 2048, 20
    buf, meta, ids, vals = build_v3_buffer(rows, nnz, w, seed=3)
    decode = make_decoder(rows, meta)
    d = jax.jit(decode)(buf)
    np.testing.assert_array_equal(np.asarray(d["ids"]), ids.astype(np.int64))
    np.testing.assert_array_equal(np.asarray(d["vals"]), vals)
    # the fused decode+consume program lowers and runs
    table = jax.random.normal(jax.random.PRNGKey(0), (1 << w, 16))

    @jax.jit
    def fused(b):
        d2 = decode(b)
        return fm_pairwise(d2["ids"], d2["vals"], d2["segments"], table,
                           rows)

    out = fused(buf)
    assert out.shape == (rows,)
    assert bool(np.isfinite(np.asarray(out)).all())
