"""Replicated serving fleet: registry membership/liveness, router
correctness + pick-2 semantics, router-less client failover, shed
masking under injected admission faults, the rolling-restart chaos
drill, and the canary rollout promote/auto-rollback loop — all on CPU
with real TCP on loopback."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.models import SparseLogReg  # noqa: E402
from dmlc_core_tpu.serving import (  # noqa: E402
    BucketLadder, InferenceEngine, PredictClient, PredictionServer,
    ReplicaAgent, ReplicaRegistry, ServingRouter, fleet_rpc, run_load)
from dmlc_core_tpu.telemetry import flight as telflight  # noqa: E402
from dmlc_core_tpu.utils import (CheckpointManager, clear_faults,  # noqa: E402
                                 inject_faults)
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

F = 5000


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


def _counter(name):
    return metrics.counter(name).value


def _wait_for(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _engine(w_scale=1.0):
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.full((F,), w_scale, jnp.float32),
              "b": jnp.float32(0.0)}
    return InferenceEngine(model, params,
                           buckets=BucketLadder([(16, 512)]))


def _req(rng, rows=4, nnz_per_row=16):
    counts = rng.integers(1, nnz_per_row + 1, size=rows)
    ids = rng.integers(0, F, size=int(counts.sum())).astype(np.int32)
    vals = rng.random(len(ids), dtype=np.float32)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    return ids, vals, row_ptr


def _ref_scores(w_scale, ids, vals, row_ptr):
    return np.array([w_scale * float(vals[row_ptr[r]:row_ptr[r + 1]].sum())
                     for r in range(len(row_ptr) - 1)])


def _save_ckpt(directory, step, scale):
    params = {"w": jnp.full((F,), scale, jnp.float32),
              "b": jnp.float32(0.0)}
    CheckpointManager(str(directory)).save(
        step, {"params": params, "opt_state": {"count": jnp.int32(0)}},
        meta={"model": "logreg"})


def _fleet(n, *, model_ids=None, heartbeat_s=0.1, timeout_s=2.0,
           telemetry_port=None, server_kw=None):
    """registry + n (server, agent) pairs, heartbeating fast."""
    reg = ReplicaRegistry(heartbeat_timeout_s=timeout_s,
                          telemetry_port=telemetry_port).start()
    pairs = []
    for i in range(n):
        mid = (model_ids or {}).get(i, "default") \
            if isinstance(model_ids, dict) else \
            (model_ids[i] if model_ids else "default")
        srv = PredictionServer(_engine(), metrics_port=0,
                               model_id=mid,
                               **(server_kw or {})).start()
        ag = ReplicaAgent(srv, reg.address, model_id=mid,
                          interval_s=heartbeat_s).start()
        pairs.append((srv, ag))
    return reg, pairs


def _teardown(reg, pairs, router=None, clients=()):
    for c in clients:
        c.close()
    if router is not None:
        router.stop()
    for srv, ag in pairs:
        ag.stop()
        srv.stop()
    reg.stop()


# ---------------------------------------------------------------------------
# registry control plane
# ---------------------------------------------------------------------------

class _StubReplica:
    """Just enough of a PredictionServer for agent/registry unit tests."""

    def __init__(self, host="127.0.0.1", port=1, model_id="default"):
        self.host, self.port, self.model_id = host, port, model_id
        self.engine = type("E", (), {"params_version": 1})()
        self.telemetry = None
        self.reloads = []

    def health_doc(self):
        return {"status": "ok", "queue_depth": 0,
                "queue_fraction": 0.0, "inflight": 0}

    def reload_from_checkpoint(self, directory, step=None):
        self.reloads.append((directory, step))
        return step or 0


def test_registry_membership_multi_model_and_liveness():
    with ReplicaRegistry(heartbeat_timeout_s=0.4) as reg:
        reg.start()
        a1 = ReplicaAgent(_StubReplica(port=1001, model_id="m1"),
                          reg.address, interval_s=0.1).start()
        a2 = ReplicaAgent(_StubReplica(port=1002, model_id="m2"),
                          reg.address, interval_s=0.1).start()
        assert _wait_for(lambda: len(reg.replica_records()) == 2)
        # multi-model map: list_replicas filters by model
        only_m1 = fleet_rpc(reg.address, {"cmd": "list_replicas",
                                          "model_id": "m1"})["replicas"]
        assert [r["jobid"] for r in only_m1] == ["replica-127.0.0.1:1001"]
        models = fleet_rpc(reg.address, {"cmd": "models"})["models"]
        assert set(models) == {"m1", "m2"}
        # a heartbeat from an UNKNOWN jobid carrying an address is an
        # auto-registration (registry-restart tolerance)
        reply = fleet_rpc(reg.address, {
            "cmd": "heartbeat", "jobid": "ghost", "host": "127.0.0.1",
            "port": 1003, "model_id": "m1", "health": "ok"})
        assert reply["ok"] and "ghost" in reg.replica_records()
        # silence → dead: the stub "ghost" never beats again
        assert _wait_for(
            lambda: not reg.replica_records()["ghost"]["alive"],
            timeout=5.0)
        # the real agents keep beating and stay alive through the sweep
        recs = reg.replica_records()
        assert recs["replica-127.0.0.1:1001"]["alive"]
        # deregister removes the record entirely
        a2.stop()
        assert _wait_for(
            lambda: "replica-127.0.0.1:1002" not in reg.replica_records())
        a1.stop()


def test_registry_queues_directives_and_collects_acks():
    with ReplicaRegistry(heartbeat_timeout_s=2.0) as reg:
        reg.start()
        stub = _StubReplica(port=1005)
        ag = ReplicaAgent(stub, reg.address, interval_s=0.05).start()
        assert _wait_for(lambda: len(reg.replica_records()) == 1)
        reg.push_directive(ag.jobid, {"kind": "reload", "rollout_id": "x",
                                      "ckpt_dir": "/tmp/ck", "step": 9})
        # directive rides a heartbeat reply; the apply lands on the stub
        assert _wait_for(lambda: stub.reloads == [("/tmp/ck", 9)])
        ag.stop()


# ---------------------------------------------------------------------------
# router: correctness and selection
# ---------------------------------------------------------------------------

def test_router_scores_match_direct_and_spread_load():
    reg, pairs = _fleet(2)
    router = ServingRouter(registry=reg.address, sync_s=0.1,
                           health_poll_s=0.1).start()
    cli = PredictClient(router.host, router.port, model_id="default")
    try:
        rng = np.random.default_rng(0)
        for _ in range(30):
            ids, vals, row_ptr = _req(rng)
            out = cli.predict(ids, vals, row_ptr, timeout=10.0)
            np.testing.assert_allclose(
                out, _ref_scores(1.0, ids, vals, row_ptr), rtol=1e-5)
        board = router.fleet_snapshot()["replicas"]
        assert len(board) == 2
        # pick-2 over idle equals should touch both replicas eventually
        assert sum(1 for r in board.values() if r["connected"]) >= 1
    finally:
        _teardown(reg, pairs, router, [cli])


def test_router_rejects_unknown_model_requests():
    reg, pairs = _fleet(1)     # serves "default" only
    router = ServingRouter(registry=reg.address, sync_s=0.1).start()
    cli = PredictClient(router.host, router.port, model_id="nope")
    try:
        from dmlc_core_tpu.serving import ServerOverloaded
        rng = np.random.default_rng(1)
        ids, vals, row_ptr = _req(rng)
        with pytest.raises(ServerOverloaded):
            # no replica serves "nope": the router sheds rather than
            # scoring against the wrong checkpoint
            cli.predict(ids, vals, row_ptr, timeout=3.0)
    finally:
        _teardown(reg, pairs, router, [cli])


def test_pick2_filters_and_drains_degraded():
    router = ServingRouter(replicas=[("127.0.0.1", 1), ("127.0.0.1", 2),
                                     ("127.0.0.1", 3)])
    try:
        reps = router._replicas
        a, b, c = (reps[f"127.0.0.1:{i}"] for i in (1, 2, 3))
        # all ok → pick-2 returns the less loaded of a sampled pair
        a.inflight, b.inflight, c.inflight = 5, 0, 5
        picks = {router._pick("default", set()).key for _ in range(40)}
        assert "127.0.0.1:2" in picks
        # degraded replicas drain: never chosen while an ok one exists
        b.state = "degraded"
        for _ in range(20):
            assert router._pick("default", {"127.0.0.1:3"}).key == \
                "127.0.0.1:1"
        # ... but remain the last resort when every ok replica is gone
        a.state = "overloaded"
        c.straggler = True
        assert router._pick("default", set()).key == "127.0.0.1:2"
        # dead/straggler/overloaded/tried all filter to nothing
        b.alive = False
        assert router._pick("default", set()) is None
        # model filter: nothing serves "other"
        b.alive, b.state, a.state, c.straggler = True, "ok", "ok", False
        assert router._pick("other", set()) is None
    finally:
        router.stop()


def test_router_masks_injected_admission_sheds(monkeypatch):
    """An OVERLOADED answer from one replica is hedge-resubmitted to
    another inside the router — the client never sees the shed."""
    monkeypatch.setenv("DMLC_ROUTER_RETRIES", "4")
    reg, pairs = _fleet(2)
    router = ServingRouter(registry=reg.address, sync_s=0.1).start()
    cli = PredictClient(router.host, router.port, model_id="default")
    try:
        rng = np.random.default_rng(2)
        retries0 = _counter("serving.router.retries")
        with inject_faults("serving.server.admit:error=1.0:times=1"):
            ids, vals, row_ptr = _req(rng)
            out = cli.predict(ids, vals, row_ptr, timeout=10.0)
        np.testing.assert_allclose(
            out, _ref_scores(1.0, ids, vals, row_ptr), rtol=1e-5)
        assert _counter("serving.router.retries") - retries0 >= 1
    finally:
        _teardown(reg, pairs, router, [cli])


# ---------------------------------------------------------------------------
# router-less client failover
# ---------------------------------------------------------------------------

def test_client_endpoint_list_failover():
    srv1 = PredictionServer(_engine()).start()
    srv2 = PredictionServer(_engine()).start()
    cli = PredictClient(srv1.host, srv1.port,
                        endpoints=[(srv2.host, srv2.port)],
                        model_id="default")
    rng = np.random.default_rng(3)
    try:
        ids, vals, row_ptr = _req(rng)
        ref = _ref_scores(1.0, ids, vals, row_ptr)
        np.testing.assert_allclose(cli.predict(ids, vals, row_ptr,
                                               timeout=10.0), ref,
                                   rtol=1e-5)
        f0 = _counter("serving.client.failovers")
        srv1.stop()            # primary gone; the sweep lands on srv2
        np.testing.assert_allclose(cli.predict(ids, vals, row_ptr,
                                               timeout=15.0), ref,
                                   rtol=1e-5)
        assert _counter("serving.client.failovers") - f0 >= 1
    finally:
        cli.close()
        srv2.stop()


def test_hello_rejects_model_mismatch():
    srv = PredictionServer(_engine(), model_id="m1").start()
    try:
        from dmlc_core_tpu.utils.logging import DMLCError
        cli = PredictClient(srv.host, srv.port, model_id="m2",
                            reconnect=False)
        rng = np.random.default_rng(4)
        ids, vals, row_ptr = _req(rng)
        with pytest.raises(DMLCError):
            cli.predict(ids, vals, row_ptr, timeout=5.0)
        cli.close()
        # matching hello works
        ok = PredictClient(srv.host, srv.port, model_id="m1")
        out = ok.predict(ids, vals, row_ptr, timeout=10.0)
        np.testing.assert_allclose(out, _ref_scores(1.0, ids, vals,
                                                    row_ptr), rtol=1e-5)
        ok.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# rolling-restart chaos drill
# ---------------------------------------------------------------------------

def test_rolling_restart_zero_failed_requests(monkeypatch):
    """Stop replicas one at a time under live load: the router refans
    in-flight requests, no request fails, p99 stays bounded."""
    monkeypatch.setenv("DMLC_ROUTER_RETRIES", "6")
    reg, pairs = _fleet(3, heartbeat_s=0.1, timeout_s=1.0)
    router = ServingRouter(registry=reg.address, sync_s=0.1,
                           health_poll_s=0.1).start()
    report = {}

    def load():
        report.update(run_load(
            router.host, router.port, requests=600, concurrency=3,
            pipeline_depth=4, rows_per_req=4, nnz_per_row=16,
            features=F, timeout=60.0, model_id="default"))

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        time.sleep(0.3)                  # load established
        for i in range(3):
            srv, ag = pairs[i]
            ag.stop()
            srv.stop()                   # drain + drop connections
            time.sleep(0.3)
            # restart: a fresh replica on a new port joins the fleet
            srv2 = PredictionServer(_engine(), metrics_port=0).start()
            ag2 = ReplicaAgent(srv2, reg.address,
                               interval_s=0.1).start()
            pairs[i] = (srv2, ag2)
            assert _wait_for(
                lambda: len([r for r in reg.replica_records().values()
                             if r["alive"]]) >= 3, timeout=5.0)
        t.join(timeout=120.0)
        assert not t.is_alive(), "load generator wedged"
        assert report["rejected"] == 0, report
        assert report["ok"] + report["overload"] == 600, report
        assert report["overload"] == 0, report    # retries masked drains
        assert report["latency_ms"]["p99"] < 5000.0, report
    finally:
        _teardown(reg, pairs, router)


# ---------------------------------------------------------------------------
# canary rollout
# ---------------------------------------------------------------------------

def _rollouts_http(port):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5.0)
    try:
        conn.request("GET", "/rollouts")
        rsp = conn.getresponse()
        return rsp.status, json.loads(rsp.read())
    finally:
        conn.close()


def test_canary_promote_on_pass_and_rollback_on_breach(tmp_path):
    ck_v1 = tmp_path / "v1"
    ck_v2 = tmp_path / "v2"
    ck_v3 = tmp_path / "v3"
    _save_ckpt(ck_v1, 1, 1.0)
    _save_ckpt(ck_v2, 2, 5.0)
    _save_ckpt(ck_v3, 3, 9.0)
    reg, pairs = _fleet(2, heartbeat_s=0.1, telemetry_port=0)
    try:
        fleet_rpc(reg.address, {"cmd": "set_model", "model_id": "default",
                                "ckpt_dir": str(ck_v1), "step": 1})
        assert _wait_for(lambda: len(reg.replica_records()) == 2)
        rng = np.random.default_rng(5)
        ids, vals, row_ptr = _req(rng, rows=2)

        def fleet_scale():
            return sorted(round(float(
                srv.engine.predict(ids, vals, row_ptr)[0]
                / _ref_scores(1.0, ids, vals, row_ptr)[0]))
                for srv, _ in pairs)

        # --- promote on pass ------------------------------------------
        staged = fleet_rpc(reg.address, {
            "cmd": "stage_rollout", "model_id": "default",
            "ckpt_dir": str(ck_v2), "step": 2, "fraction": 0.5,
            "bake_s": 0.4})
        assert len(staged["canaries"]) == 1
        assert _wait_for(lambda: fleet_scale() == [5, 5], timeout=15.0), \
            fleet_scale()
        assert reg.stable_pointer("default")["ckpt_dir"] == str(ck_v2)
        status, doc = _rollouts_http(reg.telemetry.port)
        assert status == 200
        assert [e["event"] for e in doc["events"]] == ["staged",
                                                       "promoted"]

        # --- auto-rollback on injected SLO breach ---------------------
        canary_jobid = staged["canaries"][0]
        canary_agent = next(ag for _, ag in pairs
                            if ag.jobid == canary_jobid)
        canary_agent.report_overrides = {"slo_breaches": 1}
        staged2 = fleet_rpc(reg.address, {
            "cmd": "stage_rollout", "model_id": "default",
            "ckpt_dir": str(ck_v3), "step": 3, "fraction": 0.5,
            "bake_s": 5.0})
        assert staged2["canaries"] == [canary_jobid]
        assert _wait_for(
            lambda: any(e["event"] == "rolled_back"
                        for e in reg.rollouts.snapshot()["events"]),
            timeout=15.0)
        canary_agent.report_overrides = {}
        # the canary reloads the STABLE pointer (v2), not v3
        assert _wait_for(lambda: fleet_scale() == [5, 5], timeout=15.0), \
            fleet_scale()
        assert reg.stable_pointer("default")["ckpt_dir"] == str(ck_v2)
        # transitions visible in the ledger AND in a flight bundle
        _, doc = _rollouts_http(reg.telemetry.port)
        events = [e["event"] for e in doc["events"]]
        assert events == ["staged", "promoted", "staged", "rolled_back"]
        bundle = telflight.flight_recorder.bundle("test")
        ledger = bundle["rollout_ledger"]
        assert [e["event"] for e in ledger["events"]] == events
    finally:
        _teardown(reg, pairs)


def test_rollout_rejects_double_stage_and_no_replicas():
    with ReplicaRegistry(heartbeat_timeout_s=2.0) as reg:
        reg.start()
        out = reg.rollouts.stage("default", "/tmp/ck")
        assert "error" in out            # no live replicas
        stub = _StubReplica(port=1009)
        ag = ReplicaAgent(stub, reg.address, interval_s=0.05).start()
        assert _wait_for(lambda: len(reg.replica_records()) == 1)
        first = reg.rollouts.stage("default", "/tmp/ck", bake_s=30.0)
        assert "rollout_id" in first
        second = reg.rollouts.stage("default", "/tmp/ck2")
        assert "error" in second         # one in flight per model
        ag.stop()
