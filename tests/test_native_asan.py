"""Memory-safety + UB tier for the native library (SURVEY §5
race/sanitizer analog): build dmlc_native.cpp with
-fsanitize=address,undefined (UB aborts — no recover) and drive every
hot path in a subprocess.  The SWAR fast paths type-pun 8-byte windows;
UBSan guards the pun staying on the memcpy idiom.  The reference gets this from sanitizer CI
builds of its C++ core; here the single-TU build makes it a regular
test wherever g++ + libasan exist (CI runners included)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "dmlc_core_tpu", "native", "dmlc_native.cpp")


def _sanitizer_runtime(lib: str) -> str:
    """Absolute path of g++'s runtime for ``lib`` ("libasan.so" /
    "libtsan.so"), or "" when unavailable (test skips)."""
    try:
        out = subprocess.run(["g++", f"-print-file-name={lib}"],
                             capture_output=True, text=True, timeout=30)
        path = out.stdout.strip()
        return path if os.path.isabs(path) and os.path.exists(path) else ""
    except (OSError, subprocess.TimeoutExpired):
        return ""


def test_native_hot_paths_asan_clean(tmp_path):
    asan = _sanitizer_runtime("libasan.so")
    if not asan:
        pytest.skip("g++/libasan unavailable")
    so = tmp_path / "libdmlc_native_asan.so"
    build = subprocess.run(
        ["g++", "-fsanitize=address,undefined",
         "-fno-sanitize-recover=undefined", "-O1", "-std=c++17", "-shared",
         "-fPIC", "-fno-omit-frame-pointer", "-fopenmp", SRC, "-o", str(so)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "asan_exercise.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "LD_PRELOAD": asan, "ASAN_LIB": str(so),
             # python itself leaks by design; we're after the C++ paths
             "ASAN_OPTIONS": "detect_leaks=0"})
    assert p.returncode == 0, (p.stdout[-1000:], p.stderr[-3000:])
    assert "ASAN-NATIVE-COMPLETE" in p.stdout
    assert "AddressSanitizer" not in p.stderr, p.stderr[-3000:]


def test_native_openmp_race_free_under_tsan(tmp_path):
    """ThreadSanitizer over the OpenMP chunk parse (the one parallel
    region in the native lib).  parse_parallel carries explicit
    release/acquire edges mirroring both omp barriers, so worker<->main
    data flow is tool-visible; what remains is libgomp's own outlined-
    function preamble reading its argument struct (uninstrumented
    runtime, reported as main-thread-STACK races before our acquire can
    run).  The test therefore requires every surviving report to be of
    that exact class — a real race between workers (or on the parsed
    heap blocks) reports a heap or worker-stack location and fails."""
    tsan = _sanitizer_runtime("libtsan.so")
    if not tsan:
        pytest.skip("g++/libtsan unavailable")
    so = tmp_path / "libdmlc_native_tsan.so"
    build = subprocess.run(
        ["g++", "-fsanitize=thread", "-O1", "-std=c++17", "-shared",
         "-fPIC", "-fopenmp", SRC, "-o", str(so)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "asan_exercise.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "LD_PRELOAD": tsan, "ASAN_LIB": str(so)})
    assert "ASAN-NATIVE-COMPLETE" in p.stdout, (p.stdout[-500:],
                                                p.stderr[-2000:])
    reports = p.stderr.split("WARNING: ThreadSanitizer:")[1:]

    def benign_preamble(r: str) -> bool:
        # the known-benign class and ONLY it: libgomp's outlined-function
        # preamble reading its argument struct — main-stack location AND
        # the worker-side frames never enter user parse code.  A real
        # worker race through blocks/cuts (also main-stack objects) has
        # frames in parse_sparse_range / vector internals and fails here.
        if "Location is stack of main thread" not in r:
            return False
        # NOTE: "ThreadBlock" cannot be a marker — the outlined clone's
        # demangled lambda signature contains "ThreadBlock*" in every
        # report; the discriminators are frame FUNCTION names only
        for marker in ("parse_sparse_range", "parse_csv_range",
                       "reserve", "_M_"):
            if marker in r:
                return False
        if "libgomp" in r:
            return True
        # stripped/unsymbolized runtime (ADVICE r4): libgomp frames may
        # not resolve to a name.  The user-code discriminators above
        # already rejected anything attributable to parse code, so a
        # report whose frames are ALL anonymous (<null> / module+offset
        # only) is the same benign preamble with symbols missing —
        # accept it instead of failing spuriously
        frames = [ln for ln in r.splitlines()
                  if ln.lstrip().startswith("#")]
        return bool(frames) and all(
            "<null>" in ln or " in " not in ln for ln in frames)
    bad = [r[:600] for r in reports if not benign_preamble(r)]
    assert not bad, f"{len(bad)} non-preamble TSAN reports:\n" + \
        "\n---\n".join(bad)
