"""Memory-safety + UB tier for the native library (SURVEY §5
race/sanitizer analog): build dmlc_native.cpp with
-fsanitize=address,undefined (UB aborts — no recover) and drive every
hot path in a subprocess.  The SWAR fast paths type-pun 8-byte windows;
UBSan guards the pun staying on the memcpy idiom.  The reference gets this from sanitizer CI
builds of its C++ core; here the single-TU build makes it a regular
test wherever g++ + libasan exist (CI runners included)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "dmlc_core_tpu", "native", "dmlc_native.cpp")


def _asan_runtime() -> str:
    try:
        out = subprocess.run(["g++", "-print-file-name=libasan.so"],
                             capture_output=True, text=True, timeout=30)
        path = out.stdout.strip()
        return path if os.path.isabs(path) and os.path.exists(path) else ""
    except OSError:
        return ""


def test_native_hot_paths_asan_clean(tmp_path):
    asan = _asan_runtime()
    if not asan:
        pytest.skip("g++/libasan unavailable")
    so = tmp_path / "libdmlc_native_asan.so"
    build = subprocess.run(
        ["g++", "-fsanitize=address,undefined",
         "-fno-sanitize-recover=undefined", "-O1", "-std=c++17", "-shared",
         "-fPIC", "-fno-omit-frame-pointer", "-fopenmp", SRC, "-o", str(so)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "asan_exercise.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "LD_PRELOAD": asan, "ASAN_LIB": str(so),
             # python itself leaks by design; we're after the C++ paths
             "ASAN_OPTIONS": "detect_leaks=0"})
    assert p.returncode == 0, (p.stdout[-1000:], p.stderr[-3000:])
    assert "ASAN-NATIVE-COMPLETE" in p.stdout
    assert "AddressSanitizer" not in p.stderr, p.stderr[-3000:]
