"""Telemetry time machine (PR 14): the tiered history store, burn-rate
SLO engine, critical-path analytics, counter-reset guards at the fleet
ingestion points, the ``/timeline`` + ``/analyze`` endpoints over real
sockets, Prometheus exposition conformance, and the e2e chaos drill
that ties the whole plane together — all CPU, all stdlib wire."""

import json
import math
import os
import socket
import time
import urllib.request

import pytest

from dmlc_core_tpu.telemetry import aggregate, critical_path, exposition, slo
from dmlc_core_tpu.telemetry import timeseries as ts
from dmlc_core_tpu.telemetry import trace as teltrace
from dmlc_core_tpu.telemetry.anomaly import SloSpecError
from dmlc_core_tpu.utils.metrics import MetricsRegistry, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: fixed synthetic epoch, multiple of every tier step used below, so
#: downsample bucket edges land exactly on T0 + k*step
T0 = 1_000_000.0


@pytest.fixture(autouse=True)
def _clean_recorder():
    teltrace.recorder.clear()
    yield
    teltrace.recorder.clear()


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _gauge_store(vals, tiers):
    """A store over one mutable gauge — the minimal deterministic source."""
    return ts.HistoryStore(
        snapshot_fn=lambda: {"g": {"type": "gauge", "value": vals["v"]}},
        tiers=tiers)


# ---------------------------------------------------------------------------
# history store: tiers, flattening, rates, resets
# ---------------------------------------------------------------------------

def test_parse_tiers():
    assert ts.parse_tiers("1x300,10x360") == [(1.0, 300), (10.0, 360)]
    assert ts.parse_tiers(" 0.5x10 ") == [(0.5, 10)]
    for bad in ("5", "", "0x10", "1xz", "10x5,1x300"):
        with pytest.raises(ts.TierSpecError):
            ts.parse_tiers(bad)


def test_tier_boundary_downsampling():
    """Tier 0 keeps raw samples; tier 1 closes each bucket at
    bucket_id*step with the bucket mean — and the still-open bucket is
    not visible until it closes."""
    vals = {"v": 0.0}
    store = _gauge_store(vals, tiers=[(1.0, 5), (10.0, 4)])
    for i in range(25):
        vals["v"] = float(i)
        store.sample_once(now=T0 + i)
    # tier 0: raw ring holds the last 5 samples
    tier0 = store.query("g", since=4.0, now=T0 + 24)
    assert tier0 == [(T0 + 20.0, 20.0), (T0 + 21.0, 21.0),
                     (T0 + 22.0, 22.0), (T0 + 23.0, 23.0),
                     (T0 + 24.0, 24.0)]
    # tier 1: buckets [T0,T0+10) and [T0+10,T0+20) closed as their
    # means, stamped at the bucket START; [T0+20,..) is still open
    tier1 = store.query("g", since=30.0, now=T0 + 24)
    assert tier1 == [(T0, 4.5), (T0 + 10.0, 14.5)]


def test_query_picks_finest_covering_tier():
    vals = {"v": 1.0}
    store = _gauge_store(vals, tiers=[(1.0, 5), (10.0, 4)])
    for i in range(25):
        store.sample_once(now=T0 + i)
    # since=4 fits in tier 0 (1s*5); since=20 does not → tier 1 (10s*4)
    assert len(store.query("g", since=4.0, now=T0 + 24)) == 5
    t1 = store.query("g", since=20.0, now=T0 + 24)
    assert [p[0] for p in t1] == [T0 + 10.0]   # cutoff T0+4 < bucket start
    # no since → coarsest tier, whole ring
    assert store.query("g") == [(T0, 1.0), (T0 + 10.0, 1.0)]


def test_counter_rate_and_reset_rebaseline():
    vals = {"v": 0.0}
    store = ts.HistoryStore(
        snapshot_fn=lambda: {"reqs": {"type": "counter", "value": vals["v"]}},
        tiers=[(1.0, 60)])
    base = metrics.counter("telemetry.counter_resets").value
    vals["v"] = 10.0
    store.sample_once(now=T0)            # first sample: baseline only
    assert store.query("reqs.rate") == []
    vals["v"] = 20.0
    store.sample_once(now=T0 + 2)        # +10 over 2s
    assert store.query("reqs.rate") == [(T0 + 2, 5.0)]
    vals["v"] = 3.0                      # restart: counter went backwards
    store.sample_once(now=T0 + 3)
    pts = store.query("reqs.rate")
    assert pts[-1] == (T0 + 3, 3.0)      # re-baselined at 0, not -17/s
    assert metrics.counter("telemetry.counter_resets").value == base + 1


def test_flattened_series_per_metric_type():
    reg = MetricsRegistry()
    reg.gauge("q.depth").set(7.0)
    h = reg.histogram("lat_s")
    for i in range(100):
        h.observe(0.01 + i * 0.001)
    st = reg.stage("step")
    with st.time():
        pass
    store = ts.HistoryStore(snapshot_fn=reg.snapshot, tiers=[(1.0, 60)])
    store.sample_once(now=T0)
    with st.time():
        time.sleep(0.001)
    store.sample_once(now=T0 + 1)
    names = set(store.series_names())
    assert {"q.depth", "lat_s.p50", "lat_s.p99", "lat_s.rate",
            "step.mean_s", "step.rate"} <= names
    assert store.query("q.depth")[-1][1] == 7.0
    assert store.query("step.rate")[-1][1] == 1.0   # one new call over 1s
    assert store.query("step.mean_s")[-1][1] > 0.0


def test_max_series_overflow_dropped_and_counted():
    snap = {f"g{i}": {"type": "gauge", "value": 1.0} for i in range(4)}
    store = ts.HistoryStore(snapshot_fn=lambda: snap,
                            tiers=[(1.0, 10)], max_series=2)
    base = metrics.counter("telemetry.timeline.dropped_series").value
    store.sample_once(now=T0)
    store.sample_once(now=T0 + 1)        # drops counted once per series
    assert len(store.series_names()) == 2
    assert metrics.counter(
        "telemetry.timeline.dropped_series").value == base + 2


def test_timeline_doc_and_text_render():
    vals = {"v": 0.0}
    store = _gauge_store(vals, tiers=[(1.0, 30)])
    for i in range(5):
        vals["v"] = float(i)
        store.sample_once(now=time.time() - 5 + i)
    index = store.timeline()
    assert index["schema"] == ts.TIMELINE_SCHEMA
    assert index["series"] == ["g"] and index["series_count"] == 1
    assert "  g" in ts.render_timeline_text(index)
    doc = store.timeline("g", since=60.0)
    pts = doc["series"]["g"]["tiers"][0]["points"]
    assert [v for _t, v in pts] == [0.0, 1.0, 2.0, 3.0, 4.0]
    text = ts.render_timeline_text(doc)
    assert text.startswith("g: last=4 min=0 max=4 n=5 [")
    assert ts.render_timeline_text({"series": {}}).startswith(
        "timeline: no matching series")


def test_sampler_thread_lifecycle():
    vals = {"v": 1.0}
    store = _gauge_store(vals, tiers=[(1.0, 30)])
    store.start(interval_s=0.02)
    assert store.running
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not store.query("g"):
        time.sleep(0.01)
    store.stop()
    assert not store.running
    assert store.query("g")


# ---------------------------------------------------------------------------
# counter-reset guard at the fleet ingestion point
# ---------------------------------------------------------------------------

def test_reset_guard_rebases_monotonic_fields():
    reg = MetricsRegistry()
    guard = aggregate.ResetGuard(registry=reg)
    s = guard.fold("0", {"c": {"type": "counter", "value": 10.0},
                         "g": {"type": "gauge", "value": 5.0}})
    assert s["c"]["value"] == 10.0 and s["g"]["value"] == 5.0
    # restart: the worker's counter fell — the fleet total must not
    s = guard.fold("0", {"c": {"type": "counter", "value": 3.0},
                         "g": {"type": "gauge", "value": 1.0}})
    assert s["c"]["value"] == 13.0       # banked 10 + new 3
    assert s["g"]["value"] == 1.0        # gauges are not monotonic
    assert reg.counter("telemetry.counter_resets").value == 1
    # another rank is an independent baseline
    s = guard.fold("1", {"c": {"type": "counter", "value": 2.0}})
    assert s["c"]["value"] == 2.0
    assert reg.counter("telemetry.counter_resets").value == 1


def test_reset_guard_stage_multifield_and_forget():
    reg = MetricsRegistry()
    guard = aggregate.ResetGuard(registry=reg)
    guard.fold("w", {"st": {"type": "stage", "count": 5,
                            "total_sec": 2.0, "mean_sec": 0.4}})
    s = guard.fold("w", {"st": {"type": "stage", "count": 2,
                                "total_sec": 0.5, "mean_sec": 0.25}})
    assert s["st"]["count"] == 7.0 and s["st"]["total_sec"] == 2.5
    assert reg.counter("telemetry.counter_resets").value == 1  # once/metric
    # forget(): a recycled rank id starts fresh — lower is not a reset
    guard.forget("w")
    s = guard.fold("w", {"st": {"type": "stage", "count": 1,
                                "total_sec": 0.1, "mean_sec": 0.1}})
    assert s["st"]["count"] == 1
    assert reg.counter("telemetry.counter_resets").value == 1


# ---------------------------------------------------------------------------
# burn-rate SLO engine
# ---------------------------------------------------------------------------

def test_parse_duration():
    assert slo.parse_duration("250ms") == pytest.approx(0.25)
    assert slo.parse_duration("30s") == 30.0
    assert slo.parse_duration("5m") == 300.0
    assert slo.parse_duration("1h") == 3600.0
    assert slo.parse_duration("12") == 12.0
    with pytest.raises(SloSpecError):
        slo.parse_duration("soon")


def test_parse_slo_spec_superset_grammar():
    plain, burn = slo.parse_slo_spec(
        "a.lat_s:field=p99:max=50ms,"
        "b.q:min=1:budget=0.02:fast=30s/14:slow=5m/6")
    assert len(plain) == 1 and len(burn) == 1
    assert plain[0].metric == "a.lat_s"      # old grammar parses unchanged
    r = burn[0]
    assert (r.metric, r.min_v, r.budget) == ("b.q", 1.0, 0.02)
    assert (r.fast_w, r.fast_r) == (30.0, 14.0)
    assert (r.slow_w, r.slow_r) == (300.0, 6.0)
    assert "budget=0.02" in r.name
    for bad in ("a:max=1:fast=30s/14",        # burn window without budget
                "a:max=1:budget=2",           # budget outside (0, 1]
                "a:max=1:budget=x",
                "a:max=1:budget=0.1:fast=30s",   # window is not W/R
                "a:max=1:budget=0.1:slow=30s/0",
                "a:budget=0.1",               # neither max nor min
                "a:max=1:bogus=2"):
        with pytest.raises(SloSpecError):
            slo.parse_slo_spec(bad)


def _fed_store(values, now, step=1.0):
    """A store over one gauge fed with ``values`` ending at ``now``."""
    vals = {"v": 0.0}
    store = ts.HistoryStore(
        snapshot_fn=lambda: {"lat": {"type": "gauge", "value": vals["v"]}},
        tiers=[(step, 600)])
    t0 = now - (len(values) - 1) * step
    for i, v in enumerate(values):
        vals["v"] = v
        store.sample_once(now=t0 + i * step)
    return store


def test_burn_rate_fast_window_fires():
    now = time.time()
    rule = slo.BurnRateRule("lat", None, max_v=0.1, min_v=None, budget=0.1,
                            fast=(10.0, 5.0), slow=(60.0, 4.0))
    store = _fed_store([0.01] * 50 + [1.0] * 11, now)
    b = rule.check(store, now=now)
    assert b is not None and b["severity"] == "fast"
    assert b["burn_rate"] >= 5.0 and b["value"] == 1.0
    assert b["window_s"] == 10.0 and b["samples"] >= 10


def test_burn_rate_still_burning_gate_suppresses_fast():
    """A fast burn whose latest sample recovered must not page — but a
    sustained slow burn fires with no such gate."""
    now = time.time()
    rule = slo.BurnRateRule("lat", None, max_v=0.1, min_v=None, budget=0.1,
                            fast=(10.0, 5.0), slow=(60.0, 4.0))
    store = _fed_store([0.01] * 50 + [1.0] * 10 + [0.01], now)
    assert rule.check(store, now=now) is None
    # slow: half the hour-window bad → burn 5 ≥ 4, latest sample good
    store = _fed_store([1.0] * 30 + [0.01] * 31, now)
    b = rule.check(store, now=now)
    assert b is not None and b["severity"] == "slow"


def test_burn_rate_empty_window_and_under_budget():
    now = time.time()
    rule = slo.BurnRateRule("lat", None, max_v=0.1, min_v=None, budget=0.5,
                            fast=(10.0, 5.0), slow=(60.0, 4.0))
    assert rule.check(ts.HistoryStore(snapshot_fn=dict), now=now) is None
    store = _fed_store([0.01] * 40 + [1.0], now)   # one bad sample
    assert rule.check(store, now=now) is None


def test_burn_rate_series_resolution():
    store = ts.HistoryStore(
        snapshot_fn=lambda: {"m": {"type": "histogram", "count": 3,
                                   "p50": 0.1, "p99": 0.5, "mean": 0.2}},
        tiers=[(1.0, 10)])
    store.sample_once(now=T0)
    r = slo.BurnRateRule("m", "p99", 1.0, None, budget=0.1)
    assert r._series_name(store) == "m.p99"
    r = slo.BurnRateRule("m", None, 1.0, None, budget=0.1)
    assert r._series_name(store) == "m.p99"      # flattened field wins
    r = slo.BurnRateRule("other", None, 1.0, None, budget=0.1)
    assert r._series_name(store) == "other"      # gauge fallback
    r = slo.BurnRateRule("m", "value", 1.0, None, budget=0.1)
    assert r._series_name(store) == "m"


def test_burn_rate_monitor_evaluate_once():
    now = time.time()
    reg = MetricsRegistry()
    store = _fed_store([1.0] * 30, now)
    plain, burn = slo.parse_slo_spec("lat:max=0.1:budget=0.1:fast=10s/5")
    mon = slo.BurnRateMonitor(plain, burn, history=store, registry=reg)
    fired = mon.evaluate_once()
    assert len(fired) == 1 and fired[0]["severity"] == "fast"
    assert reg.gauge("slo.active_breaches").value == 1
    assert reg.counter("slo.breaches").value == 1
    # recovery clears the active-breach gauge on the next pass
    mon.history = _fed_store([0.01] * 30, now)
    assert mon.evaluate_once() == []
    assert reg.gauge("slo.active_breaches").value == 0


# ---------------------------------------------------------------------------
# critical-path analytics
# ---------------------------------------------------------------------------

def _rec(name, tid, sid, parent, ts_us, dur_us):
    return {"kind": "span", "name": name, "trace_id": tid, "span_id": sid,
            "parent_id": parent, "ts_us": ts_us, "dur_us": dur_us}


def test_critical_path_is_a_complete_accounting():
    recs = [_rec("root", "t1", "r", None, 0, 100),
            _rec("a", "t1", "a", "r", 10, 30),
            _rec("b", "t1", "b", "r", 50, 40)]
    (root,) = critical_path.assemble(recs)["t1"]
    path = critical_path.critical_path(root)
    # chronological: root gap, a, root gap, b, root tail — self times
    # sum exactly to the root duration
    assert path == [("root", 10), ("a", 30), ("root", 10),
                    ("b", 40), ("root", 10)]
    assert sum(us for _n, us in path) == 100


def test_evicted_parent_roots_its_subtree():
    recs = [_rec("orphan", "t2", "x", "evicted-id", 5, 50),
            _rec("child", "t2", "y", "x", 10, 20)]
    roots = critical_path.assemble(recs)["t2"]
    assert [n.name for n in roots] == ["orphan"]
    assert [c.name for c in roots[0].children] == ["child"]


def test_analyze_top_n_and_self_time_aggregation():
    recs = [_rec("slow", "t1", "r1", None, 0, 1000),
            _rec("inner", "t1", "i1", "r1", 100, 800),
            _rec("fast", "t2", "r2", None, 0, 10)]
    doc = critical_path.analyze(top=1, records=recs)
    assert doc["schema"] == critical_path.ANALYZE_SCHEMA
    assert doc["traces_seen"] == 2
    assert [t["root"] for t in doc["top"]] == ["slow"]
    assert doc["self_time_us"] == {"inner": 800, "slow": 200}
    text = critical_path.render_text(doc)
    assert "self time by span:" in text and "inner" in text
    # top is clamped, never a crash
    assert critical_path.analyze(top=0, records=recs)["top"]


def test_incident_breakdown_empty_without_spans():
    assert critical_path.incident_breakdown() == ""


# ---------------------------------------------------------------------------
# endpoints over real sockets
# ---------------------------------------------------------------------------

def test_timeline_and_analyze_endpoints_http():
    vals = {"v": 0.0}
    store = _gauge_store(vals, tiers=[(1.0, 30), (10.0, 6)])
    t0 = time.time() - 24
    for i in range(25):
        vals["v"] = float(i % 7)
        store.sample_once(now=t0 + i)
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1",
                                     timeline_fn=store.timeline).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(f"{base}/timeline")
        doc = json.loads(body)
        assert code == 200 and doc["schema"] == ts.TIMELINE_SCHEMA
        assert "g" in doc["series"]
        code, body = _get(f"{base}/timeline?metric=g&since=20")
        doc = json.loads(body)
        tiers = doc["series"]["g"]["tiers"]
        assert len(tiers) == 2 and tiers[0]["points"]
        code, body = _get(f"{base}/timeline?metric=g&format=text")
        assert code == 200 and body.startswith("g: last=")
        code, body = _get(f"{base}/timeline?metric=nope")
        assert json.loads(body)["series"] == {}
        # /analyze over the live span ring
        with teltrace.span("req"):
            with teltrace.span("stepA"):
                time.sleep(0.002)
        code, body = _get(f"{base}/analyze?top=3")
        doc = json.loads(body)
        assert code == 200
        assert doc["schema"] == critical_path.ANALYZE_SCHEMA
        assert doc["top"] and doc["top"][0]["root"] == "req"
        code, body = _get(f"{base}/analyze?format=text")
        assert "self time by span:" in body
        code, _body = _get(f"{base}/definitely_not_a_route")
        assert code == 404
    finally:
        srv.stop()


def test_tracker_fleet_timeline_merges_across_ranks():
    """Rank-tagged pushes over real sockets fold into one queryable
    fleet timeline, both tiers, and a restarted worker re-bases instead
    of driving the merged counters backwards."""
    from dmlc_core_tpu.parallel.tracker import RabitTracker, send_json

    t = RabitTracker(num_workers=2, host_ip="127.0.0.1", telemetry_port=0)
    t.start()
    try:
        assert t.telemetry is not None

        def push(rank, value):
            reg = MetricsRegistry()
            reg.counter("reqs").add(value)
            s = socket.create_connection((t.host_ip, t.port), timeout=5)
            try:
                send_json(s, {"cmd": "telemetry", "jobid": f"j{rank}",
                              "rank": rank, "state": reg.state()})
            finally:
                s.close()

        def wait_for(pred):
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st = t.telemetry_states()
                if pred(st):
                    return
                time.sleep(0.02)
            raise AssertionError(f"tracker states never converged: "
                                 f"{t.telemetry_states()}")

        def folded(st, rank):
            return st.get(rank, {}).get("reqs", {}).get("value")

        resets = metrics.counter("telemetry.counter_resets").value
        # bucket-aligned synthetic clock in the recent past, so the
        # coarse tier closes a bucket inside the query window
        t0 = math.floor((time.time() - 20) / 10.0) * 10.0
        push(0, 10)
        push(1, 30)
        wait_for(lambda st: folded(st, "0") == 10 and folded(st, "1") == 30)
        t.history.sample_once(now=t0)          # merged 40: baseline
        push(0, 25)
        push(1, 5)          # rank 1 restarted: 30 → 5 re-bases to 35
        wait_for(lambda st: folded(st, "0") == 25 and folded(st, "1") == 35)
        assert metrics.counter(
            "telemetry.counter_resets").value == resets + 1
        t.history.sample_once(now=t0 + 1)      # merged 60: +20 over 1s
        t.history.sample_once(now=t0 + 11)     # closes the 10s bucket
        assert t.history.query("reqs.rate", since=300.0)[0] == (t0 + 1, 20.0)
        code, body = _get(f"http://127.0.0.1:{t.telemetry.port}"
                          f"/timeline?metric=reqs&since=60")
        assert code == 200
        doc = json.loads(body)
        tiers = doc["series"]["reqs.rate"]["tiers"]
        assert [t0 + 1, 20.0] in tiers[0]["points"]     # fine tier
        assert tiers[1]["points"] == [[t0, 20.0]]       # closed 10s bucket
    finally:
        t.stop()


# ---------------------------------------------------------------------------
# Prometheus exposition conformance + HELP catalog
# ---------------------------------------------------------------------------

def _conformance(page):
    """Every sample line sits under its family's single # TYPE header;
    counter-typed families carry the _total/_count suffix."""
    families = {}
    current = None
    for ln in page.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _h, _t, fam, typ = ln.split(" ")
            assert fam not in families, f"duplicate TYPE for {fam}"
            families[fam] = typ
            current = fam
        elif ln.startswith("# HELP "):
            continue
        else:
            name = ln.split("{")[0].split(" ")[0]
            assert current is not None, f"sample before any TYPE: {ln}"
            assert name in (current, f"{current}_sum",
                            f"{current}_count"), ln
    for fam, typ in families.items():
        if typ == "counter":
            assert fam.endswith(("_total", "_count")), \
                f"counter family {fam} lacks a counter suffix"
    return families


def test_prometheus_conformance_golden():
    reg = MetricsRegistry()
    reg.counter("telemetry.counter_resets").add(2)
    reg.gauge("slo.active_breaches").set(1)
    h = reg.histogram("x.lat_s")
    for i in range(10):
        h.observe(i / 100)
    reg.throughput("x.bytes").add(100)
    with reg.stage("x.step").time():
        pass
    page = exposition.render_prometheus(reg.snapshot())
    families = _conformance(page)
    assert families["dmlc_telemetry_counter_resets_total"] == "counter"
    assert families["dmlc_x_lat_s"] == "summary"
    assert families["dmlc_x_step_seconds_total"] == "counter"
    assert families["dmlc_x_step_count"] == "counter"
    # the live process registry renders conformant too
    _conformance(exposition.render_prometheus(metrics.snapshot()))


def test_help_lines_source_from_doc_catalog():
    """# HELP text, the committed inventory, and the docs metric catalog
    are the same strings — the two-way contract of the satellite."""
    from dmlc_core_tpu.analysis.inventory import doc_help, load

    inv = load(os.path.join(REPO, "docs", "inventory.json"))
    helps = inv["help"]
    assert helps == doc_help(os.path.join(REPO, "docs"))
    assert "telemetry.counter_resets" in helps
    assert "slo.active_breaches" in helps
    reg = MetricsRegistry()
    reg.counter("telemetry.counter_resets").add(1)
    reg.gauge("slo.active_breaches").set(0)
    page = exposition.render_prometheus(reg.snapshot(), help_map=helps)
    esc = exposition._escape_help
    assert (f"# HELP dmlc_telemetry_counter_resets_total "
            f"{esc(helps['telemetry.counter_resets'])}") in page
    assert (f"# HELP dmlc_slo_active_breaches "
            f"{esc(helps['slo.active_breaches'])}") in page
    # HELP precedes TYPE for the family (text-format convention)
    lines = page.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("# HELP "):
            assert lines[i + 1].startswith("# TYPE " + ln.split(" ")[2])
    # help_map={} disables HELP emission entirely
    assert "# HELP" not in exposition.render_prometheus(reg.snapshot(),
                                                        help_map={})


def test_inventory_endpoints_match_route_table():
    """The committed inventory's endpoint set IS the exposition route
    table — the greppable contract the endpoint-vocabulary rule gates."""
    from dmlc_core_tpu.analysis.inventory import load

    inv = load(os.path.join(REPO, "docs", "inventory.json"))
    assert set(inv["endpoints"]) == set(exposition._ROUTES)


# ---------------------------------------------------------------------------
# bench trajectory history (check_regression --emit-history)
# ---------------------------------------------------------------------------

def _load_check_regression():
    import importlib.util
    path = os.path.join(REPO, "benchmarks", "check_regression.py")
    spec = importlib.util.spec_from_file_location("_cr_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_regression_emit_history(tmp_path):
    cr = _load_check_regression()
    (tmp_path / "BENCH_demo_r01.json").write_text(json.dumps(
        {"qps": 100.0, "latency_ms": {"p50": 2.0}, "note": 3.0}))
    (tmp_path / "BENCH_demo_r02.json").write_text(json.dumps(
        {"qps": 120.0, "latency_ms": {"p50": 1.5}}))
    assert cr.main(["--dir", str(tmp_path), "--emit-history"]) == 0
    lines = [json.loads(ln) for ln in
             (tmp_path / "PROGRESS.jsonl").read_text().splitlines()]
    assert len(lines) == 1
    rec = lines[0]
    assert rec["schema"] == "dmlc.bench.progress/1"
    assert (rec["family"], rec["round"], rec["status"]) == ("demo", 2,
                                                            "pass")
    assert rec["metrics"] == {"qps": 120.0, "latency_ms.p50": 1.5}
    # a regressed round still gates exit 1 AND is recorded as regressed
    (tmp_path / "BENCH_demo_r03.json").write_text(json.dumps(
        {"qps": 60.0, "latency_ms": {"p50": 1.5}}))
    assert cr.main(["--dir", str(tmp_path), "--emit-history"]) == 1
    lines = [json.loads(ln) for ln in
             (tmp_path / "PROGRESS.jsonl").read_text().splitlines()]
    assert lines[-1]["status"] == "regressed" and lines[-1]["round"] == 3
    # without the flag, nothing is appended
    n = len(lines)
    assert cr.main(["--dir", str(tmp_path)]) == 1
    assert len((tmp_path / "PROGRESS.jsonl").read_text()
               .splitlines()) == n


def test_committed_progress_history_is_valid():
    # PROGRESS.jsonl is append-only and heterogeneous: bench-trajectory
    # records carry the schema key, other telemetry lines don't
    path = os.path.join(REPO, "PROGRESS.jsonl")
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    bench = [r for r in lines if r.get("schema") == "dmlc.bench.progress/1"]
    assert bench, "no bench-trajectory records in PROGRESS.jsonl"
    assert all({"family", "round", "artifact", "status",
                "metrics"} <= set(r) for r in bench)
    fams = {r["family"] for r in bench}
    assert "timeline" in fams       # this PR's sampler-overhead family


# ---------------------------------------------------------------------------
# e2e chaos drill: fault → burn alert → degraded health → evidence bundle
# ---------------------------------------------------------------------------

def test_e2e_chaos_drill_latency_to_bundle(tmp_path, monkeypatch):
    from dmlc_core_tpu.telemetry import flight
    from dmlc_core_tpu.utils import clear_faults, fault_point, inject_faults

    # own the sampler cadence: drive the store by hand, no daemon thread
    monkeypatch.setenv("DMLC_TIMELINE", "0")
    store = ts.HistoryStore(tiers=[(1.0, 120), (10.0, 60)])
    monkeypatch.setattr(ts, "history", store)
    metrics.gauge("serving.server.health").set(0)
    flight.flight_recorder.arm(str(tmp_path))
    try:
        hist = metrics.histogram("drill.lat_s")
        with inject_faults("drill.step:latency=20ms"):
            for _ in range(6):
                with teltrace.span("drill.request"):
                    start = time.perf_counter()
                    with teltrace.span("drill.step"):
                        fault_point("drill.step")
                    hist.observe(time.perf_counter() - start)
        # sample the breach into both tiers: bucket-aligned synthetic
        # clock ending ~now, far enough back to close two 10s buckets
        base = math.floor((time.time() - 26) / 10.0) * 10.0
        for i in range(26):
            store.sample_once(now=base + i)
        plain, burn = slo.parse_slo_spec(
            "drill.lat_s:field=p99:max=5ms:budget=0.01:fast=20s/2:slow=2m/2")
        mon = slo.BurnRateMonitor(plain, burn)
        fired = mon.evaluate_once()
        assert fired and fired[0]["severity"] == "fast"
        assert fired[0]["series"] == "drill.lat_s.p99"
        assert metrics.gauge("slo.active_breaches").value >= 1

        srv = exposition.TelemetryServer(port=0, host="127.0.0.1").start()
        try:
            url = f"http://127.0.0.1:{srv.port}"
            code, body = _get(f"{url}/healthz")
            assert code == 200
            assert json.loads(body)["status"] == "degraded"
            # the breach is visible on /timeline at BOTH tiers
            code, body = _get(f"{url}/timeline?metric=drill.lat_s&since=2m")
            doc = json.loads(body)
            tiers = doc["series"]["drill.lat_s.p99"]["tiers"]
            assert tiers[0]["points"] and tiers[1]["points"]
            assert tiers[0]["points"][-1][1] > 0.005
            assert tiers[1]["points"][-1][1] > 0.005
        finally:
            srv.stop()

        # the breach dumped a bundle carrying the timeline slice and
        # the critical-path breakdown
        bundles = sorted(tmp_path.glob("incident-*"))
        assert bundles, "SLO breach must dump a flight bundle"
        bundle = bundles[-1]
        incident = json.loads((bundle / "incident.json").read_text())
        assert incident["files"]["timeline"] == "timeline.json"
        assert incident["files"]["critical_path"] == "critical_path.txt"
        tl = json.loads((bundle / "timeline.json").read_text())
        assert tl["schema"] == ts.TIMELINE_SCHEMA
        assert "drill.lat_s.p99" in tl["series"]
        cp = (bundle / "critical_path.txt").read_text()
        assert cp.strip() and "drill.step" in cp
    finally:
        flight.flight_recorder.disarm()
        clear_faults()
        metrics.gauge("slo.active_breaches").set(0)
