"""Serving subsystem: bucket ladder + no-retrace invariant, micro-batcher
deadline/overload/drain semantics, server↔client round-trip, checkpoint
hot-reload mid-stream, and load-generator integrity — all on CPU."""

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.models import FactorizationMachine, SparseLogReg  # noqa: E402
from dmlc_core_tpu.serving import (  # noqa: E402
    BucketLadder, DeadlineExceeded, InferenceEngine, MicroBatcher,
    Overloaded, PredictClient, PredictionServer, RequestTooLarge,
    ServerOverloaded, Shutdown, run_load)
from dmlc_core_tpu.utils import CheckpointManager, load_for_inference  # noqa: E402

F = 5000  # feature space for all serving tests


def _logreg_engine(w_scale=1.0, **kw):
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.arange(F, dtype=jnp.float32) / F * w_scale,
              "b": jnp.float32(0.25)}
    return InferenceEngine(model, params, **kw), model, params


def _req(rng, rows, nnz_per_row):
    counts = rng.integers(1, nnz_per_row + 1, size=rows)
    ids = rng.integers(0, F, size=int(counts.sum())).astype(np.int32)
    vals = rng.random(len(ids), dtype=np.float32)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return ids, vals, row_ptr


def _ref_scores(params, ids, vals, row_ptr):
    w = np.asarray(params["w"])
    return np.array([
        float(vals[row_ptr[r]:row_ptr[r + 1]]
              @ w[ids[row_ptr[r]:row_ptr[r + 1]]]) + float(params["b"])
        for r in range(len(row_ptr) - 1)])


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------

def test_ladder_selects_smallest_fit():
    ladder = BucketLadder([(8, 64), (8, 512), (32, 512), (128, 4096)])
    assert ladder.select(3, 20) == (8, 64)
    assert ladder.select(8, 64) == (8, 64)
    assert ladder.select(8, 65) == (8, 512)       # nnz forces wider
    assert ladder.select(9, 10) == (32, 512)      # rows force taller
    assert ladder.select(128, 4096) == (128, 4096)
    with pytest.raises(RequestTooLarge):
        ladder.select(129, 1)
    with pytest.raises(RequestTooLarge):
        ladder.select(1, 5000)


def test_ladder_min_area_not_row_greedy():
    """A 1-row/1024-nnz request must land in the tall-narrow bucket, not
    the widest one (area-ordered selection)."""
    ladder = BucketLadder([(128, 8192), (8, 1024)])
    assert ladder.select(1, 1024) == (8, 1024)


# ---------------------------------------------------------------------------
# engine: correctness + no-retrace invariant
# ---------------------------------------------------------------------------

def test_engine_scores_match_dense_reference():
    eng, _, params = _logreg_engine(
        buckets=BucketLadder([(8, 256), (32, 1024)]))
    rng = np.random.default_rng(0)
    ids, vals, row_ptr = _req(rng, 5, 30)
    out = eng.predict(ids, vals, row_ptr)
    np.testing.assert_allclose(out, _ref_scores(params, ids, vals, row_ptr),
                               rtol=1e-5)


def test_engine_compiles_at_most_once_per_bucket_over_100_requests():
    """The acceptance invariant: a 100-request ragged stream compiles at
    most once per shape bucket — no request ever triggers a retrace."""
    ladder = BucketLadder([(4, 64), (16, 256), (64, 1024)])
    eng, _, params = _logreg_engine(buckets=ladder)
    rng = np.random.default_rng(1)
    used = set()
    for _ in range(100):
        rows = int(rng.integers(1, 40))
        ids, vals, row_ptr = _req(rng, rows, 12)
        used.add(ladder.select(rows, len(ids)))
        out = eng.predict(ids, vals, row_ptr)
        assert out.shape == (rows,)
    assert eng.compile_count == len(used) <= len(ladder)
    # the executables are AOT: same stream again adds zero compilations
    rng = np.random.default_rng(1)
    for _ in range(100):
        rows = int(rng.integers(1, 40))
        ids, vals, row_ptr = _req(rng, rows, 12)
        eng.predict(ids, vals, row_ptr)
    assert eng.compile_count == len(used)


def test_engine_warmup_compiles_whole_ladder():
    ladder = BucketLadder([(4, 64), (16, 256)])
    eng, _, _ = _logreg_engine(buckets=ladder, warmup=True)
    assert eng.compile_count == len(ladder)


def test_engine_sigmoid_postprocess():
    eng, _, params = _logreg_engine(
        buckets=BucketLadder([(8, 256)]), postprocess="sigmoid")
    rng = np.random.default_rng(2)
    ids, vals, row_ptr = _req(rng, 3, 10)
    out = eng.predict(ids, vals, row_ptr)
    ref = 1.0 / (1.0 + np.exp(-_ref_scores(params, ids, vals, row_ptr)))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_engine_reload_refuses_mismatched_architecture():
    eng, _, _ = _logreg_engine(buckets=BucketLadder([(8, 256)]))
    with pytest.raises(Exception, match="hot-reload refused"):
        eng.reload({"w": jnp.zeros(F + 1), "b": jnp.float32(0.0)})
    # and the old weights keep serving
    out = eng.predict(np.array([1], np.int32), np.ones(1, np.float32))
    assert out.shape == (1,)


def test_engine_reload_swaps_weights_without_recompiling():
    eng, _, _ = _logreg_engine(buckets=BucketLadder([(8, 256)]))
    ids = np.array([100], np.int32)
    vals = np.ones(1, np.float32)
    before = eng.predict(ids, vals)[0]
    n_compiles = eng.compile_count
    eng.reload({"w": jnp.zeros(F, jnp.float32), "b": jnp.float32(7.0)})
    after = eng.predict(ids, vals)[0]
    assert before != after
    assert after == pytest.approx(7.0)
    assert eng.compile_count == n_compiles


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------

class _SlowEngine:
    """Engine stub: records calls, optional per-call delay/failure."""

    def __init__(self, delay=0.0):
        self.ladder = BucketLadder([(64, 4096)])
        self.delay = delay
        self.calls = []
        self.fail = False

    def predict(self, ids, vals, row_ptr):
        if self.delay:
            time.sleep(self.delay)
        if self.fail:
            raise RuntimeError("injected engine failure")
        self.calls.append(len(row_ptr) - 1)
        return np.arange(len(row_ptr) - 1, dtype=np.float32)


def test_batcher_aggregates_and_splits():
    eng, _, params = _logreg_engine(buckets=BucketLadder([(64, 4096)]))
    b = MicroBatcher(eng, max_delay_s=0.02)
    rng = np.random.default_rng(3)
    reqs = [_req(rng, int(rng.integers(1, 5)), 8) for _ in range(20)]
    futs = [b.submit(*r) for r in reqs]
    for (ids, vals, row_ptr), f in zip(reqs, futs):
        np.testing.assert_allclose(
            f.result(timeout=10),
            _ref_scores(params, ids, vals, row_ptr), rtol=1e-4)
    b.close()


def test_batcher_delay_trigger_cuts_partial_batch():
    """One lone request must not wait for a full batch — the delay
    trigger serves it after ~max_delay_s."""
    stub = _SlowEngine()
    b = MicroBatcher(stub, max_delay_s=0.01)
    t0 = time.monotonic()
    f = b.submit(np.array([1], np.int32), np.ones(1, np.float32))
    f.result(timeout=5)
    assert time.monotonic() - t0 < 2.0
    assert stub.calls == [1]
    b.close()


def test_batcher_size_trigger_fills_batch():
    stub = _SlowEngine(delay=0.05)       # slow call lets the queue pool
    b = MicroBatcher(stub, max_delay_s=10.0, max_batch_rows=8)
    futs = [b.submit(np.array([1], np.int32), np.ones(1, np.float32))
            for _ in range(16)]
    for f in futs:
        f.result(timeout=10)
    b.close()
    # with a 10s delay trigger, only the size trigger can have cut these
    assert max(stub.calls) == 8
    assert sum(stub.calls) == 16


def test_batcher_overload_rejects_explicitly():
    stub = _SlowEngine(delay=0.2)
    b = MicroBatcher(stub, max_delay_s=0.001, max_queue=4)
    futs = [b.submit(np.array([1], np.int32), np.ones(1, np.float32))
            for _ in range(40)]
    outcomes = []
    for f in futs:
        try:
            f.result(timeout=20)
            outcomes.append("ok")
        except Overloaded:
            outcomes.append("overload")
    assert "overload" in outcomes          # burst over capacity: shed
    assert "ok" in outcomes                # but admitted work completes
    b.close()


def test_batcher_deadline_expires_queued_request():
    stub = _SlowEngine(delay=0.15)
    b = MicroBatcher(stub, max_delay_s=0.001, max_queue=64)
    first = b.submit(np.array([1], np.int32), np.ones(1, np.float32))
    give_up = time.monotonic() + 5
    while b.queue_depth > 0 and time.monotonic() < give_up:
        time.sleep(0.001)             # first is now INSIDE the engine call
    # queued behind a 150ms engine call with a 10ms deadline: must expire
    doomed = b.submit(np.array([1], np.int32), np.ones(1, np.float32),
                      deadline_s=0.01)
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=10)
    first.result(timeout=10)
    b.close()


def test_batcher_oversized_request_fails_fast():
    stub = _SlowEngine()
    b = MicroBatcher(stub, max_delay_s=0.001)
    f = b.submit(np.zeros(5000, np.int32), np.zeros(5000, np.float32))
    with pytest.raises(RequestTooLarge):
        f.result(timeout=5)
    b.close()


def test_batcher_engine_failure_fans_out_and_worker_survives():
    stub = _SlowEngine()
    b = MicroBatcher(stub, max_delay_s=0.001)
    stub.fail = True
    f = b.submit(np.array([1], np.int32), np.ones(1, np.float32))
    with pytest.raises(RuntimeError, match="injected"):
        f.result(timeout=5)
    stub.fail = False                     # worker must still be alive
    f2 = b.submit(np.array([1], np.int32), np.ones(1, np.float32))
    assert f2.result(timeout=5).shape == (1,)
    b.close()


def test_batcher_graceful_drain_serves_queue():
    stub = _SlowEngine(delay=0.02)
    b = MicroBatcher(stub, max_delay_s=5.0, max_batch_rows=4)
    futs = [b.submit(np.array([1], np.int32), np.ones(1, np.float32))
            for _ in range(10)]
    b.close(drain=True)                   # delay trigger never fired
    for f in futs:
        assert f.result(timeout=1).shape == (1,)
    f = b.submit(np.array([1], np.int32), np.ones(1, np.float32))
    with pytest.raises(Shutdown):
        f.result(timeout=1)


def test_batcher_hard_shutdown_fails_queue():
    stub = _SlowEngine(delay=0.05)
    b = MicroBatcher(stub, max_delay_s=5.0)
    futs = [b.submit(np.array([1], np.int32), np.ones(1, np.float32))
            for _ in range(4)]
    b.close(drain=False)
    failed = 0
    for f in futs:
        try:
            f.result(timeout=1)
        except Shutdown:
            failed += 1
    assert failed >= 1


# ---------------------------------------------------------------------------
# end-to-end: server <-> client
# ---------------------------------------------------------------------------

def test_server_client_roundtrip():
    eng, _, params = _logreg_engine(
        buckets=BucketLadder([(16, 512), (64, 2048)]))
    with PredictionServer(eng, warmup=True).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            rng = np.random.default_rng(4)
            for _ in range(20):
                ids, vals, row_ptr = _req(rng, int(rng.integers(1, 10)), 16)
                out = c.predict(ids, vals, row_ptr)
                np.testing.assert_allclose(
                    out, _ref_scores(params, ids, vals, row_ptr),
                    rtol=1e-4, atol=1e-5)


def test_server_pipelined_requests_one_connection():
    eng, _, params = _logreg_engine(buckets=BucketLadder([(64, 2048)]))
    with PredictionServer(eng, warmup=True).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            rng = np.random.default_rng(5)
            reqs = [_req(rng, 2, 8) for _ in range(50)]
            futs = [c.submit(*r) for r in reqs]
            for (ids, vals, row_ptr), f in zip(reqs, futs):
                np.testing.assert_allclose(
                    f.result(timeout=30),
                    _ref_scores(params, ids, vals, row_ptr),
                    rtol=1e-4, atol=1e-5)


def test_server_overload_surfaces_as_typed_error():
    eng, _, _ = _logreg_engine(buckets=BucketLadder([(16, 512)]))
    # slow the engine AFTER warmup so the bounded queue actually fills
    # (a sleep in forward() would only fire at trace time — AOT never
    # re-runs the python)
    orig_predict = eng.predict

    def slow_predict(ids, vals, row_ptr=None):
        time.sleep(0.1)
        return orig_predict(ids, vals, row_ptr)

    eng.predict = slow_predict
    with PredictionServer(eng, warmup=True, max_queue=2,
                          max_delay_s=0.001).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            futs = [c.submit(np.array([1], np.int32),
                             np.ones(1, np.float32)) for _ in range(30)]
            shed = ok = 0
            for f in futs:
                try:
                    f.result(timeout=30)
                    ok += 1
                except ServerOverloaded:
                    shed += 1
            assert shed > 0, "burst over a queue of 2 must shed load"
            assert ok > 0, "admitted requests must still complete"


def test_predict_timeout_abandons_pending_entry():
    """Regression: a timed-out predict() must remove its req_id from the
    pending map — a leaked entry pins the future and its frame forever
    and would be replayed on every subsequent reconnect."""
    stub = _SlowEngine(delay=0.6)
    with PredictionServer(stub, warmup=False,
                          default_deadline_s=10.0).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            with pytest.raises(FutureTimeout):
                c.predict(np.array([1], np.int32),
                          np.ones(1, np.float32), timeout=0.2)
            assert c._pending == {}
            # the connection stays usable: the late response for the
            # abandoned request is discarded, not misdelivered
            out = c.predict(np.array([1], np.int32),
                            np.ones(1, np.float32), timeout=10.0)
            assert out.shape == (1,)
            assert c._pending == {}


def test_server_load_generator_reports():
    eng, _, _ = _logreg_engine(buckets=BucketLadder([(64, 2048)]),
                               postprocess="sigmoid")
    with PredictionServer(eng, warmup=True).start() as srv:
        rep = run_load(srv.host, srv.port, requests=200, concurrency=2,
                       pipeline_depth=8, rows_per_req=2, nnz_per_row=8,
                       features=F)
    assert rep["ok"] == 200 and rep["rejected"] == 0, rep["errors"]
    assert rep["qps"] > 0
    assert 0 < rep["latency_ms"]["p50"] <= rep["latency_ms"]["p99"]


# ---------------------------------------------------------------------------
# checkpoint hot-reload
# ---------------------------------------------------------------------------

def _save_ckpt(tmp_path, step, scale):
    params = {"w": jnp.full((F,), scale, jnp.float32),
              "b": jnp.float32(0.0)}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(step, {"params": params, "opt_state": {"count": jnp.int32(0)}},
             meta={"model": "logreg"})
    return params


def test_load_for_inference_strips_opt_state(tmp_path):
    _save_ckpt(tmp_path, 7, 2.0)
    step, params, meta = load_for_inference(str(tmp_path))
    assert step == 7
    assert set(params) == {"w", "b"}
    assert meta["model"] == "logreg"
    np.testing.assert_allclose(np.asarray(params["w"])[:3], 2.0)


def test_hot_reload_mid_stream_no_dropped_requests(tmp_path):
    """Requests stream while the checkpoint is swapped under the engine:
    nothing may fail, early answers use the old weights, late answers the
    new ones."""
    _save_ckpt(tmp_path, 1, 1.0)
    model = SparseLogReg(num_features=F)
    step, params, _ = load_for_inference(str(tmp_path))
    eng = InferenceEngine(model, params,
                          buckets=BucketLadder([(16, 512)]))
    ids = np.array([123], np.int32)
    vals = np.ones(1, np.float32)

    with PredictionServer(eng, warmup=True).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            stop = threading.Event()
            results, failures = [], []

            def stream():
                while not stop.is_set():
                    try:
                        results.append(float(c.predict(ids, vals,
                                                       timeout=30)[0]))
                    except Exception as e:  # noqa: BLE001 — the assert
                        failures.append(repr(e))
                        return

            t = threading.Thread(target=stream, daemon=True)
            t.start()
            while len(results) < 20:      # stream established
                time.sleep(0.001)
            _save_ckpt(tmp_path, 2, 5.0)  # trainer publishes new weights
            reloaded_step = srv.reload_from_checkpoint(str(tmp_path))
            n_at_reload = len(results)
            while len(results) < n_at_reload + 20:
                time.sleep(0.001)
            stop.set()
            t.join(timeout=10)

    assert failures == [], failures
    assert reloaded_step == 2
    assert results[0] == pytest.approx(1.0)     # old: w=1 → 1·1+0
    assert results[-1] == pytest.approx(5.0)    # new: w=5
    # exactly one switch point, no corrupt interleaving
    assert sorted(set(results)) == [1.0, 5.0]


def test_watch_checkpoints_picks_up_new_step(tmp_path):
    _save_ckpt(tmp_path, 1, 1.0)
    model = SparseLogReg(num_features=F)
    _, params, _ = load_for_inference(str(tmp_path))
    eng = InferenceEngine(model, params, buckets=BucketLadder([(16, 512)]))
    srv = PredictionServer(eng, warmup=True)
    srv.watch_checkpoints(str(tmp_path), interval_s=0.05)
    v0 = eng.params_version           # initial poll already loaded step 1
    srv.start()
    try:
        _save_ckpt(tmp_path, 9, 3.0)
        deadline = time.monotonic() + 20
        while eng.params_version == v0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.params_version > v0, "watcher never reloaded"
        with PredictClient(srv.host, srv.port) as c:
            out = c.predict(np.array([1], np.int32),
                            np.ones(1, np.float32))
        assert out[0] == pytest.approx(3.0)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the zoo: an FM engine serves too (bucketed path is model-agnostic)
# ---------------------------------------------------------------------------

def test_fm_model_serves():
    model = FactorizationMachine(num_features=F, dim=4)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params,
                          buckets=BucketLadder([(8, 256)]))
    rng = np.random.default_rng(6)
    ids, vals, row_ptr = _req(rng, 4, 10)
    out = eng.predict(ids, vals, row_ptr)
    assert out.shape == (4,) and np.isfinite(out).all()
