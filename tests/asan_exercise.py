"""AddressSanitizer exercise of every native hot path — run by
test_native_asan.py in a subprocess with LD_PRELOAD=libasan.

No jax in-process (ASAN interception makes XLA startup minutes-slow);
the native library is exercised directly: chunk-parallel parsers at
nt=1/4 with ragged/garbage rows, CSV with malformed cells, the
two-stage packer at adversarial (batch_rows, nnz_cap, quantum) shapes,
and the fused streampack across random record-aligned chunk cuts —
both wire layouts.  The SWAR parsers read 8-byte windows and the
packers do manual pointer arithmetic (dmlc_native.cpp): this is
exactly the code class where an over-read hides until it corrupts.

Usage: ASAN_LIB=<path to instrumented .so> python asan_exercise.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import dmlc_core_tpu.native as nat
from dmlc_core_tpu.native import build as nat_build

nat._LIB_PATH = os.environ["ASAN_LIB"]
# _load() gates on the DEFAULT .so's freshness sidecar; on a fresh
# checkout that triggers a redundant -O3 build of the non-instrumented
# lib before loading the ASAN one — stub it, the instrumented .so at
# _LIB_PATH is the one under test
nat_build.is_fresh = lambda: True
assert nat.available()
rng = np.random.default_rng(0)

def corpus(fmt, rows=3000):
    out = []
    for i in range(rows):
        n = int(rng.integers(1, 25))
        idx = sorted(rng.choice(1 << 20, n, replace=False).tolist())
        if fmt == "libsvm":
            toks = " ".join(f"{j}:{rng.random():.6f}" for j in idx)
        else:
            toks = " ".join(f"{j % 13}:{j}:{rng.random():.6f}" for j in idx)
        pad = "  " if i % 7 == 0 else ""
        out.append(f"{i % 2} {toks}{pad}")
    # ragged garbage the parsers must survive
    out += ["", "1", "0 bad:token:x:y", "1 5:"]
    return ("\n".join(out) + "\n").encode()

# 1) chunked parse at nt=1 and nt=4, both formats, chunk ends mid-row
for fmt, fn in (("libsvm", nat.parse_libsvm), ("libfm", nat.parse_libfm)):
    data = corpus(fmt)
    for nt in (1, 4):
        blk = fn(data, nthreads=nt)
        assert blk is not None and len(blk["offsets"]) > 3000, (fmt, nt)
    print(fmt, "parse OK")

# csv with trailing delim + short rows
csv = b"".join(b"%f,%f,%f\n" % tuple(rng.random(3)) for _ in range(2000))
csv += b"1.0,2.0\n0.5,,3.0\n"
blk = nat.parse_csv(csv)
assert blk is not None
print("csv parse OK")

# 2) two-stage packer, both wire layouts, odd shapes incl. tiny quantum
from dmlc_core_tpu.data.row_block import RowBlock
d = nat.parse_libsvm(corpus("libsvm"))
rb = RowBlock(d["offsets"], d["labels"], d["indices"], d["values"], None)
for compact in (False, True):
    for (br, cap, q) in ((64, 512, 1), (1000, 16384, 777), (4096, 131072, 0)):
        p = nat.Packer(br, cap, id_mod=1 << 20, quantum=q, compact=compact)
        n = sum(1 for _ in p.feed(rb, max_out=1 << 30))
        n += p.flush() is not None   # flush: one (buf, meta) or None
        p.close()
        assert n > 0
print("packer OK")

# 3) fused streampack, all formats x layouts, record-aligned random chunks
for fmt in ("libsvm", "libfm"):
    data = corpus(fmt)
    for compact in (False, True):
        sp = nat.SpPacker(512, 8192, id_mod=1 << 20, compact=compact, fmt=fmt)
        pos, n = 0, 0
        while pos < len(data):
            cut = data.find(b"\n", min(pos + int(rng.integers(1000, 50000)),
                                       len(data) - 1))
            cut = len(data) if cut < 0 else cut + 1
            n += sum(1 for _ in sp.feed_text(data[pos:cut]))
            pos = cut
        n += sp.flush() is not None   # flush: one (buf, meta) or None
        sp.close()
        assert n > 0
print("sppack OK")

# 4) raw garbage: random bytes (NULs, no structure, no trailing newline),
# pathological token shapes, and huge digit runs — the parsers must
# survive arbitrary input with bad-line accounting, never memory errors
for seed in range(8):
    grng = np.random.default_rng(seed)
    junk = grng.integers(0, 256, int(grng.integers(1, 200000)),
                         dtype=np.uint8).tobytes()
    nat.parse_libsvm(junk)
    nat.parse_libfm(junk)
    nat.parse_csv(junk)
    sp = nat.SpPacker(64, 512, id_mod=1 << 16, fmt="libsvm")
    for _ in sp.feed_text(junk):
        pass
    sp.flush()
    sp.close()
evil = (b"0 " + b"9" * 4096 + b":" + b"1" * 4096 + b"\n"
        b"1 :::::::\n"
        b"0 " + b" " * 8192 + b"\n"
        b"1 5:1e" + b"9" * 64 + b"\n"
        b"0 -1:-0.0 18446744073709551615:5e-324\n")
for fn in (nat.parse_libsvm, nat.parse_libfm, nat.parse_csv):
    fn(evil)
print("garbage-fuzz OK")
print("ASAN-NATIVE-COMPLETE")
