"""Deterministic chaos tests: the fault-injection harness drives real
failure schedules through the I/O, ingest, and serving stacks and the
resilience layer must absorb them — bounded wall time, fixed seeds, retry
counters visible in ``metrics.snapshot()``.

The fast tests stay tier-1 (each well under 10s); the soak rides the
``slow`` marker."""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.io import open_seek_stream_for_read  # noqa: E402
from dmlc_core_tpu.models import SparseLogReg  # noqa: E402
from dmlc_core_tpu.pipeline import RemoteIngestLoader  # noqa: E402
from dmlc_core_tpu.serving import (  # noqa: E402
    BucketLadder, InferenceEngine, PredictClient, PredictionServer)
from dmlc_core_tpu.utils import clear_faults, fault_point, inject_faults  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

from conftest import free_port, start_ingest_worker  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


def _counter(name):
    return metrics.counter(name).value


# ---------------------------------------------------------------------------
# (a) ranged S3-style reads ride over drops / latency / 5xx
# ---------------------------------------------------------------------------

class _FlakyRangeHandler(BaseHTTPRequestHandler):
    """Range GET server that answers 500 for the first ``fail_500`` GETs —
    the real-wire half of the chaos schedule (the injected half lives at
    the ``s3.request`` probe inside ``_http_request``)."""
    files = {}
    fail_500 = [0]

    def log_message(self, *a):
        pass

    def do_HEAD(self):
        data = self.files.get(self.path.split("?")[0])
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if self.fail_500[0] > 0:
            self.fail_500[0] -= 1
            body = b"injected server error"
            self.send_response(500)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = self.files.get(self.path.split("?")[0])
        if data is None:
            self.send_error(404)
            return
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo, hi = rng[6:].split("-")
            lo = int(lo)
            hi = min(int(hi), len(data) - 1) if hi else len(data) - 1
            part = data[lo:hi + 1]
            self.send_response(206)
            self.send_header("Content-Range", f"bytes {lo}-{hi}/{len(data)}")
            self.send_header("Content-Length", str(len(part)))
            self.end_headers()
            self.wfile.write(part)
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)


@pytest.fixture
def flaky_server():
    _FlakyRangeHandler.files = {}
    _FlakyRangeHandler.fail_500 = [0]
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FlakyRangeHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv, _FlakyRangeHandler
    srv.shutdown()


def test_ranged_read_completes_under_injected_drops_and_latency(
        flaky_server, monkeypatch):
    srv, h = flaky_server
    data = bytes(range(256)) * 256           # 64 KiB
    h.files["/blob"] = data
    monkeypatch.setenv("DMLC_IO_RETRIES", "6")
    retries_before = _counter("retry.io.http.retries")
    t0 = time.monotonic()
    with inject_faults("s3.request:error=0.3:seed=7:latency=2ms:lp=0.5"):
        url = f"http://127.0.0.1:{srv.server_address[1]}/blob"
        with open_seek_stream_for_read(url) as s:
            # ragged read pattern: sequential reads + out-of-buffer seeks,
            # each refill crossing the fault probe
            assert s.read(1000) == data[:1000]
            s.seek(50000)
            assert s.read(500) == data[50000:50500]
            s.seek(10)
            assert s.read() == data[10:]
    assert time.monotonic() - t0 < 10.0
    assert _counter("faults.s3.request.errors") > 0   # faults actually fired
    assert _counter("retry.io.http.retries") > retries_before


def test_ranged_read_rides_over_real_5xx(flaky_server):
    srv, h = flaky_server
    data = b"durable payload " * 512
    h.files["/five"] = data
    h.fail_500[0] = 2                        # first two GETs answer 500
    retries_before = _counter("retry.io.http.retries")
    url = f"http://127.0.0.1:{srv.server_address[1]}/five"
    with open_seek_stream_for_read(url) as s:
        assert s.read() == data
    assert h.fail_500[0] == 0
    assert _counter("retry.io.http.retries") >= retries_before + 2


# ---------------------------------------------------------------------------
# (b) ingest epoch completes after a mid-epoch reader kill
# ---------------------------------------------------------------------------

def _libsvm(tmp_path, rows=400):
    rng = np.random.default_rng(0)
    path = tmp_path / "chaos.libsvm"
    with open(path, "w") as f:
        for r in range(rows):
            k = int(rng.integers(1, 5))
            idx = np.sort(rng.choice(3000, size=k, replace=False))
            f.write(f"{r} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    return str(path), rows


def test_ingest_epoch_survives_mid_epoch_reader_kill(tmp_path):
    uri, nrows = _libsvm(tmp_path)
    port = free_port()
    # two epoch budget: the killed first connection burns one, the
    # reader's restart connection replays the partition on the second
    start_ingest_worker(f"file://{uri}", 0, 1, port=port, max_epochs=2)
    restarts_before = _counter("ingest.reader.restarts")
    t0 = time.monotonic()
    # deterministic kill: frame 3 of the stream dies exactly once
    with inject_faults("ingest.send:error=1:times=1:after=2"):
        loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64)
        try:
            seen = []
            for b in loader:
                w = np.asarray(b["weights"]) > 0
                seen.extend(np.asarray(b["labels"])[w].astype(int).tolist())
        finally:
            loader.close()
    assert time.monotonic() - t0 < 10.0
    # the restarted reader re-serves its partition from the start, so
    # relaxed-ordering duplicates are expected — the UNION must be exact
    assert sorted(set(seen)) == list(range(nrows))
    assert _counter("ingest.reader.restarts") >= restarts_before + 1
    assert _counter("faults.ingest.send.errors") > 0


def test_ingest_reader_retries_zero_restores_fail_fast(tmp_path, monkeypatch):
    uri, _ = _libsvm(tmp_path, rows=200)
    port = free_port()
    start_ingest_worker(f"file://{uri}", 0, 1, port=port, max_epochs=2)
    monkeypatch.setenv("DMLC_INGEST_READER_RETRIES", "0")
    with inject_faults("ingest.send:error=1:times=1:after=2"):
        loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64)
        try:
            with pytest.raises(Exception, match="mid-frame|mid-stream|reader"):
                for _ in loader:
                    pass
        finally:
            loader.close()


# ---------------------------------------------------------------------------
# (c) serving round trip through an Overloaded burst and a server restart
# ---------------------------------------------------------------------------

F = 3000


def _engine():
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.arange(F, dtype=jnp.float32) / F,
              "b": jnp.float32(0.5)}
    return InferenceEngine(model, params, buckets=BucketLadder([(8, 256)]))


def test_predict_retries_through_overloaded_burst():
    eng = _engine()
    ids = np.array([100], np.int32)
    vals = np.ones(1, np.float32)
    expect = 100.0 / F + 0.5
    retries_before = _counter("retry.serving.client.retries")
    shed_before = _counter("serving.server.shed")
    with PredictionServer(eng, warmup=True).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            t0 = time.monotonic()
            # exactly 3 sheds; the default 4-attempt budget absorbs them
            with inject_faults("serving.server.admit:error=1:times=3"):
                out = c.predict(ids, vals, timeout=20.0)
            assert time.monotonic() - t0 < 10.0
    assert out[0] == pytest.approx(expect, rel=1e-5)
    assert _counter("retry.serving.client.retries") == retries_before + 3
    assert _counter("serving.server.shed") == shed_before + 3


def test_predict_survives_server_restart(monkeypatch):
    # generous reconnect budget: the dial schedule must span the rebind
    # window however the jitter draws
    monkeypatch.setenv("DMLC_SERVING_RECONNECT_RETRIES", "60")
    monkeypatch.setenv("DMLC_SERVING_RECONNECT_BACKOFF", "0.05")
    monkeypatch.setenv("DMLC_SERVING_BREAKER_THRESHOLD", "1000")
    eng = _engine()
    ids = np.array([200], np.int32)
    vals = np.ones(1, np.float32)
    expect = 200.0 / F + 0.5
    reconnects_before = _counter("serving.client.reconnects")
    port = free_port()
    srv = PredictionServer(eng, port=port, warmup=True).start()
    client = PredictClient(srv.host, port)
    try:
        assert client.predict(ids, vals)[0] == pytest.approx(expect,
                                                             rel=1e-5)
        srv.stop()                           # take the replica down...
        srv = PredictionServer(eng, port=port, warmup=False).start()
        t0 = time.monotonic()                # ...and bring a new one up
        out = client.predict(ids, vals, timeout=20.0)
        assert time.monotonic() - t0 < 15.0
        assert out[0] == pytest.approx(expect, rel=1e-5)
    finally:
        client.close()
        srv.stop()
    assert _counter("serving.client.reconnects") >= reconnects_before + 1


def test_pipelined_inflight_requests_resubmitted_across_restart(monkeypatch):
    """Kill the server while pipelined requests are in flight: the client
    replays every registered frame on the new connection and all futures
    complete (predictions are pure, so replay is idempotent)."""
    monkeypatch.setenv("DMLC_SERVING_RECONNECT_RETRIES", "60")
    monkeypatch.setenv("DMLC_SERVING_RECONNECT_BACKOFF", "0.05")
    monkeypatch.setenv("DMLC_SERVING_BREAKER_THRESHOLD", "1000")
    eng = _engine()
    port = free_port()
    srv = PredictionServer(eng, port=port, warmup=True).start()
    client = PredictClient(srv.host, port)
    try:
        # a first round trip proves the link, then the server dies with
        # requests submitted against the dead socket
        client.predict(np.array([1], np.int32), np.ones(1, np.float32))
        srv.stop(drain=False)
        futs = [client.submit(np.array([i], np.int32),
                              np.ones(1, np.float32)) for i in range(8)]
        srv = PredictionServer(eng, port=port, warmup=False).start()
        for i, f in enumerate(futs):
            out = f.result(timeout=20)
            assert out[0] == pytest.approx(i / F + 0.5, rel=1e-4, abs=1e-5)
        assert client._pending == {}         # nothing leaked
    finally:
        client.close()
        srv.stop()


# ---------------------------------------------------------------------------
# (d) probes are exact no-ops when no spec is armed
# ---------------------------------------------------------------------------

def test_probes_are_noops_without_spec(flaky_server):
    clear_faults()
    srv, h = flaky_server
    data = b"quiet wire " * 100
    h.files["/quiet"] = data
    faults_before = {k: v for k, v in metrics.snapshot().items()
                     if k.startswith("faults.")}
    url = f"http://127.0.0.1:{srv.server_address[1]}/quiet"
    with open_seek_stream_for_read(url) as s:
        assert s.read() == data              # real path crosses the probe
    for _ in range(50):
        fault_point("s3.request")
        fault_point("ingest.send")
        fault_point("serving.server.admit")
    faults_after = {k: v for k, v in metrics.snapshot().items()
                    if k.startswith("faults.")}
    assert faults_before == faults_after


# ---------------------------------------------------------------------------
# soak (slow): sustained probabilistic chaos across serving + io
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_serving_and_io(flaky_server, monkeypatch):
    srv_http, h = flaky_server
    data = bytes(range(256)) * 64
    h.files["/soak"] = data
    monkeypatch.setenv("DMLC_IO_RETRIES", "8")
    monkeypatch.setenv("DMLC_SERVING_RETRIES", "8")
    eng = _engine()
    url = f"http://127.0.0.1:{srv_http.server_address[1]}/soak"
    spec = ("s3.request:error=0.25:seed=11:latency=1ms:lp=0.3,"
            "serving.server.admit:error=0.2:seed=13")
    with PredictionServer(eng, warmup=True).start() as srv:
        with PredictClient(srv.host, srv.port) as c:
            with inject_faults(spec):
                rng = np.random.default_rng(17)
                for i in range(200):
                    ids = rng.integers(0, F, size=4).astype(np.int32)
                    vals = np.ones(4, np.float32)
                    out = c.predict(ids, vals, timeout=30.0)
                    assert out.shape == (1,) and np.isfinite(out).all()
                    if i % 10 == 0:
                        with open_seek_stream_for_read(url) as s:
                            s.seek(int(rng.integers(0, len(data) - 64)))
                            assert len(s.read(64)) == 64
    assert _counter("faults.serving.server.admit.errors") > 0
    assert _counter("faults.s3.request.errors") > 0


# ---------------------------------------------------------------------------
# (f) elastic cohort: kill one rank mid-epoch, checkpoint-free recovery
# ---------------------------------------------------------------------------

def test_elastic_kill_one_rank_recovers_from_peers(tmp_path):
    """``DMLC_FAULT_SPEC`` kills one rank of a 3-rank elastic cohort
    between its epoch-1 compute and the sync collectives (the
    ``elastic.epoch`` probe in examples/elastic_train.py).  The respawned
    rank must rejoin at epoch 2's timeline position — i.e. skip compute
    on its join epoch, not replay it — with its full state served live
    from the survivors: zero checkpoint reads, state digest bit-equal on
    every rank, loss curve continuous (every epoch exactly once,
    identical loss on all ranks)."""
    import json
    import os
    import subprocess
    import sys

    from dmlc_core_tpu.parallel import RabitTracker

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    uri, _ = _libsvm(tmp_path)
    world = 3
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    tenv = tracker.worker_envs()
    base = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu",
            "DMLC_TRACKER_URI": tenv["DMLC_TRACKER_URI"],
            "DMLC_TRACKER_PORT": str(tenv["DMLC_TRACKER_PORT"]),
            "DMLC_ELASTIC_BASE_PORT": str(free_port()),
            # control-plane-only cohort: this jax's CPU backend has no
            # multi-process collectives, and every collective in the
            # example rides rabit anyway — the rejoin protocol (barriers,
            # generation agreement, resharding) is identical
            "DMLC_ELASTIC_DATA_PLANE": "0",
            "DMLC_CHECKPOINT_DIR": str(tmp_path / "ckpt"),
            "DMLC_CONNECT_TIMEOUT": "120", "DMLC_RECOVER_TIMEOUT": "300"}
    (tmp_path / "ckpt").mkdir()
    base.pop("DMLC_FAULT_SPEC", None)
    cmd = [sys.executable,
           os.path.join(repo, "examples", "elastic_train.py"),
           f"file://{uri}", "--epochs", "3", "--features", "512",
           "--batch-rows", "64"]

    def spawn(i, attempt, fault=None):
        env = dict(base, DMLC_TASK_ID=f"c{i}",
                   DMLC_NUM_ATTEMPT=str(attempt))
        if fault:
            env["DMLC_FAULT_SPEC"] = fault
        return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    # after=1: the probe passes at epoch 0 and fires at epoch 1, exactly
    # once — the respawned incarnation runs with the spec removed
    procs = [spawn(i, 0, "elastic.epoch:error=1.0:times=1:after=1"
                   if i == 2 else None) for i in range(world)]
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline and procs[2].poll() is None:
        time.sleep(0.2)
    crash_out, crash_err = procs[2].communicate()
    assert procs[2].returncode == 7, \
        f"victim rc={procs[2].returncode}: {crash_err[-2000:]}"
    assert "CRASHING at epoch 1" in crash_out
    reborn = spawn(2, 1)

    outs = [(crash_out, crash_err)]
    for p in (procs[0], procs[1], reborn):
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-2000:]
        outs.append((out, err))
    tracker.join(timeout=30)
    assert "reborn (attempt 1), resuming at epoch 1" in outs[-1][0]

    recs = [json.loads(ln[6:]) for out, _ in outs
            for ln in out.splitlines() if ln.startswith("EPOCH ")]
    by_rank = {}
    for r in recs:
        by_rank.setdefault(r["rank"], []).append(r)
    assert sorted(by_rank) == [0, 1, 2]
    for rank, rs in by_rank.items():
        # continuity: every epoch exactly once, in order, across BOTH of
        # the victim's incarnations — nothing replayed, nothing skipped
        assert [r["epoch"] for r in rs] == [0, 1, 2], (rank, rs)
        # zero checkpoint reads anywhere in the run
        assert all(r["from_ckpt"] == 0 for r in rs)
    for e in range(3):
        losses = {r["loss"] for r in recs if r["epoch"] == e}
        digests = {r["digest"] for r in recs if r["epoch"] == e}
        assert len(losses) == 1, (e, losses)     # same curve on every rank
        assert len(digests) == 1, (e, digests)   # state bit-equal

    # the join epoch: the reborn rank computed nothing and received every
    # leaf from peers (params + adam state of the 512-feature FM)
    joins = [r for r in recs if not r["contributed"]]
    assert len(joins) == 1
    join = joins[0]
    assert join["epoch"] == 1 and join["rebuilt"] and join["gen"] == 1
    assert join["from_peers"] >= 3 and join["bytes_moved"] > 0
    # survivors crossed the same rebuild, serving their state, reading
    # no checkpoint
    for rank, rs in by_rank.items():
        assert rs[1]["gen"] == 1 and rs[1]["rebuilt"]
