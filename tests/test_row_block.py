"""RowBlock/RowBlockContainer tests (reference row_block.h semantics:
push, zero-copy views, slice, save/load)."""

import io

import numpy as np
import pytest

from dmlc_core_tpu.data import RowBlock, RowBlockContainer


def make_container():
    c = RowBlockContainer()
    c.push_row(1.0, [1, 5, 9], [0.5, 1.5, 2.5])
    c.push_row(0.0, [2], [1.0], weight=2.0)
    c.push_row(1.0, [], [])
    c.push_row(-1.0, [7, 8], [3.0, 4.0])
    return c


def test_push_and_block():
    c = make_container()
    b = c.get_block()
    assert b.size == 4
    assert b.num_values == 6
    assert b.max_index == 9 and b.num_col == 10
    label, idx, vals = b.row(0)
    assert label == 1.0
    np.testing.assert_array_equal(idx, [1, 5, 9])
    np.testing.assert_array_equal(vals, [0.5, 1.5, 2.5])
    assert b.weight(1) == 2.0 and b.weight(0) == 1.0
    label2, idx2, _ = b.row(2)
    assert len(idx2) == 0


def test_sdot():
    c = make_container()
    b = c.get_block()
    dense = np.arange(10, dtype=np.float32)
    # row0: 0.5*1 + 1.5*5 + 2.5*9 = 30.5
    assert b.sdot(0, dense) == pytest.approx(30.5)


def test_slice():
    b = make_container().get_block()
    s = b.slice(1, 3)
    assert s.size == 2
    label, idx, vals = s.row(0)
    assert label == 0.0
    np.testing.assert_array_equal(idx, [2])
    assert s.offsets[0] == 0


def test_push_block_merge():
    c1 = make_container()
    c2 = RowBlockContainer()
    c2.push_block(c1.get_block())
    c2.push_block(c1.get_block())
    b = c2.get_block()
    assert b.size == 8 and b.num_values == 12
    assert b.max_index == 9


def test_push_after_get_block():
    c = make_container()
    _ = c.get_block()
    c.push_row(5.0, [3], [1.0])
    b = c.get_block()
    assert b.size == 5
    assert b.labels[-1] == 5.0


def test_save_load_roundtrip():
    c = make_container()
    buf = io.BytesIO()
    c.save(buf)
    buf.seek(0)
    c2 = RowBlockContainer()
    c2.load(buf)
    b1, b2 = c.get_block(), c2.get_block()
    np.testing.assert_array_equal(b1.offsets, b2.offsets)
    np.testing.assert_array_equal(b1.labels, b2.labels)
    np.testing.assert_array_equal(b1.indices, b2.indices)
    np.testing.assert_array_equal(b1.values, b2.values)
    assert b2.weight(1) == 2.0


def test_from_arrays_zero_copy():
    offsets = np.array([0, 2, 3], np.int64)
    labels = np.array([1, 0], np.float32)
    indices = np.array([4, 2, 0], np.uint64)
    values = np.array([1, 2, 3], np.float32)
    c = RowBlockContainer.from_arrays(offsets, labels, indices, values)
    b = c.get_block()
    assert b.size == 2 and b.max_index == 4
    # push after wrap folds the block into growable form
    c.push_row(2.0, [9], [9.0])
    assert c.get_block().size == 3
    assert c.get_block().max_index == 9
