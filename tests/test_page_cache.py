"""Packed-page epoch cache (`pipeline/page_cache.py` + DeviceLoader
integration): byte-identical replay, fingerprint invalidation, partition
isolation, crash safety (truncation + fault-injected kill mid-write), and
the CachedInputSplit atomic-rename satellite."""

import glob
import os
import struct
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.io import create_input_split  # noqa: E402
from dmlc_core_tpu.pipeline import DeviceLoader  # noqa: E402
from dmlc_core_tpu.pipeline import page_cache  # noqa: E402
from dmlc_core_tpu.utils import clear_faults  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_state():
    metrics.reset()
    clear_faults()
    yield
    clear_faults()


def _write_libsvm(path, rows=900, seed=3):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(rows):
            n = int(rng.integers(1, 6))
            idx = sorted(rng.choice(500, n, replace=False).tolist())
            f.write(f"{i % 2} "
                    + " ".join(f"{j}:{rng.random():.3f}" for j in idx)
                    + "\n")


def _mk_loader(src, cache="auto", part=0, nparts=1, **kw):
    kw.setdefault("batch_rows", 128)
    kw.setdefault("nnz_cap", 1024)
    return DeviceLoader(
        create_parser(str(src), part, nparts, "libsvm",
                      nthreads=1, threaded=False),
        cache=cache if cache in (None, "auto") else str(cache), **kw)


def _epoch(loader):
    return [{k: np.asarray(v) for k, v in b.items()} for b in loader]


def _assert_epochs_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


def _rows_served(batches):
    # padded rows carry weight 0, real rows weight > 0
    return int(sum((b["weights"] > 0).sum() for b in batches))


def test_cached_epochs_byte_identical(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    with DeviceLoader(create_parser(str(src), 0, 1, "libsvm",
                                    nthreads=1, threaded=False),
                      128, 1024) as ref:
        base = _epoch(ref)

    loader = _mk_loader(src, cache=tmp_path / "pc")
    try:
        ep1 = _epoch(loader)                 # miss → write-through build
        assert metrics.counter("page_cache.misses").value == 1
        assert os.path.exists(tmp_path / "pc")
        metrics.reset()
        loader.before_first()
        ep2 = _epoch(loader)                 # hit → mmap replay
        assert metrics.counter("page_cache.hits").value == 1
        assert metrics.counter("page_cache.misses").value == 0
        assert metrics.counter("page_cache.bytes_read").value > 0
        # the whole point: no parse, no pack on a cached epoch
        assert metrics.stage("device_loader.pack").total_sec == 0.0
        assert metrics.stage("parser.parse").total_sec == 0.0
        loader.before_first()
        ep3 = _epoch(loader)
    finally:
        loader.close()
    _assert_epochs_equal(base, ep1)
    _assert_epochs_equal(base, ep2)
    _assert_epochs_equal(base, ep3)


def test_cache_invalidated_on_source_change(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src, rows=600, seed=1)
    cache = tmp_path / "pc"
    l1 = _mk_loader(src, cache=cache)
    _epoch(l1)
    l1.close()

    _write_libsvm(src, rows=700, seed=2)     # different size and content
    metrics.reset()
    l2 = _mk_loader(src, cache=cache)
    try:
        ep2 = _epoch(l2)
        assert metrics.counter("page_cache.misses").value == 1
        assert metrics.counter("page_cache.hits").value == 0
        assert _rows_served(ep2) == 700      # the NEW data, not the cache
        l2.before_first()
        ep3 = _epoch(l2)                     # rebuilt cache now serves
        assert metrics.counter("page_cache.hits").value == 1
        _assert_epochs_equal(ep2, ep3)
    finally:
        l2.close()


def test_cache_invalidated_on_mtime_only(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cache = tmp_path / "pc"
    l1 = _mk_loader(src, cache=cache)
    _epoch(l1)
    l1.close()

    st = os.stat(src)
    os.utime(src, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
    metrics.reset()
    l2 = _mk_loader(src, cache=cache)
    try:
        _epoch(l2)
        assert metrics.counter("page_cache.misses").value == 1
    finally:
        l2.close()


def test_cache_invalidated_on_pack_config_change(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cache = tmp_path / "pc"
    l1 = _mk_loader(src, cache=cache, nnz_cap=1024)
    base = _epoch(l1)
    l1.close()

    metrics.reset()
    l2 = _mk_loader(src, cache=cache, nnz_cap=2048)
    try:
        ep = _epoch(l2)
        assert metrics.counter("page_cache.misses").value == 1
        assert _rows_served(ep) == _rows_served(base)
    finally:
        l2.close()


def test_partition_suffix_isolation(tmp_path):
    """The URI fragment's .splitN.partK suffix keeps ranks' page files
    apart, and each partition replays only its own shard."""
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cc = tmp_path / "cc"
    uri = f"{src}#{cc}"
    per_part = []
    for part in (0, 1):
        metrics.reset()                      # before construction: the
        loader = _mk_loader(uri, part=part, nparts=2)  # pack thread is eager
        try:
            ep1 = _epoch(loader)
            assert metrics.counter("page_cache.misses").value == 1
            loader.before_first()
            ep2 = _epoch(loader)
            assert metrics.counter("page_cache.hits").value == 1
            _assert_epochs_equal(ep1, ep2)
            per_part.append(ep1)
        finally:
            loader.close()
        assert os.path.exists(f"{cc}.split2.part{part}.pages")
    assert (_rows_served(per_part[0]) + _rows_served(per_part[1])) == 900


def test_reset_partition_invalidates(tmp_path):
    """Repartitioning between epochs shifts the fingerprint: the loader
    must serve the NEW partition from source, then cache it."""
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    parser = create_parser(str(src), 0, 2, "libsvm",
                           nthreads=1, threaded=False)
    loader = DeviceLoader(parser, 128, 1024, cache=str(tmp_path / "pc"))
    try:
        ep_p0 = _epoch(loader)
        parser.source.reset_partition(1, 2)
        metrics.reset()
        loader.before_first()
        ep_p1 = _epoch(loader)
        assert metrics.counter("page_cache.misses").value == 1
        assert metrics.counter("page_cache.hits").value == 0
        assert _rows_served(ep_p0) + _rows_served(ep_p1) == 900
        loader.before_first()
        ep_p1b = _epoch(loader)              # rebuilt for part 1 → hit
        assert metrics.counter("page_cache.hits").value == 1
        _assert_epochs_equal(ep_p1, ep_p1b)
    finally:
        loader.close()


def test_truncated_cache_rebuilt(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cache = tmp_path / "pc"
    l1 = _mk_loader(src, cache=cache)
    base = _epoch(l1)
    l1.close()

    size = os.path.getsize(cache)
    with open(cache, "r+b") as f:
        f.truncate(size // 2)                # footer + index gone
    metrics.reset()
    l2 = _mk_loader(src, cache=cache)
    try:
        ep = _epoch(l2)
        assert metrics.counter("page_cache.misses").value == 1
        _assert_epochs_equal(base, ep)
        l2.before_first()
        _assert_epochs_equal(base, _epoch(l2))
        assert metrics.counter("page_cache.hits").value == 1
    finally:
        l2.close()


def test_corrupt_footer_rebuilt(tmp_path):
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cache = tmp_path / "pc"
    l1 = _mk_loader(src, cache=cache)
    base = _epoch(l1)
    l1.close()

    with open(cache, "r+b") as f:            # flip the finalize magic
        f.seek(-4, os.SEEK_END)
        f.write(b"XXXX")
    metrics.reset()
    l2 = _mk_loader(src, cache=cache)
    try:
        _assert_epochs_equal(base, _epoch(l2))
        assert metrics.counter("page_cache.misses").value == 1
    finally:
        l2.close()


def test_chaos_kill_mid_write_rebuilds(tmp_path, monkeypatch):
    """DMLC_FAULT_SPEC kills the page writer mid-file: the epoch is still
    served in full, no cache survives under the real name (no tmp litter
    either), and the next run rebuilds cleanly."""
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cache = tmp_path / "pc"
    monkeypatch.setenv("DMLC_FAULT_SPEC", "page_cache.write:error=1:after=2")
    clear_faults()                           # re-arm from the env var
    l1 = _mk_loader(src, cache=cache)
    try:
        ep1 = _epoch(l1)                     # served despite the dead build
        assert _rows_served(ep1) == 900
    finally:
        l1.close()
    assert not os.path.exists(cache)
    assert glob.glob(f"{cache}.tmp.*") == []

    monkeypatch.delenv("DMLC_FAULT_SPEC")
    clear_faults()
    metrics.reset()
    l2 = _mk_loader(src, cache=cache)
    try:
        ep2 = _epoch(l2)                     # rebuild succeeds now
        assert metrics.counter("page_cache.misses").value == 1
        assert os.path.exists(cache)
        l2.before_first()
        ep3 = _epoch(l2)
        assert metrics.counter("page_cache.hits").value == 1
        _assert_epochs_equal(ep1, ep2)
        _assert_epochs_equal(ep2, ep3)
    finally:
        l2.close()


def test_uri_fragment_enables_page_cache(tmp_path):
    """#cachefile on the URI auto-enables the page cache (cache='auto'),
    coexisting with CachedInputSplit's raw-chunk log on the same path."""
    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    cc = tmp_path / "cc"
    loader = _mk_loader(f"{src}#{cc}")
    try:
        ep1 = _epoch(loader)
        loader.before_first()
        ep2 = _epoch(loader)
        _assert_epochs_equal(ep1, ep2)
    finally:
        loader.close()
    assert os.path.exists(f"{cc}.pages")     # page cache
    assert os.path.exists(cc)                # chunk log, both live
    assert os.path.exists(f"{cc}.done")
    assert metrics.counter("page_cache.hits").value >= 1


def test_emit_host_cached_views_not_recycled(tmp_path):
    """emit='host' consumers recycle() every buffer; mmap'd page views
    must bounce off the pool (writeable guard) and later epochs must stay
    intact — a poisoned pool would corrupt subsequent builds."""
    from dmlc_core_tpu.pipeline.device_loader import _fused_words_meta

    src = tmp_path / "d.libsvm"
    _write_libsvm(src)
    loader = _mk_loader(src, cache=tmp_path / "pc", emit="host")

    def host_epoch():
        out = []
        saw_readonly = False
        while True:
            item = loader.next_batch()
            if item is None:
                return out, saw_readonly
            _, buf, meta, _rows = item
            words = _fused_words_meta(128, int(meta))
            out.append(bytes(np.ascontiguousarray(buf[:words]).tobytes()))
            saw_readonly = saw_readonly or not buf.flags.writeable
            loader.recycle(buf)

    try:
        ep1, ro1 = host_epoch()
        assert not ro1                        # build epoch: pool buffers
        loader.before_first()
        ep2, ro2 = host_epoch()
        assert ro2                            # cached epoch: mmap views
        loader.before_first()
        ep3, _ = host_epoch()
    finally:
        loader.close()
    assert ep1 == ep2 == ep3


def test_page_file_format_probes(tmp_path):
    """Reader-level validation: unfinalized tmp never validates, a valid
    file round-trips pages exactly, fingerprint mismatch returns None."""
    path = str(tmp_path / "p.pages")
    fp = {"k": 1}
    w = page_cache.PageCacheWriter(path, fp, queue_pages=4)
    payloads = [np.arange(16, dtype=np.int32) + i for i in range(3)]
    for i, p in enumerate(payloads):
        assert w.offer(p, meta=100 + i, rows=None if i else 7, words=16)
    assert not os.path.exists(path)          # nothing before finalize
    assert w.finalize()
    assert os.path.exists(path)
    assert glob.glob(f"{path}.tmp.*") == []

    assert page_cache.open_reader(path, {"k": 2}) is None   # stale
    r = page_cache.open_reader(path, fp, expected_words=lambda m: 16)
    assert r is not None and r.npages == 3
    got = list(r.pages())
    r.close()
    for i, (meta, rows, view) in enumerate(got):
        assert meta == 100 + i
        assert rows == (7 if i == 0 else None)
        assert not view.flags.writeable
        np.testing.assert_array_equal(view, payloads[i])
    # wrong expected word count ⇒ rejected, not served
    assert page_cache.open_reader(path, fp,
                                  expected_words=lambda m: 32) is None


def test_chunk_cache_truncated_rebuilt(tmp_path):
    """CachedInputSplit satellite: a damaged chunk log behind a surviving
    .done marker is discarded and rebuilt from source — never allowed to
    truncate the epoch."""
    src = tmp_path / "d.txt"
    with open(src, "w") as f:
        for i in range(200):
            f.write(f"line-{i:04d}\n")
    cache = str(tmp_path / "chunks")
    uri = f"{src}#{cache}"

    def drain(split):
        chunks = []
        while True:
            c = split.next_chunk()
            if c is None:
                return chunks
            chunks.append(bytes(c))

    s1 = create_input_split(uri, 0, 1, "text")
    first = drain(s1)
    s1.close()
    assert os.path.exists(cache) and os.path.exists(cache + ".done")

    with open(cache, "r+b") as f:            # chop mid-record
        f.truncate(os.path.getsize(cache) - 5)
    s2 = create_input_split(uri, 0, 1, "text")
    rebuilt = drain(s2)
    assert b"".join(rebuilt) == b"".join(first)
    # after the rebuild pass the cache is whole again and replays
    s2.before_first()
    replay = drain(s2)
    s2.close()
    assert b"".join(replay) == b"".join(first)


def test_chunk_cache_killed_first_pass_leaves_nothing(tmp_path):
    """An abandoned first pass must leave no file under the final cache
    name (atomic tmp + rename), so the next open rebuilds from source."""
    src = tmp_path / "d.txt"
    with open(src, "w") as f:
        for i in range(50):
            f.write(f"line-{i:04d}\n")
    cache = str(tmp_path / "chunks")
    s = create_input_split(f"{src}#{cache}", 0, 1, "text")
    assert s.next_chunk() is not None        # partial first pass
    s.close()
    assert not os.path.exists(cache)
    assert not os.path.exists(cache + ".done")
    assert glob.glob(f"{cache}.tmp.*") == []


def test_chunk_cache_log_is_length_prefixed(tmp_path):
    """The on-disk chunk log framing the validator walks is the framing
    the writer produces (guards against silent format drift)."""
    src = tmp_path / "d.txt"
    with open(src, "w") as f:
        f.write("hello\nworld\n")
    cache = str(tmp_path / "chunks")
    s = create_input_split(f"{src}#{cache}", 0, 1, "text")
    while s.next_chunk() is not None:
        pass
    s.close()
    with open(cache, "rb") as f:
        blob = f.read()
    pos, total = 0, 0
    while pos < len(blob):
        (n,) = struct.unpack_from("<Q", blob, pos)
        pos += 8 + n
        total += n
    assert pos == len(blob) and total == 12
