"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's test strategy (SURVEY §4): everything runs single-host
CPU; distributed behavior is validated on simulated devices
(``xla_force_host_platform_device_count``) the way the reference validates
partitioning single-process and the tracker with ``--cluster local``.

The axon TPU plugin (registered process-wide by a sitecustomize hook) is
explicitly deregistered: tests must never depend on — or hang on — the
tunneled real chip, and ``JAX_PLATFORMS=cpu`` alone does not stop the plugin's
client initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Opt-in runtime lock-order checking (the dmlclint lock-discipline rule's
# dynamic companion): DMLC_LOCKCHECK=1 shims package lock creation so the
# whole suite doubles as ordering coverage.  Installed before any package
# import so every lock the modules create at import time is wrapped too.
if os.environ.get("DMLC_LOCKCHECK") == "1":
    from dmlc_core_tpu.utils import lockcheck as _lockcheck

    _lockcheck.install()

    def pytest_terminal_summary(terminalreporter, exitstatus, config):
        _lockcheck.flush()          # land queued metric/flight emission
        rep = _lockcheck.report()
        terminalreporter.write_line(
            "lockcheck: %d lock(s), %d edge(s), %d inversion(s), "
            "%d long hold(s)" % (rep["locks"], rep["edges"],
                                 len(rep["inversions"]),
                                 len(rep["long_holds"])))
        for inv in rep["inversions"]:
            terminalreporter.write_line(
                "lockcheck INVERSION: held %(held)s while acquiring "
                "%(acquiring)s at %(site)s [%(thread)s]" % inv)


def _force_cpu_jax() -> None:
    """The axon register() hook may override jax_platforms via config (which
    wins over env), so pin the config AND drop the axon backend factory."""
    try:
        import jax
        from jax._src import xla_bridge
    except Exception:
        return
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    reg = getattr(xla_bridge, "_backend_factories", None)
    if isinstance(reg, dict):
        reg.pop("axon", None)


_force_cpu_jax()


# -- shared test helpers (imported by test modules via conftest) ----------

def free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def start_ingest_worker(uri: str, part: int, nparts: int,
                        fmt: str = "libsvm", *, port: int = 0,
                        batch_rows: int = 64, nnz_cap: int = 1024,
                        max_epochs: int = 1, **kw) -> int:
    """Spawn one serve_ingest daemon thread; block until it listens and
    return its port.  One home for the port-probe + ready-event dance
    (used by test_ingest_service and the CLI workers= tests)."""
    import threading

    from dmlc_core_tpu.pipeline import serve_ingest
    port = port or free_port()
    ev = threading.Event()
    threading.Thread(
        target=serve_ingest,
        args=(uri, part, nparts, fmt),
        kwargs=dict(batch_rows=batch_rows, nnz_cap=nnz_cap, port=port,
                    host="127.0.0.1", max_epochs=max_epochs,
                    ready_event=ev, **kw),
        daemon=True).start()
    assert ev.wait(timeout=30), "ingest worker never became ready"
    return port
