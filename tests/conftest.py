"""Test config: force an 8-device virtual CPU mesh before JAX is imported.

Mirrors the reference's test strategy (SURVEY §4): everything runs single-host
CPU; distributed behavior is validated on simulated devices
(``xla_force_host_platform_device_count``) the way the reference validates
partitioning single-process and the tracker with ``--cluster local``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
