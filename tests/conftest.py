"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's test strategy (SURVEY §4): everything runs single-host
CPU; distributed behavior is validated on simulated devices
(``xla_force_host_platform_device_count``) the way the reference validates
partitioning single-process and the tracker with ``--cluster local``.

The axon TPU plugin (registered process-wide by a sitecustomize hook) is
explicitly deregistered: tests must never depend on — or hang on — the
tunneled real chip, and ``JAX_PLATFORMS=cpu`` alone does not stop the plugin's
client initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")


def _force_cpu_jax() -> None:
    """The axon register() hook may override jax_platforms via config (which
    wins over env), so pin the config AND drop the axon backend factory."""
    try:
        import jax
        from jax._src import xla_bridge
    except Exception:
        return
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    reg = getattr(xla_bridge, "_backend_factories", None)
    if isinstance(reg, dict):
        reg.pop("axon", None)


_force_cpu_jax()
