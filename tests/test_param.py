"""Parameter system tests (reference behavior: ``test/unittest/unittest_param.cc``)."""

import os

import pytest

from dmlc_core_tpu.utils import Parameter, ParamError, field, get_env
from dmlc_core_tpu.utils.serializer import  read_uint64  # noqa: F401
import io as _io


class LearningParam(Parameter):
    num_hidden = field(int, default=100, range=(1, 10000), help="hidden units")
    learning_rate = field(float, default=0.01, lower_bound=0.0)
    activation = field(str, default="relu", enum=["relu", "tanh", "sigmoid"])
    use_bias = field(bool, default=True)
    name = field(str, aliases=["moniker"], default="net")


class RequiredParam(Parameter):
    size = field(int)
    scale = field(float, default=1.0)


def test_defaults():
    p = LearningParam()
    assert p.num_hidden == 100
    assert p.learning_rate == 0.01
    assert p.activation == "relu"
    assert p.use_bias is True


def test_init_and_types():
    p = LearningParam()
    p.init({"num_hidden": "256", "learning_rate": "0.5", "use_bias": "false"})
    assert p.num_hidden == 256
    assert p.learning_rate == 0.5
    assert p.use_bias is False


def test_range_violation_raises():
    # mirrors unittest_param.cc:13-21 (out-of-range init throws ParamError)
    p = LearningParam()
    with pytest.raises(ParamError):
        p.init({"num_hidden": 0})
    with pytest.raises(ParamError):
        p.init({"num_hidden": 100000})
    with pytest.raises(ParamError):
        p.init({"learning_rate": -1.0})


def test_float_underflow_like_badvalue():
    p = LearningParam()
    with pytest.raises(ParamError):
        p.init({"learning_rate": "not_a_number"})
    with pytest.raises(ParamError):
        p.init({"num_hidden": "2.5"})  # non-integral


def test_enum():
    p = LearningParam()
    p.init({"activation": "tanh"})
    assert p.activation == "tanh"
    with pytest.raises(ParamError):
        p.init({"activation": "gelu"})


def test_alias():
    p = LearningParam()
    p.init({"moniker": "alpha"})
    assert p.name == "alpha"


def test_unknown_rejected_and_allowed():
    p = LearningParam()
    with pytest.raises(ParamError):
        p.init({"numhidden": 10})
    unknown = p.init({"numhidden": 10, "num_hidden": 7}, allow_unknown=True)
    assert unknown == {"numhidden": 10}
    assert p.num_hidden == 7


def test_required():
    p = RequiredParam()
    with pytest.raises(ParamError):
        p.init({})
    p.init({"size": 5})
    assert p.size == 5 and p.scale == 1.0


def test_dict_and_json_roundtrip():
    p = LearningParam()
    p.init({"num_hidden": 42, "activation": "sigmoid"})
    d = p.to_dict()
    assert d["num_hidden"] == 42
    s = p.save_json()
    q = LearningParam()
    q.load_json(s)
    assert q == p


def test_stream_save_load():
    p = LearningParam()
    p.init({"num_hidden": 9})
    buf = _io.BytesIO()
    p.save(buf)
    buf.seek(0)
    q = LearningParam()
    q.load(buf)
    assert q.num_hidden == 9


def test_docstring():
    doc = LearningParam.doc_string()
    assert "num_hidden" in doc and "range=[1, 10000]" in doc
    assert "choices=['relu', 'tanh', 'sigmoid']" in doc


def test_get_env(monkeypatch):
    monkeypatch.setenv("DMLC_TEST_NUM", "17")
    assert get_env("DMLC_TEST_NUM", 3) == 17
    assert get_env("DMLC_TEST_MISSING", 3) == 3
    monkeypatch.setenv("DMLC_TEST_FLAG", "true")
    assert get_env("DMLC_TEST_FLAG", False) is True


def test_param_fuzz_never_crashes_unstructured():
    """Generative sweep: arbitrary key/value strings through a Parameter
    struct either succeed or raise ParamError — never any other failure
    (the CLI feeds raw user config straight into init)."""
    import numpy as np
    from dmlc_core_tpu.models.cli import TrainParams
    from dmlc_core_tpu.utils import ParamError

    rng = np.random.default_rng(0)
    keys = ["data", "model", "dim", "epochs", "lr", "task", "bogus",
            "batch_rows", "", "features", "résumé", "mode", "a b"]
    vals = ["", "fm", "x", "-1", "0", "1e9", "3.5", "True", "none",
            "libsvm", "🤖", "1,2", " 7 ", "nan", "inf", "-"]
    for _ in range(300):
        kv = {str(rng.choice(keys)): str(rng.choice(vals))
              for _ in range(int(rng.integers(1, 6)))}
        try:
            p = TrainParams()
            p.init(dict(kv))
        except ParamError:
            continue
        # success ⇒ every set field round-trips through to_dict
        d = p.to_dict()
        assert isinstance(d, dict)
