"""Mesh helpers + eager MeshCollectives on the 8-device virtual CPU mesh, and
the driver dry-run entry (full sharded train step)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from dmlc_core_tpu.parallel import (MeshCollectives, data_parallel_mesh,  # noqa: E402
                                    make_mesh, parse_mesh_spec)
from dmlc_core_tpu.utils import DMLCError  # noqa: E402


def need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_parse_mesh_spec():
    assert parse_mesh_spec("dp=4,mp=2") == {"dp": 4, "mp": 2}
    assert parse_mesh_spec("dp=-1") == {"dp": -1}
    with pytest.raises(DMLCError):
        parse_mesh_spec("dp")


def test_make_mesh_shapes():
    need8()
    m = make_mesh("dp=4,mp=2")
    assert dict(m.shape) == {"dp": 4, "mp": 2}
    m2 = make_mesh("dp=-1,mp=2")
    assert dict(m2.shape) == {"dp": 4, "mp": 2}
    m3 = data_parallel_mesh()
    assert dict(m3.shape) == {"dp": 8}


def test_mesh_collectives_allreduce_broadcast_allgather():
    need8()
    mesh = data_parallel_mesh()
    coll = MeshCollectives(mesh, "dp")
    world = coll.world_size
    per_rank = np.stack([np.full(3, r, np.float32) for r in range(world)])
    np.testing.assert_allclose(coll.allreduce(per_rank),
                               per_rank.sum(axis=0))
    np.testing.assert_allclose(coll.allreduce(per_rank, op="max"),
                               per_rank.max(axis=0))
    np.testing.assert_allclose(coll.broadcast(per_rank, root=3),
                               per_rank[3])
    np.testing.assert_allclose(coll.allgather(per_rank), per_rank)


def test_mesh_collectives_all_to_all_transposes_rank_blocks():
    """The embedding-exchange primitive: rank s's d-th slot lands in rank
    d's s-th slot — globally a transpose of the leading two axes."""
    need8()
    mesh = data_parallel_mesh()
    coll = MeshCollectives(mesh, "dp")
    world = coll.world_size
    per_rank = np.arange(world * world * 3, dtype=np.float32).reshape(
        world, world, 3)
    got = coll.all_to_all(per_rank)
    np.testing.assert_allclose(got, per_rank.swapaxes(0, 1))
    # involution: exchanging twice is the identity
    np.testing.assert_allclose(coll.all_to_all(np.asarray(got)), per_rank)


def test_graft_entry_dryrun():
    need8()
    import sys
    sys.path.insert(0, "/root/repo")
    try:
        import __graft_entry__ as g
        g.dryrun_multichip(8)
        fn, (params, batch) = g.entry()
        out = jax.jit(fn)(params, batch)
        assert out.shape == (1024,)
    finally:
        sys.path.pop(0)


def test_kbatch_scan_matches_sequential_on_dp_mesh():
    """make_train_step_kbatch: k dp-sharded steps in ONE dispatch follow
    the same trajectory as k sequential mesh steps (the RTT-amortization
    primitive composed with GSPMD's gradient all-reduce)."""
    import optax

    from dmlc_core_tpu.models import (FactorizationMachine, make_train_step,
                                      make_train_step_kbatch, param_shardings,
                                      shard_params, stack_batches)

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    mesh = Mesh(np.array(devices), ("dp",))
    model = FactorizationMachine(num_features=64, dim=8)
    opt = optax.adam(0.05)

    def mk_batch(seed):
        r = np.random.default_rng(seed)
        rows, nnz = 64, 256
        rp = np.linspace(0, nnz, rows + 1).astype(np.int32)
        return {
            "ids": jnp.asarray(r.integers(0, 64, nnz), jnp.int32),
            "vals": jnp.asarray(r.random(nnz), jnp.float32),
            "segments": jnp.asarray(
                np.repeat(np.arange(rows), np.diff(rp)), jnp.int32),
            "labels": jnp.asarray(r.integers(0, 2, rows), jnp.float32),
            "weights": jnp.ones(rows, jnp.float32),
        }

    batches = [mk_batch(s) for s in range(5)]

    def init_state():
        params = model.init(jax.random.PRNGKey(1))
        params = shard_params(params,
                              param_shardings(model, params, mesh))
        return params, opt.init(params)

    # sequential mesh steps (the proven baseline path)
    params_a, opt_a = init_state()
    step = make_train_step(model, opt, mesh, donate=False)
    for b in batches:
        params_a, opt_a, loss_a = step(params_a, opt_a, b)

    # one scanned dispatch over the stacked batches
    params_b, opt_b = init_state()
    kstep = make_train_step_kbatch(model, opt, mesh, donate=False)
    params_b, opt_b, losses = kstep(params_b, opt_b,
                                    stack_batches(batches))
    assert losses.shape == (5,)
    np.testing.assert_allclose(float(losses[-1]), float(loss_a),
                               rtol=1e-5, atol=1e-6)
    for key in params_a:
        np.testing.assert_allclose(np.asarray(params_b[key]),
                                   np.asarray(params_a[key]),
                                   rtol=1e-5, atol=1e-6)
