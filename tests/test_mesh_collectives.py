"""Mesh helpers + eager MeshCollectives on the 8-device virtual CPU mesh, and
the driver dry-run entry (full sharded train step)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.parallel import (MeshCollectives, data_parallel_mesh,  # noqa: E402
                                    make_mesh, parse_mesh_spec)
from dmlc_core_tpu.utils import DMLCError  # noqa: E402


def need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_parse_mesh_spec():
    assert parse_mesh_spec("dp=4,mp=2") == {"dp": 4, "mp": 2}
    assert parse_mesh_spec("dp=-1") == {"dp": -1}
    with pytest.raises(DMLCError):
        parse_mesh_spec("dp")


def test_make_mesh_shapes():
    need8()
    m = make_mesh("dp=4,mp=2")
    assert dict(m.shape) == {"dp": 4, "mp": 2}
    m2 = make_mesh("dp=-1,mp=2")
    assert dict(m2.shape) == {"dp": 4, "mp": 2}
    m3 = data_parallel_mesh()
    assert dict(m3.shape) == {"dp": 8}


def test_mesh_collectives_allreduce_broadcast_allgather():
    need8()
    mesh = data_parallel_mesh()
    coll = MeshCollectives(mesh, "dp")
    world = coll.world_size
    per_rank = np.stack([np.full(3, r, np.float32) for r in range(world)])
    np.testing.assert_allclose(coll.allreduce(per_rank),
                               per_rank.sum(axis=0))
    np.testing.assert_allclose(coll.allreduce(per_rank, op="max"),
                               per_rank.max(axis=0))
    np.testing.assert_allclose(coll.broadcast(per_rank, root=3),
                               per_rank[3])
    np.testing.assert_allclose(coll.allgather(per_rank), per_rank)


def test_graft_entry_dryrun():
    need8()
    import sys
    sys.path.insert(0, "/root/repo")
    try:
        import __graft_entry__ as g
        g.dryrun_multichip(8)
        fn, (params, batch) = g.entry()
        out = jax.jit(fn)(params, batch)
        assert out.shape == (1024,)
    finally:
        sys.path.pop(0)
