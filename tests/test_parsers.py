"""Format parser tests (reference libsvm/libfm/csv parser tests): native and
fallback kernels agree; streaming parser over partitions covers all rows."""

import numpy as np
import pytest

from dmlc_core_tpu import native
from dmlc_core_tpu.data import create_parser, py_parsers
from dmlc_core_tpu.utils import DMLCError

LIBSVM = b"""1 1:0.5 3:1.5 7:2
0:0.5 2:1
1 9:3.5
0
-1:2 4:1 5:1
"""
LIBFM = b"""1 0:1:0.5 2:3:1.5
0:0.5 1:2:1
"""
CSV = b"""1.0,0.5,2.5
0.0,1.5,3.5
1.0,2.5,4.5
"""


def kernels(fmt):
    ks = [getattr(py_parsers, f"parse_{fmt}")]
    if native.available():
        ks.append(getattr(native, f"parse_{fmt}"))
    return ks


@pytest.mark.parametrize("kernel", kernels("libsvm"))
def test_libsvm_kernel(kernel):
    d = kernel(LIBSVM)
    np.testing.assert_array_equal(d["offsets"], [0, 3, 4, 5, 5, 7])
    np.testing.assert_array_equal(d["labels"], [1, 0, 1, 0, -1])
    np.testing.assert_array_equal(d["weights"], [1, 0.5, 1, 1, 2])
    np.testing.assert_array_equal(d["indices"], [1, 3, 7, 2, 9, 4, 5])
    np.testing.assert_allclose(d["values"], [0.5, 1.5, 2, 1, 3.5, 1, 1])
    assert d["max_index"] == 9


@pytest.mark.parametrize("kernel", kernels("libfm"))
def test_libfm_kernel(kernel):
    d = kernel(LIBFM)
    np.testing.assert_array_equal(d["fields"], [0, 2, 1])
    np.testing.assert_array_equal(d["indices"], [1, 3, 2])
    np.testing.assert_allclose(d["values"], [0.5, 1.5, 1.0])
    np.testing.assert_array_equal(d["labels"], [1, 0])
    np.testing.assert_array_equal(d["weights"], [1, 0.5])
    assert d["max_field"] == 2 and d["max_index"] == 3


@pytest.mark.parametrize("kernel", kernels("csv"))
def test_csv_kernel(kernel):
    d = kernel(CSV, 0)  # label_col=0
    np.testing.assert_array_equal(d["labels"], [1, 0, 1])
    np.testing.assert_array_equal(d["offsets"], [0, 2, 4, 6])
    np.testing.assert_allclose(d["values"], [0.5, 2.5, 1.5, 3.5, 2.5, 4.5])
    assert d["max_index"] == 1


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_native_matches_fallback_on_fuzz():
    rng = np.random.default_rng(0)
    lines = []
    for i in range(500):
        n = int(rng.integers(0, 20))
        idx = sorted(rng.choice(10000, size=n, replace=False).tolist())
        feats = " ".join(f"{j}:{rng.random()*10:.6f}" for j in idx)
        label = int(rng.integers(0, 2))
        w = f":{rng.random():.4f}" if rng.random() < 0.3 else ""
        lines.append(f"{label}{w} {feats}")
    data = ("\n".join(lines) + "\n").encode()
    a = native.parse_libsvm(data)
    b = py_parsers.parse_libsvm(data)
    np.testing.assert_array_equal(a["offsets"], b["offsets"])
    np.testing.assert_array_equal(a["indices"], b["indices"])
    np.testing.assert_allclose(a["values"], b["values"], rtol=1e-5)
    np.testing.assert_allclose(a["labels"], b["labels"])
    np.testing.assert_allclose(a["weights"], b["weights"], rtol=1e-5)


def test_streaming_parser_partitions(tmp_path):
    rng = np.random.default_rng(1)
    lines = []
    for i in range(3000):
        n = int(rng.integers(1, 10))
        idx = sorted(rng.choice(1000, size=n, replace=False).tolist())
        lines.append(f"{i % 2} " + " ".join(f"{j}:1.5" for j in idx))
    path = tmp_path / "train.libsvm"
    path.write_text("\n".join(lines) + "\n")

    total_rows = 0
    all_labels = []
    for k in range(4):
        with create_parser(str(path), k, 4, "libsvm") as p:
            for c in p:
                blk = c.get_block()
                total_rows += blk.size
                all_labels.extend(blk.labels.tolist())
    assert total_rows == 3000
    assert sum(all_labels) == sum(i % 2 for i in range(3000))


def test_parser_auto_format(tmp_path):
    path = tmp_path / "d.txt"
    path.write_text("1.0,2.0\n0.0,3.0\n")
    with create_parser(f"{path}?format=csv&label_column=0") as p:
        blocks = list(p)
    assert sum(b.get_block().size for b in blocks) == 2
    lbls = np.concatenate([b.get_block().labels for b in blocks])
    np.testing.assert_array_equal(sorted(lbls.tolist()), [0.0, 1.0])
    with pytest.raises(DMLCError):
        create_parser(str(path), parser_type="parquet")


MALFORMED_CASES = [
    (b"1,abc,3\n2,3,4\n", "csv", {"label_col": -1}),   # bad field drops row
    (b"1, 2 ,3\n", "csv", {"label_col": 0}),           # spaces around fields
    (b"1,2,\n", "csv", {"label_col": -1}),             # trailing empty cell
    (b"1 3 5 7\n", "libsvm", {}),                      # value-less implicit 1.0
    (b"1:bad 2:3\n", "libsvm", {}),                    # bad weight drops row
    (b"1 1:1e1000000000\n", "libsvm", {}),             # hostile exponent
    (b"1 2:3.5e-2 4:2E3\n", "libsvm", {}),             # scientific notation
]


@pytest.mark.skipif(not native.available(), reason="native lib not built")
@pytest.mark.parametrize("data,fmt,kw", MALFORMED_CASES)
def test_native_fallback_parity_on_malformed(data, fmt, kw):
    # the two kernels must produce identical results so training data does
    # not depend on whether libdmlc_native.so happens to be built
    a = getattr(native, f"parse_{fmt}")(data, **kw)
    b = getattr(py_parsers, f"parse_{fmt}")(data, **kw)
    np.testing.assert_array_equal(a["offsets"], b["offsets"])
    np.testing.assert_array_equal(a["labels"], b["labels"])
    np.testing.assert_allclose(a["values"], b["values"], rtol=1e-6)
    assert a["bad_lines"] == b["bad_lines"]


def test_valueless_libsvm_implicit_one():
    d = py_parsers.parse_libsvm(b"1 3 5 7\n")
    np.testing.assert_array_equal(d["indices"], [3, 5, 7])
    np.testing.assert_array_equal(d["values"], [1.0, 1.0, 1.0])


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_zero_copy_view_lifetime():
    import gc
    out = native.parse_libsvm(b"1 1:0.5 2:1.5\n0 3:2.5\n")
    vals = out["values"]
    del out
    gc.collect()
    assert vals.tolist() == [0.5, 1.5, 2.5]  # view owns the native block


def test_bad_lines_counted():
    d = py_parsers.parse_libsvm(b"1 3:1\nnot_a_label x\n0 5:2\n")
    assert d["bad_lines"] == 1
    np.testing.assert_array_equal(d["labels"], [1, 0])
    if native.available():
        d2 = native.parse_libsvm(b"1 3:1\nnot_a_label x\n0 5:2\n")
        assert d2["bad_lines"] >= 1
        np.testing.assert_array_equal(d2["labels"], [1, 0])


def test_native_float_leading_zeros_and_line_endings():
    """Regression: integer-mantissa float parse must not count leading zeros
    as significant digits, and lone-CR / CRLF line endings must split
    records exactly like the pure-python kernels."""
    import numpy as np
    from dmlc_core_tpu import native
    if not native.available():
        import pytest
        pytest.skip("native lib unavailable")
    d = native.parse_libsvm(
        b"1 1:0.0000000000000000001 2:00000000000000000012 3:0.0005 4:007\n", 1)
    for got, want in zip(d["values"], [1e-19, 12.0, 0.0005, 7.0]):
        assert abs(got - np.float32(want)) <= abs(np.float32(want)) * 1e-6
    d = native.parse_libsvm(b"1 1:2\r0 2:3\r", 1)
    assert list(d["labels"]) == [1.0, 0.0] and list(d["indices"]) == [1, 2]
    d = native.parse_csv(b"1,2.5,3\r\n0,1.5,4\r\n", 0, ",", 1)
    assert list(d["labels"]) == [1.0, 0.0]
    assert list(d["values"]) == [2.5, 3.0, 1.5, 4.0]


@pytest.mark.skipif(not native.available(), reason="native lib not built")
@pytest.mark.parametrize("fmt,gen", [
    ("libsvm", lambda i, rng: " ".join(
        f"{j}:{rng.random()*10:.6f}"
        for j in sorted(rng.choice(10000, size=int(rng.integers(0, 15)),
                                   replace=False).tolist()))),
    ("libfm", lambda i, rng: " ".join(
        f"{int(rng.integers(0, 30))}:{j}:{rng.random():.4f}"
        for j in sorted(rng.choice(10000, size=int(rng.integers(0, 10)),
                                   replace=False).tolist()))),
])
def test_multithread_parse_equivalence(fmt, gen):
    """VERDICT r2 #7: the OpenMP chunk-cut + merge path (nthreads=4) must
    produce output identical to the sequential path (nthreads=1) — row
    order, offsets, values, per-value fields — on data large enough that
    every thread really owns a chunk (reference `text_parser.h:100-115`)."""
    rng = np.random.default_rng(42)
    lines = []
    for i in range(4000):
        label = int(rng.integers(0, 2))
        feats = gen(i, rng)
        lines.append(f"{label} {feats}" if feats else f"{label}")
    data = ("\n".join(lines) + "\n").encode()
    kernel = getattr(native, f"parse_{fmt}")
    a = kernel(data, nthreads=1)
    b = kernel(data, nthreads=4)
    for key in ("offsets", "indices", "labels"):
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)
    np.testing.assert_allclose(a["values"], b["values"], rtol=0)
    if fmt == "libfm":
        np.testing.assert_array_equal(a["fields"], b["fields"])
    assert a["bad_lines"] == b["bad_lines"]
    assert len(a["offsets"]) == 4001


def test_float_shapes_exact_vs_python():
    """The SWAR float fast path (one-window 'd.dddd' splice) must agree
    with Python's float() to the float32 ulp across shape edge cases:
    dot positions, window-boundary lengths, leading zeros, exponents,
    signs, and value-less fallthroughs."""
    shapes = ["0.5", "0.25", "0.1234", "0.123456", "0.1234567",
              "0.12345678", "12.5", "123.4567", "1234567.1",
              ".5", ".0625", "0.0", "00.5", "7", "42", "1234567",
              "1e3", "1.5e-4", "2.5E2", "-0.75", "+0.125",
              "0.00001", "12345.67", "999999.9", "3.14159265358979",
              # dot at/near the 8-byte window boundary (the d==7 shape was
              # a UB shift-by-64 before the d<7 guard)
              "1234567.5", "1234567.", "123456.7", "12345678.5",
              "1234567.89", "999999.", "0.9999999"]
    lines = []
    for i, s in enumerate(shapes):
        lines.append(f"{i % 2} {i}:{s}")
    data = ("\n".join(lines) + "\n").encode()
    out = native.parse_libsvm(data)
    got = out["values"]
    want = np.array([np.float32(float(s)) for s in shapes], np.float32)
    np.testing.assert_array_equal(got.view(np.uint32), want.view(np.uint32),
                                  err_msg=str(list(zip(shapes, got, want))))


def test_parser_before_first_mid_stream(tmp_path):
    """Mid-stream before_first on the (threaded) parser must replay from
    the top — the ThreadedIter reset protocol drains the producer without
    leaking the in-flight chunk into epoch 2 (reference
    split_repeat_read_test.cc discipline, one layer up)."""
    rng = np.random.default_rng(3)
    path = tmp_path / "m.libsvm"
    with open(path, "w") as f:
        for i in range(2000):
            idx = sorted(rng.choice(500, 4, replace=False).tolist())
            f.write(f"{i % 2} " + " ".join(f"{j}:1" for j in idx) + "\n")
    for threaded in (False, True):
        with create_parser(str(path), 0, 1, "libsvm",
                           threaded=threaded) as p:
            it = iter(p)
            first = next(it).get_block()
            head = first.labels[:5].tolist()
            p.before_first()
            labels = []
            for c in p:
                labels.extend(c.get_block().labels.tolist())
        assert len(labels) == 2000, threaded
        assert labels[:5] == head, threaded
        assert sum(labels) == sum(i % 2 for i in range(2000)), threaded
