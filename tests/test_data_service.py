"""Disaggregated ingest data service: lease lifecycle, exactly-once
delivery under worker churn, stale-grant rejection, the stranded-sender
timeout, and the ambient serving autotune loop.

Chaos schedules ride the fault-injection harness (``DMLC_FAULT_SPEC`` /
``inject_faults``) — deterministic counts, bounded wall time, every test
asserting both that the fault fired and that the fleet absorbed it."""

import hashlib
import socket
import threading
import time
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.pipeline.data_service import (  # noqa: E402
    DataServiceLoader, DataServiceWorker, Dispatcher, dispatcher_rpc)
from dmlc_core_tpu.pipeline.device_loader import (  # noqa: E402
    DeviceLoader, _fused_words_meta, _put_fused_buf)
from dmlc_core_tpu.utils import clear_faults, inject_faults  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

from conftest import free_port, start_ingest_worker  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    clear_faults()
    yield
    clear_faults()


def _counter(name):
    return metrics.counter(name).value


ROWS = 400
BATCH_ROWS = 32
NNZ_CAP = 1024


def _libsvm(tmp_path, rows=ROWS):
    """Labels are 1..rows (never 0): fused-frame padding rows carry label
    0, so a nonzero label identifies a real row unambiguously."""
    rng = np.random.default_rng(7)
    path = tmp_path / "ds.libsvm"
    with open(path, "w") as f:
        for i in range(rows):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    return str(path)


def _spec(uri, num_parts):
    return {"uri": uri, "fmt": "libsvm", "num_parts": num_parts,
            "batch_rows": BATCH_ROWS, "nnz_cap": NNZ_CAP}


def _frame_digest(buf, meta):
    words = _fused_words_meta(BATCH_ROWS, int(meta))
    return hashlib.sha1(np.asarray(buf)[:words].tobytes()).hexdigest()


def _drain(loader):
    """Consume one epoch: (label multiset, frame-digest multiset)."""
    labels, digests = Counter(), Counter()
    for kind, buf, meta, _rows in loader:
        assert kind == "fused"
        digests[_frame_digest(buf, meta)] += 1
        out = _put_fused_buf(
            np.asarray(buf)[: _fused_words_meta(BATCH_ROWS, int(meta))],
            BATCH_ROWS, int(meta))
        labels.update(int(x) for x in np.asarray(out["labels"])
                      if int(x) > 0)
        loader.recycle(buf)
    return labels, digests


def _single_host_baseline(uri, num_parts):
    """The ground truth a fleet epoch must reproduce: every part served
    by one local DeviceLoader with the worker's exact parser config."""
    labels, digests = Counter(), Counter()
    for part in range(num_parts):
        loader = DeviceLoader(
            create_parser(uri, part, num_parts, "libsvm", nthreads=1,
                          threaded=False),
            batch_rows=BATCH_ROWS, nnz_cap=NNZ_CAP, emit="host")
        try:
            for kind, buf, meta, _rows in loader:
                digests[_frame_digest(buf, meta)] += 1
                out = _put_fused_buf(
                    np.asarray(buf)[: _fused_words_meta(BATCH_ROWS,
                                                        int(meta))],
                    BATCH_ROWS, int(meta))
                labels.update(int(x) for x in np.asarray(out["labels"])
                              if int(x) > 0)
        finally:
            loader.close()
    return labels, digests


def _wait_for(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# lease state machine (dispatcher alone, RPC-level fake workers)
# ---------------------------------------------------------------------------

def test_expired_lease_regranted_exactly_once(tmp_path):
    """A granted lease whose TTL lapses is re-queued ONCE with a bumped
    lease epoch — the sweep must not regrant an already-pending shard on
    every pass."""
    uri = _libsvm(tmp_path)
    e0 = _counter("data_service.leases_expired")
    with Dispatcher(lease_ttl_s=0.3, heartbeat_timeout_s=60.0) as d:
        d.start()
        dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": "w1",
                                   "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 2)})["key"]
        lease = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                           "jobid": "w1"})["lease"]
        assert lease["part"] == 0 and lease["lease_epoch"] == 1
        assert _wait_for(lambda: d.dataset_status(key)["regrants"] == 1,
                         timeout=5.0), d.dataset_status(key)
        assert _counter("data_service.leases_expired") - e0 == 1
        # several sweep intervals later the count must still be one: a
        # pending shard is not "expired" again and again
        time.sleep(0.5)
        assert d.dataset_status(key)["regrants"] == 1
        # the re-queued shard goes out under the NEW lease epoch and a
        # completion against it lands
        lease2 = dispatcher_rpc(d.address, {"cmd": "next_lease",
                                            "key": key,
                                            "jobid": "w1"})["lease"]
        assert lease2["part"] == 0 and lease2["lease_epoch"] == 2
        ok = dispatcher_rpc(d.address, {"cmd": "complete_lease",
                                        "key": key, "part": 0,
                                        "lease_epoch": 2, "jobid": "w1"})
        assert ok["ok"] is True


def test_stale_completion_from_resurrected_worker_rejected(tmp_path):
    """A worker that went silent, lost its lease to a regrant, and then
    reports the OLD grant complete must be rejected — the shard now
    belongs to the new lease epoch."""
    uri = _libsvm(tmp_path)
    s0 = _counter("data_service.stale_completions")
    with Dispatcher(lease_ttl_s=0.3, heartbeat_timeout_s=60.0) as d:
        d.start()
        for w in ("w1", "w2"):
            dispatcher_rpc(d.address, {"cmd": "register_worker", "jobid": w,
                                       "host": "127.0.0.1", "port": 1})
        key = dispatcher_rpc(d.address, {"cmd": "register_dataset",
                                         "spec": _spec(uri, 1)})["key"]
        dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                   "jobid": "w1"})
        assert _wait_for(lambda: d.dataset_status(key)["regrants"] == 1,
                         timeout=5.0)
        # w1 resurrects and finishes the shard it no longer owns
        stale = dispatcher_rpc(d.address, {"cmd": "complete_lease",
                                           "key": key, "part": 0,
                                           "lease_epoch": 1, "jobid": "w1"})
        assert stale == {"ok": False, "stale": True}
        assert _counter("data_service.stale_completions") - s0 == 1
        assert d.dataset_status(key)["completed"] == 0
        # the survivor's completion under the current epoch stands
        lease = dispatcher_rpc(d.address, {"cmd": "next_lease", "key": key,
                                           "jobid": "w2"})["lease"]
        ok = dispatcher_rpc(d.address, {"cmd": "complete_lease",
                                        "key": key, "part": 0,
                                        "lease_epoch":
                                            lease["lease_epoch"],
                                        "jobid": "w2"})
        assert ok["ok"] is True
        assert d.dataset_status(key)["completed"] == 1


# ---------------------------------------------------------------------------
# chaos: worker death and mid-shard send failure, exactly-once both ways
# ---------------------------------------------------------------------------

def test_worker_killed_mid_epoch_rows_and_checksums_match(tmp_path,
                                                          monkeypatch):
    """DMLC_FAULT_SPEC kills one fleet worker between lease grant and
    first frame; the epoch must still deliver every row exactly once and
    every frame byte-identical to the single-host baseline."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 4)
    assert set(base_labels) == set(range(1, ROWS + 1))
    f0 = _counter("faults.data_service.lease.errors")
    d0 = _counter("data_service.dead_workers")
    r0 = _counter("data_service.lease_regrants")
    # the second lease pull anywhere in the fleet dies — a hard kill: no
    # deregistration, no cleanup; the dispatcher must notice via missed
    # heartbeats and the consumer via the broken stream
    monkeypatch.setenv("DMLC_FAULT_SPEC",
                       "data_service.lease:error=1:times=1:after=1")
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=0.5) as d:
        d.start()
        workers = [DataServiceWorker(d.address,
                                     heartbeat_interval_s=0.1).start()
                   for _ in range(2)]
        try:
            ldr = DataServiceLoader(d.address, _spec(uri, 4))
            labels, digests = _drain(ldr)
            ldr.close()
        finally:
            for w in workers:
                w.kill()
    assert _counter("faults.data_service.lease.errors") - f0 == 1
    assert labels == base_labels          # every row exactly once
    assert digests == base_digests        # every frame byte-identical
    assert _counter("data_service.dead_workers") - d0 >= 1
    assert _counter("data_service.lease_regrants") - r0 >= 1


def test_send_fault_mid_shard_replays_with_dedup(tmp_path):
    """An ingest.send failure mid-shard fails the lease (worker stays
    alive); the replay re-serves the shard from frame 0 and the consumer
    discards the prefix it already delivered."""
    uri = _libsvm(tmp_path)
    base_labels, base_digests = _single_host_baseline(uri, 2)
    dup0 = _counter("data_service.client.dup_frames")
    fo0 = _counter("data_service.client.failovers")
    with Dispatcher(lease_ttl_s=10.0, heartbeat_timeout_s=10.0) as d:
        d.start()
        with DataServiceWorker(d.address) as w:
            w.start()
            # frames 1-2 of the first shard land, frame 3's send dies
            with inject_faults("ingest.send:error=1:times=1:after=2"):
                ldr = DataServiceLoader(d.address, _spec(uri, 2))
                labels, digests = _drain(ldr)
                assert labels == base_labels
                assert digests == base_digests
                # the delivered prefix of the replayed shard was dropped,
                # not re-yielded
                assert _counter("data_service.client.dup_frames") - dup0 \
                    == 2
                assert _counter("data_service.client.failovers") - fo0 >= 1
                # the worker survived the fault: the next epoch streams
                # clean end to end through the same process
                labels2, digests2 = _drain(ldr)
                assert labels2 == base_labels
                assert digests2 == base_digests
                ldr.close()


# ---------------------------------------------------------------------------
# satellite: serve_ingest stranded-sender timeout
# ---------------------------------------------------------------------------

def test_stranded_consumer_times_out_and_worker_serves_again(tmp_path,
                                                             monkeypatch):
    """A consumer that connects and stops draining must not wedge the
    ingest worker forever: the send times out (DMLC_INGEST_SEND_TIMEOUT),
    ``ingest.client_drops`` counts it, and the worker serves the next
    connection in full."""
    # the payload must overflow what a stalled loopback connection can
    # swallow in kernel buffers (~4 MB of autotuned sndbuf + the rcvbuf)
    # or sendall never blocks: ~6 MB of identical dense-ish rows
    path = tmp_path / "big.libsvm"
    nrows = 12000
    body = " ".join(f"{j}:1" for j in range(1, 65))
    with open(path, "w") as f:
        for i in range(nrows):
            f.write(f"{i + 1} {body}\n")
    monkeypatch.setenv("DMLC_INGEST_SEND_TIMEOUT", "1")
    c0 = _counter("ingest.client_drops")
    port = start_ingest_worker(str(path), 0, 1, max_epochs=2,
                               batch_rows=64, nnz_cap=8192)
    # the stranded client: tiny receive window, connect, read nothing
    stuck = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    stuck.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    stuck.connect(("127.0.0.1", port))
    try:
        assert _wait_for(
            lambda: _counter("ingest.client_drops") - c0 == 1,
            timeout=30.0), "send never timed out"
    finally:
        stuck.close()
    # the worker is back in accept(): the second connection gets the
    # whole partition
    from dmlc_core_tpu.pipeline import RemoteIngestLoader
    rl = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64,
                            emit="host")
    seen = set()
    try:
        for kind, buf, meta, _rows in rl:
            out = _put_fused_buf(
                np.asarray(buf)[: _fused_words_meta(64, int(meta))],
                64, int(meta))
            seen.update(int(x) for x in np.asarray(out["labels"])
                        if int(x) > 0)
            rl.recycle(buf)
    finally:
        rl.close()
    assert seen == set(range(1, nrows + 1))


# ---------------------------------------------------------------------------
# satellite: ambient serving autotuner behind serve_forever
# ---------------------------------------------------------------------------

def _tiny_server():
    from dmlc_core_tpu.models import SparseLogReg
    from dmlc_core_tpu.serving import (BucketLadder, InferenceEngine,
                                       PredictionServer)
    F = 300
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.arange(F, dtype=jnp.float32) / F,
              "b": jnp.float32(0.5)}
    eng = InferenceEngine(model, params, buckets=BucketLadder([(8, 256)]))
    return PredictionServer(eng, warmup=False)


def test_serve_forever_without_autotune_is_inert(monkeypatch):
    """DMLC_AUTOTUNE unset (and =0): the foreground loop only sleeps —
    no tuner epochs, no knob movement."""
    for gate in (None, "0"):
        if gate is None:
            monkeypatch.delenv("DMLC_AUTOTUNE", raising=False)
        else:
            monkeypatch.setenv("DMLC_AUTOTUNE", gate)
        srv = _tiny_server().start()
        try:
            e0 = _counter("autotune.epochs")
            d0 = srv.batcher.max_delay_s
            assert srv.serve_forever(window_s=0.02, max_windows=2) == 2
            assert _counter("autotune.epochs") == e0
            assert srv.batcher.max_delay_s == d0
        finally:
            srv.stop()


def test_serve_forever_drives_serving_autotuner(monkeypatch):
    """DMLC_AUTOTUNE=1: each traffic-bearing window is one judged tuner
    epoch over the live batcher knobs; idle windows abort instead."""
    monkeypatch.setenv("DMLC_AUTOTUNE", "1")
    srv = _tiny_server().start()
    e0 = _counter("autotune.epochs")
    a0 = _counter("autotune.aborted")
    stop = threading.Event()

    def traffic():
        ids = np.array([1, 2, 3], dtype=np.int32)
        vals = np.ones(3, dtype=np.float32)
        ptr = np.array([0, 3], dtype=np.int32)
        while not stop.is_set():
            try:
                srv.batcher.submit(ids, vals, row_ptr=ptr).result(timeout=2)
            except Exception:       # noqa: BLE001 — shutdown race only
                return

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        time.sleep(0.1)             # first requests land before window 1
        n = srv.serve_forever(window_s=0.2, max_windows=3)
        assert n == 3
        judged = _counter("autotune.epochs") - e0
        aborted = _counter("autotune.aborted") - a0
        assert judged >= 2          # live traffic windows were judged
        assert judged + aborted >= 3
        assert metrics.gauge("autotune.objective").value > 0
    finally:
        stop.set()
        t.join(timeout=5.0)
        srv.stop()
