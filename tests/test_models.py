"""Model tests: logreg/FM learn synthetic data end-to-end through the full
ingest pipeline; mesh-sharded training matches single-device results."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.models import (FactorizationMachine, SparseLogReg,  # noqa: E402
                                  batch_sharding, fit_stream, make_eval_step,
                                  make_train_step, param_shardings,
                                  shard_params)
from dmlc_core_tpu.pipeline import DeviceLoader  # noqa: E402


def write_linear_dataset(path, rng, n=3000, f=60):
    w_true = rng.normal(size=f)
    with open(path, "w") as fh:
        for _ in range(n):
            idx = np.sort(rng.choice(f, size=10, replace=False))
            x = rng.random(10)
            y = 1 if (w_true[idx] * x).sum() > 0 else 0
            fh.write(f"{y} " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(idx, x)) + "\n")


def test_logreg_learns(tmp_path):
    rng = np.random.default_rng(0)
    path = str(tmp_path / "lin.libsvm")
    write_linear_dataset(path, rng)
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=4096)
    model = SparseLogReg(num_features=60)
    params, _ = fit_stream(model, loader, epochs=3,
                           optimizer=optax.adam(0.05), log_every=0)
    ev = make_eval_step(model)
    loader.before_first()
    corr = tot = 0.0
    for b in loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    loader.close()
    assert corr / tot > 0.88


def _per_step_baseline(model, path, batch_rows, nnz_cap, n_epochs=1):
    """The classic one-dispatch-per-step loop the fused trainer replaces."""
    opt = optax.adam(0.05)
    params = model.init(jax.random.PRNGKey(7))
    opt_state = opt.init(params)
    step = make_train_step(model, opt)
    loader = DeviceLoader(create_parser(path), batch_rows=batch_rows,
                          nnz_cap=nnz_cap)
    try:
        for _ in range(n_epochs):
            for b in loader:
                params, opt_state, loss = step(params, opt_state, b)
            loader.before_first()
    finally:
        loader.close()
    return params, float(loss)


@pytest.mark.parametrize("k", [1, 4, 7])
def test_fused_kstep_matches_per_step(tmp_path, k):
    """lax.scan k-step dispatch follows the SAME SGD trajectory as the
    per-step loop (stream order preserved across meta-change flushes and
    the partial tail group)."""
    from dmlc_core_tpu.models import FusedTrainer

    rng = np.random.default_rng(3)
    path = str(tmp_path / "lin.libsvm")
    write_linear_dataset(path, rng, n=1100, f=60)  # 1100/128 -> tail batch
    model = FactorizationMachine(num_features=60, dim=4)
    ref_params, ref_loss = _per_step_baseline(model, path, 128, 2048)

    opt = optax.adam(0.05)
    loader = DeviceLoader(create_parser(path), batch_rows=128, nnz_cap=2048,
                          emit="host")
    try:
        tr = FusedTrainer(model, opt, loader, k=k, seed=7)
        loss = tr.run_epoch()
    finally:
        loader.close()
    assert tr.steps == 9  # ceil(1100/128): every batch trained exactly once
    for key in ref_params:
        np.testing.assert_allclose(np.asarray(tr.params[key]),
                                   np.asarray(ref_params[key]),
                                   rtol=1e-5, atol=1e-6)
    assert abs(loss - ref_loss) < 1e-4


def test_fused_kstep_meta_change_flush(tmp_path):
    """Rows with wildly different nnz force multiple packer buckets; the
    trainer must flush on meta change and still train every batch once."""
    from dmlc_core_tpu.models import FusedTrainer

    rng = np.random.default_rng(4)
    path = str(tmp_path / "var.libsvm")
    with open(path, "w") as fh:
        for i in range(600):
            # alternate sparse / dense blocks to swing the nnz bucket
            nnz = 2 if (i // 64) % 2 == 0 else 30
            idx = np.sort(rng.choice(60, size=nnz, replace=False))
            y = i % 2
            fh.write(f"{y} " + " ".join(
                f"{j}:{v:.3f}" for j, v in zip(idx, rng.random(nnz))) + "\n")
    model = FactorizationMachine(num_features=60, dim=4)
    ref_params, _ = _per_step_baseline(model, path, 64, 64 * 32)
    loader = DeviceLoader(create_parser(path), batch_rows=64,
                          nnz_cap=64 * 32, emit="host")
    try:
        tr = FusedTrainer(model, optax.adam(0.05), loader, k=4, seed=7)
        tr.run_epoch()
    finally:
        loader.close()
    assert tr.steps == 10  # ceil(600/64)
    for key in ref_params:
        np.testing.assert_allclose(np.asarray(tr.params[key]),
                                   np.asarray(ref_params[key]),
                                   rtol=1e-5, atol=1e-6)


def test_fm_learns_interactions(tmp_path):
    # labels depend ONLY on a feature pair interaction — linear can't fit it
    rng = np.random.default_rng(1)
    path = str(tmp_path / "xor.libsvm")
    with open(path, "w") as fh:
        for _ in range(4000):
            a, b = rng.integers(0, 2), rng.integers(0, 2)
            y = a ^ b
            feats = [f"{0 if a else 1}:1", f"{2 if b else 3}:1"]
            fh.write(f"{y} " + " ".join(feats) + "\n")
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=1024)
    model = FactorizationMachine(num_features=4, dim=4)
    params, _ = fit_stream(model, loader, epochs=6,
                           optimizer=optax.adam(0.1), log_every=0)
    ev = make_eval_step(model)
    loader.before_first()
    corr = tot = 0.0
    for b in loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    loader.close()
    assert corr / tot > 0.95


def _run_sharded(model, path, mesh_arg, table_shard="dim"):
    """One training pass under the family sharding recipe — the shared
    harness of every sharded-vs-single equivalence test (loader args,
    recipe application, step loop live HERE once)."""
    opt = optax.sgd(0.1)
    loader = DeviceLoader(create_parser(path), batch_rows=64, nnz_cap=1024,
                          sharding=batch_sharding(mesh_arg))
    params = model.init(jax.random.PRNGKey(0))
    params = shard_params(params, param_shardings(
        model, params, mesh_arg, table_shard=table_shard))
    opt_state = opt.init(params)
    step = make_train_step(model, opt, mesh_arg, donate=False)
    losses = []
    for batch in loader:
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    loader.close()
    return losses, params


def _mesh_4x2_or_skip():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    return Mesh(np.array(devices).reshape(4, 2), ("dp", "mp"))


def _dcn_factory():
    from dmlc_core_tpu.models.dcn import DCNv2

    return DCNv2(num_features=64, dim=8, layers=2)


@pytest.mark.parametrize("model_factory", [
    lambda: FactorizationMachine(num_features=64, dim=8),
    _dcn_factory,
], ids=["fm", "dcn"])
def test_sharded_step_matches_single_device(model_factory, tmp_path):
    """dp batch + dim-sharded factor table: per-step losses must match the
    single-device run for every family member (nested DCN cross params
    included), and v really is sharded over mp."""
    mesh = _mesh_4x2_or_skip()
    rng = np.random.default_rng(2)
    path = str(tmp_path / "s.libsvm")
    write_linear_dataset(path, rng, n=512)
    model = model_factory()
    losses_single, _ = _run_sharded(model, path, None)
    losses_mesh, params_mesh = _run_sharded(model, path, mesh)
    np.testing.assert_allclose(losses_single, losses_mesh, rtol=2e-4, atol=2e-5)
    # the factor table really is sharded over mp
    assert params_mesh["v"].sharding.spec == P(None, "mp")


def test_row_sharded_table_matches_single_device(tmp_path):
    """table_shard='rows' (ps/ep-style feature sharding, SURVEY §5.8):
    losses match the single-device run bit-for-tolerance and each chip
    holds a feature slice of BOTH v and w."""
    mesh = _mesh_4x2_or_skip()
    rng = np.random.default_rng(4)
    path = str(tmp_path / "r.libsvm")
    write_linear_dataset(path, rng, n=512)

    model = FactorizationMachine(num_features=64, dim=8)

    losses_single, _ = _run_sharded(model, path, None)
    losses_rows, params_rows = _run_sharded(model, path, mesh,
                                            table_shard="rows")
    np.testing.assert_allclose(losses_single, losses_rows,
                               rtol=2e-4, atol=2e-5)
    assert params_rows["v"].sharding.spec == P("mp", None)
    assert params_rows["w"].sharding.spec == P("mp")
    with pytest.raises(ValueError):
        param_shardings(model, model.init(jax.random.PRNGKey(0)), mesh,
                        table_shard="bogus")


@pytest.mark.parametrize("engine", ["xla", "pallas"])
def test_rowmajor_forward_matches_flat(engine, tmp_path):
    """VERDICT r2 #3: the models consume rowmajor batches through the
    engine-dispatching embedding bag (pallas kernel — interpret mode on
    CPU) and must agree with the flat-CSR segment-sum path on the same
    rows."""
    rng = np.random.default_rng(3)
    path = tmp_path / "d.libsvm"
    with open(path, "w") as f:
        for i in range(200):
            n = int(rng.integers(1, 6))
            idx = sorted(rng.choice(512, n, replace=False).tolist())
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    flat_batches, row_batches = [], []
    with DeviceLoader(create_parser(str(path)), batch_rows=64,
                      nnz_cap=1024) as ld:
        flat_batches = list(ld)
    with DeviceLoader(create_parser(str(path)), batch_rows=64, nnz_cap=8,
                      layout="rowmajor") as ld:
        row_batches = list(ld)
    assert len(flat_batches) == len(row_batches)
    for Model, kw in ((SparseLogReg, {}),
                      (FactorizationMachine, {"dim": 8, "engine": engine})):
        model = Model(num_features=512, **kw)
        params = model.init(jax.random.PRNGKey(0))
        # randomize the zero-initialized leaves: an all-zero w would make
        # the linear-term comparison vacuously 0 == 0
        keys = jax.random.split(jax.random.PRNGKey(7), len(params))
        params = {k: v + 0.1 * jax.random.normal(key, v.shape, v.dtype)
                  for (k, v), key in zip(sorted(params.items()), keys)}
        for fb, rb in zip(flat_batches, row_batches):
            np.testing.assert_allclose(
                np.asarray(model.forward(params, fb)),
                np.asarray(model.forward(params, rb)),
                rtol=2e-4, atol=2e-5)


def test_rowmajor_pallas_trains(tmp_path):
    """The rowmajor+pallas path must be TRAINABLE: grads flow through the
    kernel via its custom VJP (XLA backward), and a short fit reduces the
    loss — matching the xla-engine result on the same stream."""
    import optax
    rng = np.random.default_rng(5)
    path = tmp_path / "t.libsvm"
    with open(path, "w") as f:
        for i in range(512):
            hot = [1, 2] if i % 2 else [3, 4]
            f.write(f"{i % 2} " + " ".join(f"{j}:1.0" for j in hot) + "\n")

    def run(engine):
        model = FactorizationMachine(num_features=16, dim=4, engine=engine)
        params = model.init(jax.random.PRNGKey(0))
        opt = optax.adam(5e-2)
        state = opt.init(params)
        step = make_train_step(model, opt, donate=False)
        losses = []
        with DeviceLoader(create_parser(str(path)), batch_rows=128,
                          nnz_cap=4, layout="rowmajor") as ld:
            for epoch in range(6):
                for b in ld:
                    params, state, loss = step(params, state, b)
                    losses.append(float(loss))
                ld.before_first()
        return losses

    for engine in ("pallas", "xla"):
        losses = run(engine)
        assert losses[-1] < 0.25 * losses[0], (engine, losses[0], losses[-1])


def test_streaming_auc_matches_sklearn_style_reference():
    """Binned streaming AUC equals the exact pairwise AUC within bin
    resolution, accumulates across batches, and handles weights."""
    from dmlc_core_tpu.models import streaming_auc, auc_from_histograms

    rng = np.random.default_rng(0)
    n = 4000
    labels = rng.integers(0, 2, n).astype(np.float32)
    # informative but noisy scores
    scores = (labels * 1.5 - 0.75 + rng.standard_normal(n)).astype(np.float32)
    weights = rng.random(n).astype(np.float32)

    def exact_auc(s, y, w):
        pos, neg = s[y > 0], s[y == 0]
        wp, wn = w[y > 0], w[y == 0]
        wins = ties = 0.0
        for a, wa in zip(pos, wp):
            wins += wa * (wn * (a > neg)).sum()
            ties += wa * (wn * (a == neg)).sum()
        return (wins + 0.5 * ties) / (wp.sum() * wn.sum())

    want = exact_auc(scores, labels, weights)
    # accumulate over 4 streaming batches
    pos = neg = 0.0
    for i in range(0, n, 1000):
        p, q = streaming_auc(jnp.asarray(scores[i:i + 1000]),
                             jnp.asarray(labels[i:i + 1000]),
                             jnp.asarray(weights[i:i + 1000]),
                             num_bins=4096)
        pos, neg = pos + p, neg + q
    got = float(auc_from_histograms(pos, neg))
    assert abs(got - want) < 5e-3, (got, want)

    # degenerate single-class input stays finite
    p, q = streaming_auc(jnp.asarray(scores[:10]), jnp.ones((10,)),
                         jnp.ones((10,)))
    assert np.isfinite(float(auc_from_histograms(p, q)))


def test_evaluate_stream_helper(tmp_path):
    from dmlc_core_tpu.models import evaluate_stream
    rng = np.random.default_rng(6)
    path = str(tmp_path / "e.libsvm")
    write_linear_dataset(path, rng, n=600)
    loader = DeviceLoader(create_parser(path), batch_rows=128, nnz_cap=2048)
    model = SparseLogReg(num_features=60)
    params, _ = fit_stream(model, loader, epochs=3,
                           optimizer=optax.adam(0.05), log_every=0)
    loader.before_first()
    r = evaluate_stream(model, params, loader)
    loader.close()
    assert r["accuracy"] > 0.85 and 0.85 < r["auc"] <= 1.0, r
    assert r["weight"] == 600


def test_dcn_learns_interactions(tmp_path):
    """The cross network must capture a pure pairwise interaction (XOR on
    two one-hot groups) that the linear term cannot — same bar as the FM
    interaction test, met by learned cross weights instead of a fixed
    inner-product form."""
    from dmlc_core_tpu.models.dcn import DCNv2

    rng = np.random.default_rng(4)
    path = str(tmp_path / "xor.libsvm")
    with open(path, "w") as fh:
        for _ in range(4000):
            a, b = rng.integers(0, 2), rng.integers(0, 2)
            y = a ^ b
            feats = [f"{0 if a else 1}:1", f"{2 if b else 3}:1"]
            fh.write(f"{y} " + " ".join(feats) + "\n")
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=1024)
    model = DCNv2(num_features=4, dim=8, layers=2)
    params, _ = fit_stream(model, loader, epochs=6,
                           optimizer=optax.adam(0.1), log_every=0)
    ev = make_eval_step(model)
    loader.before_first()
    corr = tot = 0.0
    for b in loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    loader.close()
    assert corr / tot > 0.95


def test_dcn_cross_layer_closed_form():
    """One cross layer is x0*(x0@W + b) + x0 exactly (DCNv2 definition) —
    pin the scan against a hand-computed numpy reference so a future
    stacking/scan refactor cannot silently reorder the recurrence."""
    from dmlc_core_tpu.models.dcn import DCNv2

    rng = np.random.default_rng(5)
    B, D = 4, 6
    x0 = rng.standard_normal((B, D)).astype(np.float32)
    w1 = rng.standard_normal((D, D)).astype(np.float32)
    b1 = rng.standard_normal(D).astype(np.float32)
    w2 = rng.standard_normal((D, D)).astype(np.float32)
    b2 = rng.standard_normal(D).astype(np.float32)
    cross = {"w": jnp.stack([w1, w2]), "b": jnp.stack([b1, b2])}
    x1 = x0 * (x0 @ w1 + b1) + x0
    x2 = x0 * (x1 @ w2 + b2) + x1            # note: x0, not x1, multiplies
    got = DCNv2._cross(cross, jnp.asarray(x0))
    np.testing.assert_allclose(np.asarray(got), x2, rtol=1e-5, atol=1e-5)


def test_dcn_rowmajor_forward_matches_flat(tmp_path):
    """Both batch layouts produce the same DCN scores on the same rows
    (the family-wide contract, VERDICT r2 #3)."""
    from dmlc_core_tpu.models.dcn import DCNv2

    rng = np.random.default_rng(6)
    path = tmp_path / "d.libsvm"
    with open(path, "w") as f:
        for i in range(200):
            n = int(rng.integers(1, 6))
            idx = sorted(rng.choice(512, n, replace=False).tolist())
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{rng.random():.4f}" for j in idx) + "\n")
    with DeviceLoader(create_parser(str(path)), batch_rows=64,
                      nnz_cap=1024) as ld:
        flat_batches = list(ld)
    with DeviceLoader(create_parser(str(path)), batch_rows=64, nnz_cap=8,
                      layout="rowmajor") as ld:
        row_batches = list(ld)
    model = DCNv2(num_features=512, dim=8, layers=2)
    params = model.init(jax.random.PRNGKey(0))
    keys = jax.random.split(jax.random.PRNGKey(7), len(params))
    params = {k: jax.tree_util.tree_map(
        lambda v, key=key: v + 0.1 * jax.random.normal(key, v.shape, v.dtype),
        v) for (k, v), key in zip(sorted(params.items()), keys)}
    for fb, rb in zip(flat_batches, row_batches):
        np.testing.assert_allclose(
            np.asarray(model.forward(params, fb)),
            np.asarray(model.forward(params, rb)),
            rtol=2e-4, atol=2e-5)


def test_dcn_registered_in_cli():
    """Registered AND reachable: the CLI enum derives from the registry,
    so a registered model must validate as a TrainParams.model value (a
    hardcoded enum once orphaned dcn — r4 review catch)."""
    from dmlc_core_tpu.models.cli import MODEL_REGISTRY, TrainParams

    assert MODEL_REGISTRY.find("dcn") is not None
    p = TrainParams()
    p.init({"data": "x.libsvm", "model": "dcn"})
    assert p.model == "dcn"


def test_plugin_model_registered_after_import_validates():
    """The model enum is LAZY (ADVICE r4): a model registered after
    models.cli imported — a user plugin — must pass TrainParams
    validation, not just MODEL_REGISTRY.find."""
    from dmlc_core_tpu.models.cli import MODEL_REGISTRY, TrainParams

    name = "plugin_model_under_test"
    MODEL_REGISTRY.register(name, "late-registered plugin")(lambda p: None)
    try:
        p = TrainParams()
        p.init({"data": "x.libsvm", "model": name})
        assert p.model == name
    finally:
        MODEL_REGISTRY.remove(name)
    with pytest.raises(Exception):
        TrainParams().init({"data": "x.libsvm", "model": name})



def test_fit_stream_host_loader_routes_through_fused(tmp_path):
    """fit_stream on an emit='host' loader trains via the k-step fused
    dispatch and learns the same task the per-step path does."""
    rng = np.random.default_rng(5)
    path = str(tmp_path / "fs.libsvm")
    write_linear_dataset(path, rng, n=2500, f=60)
    model = SparseLogReg(num_features=60)
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=4096,
                          emit="host")
    try:
        params, history = fit_stream(model, loader, epochs=4,
                                     optimizer=optax.adam(0.05),
                                     log_every=1, kstep=4)
    finally:
        loader.close()
    assert len(history) == 4 and history[-1] < history[0]
    # a device-emitting loader must REJECT kstep, not silently ignore it
    dev_loader = DeviceLoader(create_parser(path), batch_rows=256,
                              nnz_cap=4096)
    try:
        with pytest.raises(ValueError, match="emit='host'"):
            fit_stream(model, dev_loader, epochs=1, kstep=4)
    finally:
        dev_loader.close()
    ev_loader = DeviceLoader(create_parser(path), batch_rows=256,
                             nnz_cap=4096)
    ev = make_eval_step(model)
    corr = tot = 0.0
    for b in ev_loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    ev_loader.close()
    assert corr / tot > 0.85


def test_fused_kstep_fuzz_random_shapes(tmp_path):
    """Property fuzz: random row-count/nnz-distribution corpora × random k
    — the fused trainer's step count always equals the per-step loop's,
    and final params match bitwise-closely regardless of how bucket
    boundaries and tail groups land."""
    from dmlc_core_tpu.models import FusedTrainer

    rng = np.random.default_rng(12)
    for trial in range(4):
        n = int(rng.integers(150, 900))
        k = int(rng.integers(2, 9))
        batch_rows = int(rng.choice([32, 64, 128]))
        path = str(tmp_path / f"fz{trial}.libsvm")
        with open(path, "w") as fh:
            for i in range(n):
                nnz = int(rng.integers(1, 24))
                idx = np.sort(rng.choice(60, size=nnz, replace=False))
                fh.write(f"{i % 2} " + " ".join(
                    f"{j}:{v:.3f}"
                    for j, v in zip(idx, rng.random(nnz))) + "\n")
        model = FactorizationMachine(num_features=60, dim=4)
        ref_params, _ = _per_step_baseline(model, path, batch_rows,
                                           batch_rows * 24)
        loader = DeviceLoader(create_parser(path), batch_rows=batch_rows,
                              nnz_cap=batch_rows * 24, emit="host")
        try:
            tr = FusedTrainer(model, optax.adam(0.05), loader, k=k, seed=7)
            tr.run_epoch()
        finally:
            loader.close()
        expect_steps = -(-n // batch_rows)
        assert tr.steps == expect_steps, (trial, n, batch_rows, k)
        for key in ref_params:
            np.testing.assert_allclose(
                np.asarray(tr.params[key]), np.asarray(ref_params[key]),
                rtol=1e-5, atol=1e-6,
                err_msg=f"trial {trial} n={n} k={k} rows={batch_rows}")
