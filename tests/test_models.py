"""Model tests: logreg/FM learn synthetic data end-to-end through the full
ingest pipeline; mesh-sharded training matches single-device results."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.models import (FactorizationMachine, SparseLogReg,  # noqa: E402
                                  batch_sharding, fit_stream, make_eval_step,
                                  make_train_step, param_shardings,
                                  shard_params)
from dmlc_core_tpu.pipeline import DeviceLoader  # noqa: E402


def write_linear_dataset(path, rng, n=3000, f=60):
    w_true = rng.normal(size=f)
    with open(path, "w") as fh:
        for _ in range(n):
            idx = np.sort(rng.choice(f, size=10, replace=False))
            x = rng.random(10)
            y = 1 if (w_true[idx] * x).sum() > 0 else 0
            fh.write(f"{y} " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(idx, x)) + "\n")


def test_logreg_learns(tmp_path):
    rng = np.random.default_rng(0)
    path = str(tmp_path / "lin.libsvm")
    write_linear_dataset(path, rng)
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=4096)
    model = SparseLogReg(num_features=60)
    params, _ = fit_stream(model, loader, epochs=3,
                           optimizer=optax.adam(0.05), log_every=0)
    ev = make_eval_step(model)
    loader.before_first()
    corr = tot = 0.0
    for b in loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    loader.close()
    assert corr / tot > 0.88


def test_fm_learns_interactions(tmp_path):
    # labels depend ONLY on a feature pair interaction — linear can't fit it
    rng = np.random.default_rng(1)
    path = str(tmp_path / "xor.libsvm")
    with open(path, "w") as fh:
        for _ in range(4000):
            a, b = rng.integers(0, 2), rng.integers(0, 2)
            y = a ^ b
            feats = [f"{0 if a else 1}:1", f"{2 if b else 3}:1"]
            fh.write(f"{y} " + " ".join(feats) + "\n")
    loader = DeviceLoader(create_parser(path), batch_rows=256, nnz_cap=1024)
    model = FactorizationMachine(num_features=4, dim=4)
    params, _ = fit_stream(model, loader, epochs=6,
                           optimizer=optax.adam(0.1), log_every=0)
    ev = make_eval_step(model)
    loader.before_first()
    corr = tot = 0.0
    for b in loader:
        c, t = ev(params, b)
        corr += float(c)
        tot += float(t)
    loader.close()
    assert corr / tot > 0.95


def test_sharded_step_matches_single_device(tmp_path):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    mesh = Mesh(np.array(devices).reshape(4, 2), ("dp", "mp"))
    rng = np.random.default_rng(2)
    path = str(tmp_path / "s.libsvm")
    write_linear_dataset(path, rng, n=512)

    model = FactorizationMachine(num_features=64, dim=8)
    opt = optax.sgd(0.1)

    def run(mesh_arg):
        loader = DeviceLoader(create_parser(path), batch_rows=64, nnz_cap=1024,
                              sharding=batch_sharding(mesh_arg))
        params = model.init(jax.random.PRNGKey(0))
        params = shard_params(params, param_shardings(model, params, mesh_arg))
        opt_state = opt.init(params)
        step = make_train_step(model, opt, mesh_arg, donate=False)
        losses = []
        for batch in loader:
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        loader.close()
        return losses, params

    losses_single, _ = run(None)
    losses_mesh, params_mesh = run(mesh)
    np.testing.assert_allclose(losses_single, losses_mesh, rtol=2e-4, atol=2e-5)
    # the factor table really is sharded over mp
    v_shard = params_mesh["v"].sharding
    assert v_shard.spec == P(None, "mp")
