"""Control-plane HA (r17): kill-any-singleton chaos drill.

Every control-plane singleton — the serving-fleet registry, the rabit
tracker, the data-service dispatcher — journals through the shared
:class:`~dmlc_core_tpu.utils.durable.StateJournal` substrate and is
killable: one subprocess harness (:func:`_spawn_singleton`) spawns each
one's module CLI, SIGKILLs it at the worst moment, and restarts it on
the same port + journal.

Targets:

* **registry mid-canary** — live serving load through the router while
  the registry dies between canary ack and promote; zero failed
  requests, exactly-once promote after the restart.
* **tracker mid-epoch** — an assigned cohort's tracker dies; restarted
  on the same journal it re-admits every worker at its old rank and the
  current generation (no spurious reset), while a *moved* worker still
  bumps the generation.
* **dispatcher mid-epoch** — the journal drill from
  ``test_data_service_v2`` rerun through the shared harness with the
  consumer on a multi-endpoint list (dead endpoint first), proving no
  replayed ingest frames.

Plus the write-ahead property tests: any prefix of the registry journal
replays consistent, a fenced (superseded) primary refuses writes, and a
warm standby takes over the lease with a higher ``control_epoch``.
"""

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import Counter

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = jax.numpy

from dmlc_core_tpu.data import create_parser  # noqa: E402
from dmlc_core_tpu.parallel.tracker import (  # noqa: E402
    recv_json, send_json)
from dmlc_core_tpu.pipeline.data_service import (  # noqa: E402
    DataServiceLoader, DataServiceWorker)
from dmlc_core_tpu.pipeline.device_loader import (  # noqa: E402
    DeviceLoader, _fused_words_meta)
from dmlc_core_tpu.models import SparseLogReg  # noqa: E402
from dmlc_core_tpu.serving import (  # noqa: E402
    BucketLadder, InferenceEngine, PredictionServer, ReplicaAgent,
    ReplicaRegistry, ServingRouter, fleet_rpc, run_load)
from dmlc_core_tpu.serving.fleet.registry import (  # noqa: E402
    REGISTRY_SNAP_SCHEMA, replay_registry_state)
from dmlc_core_tpu.transport.endpoints import (  # noqa: E402
    EndpointSet, parse_endpoints)
from dmlc_core_tpu.utils import CheckpointManager  # noqa: E402
from dmlc_core_tpu.utils.durable import FencedLease, StateJournal  # noqa: E402
from dmlc_core_tpu.utils.logging import DMLCError  # noqa: E402
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
F = 2000
BATCH_ROWS = 32
NNZ_CAP = 1024


def _wait_for(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _free_port():
    """A port nothing listens on (bound once, then released)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# the kill-any-singleton harness
# ---------------------------------------------------------------------------

def _spawn_singleton(module, **kw):
    """Spawn ``python -m <module> k=v ...``; every singleton CLI prints
    one JSON bind line on stdout.  Returns ``(proc, (host, port))``."""
    args = [f"{k}={v}" for k, v in kw.items()]
    proc = subprocess.Popen(
        [sys.executable, "-m", module] + args,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    line = proc.stdout.readline()
    assert line, f"{module} subprocess died before binding"
    doc = json.loads(line)
    return proc, (str(doc["host"]), int(doc["port"]))


def _sigkill(proc):
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()


# ---------------------------------------------------------------------------
# endpoint sets: grammar + fencing (the client half of HA)
# ---------------------------------------------------------------------------

def test_parse_endpoints_grammar():
    assert parse_endpoints(("h", 1)) == [("h", 1)]
    assert parse_endpoints("a:1,b:2, a:1") == [("a", 1), ("b", 2)]
    assert parse_endpoints([("a", 1), "b:2,c:3"]) == \
        [("a", 1), ("b", 2), ("c", 3)]
    # IPv6: the LAST colon separates host from port
    assert parse_endpoints("::1:9000") == [("::1", 9000)]
    with pytest.raises(DMLCError):
        parse_endpoints("")
    with pytest.raises(DMLCError):
        parse_endpoints("noport")


def test_endpointset_failover_and_stale_epoch_rejection():
    es = EndpointSet("a:1,b:2", name="t")
    calls = []

    def fn_factory(replies):
        def fn(addr):
            calls.append(addr)
            out = replies[addr]
            if isinstance(out, Exception):
                raise out
            return out
        return fn

    # primary answers: sticky
    assert es.call(fn_factory({("a", 1): {"ok": 1, "control_epoch": 3},
                               ("b", 2): {"ok": 2}})) == \
        {"ok": 1, "control_epoch": 3}
    assert es.control_epoch() == 3
    # primary dead → walk to b; b becomes the sticky current endpoint
    out = es.call(fn_factory({("a", 1): OSError("down"),
                              ("b", 2): {"ok": 2, "control_epoch": 4}}))
    assert out == {"ok": 2, "control_epoch": 4}
    assert es.current() == ("b", 2)
    # a reply stamped BELOW the highest seen epoch is a fenced primary:
    # rejected, call lands on the other endpoint
    out = es.call(fn_factory({("b", 2): {"ok": "stale",
                                         "control_epoch": 3},
                              ("a", 1): {"ok": "fresh",
                                         "control_epoch": 4}}))
    assert out["ok"] == "fresh"


# ---------------------------------------------------------------------------
# registry journal: prefix-replay property
# ---------------------------------------------------------------------------

def _assert_registry_consistent(state):
    assert int(state["control_epoch"]) >= 0
    for jobid, rec in state["replicas"].items():
        assert isinstance(jobid, str) and "host" in rec and "port" in rec
    for jobid, q in state["directives"].items():
        assert q, (jobid, "empty directive queue survived replay")
    ro = state["rollouts"]
    for model_id, r in ro["active"].items():
        assert r.get("id") and r.get("model_id") == model_id
        assert set(r.get("acked", [])) <= set(r.get("canaries", [])) | \
            set(r.get("acked", []))       # lists of jobids, no junk
    assert len(ro["ledger"]) <= 4096


def test_any_registry_journal_prefix_replays_consistent(tmp_path):
    """A crash can truncate the registry log after ANY record; every
    prefix must replay to a consistent control-plane state with a
    monotone ``control_epoch``."""
    prefix = str(tmp_path / "reg" / "registry")
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0, journal=prefix)
    reg.start()
    try:
        addr = reg.address
        for i in (1, 2):
            fleet_rpc(addr, {"cmd": "register_replica",
                             "jobid": f"r{i}", "host": "127.0.0.1",
                             "port": 9000 + i, "model_id": "default"})
        fleet_rpc(addr, {"cmd": "set_model", "model_id": "default",
                         "ckpt_dir": "/ck/v1", "step": 1})
        staged = fleet_rpc(addr, {"cmd": "stage_rollout",
                                  "model_id": "default",
                                  "ckpt_dir": "/ck/v2", "step": 2,
                                  "fraction": 0.5, "bake_s": 600.0})
        canary = staged["canaries"][0]
        # heartbeat drains the reload directive, then acks it
        hb = fleet_rpc(addr, {"cmd": "heartbeat", "jobid": canary})
        assert [d["kind"] for d in hb["directives"]] == ["reload"]
        fleet_rpc(addr, {"cmd": "heartbeat", "jobid": canary,
                         "applied": [{"rollout_id": staged["rollout_id"],
                                      "kind": "reload", "ok": True}]})
        fleet_rpc(addr, {"cmd": "deregister_replica", "jobid": "r2"})
        # read the journal BEFORE the clean stop compacts it away
        snap, records = StateJournal(
            prefix, snap_schema=REGISTRY_SNAP_SCHEMA).load()
    finally:
        reg.stop()
    assert len(records) >= 7          # epoch/replica/model/rollout mix
    last_epoch = 0
    for k in range(len(records) + 1):
        state = replay_registry_state(snap, records[:k])
        _assert_registry_consistent(state)
        assert state["control_epoch"] >= last_epoch
        last_epoch = state["control_epoch"]
    full = replay_registry_state(snap, records)
    assert set(full["replicas"]) == {"r1"}        # r2 deregistered
    assert full["models"]["default"]["ckpt_dir"] == "/ck/v1"
    ro = full["rollouts"]["active"]["default"]
    assert ro["canaries"] == [canary] and ro["acked"] == [canary]


# ---------------------------------------------------------------------------
# fencing: stale primary + warm-standby takeover
# ---------------------------------------------------------------------------

def test_stale_primary_writes_rejected_by_control_epoch(tmp_path):
    prefix = str(tmp_path / "fence" / "registry")
    with ReplicaRegistry(heartbeat_timeout_s=60.0, journal=prefix) as reg:
        reg.start()
        epoch = reg._control_epoch
        assert epoch >= 1
        fleet_rpc(reg.address, {"cmd": "register_replica", "jobid": "r1",
                                "host": "127.0.0.1", "port": 9001})
        # a standby took over: the shared lease now carries a higher
        # epoch than this (GC-paused, network-partitioned, ...) primary
        FencedLease(prefix + ".lease", ttl_s=60.0) \
            .refresh("usurper", epoch + 1)
        with pytest.raises(DMLCError, match="fenced"):
            fleet_rpc(reg.address, {"cmd": "set_model",
                                    "model_id": "default",
                                    "ckpt_dir": "/ck", "step": 1})
        # reads keep flowing from the fenced primary (stale-read mode);
        # the reply's epoch lets EndpointSet clients reject it
        listing = fleet_rpc(reg.address, {"cmd": "list_replicas"})
        assert listing["control_epoch"] == epoch


def test_warm_standby_takes_over_expired_lease(tmp_path, monkeypatch):
    monkeypatch.setenv("DMLC_CONTROL_LEASE_S", "0.4")
    prefix = str(tmp_path / "ha" / "registry")
    primary = ReplicaRegistry(heartbeat_timeout_s=60.0, journal=prefix)
    primary.start()
    fleet_rpc(primary.address, {"cmd": "register_replica", "jobid": "r1",
                                "host": "127.0.0.1", "port": 9001,
                                "model_id": "default"})
    epoch1 = primary._control_epoch
    standby = ReplicaRegistry(heartbeat_timeout_s=60.0, journal=prefix,
                              standby=True)
    standby.start()
    try:
        # a standby refuses writes outright pre-promotion
        with pytest.raises(DMLCError, match="standby"):
            fleet_rpc(standby.address, {"cmd": "set_model",
                                        "model_id": "default",
                                        "ckpt_dir": "/ck", "step": 1})
        # crash the primary: no stop(), the lease simply stops refreshing
        primary._stop_ev.set()
        try:
            primary._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        primary._srv.close()
        primary.rollouts.stop()
        primary._journal.close()
        assert _wait_for(lambda: not standby.standby, timeout=10.0), \
            "standby never took over the expired lease"
        # the takeover replayed the shared journal and bumped the epoch
        es = EndpointSet([primary.address, standby.address],
                         name="ha.client")
        listing = es.call(lambda addr: fleet_rpc(
            addr, {"cmd": "list_replicas"}, timeout=2.0))
        assert [r["jobid"] for r in listing["replicas"]] == ["r1"]
        assert listing["control_epoch"] > epoch1
        assert es.current() == standby.address
        ok = es.call(lambda addr: fleet_rpc(
            addr, {"cmd": "set_model", "model_id": "default",
                   "ckpt_dir": "/ck/v2", "step": 2}, timeout=2.0))
        assert ok["ok"] and standby.stable_pointer(
            "default")["ckpt_dir"] == "/ck/v2"
    finally:
        standby.stop()


# ---------------------------------------------------------------------------
# drill target 1: registry SIGKILLed mid-canary
# ---------------------------------------------------------------------------

def _engine(w_scale=1.0):
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.full((F,), w_scale, jnp.float32),
              "b": jnp.float32(0.0)}
    return InferenceEngine(model, params,
                           buckets=BucketLadder([(16, 512)]))


def _save_ckpt(directory, step, scale):
    params = {"w": jnp.full((F,), scale, jnp.float32),
              "b": jnp.float32(0.0)}
    CheckpointManager(str(directory)).save(
        step, {"params": params, "opt_state": {"count": jnp.int32(0)}},
        meta={"model": "logreg"})


def _req(rng, rows=4, nnz_per_row=16):
    counts = rng.integers(1, nnz_per_row + 1, size=rows)
    ids = rng.integers(0, F, size=int(counts.sum())).astype(np.int32)
    vals = rng.random(len(ids), dtype=np.float32)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    return ids, vals, row_ptr


def test_registry_sigkilled_mid_canary_exactly_once_promote(
        tmp_path, monkeypatch):
    """The registry dies between canary ack and promote, under live
    serving load.  The router serves its cached fleet through the dead
    window (zero failed requests), the restarted registry replays the
    canary set + pending acks, the bake re-runs, and the promote lands
    exactly once."""
    monkeypatch.setenv("DMLC_ROUTER_RETRIES", "6")
    # short breaker cooldowns so agents/router re-attach promptly after
    # the restart instead of waiting out a 5 s open circuit
    monkeypatch.setenv("DMLC_ROUTER_BREAKER_COOLDOWN", "0.3")
    monkeypatch.setenv("DMLC_ROUTER_BREAKER_THRESHOLD", "3")
    ck_v1, ck_v2 = tmp_path / "v1", tmp_path / "v2"
    _save_ckpt(ck_v1, 1, 1.0)
    _save_ckpt(ck_v2, 2, 5.0)
    journal = str(tmp_path / "reg" / "registry")
    reg_proc, addr = _spawn_singleton(
        "dmlc_core_tpu.serving.fleet.registry",
        port=0, journal=journal, heartbeat_timeout=5.0)
    port = addr[1]
    fleet_rpc(addr, {"cmd": "set_model", "model_id": "default",
                     "ckpt_dir": str(ck_v1), "step": 1})
    pairs = []
    router = None
    report = {}
    try:
        for _ in range(2):
            srv = PredictionServer(_engine(), metrics_port=0).start()
            ag = ReplicaAgent(srv, addr, interval_s=0.1).start()
            pairs.append((srv, ag))
        assert _wait_for(lambda: len(fleet_rpc(
            addr, {"cmd": "list_replicas"})["replicas"]) == 2)
        # the router takes the registry as a comma-string endpoint spec
        # (the DMLC_ROUTER_REGISTRY shape)
        router = ServingRouter(registry=f"127.0.0.1:{port}",
                               sync_s=0.2, health_poll_s=0.2).start()

        def load():
            report.update(run_load(
                router.host, router.port, requests=500, concurrency=2,
                pipeline_depth=4, rows_per_req=4, nnz_per_row=16,
                features=F, timeout=60.0, model_id="default"))

        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(0.3)                       # load established
        staged = fleet_rpc(addr, {
            "cmd": "stage_rollout", "model_id": "default",
            "ckpt_dir": str(ck_v2), "step": 2, "fraction": 0.5,
            "bake_s": 3.0})
        canary = staged["canaries"][0]

        def canary_acked():
            ro = fleet_rpc(addr, {"cmd": "rollouts"},
                           timeout=2.0)["active"].get("default")
            return ro is not None and canary in ro["acked"]

        assert _wait_for(canary_acked, timeout=15.0), \
            "canary never acked its reload"
        # -- the kill: acked but not yet promoted (3 s bake) ------------
        _sigkill(reg_proc)
        reg_proc, addr2 = _spawn_singleton(
            "dmlc_core_tpu.serving.fleet.registry",
            port=port, journal=journal, heartbeat_timeout=5.0)
        assert addr2 == addr
        # the replayed rollout carries the canary set + pending ack and
        # the bake window restarted
        ro = fleet_rpc(addr, {"cmd": "rollouts"})["active"]["default"]
        assert ro["id"] == staged["rollout_id"]
        assert ro["canaries"] == [canary] and canary in ro["acked"]

        def promoted():
            doc = fleet_rpc(addr, {"cmd": "rollouts"}, timeout=2.0)
            return not doc["active"] and any(
                e["event"] == "promoted" for e in doc["events"])

        assert _wait_for(promoted, timeout=30.0), \
            fleet_rpc(addr, {"cmd": "rollouts"})
        doc = fleet_rpc(addr, {"cmd": "rollouts"})
        events = Counter(e["event"] for e in doc["events"])
        assert events["promoted"] == 1        # exactly-once across the kill
        assert events["staged"] == 1
        assert events.get("rolled_back", 0) == 0
        assert fleet_rpc(addr, {"cmd": "models"})["models"]["default"][
            "ckpt_dir"] == str(ck_v2)
        # the whole fleet converges on v2 (promote reloaded the rest)
        rng = np.random.default_rng(7)
        ids, vals, row_ptr = _req(rng, rows=2)
        ref = float(vals[row_ptr[0]:row_ptr[1]].sum())

        def fleet_scale():
            return sorted(round(float(
                srv.engine.predict(ids, vals, row_ptr)[0] / ref))
                for srv, _ in pairs)

        assert _wait_for(lambda: fleet_scale() == [5, 5], timeout=20.0), \
            fleet_scale()
        # -- zero failed serving requests through the whole drill -------
        t.join(timeout=180.0)
        assert not t.is_alive(), "load generator wedged"
        assert report["rejected"] == 0, report
        assert report["overload"] == 0, report
        assert report["ok"] == 500, report
    finally:
        if router is not None:
            router.stop()
        for srv, ag in pairs:
            ag.stop()
            srv.stop()
        reg_proc.kill()
        reg_proc.wait()


# ---------------------------------------------------------------------------
# drill target 2: tracker SIGKILLed mid-epoch
# ---------------------------------------------------------------------------

def _tracker_cmd(addr, msg, timeout=30.0):
    with socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        send_json(s, msg)
        return recv_json(s.makefile("r"))


def test_tracker_sigkilled_mid_epoch_readmits_cohort(tmp_path):
    """An assigned cohort's tracker dies mid-epoch; restarted on the
    same port + journal it re-admits both workers at their old ranks and
    generation 0 (no spurious link reset), while a worker that actually
    MOVED still bumps the generation."""
    journal = str(tmp_path / "trk" / "tracker")
    proc, addr = _spawn_singleton("dmlc_core_tpu.parallel.tracker",
                                  port=0, workers=2, journal=journal)
    port = addr[1]
    # real listening sockets as the workers' peer ports, so the moved-
    # worker reset notify connects instead of retrying against a corpse
    peers = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.listen(4)
        peers.append(s)
    p1, p2, p3 = (s.getsockname()[1] for s in peers)
    try:
        replies = {}

        def register(jobid, wport, cmd):
            replies[jobid, cmd] = _tracker_cmd(addr, {
                "cmd": cmd, "jobid": jobid,
                "host": "127.0.0.1", "port": wport})

        # "start" blocks until the full cohort is present → two threads
        ts = [threading.Thread(target=register, args=(j, p, "start"))
              for j, p in (("w1", p1), ("w2", p2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30.0)
            assert not t.is_alive(), "rendezvous wedged"
        ranks = {j: replies[j, "start"]["rank"] for j in ("w1", "w2")}
        assert sorted(ranks.values()) == [0, 1]
        assert all(replies[j, "start"]["generation"] == 0
                   for j in ("w1", "w2"))
        # -- the kill: cohort assigned, epoch notionally in flight ------
        _sigkill(proc)
        proc, addr2 = _spawn_singleton("dmlc_core_tpu.parallel.tracker",
                                       port=port, workers=2,
                                       journal=journal)
        assert addr2 == addr
        # recover from an UNCHANGED address: same rank, generation 0 —
        # the workers never died, no reset storm
        for jobid, wport in (("w1", p1), ("w2", p2)):
            r = _tracker_cmd(addr, {"cmd": "recover", "jobid": jobid,
                                    "host": "127.0.0.1", "port": wport})
            assert r["rank"] == ranks[jobid], (jobid, r)
            assert r["generation"] == 0, (jobid, r)
        # a worker that MOVED (new port) is a real mid-job restart:
        # same rank, generation bumps, survivors get the reset
        r = _tracker_cmd(addr, {"cmd": "recover", "jobid": "w2",
                                "host": "127.0.0.1", "port": p3})
        assert r["rank"] == ranks["w2"] and r["generation"] == 1
    finally:
        for s in peers:
            s.close()
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------
# drill target 3: dispatcher SIGKILLed mid-epoch (multi-endpoint loader)
# ---------------------------------------------------------------------------

def _libsvm(tmp_path, rows=240):
    rng = np.random.default_rng(13)
    path = tmp_path / "ha.libsvm"
    with open(path, "w") as f:
        for i in range(rows):
            idx = np.sort(rng.choice(np.arange(1, 300), size=6,
                                     replace=False))
            f.write(f"{i + 1} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    return str(path)


def _spec(uri, num_parts):
    return {"uri": uri, "fmt": "libsvm", "num_parts": num_parts,
            "batch_rows": BATCH_ROWS, "nnz_cap": NNZ_CAP}


def _frame_digest(buf, meta):
    words = _fused_words_meta(BATCH_ROWS, int(meta))
    return hashlib.sha1(np.asarray(buf)[:words].tobytes()).hexdigest()


def _single_host_baseline(uri, num_parts):
    digests = Counter()
    for part in range(num_parts):
        loader = DeviceLoader(
            create_parser(uri, part, num_parts, "libsvm", nthreads=1,
                          threaded=False),
            batch_rows=BATCH_ROWS, nnz_cap=NNZ_CAP, emit="host")
        try:
            for kind, buf, meta, _rows in loader:
                digests[_frame_digest(buf, meta)] += 1
        finally:
            loader.close()
    return digests


def test_dispatcher_sigkilled_mid_epoch_no_replayed_frames(
        tmp_path, monkeypatch):
    """The shared-harness dispatcher target: the consumer rides a
    two-endpoint list whose FIRST endpoint is dead (EndpointSet walks to
    the live one), the dispatcher is SIGKILLed after frames are in
    flight and restarted on the same port + journal, and the epoch
    completes with frame-sha1 parity — no replayed ingest frames."""
    uri = _libsvm(tmp_path)
    base_digests = _single_host_baseline(uri, 4)
    monkeypatch.setenv("DMLC_DATA_CLIENT_RETRIES", "40")
    monkeypatch.setenv("DMLC_DATA_CLIENT_BREAKER_THRESHOLD", "1000")
    monkeypatch.setenv("DMLC_DS_CTRL_RETRIES", "40")
    journal = str(tmp_path / "disp" / "dispatch")
    proc, addr = _spawn_singleton(
        "dmlc_core_tpu.pipeline.data_service.dispatcher",
        port=0, journal=journal)
    port = addr[1]
    dead = _free_port()
    workers = [DataServiceWorker(addr, heartbeat_interval_s=0.2).start()
               for _ in range(2)]
    frames_seen = threading.Event()
    result = {}

    def consume():
        # dead endpoint first: every control RPC walks the list
        ldr = DataServiceLoader(f"127.0.0.1:{dead},127.0.0.1:{port}",
                                _spec(uri, 4))
        assert ldr.dispatcher == ("127.0.0.1", dead)   # compat alias
        digests = Counter()
        try:
            for kind, buf, meta, _rows in ldr:
                digests[_frame_digest(buf, meta)] += 1
                ldr.recycle(buf)
                frames_seen.set()
                time.sleep(0.05)
        finally:
            ldr.close()
        result["digests"] = digests

    t = threading.Thread(target=consume, daemon=True)
    try:
        t.start()
        assert frames_seen.wait(timeout=60.0), "no frames before the kill"
        _sigkill(proc)                        # mid-epoch, leases granted
        proc, addr2 = _spawn_singleton(
            "dmlc_core_tpu.pipeline.data_service.dispatcher",
            port=port, journal=journal)
        assert addr2 == addr
        t.join(timeout=180.0)
        assert not t.is_alive(), "consumer stuck after failover"
    finally:
        for w in workers:
            w.kill()
        proc.kill()
        proc.wait()
    assert result["digests"] == base_digests   # every frame exactly once
    assert max(result["digests"].values()) == 1
    assert metrics.counter("transport.endpoints.failovers").value >= 1
