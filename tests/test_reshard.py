"""Checkpoint-free elastic resharding: the live state-redistribution
protocol (``parallel/reshard.py``) over real tracker + loopback sockets.

Covers the full decision tree — local pieces → peer fetch → leaf-granular
checkpoint read → cohort-wide failure — plus the pure planning helpers
(``row_partition``/``remap_rows``) and the snapshot budget demotion."""

import collections
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_core_tpu.parallel import (HostSnapshot, RabitContext,  # noqa: E402
                                    RabitTracker, redistribute,
                                    remap_rows, row_partition, snapshot_tree)
from dmlc_core_tpu.utils import DMLCError  # noqa: E402
from dmlc_core_tpu.utils.checkpoint import (CheckpointManager,  # noqa: E402
                                            flatten_tree, unflatten_like)
from dmlc_core_tpu.utils.metrics import metrics  # noqa: E402


# ---------------------------------------------------------------------------
# pure planning helpers
# ---------------------------------------------------------------------------

def test_row_partition_contract():
    assert row_partition(9, 3) == [(0, 3), (3, 6), (6, 9)]
    # first n % parts ranges carry the extra row
    assert row_partition(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert row_partition(2, 4) == [(0, 1), (1, 2), (2, 2), (2, 2)]
    assert row_partition(0, 2) == [(0, 0), (0, 0)]
    # exhaustive cover property
    for n in (1, 5, 17, 100):
        for p in (1, 2, 3, 7):
            parts = row_partition(n, p)
            assert parts[0][0] == 0 and parts[-1][1] == n
            assert all(a[1] == b[0] for a, b in zip(parts, parts[1:]))


def test_remap_rows_shrink_and_grow():
    # 3 -> 2: new rank 0 keeps its rows and pulls the head of old rank 1
    plan = remap_rows(9, 3, 2)
    assert plan == [[(0, 0, 3), (1, 3, 5)], [(1, 5, 6), (2, 6, 9)]]
    # 2 -> 3: feeds cover each new range exactly, in order
    for feeds, (ns, ne) in zip(remap_rows(10, 2, 3), row_partition(10, 3)):
        assert feeds[0][1] == ns and feeds[-1][2] == ne
        assert all(a[2] == b[1] for a, b in zip(feeds, feeds[1:]))


def test_remap_rows_edge_cases_and_minimality():
    """Degenerate layouts (more parts than rows, empty shards) and the
    moved-set property: a remap plan must move EXACTLY the rows whose
    owner changed — nothing replayed, nothing gratuitous."""
    from dmlc_core_tpu.parallel import row_owners

    # parts > n_rows on either side: trailing empty shards get no feeds
    assert remap_rows(2, 4, 1) == [[(0, 0, 1), (1, 1, 2)]]
    assert remap_rows(2, 1, 4) == [[(0, 0, 1)], [(0, 1, 2)], [], []]
    assert remap_rows(0, 2, 3) == [[], [], []]

    for n in (1, 2, 7, 10, 97):
        for old_p in (1, 2, 3, 5, 12):
            for new_p in (1, 2, 4, 11):
                plan = remap_rows(n, old_p, new_p)
                assert len(plan) == new_p
                # exactly-once cover: the union of feeds is a disjoint
                # in-order tiling of [0, n)
                cover = [iv for feeds in plan for iv in feeds]
                assert sum(hi - lo for _, lo, hi in cover) == n
                flat = sorted((lo, hi) for _, lo, hi in cover)
                assert all(a[1] == b[0] for a, b in zip(flat, flat[1:]))
                if n:
                    assert flat[0][0] == 0 and flat[-1][1] == n
                # feeds only name ranks that actually own those rows
                rows = np.arange(n, dtype=np.int64)
                old_own = row_owners(n, old_p, rows) if n else rows
                new_own = row_owners(n, new_p, rows) if n else rows
                moved = 0
                for new_rank, feeds in enumerate(plan):
                    for old_rank, lo, hi in feeds:
                        assert (old_own[lo:hi] == old_rank).all()
                        assert (new_own[lo:hi] == new_rank).all()
                        if old_rank != new_rank:
                            moved += hi - lo
                # minimality: moved rows == rows whose owner changed
                assert moved == int((old_own != new_own).sum())


# ---------------------------------------------------------------------------
# snapshot
# ---------------------------------------------------------------------------

def test_snapshot_tree_roundtrip_and_zero_d():
    tree = {"w": np.arange(12, dtype=np.float32).reshape(4, 3),
            "b": np.float64(3.5)}
    snap = snapshot_tree(tree)
    assert snap.schema["w"] == ((4, 3), "float32")
    # 0-d leaves ride as one (1,) row against a () global shape
    assert snap.schema["b"] == ((), "float64")
    (s, e, arr) = snap.pieces["b"][0]
    assert (s, e) == (0, 1) and arr.shape == (1,)


def test_snapshot_budget_demotes_to_non_holder():
    before = metrics.counter("reshard.snapshot_skipped").value
    big = {"w": np.zeros((1024, 1024), np.float32)}       # 4 MiB
    assert snapshot_tree(big, max_bytes=1 << 20) is None
    assert metrics.counter("reshard.snapshot_skipped").value == before + 1
    assert snapshot_tree(big, max_bytes=1 << 23) is not None


def test_flatten_unflatten_preserves_namedtuples():
    Opt = collections.namedtuple("Opt", ["mu", "nu"])
    tree = {"params": [np.ones(2), np.zeros(3)],
            "opt": Opt(mu={"w": np.full(2, 7.0)}, nu=np.int32(4))}
    flat = flatten_tree(tree)
    # NamedTuples path by position, like plain tuples — the checkpoint
    # treedef has no field names to agree on across ranks
    assert sorted(flat) == ["opt/0/w", "opt/1", "params/0", "params/1"]
    back = unflatten_like(tree, flat)
    assert isinstance(back["opt"], Opt)
    assert isinstance(back["params"], list)
    np.testing.assert_array_equal(back["opt"].mu["w"], np.full(2, 7.0))
    assert back["opt"].nu.shape == ()


# ---------------------------------------------------------------------------
# the cohort protocol
# ---------------------------------------------------------------------------

def _cohort(world, fn, timeout=60):
    """Tracker + thread workers; fn(ctx, rank) -> result.  Returns
    (results, errors) so failure tests can assert cohort-wide raises."""
    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    env = tracker.worker_envs()
    results = [None] * world
    errors = [None] * world

    def worker(i):
        ctx = None
        try:
            ctx = RabitContext(env["DMLC_TRACKER_URI"],
                               int(env["DMLC_TRACKER_PORT"]), jobid=f"w{i}")
            results[ctx.rank] = fn(ctx, ctx.rank)
        except Exception as e:  # noqa: BLE001
            errors[i] = e
        finally:
            if ctx is not None:
                try:
                    ctx.shutdown()
                except Exception:  # noqa: BLE001
                    pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    tracker.join(timeout=30)
    return results, [e for e in errors if e is not None]


def _digest(flat):
    import hashlib
    h = hashlib.sha1()
    for p in sorted(flat):
        a = np.ascontiguousarray(flat[p])
        h.update(p.encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def test_redistribute_rebirth_replicated():
    """Rank 2 is reborn (holds nothing): it must receive every leaf from
    the survivors bit-equal, with zero checkpoint reads."""
    state = {"params": {"w": np.arange(24, dtype=np.float32).reshape(6, 4),
                        "b": np.float64(1.25)},
             "step": np.int32(7)}

    def fn(ctx, rank):
        snap = snapshot_tree(state) if rank != 2 else None
        restored, stats = redistribute(ctx, snap, template=state,
                                       generation=1)
        return restored, stats

    results, errors = _cohort(3, fn)
    assert not errors, errors
    digests = set()
    for rank, (restored, stats) in enumerate(results):
        flat = flatten_tree(restored)
        digests.add(_digest(flat))
        assert stats.leaves_from_checkpoint == 0
        assert restored["params"]["b"].shape == ()       # 0-d survives
        assert restored["step"].dtype == np.int32
        if rank == 2:
            assert stats.leaves_from_peers == 3
            assert stats.bytes_moved > 0
        else:
            assert stats.leaves_local == 3
            assert stats.bytes_moved == 0
    assert len(digests) == 1                             # bit-equal cohort


def test_redistribute_shrink_without_checkpoint():
    """Planned 3 -> 2 resize: survivors re-partition a row-sharded table
    from each other's shards; the departing rank serves its rows out and
    keeps nothing.  No checkpoint is configured — zero reads by
    construction."""
    table = np.arange(27, dtype=np.float32).reshape(9, 3)
    old = row_partition(9, 3)
    new = row_partition(9, 2)

    def fn(ctx, rank):
        snap = HostSnapshot()
        s, e = old[rank]
        snap.add("table", table[s:e], start=s, global_rows=9)

        def plan(path, gshape):
            return new[rank] if rank < 2 else (0, 0)

        restored, stats = redistribute(ctx, snap, plan=plan, generation=1)
        return restored, stats

    results, errors = _cohort(3, fn)
    assert not errors, errors
    for rank in (0, 1):
        restored, stats = results[rank]
        s, e = new[rank]
        np.testing.assert_array_equal(restored["table"], table[s:e])
        assert stats.leaves_from_checkpoint == 0
        assert stats.bytes_moved > 0                     # pulled peer rows
    restored, stats = results[2]                         # departing rank
    assert restored is None
    assert stats.leaves_from_checkpoint == 0


def test_redistribute_checkpoint_fallback(tmp_path):
    """A leaf NO survivor holds comes from the checkpoint — and only that
    leaf (leaf-granular restore, not a full reload)."""
    held = {"kept": np.full((4, 2), 3.0, np.float32)}
    lost = {"kept": held["kept"], "lost": np.arange(5, dtype=np.float64)}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(3, lost)

    def fn(ctx, rank):
        # every rank holds only "kept"; "lost" exists only in the
        # checkpoint schema of rank 0's manifest broadcast
        snap = snapshot_tree(held)
        if rank == 0:
            snap.schema["lost"] = ((5,), "float64")      # advertised, empty
        restored, stats = redistribute(
            ctx, snap, checkpoint=CheckpointManager(str(tmp_path)),
            generation=2)
        return restored, stats

    results, errors = _cohort(2, fn)
    assert not errors, errors
    for restored, stats in results:
        np.testing.assert_array_equal(restored["lost"],
                                      np.arange(5, dtype=np.float64))
        assert stats.leaves_from_checkpoint == 1
        np.testing.assert_array_equal(restored["kept"], held["kept"])


def test_redistribute_unrecoverable_raises_cohort_wide():
    """A gap with no holder and no checkpoint must raise on EVERY rank —
    half-restored cohorts don't train."""
    held = {"w": np.ones((2, 2), np.float32)}

    def fn(ctx, rank):
        snap = snapshot_tree(held)
        if rank == 0:
            snap.schema["ghost"] = ((3,), "float32")     # nobody has it
        return redistribute(ctx, snap, generation=3)

    before = metrics.counter("reshard.failures").value
    results, errors = _cohort(2, fn)
    assert len(errors) == 2
    assert all(isinstance(e, DMLCError) for e in errors)
    assert all("unrecoverable" in str(e) for e in errors)
    assert metrics.counter("reshard.failures").value >= before + 2
