"""Telemetry plane: trace propagation (in-process, cross-thread, over the
serving wire), Chrome-trace export schema, Prometheus exposition format,
mergeable metric states, the HTTP exporter, tracker fleet aggregation,
and log correlation — all on CPU."""

import json
import socket
import time
import urllib.request

import numpy as np
import pytest

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import aggregate, chrome_trace, exposition
from dmlc_core_tpu.telemetry import trace as teltrace
from dmlc_core_tpu.utils.logging import set_log_context, set_log_sink
from dmlc_core_tpu.utils.metrics import Histogram, MetricsRegistry
from dmlc_core_tpu.utils.retry import RetryPolicy


@pytest.fixture(autouse=True)
def _clean_recorder():
    teltrace.recorder.clear()
    yield
    teltrace.recorder.clear()


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# trace context + spans
# ---------------------------------------------------------------------------

def test_span_nesting_shares_trace_id():
    with teltrace.span("outer") as outer:
        with teltrace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert teltrace.current() == inner.context
    assert teltrace.current() is None
    recs = {r["name"]: r for r in teltrace.recorder.snapshot()}
    assert recs["inner"]["trace_id"] == recs["outer"]["trace_id"]
    assert recs["inner"]["parent_id"] == recs["outer"]["span_id"]
    assert recs["outer"]["parent_id"] is None


def test_activate_crosses_boundaries():
    """A bare TraceContext re-activated on 'the other side' parents new
    spans correctly (the thread/wire-crossing contract)."""
    ctx = teltrace.TraceContext(teltrace.new_trace_id(),
                                teltrace.new_trace_id())
    with teltrace.activate(ctx):
        with teltrace.span("child") as child:
            assert child.trace_id == ctx.trace_id
            assert child.parent_id == ctx.span_id
    with teltrace.activate(None):        # None is a no-op, not an error
        assert teltrace.current() is None


def test_span_records_error_and_events():
    with pytest.raises(ValueError):
        with teltrace.span("boom") as s:
            s.event("checkpoint", step=3)
            raise ValueError("nope")
    (rec,) = teltrace.recorder.snapshot()
    assert rec["attrs"]["error"].startswith("ValueError")
    assert rec["events"][0]["name"] == "checkpoint"
    assert rec["events"][0]["attrs"]["step"] == 3


def test_add_event_without_span_records_instant():
    teltrace.add_event("orphan", detail="x")
    (rec,) = teltrace.recorder.snapshot()
    assert rec["kind"] == "event" and rec["name"] == "orphan"
    assert rec["trace_id"] is None


def test_recorder_ring_is_bounded():
    r = teltrace.SpanRecorder(capacity=4)
    for i in range(10):
        r.record({"name": str(i)})
    assert [x["name"] for x in r.snapshot()] == ["6", "7", "8", "9"]


def test_retry_emits_span_events():
    """utils.retry reports retries into the active span (satellite: the
    resilience layer feeds the telemetry plane without importing it)."""
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    pol = RetryPolicy(max_attempts=5, base_delay_s=0.0,
                      retryable=lambda e: isinstance(e, OSError))
    with teltrace.span("op") as s:
        assert pol.call(flaky) == "ok"
        names = [e["name"] for e in s.events]
    assert names.count("retry") == 2


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def test_chrome_trace_schema(tmp_path):
    with teltrace.span("parent"):
        with teltrace.span("child"):
            teltrace.add_event("tick", k=1)
    doc = chrome_trace.to_chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"parent", "child"}
    for e in xs:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    # async nesting pair per span, keyed by the shared trace id
    bs = [e for e in events if e["ph"] == "b"]
    es = [e for e in events if e["ph"] == "e"]
    assert len(bs) == len(es) == 2
    assert len({e["id"] for e in bs}) == 1     # one trace → one async id
    assert any(e["ph"] == "i" and e["name"] == "tick" for e in events)
    # the file form is valid JSON Perfetto can open
    p = tmp_path / "trace.json"
    chrome_trace.write_chrome_trace(str(p))
    assert json.loads(p.read_text())["traceEvents"]


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_render_prometheus_golden():
    reg = MetricsRegistry()
    reg.counter("reqs.total").add(7)
    reg.gauge("queue.depth").set(3)
    h = reg.histogram("lat_s")
    for v in [0.1] * 99 + [1.0]:
        h.observe(v)
    text = exposition.render_prometheus(reg.snapshot())
    lines = text.splitlines()
    assert "# TYPE dmlc_reqs_total_total counter" in lines
    assert "dmlc_reqs_total_total 7" in lines
    assert "# TYPE dmlc_queue_depth gauge" in lines
    assert "dmlc_queue_depth 3" in lines
    assert "# TYPE dmlc_lat_s summary" in lines
    assert 'dmlc_lat_s{quantile="0.5"} 0.1' in lines
    assert "dmlc_lat_s_count 100" in lines
    # every non-comment line is `name{labels} value`
    for ln in lines:
        if not ln.startswith("#"):
            name, val = ln.rsplit(" ", 1)
            float(val)
            assert name[0].isalpha() or name[0] == "_"


def test_render_prometheus_sanitizes_and_labels():
    reg = MetricsRegistry()
    reg.counter("weird-name.1").add(1)
    text = exposition.render_prometheus(reg.snapshot(),
                                        labels={"rank": "3"})
    assert 'dmlc_weird_name_1_total{rank="3"} 1' in text


def test_render_prometheus_hostile_label_values_golden():
    """Label values carrying backslash, newline, and double-quote must be
    escaped per the Prometheus 0.0.4 text format (backslash first, so the
    escapes the other two introduce aren't re-escaped)."""
    reg = MetricsRegistry()
    reg.counter("c").add(1)
    text = exposition.render_prometheus(
        reg.snapshot(),
        labels={"path": 'C:\\tmp\n"x"', "host": "plain"})
    assert ('dmlc_c_total{host="plain",path="C:\\\\tmp\\n\\"x\\""} 1'
            in text.splitlines())
    # and the page stays one-line-per-sample: a raw newline in a label
    # value would split the sample across lines
    for ln in text.splitlines():
        if not ln.startswith("#"):
            float(ln.rsplit(" ", 1)[1])


def test_render_series_single_type_header():
    """The same family across label sets must emit ONE # TYPE header."""
    r0, r1 = MetricsRegistry(), MetricsRegistry()
    r0.counter("c").add(1)
    r1.counter("c").add(2)
    text = exposition.render_series([({"rank": "0"}, r0.snapshot()),
                                     ({"rank": "1"}, r1.snapshot())])
    assert text.count("# TYPE dmlc_c_total counter") == 1
    assert 'dmlc_c_total{rank="0"} 1' in text
    assert 'dmlc_c_total{rank="1"} 2' in text


# ---------------------------------------------------------------------------
# mergeable metric states
# ---------------------------------------------------------------------------

def test_histogram_merge_matches_combined_stream():
    rng = np.random.default_rng(7)
    a = rng.normal(0.0, 1.0, 1200)
    b = rng.normal(4.0, 0.5, 800)
    ha, hb, ref = Histogram(), Histogram(), Histogram(max_samples=4096)
    for v in a:
        ha.observe(float(v))
        ref.observe(float(v))
    for v in b:
        hb.observe(float(v))
        ref.observe(float(v))
    merged = Histogram.merge([ha.state(), hb.state()])
    want = ref.snapshot()
    assert merged["count"] == 2000
    assert merged["mean"] == pytest.approx(want["mean"], abs=1e-9)
    assert merged["min"] == want["min"] and merged["max"] == want["max"]
    for q in ("p50", "p95", "p99"):
        assert merged[q] == pytest.approx(want[q], abs=0.2)


def test_merge_states_counters_gauges_and_skew():
    per_rank = {
        "0": {"reqs": {"type": "counter", "value": 5},
              "health": {"type": "gauge", "value": 0},
              "skewed": {"type": "counter", "value": 1}},
        "1": {"reqs": {"type": "counter", "value": 7},
              "health": {"type": "gauge", "value": 2},
              "skewed": {"type": "gauge", "value": 1}},
    }
    merged = aggregate.merge_states(per_rank)
    assert merged["reqs"]["value"] == 12
    assert merged["health"]["value"] == 2     # gauge merge = worst rank
    assert "skewed" not in merged             # type skew dropped, not guessed


def test_registry_state_round_trips_through_renderer():
    reg = MetricsRegistry()
    reg.counter("c").add(3)
    reg.histogram("h").observe(1.5)
    reg.throughput("tp").add(10)
    with reg.stage("st").time():
        pass
    state = reg.state()
    text = aggregate.render_fleet({"0": state})
    assert "dmlc_c_total 3" in text
    assert 'dmlc_h{quantile="0.5"} 1.5' in text
    assert "dmlc_tp_total 10" in text
    assert "dmlc_st_count 1" in text


# ---------------------------------------------------------------------------
# HTTP exporter
# ---------------------------------------------------------------------------

def test_exporter_endpoints_smoke():
    """Tier-1 exporter smoke on an ephemeral port: /metrics renders the
    global registry, /healthz is JSON, /spans returns recorded spans."""
    from dmlc_core_tpu.utils.metrics import metrics
    metrics.counter("telemetry.test.hits").add(2)
    with teltrace.span("exporter-smoke"):
        pass
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(base + "/metrics")
        assert code == 200
        assert "dmlc_telemetry_test_hits_total 2" in body
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, body = _get(base + "/spans")
        assert code == 200
        assert any(s["name"] == "exporter-smoke"
                   for s in json.loads(body)["spans"])
        code, _ = _get(base + "/nope")
        assert code == 404
    finally:
        srv.stop()


def test_exporter_healthz_maps_overloaded_to_503():
    srv = exposition.TelemetryServer(port=0, host="127.0.0.1",
                                     health_fn=lambda: "overloaded").start()
    try:
        code, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert code == 503 and json.loads(body)["status"] == "overloaded"
    finally:
        srv.stop()


def test_maybe_start_from_env(monkeypatch):
    monkeypatch.delenv("DMLC_METRICS_PORT", raising=False)
    assert exposition.maybe_start_from_env() is None
    monkeypatch.setenv("DMLC_METRICS_PORT", "0")
    srv = exposition.maybe_start_from_env()
    assert srv is not None
    try:
        code, _ = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert code == 200
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# live client → server → engine propagation
# ---------------------------------------------------------------------------

def test_serving_trace_propagates_end_to_end():
    jax = pytest.importorskip("jax")
    jnp = jax.numpy
    from dmlc_core_tpu.models import SparseLogReg
    from dmlc_core_tpu.serving import (BucketLadder, InferenceEngine,
                                       PredictClient, PredictionServer)

    F = 5000
    model = SparseLogReg(num_features=F)
    params = {"w": jnp.arange(F, dtype=jnp.float32) / F,
              "b": jnp.float32(0.25)}
    engine = InferenceEngine(model, params,
                             buckets=BucketLadder([(16, 512)]))
    srv = PredictionServer(engine, warmup=True, metrics_port=0).start()
    try:
        rng = np.random.default_rng(0)
        with PredictClient(srv.host, srv.port) as client:
            n = 16
            client.predict(rng.integers(0, F, n, np.int32),
                           rng.random(n, np.float32))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            recs = {r["name"]: r for r in teltrace.recorder.snapshot()}
            if {"serving.client.predict", "serving.server.request",
                    "serving.engine.forward"} <= set(recs):
                break
            time.sleep(0.02)
        c = recs["serving.client.predict"]
        s = recs["serving.server.request"]
        e = recs["serving.engine.forward"]
        # one trace id rides client → wire → server → batcher → engine
        assert c["trace_id"] == s["trace_id"] == e["trace_id"]
        assert s["parent_id"] == c["span_id"]
        assert e["parent_id"] == s["span_id"]
        assert s["attrs"]["status"] == "OK"
        # the mounted exporter serves this process's registry + spans
        assert srv.telemetry is not None
        base = f"http://127.0.0.1:{srv.telemetry.port}"
        code, body = _get(base + "/metrics")
        assert code == 200 and "dmlc_serving_latency_s" in body
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# tracker fleet aggregation
# ---------------------------------------------------------------------------

def test_tracker_merges_rank_tagged_states():
    from dmlc_core_tpu.parallel.tracker import RabitTracker, send_json

    t = RabitTracker(num_workers=2, host_ip="127.0.0.1", telemetry_port=0)
    t.start()
    try:
        assert t.telemetry is not None

        def push(rank, lat_base):
            reg = MetricsRegistry()
            reg.counter("reqs").add(5 + rank * 2)
            h = reg.histogram("lat_s")
            for i in range(100):
                h.observe(lat_base + i * 0.001)
            s = socket.create_connection((t.host_ip, t.port), timeout=5)
            try:
                send_json(s, {"cmd": "telemetry", "jobid": f"j{rank}",
                              "rank": rank, "state": reg.state()})
            finally:
                s.close()

        push(0, 0.1)
        push(1, 0.5)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(t.telemetry_states()) < 2:
            time.sleep(0.02)
        assert set(t.telemetry_states()) == {"0", "1"}
        code, body = _get(f"http://127.0.0.1:{t.telemetry.port}/metrics")
        assert code == 200
        lines = body.splitlines()
        assert "dmlc_reqs_total 12" in lines           # merged fleet total
        assert 'dmlc_reqs_total{rank="0"} 5' in lines  # drill-down series
        assert 'dmlc_reqs_total{rank="1"} 7' in lines
        # merged histogram quantiles span both ranks' reservoirs
        p99 = next(float(ln.rsplit(" ", 1)[1]) for ln in lines
                   if ln.startswith('dmlc_lat_s{quantile="0.99"}'))
        assert 0.5 < p99 < 0.7
        assert any(ln.startswith('dmlc_lat_s{quantile="0.5",rank="1"}')
                   for ln in lines)
    finally:
        t.stop()


def test_rabit_push_telemetry_cadence():
    """A worker with DMLC_TELEMETRY_INTERVAL pushes its registry to the
    tracker without any explicit call (plus one final push at shutdown)."""
    from dmlc_core_tpu.parallel.rabit import RabitContext
    from dmlc_core_tpu.parallel.tracker import RabitTracker
    from dmlc_core_tpu.utils.metrics import metrics

    t = RabitTracker(num_workers=1, host_ip="127.0.0.1", telemetry_port=0)
    t.start()
    try:
        rc = RabitContext(t.host_ip, t.port, jobid="w0",
                          heartbeat_interval=0, telemetry_interval=0.05)
        try:
            metrics.counter("worker.work_done").add(3)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                states = t.telemetry_states()
                if "0" in states and "worker.work_done" in states["0"]:
                    break
                time.sleep(0.02)
            assert states["0"]["worker.work_done"]["value"] == 3
        finally:
            rc.shutdown()
        code, body = _get(f"http://127.0.0.1:{t.telemetry.port}/metrics")
        assert code == 200 and "dmlc_worker_work_done_total" in body
    finally:
        t.stop()


# ---------------------------------------------------------------------------
# log correlation
# ---------------------------------------------------------------------------

def test_log_text_mode_carries_rank_and_trace_id():
    from dmlc_core_tpu.utils.logging import log_info

    captured = []
    set_log_sink(lambda sev, msg: captured.append((sev, msg)))
    try:
        set_log_context(rank=3)
        with teltrace.span("logged-op") as s:
            log_info("inside")
        log_info("outside")
    finally:
        set_log_sink(None)
        set_log_context(rank=None)
    assert "rank=3" in captured[0][1]
    assert teltrace.format_id(s.trace_id) in captured[0][1]
    assert "trace_id" not in captured[1][1]


def test_log_json_mode_emits_json_lines(monkeypatch):
    from dmlc_core_tpu.utils.logging import log_warning

    monkeypatch.setenv("DMLC_LOG_FORMAT", "json")
    captured = []
    set_log_sink(lambda sev, line: captured.append((sev, line)))
    try:
        set_log_context(rank=1)
        with teltrace.span("json-op") as s:
            log_warning("careful: %d", 42)
    finally:
        set_log_sink(None)
        set_log_context(rank=None)
    sev, line = captured[0]
    rec = json.loads(line)
    assert sev == "WARNING" and rec["level"] == "WARNING"
    assert rec["msg"] == "careful: 42"
    assert rec["rank"] == 1
    assert rec["trace_id"] == teltrace.format_id(s.trace_id)
    assert isinstance(rec["ts"], float)


# ---------------------------------------------------------------------------
# artifacts
# ---------------------------------------------------------------------------

def test_dump_artifacts(tmp_path):
    reg = MetricsRegistry()
    reg.counter("done").add(1)
    with teltrace.span("artifact-op"):
        pass
    prefix = str(tmp_path / "run1")
    paths = telemetry.dump_artifacts(prefix, registry=reg)
    snap = json.loads(open(paths["metrics"]).read())["snapshot"]
    assert snap["done"]["value"] == 1
    doc = json.loads(open(paths["trace"]).read())
    assert any(e.get("name") == "artifact-op" for e in doc["traceEvents"])
