"""Checkpoint/resume tests: pytree round trips, atomic manager semantics,
retention, crash-safety, and full train-interrupt-resume on a real model."""

import io
import os

import numpy as np
import pytest

from dmlc_core_tpu.utils import DMLCError
from dmlc_core_tpu.utils.checkpoint import (
    CheckpointManager,
    fast_forward,
    load_pytree,
    save_pytree,
)


def _roundtrip(tree):
    buf = io.BytesIO()
    save_pytree(buf, tree)
    buf.seek(0)
    return load_pytree(buf)


def test_pytree_roundtrip_mixed():
    tree = {
        "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                   "b": np.zeros(4, dtype=np.float64)},
        "step": 17,
        "lr": 0.125,
        "name": "fm",
        "flags": [True, False, None],
        "shape": (3, 4),
        "ints": np.array([1, 2, 3], dtype=np.int64),
    }
    out = _roundtrip(tree)
    np.testing.assert_array_equal(out["params"]["w"], tree["params"]["w"])
    assert out["params"]["w"].dtype == np.float32
    np.testing.assert_array_equal(out["ints"], tree["ints"])
    assert out["step"] == 17 and out["lr"] == 0.125
    assert out["flags"] == [True, False, None]
    assert out["shape"] == (3, 4)          # tuples survive as tuples


def test_pytree_scalar_leaf_keeps_0d_shape():
    # np.ascontiguousarray is "at least 1-d": the writer must record the
    # shape before it, or an FM-style 0-d bias comes back as (1,) and no
    # longer matches the model's avals (breaks serving hot-reload)
    tree = {"w0": np.float32(0.25), "z": np.zeros((), np.float64),
            "w": np.arange(3, dtype=np.float32)}
    out = _roundtrip(tree)
    assert out["w0"].shape == () and out["w0"] == np.float32(0.25)
    assert out["z"].shape == ()
    assert out["w"].shape == (3,)


def test_pytree_template_heals_legacy_1d_scalars():
    # checkpoints written before the 0-d fix hold scalars as (1,); a
    # template restore reshapes single-element leaves to the template's
    # shape, but larger leaves must still match exactly
    buf = io.BytesIO()
    save_pytree(buf, {"w0": np.full((1,), 0.5, np.float32),
                      "w": np.arange(4, dtype=np.float32)})
    buf.seek(0)
    out = load_pytree(buf, template={"w0": np.zeros((), np.float32),
                                     "w": np.zeros(4, np.float32)})
    assert out["w0"].shape == () and out["w0"] == np.float32(0.5)
    assert out["w"].shape == (4,)


def test_pytree_jax_arrays_roundtrip_as_numpy():
    import jax.numpy as jnp
    tree = {"w": jnp.arange(8, dtype=jnp.float32), "nested": [jnp.ones(3)]}
    out = _roundtrip(tree)
    assert isinstance(out["w"], np.ndarray)
    np.testing.assert_array_equal(out["w"], np.arange(8, dtype=np.float32))


def test_pytree_bad_magic():
    with pytest.raises(DMLCError, match="magic"):
        load_pytree(io.BytesIO(b"NOTACKPTxxxx"))


def test_pytree_unserializable_type():
    with pytest.raises(DMLCError, match="cannot checkpoint"):
        save_pytree(io.BytesIO(), {"f": lambda: 1})


def test_pytree_object_dtype_rejected_at_save():
    with pytest.raises(DMLCError, match="object-dtype"):
        save_pytree(io.BytesIO(),
                    {"x": np.array(["a", "bb"], dtype=object)})


def test_template_list_length_mismatch_errors():
    buf = io.BytesIO()
    save_pytree(buf, {"layers": [np.ones(2), np.ones(3), np.ones(4)]})
    buf.seek(0)
    with pytest.raises(DMLCError, match="template mismatch"):
        load_pytree(buf, template={"layers": [np.zeros(2), np.zeros(3)]})


def test_corrupt_manifest_rebuilt_from_files(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(3, {"x": np.ones(2)})
    mgr.save(7, {"x": np.full(2, 7.0)})
    # simulate crash-truncated manifest
    open(os.path.join(tmp_path, "MANIFEST.json"), "w").close()
    mgr2 = CheckpointManager(str(tmp_path))
    assert mgr2.latest_step == 7
    step, state = mgr2.restore()
    np.testing.assert_array_equal(state["x"], np.full(2, 7.0))


def test_manager_save_restore_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    assert mgr.latest_step is None
    for step in [10, 20, 30]:
        mgr.save(step, {"w": np.full(4, step, np.float32), "step": step},
                 meta={"loss": 1.0 / step})
    step, state = mgr.restore()
    assert step == 30
    np.testing.assert_array_equal(state["w"], np.full(4, 30, np.float32))
    step, state = mgr.restore(20)
    assert state["step"] == 20
    assert mgr.meta(20) == {"loss": 0.05}


def test_manager_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    for step in range(5):
        mgr.save(step, {"s": step})
    assert mgr.steps == [3, 4]
    assert not os.path.exists(os.path.join(tmp_path, "ckpt-0.bin"))
    with pytest.raises(DMLCError, match="no checkpoint for step 0"):
        mgr.restore(0)


def test_manager_crash_safety(tmp_path, monkeypatch):
    """A save that dies mid-write must leave the previous checkpoint and
    manifest fully intact (atomic publish)."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"w": np.ones(3)})

    import dmlc_core_tpu.utils.checkpoint as cp
    real = cp.save_pytree

    def exploding(stream, tree):
        real(stream, {"partial": np.ones(1)})
        raise RuntimeError("disk died")

    monkeypatch.setattr(cp, "save_pytree", exploding)
    with pytest.raises(RuntimeError):
        mgr.save(2, {"w": np.zeros(3)})
    monkeypatch.setattr(cp, "save_pytree", real)

    assert mgr.latest_step == 1
    step, state = mgr.restore()
    np.testing.assert_array_equal(state["w"], np.ones(3))
    # no stray temp files
    assert all(not f.startswith(".ckpt") for f in os.listdir(tmp_path))


def test_manager_reopen_between_runs(tmp_path):
    CheckpointManager(str(tmp_path)).save(5, {"x": 1})
    mgr2 = CheckpointManager(str(tmp_path))   # fresh process analog
    step, state = mgr2.restore()
    assert (step, state["x"]) == (5, 1)


def test_train_interrupt_resume(tmp_path):
    """The full contract: train k steps, checkpoint, 'crash', restore into a
    fresh model+loader, fast-forward the data, finish — final params equal
    an uninterrupted run (bitwise, since the data order is deterministic)."""
    import jax
    import jax.numpy as jnp
    import optax
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import FactorizationMachine
    from dmlc_core_tpu.models.train import make_train_step
    from dmlc_core_tpu.pipeline.device_loader import DeviceLoader

    path = tmp_path / "t.libsvm"
    path.write_text("".join(
        f"{i%2} {i%13+1}:0.5 {(i*3)%13+1}:1.0\n" for i in range(512)))

    def make_loader():
        p = create_parser(f"file://{path}", 0, 1, "libsvm")
        return DeviceLoader(p, batch_rows=64, nnz_cap=256)

    model = FactorizationMachine(num_features=16, dim=4)
    opt = optax.adam(1e-2)
    step_fn = jax.jit(make_train_step(model, opt))

    def init_state():
        params = model.init(jax.random.PRNGKey(0))
        return params, opt.init(params)

    # --- uninterrupted run: 8 batches
    params, opt_state = init_state()
    loader = make_loader()
    for _ in range(8):
        batch = loader.next_batch()
        params, opt_state, _loss = step_fn(params, opt_state, batch)
    loader.close()
    ref = jax.tree_util.tree_map(np.asarray, params)

    # --- interrupted run: 5 batches, checkpoint, crash, resume, 3 more
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    params, opt_state = init_state()
    loader = make_loader()
    for i in range(5):
        batch = loader.next_batch()
        params, opt_state, _loss = step_fn(params, opt_state, batch)
    mgr.save(5, {"params": params, "opt_state": opt_state,
                 "batches_consumed": 5})
    loader.close()
    del params, opt_state                      # "crash"

    # template restore: optax NamedTuple state types must come back intact
    p0, o0 = init_state()
    step, state = mgr.restore(
        template={"params": p0, "opt_state": o0, "batches_consumed": 0})
    assert step == 5 and state["batches_consumed"] == 5
    params = jax.tree_util.tree_map(jnp.asarray, state["params"])
    opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
    assert type(opt_state) is type(o0)
    loader = make_loader()
    assert fast_forward(loader, state["batches_consumed"]) == 5
    for _ in range(3):
        batch = loader.next_batch()
        params, opt_state, _loss = step_fn(params, opt_state, batch)
    loader.close()

    resumed = jax.tree_util.tree_map(np.asarray, params)
    jax.tree_util.tree_map(np.testing.assert_array_equal, ref, resumed)


def test_orbax_interop_roundtrip(tmp_path):
    """Orbax bridge: save a params pytree via orbax, restore with and
    without a template, values identical to the native format's."""
    pytest.importorskip("orbax.checkpoint")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dmlc_core_tpu.utils import save_orbax, restore_orbax

    tree = {"w": jnp.arange(6.0).reshape(2, 3),
            "inner": {"b": jnp.ones((4,), jnp.float32)},
            "step": np.int64(17)}
    path = tmp_path / "ock"
    save_orbax(str(path), tree)
    back = restore_orbax(str(path))
    np.testing.assert_array_equal(np.asarray(back["w"]),
                                  np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(back["inner"]["b"]),
                                  np.asarray(tree["inner"]["b"]))

    tmpl = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        tree)
    back2 = restore_orbax(str(path), tmpl)
    np.testing.assert_array_equal(np.asarray(back2["w"]),
                                  np.asarray(tree["w"]))


def test_checkpoint_of_mesh_sharded_params(tmp_path):
    """Save a dp×mp-sharded training state, restore, re-place on the mesh:
    values identical — the multi-chip checkpoint path users actually hit."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from dmlc_core_tpu.utils import CheckpointManager

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(np.array(devices).reshape(4, 2), ("dp", "mp"))
    rng = np.random.default_rng(0)
    v = jax.device_put(jnp.asarray(rng.standard_normal((64, 8)),
                                   jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.standard_normal(64), jnp.float32),
                       NamedSharding(mesh, P()))
    state = {"params": {"v": v, "w": w}, "step": 7}

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(7, state)
    step, back = CheckpointManager(str(tmp_path / "ck")).restore()
    assert step == 7
    np.testing.assert_array_equal(np.asarray(back["params"]["v"]),
                                  np.asarray(v))
    np.testing.assert_array_equal(np.asarray(back["params"]["w"]),
                                  np.asarray(w))
    # re-place on the mesh and keep training-shape invariants
    v2 = jax.device_put(jnp.asarray(back["params"]["v"]),
                        NamedSharding(mesh, P(None, "mp")))
    assert v2.sharding.spec == P(None, "mp")
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(v))


def test_async_save_matches_sync(tmp_path):
    """save_async publishes the same bytes as save; wait() returns the
    path; numpy leaves are snapshotted at call time so in-place mutation
    after the call cannot corrupt the checkpoint."""
    import jax.numpy as jnp

    from dmlc_core_tpu.utils import CheckpointManager

    state = {"w": jnp.arange(8.0), "h": np.arange(4, dtype=np.float32)}
    a = CheckpointManager(str(tmp_path / "sync"))
    a.save(3, state, meta={"k": 1})

    b = CheckpointManager(str(tmp_path / "async"))
    b.save_async(3, state, meta={"k": 1})
    state["h"][:] = -1          # mutate AFTER queueing: must not be seen
    path = b.wait()
    assert path and path.endswith("ckpt-3.bin")

    sa = open(tmp_path / "sync" / "ckpt-3.bin", "rb").read()
    sb = open(tmp_path / "async" / "ckpt-3.bin", "rb").read()
    assert sa == sb
    _, got = b.restore()
    np.testing.assert_array_equal(np.asarray(got["h"]),
                                  np.arange(4, dtype=np.float32))


def test_async_save_serializes_and_surfaces_errors(tmp_path):
    """Back-to-back save_async calls serialize (second waits for first);
    a failing background save raises at the NEXT save_async/wait, never
    silently."""
    from dmlc_core_tpu.utils import CheckpointManager, DMLCError

    m = CheckpointManager(str(tmp_path / "ck"))
    for step in (1, 2, 3):
        m.save_async(step, {"x": np.full(1000, step, np.float32)})
    m.wait()
    assert m.steps == [1, 2, 3]
    # all three restorable with the right contents
    for step in (1, 2, 3):
        _, st = m.restore(step)
        assert st["x"][0] == step

    # failing store (injected at the store layer: as root a read-only dir
    # would not actually block writes) -> the background failure surfaces
    # on wait()
    bad = CheckpointManager(str(tmp_path / "bad"))

    def boom(name, write_fn):
        raise OSError("store write refused")

    bad._store.write_stream = boom
    bad.save_async(1, {"x": np.zeros(2)})
    with pytest.raises(DMLCError, match="async checkpoint save failed"):
        bad.wait()
    # and a failure also surfaces on the NEXT save_async
    bad.save_async(2, {"x": np.zeros(2)})
    with pytest.raises(DMLCError, match="async checkpoint save failed"):
        bad.save_async(3, {"x": np.zeros(2)})


# ---------------------------------------------------------------------------
# leaf-granular partial restore (the elastic resharder's fallback path)
# ---------------------------------------------------------------------------

def test_load_pytree_leaves_partial():
    from dmlc_core_tpu.utils.checkpoint import load_pytree_leaves

    tree = {"params": {"w": np.arange(20, dtype=np.float32).reshape(5, 4),
                       "b": np.float64(2.5)},
            "opt": [np.ones(3, np.int64), np.zeros((2, 2), np.float32)],
            "step": 9}
    buf = io.BytesIO()
    save_pytree(buf, tree)
    buf.seek(0)
    got = load_pytree_leaves(buf, ["params/w", "opt/1"])
    assert sorted(got) == ["opt/1", "params/w"]
    np.testing.assert_array_equal(got["params/w"], tree["params"]["w"])
    np.testing.assert_array_equal(got["opt/1"], tree["opt"][1])
    # unknown paths simply come back absent — the resharder treats that
    # as "checkpoint can't cover this leaf" and fails loudly itself
    buf.seek(0)
    assert load_pytree_leaves(buf, ["nope"]) == {}
    # 0-d leaves keep their shape through the seek path
    buf.seek(0)
    assert load_pytree_leaves(buf, ["params/b"])["params/b"].shape == ()


def test_manager_restore_leaves(tmp_path):
    m = CheckpointManager(str(tmp_path))
    m.save(4, {"a": np.full((3, 3), 4.0, np.float32), "z": np.arange(6)})
    m.save(7, {"a": np.full((3, 3), 7.0, np.float32), "z": np.arange(6)})
    step, got = m.restore_leaves(["a"])
    assert step == 7 and sorted(got) == ["a"]
    assert got["a"][0, 0] == 7.0
    step, got = m.restore_leaves(["a", "z"], step=4)
    assert step == 4 and got["a"][0, 0] == 4.0
    np.testing.assert_array_equal(got["z"], np.arange(6))
    with pytest.raises(DMLCError):
        m.restore_leaves(["a"], step=99)
