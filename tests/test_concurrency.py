"""Concurrency/memory-stream tests — mirrors reference
``unittest_concurrency-like`` coverage plus memory_io round trips."""

import os
import threading
import time

import pytest

from dmlc_core_tpu.utils.common import byteswap, hash_combine, split
from dmlc_core_tpu.utils.concurrency import (
    FIFO,
    PRIORITY,
    ConcurrentBlockingQueue,
    ObjectPool,
    Spinlock,
    ThreadLocalStore,
)
from dmlc_core_tpu.utils.memory_io import (
    MemoryFixedSizeStream,
    MemoryStringStream,
)
from dmlc_core_tpu.utils import DMLCError, serializer


# -- ConcurrentBlockingQueue -------------------------------------------------

def test_queue_fifo_order():
    q = ConcurrentBlockingQueue()
    for i in range(10):
        q.push(i)
    assert [q.pop() for _ in range(10)] == list(range(10))


def test_queue_priority_order():
    q = ConcurrentBlockingQueue(policy=PRIORITY)
    q.push("low", priority=1)
    q.push("high", priority=10)
    q.push("mid", priority=5)
    q.push("high2", priority=10)    # same priority: FIFO tiebreak
    assert [q.pop() for _ in range(4)] == ["high", "high2", "mid", "low"]


def test_queue_bounded_blocks_and_unblocks():
    q = ConcurrentBlockingQueue(max_size=2)
    q.push(1)
    q.push(2)
    done = []

    def producer():
        q.push(3)           # blocks until a pop frees a cell
        done.append(True)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.05)
    assert not done
    assert q.pop() == 1
    t.join(2)
    assert done


def test_queue_mpmc_stress():
    q = ConcurrentBlockingQueue(max_size=8)
    N, NPROD, NCONS = 500, 4, 4
    got = []
    got_lock = threading.Lock()

    def prod(base):
        for i in range(N):
            q.push(base + i)

    def cons():
        while True:
            v = q.pop(timeout=2)
            if v is None:
                return
            with got_lock:
                got.append(v)

    ps = [threading.Thread(target=prod, args=(k * N,)) for k in range(NPROD)]
    cs = [threading.Thread(target=cons) for _ in range(NCONS)]
    for t in ps + cs:
        t.start()
    for t in ps:
        t.join()
    while len(got) < NPROD * N:
        time.sleep(0.01)
    q.signal_for_kill()
    for t in cs:
        t.join(2)
    assert sorted(got) == list(range(NPROD * N))


def test_queue_signal_for_kill_wakes_blocked_pop():
    q = ConcurrentBlockingQueue()
    result = ["sentinel"]

    def blocked():
        result[0] = q.pop()

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.05)
    q.signal_for_kill()
    t.join(2)
    assert result[0] is None
    # sticky until resume (`concurrency.h:208` semantics)
    assert q.push(1) is False
    q.resume()
    assert q.push(1) is True
    assert q.pop() == 1


def test_queue_kill_drains_remaining():
    q = ConcurrentBlockingQueue()
    q.push(1)
    q.push(2)
    q.signal_for_kill()
    # items already queued still pop; then None
    assert q.pop() == 1
    assert q.pop() == 2
    assert q.pop() is None


# -- Spinlock / ThreadLocalStore / ObjectPool --------------------------------

def test_spinlock_mutual_exclusion():
    lock = Spinlock()
    counter = [0]

    def bump():
        for _ in range(1000):
            with lock:
                counter[0] += 1

    ts = [threading.Thread(target=bump) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert counter[0] == 4000


def test_thread_local_store_per_thread_instances():
    ThreadLocalStore.clear()
    ids = {}

    def factory():
        return object()

    def grab(name):
        a = ThreadLocalStore.get(factory)
        b = ThreadLocalStore.get(factory)
        ids[name] = (id(a), id(b))

    grab("main")
    t = threading.Thread(target=grab, args=("t1",))
    t.start()
    t.join()
    assert ids["main"][0] == ids["main"][1]      # same within a thread
    assert ids["main"][0] != ids["t1"][0]        # distinct across threads


def test_object_pool_recycles():
    made = []

    def factory():
        b = bytearray(8)
        made.append(b)
        return b

    pool = ObjectPool(factory, max_free=2)
    a = pool.acquire()
    pool.release(a)
    b = pool.acquire()
    assert b is a                 # recycled, not re-made
    assert len(made) == 1
    # over max_free: dropped
    x, y, z = pool.acquire(), pool.acquire(), pool.acquire()
    pool.release(x)
    pool.release(y)
    pool.release(z)
    assert len(pool._free) == 2


# -- memory streams ----------------------------------------------------------

def test_fixed_stream_rw_roundtrip():
    buf = bytearray(64)
    s = MemoryFixedSizeStream(buf)
    s.write(b"hello")
    s.seek(0)
    assert s.read(5) == b"hello"


def test_fixed_stream_overflow_raises():
    s = MemoryFixedSizeStream(bytearray(4))
    with pytest.raises(DMLCError):
        s.write(b"too long for four")


def test_fixed_stream_readonly():
    s = MemoryFixedSizeStream(b"readonly")
    assert s.read() == b"readonly"
    with pytest.raises(DMLCError):
        s.seek(0) or s.write(b"x")


def test_fixed_stream_seek_bounds():
    s = MemoryFixedSizeStream(bytearray(10))
    s.seek(10)                      # end is legal
    with pytest.raises(DMLCError):
        s.seek(11)
    s.seek(-3, os.SEEK_END)
    assert s.tell() == 7


def test_string_stream_with_serializer():
    """The reference's main use: serializer round trips over memory streams
    (`unittest_serializer.cc:12-25`)."""
    s = MemoryStringStream()
    obj = {"a": [1, 2, 3], "b": "text", "c": (1.5, 2.5)}
    serializer.save(s, obj)
    s.seek(0)
    out = serializer.load(s)
    assert out["a"] == [1, 2, 3]
    assert out["b"] == "text"


def test_fixed_stream_with_serializer():
    buf = bytearray(4096)
    s = MemoryFixedSizeStream(buf)
    serializer.save(s, [1, 2, 3, "four"])
    end = s.tell()
    s.seek(0)
    assert serializer.load(s) == [1, 2, 3, "four"]
    assert s.tell() == end


# -- common helpers ----------------------------------------------------------

def test_split_getline_semantics():
    # interior empties kept, trailing delimiter dropped (dmlc::Split)
    assert split("a,b,,c,", ",") == ["a", "b", "", "c"]
    assert split("", ",") == []
    assert split("a", ",") == ["a"]
    from dmlc_core_tpu import utils
    assert utils.split is split     # single exported implementation


def test_hash_combine_deterministic_and_mixing():
    a = hash_combine(0, 42)
    assert a == hash_combine(0, 42)
    assert a != hash_combine(1, 42)
    assert a != hash_combine(0, 43)
    assert 0 <= a <= 0xFFFFFFFF


def test_byteswap():
    assert byteswap(b"\x01\x02\x03\x04", 4) == b"\x04\x03\x02\x01"
    assert byteswap(b"\x01\x02\x03\x04", 2) == b"\x02\x01\x04\x03"
    assert byteswap(b"ab", 1) == b"ab"
    with pytest.raises(ValueError):
        byteswap(b"abc", 2)
