"""Metrics/tracing subsystem tests + wiring checks (ingest stages must
populate the process-global registry)."""

import threading

import pytest

from dmlc_core_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StageTimer,
    ThroughputMeter,
    metrics,
    trace_span,
)


def test_counter_thread_safe():
    c = Counter()

    def bump():
        for _ in range(1000):
            c.add()

    ts = [threading.Thread(target=bump) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 4000


def test_gauge():
    g = Gauge()
    g.set(3.5)
    assert g.value == 3.5
    assert g.snapshot() == {"type": "gauge", "value": 3.5}


def test_throughput_meter_rates():
    now = [0.0]
    m = ThroughputMeter(window_sec=1.0, clock=lambda: now[0])
    now[0] = 1.0
    m.add(100)          # closes no window yet? t=1.0, win_start=0 → closes
    assert m.total == 100
    assert m.rate() == pytest.approx(100.0)
    now[0] = 2.0
    m.add(50)
    assert m.windowed_rate() > 0


def test_stage_timer_context_and_decorator():
    now = [0.0]
    st = StageTimer(clock=lambda: now[0])
    with st.time():
        now[0] += 2.0
    assert st.count == 1
    assert st.total_sec == pytest.approx(2.0)

    @st
    def work():
        now[0] += 1.0
        return 7

    assert work() == 7
    assert st.count == 2
    assert st.mean_sec == pytest.approx(1.5)


def test_registry_snapshot_and_reuse():
    r = MetricsRegistry()
    r.counter("a.b").add(3)
    r.counter("a.b").add(2)          # same instance by name
    r.gauge("g").set(1.0)
    with r.stage("s").time():
        pass
    snap = r.snapshot()
    assert snap["a.b"]["value"] == 5
    assert snap["g"]["value"] == 1.0
    assert snap["s"]["count"] == 1
    import json
    json.dumps(snap)                  # snapshot must be JSON-serializable
    r.report()                        # must not raise
    r.reset()
    assert r.snapshot() == {}


def test_histogram_exact_quantiles_under_cap():
    """While the sample count fits the reservoir, quantiles are EXACT
    (linear interpolation between closest ranks)."""
    h = Histogram(max_samples=1000)
    for v in range(1, 101):               # 1..100, in order
        h.observe(float(v))
    assert h.count == 100
    assert h.min == 1.0 and h.max == 100.0
    assert h.mean == pytest.approx(50.5)
    assert h.quantile(0.0) == 1.0
    assert h.quantile(1.0) == 100.0
    assert h.quantile(0.5) == pytest.approx(50.5)
    p50, p95, p99 = h.quantiles([0.5, 0.95, 0.99])
    assert p50 == pytest.approx(50.5)
    assert p95 == pytest.approx(95.05)
    assert p99 == pytest.approx(99.01)


def test_histogram_insertion_order_irrelevant():
    import random
    vals = list(range(1, 101))
    random.Random(7).shuffle(vals)
    h = Histogram(max_samples=1000)
    for v in vals:
        h.observe(float(v))
    assert h.quantile(0.5) == pytest.approx(50.5)


def test_histogram_reservoir_beyond_cap_stays_bounded_and_sane():
    h = Histogram(max_samples=64, seed=3)
    for v in range(10_000):
        h.observe(float(v))
    assert h.count == 10_000              # exact even when sampling
    assert h.mean == pytest.approx(4999.5)
    assert h.min == 0.0 and h.max == 9999.0
    # sampled median of U[0,10000) lands near the middle
    assert 2000.0 < h.quantile(0.5) < 8000.0


def test_histogram_empty_and_errors():
    h = Histogram()
    assert h.count == 0
    assert h.quantile(0.5) == 0.0
    assert h.mean == 0.0
    with pytest.raises(ValueError):
        h.quantile(1.5)
    with pytest.raises(ValueError):
        h.quantile(-0.1)
    with pytest.raises(ValueError):
        Histogram(max_samples=0)


def test_histogram_snapshot_and_registry():
    r = MetricsRegistry()
    h = r.histogram("lat")
    assert r.histogram("lat") is h        # same instance by name
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    snap = r.snapshot()["lat"]
    assert snap["type"] == "histogram"
    assert snap["count"] == 4
    assert snap["p50"] == pytest.approx(2.5)
    import json
    json.dumps(snap)


def test_histogram_time_context():
    h = Histogram()
    with h.time():
        pass
    assert h.count == 1
    assert h.min >= 0.0


def test_trace_span_noop_safe():
    with trace_span("unit-test-span"):
        x = 1 + 1
    assert x == 2


def test_cached_handles_rebind_after_reset(tmp_path):
    """A parser built BEFORE metrics.reset() must still report into the
    registry afterwards (generation-based rebinding)."""
    f = tmp_path / "r.libsvm"
    f.write_text("".join(f"{i%2} {i%5+1}:1.0\n" for i in range(100)))
    from dmlc_core_tpu.data import create_parser
    p = create_parser(f"file://{f}", 0, 1, "libsvm", threaded=False)
    metrics.reset()                       # epoch boundary
    rows = sum(blk.size for blk in p)
    p.close()
    assert rows == 100
    assert metrics.snapshot()["parser.bytes"]["total"] == f.stat().st_size


def test_ingest_populates_global_metrics(tmp_path):
    metrics.reset()
    f = tmp_path / "d.libsvm"
    f.write_text("".join(f"{i%2} {i%5+1}:1.0\n" for i in range(200)))
    from dmlc_core_tpu.data import create_parser
    p = create_parser(f"file://{f}", 0, 1, "libsvm")
    rows = sum(blk.size for blk in p)
    p.close()
    assert rows == 200
    snap = metrics.snapshot()
    assert snap["parser.bytes"]["total"] == f.stat().st_size
    assert snap["parser.parse"]["count"] >= 1
    assert snap["parser.chunk"]["count"] >= 1
