"""Closed-loop autotuner (ISSUE 7): hill-climb convergence + persistence,
freeze-on-anomaly with rollback, tuned.resolve precedence, concurrent
tuned-file writers, and lenient env-knob parsing."""

import json
import os
import threading
import time
import types

import pytest

from dmlc_core_tpu.pipeline import autotune as at
from dmlc_core_tpu.pipeline import fingerprint as fp
from dmlc_core_tpu.pipeline import tuned
from dmlc_core_tpu.utils.metrics import metrics


@pytest.fixture()
def tuned_file(tmp_path, monkeypatch):
    path = tmp_path / "tuned.json"
    monkeypatch.setenv("DMLC_TUNED_CONFIG", str(path))
    return path


def _counter(name):
    return metrics.counter(name).value


def _run_to_convergence(tuner, objective, max_epochs=60):
    for _ in range(max_epochs):
        cfg = tuner.begin_epoch()
        tuner.end_epoch(objective(cfg))
        if tuner.converged:
            return
    raise AssertionError("did not converge")


# -- controller core ---------------------------------------------------


def test_hill_climb_finds_optimum_and_persists(tuned_file):
    metrics.gauge("slo.active_breaches").set(0)
    knobs = [at.Knob("threads", (1, 2, 4, 8), baseline=1),
             at.Knob("prefetch", (1, 2, 4), baseline=1)]
    t = at.Autotuner(knobs, key="deadbeef|c1|host", min_gain=0.01)
    e0 = _counter("autotune.epochs")

    def objective(cfg):      # unimodal peak at threads=4, prefetch=4
        return 100.0 - 10 * abs(cfg["threads"] - 4) \
                     - 10 * abs(cfg["prefetch"] - 4)

    _run_to_convergence(t, objective)
    assert t.best_config() == {"threads": 4, "prefetch": 4}
    assert metrics.gauge("autotune.converged").value == 1.0
    assert _counter("autotune.epochs") - e0 == t.epoch
    assert metrics.gauge("autotune.knob.threads").value == 4.0
    # converged winner persisted under the reserved autotune section
    doc = json.loads(tuned_file.read_text())
    saved = doc["autotune"]["deadbeef|c1|host"]
    assert saved["knobs"] == {"threads": 4, "prefetch": 4}
    assert saved["objective"] == pytest.approx(100.0)
    # steady state after convergence: no further mutations proposed
    m0 = _counter("autotune.mutations")
    cfg = t.begin_epoch()
    assert cfg == {"threads": 4, "prefetch": 4}
    assert t.end_epoch(objective(cfg))["action"] == "steady"
    assert _counter("autotune.mutations") == m0


def test_warm_start_skips_search(tuned_file):
    metrics.gauge("slo.active_breaches").set(0)
    tuned.save_autotuned("k|c1|host", {"knobs": {"threads": 8},
                                       "objective": 50.0})
    t = at.Autotuner([at.Knob("threads", (1, 2, 4, 8), baseline=1)],
                     key="k|c1|host")
    assert t.converged and t.config() == {"threads": 8}
    m0 = _counter("autotune.mutations")
    t.begin_epoch()
    assert t.end_epoch(49.0)["action"] == "steady"
    assert _counter("autotune.mutations") == m0


def test_rejected_mutation_rolls_back(tuned_file):
    metrics.gauge("slo.active_breaches").set(0)
    t = at.Autotuner([at.Knob("k", (1, 2, 4), baseline=2)], key=None)
    t.begin_epoch()
    out = t.end_epoch(10.0)                      # baseline; mutation staged
    assert out["action"] == "baseline" and "next_knob" in out
    mutated = t.config()["k"]
    assert mutated != 2
    t.begin_epoch()
    out = t.end_epoch(5.0)                       # worse: revert
    assert out["action"] == "reject"
    assert t.config()["k"] != mutated or t.config()["k"] == 2
    assert t.best_config() == {"k": 2}


def test_abort_epoch_reverts_unjudged(tuned_file):
    metrics.gauge("slo.active_breaches").set(0)
    t = at.Autotuner([at.Knob("k", (1, 2, 4), baseline=1)], key=None)
    t.begin_epoch()
    t.end_epoch(10.0)                            # stages first mutation
    assert t.config() != t.best_config()
    t.begin_epoch()
    t.abort_epoch()                              # peer died mid-epoch
    assert t.config() == t.best_config()         # mutation reverted
    assert t.best_config() == {"k": 1}           # ...and never judged
    # the controller keeps going afterwards
    t.begin_epoch()
    t.end_epoch(10.0)


def test_freeze_on_injected_stall_halts_and_rolls_back(tuned_file,
                                                       monkeypatch):
    """Satellite 4: a DMLC_FAULT_SPEC-injected stall flagged by the real
    StallDetector must halt mutations and roll back to last-good."""
    from dmlc_core_tpu.telemetry.anomaly import StallDetector
    from dmlc_core_tpu.utils.faults import clear_faults, fault_point

    metrics.gauge("slo.active_breaches").set(0)
    monkeypatch.delenv("DMLC_FAULT_SPEC", raising=False)
    clear_faults()
    det = StallDetector("autotune_test", z_threshold=8.0, min_samples=4)

    def tick():
        t0 = time.perf_counter()
        fault_point("autotune.test.stage")
        det.observe(time.perf_counter() - t0)

    t = at.Autotuner([at.Knob("k", (1, 2, 4), baseline=1)], key=None,
                     backoff_epochs=2)
    t.begin_epoch()
    for _ in range(10):
        tick()                                   # clean warmup epoch
    t.end_epoch(10.0)                            # baseline; mutation staged
    assert t.config() == {"k": 2}
    stalls0 = _counter("anomaly.stalls.autotune_test")
    t.begin_epoch()
    monkeypatch.setenv("DMLC_FAULT_SPEC",
                       "autotune.test.stage:latency=150ms")
    tick()                                       # injected stall fires
    monkeypatch.delenv("DMLC_FAULT_SPEC")
    clear_faults()
    assert _counter("anomaly.stalls.autotune_test") > stalls0
    out = t.end_epoch(99.0)                      # great number, but flagged
    assert out["action"] == "freeze"
    # rolled back to last-good, the 99.0 was never believed
    assert t.config() == t.best_config() == {"k": 1}
    # frozen: the next epochs back off with no new mutation
    m0 = _counter("autotune.mutations")
    t.begin_epoch()
    assert t.end_epoch(10.0)["action"] == "backoff"
    t.begin_epoch()
    assert t.end_epoch(10.0)["action"] == "backoff"
    assert _counter("autotune.mutations") == m0
    # pressure gone: the search resumes
    t.begin_epoch()
    assert t.end_epoch(10.0)["action"] == "resume"
    assert _counter("autotune.mutations") == m0 + 1


def test_freeze_on_active_slo_breach(tuned_file):
    t = at.Autotuner([at.Knob("k", (1, 2), baseline=1)], key=None)
    metrics.gauge("slo.active_breaches").set(1)
    try:
        t.begin_epoch()
        assert t.end_epoch(10.0)["action"] == "freeze"
    finally:
        metrics.gauge("slo.active_breaches").set(0)


# -- ambient gating (DMLC_AUTOTUNE) ------------------------------------


def test_maybe_autotuner_gating(tuned_file, monkeypatch):
    factory = lambda: [at.Knob("k", (1, 2))]      # noqa: E731
    monkeypatch.delenv("DMLC_AUTOTUNE", raising=False)
    assert at.maybe_autotuner(factory) is None            # opt-in only
    assert at.maybe_autotuner(factory, gate=False) is None
    assert at.maybe_autotuner(factory, gate=True) is not None
    monkeypatch.setenv("DMLC_AUTOTUNE", "0")
    assert at.maybe_autotuner(factory) is None            # kill switch
    assert at.maybe_autotuner(factory, gate=True) is None  # ...beats force
    monkeypatch.setenv("DMLC_AUTOTUNE", "1")
    assert at.maybe_autotuner(factory) is not None
    assert not at.enabled() if os.environ.get("DMLC_AUTOTUNE") == "0" \
        else at.enabled()


# -- tuned.py: precedence + concurrency --------------------------------


def test_resolve_precedence(tuned_file, monkeypatch):
    """explicit ctor value > env > persisted file > built-in default."""
    monkeypatch.delenv("DMLC_PUT_THREADS", raising=False)
    monkeypatch.delenv("DMLC_WIRE_COMPACT", raising=False)
    # built-in defaults (no env, no file)
    assert tuned.resolve("tpu", "auto", "auto") == (1, True)
    assert tuned.resolve("cpu", "auto", "auto") == (1, False)
    # persisted file replaces built-ins
    tuned.save_tuned({"platform": "tpu", "put_threads": 4,
                      "wire_compact": False})
    assert tuned.resolve("tpu", "auto", "auto") == (4, False)
    # env beats the file
    monkeypatch.setenv("DMLC_PUT_THREADS", "2")
    monkeypatch.setenv("DMLC_WIRE_COMPACT", "1")
    assert tuned.resolve("tpu", "auto", "auto") == (2, True)
    # explicit ctor values beat everything
    assert tuned.resolve("tpu", 8, False) == (8, False)
    # malformed env falls through to the file tier (lenient, no raise)
    monkeypatch.setenv("DMLC_PUT_THREADS", "banana")
    monkeypatch.setenv("DMLC_WIRE_COMPACT", "definitely")
    assert tuned.resolve("tpu", "auto", "auto") == (4, False)


def test_save_tuned_concurrent_writers(tuned_file):
    """Satellite 1: N concurrent writers (platform entries AND autotune
    entries) must all land — the read-modify-write is lock-serialized."""
    n = 12
    errors = []
    barrier = threading.Barrier(n)

    def write(i):
        try:
            barrier.wait(timeout=30)
            if i % 2:
                tuned.save_tuned({"platform": f"plat{i}", "value": i})
            else:
                tuned.save_autotuned(f"key{i}", {"knobs": {"k": i}})
        except Exception as e:                       # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=write, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors
    doc = json.loads(tuned_file.read_text())
    for i in range(n):
        if i % 2:
            assert doc[f"plat{i}"]["value"] == i
        else:
            assert doc["autotune"][f"key{i}"]["knobs"]["k"] == i
    # and the readers see what the writers wrote
    assert tuned.load_tuned("plat1") == {"platform": "plat1", "value": 1}
    assert tuned.load_autotuned("key0") == {"knobs": {"k": 0}}


# -- env-knob hardening (satellite 3) ----------------------------------


def test_env_int_lenient_with_one_warning(monkeypatch, caplog):
    from dmlc_core_tpu.utils import parameter as pm

    monkeypatch.setattr(pm, "_env_warned", set())
    monkeypatch.setenv("DMLC_TEST_KNOB", "8x")
    with caplog.at_level("WARNING"):
        assert pm.env_int("DMLC_TEST_KNOB", 7) == 7
        assert pm.env_int("DMLC_TEST_KNOB", 7) == 7
    warned = [r for r in caplog.records if "DMLC_TEST_KNOB" in r.message]
    assert len(warned) == 1                    # one WARNING, not one per use
    monkeypatch.setenv("DMLC_TEST_KNOB", "3")
    assert pm.env_int("DMLC_TEST_KNOB", 7, minimum=1) == 3
    monkeypatch.setenv("DMLC_TEST_KNOB", "0")
    assert pm.env_int("DMLC_TEST_KNOB", 7, minimum=1) == 1   # clamped
    monkeypatch.delenv("DMLC_TEST_KNOB")
    assert pm.env_int("DMLC_TEST_KNOB", 7) == 7


def test_malformed_page_cache_queue_does_not_raise(tmp_path, monkeypatch):
    from dmlc_core_tpu.pipeline.page_cache import PageCacheWriter
    from dmlc_core_tpu.utils import parameter as pm

    monkeypatch.setattr(pm, "_env_warned", set())
    monkeypatch.setenv("DMLC_PAGE_CACHE_QUEUE", "not-a-number")
    w = PageCacheWriter(str(tmp_path / "x.pages"), {"f": 1})
    try:
        assert w._q.maxsize == 8               # fell back to the default
    finally:
        w.abort()


def test_malformed_num_threads_does_not_raise(monkeypatch):
    from dmlc_core_tpu.data.parser import _default_nthreads
    from dmlc_core_tpu.utils import parameter as pm

    monkeypatch.setattr(pm, "_env_warned", set())
    monkeypatch.setenv("DMLC_NUM_THREADS", "four")
    monkeypatch.delenv("OMP_NUM_THREADS", raising=False)
    assert _default_nthreads() >= 1            # heuristic fallback, no raise
    monkeypatch.setenv("DMLC_NUM_THREADS", "3")
    assert _default_nthreads() == 3


# -- fingerprint / tuning keys -----------------------------------------


def test_autotune_key_relaxed_projection():
    base = {"page_format": 1,
            "files": [["/d/a.svm", 100, 111], ["/d/b.svm", 200, 222]],
            "batch_rows": 64, "nnz_cap": 1024}
    touched = dict(base, files=[["/d/a.svm", 100, 999],
                                ["/d/b.svm", 200, 222]])
    resized = dict(base, files=[["/d/a.svm", 101, 111],
                                ["/d/b.svm", 200, 222]])
    format_bump = dict(base, page_format=2)
    k = fp.autotune_key(base, "host", shape="c1")
    assert fp.autotune_key(touched, "host", shape="c1") == k       # mtime
    assert fp.autotune_key(format_bump, "host", shape="c1") == k   # version
    assert fp.autotune_key(resized, "host", shape="c1") != k       # data
    assert fp.autotune_key(base, "tpu", shape="c1") != k           # platform
    assert fp.autotune_key(base, "host", shape="c8") != k          # host
    assert k.endswith("|c1|host")
    # un-stat-able sources still key per host+platform
    assert fp.autotune_key(None, "host", shape="c1").endswith("|c1|host")


def test_device_loader_fingerprint_uses_shared_builder(tmp_path):
    """The page-cache fingerprint and the tuning key must come from one
    builder — this pins the loader to fingerprint.pack_fingerprint."""
    import numpy as np

    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline.device_loader import DeviceLoader

    data = tmp_path / "t.libsvm"
    rng = np.random.default_rng(0)
    with open(data, "w") as f:
        for r in range(50):
            idx = np.sort(rng.choice(1000, size=5, replace=False))
            f.write(f"{r % 2} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    loader = DeviceLoader(create_parser(str(data), 0, 1, "libsvm",
                                        nthreads=1, threaded=False),
                          batch_rows=16, nnz_cap=128, emit="host",
                          cache=str(tmp_path / "t.pages"))
    try:
        got = loader._cache_fingerprint()
        split = fp.find_file_split(loader.source)
        assert split is not None
        assert got["files"] == fp.split_files(split)
        key = fp.autotune_key(got, "host")
        assert key == fp.autotune_key(got, "host")     # deterministic
    finally:
        loader.close()


# -- serving knob space -------------------------------------------------


def test_serving_knob_space_applies_live():
    applied = []
    fake = types.SimpleNamespace(
        engine=types.SimpleNamespace(
            ladder=types.SimpleNamespace(max_rows=64, max_nnz=4096)),
        max_delay_s=0.002, max_batch_rows=64, max_batch_nnz=4096,
        apply_knobs=lambda **kw: applied.append(kw))
    knobs = at.serving_knob_space(fake)
    by = {k.name: k for k in knobs}
    assert by["max_batch_rows"].values[-1] == 64       # bounded by ladder
    assert by["max_batch_nnz"].values[-1] == 4096
    assert by["max_delay_s"].value == pytest.approx(0.002)  # baseline kept
    t = at.Autotuner(knobs, key=None)
    t.begin_epoch()                                    # pushes live values
    assert {"max_delay_s": 0.002} in applied
    assert {"max_batch_rows": 64} in applied


def test_micro_batcher_apply_knobs_bounds():
    from dmlc_core_tpu.serving.batcher import MicroBatcher
    from dmlc_core_tpu.utils.logging import DMLCError

    engine = types.SimpleNamespace(
        ladder=types.SimpleNamespace(max_rows=32, max_nnz=1024))
    b = MicroBatcher(engine, max_queue=4)
    try:
        b.apply_knobs(max_delay_s=0.004, max_batch_rows=16,
                      max_batch_nnz=512)
        assert (b.max_delay_s, b.max_batch_rows, b.max_batch_nnz) \
            == (0.004, 16, 512)
        with pytest.raises(DMLCError):
            b.apply_knobs(max_batch_rows=64)           # beyond the ladder
        with pytest.raises(DMLCError):
            b.apply_knobs(max_delay_s=-1.0)
        assert b.max_batch_rows == 16                  # rejected, unchanged
    finally:
        b.close(drain=False)


# -- end-to-end: serve_ingest wiring ------------------------------------


def test_serve_ingest_autotunes_across_connections(tmp_path, monkeypatch):
    """Three served connections = three evaluation epochs; the tuner must
    count them and export knob gauges while frames flow unchanged."""
    import numpy as np

    from conftest import start_ingest_worker
    from dmlc_core_tpu.pipeline import RemoteIngestLoader

    monkeypatch.setenv("DMLC_TUNED_CONFIG", str(tmp_path / "tuned.json"))
    monkeypatch.delenv("DMLC_AUTOTUNE", raising=False)
    metrics.gauge("slo.active_breaches").set(0)
    data = tmp_path / "w.libsvm"
    rng = np.random.default_rng(1)
    with open(data, "w") as f:
        for r in range(300):
            idx = np.sort(rng.choice(5000, size=8, replace=False))
            f.write(f"{r % 2} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    e0 = _counter("autotune.epochs")
    port = start_ingest_worker(str(data), 0, 1, max_epochs=3,
                               autotune=True)
    for _ in range(3):
        rl = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64,
                                emit="host")
        frames = 0
        for kind, buf, meta, rows in rl:
            assert kind == "fused"
            rl.recycle(buf)
            frames += 1
        rl.close()
        assert frames > 0
    deadline = time.monotonic() + 10
    while (_counter("autotune.epochs") - e0 < 3
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert _counter("autotune.epochs") - e0 == 3
    assert metrics.gauge("autotune.knob.parser_threads").value >= 1


def test_serve_ingest_autotune_off_is_noop(tmp_path, monkeypatch):
    """DMLC_AUTOTUNE unset (or =0): serve_ingest must not construct a
    controller — no autotune.* activity at all."""
    import numpy as np

    from conftest import start_ingest_worker
    from dmlc_core_tpu.pipeline import RemoteIngestLoader

    monkeypatch.setenv("DMLC_AUTOTUNE", "0")
    data = tmp_path / "n.libsvm"
    rng = np.random.default_rng(2)
    with open(data, "w") as f:
        for r in range(100):
            idx = np.sort(rng.choice(1000, size=5, replace=False))
            f.write(f"{r % 2} " + " ".join(
                f"{j}:{rng.random():.3f}" for j in idx) + "\n")
    e0 = _counter("autotune.epochs")
    m0 = _counter("autotune.mutations")
    port = start_ingest_worker(str(data), 0, 1, max_epochs=1,
                               autotune=True)   # kill switch beats force
    rl = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=64,
                            emit="host")
    frames = 0
    for kind, buf, meta, rows in rl:
        rl.recycle(buf)
        frames += 1
    rl.close()
    assert frames > 0
    time.sleep(0.2)
    assert _counter("autotune.epochs") == e0
    assert _counter("autotune.mutations") == m0
