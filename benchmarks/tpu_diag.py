"""Host→device transfer diagnostics for the axon-tunnelled TPU.

The ingest pipeline's narrowest link is ``jax.device_put`` over the tunnel
(BENCH_r02: h2d 0.85→1.41s across repeats while wall degraded 113→63 MB/s).
This script characterizes that link so the bench config (batch size, transfer
streams, prefetch depth) is chosen from measurement, not guesswork:

* ``put_bw``      — bandwidth + per-put latency vs payload size (the knee
                    tells us how big a fused batch must be to amortize RPC
                    overhead).
* ``put_streams`` — aggregate bandwidth with K concurrent transfer threads
                    (whether parallel RPC streams pipeline the tunnel; feeds
                    DeviceLoader ``put_threads``).
* ``put_drift``   — N consecutive equal puts, first/last-quartile ratio
                    (the run-over-run degradation telemetry, VERDICT r2
                    weak#1).
* ``unpack``      — cost of the jitted fused-buffer unpack (slices + bitcast
                    + searchsorted) relative to the raw put.

Usage: ``python benchmarks/tpu_diag.py [out.json]`` — prints one JSON doc,
optionally writes it to the given path.  Safe on CPU (labels the platform).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _bw(nbytes: int, sec: float) -> float:
    return nbytes / max(sec, 1e-9) / (1 << 20)


def bench_put_bw(jax, np) -> list:
    """Per-put times ride block_until_ready (diagnostic resolution), but
    each rep's bytes are mutated (dedupe-proof) and the whole sequence
    ends in a d2h value read whose wall backs ``verified_mbps`` — the
    number to trust when the per-put futures resolve early (03:5x window:
    ready-futures are not completion proof on the tunnel runtime)."""
    out = []
    for mb in (1, 4, 16, 64):
        words = mb * (1 << 20) // 4
        host = np.arange(words, dtype=np.int32)
        # one warm put (allocator/tunnel setup), then timed reps
        jax.block_until_ready(jax.device_put(host))
        times = []
        t_all = time.perf_counter()
        h = None
        for rep in range(5):
            host[rep] = -rep - 1          # distinct bytes per rep
            t0 = time.perf_counter()
            h = jax.device_put(host)
            jax.block_until_ready(h)
            times.append(time.perf_counter() - t0)
        int(np.asarray(h[:1])[0])         # sequence completion proof
        wall = time.perf_counter() - t_all
        med = statistics.median(times)
        out.append({"mb": mb, "median_s": round(med, 4),
                    "min_s": round(min(times), 4),
                    "mbps": round(_bw(words * 4, med), 1),
                    "verified_mbps": round(_bw(5 * words * 4, wall), 1)})
    return out


def bench_put_streams(jax, np) -> list:
    mb = 16
    words = mb * (1 << 20) // 4
    out = []
    for k in (1, 2, 4):
        hosts = [np.arange(words, dtype=np.int32) + i for i in range(k)]
        for h in hosts:  # warm
            jax.block_until_ready(jax.device_put(h))
        reps = 3
        handles = [None] * k
        t0 = time.perf_counter()

        def run(i, h):
            for rep in range(reps):
                h[rep] = -(i * reps + rep) - 1   # distinct bytes per put
                handles[i] = jax.device_put(h)
                jax.block_until_ready(handles[i])

        threads = [threading.Thread(target=run, args=(i, h))
                   for i, h in enumerate(hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for h in handles:                 # completion proof, every stream
            int(np.asarray(h[:1])[0])
        dt = time.perf_counter() - t0
        out.append({"streams": k,
                    "agg_mbps": round(_bw(k * reps * words * 4, dt), 1)})
    return out


def bench_put_drift(jax, np, n: int = 20) -> dict:
    words = 16 * (1 << 20) // 4
    host = np.arange(words, dtype=np.int32)
    jax.block_until_ready(jax.device_put(host))
    times = []
    h = None
    for i in range(n):
        host[i] = -i - 1                  # distinct bytes per put
        t0 = time.perf_counter()
        h = jax.device_put(host)
        jax.block_until_ready(h)
        times.append(time.perf_counter() - t0)
    int(np.asarray(h[:1])[0])             # sequence completion proof
    q = max(1, n // 4)
    first, last = statistics.mean(times[:q]), statistics.mean(times[-q:])
    return {"n": n, "first_quartile_s": round(first, 4),
            "last_quartile_s": round(last, 4),
            "drift_ratio": round(last / first, 3),
            "all_s": [round(t, 4) for t in times]}


def _time_put_unpack(jax, buf, unpack) -> dict:
    # wire buffers can't be byte-mutated (it would corrupt the format),
    # so per-phase times keep block_until_ready resolution; the trailing
    # value read at least proves the final put+unpack really completed
    jax.block_until_ready(unpack(jax.device_put(buf))["vals"])  # compile
    t_put, t_unp = [], []
    vals = None
    for _ in range(5):
        t0 = time.perf_counter()
        dev = jax.device_put(buf)
        jax.block_until_ready(dev)
        t_put.append(time.perf_counter() - t0)
        t1 = time.perf_counter()
        vals = unpack(dev)["vals"]
        jax.block_until_ready(vals)
        t_unp.append(time.perf_counter() - t1)
    float(vals.ravel()[0])
    return {"buf_mb": round(len(buf) * 4 / (1 << 20), 2),
            "put_median_s": round(statistics.median(t_put), 4),
            "unpack_median_s": round(statistics.median(t_unp), 4)}


def bench_unpack(jax, np) -> dict:
    """Put+decode cost for the v2 layout AND the compact v3 layout on the
    same batch: whether the v3 wire saving survives its on-device decode
    (shifts + gathers) is the go/no-go for wire compaction on this link."""
    from dmlc_core_tpu import native
    from dmlc_core_tpu.data.row_block import RowBlockContainer
    from dmlc_core_tpu.pipeline.device_loader import (_fused_words_meta,
                                                      _get_unpack,
                                                      _host_fused)
    rows, nnz = 16384, 360448
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1 << 20, nnz).astype(np.int64)
    vals = (rng.integers(0, 10000, nnz) / 10000).astype(np.float32)
    row_ptr = np.linspace(0, nnz, rows + 1).astype(np.int64)
    host = {
        "ids": ids.astype(np.int32),
        "vals": vals,
        "row_ptr": row_ptr.astype(np.int32),
        "labels": rng.random(rows).astype(np.float32),
        "weights": np.ones(rows, np.float32),
    }
    out = {"rows": rows, "nnz": nnz,
           "v2": _time_put_unpack(jax, _host_fused(host, rows, nnz),
                                  _get_unpack(rows, nnz))}
    if native.has_compact():
        c = RowBlockContainer()
        blk = type("B", (), {"offsets": row_ptr, "labels": host["labels"],
                             "weights": host["weights"],
                             "indices": ids.astype(np.uint64),
                             "values": vals, "size": rows})()
        del c
        p = native.Packer(rows, nnz, compact=True)
        items = list(p.feed(blk)) or []
        tail = p.flush()
        if tail is not None:
            items.append(tail)
        p.close()
        buf, meta = items[0]
        out["v3"] = _time_put_unpack(
            jax, buf[:_fused_words_meta(rows, meta)], _get_unpack(rows, meta))
        out["v3"]["meta"] = int(meta)
    return out


def main() -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    import jax

    import bench as bench_mod
    if os.environ.get("DMLC_FORCE_CPU") == "1":
        # the axon plugin's client init can block on a busy tunnel even
        # under JAX_PLATFORMS=cpu — pin cpu + drop its backend factory
        bench_mod.force_cpu()
    elif os.environ.get("DMLC_REQUIRE_TPU") == "1":
        # probe in a SUBPROCESS first: jax.devices() against a dead/busy
        # tunnel blocks indefinitely in-process (see tpu_micro.py)
        if not bench_mod.probe_tpu():
            bench_mod.require_tpu_or_exit("cpu")
    import numpy as np

    bench_mod.require_tpu_or_exit(jax.devices()[0].platform)

    doc = {"platform": jax.devices()[0].platform,
           "put_bw": bench_put_bw(jax, np),
           "put_streams": bench_put_streams(jax, np),
           "put_drift": bench_put_drift(jax, np),
           "unpack": bench_unpack(jax, np)}
    text = json.dumps(doc, indent=1)
    print(text)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(text)


if __name__ == "__main__":
    main()
