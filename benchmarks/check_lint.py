"""Lint gate over the committed ``lint_baseline.json``.

The same shape as ``check_regression.py``: a committed artifact is the
contract, the tool exits non-zero when the tree moves past it.  Here
the artifact is the dmlclint finding set — the baseline is **empty**
after the ISSUE 9 sweep, so any new finding fails CI until it is fixed
or carries an in-source ``# dmlclint: disable=<rule>`` suppression
with a justification.

Findings are keyed by ``(rule, path, message)`` — line numbers churn
with unrelated edits and are deliberately not part of the key.  A
baselined finding that disappears is reported as fixed and the tool
suggests re-baselining (``--update``) so the shrink is committed.

Usage::

    python benchmarks/check_lint.py [--update] [--baseline PATH] [paths]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from dmlc_core_tpu.analysis.core import lint_paths  # noqa: E402

SCHEMA = "dmlc.lint.baseline/1"
_DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "lint_baseline.json")


def _key(f: Dict[str, Any]) -> Tuple[str, str, str]:
    return (f["rule"], f["path"], f["message"])


def run(paths: List[str]) -> List[Dict[str, Any]]:
    findings, _stats, _ctx = lint_paths(paths, repo_root=_REPO)
    return [f.to_dict() for f in findings]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the tree against the committed lint baseline")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_REPO, "dmlc_core_tpu")],
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline artifact (default: "
                         "benchmarks/lint_baseline.json)")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline from this run and exit 0")
    args = ap.parse_args(argv)

    current = run(args.paths)

    if args.update:
        payload = {"schema": SCHEMA,
                   "findings": sorted(
                       current, key=lambda f: (f["rule"], f["path"],
                                               f["message"]))}
        tmp = f"{args.baseline}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.baseline)
        print(f"check_lint: baseline rewritten with {len(current)} "
              f"finding(s) → {args.baseline}")
        return 0

    try:
        with open(args.baseline, encoding="utf-8") as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"check_lint: baseline unreadable ({e}) — run with --update")
        return 1
    known = {_key(f) for f in baseline.get("findings", [])}
    cur_keys = {_key(f) for f in current}

    new = [f for f in current if _key(f) not in known]
    fixed = sorted(known - cur_keys)
    if fixed:
        print(f"check_lint: {len(fixed)} baselined finding(s) no longer "
              f"fire — shrink the baseline with --update:")
        for rule, path, _msg in fixed[:10]:
            print(f"  fixed: {rule} @ {path}")
    if new:
        print(f"check_lint: {len(new)} NEW finding(s) past the baseline:")
        for f in new:
            print(f"  {f['path']}:{f['line']}: {f['rule']}: {f['message']}")
        print("fix them or suppress with a justified "
              "`# dmlclint: disable=<rule>` (see docs/analysis.md)")
        return 1
    print(f"check_lint: ok ({len(current)} finding(s), all baselined; "
          f"baseline {len(known)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
