#!/bin/bash
# Soak: failure-injection + elastic-rejoin tests under repetition (r5).
#
# Coordination with the TPU harvest on a 1-core host: harvest_run.sh
# touches /tmp/harvest_active for its whole run, and this soak waits while
# it exists (plus a process check as backstop).  The guard is start-of-
# iteration granularity — a grant arriving MID-iteration can still overlap
# up to one iteration (~1-3 min) of soak load with the window's first
# config; the harvest's best-of-N timing absorbs that, and the residual is
# stated here rather than pretended away.
cd "$(dirname "$0")/.."
LOG=${SOAK_LOG:-/tmp/soak_r5.log}
wait_clear() {
    while [ -e /tmp/harvest_active ] || pgrep -f \
        "python bench.py|bench_suite.py|tpu_micro.py|tpu_diag.py" \
        >/dev/null; do
        sleep 30
    done
}
echo "=== soak: 20x failure-injection + 10x elastic (started $(date -u +%H:%M)) ===" >"$LOG"
pass=0; fail=0
for i in $(seq 1 20); do
    wait_clear
    if timeout 900 python -m pytest tests/test_examples.py -q \
        -k "failure_injection" >>"$LOG" 2>&1; then
        echo "iter $i: PASS" >>"$LOG"; pass=$((pass+1))
    else
        echo "iter $i: FAIL" >>"$LOG"; fail=$((fail+1))
    fi
done
echo "fi soak done: $pass pass / $fail fail" >>"$LOG"
epass=0; efail=0
for i in $(seq 1 10); do
    wait_clear
    if timeout 900 python -m pytest tests/test_tracker_rabit.py -q \
        -k "elastic" >>"$LOG" 2>&1; then
        echo "elastic iter $i: PASS" >>"$LOG"; epass=$((epass+1))
    else
        echo "elastic iter $i: FAIL" >>"$LOG"; efail=$((efail+1))
    fi
done
echo "elastic soak done: $epass pass / $efail fail" >>"$LOG"
echo DONE >>"$LOG"
