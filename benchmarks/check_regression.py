"""Regression gate over the committed ``BENCH_*.json`` trajectory.

Every benchmark writes one ``BENCH_<family>_r<round>.json`` artifact per
round (``BENCH_serving_r06.json``, ``BENCH_capacity_r05.json``, bare
``BENCH_r05.json``).  Until now those were a folder of JSON — nothing
failed when a PR made serving 30% slower.  This tool turns the
trajectory into a gate:

* group artifacts by family, order by round number;
* flatten the newest and the previous round into dotted numeric keys
  (``scenarios.concurrent.latency_ms.p50``);
* classify each shared key by name — throughput-like tokens
  (qps/rate/throughput/mb_s/rows) regress when they DROP, latency-like
  tokens (latency/p50/p95/p99/seconds/ms/wall/overhead) regress when
  they RISE; keys matching neither heuristic are informational only;
* exit 1 when any shared key moved in its bad direction by more than
  the threshold (default 10%, ``--threshold 0.25`` / env
  ``DMLC_BENCH_THRESHOLD``).

A family with fewer than two rounds passes vacuously (first round of a
new bench is the baseline, not a regression).  Tiny absolute values are
ignored (``--min-abs``, default 1e-9) — a 0.0001ms → 0.0002ms "100%
regression" is measurement noise, not signal.

``--emit-history`` additionally appends one JSON line per gated family
to ``PROGRESS.jsonl`` (newest round, direction-classified headline
metrics, pass/regressed status), so the bench trajectory is
machine-readable — the telemetry time machine for the benches
themselves.

Usage::

    python benchmarks/check_regression.py [--dir REPO]
        [--threshold 0.1] [--min-abs 1e-9] [--family serving]
        [--emit-history] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

#: BENCH_<family>_r<round>.json; bare BENCH_r05.json → family "core"
_BENCH_RE = re.compile(r"^BENCH_(?:(?P<family>.+)_)?r(?P<round>\d+)"
                       r"(?P<partial>_partial)?\.json$")

_HIGHER_BETTER = ("qps", "rate", "throughput", "mb_s", "mbs", "rows",
                  "goodput", "ok", "hits", "speedup", "mfu", "fill",
                  "conns_held")
# padding_ratio (padded-nnz / true-nnz, ISSUE 6 ragged path): 1.0 is the
# floor, every point above it is padding tax — lower is better.  The
# ragged scenario families (ingest_ragged, *_ragged serving scenarios)
# need no extra tokens: their qps/latency/rows keys classify as usual.
#  epochs_to_converge (ISSUE 7 autotuner cold start): each epoch spent
#  searching is an epoch served on a worse config — fewer is better.
#  The reshard family (ISSUE 9, BENCH_reshard_r*.json) needs no extra
#  tokens either: reshard_wall_s / ckpt_reload_wall_s gate lower-better
#  via "wall", reshard_vs_reload_speedup gates higher-better via
#  "speedup".
#  bytes_per_row (ISSUE 12 sharded embeddings): wire cost of one looked-up
#  row after dedup + hot-row caching — every byte shaved is exchange
#  bandwidth back; the family's embed_lookup_rows_s gates higher-better
#  via "rows" as usual.
#  The router family (ISSUE 13, BENCH_router_r*.json) gates lower-better
#  on shed_pct (via "shed"), rolling_restart_p99_ms (via "p99"/"_ms") and
#  router_overhead_p50 (via "overhead"); scaling_qps gates higher-better
#  via "qps".
#  dispatcher_failover_s (ISSUE 16 dispatcher HA): SIGKILL→journal-replayed
#  dispatcher answering status — recovery time, lower is better.  The
#  fleet speedup keys (speedup_3v1 / parser_speedup_3v1) gate
#  higher-better via "speedup" and are stamped only on hosts with
#  cores >= workers, so a core-starved runner simply doesn't gate them.
#  The ha family (ISSUE 17, BENCH_ha_r*.json): registry_failover_s /
#  tracker_failover_s — SIGKILL→journal-replayed singleton serving its
#  control RPCs again — both gate lower-better via "failover".
#  The trace family (ISSUE 18, BENCH_trace_r*.json): three layered
#  trace_*_qps_overhead_pct keys gate lower-better via "overhead"
#  (all = span instrumentation vs untraced; sampler = buffer/decide
#  machinery at floor 1.0 vs no sampler; tail = dropping at floor 0.01
#  vs keeping everything), and trace_budget_ok (1 while the tail layer
#  stays < 1% — dropping must never cost more than keeping) gates
#  higher-better via "ok" — a budget miss reads as a 100% drop, which
#  fails the gate.
#  The c10k family (ISSUE 19, BENCH_c10k_r*.json): the connection-fabric
#  ladder gates idle_conns_held higher-better via "conns_held" (how many
#  mostly-idle connections one router process holds), and
#  mem_per_conn_kb / resident_threads lower-better — RSS per held
#  connection and the process thread count, which the reactor keeps at
#  O(loops + executor) instead of O(connections); the live-subset p99
#  keys gate lower-better via "p99" as usual.
#  The diagnose family (ISSUE 20, BENCH_diagnose_r*.json): one headline,
#  diagnose_wall_ms — a full /diagnose pass over a worst-case evidence
#  set (2048-event wide ring, 300 series x 300 points, 2k spans) —
#  gates lower-better via "_ms"; an incident diagnosis that itself
#  stalls the exporter is a regression regardless of its verdicts.
_LOWER_BETTER = ("latency", "p50", "p95", "p99", "seconds", "_ms", "ms_",
                 "wall", "overhead", "compile", "stall", "shed", "drops",
                 "errors", "misses", "padding_ratio", "truncated",
                 "epochs_to_converge", "bytes_per_row",
                 "shed_pct", "rolling_restart_p99_ms", "failover",
                 "mem_per_conn", "resident_threads")


def _direction(key: str) -> Optional[str]:
    """'up' = higher is better, 'down' = lower is better, None = no
    opinion.  Lower-better tokens win ties: 'latency_ms.p50' must read
    as latency even though 'p50' alone would too."""
    k = key.lower()
    if any(t in k for t in _LOWER_BETTER):
        return "down"
    if any(t in k for t in _HIGHER_BETTER):
        return "up"
    return None


def _flatten(doc: Any, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(_flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix] = float(doc)
    return out


def discover(directory: str, family: Optional[str] = None
             ) -> Dict[str, List[Tuple[int, str]]]:
    """family → [(round, path)] sorted ascending; partials excluded."""
    families: Dict[str, List[Tuple[int, str]]] = {}
    for name in sorted(os.listdir(directory)):
        m = _BENCH_RE.match(name)
        if m is None or m.group("partial"):
            continue
        fam = m.group("family") or "core"
        if family is not None and fam != family:
            continue
        families.setdefault(fam, []).append(
            (int(m.group("round")), os.path.join(directory, name)))
    for rounds in families.values():
        rounds.sort()
    return families


def compare(prev_path: str, new_path: str, threshold: float,
            min_abs: float) -> List[Dict[str, Any]]:
    """Regressions between two artifacts: shared numeric keys that moved
    in their bad direction past the threshold."""
    prev = _flatten(json.load(open(prev_path)))
    new = _flatten(json.load(open(new_path)))
    regressions: List[Dict[str, Any]] = []
    for key in sorted(set(prev) & set(new)):
        direction = _direction(key)
        if direction is None:
            continue
        p, n = prev[key], new[key]
        if abs(p) < min_abs or abs(n) < min_abs:
            continue
        change = (n - p) / abs(p)
        bad = change < -threshold if direction == "up" \
            else change > threshold
        if bad:
            regressions.append({"key": key, "prev": p, "new": n,
                                "change": change, "direction": direction})
    return regressions


def history_line(fam: str, rnd: int, path: str, status: str,
                 min_abs: float) -> Dict[str, Any]:
    """One ``PROGRESS.jsonl`` record: the round's direction-classified
    headline metrics (keys the gate has an opinion about — the rest is
    config echo, not trajectory).  Registry/console echoes
    (``.registry.`` / ``.router_counters.``) are excluded: they are
    runtime-dependent counters, not headline numbers."""
    flat = _flatten(json.load(open(path)))
    metrics = {k: v for k, v in sorted(flat.items())
               if _direction(k) is not None and abs(v) >= min_abs
               and ".registry." not in k and ".router_counters." not in k}
    return {"schema": "dmlc.bench.progress/1", "family": fam,
            "round": rnd, "artifact": os.path.basename(path),
            "status": status, "metrics": metrics}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the newest BENCH_*.json against the prior round")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=float(
        os.environ.get("DMLC_BENCH_THRESHOLD", "0.1")),
        help="relative move that counts as a regression (default 0.10)")
    ap.add_argument("--min-abs", type=float, default=1e-9,
                    help="ignore values smaller than this (noise floor)")
    ap.add_argument("--family", default=None,
                    help="check one family only (e.g. serving)")
    ap.add_argument("--emit-history", action="store_true",
                    help="append each gated family's headline metrics as "
                         "a JSON line to PROGRESS.jsonl")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    families = discover(args.dir, args.family)
    if not families:
        print(f"check_regression: no BENCH_*.json under {args.dir}")
        return 0
    failed = False
    history: List[Dict[str, Any]] = []
    for fam, rounds in sorted(families.items()):
        if len(rounds) < 2:
            print(f"{fam}: r{rounds[-1][0]:02d} only — baseline, pass")
            history.append(history_line(fam, rounds[-1][0], rounds[-1][1],
                                        "baseline", args.min_abs))
            continue
        (pr, prev_path), (nr, new_path) = rounds[-2], rounds[-1]
        regs = compare(prev_path, new_path, args.threshold, args.min_abs)
        if regs:
            failed = True
            print(f"{fam}: r{pr:02d} → r{nr:02d} REGRESSED "
                  f"({len(regs)} metric(s) past "
                  f"{args.threshold * 100:.0f}%):")
            for r in regs:
                arrow = "↓" if r["direction"] == "up" else "↑"
                print(f"  {arrow} {r['key']}: {r['prev']:g} → {r['new']:g} "
                      f"({r['change'] * +100:+.1f}%)")
        else:
            print(f"{fam}: r{pr:02d} → r{nr:02d} ok")
            if args.verbose:
                prev = _flatten(json.load(open(prev_path)))
                new = _flatten(json.load(open(new_path)))
                for key in sorted(set(prev) & set(new)):
                    if _direction(key) is not None and abs(prev[key]) > 0:
                        print(f"    {key}: {prev[key]:g} → {new[key]:g}")
        history.append(history_line(fam, nr, new_path,
                                    "regressed" if regs else "pass",
                                    args.min_abs))
    if args.emit_history:
        out = os.path.join(args.dir, "PROGRESS.jsonl")
        with open(out, "a", encoding="utf-8") as f:
            for line in history:
                f.write(json.dumps(line, sort_keys=True) + "\n")
        print(f"check_regression: appended {len(history)} history "
              f"line(s) to {out}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
