"""Extended benchmark suite covering BASELINE.json's config list — one JSON
line per config (the root ``bench.py`` stays the driver's single headline
number; this suite is for profiling the rest):

* ``libsvm``    — sparse text → device batches (same as bench.py)
* ``csv``       — dense HIGGS-style CSV → RowBlocks (host parse only)
* ``libfm``     — field-aware sparse (Criteo-style) → device batches
* ``recordio``  — .rec streaming: write then partitioned read MB/s
* ``stream``    — raw SeekStream read MB/s at several buffer sizes
* ``remote_ingest`` — disaggregated ingest: 2 worker subprocesses stream
                  fused wire frames to this process
* ``allreduce`` — mesh psum bus-bandwidth (GB/s) over available devices
* ``sharded``   — multi-partition libfm ingest (all parts on this host),
                  the single-host stand-in for multi-chip sharded InputSplit

Usage: ``python benchmarks/bench_suite.py [config ...]`` (default: all).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MB = 1 << 20
TARGET_MB = int(os.environ.get("DMLC_BENCH_MB", "64"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _gen_libsvm(path: str, libfm: bool = False) -> None:
    import numpy as np
    if os.path.exists(path) and os.path.getsize(path) >= TARGET_MB * MB * 0.9:
        return
    rng = np.random.default_rng(0)
    with open(path, "wb") as f:
        written = 0
        while written < TARGET_MB * MB:
            rows = []
            for i in range(10000):
                n = int(rng.integers(5, 40))
                idx = np.sort(rng.choice(1_000_000, size=n, replace=False))
                vals = rng.random(n)
                if libfm:
                    toks = b" ".join(b"%d:%d:%.4f" % (j % 40, j, v)
                                     for j, v in zip(idx.tolist(),
                                                     vals.tolist()))
                else:
                    toks = b" ".join(b"%d:%.4f" % (j, v)
                                     for j, v in zip(idx.tolist(),
                                                     vals.tolist()))
                rows.append(b"%d " % (i & 1) + toks)
            blob = b"\n".join(rows) + b"\n"
            f.write(blob)
            written += len(blob)


def _gen_csv(path: str, ncol: int = 29) -> None:
    import numpy as np
    if os.path.exists(path) and os.path.getsize(path) >= TARGET_MB * MB * 0.9:
        return
    rng = np.random.default_rng(0)
    with open(path, "wb") as f:
        written = 0
        while written < TARGET_MB * MB:
            block = rng.random((5000, ncol)).astype(np.float32)
            lines = [(b"%d," % (i & 1)) + b",".join(b"%.5f" % v for v in row)
                     for i, row in enumerate(block)]
            blob = b"\n".join(lines) + b"\n"
            f.write(blob)
            written += len(blob)


def _ingest_rate(uri: str, fmt: str, parts: int = 1) -> float:
    import bench
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader
    path = uri.split("://", 1)[-1].split("?")[0]
    size_mb = os.path.getsize(path) / MB
    # same parser discipline as the root bench: on a serial host the extra
    # parse thread only adds switches — and an un-threaded single-thread
    # parser is what lets the loader engage the fused streampack path
    cores = bench.host_cores()
    nthreads, threaded = (1, False) if cores == 1 else (cores, True)
    # batch shape: env pin > probe's persisted winner > built-in default
    # (VERDICT r4 #2 — the probe's shape is part of its speed, and the
    # suite's job is to reflect the tuned pipeline, not a worst default)
    import jax as _jax
    from dmlc_core_tpu.pipeline.tuned import load_tuned
    tuned = load_tuned(_jax.default_backend()) or {}
    batch_rows = int(os.environ.get("DMLC_BENCH_ROWS", "0")) \
        or int(tuned.get("batch_rows", 4096))
    nnz_cap = int(os.environ.get("DMLC_BENCH_NNZ", "0")) \
        or int(tuned.get("nnz_cap", 131072))
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        acc = None
        for part in range(parts):
            # env knobs (harvest_run.sh propagation) still win; otherwise
            # the loader's "auto" defaults inherit the persisted tuning
            kw = {}
            pt = int(os.environ.get("DMLC_BENCH_PUT_THREADS", "0"))
            if pt > 0:
                kw["put_threads"] = pt
            cm = os.environ.get("DMLC_BENCH_COMPACT")
            if cm is not None:
                kw["wire_compact"] = cm != "0"
            loader = DeviceLoader(
                create_parser(uri, part, parts, fmt, nthreads=nthreads,
                              threaded=threaded),
                batch_rows=batch_rows, nnz_cap=nnz_cap, prefetch=4, **kw)
            for batch in loader:
                # completion-proof accumulator (bench.consume_batch):
                # ready-futures are not completion proof on the tunnel
                # runtime; only the final value read is
                acc = bench.consume_batch(acc, batch)
            loader.close()
        bench.prove_consumed(acc)
        best = max(best, size_mb / (time.perf_counter() - t0))
    return best


def bench_libsvm() -> dict:
    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    v = _ingest_rate(f"file://{path}", "libsvm")
    return {"metric": "libsvm_ingest_to_device", "value": round(v, 1),
            "unit": "MB/s"}


def bench_ingest_cached() -> dict:
    """Packed-page epoch cache (`pipeline/page_cache.py`): one loader
    config measured three ways — cache-off baseline, epoch 1 with
    write-through, epoch ≥2 replaying mmap'd pages.  The headline value is
    the cached-epoch rate; the artifact carries the acceptance ratios
    (cached ≥ 2× uncached, write-through within 10% of baseline, pack ≤ 5%
    of cached-epoch wall)."""
    import shutil
    import tempfile

    import bench
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader
    from dmlc_core_tpu.utils.metrics import metrics

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    cores = bench.host_cores()
    nthreads, threaded = (1, False) if cores == 1 else (cores, True)
    batch_rows = int(os.environ.get("DMLC_BENCH_ROWS", "16384"))
    nnz_cap = int(os.environ.get("DMLC_BENCH_NNZ", str(512 * 1024)))

    def make_loader(cache=None):
        return DeviceLoader(
            create_parser(path, 0, 1, "libsvm", nthreads=nthreads,
                          threaded=threaded),
            batch_rows=batch_rows, nnz_cap=nnz_cap, prefetch=4,
            cache=cache)

    def epoch(loader) -> float:
        t0 = time.perf_counter()
        acc = None
        for b in loader:
            acc = bench.consume_batch(acc, b)
        bench.prove_consumed(acc)
        return time.perf_counter() - t0

    def stage_sec(name: str) -> float:
        return metrics.stage(name).total_sec

    # cache-off baseline, best of 2 epochs on one loader
    metrics.reset()
    loader = make_loader()
    base_wall = epoch(loader)
    loader.before_first()
    base_wall = min(base_wall, epoch(loader))
    loader.close()
    uncached = size_mb / base_wall

    tmp = tempfile.mkdtemp(prefix="dmlc_pagecache_")
    try:
        metrics.reset()
        loader = make_loader(cache=os.path.join(tmp, "pages"))
        wall1 = epoch(loader)                   # build (write-through)
        pack1 = stage_sec("device_loader.pack")
        write1 = stage_sec("device_loader.cache_write")
        metrics.reset()                         # per-epoch attribution
        loader.before_first()
        wall2 = epoch(loader)                   # cached replay
        pack2 = stage_sec("device_loader.pack")
        read2 = stage_sec("device_loader.cache_read")
        hits = int(metrics.counter("page_cache.hits").value)
        loader.before_first()
        wall_best = min(wall2, epoch(loader))   # best cached epoch
        loader.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    cached = size_mb / wall_best
    return {"metric": "ingest_cached", "value": round(cached, 1),
            "unit": "MB/s",
            "uncached_mbps": round(uncached, 1),
            "epoch1_mbps": round(size_mb / wall1, 1),
            "epoch2_mbps": round(size_mb / wall2, 1),
            "cached_over_uncached": round(cached / uncached, 2),
            "epoch1_over_uncached": round((size_mb / wall1) / uncached, 2),
            "pack_sec_epoch1": round(pack1, 3),
            "pack_sec_epoch2": round(pack2, 3),
            "pack_frac_epoch2": round(pack2 / wall2, 4),
            "cache_write_sec_epoch1": round(write1, 3),
            "cache_read_sec_epoch2": round(read2, 3),
            "cache_hits_epoch2": hits}


def bench_ingest_autotune() -> dict:
    """Cold-start convergence of the closed-loop autotuner (ISSUE 7):
    start from deliberately degraded defaults (parser threads 1,
    prefetch 1), let the controller hill-climb one knob per epoch, and
    report the steady-state rate it reaches plus how many epochs the
    climb took.  Acceptance: steady state within 10% of the hand-tuned
    reference measured in the same process (``ratio_vs_tuned >= 0.9``),
    and convergence well inside the epoch budget."""
    import bench
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader, autotune
    from dmlc_core_tpu.utils.metrics import metrics

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    cores = bench.host_cores()
    batch_rows = int(os.environ.get("DMLC_BENCH_ROWS", "16384"))
    nnz_cap = int(os.environ.get("DMLC_BENCH_NNZ", str(512 * 1024)))
    max_epochs = int(os.environ.get("DMLC_BENCH_AUTOTUNE_EPOCHS", "20"))

    def epoch_rate(cfg: dict) -> float:
        # same knob semantics as serve_ingest: parser_threads==1 keeps
        # the single-thread streampack fast path
        pt = int(cfg.get("parser_threads", 1))
        nthreads, threaded = (1, False) if pt <= 1 else (pt, True)
        loader = DeviceLoader(
            create_parser(path, 0, 1, "libsvm", nthreads=nthreads,
                          threaded=threaded),
            batch_rows=batch_rows, nnz_cap=nnz_cap,
            prefetch=int(cfg.get("prefetch", 2)))
        t0 = time.perf_counter()
        acc = None
        for b in loader:
            acc = bench.consume_batch(acc, b)
        loader.close()
        bench.prove_consumed(acc)
        return size_mb / (time.perf_counter() - t0)

    metrics.reset()
    metrics.gauge("slo.active_breaches").set(0)
    # hand-tuned reference: the non-degraded baselines, best of 2
    tuned_cfg = {k.name: k.value
                 for k in autotune.ingest_knob_space(cores=cores)}
    tuned_rate = max(epoch_rate(tuned_cfg), epoch_rate(tuned_cfg))
    # cold start from the worst rung; direct construction (key=None) so
    # the experiment never reads or writes the persisted winner file
    tuner = autotune.Autotuner(
        autotune.ingest_knob_space(cores=cores, degraded=True), key=None)
    cold_rate = 0.0
    epochs = 0
    for epochs in range(1, max_epochs + 1):
        cfg = tuner.begin_epoch()
        rate = epoch_rate(cfg)
        if epochs == 1:
            cold_rate = rate
        tuner.end_epoch(rate)
        if tuner.converged:
            break
    steady = epoch_rate(tuner.config())
    # steady_state_mb_s repeats the headline under a name the regression
    # gate classifies higher-better (check_regression's token list)
    return {"metric": "ingest_autotune", "value": round(steady, 1),
            "unit": "MB/s",
            "steady_state_mb_s": round(steady, 1),
            "epochs_to_converge": epochs,
            "converged": bool(tuner.converged),
            "cold_start_mbps": round(cold_rate, 1),
            "tuned_ref_mbps": round(tuned_rate, 1),
            "ratio_vs_tuned": round(steady / tuned_rate, 3),
            "best_knobs": tuner.best_config(),
            "mutations": int(metrics.counter("autotune.mutations").value),
            "accepted": int(metrics.counter("autotune.accepted").value)}


def bench_ingest_ragged() -> dict:
    """Ragged vs padded device batches at **equal batch budget**
    (ISSUE 6): the same file, the same (batch_rows, nnz_cap), once
    through the production padded path and once with ``ragged=True``
    (nnz-packed batches + ``nnz_used`` prefix words, no tail zeroing,
    never truncates).  Headline is ragged rows/s; the artifact carries
    both rates, a python-pack padded rate (same code family as the
    ragged packer — isolates the layout effect from the C++ packer),
    and the measured padding ratio (padded-nnz / true-nnz) before and
    after."""
    import bench
    from dmlc_core_tpu import native
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader
    from dmlc_core_tpu.utils.metrics import metrics

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    cores = bench.host_cores()
    nthreads, threaded = (1, False) if cores == 1 else (cores, True)
    batch_rows = int(os.environ.get("DMLC_BENCH_ROWS", "4096"))
    nnz_cap = int(os.environ.get("DMLC_BENCH_NNZ", "131072"))

    def run(ragged: bool, force_python: bool = False):
        """(rows/s best-of-2, rows, true_nnz, batches) for one config."""
        real_has_packer = native.has_packer
        if force_python:
            native.has_packer = lambda: False
        try:
            best = 0.0
            rows = true_nnz = batches = 0
            for _ in range(2):
                metrics.reset()
                loader = DeviceLoader(
                    create_parser(path, 0, 1, "libsvm",
                                  nthreads=nthreads, threaded=threaded),
                    batch_rows=batch_rows, nnz_cap=nnz_cap, prefetch=4,
                    ragged=ragged)
                t0 = time.perf_counter()
                acc = None
                for b in loader:
                    acc = bench.consume_batch(acc, b)
                bench.prove_consumed(acc)
                wall = time.perf_counter() - t0
                rows = loader.stats.rows
                true_nnz = loader.stats.true_nnz
                batches = int(
                    metrics.counter("device_loader.batches").value)
                loader.close()
                best = max(best, rows / wall)
            return best, rows, true_nnz, batches
        finally:
            native.has_packer = real_has_packer

    padded_rps, rows, _, pbatches = run(ragged=False)
    pypad_rps, _, py_nnz, pybatches = run(ragged=False,
                                          force_python=True)
    ragged_rps, rrows, r_nnz, rbatches = run(ragged=True)
    assert rrows == rows, (rrows, rows)        # ragged never drops rows
    # padded FLOP basis: every batch reduces the full nnz_cap
    pad_ratio = (pybatches * nnz_cap) / max(1, py_nnz)
    return {"metric": "ingest_ragged", "value": round(ragged_rps, 1),
            "unit": "rows/s",
            "padded_rows_per_s": round(padded_rps, 1),
            "python_padded_rows_per_s": round(pypad_rps, 1),
            "ragged_rows_per_s": round(ragged_rps, 1),
            "ragged_over_python_padded": round(
                ragged_rps / max(pypad_rps, 1e-9), 2),
            "padding_ratio_padded": round(pad_ratio, 2),
            "padding_ratio_ragged": 1.0,
            "rows": rows,
            "true_nnz": r_nnz,
            "batches_padded": pbatches,
            "batches_ragged": rbatches}


def bench_libfm() -> dict:
    path = "/tmp/bench_suite.libfm"
    _gen_libsvm(path, libfm=True)
    v = _ingest_rate(f"file://{path}", "libfm")
    return {"metric": "libfm_ingest_to_device", "value": round(v, 1),
            "unit": "MB/s"}


def bench_sharded() -> dict:
    """All 4 partitions ingested on this host — single-host stand-in for the
    multi-chip sharded InputSplit config."""
    path = "/tmp/bench_suite.libfm"
    _gen_libsvm(path, libfm=True)
    v = _ingest_rate(f"file://{path}", "libfm", parts=4)
    return {"metric": "libfm_sharded4_ingest", "value": round(v, 1),
            "unit": "MB/s"}


def bench_fm_train() -> dict:
    """Full-framework training throughput: libsvm text → parse → pack →
    h2d → jitted FM train step (grad + adam), one chip.  The reference has
    no training path — this is the net-new end-to-end number proving the
    ingest feed keeps a compute consumer busy (ingest overlaps the step:
    batch N+1 transfers while step N runs)."""
    import jax
    import optax
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import FactorizationMachine, make_train_step
    from dmlc_core_tpu.pipeline import DeviceLoader

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    model = FactorizationMachine(num_features=1 << 20, dim=32)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(model, opt)
    kstep = int(os.environ.get("DMLC_TRAIN_KSTEP", "16"))
    fused_state = {"trainer": None}
    ckpt_every = 8
    saves_done = 0

    def run_epochs(n_runs: int, ckpt_mode: str = "off",
                   max_steps: int = 0):
        """ckpt_mode: 'off' | 'sync' | 'async' — mid-train checkpointing
        every ``ckpt_every`` steps, quantifying what save_async buys over
        a blocking save at the same cadence.  ``max_steps`` > 0 bounds an
        epoch (the ckpt-mode passes use it: checkpointing at tunnel
        completion rates ran ~1700 rows/s in the r4 rehearsal, so
        full-corpus ckpt epochs alone ate ~23 min and blew the 1500 s
        per-config timeout — a step-capped pass measures the same
        sync-vs-async delta in bounded time)."""
        nonlocal params, opt_state, saves_done
        import shutil
        import tempfile

        from dmlc_core_tpu.models import FusedTrainer
        from dmlc_core_tpu.utils import CheckpointManager
        best_rows = best_mb = best_feed = 0.0
        loss = None
        # the headline ('off') pass uses the k-step fused dispatch like
        # _train_rate; the ckpt passes keep the per-step loop (they measure
        # the per-step save-cadence delta, not throughput)
        use_fused = ckpt_mode == "off" and kstep > 1
        for _ in range(n_runs):
            ckdir = (tempfile.mkdtemp(prefix="bench_ck")
                     if ckpt_mode != "off" else None)
            mgr = CheckpointManager(ckdir) if ckdir else None
            loader = DeviceLoader(
                create_parser(f"file://{path}", 0, 1, "libsvm"),
                batch_rows=4096, nnz_cap=131072, prefetch=4, id_mod=1 << 20,
                emit="host" if use_fused else "device")
            try:
                rows = 0
                nstep = 0
                t0 = time.perf_counter()
                if use_fused:
                    tr = fused_state["trainer"]
                    if tr is None:
                        tr = FusedTrainer(model, opt, loader, k=kstep,
                                          params=params,
                                          opt_state=opt_state)
                        fused_state["trainer"] = tr
                    else:
                        tr.loader = loader
                    for item in loader:
                        tr.feed(item)
                        rows += loader.batch_rows
                    tr.flush()
                    dt_submit = time.perf_counter() - t0
                    params, opt_state, loss = (tr.params, tr.opt_state,
                                               tr.losses[-1])
                    float(loss)
                    dt = time.perf_counter() - t0
                    best_rows = max(best_rows, rows / dt)
                    best_feed = max(best_feed, rows / dt_submit)
                    best_mb = max(best_mb, size_mb / dt)
                    continue
                for batch in loader:
                    params, opt_state, loss = step(params, opt_state, batch)
                    rows += int(batch["labels"].shape[0])
                    nstep += 1
                    if mgr is not None and nstep % ckpt_every == 0:
                        state = {"params": params, "opt_state": opt_state}
                        if ckpt_mode == "sync":
                            mgr.save(nstep, state)
                        else:
                            mgr.save_async(nstep, state)
                        saves_done += 1
                    if max_steps and nstep >= max_steps:
                        break
                dt_submit = time.perf_counter() - t0
                if mgr is not None:
                    mgr.wait()
                # value read-back (see _train_rate): ready-futures are not
                # completion proof on the tunnel runtime
                float(loss)
                dt = time.perf_counter() - t0
            finally:
                loader.close()
                if ckdir:
                    shutil.rmtree(ckdir, ignore_errors=True)
            best_rows = max(best_rows, rows / dt)
            best_feed = max(best_feed, rows / dt_submit)
            best_mb = max(best_mb, size_mb / dt)
        return best_rows, best_mb, best_feed, loss

    import bench
    best_rows, best_mb, best_feed, loss = run_epochs(3, "off")
    # best-of-2 per mode, STEP-CAPPED (32 steps = 131k rows, 4 saves at
    # ckpt_every=8): a single noisy epoch would swamp the sync-vs-async
    # delta, and uncapped ckpt epochs at tunnel completion rates blow the
    # per-config timeout (r4 rehearsal).  best_mb is only meaningful from
    # the uncapped pass — capped passes report rows-based rates only.
    # 36, not 32: a cap that lands ON a save boundary gives the last
    # async save zero steps to overlap with (25% of saves paying full
    # blocking cost would attenuate the very delta this measures); four
    # post-save steps keep the tail overlapped like the uncapped epoch
    sync_rows, _, _, _ = run_epochs(2, "sync", max_steps=36)
    async_rows, _, _, _ = run_epochs(2, "async", max_steps=36)
    r = {"metric": "fm_train_stream", "value": round(best_rows, 0),
         "unit": "rows/s", "text_mbps": round(best_mb, 1),
         "feed_rows_s": round(best_feed, 0),
         "kstep": kstep if kstep > 1 else 1,
         "final_loss": round(float(loss), 4),
         "ckpt_sync_rows_s": round(sync_rows, 0),
         "ckpt_async_rows_s": round(async_rows, 0),
         "ckpt_saves": saves_done, "ckpt_every": ckpt_every,
         "ckpt_host_cores": bench.host_cores()}
    if saves_done == 0:
        # tiny corpus (< ckpt_every steps/run): the comparison measured
        # nothing — say so instead of implying zero-cost checkpointing
        r["ckpt_note"] = "corpus too small: no checkpoint fired"
    elif bench.host_cores() == 1:
        # honest caveat: with no spare core the background writer steals
        # cycles from parse/train, so async can LOSE to sync here — its
        # overlap win needs a host core to absorb the writer
        r["ckpt_note"] = ("1-core host: async writer contends with the "
                          "train/parse thread; overlap benefit requires "
                          "spare host cores")
    return r


def _step_flops(model, opt, batch_rows: int = 4096,
                nnz_cap: int = 131072) -> float:
    """XLA's own FLOP estimate for one train step (grad + adam) on a
    representative flat batch — the denominator for model-level MFU
    (VERDICT r4 weak #7: single-chip MFU evidence was microbench-only).
    Returns 0.0 when cost analysis is unavailable."""
    import jax
    import jax.numpy as jnp

    from dmlc_core_tpu.models import make_train_step
    try:
        params = model.init(jax.random.PRNGKey(0))
        opt_state = opt.init(params)
        batch = {
            "ids": jnp.zeros(nnz_cap, jnp.int32),
            "vals": jnp.zeros(nnz_cap, jnp.float32),
            "segments": jnp.full(nnz_cap, batch_rows, jnp.int32),
            "row_ptr": jnp.zeros(batch_rows + 1, jnp.int32),
            "labels": jnp.zeros(batch_rows, jnp.float32),
            "weights": jnp.ones(batch_rows, jnp.float32),
        }
        step = make_train_step(model, opt, donate=False)
        cost = step.lower(params, opt_state, batch).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0))
    except Exception as e:  # noqa: BLE001 — MFU is telemetry, not a gate
        log(f"cost analysis unavailable: {type(e).__name__}: {e}")
        return 0.0


# v5e bf16 peak per chip; f32-heavy models run well below it by design —
# the MFU column is context for the MXU-dominated configs (dcn/deepfm)
_BF16_PEAK_TFLOPS = 394.0


def _mfu_fields(model, opt, rows_s: float, batch_rows: int = 4096) -> dict:
    f = _step_flops(model, opt, batch_rows=batch_rows)
    if not f or not rows_s:
        return {}
    tflops_s = f * (rows_s / batch_rows) / 1e12
    return {"step_gflops": round(f / 1e9, 2),
            "tflops_s": round(tflops_s, 4),
            "mfu_vs_bf16_peak": round(tflops_s / _BF16_PEAK_TFLOPS, 5)}


def _train_rate(model, path: str, fmt: str, *, fields: bool = False,
                id_mod: int = 1 << 20, runs: int = 2):
    """Best-of-``runs`` epoch throughput of text → parse → pack → h2d →
    jitted train step for any model in the family (shared by the
    deepfm/dcn/ffm configs; fm_train keeps its own loop for the checkpoint
    comparison it also measures).

    Default path is the k-step fused dispatch (``DMLC_TRAIN_KSTEP``,
    default 16): k batches ship as one stacked put and run as one scanned
    dispatch, so the tunnel's 68 ms per-dispatch RTT amortizes ×k — the
    fix for r4's 2.4× completion-vs-feed gap.  ``DMLC_TRAIN_KSTEP=1``
    restores the per-step loop.  The fields=True (ffm) config has no fused
    wire region for field ids and always runs per-step."""
    import jax
    import optax
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import FusedTrainer, make_train_step
    from dmlc_core_tpu.pipeline import DeviceLoader

    kstep = int(os.environ.get("DMLC_TRAIN_KSTEP", "16"))
    use_fused = kstep > 1 and not fields
    kstep_used = kstep if use_fused else 1
    size_mb = os.path.getsize(path) / MB
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = None if use_fused else make_train_step(model, opt)
    trainer = None
    best_rows = best_mb = best_feed = 0.0
    loss = None
    for _ in range(runs):
        loader = DeviceLoader(
            create_parser(f"file://{path}", 0, 1, fmt),
            batch_rows=4096, nnz_cap=131072, prefetch=4, id_mod=id_mod,
            fields=fields, emit="host" if use_fused else "device")
        try:
            rows = 0
            t0 = time.perf_counter()
            if use_fused:
                if trainer is None:
                    trainer = FusedTrainer(model, opt, loader, k=kstep,
                                           params=params,
                                           opt_state=opt_state)
                else:
                    trainer.loader = loader  # keep the jit cache warm
                for item in loader:
                    trainer.feed(item)
                    rows += loader.batch_rows
                trainer.flush()
                dt_submit = time.perf_counter() - t0
                loss = trainer.losses[-1]
            else:
                for batch in loader:
                    params, opt_state, loss = step(params, opt_state, batch)
                    rows += int(batch["labels"].shape[0])
                dt_submit = time.perf_counter() - t0
            # two rates from one epoch: loop exit = last step SUBMITTED
            # (host feed ceiling), loss read-back = last step COMPLETE.
            # block_until_ready is not completion proof on the tunnel
            # runtime (see tpu_micro.sync_value: 38x matmul over-report;
            # deepfm read 573k rows/s submitted vs 72k completed through
            # the collapsed 03:5x link), so the headline is the value-read
            # completion rate and the feed rate is recorded beside it.
            float(loss)
            dt = time.perf_counter() - t0
        finally:
            loader.close()
        best_rows = max(best_rows, rows / dt)
        best_feed = max(best_feed, rows / dt_submit)
        best_mb = max(best_mb, size_mb / dt)
    return best_rows, best_mb, best_feed, float(loss), kstep_used


def bench_deepfm_train() -> dict:
    """DeepFM end-to-end training stream (VERDICT r3 #3: at least one
    FFM/DeepFM step must complete on TPU): same feed as fm_train plus the
    dense tower — the config whose step actually exercises the MXU."""
    from dmlc_core_tpu.models.deep import DeepFM

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    rows_s, mbps, feed_s, loss, kstep_used = _train_rate(
        DeepFM(num_features=1 << 20, dim=32, layers=2), path, "libsvm")
    import optax as _optax
    r = {"metric": "deepfm_train_stream", "value": round(rows_s, 0),
         "unit": "rows/s",
         "kstep": kstep_used, "text_mbps": round(mbps, 1),
         "feed_rows_s": round(feed_s, 0), "final_loss": round(loss, 4)}
    r.update(_mfu_fields(DeepFM(num_features=1 << 20, dim=32, layers=2),
                         _optax.adam(1e-3), rows_s))
    return r


def bench_dcn_train() -> dict:
    """DCNv2 end-to-end training stream: one sparse gather then L dense
    [D,D] cross matmuls per step — the family member whose per-step work
    is almost entirely MXU."""
    from dmlc_core_tpu.models.dcn import DCNv2

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    rows_s, mbps, feed_s, loss, kstep_used = _train_rate(
        DCNv2(num_features=1 << 20, dim=32, layers=3), path, "libsvm")
    import optax as _optax
    r = {"metric": "dcn_train_stream", "value": round(rows_s, 0),
         "unit": "rows/s",
         "kstep": kstep_used, "text_mbps": round(mbps, 1),
         "feed_rows_s": round(feed_s, 0), "final_loss": round(loss, 4)}
    r.update(_mfu_fields(DCNv2(num_features=1 << 20, dim=32, layers=3),
                         _optax.adam(1e-3), rows_s))
    return r


def bench_ffm_train() -> dict:
    """FieldAwareFM training stream over libfm data with the per-value
    field ids shipped to the device (fields=True path — the libfm third
    coordinate finally consumed on chip, VERDICT r3 #3)."""
    from dmlc_core_tpu.models.ffm import FieldAwareFM

    path = "/tmp/bench_suite.libfm"
    _gen_libsvm(path, libfm=True)
    # id_mod bounds the [F, nf, d] factor table (+ its two adam moments)
    # to ~0.5 GB on chip; the generator's fields are j % 40
    rows_s, mbps, feed_s, loss, kstep_used = _train_rate(
        FieldAwareFM(num_features=1 << 18, num_fields=40, dim=4),
        path, "libfm", fields=True, id_mod=1 << 18)
    return {"metric": "ffm_train_stream", "value": round(rows_s, 0),
            "unit": "rows/s",
            "kstep": kstep_used, "text_mbps": round(mbps, 1),
            "feed_rows_s": round(feed_s, 0), "final_loss": round(loss, 4)}


def bench_a1a_train() -> dict:
    """a1a-shaped real-data config (VERDICT r4 #4; zero-egress image, so
    the corpus is a documented distribution-matched generator —
    benchmarks/realdata.py): tiny Adult-style one-hot rows through the
    full train path, reporting HELD-OUT accuracy/AUC beside the rate
    (the eval split is generated with a different sample seed over the
    same fixed ground-truth weights, mirroring the real a1a/a1a.t train/
    test pair)."""
    import jax
    import optax
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.models import (FactorizationMachine, evaluate_stream,
                                      make_train_step)
    from dmlc_core_tpu.pipeline import DeviceLoader
    from benchmarks.realdata import gen_a1a

    path = "/tmp/bench_a1a.libsvm"
    test_path = "/tmp/bench_a1a_test.libsvm"
    gen_a1a(path)
    gen_a1a(test_path, rows=800, seed=11)
    model = FactorizationMachine(num_features=124, dim=8)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(5e-2)
    opt_state = opt.init(params)
    step = make_train_step(model, opt)
    t0 = time.perf_counter()
    rows = 0
    loss = None
    for _ in range(5):                       # tiny corpus: 5 epochs
        loader = DeviceLoader(create_parser(f"file://{path}", 0, 1,
                                            "libsvm"),
                              batch_rows=256, nnz_cap=8192)
        try:
            for batch in loader:
                params, opt_state, loss = step(params, opt_state, batch)
                rows += int(batch["labels"].shape[0])
        finally:
            loader.close()
    float(loss)                              # value read-back = completion
    dt = time.perf_counter() - t0
    loader = DeviceLoader(create_parser(f"file://{test_path}", 0, 1,
                                        "libsvm"),
                          batch_rows=256, nnz_cap=8192)
    try:
        ev = evaluate_stream(model, params, loader)
    finally:
        loader.close()
    return {"metric": "a1a_train_stream", "value": round(rows / dt, 0),
            "unit": "rows/s", "data": "a1a-shaped",
            "heldout_accuracy": round(ev["accuracy"], 4),
            "heldout_auc": round(ev.get("auc", 0.0), 4)}


def bench_higgs_csv() -> dict:
    """HIGGS-shaped dense CSV parse (VERDICT r4 #4): 28 physics columns at
    full float precision through the native chunk parser — the dense-parse
    benchmark the reference runs on the real HIGGS file."""
    from benchmarks.realdata import gen_higgs_csv

    path = "/tmp/bench_higgs.csv"
    gen_higgs_csv(path, target_mb=TARGET_MB)
    size_mb = os.path.getsize(path) / MB
    from dmlc_core_tpu.data import create_parser
    best = 0.0
    rows = 0
    for _ in range(3):
        p = create_parser(f"file://{path}?format=csv&label_column=0", 0, 1,
                          "csv")
        t0 = time.perf_counter()
        rows = sum(c.get_block().size for c in p)
        dt = time.perf_counter() - t0
        p.close()
        best = max(best, size_mb / dt)
    return {"metric": "higgs_csv_parse", "value": round(best, 1),
            "unit": "MB/s", "data": "HIGGS-shaped", "rows": rows}


def _wire_v4_projection(path: str, fmt: str, batch_rows: int = 4096) -> dict:
    """Measure what delta-coded ids (the rejected wire v4) WOULD save on
    this corpus, from the parsed CSR itself (no wire implementation
    needed for a keep/reject decision).

    v3 ships every id at ``w = bits(max_id_in_batch)``.  The v4 proposal:
    per row, first id absolute at w bits, subsequent ids as (delta-1) at
    ``d = bits(max_within_row_delta_in_batch)`` — batch-global widths,
    like v3 (`NOTES_r04.md` item 3 rejected this on uniform ids because a
    single max-gap row drags d up to ~w; field-clustered data is the case
    it was deferred to)."""
    import numpy as np

    from dmlc_core_tpu.data import create_parser

    id_bits_v3 = id_bits_v4 = 0
    total_nnz = total_first = 0
    batches = 0
    p = create_parser(f"file://{path}", 0, 1, fmt)
    try:
        ids_acc, off_acc = [], [0]
        for c in p:
            blk = c.get_block()
            lo = int(blk.offsets[0])
            ids_acc.append(np.asarray(blk.indices, np.int64)[
                lo:int(blk.offsets[-1])])
            off_acc.extend((np.asarray(blk.offsets, np.int64)[1:]
                            - lo + off_acc[-1]).tolist())
            while len(off_acc) - 1 >= batch_rows:
                cut = off_acc[batch_rows]
                flat = np.concatenate(ids_acc)
                batch_ids, rest = flat[:cut], flat[cut:]
                rp = np.array(off_acc[:batch_rows + 1], np.int64)
                off_acc = [0] + [o - cut for o in off_acc[batch_rows + 1:]]
                ids_acc = [rest]
                nnz = len(batch_ids)
                if nnz == 0:
                    continue
                w = max(1, int(np.max(batch_ids)).bit_length())
                deltas = np.diff(batch_ids)
                # row-first positions are absolute, not deltas
                firsts = rp[:-1][np.diff(rp) > 0]
                mask = np.ones(max(nnz - 1, 0), bool)
                mask[firsts[firsts > 0] - 1] = False
                d = max(1, int(np.max(deltas[mask] - 1)).bit_length()) \
                    if mask.any() else 1
                n_first = len(firsts)
                id_bits_v3 += nnz * w
                id_bits_v4 += n_first * w + (nnz - n_first) * d
                total_nnz += nnz
                total_first += n_first
                batches += 1
    finally:
        p.close()
    ratio = id_bits_v4 / max(id_bits_v3, 1)
    return {"batches": batches, "nnz": total_nnz,
            "v3_id_bits_per_value": round(id_bits_v3 / max(total_nnz, 1), 2),
            "v4_id_bits_per_value": round(id_bits_v4 / max(total_nnz, 1), 2),
            "v4_over_v3_id_bytes": round(ratio, 3)}


def bench_criteo_ingest() -> dict:
    """Criteo-shaped field-clustered libfm ingest (VERDICT r4 #4) + the
    wire-v4 delta-coding re-evaluation on the id distribution it was
    deferred to.  The verdict rides in the artifact: adopt only if the
    projected id-region saving moves TOTAL wire bytes by >10% (ids are
    roughly half the compact wire; values/row_ptr/labels are untouched by
    v4)."""
    from benchmarks.realdata import gen_criteo_libfm

    path = "/tmp/bench_criteo.libfm"
    gen_criteo_libfm(path, target_mb=TARGET_MB)
    v = _ingest_rate(f"file://{path}", "libfm")
    proj = _wire_v4_projection(path, "libfm")
    uniform = "/tmp/bench_suite.libfm"
    _gen_libsvm(uniform, libfm=True)
    proj_uniform = _wire_v4_projection(uniform, "libfm")
    # id region ≈ half the wire → total saving ≈ (1 - ratio) / 2
    total_saving = (1.0 - proj["v4_over_v3_id_bytes"]) / 2.0
    verdict = "adopt" if total_saving > 0.10 else "reject"
    return {"metric": "criteo_libfm_ingest", "value": round(v, 1),
            "unit": "MB/s", "data": "criteo-shaped",
            "wire_v4": {**proj, "uniform_corpus_ratio":
                        proj_uniform["v4_over_v3_id_bytes"],
                        "projected_total_wire_saving":
                            round(total_saving, 3),
                        "verdict": verdict}}


def bench_integrity() -> dict:
    """Bit-exact end-to-end data integrity through the DEVICE path: the
    03:14 window proved the tunnel runtime's ready-futures lie about
    timing — this config proves they do not lie about BYTES.  Host-side
    parsed blocks and on-device decoded batches are checksummed with
    wrapping-int32 sums over the exact bit patterns (bitcast f32→i32;
    order- and padding-immune: pad ids/vals/labels/weights are all 0),
    through the stress transfer config (fused native parse→pack, compact
    v3 bit-pack + dict encode, 4-thread put pool, jit decode).  A single
    flipped bit anywhere in that chain fails the compare."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader

    M32 = 0xFFFFFFFF

    def wsum(a) -> int:                  # wrapping 32-bit reference sum
        return int(np.sum(np.asarray(a).astype(np.int64)) & M32)

    bits = np.float32(1.0).view(np.int32)          # weights default

    host_cache: dict = {}

    def host_sums(path: str, fmt: str, want_fields: bool) -> dict:
        """Host-side reference checksums, cached per (path, fmt, fields):
        the flat and rowmajor sub-checks over the same corpus share one
        parse pass instead of re-checksumming ~64 MB each."""
        ck = (path, fmt, want_fields)
        if ck in host_cache:
            return host_cache[ck]
        keys = ("ids", "vals", "labels", "weights") + (
            ("fields",) if want_fields else ())
        host = dict.fromkeys(keys + ("nnz", "rows"), 0)
        p = create_parser(f"file://{path}", 0, 1, fmt)
        try:
            for c in p:
                blk = c.get_block()
                # slice the CSR payload via offsets, exactly like
                # pack_flat does: a view-backed block must not leak
                # out-of-block elements into the host checksum — that
                # would be a false corruption alarm, not a detection
                lo, hi = int(blk.offsets[0]), int(blk.offsets[-1])
                host["ids"] = (host["ids"]
                               + wsum(blk.indices[lo:hi])) & M32
                host["vals"] = (host["vals"] + wsum(
                    blk.values[lo:hi].view(np.int32))) & M32
                host["labels"] = (host["labels"]
                                  + wsum(blk.labels.view(np.int32))) & M32
                w = (blk.weights.view(np.int32) if blk.weights is not None
                     else np.full(blk.size, bits, np.int32))
                host["weights"] = (host["weights"] + wsum(w)) & M32
                if want_fields:
                    host["fields"] = (host["fields"]
                                      + wsum(blk.fields[lo:hi])) & M32
                host["nnz"] += hi - lo
                host["rows"] += blk.size
        finally:
            p.close()
        host_cache[ck] = host
        return host

    def check_one(path: str, fmt: str, want_fields: bool,
                  layout: str = "flat") -> dict:
        keys = ("ids", "vals", "labels", "weights") + (
            ("fields",) if want_fields else ())
        host = host_sums(path, fmt, want_fields)

        @jax.jit
        def batch_sums(b):
            i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
            out = [jnp.sum(b["ids"]), jnp.sum(i32(b["vals"])),
                   jnp.sum(i32(b["labels"])), jnp.sum(i32(b["weights"]))]
            if want_fields:
                out.append(jnp.sum(b["fields"]))
            if "row_ptr" in b:
                out.append(b["row_ptr"][-1])
            elif "segments" in b:
                # per-array path ships segments, not row_ptr; padding
                # entries point at the scratch row (== batch_rows)
                out.append(jnp.sum(
                    (b["segments"] < b["labels"].shape[0])
                    .astype(jnp.int32)))
            else:
                # rowmajor [B, K]: no per-value row structure on device,
                # so nnz is not device-derivable — this sentinel is
                # dropped by zip(keys, s) and nnz is EXCLUDED from the
                # mismatch compare for this layout (nnz_keys below); the
                # reported nnz is the host-side count
                out.append(jnp.int32(-1))
            return tuple(out)

        dev = dict.fromkeys(keys + ("nnz",), 0)
        # nnz_cap (= K per row in rowmajor) sized so no row is truncated
        # anywhere: host ref has no truncation
        nnz_cap = 64 if layout == "rowmajor" else 262144
        loader = DeviceLoader(create_parser(f"file://{path}", 0, 1, fmt),
                              batch_rows=4096, nnz_cap=nnz_cap, prefetch=4,
                              put_threads=4, wire_compact=not want_fields,
                              fields=want_fields, layout=layout)
        try:
            for b in loader:
                s = [int(np.asarray(x)) for x in batch_sums(b)]
                for k, v in zip(keys, s):
                    dev[k] = (dev[k] + (v & M32)) & M32
                dev["nnz"] += s[-1]
            rows = loader.stats.rows
        finally:
            loader.close()

        nnz_keys = () if layout == "rowmajor" else ("nnz",)
        mismatch = {k: {"host": host[k], "device": dev[k]}
                    for k in keys + nnz_keys if host[k] != dev[k]}
        if rows != host["rows"]:
            mismatch["rows"] = {"host": host["rows"], "device": rows}
        out = {"ok": not mismatch, "rows": host["rows"],
               "nnz": host["nnz"]}
        if mismatch:
            out["mismatch"] = mismatch
        return out

    libsvm = "/tmp/bench_suite.libsvm"
    libfm = "/tmp/bench_suite.libfm"
    _gen_libsvm(libsvm)
    _gen_libsvm(libfm, libfm=True)
    # three sub-checks cover every transfer path a consumer can
    # configure: fused compact wire (libsvm flat), per-array fields path
    # (libfm, fields=True — field arrays bypass the fused wire by
    # design), and the rowmajor [B, K] layout the embedding-bag engines
    # consume (nnz not device-derivable there; value sums still exact)
    res = {"libsvm_compact": check_one(libsvm, "libsvm", False),
           "libfm_fields": check_one(libfm, "libfm", True),
           "libsvm_rowmajor": check_one(libsvm, "libsvm", False,
                                        layout="rowmajor")}
    ok = all(v["ok"] for v in res.values())
    return {"metric": "ingest_integrity", "value": 1.0 if ok else 0.0,
            "unit": "ok", "paths": res}


def bench_cache_build() -> dict:
    """Disk-cache build + replay throughput — the reference's
    ``disk_row_iter.h:117-140`` self-report ("MB/sec per 64MB page",
    BASELINE.md instrumentation table), the one baseline hook the suite
    did not yet reproduce.  Build: one parse of the libsvm corpus into
    cache pages; replay: epochs off the cache through the prefetch
    thread, best-of-2 (page deserialization + ThreadedIter, no parsing).
    Pure host/disk path — never touches a device."""
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.data.iterators import DiskRowIter

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    cache = "/tmp/bench_suite.cache"
    for sfx in ("", ".meta"):
        try:
            os.remove(cache + sfx)
        except OSError:
            pass
    t0 = time.perf_counter()
    it = DiskRowIter(create_parser(f"file://{path}", 0, 1, "libsvm"), cache)
    build_mbps = size_mb / (time.perf_counter() - t0)
    best_dt = float("inf")
    rows = 0
    try:
        for _ in range(2):
            it.before_first()
            rows = 0
            t0 = time.perf_counter()
            for blk in it:
                rows += blk.size
            best_dt = min(best_dt, time.perf_counter() - t0)
    finally:
        it.close()
    cache_mb = os.path.getsize(cache) / MB
    # two replay normalizations, both labeled: source-equivalent answers
    # "how much faster than re-parsing the text" (same denominator as the
    # build rate), cache-bytes is comparable to stream_read/recordio raw
    # IO rates
    return {"metric": "cache_build_replay", "value": round(build_mbps, 1),
            "unit": "MB/s",
            "replay_src_equiv_mbps": round(size_mb / best_dt, 1),
            "replay_cache_mbps": round(cache_mb / best_dt, 1),
            "rows": rows, "cache_mb": round(cache_mb, 1)}


def bench_csv() -> dict:
    path = "/tmp/bench_suite.csv"
    _gen_csv(path)
    from dmlc_core_tpu.data import create_parser
    size_mb = os.path.getsize(path) / MB
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        p = create_parser(f"file://{path}?label_column=0", 0, 1, "csv")
        for _blk in p:
            pass
        p.close()
        best = max(best, size_mb / (time.perf_counter() - t0))
    return {"metric": "csv_parse_rowblocks", "value": round(best, 1),
            "unit": "MB/s"}


def bench_recordio() -> dict:
    """.rec streaming: write records, then partitioned read (reference
    recordio_test.cc + split_read_test.cc instrumentation)."""
    import numpy as np
    from dmlc_core_tpu.io import RecordIOWriter, create_input_split
    path = "/tmp/bench_suite.rec"
    rng = np.random.default_rng(0)
    if not (os.path.exists(path)
            and os.path.getsize(path) >= TARGET_MB * MB * 0.9):
        with open(path, "wb") as f:
            w = RecordIOWriter(f)
            written = 0
            while written < TARGET_MB * MB:
                rec = rng.integers(0, 256, size=int(rng.integers(
                    1 << 10, 64 << 10)), dtype=np.uint8).tobytes()
                w.write_record(rec)
                written += len(rec)
    size_mb = os.path.getsize(path) / MB
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        total = 0
        for part in range(2):
            sp = create_input_split(f"file://{path}", part, 2, "recordio",
                                    threaded=True)
            while True:
                rec = sp.next_record()
                if rec is None:
                    break
                total += len(rec)
            sp.close()
        best = max(best, (total / MB) / (time.perf_counter() - t0))
    return {"metric": "recordio_partitioned_read", "value": round(best, 1),
            "unit": "MB/s"}


def _remote_ingest_rate(nworkers: int, attempts: int = 3) -> float:
    """Spawn ``nworkers`` ingest worker subprocesses (one partition each)
    and measure MB/s into device batches at the trainer, whose own parse
    stays idle — the tf.data-service shape."""
    import socket
    import subprocess
    import sys as _sys
    import bench
    from dmlc_core_tpu.pipeline import RemoteIngestLoader

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    ports = []
    for _ in range(nworkers):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    workers = [subprocess.Popen(
        [_sys.executable, "-m", "dmlc_core_tpu.pipeline.ingest_service",
         f"file://{path}", str(i), str(nworkers), "libsvm", str(port),
         "batch_rows=4096", "nnz_cap=131072"],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for i, port in enumerate(ports)]
    try:
        # wait for the workers' listeners before timing anything
        deadline = time.monotonic() + 120
        for port in ports:
            while True:
                try:
                    socket.create_connection(("127.0.0.1", port),
                                             timeout=2).close()
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"ingest worker :{port} never came up")
                    time.sleep(0.5)
        best = 0.0
        for attempt in range(attempts):
            loader = RemoteIngestLoader(
                [("127.0.0.1", p) for p in ports], batch_rows=4096,
                connect_timeout=120.0)
            acc = None
            t0 = time.perf_counter()
            for b in loader:
                acc = bench.consume_batch(acc, b)
            bench.prove_consumed(acc)
            dt = time.perf_counter() - t0
            loader.close()
            best = max(best, size_mb / dt)
        return best
    finally:
        for w in workers:
            w.kill()


def bench_remote_ingest() -> dict:
    """Disaggregated ingest at the r2/r3 artifact shape (2 workers).  NOT
    in the default run order — ingest_scale's workers_2 point measures the
    same configuration; this stays invocable by name for artifact
    continuity."""
    best = _remote_ingest_rate(2)
    return {"metric": "remote_ingest_2workers", "value": round(best, 1),
            "unit": "MB/s"}


def bench_ingest_scale() -> dict:
    """Worker-count scaling curve (VERDICT r3 #5): local parse vs N ingest
    workers feeding a parse-idle trainer, N = 1/2/4.  On a multi-core host
    2+ workers must beat 1 worker AND local; on a 1-core host every
    configuration time-slices the same core, so the curve records the
    disaggregation overhead, not the scaling — stamped via host_cores."""
    import bench
    cores = bench.host_cores()
    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    curve = {"local": round(_ingest_rate(f"file://{path}", "libsvm"), 1)}
    for n in (1, 2, 4):
        curve[f"workers_{n}"] = round(_remote_ingest_rate(n, attempts=2), 1)
    r = {"metric": "ingest_worker_scaling", "value": curve["workers_2"],
         "unit": "MB/s", "curve": curve, "host_cores": cores}
    if cores == 1:
        r["note"] = ("1-core host: trainer and all workers share one core; "
                     "curve measures disaggregation overhead, not scaling")
    return r


def _merge_child_telemetry(tag: str, states=None, trace_files=()) -> None:
    """Fold child-process telemetry into parent artifacts when
    ``--telemetry-out`` is live: ``<prefix>_<tag>.fleet_metrics.json``
    (``merge_states`` over the rank-tagged registry states) and
    ``<prefix>_<tag>.fleet_trace.json`` (child Chrome traceEvents
    concatenated into one Perfetto-openable timeline).  Never raises —
    telemetry must not fail a bench."""
    prefix = os.environ.get("DMLC_TELEMETRY_OUT")
    if not prefix:
        return
    try:
        from dmlc_core_tpu import telemetry
        if states:
            path = f"{prefix}_{tag}.fleet_metrics.json"
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"ranks": sorted(states),
                           "merged": telemetry.merge_states(states)},
                          f, indent=2, sort_keys=True, default=str)
            os.replace(tmp, path)
        events = []
        for p in trace_files:
            try:
                with open(p, "r", encoding="utf-8") as f:
                    events.extend(json.load(f).get("traceEvents", []))
            except (OSError, ValueError):
                continue  # child died before its dump — merge the rest
        if events:
            path = f"{prefix}_{tag}.fleet_trace.json"
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                          f)
            os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001 — telemetry never fails a run
        log(f"fleet telemetry merge failed: {e}")


def _fleet_ingest_rate(nworkers: int, num_parts: int = 6,
                       attempts: int = 2, batch_rows: int = 4096) -> float:
    """One dispatcher + ``nworkers`` data-service worker subprocesses
    pulling shard leases for a shared dataset; measure aggregate MB/s of
    fused host frames arriving at a single ``DataServiceLoader``
    consumer.  Differs from ``_remote_ingest_rate`` in the control
    plane: parts are leased dynamically (any worker can serve any
    shard), not statically assigned one-per-worker."""
    import subprocess
    import sys as _sys
    from dmlc_core_tpu.pipeline.data_service import (DataServiceLoader,
                                                     Dispatcher)

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    # generous TTL/heartbeat: a loaded 1-core host must not trip the
    # chaos machinery (a re-grant mid-bench would double-serve bytes and
    # corrupt the MB/s number via dup-frame discards)
    disp = Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=120.0)
    disp.start()
    workers = [subprocess.Popen(
        [_sys.executable, "-m", "dmlc_core_tpu.pipeline.data_service.worker",
         f"127.0.0.1:{disp.port}"],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for _ in range(nworkers)]
    try:
        deadline = time.monotonic() + 120
        while len(disp.workers_alive()) < nworkers:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"only {len(disp.workers_alive())}/{nworkers} "
                    f"data-service workers registered")
            time.sleep(0.25)
        spec = {"uri": f"file://{path}", "fmt": "libsvm",
                "num_parts": num_parts, "batch_rows": batch_rows,
                "nnz_cap": 131072}
        best = 0.0
        for _ in range(attempts):
            loader = DataServiceLoader((disp.host, disp.port), spec,
                                       connect_timeout=120.0, emit="host")
            frames = 0
            t0 = time.perf_counter()
            for _kind, buf, _meta, _rows in loader:
                frames += 1
                loader.recycle(buf)
            dt = time.perf_counter() - t0
            loader.close()
            if frames == 0:
                raise RuntimeError("fleet epoch delivered no frames")
            best = max(best, size_mb / dt)
        return best
    finally:
        if os.environ.get("DMLC_TELEMETRY_OUT"):
            # grab the heartbeat-pushed registry states BEFORE teardown,
            # then SIGTERM (not SIGKILL) so each worker's exit hook dumps
            # its own metrics/trace pair for the fleet merge
            try:
                states = disp.worker_states()
            except Exception:  # noqa: BLE001 — telemetry never fails a run
                states = {}
            for w in workers:
                w.terminate()
            for w in workers:
                try:
                    w.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    w.kill()
            prefix = os.environ["DMLC_TELEMETRY_OUT"]
            # the exit-dump state sidecars are authoritative: complete
            # final states, present even when the run ended before any
            # heartbeat push reached the dispatcher.  Counting a worker
            # via BOTH its sidecar and its heartbeat state would double
            # its counters in the merge, so sidecars replace wholesale.
            sidecars = {}
            for w in workers:
                try:
                    p = f"{prefix}.dsworker.{w.pid}.state.json"
                    with open(p, "r", encoding="utf-8") as f:
                        sidecars[f"pid{w.pid}"] = json.load(f)
                except (OSError, ValueError):
                    continue
            _merge_child_telemetry(
                f"ingest_fleet.{nworkers}w", states=sidecars or states,
                trace_files=[f"{prefix}.dsworker.{w.pid}.trace.json"
                             for w in workers])
        else:
            for w in workers:
                w.kill()
        disp.stop()


def _fleet_failover_s(num_parts: int = 6) -> float:
    """Dispatcher HA drill: run the dispatcher as a *subprocess* with a
    journal, SIGKILL it after the consumer has taken its first frames,
    restart it on the same port + journal, and measure kill→recovered
    (new process answering a ``status`` RPC with the epoch's state
    replayed).  The consumer keeps iterating across the outage — its
    control-plane retries ride over the dead window — so the epoch also
    completing (frames > 0 after the kill) is part of the drill, not a
    separate test."""
    import shutil
    import subprocess
    import sys as _sys
    import tempfile
    from dmlc_core_tpu.pipeline.data_service import DataServiceLoader
    from dmlc_core_tpu.pipeline.data_service.dispatcher import dispatcher_rpc

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    tmp = tempfile.mkdtemp(prefix="dmlc_failover_")
    journal = os.path.join(tmp, "dispatch")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           # fast re-registration beats: the drill's clock includes the
           # worker noticing the new dispatcher
           "DMLC_DATA_HEARTBEAT_TIMEOUT": "3"}

    def _spawn_dispatcher(port: int) -> Tuple[subprocess.Popen, int]:
        proc = subprocess.Popen(
            [_sys.executable, "-m",
             "dmlc_core_tpu.pipeline.data_service.dispatcher",
             f"port={port}", f"journal={journal}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        line = proc.stdout.readline()
        return proc, int(json.loads(line)["port"])

    disp, port = _spawn_dispatcher(0)
    worker = subprocess.Popen(
        [_sys.executable, "-m", "dmlc_core_tpu.pipeline.data_service.worker",
         f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # the consumer must out-retry the dead window: the default policy
    # gives up in ~seconds and the breaker would stop redialing the
    # (innocent) worker while its completions bounce off a dead control
    # plane
    chaos_env = {"DMLC_DATA_CLIENT_RETRIES": "40",
                 "DMLC_DATA_CLIENT_BREAKER_THRESHOLD": "1000",
                 "DMLC_DS_CTRL_RETRIES": "40"}
    saved = {k: os.environ.get(k) for k in chaos_env}
    os.environ.update(chaos_env)
    try:
        # the worker's interpreter start-up is seconds on a loaded host;
        # the consumer's first start_epoch must not race it to the
        # registry
        deadline = time.monotonic() + 120
        while True:
            try:
                if dispatcher_rpc(("127.0.0.1", port),
                                  {"cmd": "list_workers"},
                                  timeout=2.0)["workers"]:
                    break
            except (OSError, ValueError, KeyError):
                pass
            if time.monotonic() > deadline:
                raise RuntimeError("data-service worker never registered "
                                   "for the failover drill")
            time.sleep(0.25)
        spec = {"uri": f"file://{path}", "fmt": "libsvm",
                "num_parts": num_parts, "batch_rows": 4096,
                "nnz_cap": 131072}
        loader = DataServiceLoader(("127.0.0.1", port), spec,
                                   connect_timeout=120.0, emit="host")
        it = iter(loader)
        frames = 0
        for _kind, buf, _meta, _rows in it:
            frames += 1
            loader.recycle(buf)
            if frames >= 2:
                break  # mid-epoch: leases granted, parts outstanding
        disp.kill()
        disp.wait()
        t0 = time.perf_counter()
        disp, port2 = _spawn_dispatcher(port)
        deadline = time.monotonic() + 120
        while True:
            try:
                st = dispatcher_rpc(("127.0.0.1", port2),
                                    {"cmd": "status", "key": loader.key},
                                    timeout=2.0)
                if int(st.get("epoch", 0)) >= 1:
                    break  # journal replayed: the epoch survived the crash
            except (OSError, ValueError, KeyError):
                pass
            if time.monotonic() > deadline:
                raise RuntimeError("restarted dispatcher never recovered")
            time.sleep(0.05)
        failover = time.perf_counter() - t0
        for _kind, buf, _meta, _rows in it:
            frames += 1
            loader.recycle(buf)
        loader.close()
        if frames <= 2:
            raise RuntimeError("epoch did not resume after failover")
        return failover
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        worker.kill()
        disp.kill()
        worker.wait()
        disp.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_ingest_fleet() -> dict:
    """Data-service fleet scaling + HA: dispatcher + N leased workers
    feeding one consumer, N = 1/2/3, plus a SIGKILL failover drill
    against a journaled dispatcher subprocess.

    On a multi-core host 3 workers should deliver ≥ 1.6× the 1-worker
    aggregate MB/s; on a host with fewer cores than workers every
    process time-slices the same core, so the curve records the
    lease/control-plane overhead, not fleet scaling — in that case the
    ``speedup_3v1`` keys are OMITTED (not stamped at ~1.0), so the
    regression gate never judges scaling a core-starved host cannot
    exhibit (host_cores records why).  The parser-bound variant shrinks
    ``batch_rows`` 8× so per-batch parse/framing overhead dominates the
    wire — the regime where extra workers pay off first."""
    import bench
    cores = bench.host_cores()
    curve = {}
    for n in (1, 2, 3):
        curve[f"workers_{n}"] = round(_fleet_ingest_rate(n), 1)
    parser = {}
    for n in (1, 3):
        parser[f"workers_{n}"] = round(
            _fleet_ingest_rate(n, batch_rows=512), 1)
    r = {"metric": "ingest_fleet_mb_s", "value": curve["workers_3"],
         "unit": "MB/s", "curve": curve, "curve_parser_bound": parser,
         "dispatcher_failover_s": round(_fleet_failover_s(), 3),
         "host_cores": cores}
    if cores >= 3:
        r["speedup_3v1"] = round(curve["workers_3"]
                                 / max(1e-9, curve["workers_1"]), 2)
        r["parser_speedup_3v1"] = round(parser["workers_3"]
                                        / max(1e-9, parser["workers_1"]), 2)
    else:
        r["note"] = (f"{cores}-core host: dispatcher, consumer and all "
                     "workers share the core(s); curve measures "
                     "data-service overhead, not fleet scaling — "
                     "speedup keys omitted")
    return r


def _colocated_rate(mode: str, epochs: int = 1) -> Tuple[float, dict]:
    """One dispatcher + ONE worker subprocess on this host, one consumer;
    measure MB/s of the LAST epoch under a transport mode:

    * ``tcp``    — lanes disabled (`DMLC_TRANSPORT_LANE=0`), the seed's
      per-connection TCP path;
    * ``uds``    — default negotiation: colocated consumer dials the
      worker's UNIX lane, payload still streamed;
    * ``fdpass`` — UNIX lane + a page-cache-backed shard: epoch 1 builds
      the cache, epoch 2 ships one SCM_RIGHTS descriptor per shard.
    """
    import subprocess
    import sys as _sys
    from dmlc_core_tpu.pipeline.data_service import (DataServiceLoader,
                                                     Dispatcher)
    from dmlc_core_tpu.utils.metrics import metrics as _metrics

    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    overrides = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    if mode == "tcp":
        overrides["DMLC_TRANSPORT_LANE"] = "0"
    spec = {"uri": f"file://{path}", "fmt": "libsvm", "num_parts": 1,
            "batch_rows": 4096, "nnz_cap": 131072}
    if mode == "fdpass":
        spec["cache"] = f"/tmp/bench_colocated_{os.getpid()}.pages"
        epochs = max(2, epochs)
    old_env = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    disp = Dispatcher(lease_ttl_s=600.0, heartbeat_timeout_s=120.0)
    disp.start()
    worker = subprocess.Popen(
        [_sys.executable, "-m",
         "dmlc_core_tpu.pipeline.data_service.worker",
         f"127.0.0.1:{disp.port}"],
        env={**os.environ, **overrides},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    extras = {}
    try:
        deadline = time.monotonic() + 120
        while len(disp.workers_alive()) < 1:
            if time.monotonic() > deadline:
                raise RuntimeError("colocated worker never registered")
            time.sleep(0.25)
        z0 = _metrics.counter("transport.bytes_zero_copy").value
        u0 = _metrics.counter("transport.lane.uds").value
        rate = 0.0
        loader = DataServiceLoader((disp.host, disp.port), spec,
                                   connect_timeout=120.0, emit="host")
        try:
            for _ in range(epochs):
                frames = 0
                t0 = time.perf_counter()
                for _kind, buf, _meta, _rows in loader:
                    frames += 1
                    loader.recycle(buf)
                dt = time.perf_counter() - t0
                if frames == 0:
                    raise RuntimeError("colocated epoch had no frames")
                rate = size_mb / dt
        finally:
            loader.close()
        extras["uds_dials"] = int(
            _metrics.counter("transport.lane.uds").value - u0)
        extras["zero_copy_bytes"] = int(
            _metrics.counter("transport.bytes_zero_copy").value - z0)
        return rate, extras
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        worker.kill()
        disp.stop()
        if mode == "fdpass":
            for suffix in ("", ".meta.json"):
                try:
                    os.remove(spec["cache"] + suffix)
                except OSError:
                    pass


def bench_ingest_colocated() -> dict:
    """Transport-lane comparison (ISSUE 15): same host, same dataset, one
    worker feeding one consumer over (a) per-connection TCP, (b) the
    negotiated UNIX-domain lane, (c) the lane with SCM_RIGHTS fd-passing
    of the packed-page cache.  The lane must not lose to TCP; fd-passing
    removes the payload bytes from the wire entirely."""
    import bench
    tcp, _ = _colocated_rate("tcp")
    uds, uex = _colocated_rate("uds")
    fdp, fex = _colocated_rate("fdpass")
    return {"metric": "ingest_colocated_uds_mb_s", "value": round(uds, 1),
            "unit": "MB/s",
            "tcp_mb_s": round(tcp, 1), "uds_mb_s": round(uds, 1),
            "fdpass_mb_s": round(fdp, 1),
            "uds_vs_tcp_speedup": round(uds / max(tcp, 1e-9), 2),
            "fdpass_vs_tcp_speedup": round(fdp / max(tcp, 1e-9), 2),
            "uds_dials": uex["uds_dials"],
            "fdpass_zero_copy_bytes": fex["zero_copy_bytes"],
            "host_cores": bench.host_cores()}


def bench_stream() -> dict:
    """Raw SeekStream read throughput at several buffer sizes (reference
    `test/stream_read_test.cc:16-43` instrumentation) — isolates the L3
    byte-pump from parse/pack so a regression there is attributable."""
    from dmlc_core_tpu.io import open_seek_stream_for_read
    path = "/tmp/bench_suite.libsvm"
    _gen_libsvm(path)
    size_mb = os.path.getsize(path) / MB
    out = {}
    for buf_kb in (4, 64, 1024):
        best = 0.0
        for _ in range(3):
            s = open_seek_stream_for_read(f"file://{path}")
            t0 = time.perf_counter()
            while s.read(buf_kb << 10):
                pass
            best = max(best, size_mb / (time.perf_counter() - t0))
            s.close()
        out[f"buf{buf_kb}k_mbps"] = round(best, 1)
    return {"metric": "stream_read", "unit": "MB/s",
            "value": out["buf1024k_mbps"], **out}


def bench_allreduce() -> dict:
    """psum bus-bandwidth over all available devices (ICI on a pod; this
    host's devices otherwise). Bus BW = 2*(n-1)/n * bytes / time.

    Single-chip interpretation (defined per VERDICT r1 #7): with one
    device there is no inter-chip traffic to measure, so the config
    reports on-device copy bandwidth (d2d) instead — the upper bound any
    1-chip collective could move — and labels itself accordingly."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from dmlc_core_tpu.utils.jax_compat import shard_map
    devs = jax.devices()
    n = len(devs)
    elems = (TARGET_MB * MB) // 4
    if n == 1:
        # feedback chain + value read-back, RTT-corrected: 5 identical
        # copy(x) dispatches behind block_until_ready read 6661 GB/s on a
        # v5e (~0.8 TB/s HBM) in the 03:20 window — dedupe + early-resolving
        # ready-futures, the same two holes tpu_micro.timed_fb closes
        x = jnp.ones((elems,), jnp.float32)
        bump = jax.jit(lambda v: v + 1.0)     # full HBM read + write
        y = bump(x)
        float(y[0])                            # compile + land

        def rtt() -> float:
            t0 = time.perf_counter()
            float(y[0])
            return time.perf_counter() - t0

        rtt_s = min(rtt() for _ in range(3))
        reps = 256
        t0 = time.perf_counter()
        for _ in range(reps):
            y = bump(y)
        float(y[0])
        t = time.perf_counter() - t0
        dt = max(t - rtt_s, 0.05 * t, 1e-9)
        bw = reps * 2 * elems * 4 / dt / (1 << 30)
        return {"metric": "allreduce_singleton_d2d_bw", "value": round(bw, 2),
                "unit": "GB/s", "devices": 1, "reps": reps,
                "rtt_ms": round(rtt_s * 1e3, 1),
                "note": "1 device: no ICI traffic; reporting on-device "
                        "copy bandwidth as the collective upper bound"}
    mesh = Mesh(np.array(devs), ("dp",))
    x = jnp.ones((elems,), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(None)))

    @jax.jit
    def psum_all(v):
        # the +1.0 rides INSIDE the jitted program (fused by XLA, no
        # extra eager HBM pass) and keeps every dispatch's operand
        # distinct so the runtime cannot dedupe repeats
        return shard_map(lambda t: jax.lax.psum(t, "dp") + 1.0, mesh=mesh,
                         in_specs=P(None), out_specs=P(None),
                         check_vma=False)(v)

    ys = psum_all(xs)                         # compile
    float(ys[0])

    def rtt() -> float:
        t0 = time.perf_counter()
        float(ys[0])
        return time.perf_counter() - t0

    rtt_s = min(rtt() for _ in range(3))
    reps = 16
    t0 = time.perf_counter()
    for _ in range(reps):
        ys = psum_all(ys)
    float(ys[0])                              # completion proof
    t = time.perf_counter() - t0
    dt = max(t - rtt_s, 0.05 * t, 1e-9)       # same floor as the n==1 branch
    bus = reps * (2 * (n - 1) / max(n, 1)) * (elems * 4) / dt / (1 << 30)
    return {"metric": "allreduce_bus_bw", "value": round(bus, 2),
            "unit": "GB/s", "devices": n, "reps": reps,
            "rtt_ms": round(rtt_s * 1e3, 1)}


def bench_allreduce_mesh8() -> dict:
    """8-way virtual-mesh psum wall time (VERDICT r2 weak#5): fixed-size
    collective on the forced-host 8-device mesh, so round-over-round
    movement of the collective path is visible even with one real chip.
    Runs in a subprocess — the virtual-device flag is process-global."""
    import subprocess
    code = (
        "import jax\n"
        # env JAX_PLATFORMS is overridden by the axon register hook, so the
        # CPU pin must be config-level (same trick as bench.force_cpu)
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax._src import xla_bridge\n"
        "reg = getattr(xla_bridge, '_backend_factories', None)\n"
        "isinstance(reg, dict) and reg.pop('axon', None)\n"
        "import time, numpy as np, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dmlc_core_tpu.utils.jax_compat import shard_map\n"
        "devs = jax.devices(); n = len(devs)\n"
        "mesh = Mesh(np.array(devs), ('dp',))\n"
        "x = jax.device_put(jnp.ones((4 << 20,), jnp.float32),\n"
        "                   NamedSharding(mesh, P('dp')))\n"
        "f = jax.jit(shard_map(lambda t: jax.lax.psum(t, 'dp'), mesh=mesh,\n"
        "            in_specs=P('dp'), out_specs=P('dp'), check_vma=False))\n"
        "f(x).block_until_ready()\n"
        "best = 1e9\n"
        "for _ in range(5):\n"
        "    t0 = time.perf_counter(); f(x).block_until_ready()\n"
        "    best = min(best, time.perf_counter() - t0)\n"
        "print('RESULT', n, best)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=600, env=env)
    if out.returncode != 0:
        raise RuntimeError(f"mesh8 child rc={out.returncode}: "
                           f"{out.stderr[-500:]}")
    line = next((ln for ln in out.stdout.splitlines()
                 if ln.startswith("RESULT")), None)
    if line is None:
        raise RuntimeError(f"mesh8 child produced no RESULT; stderr: "
                           f"{out.stderr[-500:]}")
    _, n, sec = line.split()
    return {"metric": "allreduce_mesh8_psum_wall", "value": round(
        float(sec) * 1e3, 2), "unit": "ms", "devices": int(n),
        "note": "16MiB psum on the 8-device virtual host mesh"}


def bench_sp_mesh8() -> dict:
    """Sequence-parallel attention wall time on the 8-device virtual mesh:
    ring (ppermute + online softmax) vs Ulysses (all-to-all) on the same
    sharded QKV — the long-context analog of allreduce_mesh8, so the sp
    layer's round-over-round movement is visible with one real chip."""
    import subprocess
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax._src import xla_bridge\n"
        "reg = getattr(xla_bridge, '_backend_factories', None)\n"
        "isinstance(reg, dict) and reg.pop('axon', None)\n"
        "import time, numpy as np, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dmlc_core_tpu.ops.ring_attention import make_ring_attention\n"
        "from dmlc_core_tpu.ops.ulysses import make_ulysses_attention\n"
        "devs = jax.devices(); n = len(devs)\n"
        "mesh = Mesh(np.array(devs), ('sp',))\n"
        "B, H, S, D = 1, 8, 2048, 64\n"
        "rng = np.random.default_rng(0)\n"
        "sh = NamedSharding(mesh, P(None, None, 'sp', None))\n"
        "qkv = [jax.device_put(rng.standard_normal((B, H, S, D),\n"
        "       dtype=np.float32), sh) for _ in range(3)]\n"
        "out = {}\n"
        "for name, mk in (('ring', make_ring_attention),\n"
        "                 ('ulysses', make_ulysses_attention)):\n"
        "    f = mk(mesh, 'sp', causal=True)\n"
        "    f(*qkv)[0].block_until_ready()\n"
        "    best = 1e9\n"
        "    for _ in range(5):\n"
        "        t0 = time.perf_counter(); f(*qkv).block_until_ready()\n"
        "        best = min(best, time.perf_counter() - t0)\n"
        "    out[name] = best\n"
        "print('RESULT', n, out['ring'], out['ulysses'])\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=900, env=env)
    if out.returncode != 0:
        raise RuntimeError(f"sp_mesh8 child rc={out.returncode}: "
                           f"{out.stderr[-500:]}")
    line = next((ln for ln in out.stdout.splitlines()
                 if ln.startswith("RESULT")), None)
    if line is None:
        raise RuntimeError(f"sp_mesh8 child produced no RESULT; stderr: "
                           f"{out.stderr[-500:]}")
    _, n, ring_s, uly_s = line.split()
    return {"metric": "sp_mesh8_attention_wall",
            "value": round(float(ring_s) * 1e3, 2), "unit": "ms",
            "ulysses_ms": round(float(uly_s) * 1e3, 2), "devices": int(n),
            "note": "B1 H8 S2048 D64 causal attention, seq sharded 8-way"}


_RESHARD_CHILD = r"""
import sys, time
import numpy as np
from dmlc_core_tpu.parallel import RabitContext
from dmlc_core_tpu.parallel.reshard import snapshot_tree, redistribute
from dmlc_core_tpu.utils.checkpoint import CheckpointManager

uri, port, jobid, tmp, mode = sys.argv[1:6]
ctx = RabitContext(uri, int(port), jobid=jobid)
mgr = CheckpointManager(tmp)
world = ctx.world_size
if mode == "reshard":
    snap = None
    if ctx.rank != world - 1:            # rank world-1 plays the reborn
        _, state = mgr.restore(step=0)
        snap = snapshot_tree(state)
    ctx.allreduce(np.zeros(1))           # align: measure the protocol,
    t0 = time.perf_counter()             # not rank start skew
    restored, st = redistribute(ctx, snap, generation=0)
    wall = time.perf_counter() - t0
    assert restored
    print("WALL %d %.6f %d %d %d" % (ctx.rank, wall, st.bytes_moved,
                                     st.leaves_from_peers,
                                     st.leaves_from_checkpoint), flush=True)
else:                                    # the old path: full reload
    ctx.allreduce(np.zeros(1))
    t0 = time.perf_counter()
    _, state = mgr.restore(step=0)
    for a in state.values():
        a[0, 0]                          # fault in, apples-to-apples
    wall = time.perf_counter() - t0
    print("WALL %d %.6f 0 0 0" % (ctx.rank, wall), flush=True)
ctx.shutdown()
import os
_prefix = os.environ.get("DMLC_TELEMETRY_OUT")
if _prefix:                              # --telemetry-out parity: each
    import json                          # rank leaves a metrics/trace
    from dmlc_core_tpu import telemetry  # pair + mergeable state for the
    from dmlc_core_tpu.utils.metrics import metrics  # parent fleet merge
    _p = "%s.reshard.%s.%s" % (_prefix, mode, jobid)
    telemetry.dump_artifacts(_p)
    with open(_p + ".state.json", "w") as f:
        json.dump(metrics.state(), f, default=str)
"""


def bench_elastic_reshard() -> dict:
    """Checkpoint-free recovery cost (ISSUE 9): wall time for the elastic
    resharder to hand a reborn rank the full state live from survivors,
    against the old path — every rank of the restarted cohort reloading
    the full checkpoint from disk (the restore stampede).  3 real worker
    PROCESSES over the tracker + loopback sockets (threads would share
    one GIL and throttle both sides of the transfer); state is replicated
    (the elastic-averaging layout of examples/elastic_train.py), the
    last rank plays the reborn non-holder.  Cost = the slowest rank's
    wall, barrier-aligned inside each child."""
    import subprocess
    import tempfile

    import numpy as np

    from dmlc_core_tpu.parallel import RabitTracker
    from dmlc_core_tpu.utils.checkpoint import CheckpointManager

    world = 3
    # default 4x the suite's data target: recovery cost only matters once
    # the state is big enough that a full-cohort reload visibly stalls
    # training, and fixed protocol costs (tracker rounds, ownership
    # broadcast, final allreduce) would dominate a tiny transfer
    state_mb = int(os.environ.get("DMLC_BENCH_RESHARD_MB",
                                  str(4 * TARGET_MB)))
    nleaves, cols = 8, 256
    rows = max(1, (state_mb * MB) // (4 * cols * nleaves))
    rng = np.random.default_rng(7)
    state = {f"layer{i}": rng.random((rows, cols), dtype=np.float32)
             for i in range(nleaves)}
    nbytes = sum(a.nbytes for a in state.values())

    def cohort(tmp: str, mode: str, extra_env=None):
        tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
        tracker.start()
        envd = tracker.worker_envs()
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   **(extra_env or {}))
        procs = [subprocess.Popen(
            [sys.executable, "-c", _RESHARD_CHILD,
             envd["DMLC_TRACKER_URI"], str(envd["DMLC_TRACKER_PORT"]),
             f"b{i}", tmp, mode],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for i in range(world)]
        walls, reborn = {}, (0, 0, 0)
        for p in procs:
            out, err = p.communicate(timeout=180)
            if p.returncode != 0:
                raise RuntimeError(f"reshard child rc={p.returncode}: "
                                   f"{err[-500:]}")
            for ln in out.splitlines():
                if ln.startswith("WALL "):
                    _, r, w, b, fp, fc = ln.split()
                    walls[int(r)] = float(w)
                    if int(fp) or int(b):
                        reborn = (int(b), int(fp), int(fc))
        return max(walls.values()), reborn

    try:
        with tempfile.TemporaryDirectory(prefix="bench_reshard_") as tmp:
            CheckpointManager(tmp).save(0, state)
            reload_wall, _ = cohort(tmp, "reload")
            reshard_wall, (bytes_moved, from_peers, from_ckpt) = cohort(
                tmp, "reshard")
            # schedule comparison (ISSUE 15): the same recovery with the
            # round planner disabled — one unbounded blast of fetches,
            # the seed's behavior — against the planned default above
            oneshot_wall, _ = cohort(
                tmp, "reshard",
                extra_env={"DMLC_RESHARD_PER_HOLDER": "0",
                           "DMLC_RESHARD_MAX_BYTES": str(1 << 40)})
    finally:
        # --telemetry-out parity: fold whatever rank dumps made it to
        # disk (even from a cohort that died mid-run) into one merged
        # snapshot + Chrome trace for the whole bench
        prefix = os.environ.get("DMLC_TELEMETRY_OUT")
        if prefix:
            states = {}
            for mode in ("reload", "reshard"):
                for i in range(world):
                    p = f"{prefix}.reshard.{mode}.b{i}.state.json"
                    try:
                        with open(p, "r", encoding="utf-8") as f:
                            states[f"{mode}.b{i}"] = json.load(f)
                    except (OSError, ValueError):
                        continue
            _merge_child_telemetry(
                "elastic_reshard", states=states,
                trace_files=[f"{prefix}.reshard.{mode}.b{i}.trace.json"
                             for mode in ("reload", "reshard")
                             for i in range(world)])

    return {"metric": "reshard_wall_s", "value": round(reshard_wall, 4),
            "unit": "s", "state_mb": round(nbytes / MB, 1), "world": world,
            "leaves": nleaves,
            "ckpt_reload_wall_s": round(reload_wall, 4),
            "reshard_vs_reload_speedup": round(reload_wall
                                               / max(reshard_wall, 1e-9), 2),
            "oneshot_wall_s": round(oneshot_wall, 4),
            "planned_vs_oneshot_speedup": round(
                oneshot_wall / max(reshard_wall, 1e-9), 2),
            "bytes_moved": int(bytes_moved),
            "leaves_from_peers": int(from_peers),
            "leaves_from_checkpoint": int(from_ckpt)}


_EMBED_CHILD = r"""
import sys, time
import numpy as np
from dmlc_core_tpu.parallel import RabitContext
from dmlc_core_tpu.embed import ShardedEmbeddingTable
from dmlc_core_tpu.utils.metrics import metrics

uri, port, jobid, rows_s, dim_s, steps_s, brows_s = sys.argv[1:8]
num_rows, dim = int(rows_s), int(dim_s)
steps, batch_rows = int(steps_s), int(brows_s)
ctx = RabitContext(uri, int(port), jobid=jobid)
rank, world = ctx.rank, ctx.world_size
t = ShardedEmbeddingTable(num_rows, dim, rank=rank, world=world,
                          replicas=1, seed=3, serve=True)
t.sync_addresses(ctx)
nnz = batch_rows * 16
rng = np.random.default_rng(100 + rank)
batches = []
for _ in range(steps):
    ids = rng.integers(0, num_rows, nnz)
    # half the traffic keys a hot 1% of rows: dedup + the hot-row cache
    # have real work, like production id distributions
    ids[: nnz // 2] = rng.integers(0, max(1, num_rows // 100), nnz // 2)
    batches.append({
        "ids": ids.astype(np.int64),
        "vals": rng.random(nnz).astype(np.float32),
        "segments": np.sort(rng.integers(0, batch_rows, nnz)).astype(
            np.int32),
        "labels": np.zeros(batch_rows, np.float32),
        "weights": np.ones(batch_rows, np.float32),
        "nnz_used": np.int32(nnz), "rows_used": np.int32(batch_rows)})
g = np.ones((batch_rows, dim), np.float32)
t.lookup(batches[0]); t.backward(batches[0], g)     # compile outside
ctx.allreduce(np.zeros(1))                          # align cohort start
t0 = time.perf_counter()
for b in batches:
    t.backward(b, g * 0 + t.lookup(b) * 0 + 1)      # lookup feeds grad
t.flush(ctx)
wall = time.perf_counter() - t0
snap = t.build_snapshot()                           # None over budget
print("EMB %d %.6f %d %d %d %d %d" % (
    rank, wall, steps * batch_rows,
    metrics.counter("embed.exchange_bytes").value,
    metrics.counter("embed.cache_hits").value,
    t.resident_bytes, 0 if snap is None else 1), flush=True)
ctx.allreduce(np.zeros(1))                          # all reads done
t.close()
ctx.shutdown()
"""


def bench_embed_shard() -> dict:
    """Sharded embedding lookup/update throughput (ISSUE 12): a 3-rank
    cohort cooperatively trains ONE table whose total bytes exceed a
    single rank's ``DMLC_RESHARD_MAX_BYTES`` snapshot budget — no rank
    could hold (or even snapshot) the whole table, which is the point of
    the subsystem.  Each rank streams skewed ragged batches through
    lookup (dedup → cache → fan-out exchange) + backward, then one
    collective flush.  Headline is cohort looked-up rows/s; the paired
    lower-better metric is wire bytes per looked-up row (what dedup and
    the hot-row cache exist to shrink)."""
    import subprocess

    from dmlc_core_tpu.parallel import RabitTracker

    world, dim = 3, 64
    table_mb = int(os.environ.get("DMLC_BENCH_EMBED_MB", str(TARGET_MB)))
    num_rows = (table_mb * MB) // (4 * dim)
    total_bytes = num_rows * dim * 4
    # budget below the full table, above one rank's 2/3 resident share:
    # every rank CAN snapshot what it holds, none could hold it all
    budget = int(total_bytes * 0.85)
    steps, batch_rows = 24, 256

    tracker = RabitTracker(num_workers=world, host_ip="127.0.0.1")
    tracker.start()
    envd = tracker.worker_envs()
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DMLC_RESHARD_MAX_BYTES=str(budget))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _EMBED_CHILD,
         envd["DMLC_TRACKER_URI"], str(envd["DMLC_TRACKER_PORT"]),
         f"em{i}", str(num_rows), str(dim), str(steps), str(batch_rows)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(world)]
    walls, resident, exch, hits, snap_ok = {}, {}, 0, 0, True
    rows_done = 0
    for p in procs:
        out, err = p.communicate(timeout=300)
        if p.returncode != 0:
            raise RuntimeError(f"embed child rc={p.returncode}: "
                               f"{err[-500:]}")
        for ln in out.splitlines():
            if ln.startswith("EMB "):
                _, r, w, rows, xb, ch, res, ok = ln.split()
                walls[int(r)] = float(w)
                rows_done += int(rows)
                exch += int(xb)
                hits += int(ch)
                resident[int(r)] = int(res)
                snap_ok = snap_ok and bool(int(ok))
    tracker.join(timeout=30)
    wall = max(walls.values())
    if max(resident.values()) >= total_bytes:
        raise RuntimeError("embed bench invariant broken: a rank resides "
                           "the full table")
    return {"metric": "embed_lookup_rows_s",
            "value": round(rows_done / wall, 1), "unit": "rows/s",
            "world": world, "table_mb": round(total_bytes / MB, 1),
            "num_rows": int(num_rows), "dim": dim,
            "snapshot_budget_mb": round(budget / MB, 1),
            "per_rank_resident_mb": round(max(resident.values()) / MB, 1),
            "resident_frac_of_table": round(
                max(resident.values()) / total_bytes, 3),
            "per_rank_snapshot_fits": bool(snap_ok),
            "exchange_bytes_per_row": round(exch / max(rows_done, 1), 1),
            "cache_hits": int(hits),
            "batches": steps, "batch_rows": batch_rows}


# Run order = dict order.  The virtual-mesh configs (subprocess CPU runs,
# no tunnel involved) come before the long device-bound train loop: a
# wedged tunnel grant mid-fm_train (observed r03: >1h stall inside one
# RPC) must not cost the configs that never needed the chip.
# Order = priority under a short-lived grant: the tunnel can vanish
# mid-suite (observed r04: grant lost between the 4th and 5th config), so
# the two headline TPU configs run FIRST and the host-only configs (which
# never touch the tunnel) run last.  DMLC_SUITE_PRIORITY reorders at run
# time (see main) without forking this registry.
#
# Each entry registers (config fn, headline metric).  Error/skip rows must
# carry the SAME metric key as the success path, or harvest_commit's
# cross-window merge can't pair them: a measured libfm_ingest_to_device
# from window 1 would sit beside a spurious "libfm" error row from window 2
# forever (observed r04).  allreduce's registered key is its 1-device
# metric — the only case reachable in harvest (the tunnel exposes one chip;
# a plain host exposes one cpu device); a manual multi-device run emits
# allreduce_bus_bw, a deliberately distinct key.
ALL = {
    "libsvm": (bench_libsvm, "libsvm_ingest_to_device"),
    "ingest_cached": (bench_ingest_cached, "ingest_cached"),
    "ingest_autotune": (bench_ingest_autotune, "ingest_autotune"),
    "ingest_ragged": (bench_ingest_ragged, "ingest_ragged"),
    "fm_train": (bench_fm_train, "fm_train_stream"),
    "deepfm_train": (bench_deepfm_train, "deepfm_train_stream"),
    "ffm_train": (bench_ffm_train, "ffm_train_stream"),
    "dcn_train": (bench_dcn_train, "dcn_train_stream"),
    "integrity": (bench_integrity, "ingest_integrity"),
    "a1a": (bench_a1a_train, "a1a_train_stream"),
    "criteo": (bench_criteo_ingest, "criteo_libfm_ingest"),
    "higgs": (bench_higgs_csv, "higgs_csv_parse"),
    "libfm": (bench_libfm, "libfm_ingest_to_device"),
    "sharded": (bench_sharded, "libfm_sharded4_ingest"),
    "allreduce": (bench_allreduce, "allreduce_singleton_d2d_bw"),
    "remote_ingest": (bench_remote_ingest, "remote_ingest_2workers"),
    "ingest_scale": (bench_ingest_scale, "ingest_worker_scaling"),
    "ingest_fleet": (bench_ingest_fleet, "ingest_fleet_mb_s"),
    "ingest_colocated": (bench_ingest_colocated,
                         "ingest_colocated_uds_mb_s"),
    "csv": (bench_csv, "csv_parse_rowblocks"),
    "cache": (bench_cache_build, "cache_build_replay"),
    "recordio": (bench_recordio, "recordio_partitioned_read"),
    "stream": (bench_stream, "stream_read"),
    "allreduce_mesh8": (bench_allreduce_mesh8, "allreduce_mesh8_psum_wall"),
    "sp_mesh8": (bench_sp_mesh8, "sp_mesh8_attention_wall"),
    "elastic_reshard": (bench_elastic_reshard, "reshard_wall_s"),
    "embed_shard": (bench_embed_shard, "embed_lookup_rows_s"),
}


# Configs that run on the forced-host 8-device virtual mesh (their own
# subprocesses, CPU-pinned) and never touch the tunnel.  Their platform is
# stamped "cpu_mesh8" so a by-design virtual-mesh number is never mistaken
# for an ingest config that silently fell back to CPU (VERDICT r2 weak#2).
CPU_MESH = {"allreduce_mesh8", "sp_mesh8"}
# Raw host IO / parse-only configs: no device work at all, so they skip
# backend init entirely (stamped "host").  csv + recordio moved here in r04:
# they were stamped "tpu" only because jax had initialised with the grant,
# and that init is exactly where a lost grant wedges a child for its whole
# timeout (observed 23:39 r04: recordio hung in axon client init).
#  ingest_cached is CPU-pinned by design: the page-cache acceptance gates
#  (cached ≥ 2× uncached, pack ≤ 5% of cached wall) are host-path
#  properties — measuring them through the tunnel would mix link latency
#  into a disk/pack comparison.
#  ingest_autotune is CPU-pinned for the same reason: the convergence
#  experiment compares host parse/pack rates against themselves.
#  elastic_reshard is host-path by construction: it measures the control
#  plane (tracker + loopback sockets + disk), not the device.
#  ingest_fleet is host-path by construction too: dispatcher, workers and
#  consumer all live on loopback and the consumer drains host frames —
#  the number is wire+lease throughput, no device in the loop.
#  embed_shard is host-path by construction like elastic_reshard: the
#  number is dedup + loopback-exchange + flush throughput over the
#  control plane; the per-batch pooled gather is a CPU-jitted kernel.
HOST_ONLY = {"stream", "csv", "recordio", "cache", "higgs", "ingest_cached",
             "ingest_ragged", "ingest_autotune", "elastic_reshard",
             "ingest_fleet", "ingest_colocated", "embed_shard"}
# superseded in the default order (ingest_scale measures workers_2 too);
# still runnable by explicit name
DEFAULT_SKIP = {"remote_ingest"}

if os.environ.get("DMLC_SUITE_TEST_HANG") == "1":
    # test-only config simulating the r3 wedge (one RPC pending >1h):
    # proves the per-config timeout kills a hung child and the NEXT config
    # still runs (tests/test_bench_probe.py::test_suite_hang_isolation)
    def _bench_hang() -> dict:
        time.sleep(3600)
        return {"metric": "_hang"}

    ALL["_hang"] = (_bench_hang, "_hang")
    HOST_ONLY.add("_hang")


# derived, never hand-maintained: the registry is the single source of truth
METRIC_OF = {name: metric for name, (_, metric) in ALL.items()}


def run_one(name: str) -> None:
    """``--one`` mode: run a single config in THIS process, print its JSON.

    Same platform discipline as the root bench: probe the TPU in a
    subprocess, pin to CPU on failure (the axon register hook overrides
    JAX_PLATFORMS, so the pin must be config-level)."""
    import bench
    if name in CPU_MESH:
        bench.force_cpu()
        platform = "cpu_mesh8"
    elif name in HOST_ONLY:
        bench.force_cpu()
        platform = "host"
    else:
        # the orchestrating parent already probed once and passed the
        # outcome down (DMLC_TPU_OK / DMLC_FORCE_CPU) — re-probing in every
        # child would pay the grant wait per config
        if (os.environ.get("DMLC_TPU_OK") != "1"
                and not bench.probe_tpu()):
            bench.require_tpu_or_exit("cpu")
            bench.force_cpu()
        import jax
        platform = jax.devices()[0].platform
        bench.require_tpu_or_exit(platform)
    log(f"{name}: running on platform={platform}")
    try:
        try:
            r = ALL[name][0]()
        except Exception as e:  # noqa: BLE001 - report and continue
            r = {"metric": METRIC_OF.get(name, name), "error": str(e)}
    finally:
        # flush telemetry in a finally: a scenario that dies mid-run
        # (SIGINT, OOM-killed worker raising SystemExit, a BaseException
        # the reporting path can't survive) is EXACTLY the run whose
        # telemetry you need on disk
        prefix = os.environ.get("DMLC_TELEMETRY_OUT")
        if prefix:
            # per-config observability artifact: the full registry
            # snapshot + Chrome trace of whatever spans the config
            # produced (each config is its own process, so the dump is
            # per-config by construction)
            try:
                from dmlc_core_tpu import telemetry
                telemetry.dump_artifacts(f"{prefix}_{name}")
            except Exception as e:  # noqa: BLE001 — telemetry never
                log(f"telemetry dump failed: {e}")    # fails a run
    r["platform"] = platform
    print(json.dumps(r), flush=True)


def resolve_picks(argv) -> list:
    """Config run list: explicit argv wins verbatim; otherwise the registry
    default order, optionally reordered by DMLC_SUITE_PRIORITY (harvest
    knob: listed configs run first so a short-lived grant reaches the
    never-measured ones, the REST keep their default order — the registry
    stays the single source of truth, so configs added later still run
    even if the env var goes stale; unknown names fail loudly)."""
    picks = list(argv) or [n for n in ALL if n not in DEFAULT_SKIP]
    prio = [p for p in os.environ.get("DMLC_SUITE_PRIORITY", "").split(",")
            if p]
    if prio and not argv:
        unknown = [p for p in prio if p not in ALL]
        if unknown:
            raise SystemExit(f"DMLC_SUITE_PRIORITY names unknown configs: "
                             f"{unknown} (have: {list(ALL)})")
        picks = [p for p in prio if p in picks] + [p for p in picks
                                                   if p not in prio]
    return picks


def main() -> None:
    argv = sys.argv[1:]
    if "--telemetry-out" in argv:
        # ride to the per-config children via env — each child dumps
        # <prefix>_<config>.metrics.json / .trace.json from run_one
        i = argv.index("--telemetry-out")
        os.environ["DMLC_TELEMETRY_OUT"] = argv[i + 1]
        del argv[i:i + 2]
    if argv[:1] == ["--one"]:
        run_one(argv[1])
        return
    picks = resolve_picks(argv)
    # each config runs in its own timeout-bounded subprocess: a wedged
    # tunnel RPC (observed r03: one h2d pending >1h inside fm_train) costs
    # that config, not the rest of the suite — and the claim is released
    # with the child so the next config can re-claim
    timeout_s = int(os.environ.get("DMLC_SUITE_CONFIG_TIMEOUT", "1500"))
    env = dict(os.environ)
    import subprocess
    results = []
    tpu_lost = False
    out = os.environ.get("DMLC_BENCH_SUITE_OUT")

    def write_artifact(platform: str) -> None:
        # rewritten after EVERY config: the harvest wrapper's outer timeout
        # (or a SIGKILL on a wedged child) must not erase the configs that
        # already completed
        if out:
            with open(out, "w") as f:
                json.dump({"platform": platform, "results": results},
                          f, indent=1)

    def platform_of(rs) -> str:
        plats = sorted({r["platform"] for r in rs if "platform" in r})
        return "tpu" if "tpu" in plats else "+".join(plats) or "none"

    # probe ONCE here, hand the outcome to the children via env (probe per
    # child would pay the up-to-20-min grant wait per config)
    if any(p not in CPU_MESH | HOST_ONLY for p in picks):
        import bench
        if bench.probe_tpu():
            env["DMLC_TPU_OK"] = "1"
        else:
            bench.require_tpu_or_exit("cpu")   # exits 9 under REQUIRE
            env["DMLC_FORCE_CPU"] = "1"
    for name in picks:
        if tpu_lost and name not in CPU_MESH | HOST_ONLY:
            r = {"metric": METRIC_OF.get(name, name),
                 "error": "skipped: TPU grant lost earlier"}
            results.append(r)
            print(json.dumps(r), flush=True)
            write_artifact(platform_of(results))
            continue
        log(f"running {name} (isolated, timeout {timeout_s}s) ...")
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", name],
                capture_output=True, text=True, timeout=timeout_s, env=env)
            sys.stderr.write(p.stderr)
            line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                         if ln.startswith("{")), None)
            if p.returncode == 9:
                r = {"metric": METRIC_OF.get(name, name),
                     "error": "no TPU grant (rc 9)"}
                tpu_lost = True      # don't re-pay the probe wait per config
            elif line is None:
                r = {"metric": METRIC_OF.get(name, name),
                     "error": f"no JSON from config (rc {p.returncode})"}
            else:
                r = json.loads(line)
        except subprocess.TimeoutExpired:
            r = {"metric": METRIC_OF.get(name, name),
                 "error": f"timeout after {timeout_s}s (wedged tunnel?)"}
            # a timed-out TPU config usually means the grant vanished and
            # the child wedged in backend init (r04: recordio hung 1500s
            # this way).  A short re-probe (probe_tpu retries once, so up
            # to 2x DMLC_REPROBE_S against a dead tunnel) decides: tunnel
            # dead → skip the remaining TPU configs instead of wedging
            # 1500s each — the loop's next pass re-runs them on a grant.
            # Only when we HAD a grant: on a deliberate-CPU run the
            # timeout is just a slow config, not a lost tunnel.
            if (name not in CPU_MESH | HOST_ONLY
                    and env.get("DMLC_TPU_OK") == "1"):
                import bench
                if bench.probe_tpu(timeout_s=int(
                        os.environ.get("DMLC_REPROBE_S", "120"))):
                    r["error"] += "; TPU still up (slow config)"
                else:
                    tpu_lost = True
                    r["error"] += "; re-probe: grant confirmed lost"
        results.append(r)
        print(json.dumps(r), flush=True)
        write_artifact(platform_of(results))
    platform = platform_of(results)
    if (tpu_lost and platform != "tpu"
            and os.environ.get("DMLC_REQUIRE_TPU") == "1"):
        # nothing reached the chip: propagate the grant-lost contract so
        # the harvest retries instead of committing an all-error artifact
        log("no config reached the TPU → exiting 9")
        if out:
            os.unlink(out) if os.path.exists(out) else None
        sys.exit(9)
    if out:
        log(f"wrote {out}")


if __name__ == "__main__":
    main()
