"""Chip-free pipeline capacity: what the host side can sustain with NO
device link in the way (VERDICT r3 #4).

The tunnel link (~900 MB/s best case, gone on a bad day) caps every
on-chip end-to-end number, so this bench records the number that bounds a
real deployment where the accelerator sits on local PCIe/DMA: how fast
parse → pack → wire can go when the sink costs ~nothing.

Stages measured (all CPU, axon backend dropped so a busy tunnel can't
block):
  parse_only          InputSplit → native chunk parse → CSR RowBlocks
  pack_null           + native pack into fused v2 transfer buffers,
                      buffers recycled, nothing consumed downstream
  pack_compact_null   same with the v3 compact wire (bit-packed ids +
                      dict-coded vals) — the encode cost side of the
                      0.39x byte saving
  loopback            + framing + TCP over 127.0.0.1 + decode to device
                      batches on the CPU backend (the disaggregated
                      ingest wire, minus the real network)
  nt_scaling          native OpenMP chunk parse at nt=1/2/4/...​/cores
                      (reference text_parser.h:100-115 discipline) —
                      the ratio the >=8 GB/s story depends on; on a
                      1-core host the table records that honestly

Emits one JSON object (not the driver's one-line contract — this is a
side artifact, committed as BENCH_capacity_r{N}.json).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA = "/tmp/dmlc_bench_data.libsvm"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench as root_bench
    root_bench.gen_data()
    root_bench.force_cpu()

    from dmlc_core_tpu import native
    if not native.available():
        native.build()
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader

    size_mb = os.path.getsize(DATA) / (1 << 20)
    cores = root_bench.host_cores()
    repeats = int(os.environ.get("DMLC_CAP_REPEATS", "3"))
    out = {"metric": "pipeline_capacity_chip_free", "unit": "MB/s",
           "platform": "cpu", "host_cores": cores, "data_mb": round(size_mb, 1),
           "modes": {}, "nt_scaling": {}}

    def timed(name, fn):
        runs = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            runs.append(size_mb / (time.perf_counter() - t0))
        best = max(runs)
        out["modes"][name] = {"mbps": round(best, 1),
                              "runs": [round(r, 1) for r in runs]}
        log(f"{name}: {best:.1f} MB/s (runs: "
            + ", ".join(f"{r:.1f}" for r in runs) + ")")

    def parse_only():
        p = create_parser(DATA, 0, 1, "libsvm", nthreads=1, threaded=False)
        try:
            for _ in p:
                pass
        finally:
            p.close()

    def pack_null(compact: bool):
        def run():
            loader = DeviceLoader(
                create_parser(DATA, 0, 1, "libsvm", nthreads=1,
                              threaded=False),
                batch_rows=16384, nnz_cap=512 * 1024,
                wire_compact=compact, emit="host")
            try:
                for kind, buf, meta, rows in loader:
                    loader.recycle(buf)   # null sink: recycle immediately
            finally:
                loader.close()
        return run

    def loopback():
        import socket
        import threading
        from dmlc_core_tpu.pipeline.ingest_service import (
            RemoteIngestLoader, serve_ingest)
        # an ephemeral port chosen by the OS would need a side channel;
        # bind a throwaway socket to learn a free port, then reuse it
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        ev = threading.Event()
        th = threading.Thread(
            target=serve_ingest,
            args=(f"file://{DATA}", 0, 1, "libsvm", 16384, 512 * 1024, port),
            kwargs={"host": "127.0.0.1", "max_epochs": 1, "ready_event": ev},
            daemon=True)
        th.start()
        assert ev.wait(30)
        loader = RemoteIngestLoader([("127.0.0.1", port)], batch_rows=16384)
        try:
            for _ in loader:
                pass
        finally:
            loader.close()
        th.join(30)

    timed("parse_only", parse_only)
    timed("pack_null", pack_null(False))
    timed("pack_compact_null", pack_null(True))
    # loopback includes a server thread competing for the same core on a
    # 1-core host — it understates a real 2-host deployment; recorded
    # as-is with that caveat
    repeats_lb = min(repeats, 2)
    runs = []
    for _ in range(repeats_lb):
        t0 = time.perf_counter()
        loopback()
        runs.append(size_mb / (time.perf_counter() - t0))
    out["modes"]["loopback"] = {
        "mbps": round(max(runs), 1), "runs": [round(r, 1) for r in runs],
        "note": "server+trainer share this host's cores; understates a "
                "2-host deployment when cores are scarce"}
    log(f"loopback: {max(runs):.1f} MB/s")

    # nt scaling through the native OpenMP chunk parser, same bytes
    with open(DATA, "rb") as f:
        blob = f.read(64 << 20)
    blob_mb = len(blob) / (1 << 20)
    nts = sorted({1, 2, 4, cores} & set(range(1, cores + 1))) or [1]
    for nt in nts:
        native.parse_libsvm(blob, nthreads=nt)          # warm
        t0 = time.perf_counter()
        native.parse_libsvm(blob, nthreads=nt)
        out["nt_scaling"][str(nt)] = round(
            blob_mb / (time.perf_counter() - t0), 1)
        log(f"nt={nt}: {out['nt_scaling'][str(nt)]} MB/s")
    if cores == 1:
        out["nt_scaling_note"] = (
            "host has 1 core — multi-thread ratios unmeasurable here; "
            "nt>1 rows absent by construction, not by omission")
    base = out["nt_scaling"].get("1")
    if base:
        out["nt_scaling_ratio"] = {
            k: round(v / base, 2) for k, v in out["nt_scaling"].items()}

    dest = os.environ.get("DMLC_CAP_OUT")
    line = json.dumps(out)
    if dest:
        with open(dest, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
