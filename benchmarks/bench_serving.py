"""Serving benchmark: QPS + latency quantiles through the full stack.

Drives client → TCP → server → micro-batcher → bucketed AOT engine on
the CPU backend (the same path a TPU replica runs, minus the device) and
emits one JSON artifact so future PRs can track the latency/throughput
trajectory (committed as BENCH_serving_r{N}.json, same discipline as
BENCH_capacity_r{N}.json).

Sweeps the load axes that matter for a serving replica:

  single        1 connection, depth 1 — pure round-trip latency floor
  pipelined     1 connection, deep pipeline — micro-batcher amortization
  concurrent    N connections — contended throughput (the capacity point)
  concurrent_ragged  the same load through ``ragged=True`` (capacity
                ladder + runtime ``nnz_used`` instead of the 2-D bucket
                grid) — the padding-tax comparison point
  overload      queue bound set tiny — verifies explicit shed, measures
                goodput under 4x admission pressure

``--router`` swaps the sweep for the serving-fleet one (committed as
BENCH_router_r{N}.json): a direct single-replica baseline, the same
load through a router over 1/2/3 replicas (the scaling curve), a
single-connection round-trip pair measuring the hop cost proper (the
≤10% p50 overhead bar), 2× admission pressure over two small-queue
replicas (``shed_pct``), and a rolling restart of all three replicas
under load (``rolling_restart_p99_ms``, zero failed requests).

``--timeline`` swaps the sweep for the sampler-overhead pair (committed
as BENCH_timeline_r{N}.json): back-to-back identical runs with the
time-machine sampler off vs sampling the live registry at 4 Hz —
``timeline_sampler_qps_overhead_pct`` is the acceptance number (< 1%
QPS; ``sampler_budget_ok`` gates it in ``check_regression.py``).

``--ha`` swaps the sweep for the control-plane failover drills
(committed as BENCH_ha_r{N}.json): the journaled fleet registry and the
journaled rabit tracker each run as a subprocess, get SIGKILLed with
state in flight, and are restarted on the same port + journal —
``registry_failover_s`` / ``tracker_failover_s`` measure kill→serving
control RPCs again with the pre-kill state replayed (membership +
heartbeat re-attach for the registry, rank re-admission at the current
generation for the tracker).  Both gate lower-better in
``check_regression.py`` via the "failover" token.

``--c10k`` swaps the sweep for the connection-fabric ladder (committed
as BENCH_c10k_r{N}.json): a router runs as a subprocess (so
``/proc/<pid>/status`` gives honest VmRSS and Threads numbers) in
reactor mode at 1k/5k/20k mostly-idle connections (clamped to the
``ulimit -n`` headroom, with a note when clamped) with a live traffic
subset per rung, plus a thread-per-connection baseline at 1k.
Headlines: ``idle_conns_held`` (higher-better), ``mem_per_conn_kb`` and
``resident_threads`` (both lower-better) — the reactor's thread count
must be O(loops + executor), not O(connections).

Usage: python benchmarks/bench_serving.py [out.json]
                                          [--telemetry-out PREFIX]
                                          [--router] [--timeline] [--ha]
                                          [--c10k]
Env:   DMLC_SERVE_REQUESTS (default 2000), DMLC_SERVE_FEATURES (2^16),
       DMLC_SERVE_MODEL (fm), DMLC_SERVE_DIM (16),
       DMLC_TELEMETRY_OUT (same as --telemetry-out)

``--telemetry-out p`` writes ``p.metrics.json`` (full registry snapshot)
and ``p.trace.json`` (Chrome trace — open in Perfetto) after the sweep;
a short traced predict sequence runs last so the trace carries
correlated client → server → engine spans.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def router_bench(model, params, *, requests: int, features: int):
    """The serving-fleet sweep: router scaling, shed under 2x pressure,
    rolling restart under load.  Returns the scenarios dict + headline
    numbers (callers merge into the artifact)."""
    import contextlib
    import threading

    from dmlc_core_tpu.serving import (InferenceEngine, PredictionServer,
                                       ReplicaRegistry, ServingRouter,
                                       run_load)
    from dmlc_core_tpu.utils.metrics import metrics

    @contextlib.contextmanager
    def env(**kw):
        old = {k: os.environ.get(k) for k in kw}
        os.environ.update({k: str(v) for k, v in kw.items()})
        try:
            yield
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def replica(max_queue=256):
        engine = InferenceEngine(model, params, postprocess="sigmoid")
        # metrics_port=0: ephemeral /healthz so the router reads queue
        # fraction, exactly the production wiring
        return PredictionServer(engine, max_queue=max_queue, warmup=True,
                                metrics_port=0).start()

    def counters(snap):
        return {k: v["value"] for k, v in sorted(snap.items())
                if k.startswith(("serving.router.", "fleet.",
                                 "retry.", "circuit."))
                and "value" in v}

    out = {}

    def finish(name, rep):
        snap = metrics.snapshot()
        rep["router_counters"] = counters(snap)
        out[name] = rep
        log(f"{name}: qps={rep['qps']:.0f} "
            f"p50={rep['latency_ms']['p50']:.2f}ms "
            f"p99={rep['latency_ms']['p99']:.2f}ms ok={rep['ok']} "
            f"shed={rep['overload']} rejected={rep['rejected']}")

    # direct baseline: one replica, no router — the capacity-point
    # comparison for the scaling curve below
    metrics.reset()
    srv = replica()
    try:
        finish("direct", run_load(srv.host, srv.port, requests=requests,
                                  features=features, concurrency=4,
                                  pipeline_depth=16))
    finally:
        srv.stop()

    # hop cost proper: a single-connection round trip, direct vs through
    # the router.  The saturation shapes above co-schedule the router,
    # three engines and the load generator on one interpreter, so their
    # p50 delta measures GIL contention, not the hop — this pair is the
    # ≤10% overhead acceptance bar.
    rt_requests = min(requests, 1500)
    metrics.reset()
    srv = replica()
    try:
        finish("direct_rt", run_load(srv.host, srv.port,
                                     requests=rt_requests,
                                     features=features, concurrency=1,
                                     pipeline_depth=1))
    finally:
        srv.stop()
    metrics.reset()
    srv = replica()
    router = ServingRouter(replicas=[
        (srv.host, srv.port, srv.telemetry.port)]).start()
    try:
        finish("router_rt", run_load(router.host, router.port,
                                     requests=rt_requests,
                                     features=features, concurrency=1,
                                     pipeline_depth=1))
    finally:
        router.stop()
        srv.stop()

    # the same capacity-point load through a static router over 1/2/3
    # replicas — scaling curve + the ≤10% p50 overhead acceptance bar
    for n in (1, 2, 3):
        metrics.reset()
        srvs = [replica() for _ in range(n)]
        router = ServingRouter(replicas=[
            (s.host, s.port, s.telemetry.port) for s in srvs]).start()
        try:
            finish(f"router_{n}",
                   run_load(router.host, router.port, requests=requests,
                            features=features, concurrency=4,
                            pipeline_depth=16))
        finally:
            router.stop()
            for s in srvs:
                s.stop()

    # 2x-capacity admission pressure over two tiny-queue replicas: the
    # router hedges overload rejects across the fleet first, then sheds
    # honestly once the whole fleet is saturated
    metrics.reset()
    srvs = [replica(max_queue=16) for _ in range(2)]
    router = ServingRouter(replicas=[
        (s.host, s.port, s.telemetry.port) for s in srvs]).start()
    try:
        finish("overload_2x",
               run_load(router.host, router.port, requests=requests,
                        features=features, concurrency=8,
                        pipeline_depth=32))
    finally:
        router.stop()
        for s in srvs:
            s.stop()

    # rolling restart: registry-fed router, three replicas restarted one
    # by one (new ports) under a paced closed loop — zero failed requests
    # is the acceptance bar, the p99 is the disruption headline
    metrics.reset()
    reg = ReplicaRegistry(heartbeat_timeout_s=1.0).start()
    rr_requests = max(requests, 2000)
    with env(DMLC_ROUTER_REGISTRY=f"{reg.host}:{reg.port}",
             DMLC_ROUTER_HEARTBEAT="0.1", DMLC_ROUTER_RETRIES="6"):
        srvs = [replica() for _ in range(3)]
        router = ServingRouter(registry=reg.address, sync_s=0.1).start()
        rep = {}
        t = threading.Thread(
            target=lambda: rep.update(
                run_load(router.host, router.port, requests=rr_requests,
                         features=features, concurrency=2,
                         pipeline_depth=1, timeout=120.0)),
            name="bench-rr-load", daemon=True)
        try:
            t.start()
            time.sleep(0.3)
            for i in range(3):
                old = srvs[i]
                old.stop()
                srvs[i] = replica()      # fresh port, auto-registers
                time.sleep(0.5)
            t.join(timeout=180.0)
        finally:
            router.stop()
            for s in srvs:
                s.stop()
            reg.stop()
    rep["requests"] = rr_requests
    finish("rolling_restart", rep)

    headlines = {
        "router_overhead_p50": (
            (out["router_rt"]["latency_ms"]["p50"]
             - out["direct_rt"]["latency_ms"]["p50"])
            / max(out["direct_rt"]["latency_ms"]["p50"], 1e-9)),
        "scaling_qps": {str(n): out[f"router_{n}"]["qps"]
                        for n in (1, 2, 3)},
        "shed_pct": 100.0 * out["overload_2x"]["overload"]
        / max(1, out["overload_2x"]["ok"] + out["overload_2x"]["overload"]),
        "rolling_restart_p99_ms": out["rolling_restart"]["latency_ms"]["p99"],
        "rolling_restart_failed": out["rolling_restart"]["rejected"],
    }
    log(f"router overhead p50: {headlines['router_overhead_p50'] * 100:+.1f}%"
        f"  shed_pct={headlines['shed_pct']:.1f}"
        f"  rolling_restart_p99={headlines['rolling_restart_p99_ms']:.1f}ms"
        f"  failed={headlines['rolling_restart_failed']}")
    return out, headlines


def _spawn_singleton(module: str, **kw):
    """``python -m <module> k=v ...`` — every journaled singleton CLI
    prints one JSON bind line; returns ``(proc, (host, port))``."""
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-m", module] + [f"{k}={v}" for k, v in kw.items()],
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    line = proc.stdout.readline()
    if not line:
        raise RuntimeError(f"{module} subprocess died before binding")
    doc = json.loads(line)
    return proc, (str(doc["host"]), int(doc["port"]))


def _registry_failover(model, params, *, features: int) -> dict:
    """SIGKILL a journaled registry subprocess with two heartbeating
    replicas attached, restart it on the same port + journal, and
    measure kill→membership served again (both replicas replayed)."""
    import shutil
    import signal as _signal
    import tempfile

    from dmlc_core_tpu.serving import (InferenceEngine, PredictionServer,
                                       ReplicaAgent, fleet_rpc)

    tmp = tempfile.mkdtemp(prefix="dmlc_ha_reg_")
    journal = os.path.join(tmp, "registry")
    chaos_env = {"DMLC_ROUTER_BREAKER_COOLDOWN": "0.3",
                 "DMLC_ROUTER_BREAKER_THRESHOLD": "3"}
    saved = {k: os.environ.get(k) for k in chaos_env}
    os.environ.update(chaos_env)
    proc, addr = _spawn_singleton("dmlc_core_tpu.serving.fleet.registry",
                                  port=0, journal=journal,
                                  heartbeat_timeout=5.0)
    pairs = []
    try:
        for _ in range(2):
            engine = InferenceEngine(model, params, postprocess="sigmoid")
            srv = PredictionServer(engine, metrics_port=0).start()
            pairs.append((srv, ReplicaAgent(srv, addr,
                                            interval_s=0.1).start()))

        def members(timeout=2.0):
            try:
                return [r["jobid"] for r in fleet_rpc(
                    addr, {"cmd": "list_replicas"},
                    timeout=timeout)["replicas"]]
            except (OSError, ValueError, KeyError):
                return []

        deadline = time.monotonic() + 60
        while len(members()) < 2:
            if time.monotonic() > deadline:
                raise RuntimeError("replicas never registered")
            time.sleep(0.1)
        roster = sorted(members())
        os.kill(proc.pid, _signal.SIGKILL)
        proc.wait()
        t0 = time.perf_counter()
        proc, addr2 = _spawn_singleton(
            "dmlc_core_tpu.serving.fleet.registry",
            port=addr[1], journal=journal, heartbeat_timeout=5.0)
        assert addr2 == addr
        deadline = time.monotonic() + 60
        while sorted(members()) != roster:
            if time.monotonic() > deadline:
                raise RuntimeError("restarted registry never replayed "
                                   "the membership")
            time.sleep(0.02)
        failover = time.perf_counter() - t0
        return {"failover_s": round(failover, 3), "replicas": len(roster)}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for srv, ag in pairs:
            ag.stop()
            srv.stop()
        proc.kill()
        proc.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def _tracker_failover() -> dict:
    """SIGKILL a journaled tracker subprocess holding an assigned
    two-worker cohort, restart it on the same port + journal, and
    measure kill→both workers re-admitted at their old ranks (current
    generation, no reset)."""
    import shutil
    import signal as _signal
    import socket
    import tempfile
    import threading

    from dmlc_core_tpu.parallel.tracker import recv_json, send_json

    def cmd(addr, msg, timeout=30.0):
        with socket.create_connection(addr, timeout=timeout) as s:
            s.settimeout(timeout)
            send_json(s, msg)
            return recv_json(s.makefile("r"))

    tmp = tempfile.mkdtemp(prefix="dmlc_ha_trk_")
    journal = os.path.join(tmp, "tracker")
    proc, addr = _spawn_singleton("dmlc_core_tpu.parallel.tracker",
                                  port=0, workers=2, journal=journal)
    try:
        replies = {}
        # "start" blocks until the cohort is complete — register both
        # workers concurrently
        ts = [threading.Thread(
            target=lambda j=j, p=p: replies.update(
                {j: cmd(addr, {"cmd": "start", "jobid": j,
                               "host": "127.0.0.1", "port": p})}))
            for j, p in (("w1", 7101), ("w2", 7102))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60.0)
        ranks = {j: replies[j]["rank"] for j in ("w1", "w2")}
        os.kill(proc.pid, _signal.SIGKILL)
        proc.wait()
        t0 = time.perf_counter()
        proc, addr2 = _spawn_singleton("dmlc_core_tpu.parallel.tracker",
                                       port=addr[1], workers=2,
                                       journal=journal)
        assert addr2 == addr
        for jobid, port in (("w1", 7101), ("w2", 7102)):
            r = cmd(addr, {"cmd": "recover", "jobid": jobid,
                           "host": "127.0.0.1", "port": port})
            if r.get("rank") != ranks[jobid] or r.get("generation") != 0:
                raise RuntimeError(f"re-admission broke: {jobid} {r}")
        failover = time.perf_counter() - t0
        return {"failover_s": round(failover, 3), "workers": 2}
    finally:
        proc.kill()
        proc.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def c10k_bench(model, params, *, requests: int, features: int):
    """The connection-fabric ladder (r19): how many mostly-idle
    connections one router process holds, and what each costs in RSS
    and resident threads — reactor vs thread-per-connection, measured
    on a real OS process via ``/proc/<pid>/status``."""
    import resource
    import socket
    import subprocess

    from dmlc_core_tpu.serving import (InferenceEngine, PredictionServer,
                                       run_load)

    nofile = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    # the bench process holds every idle socket itself, plus the live
    # load's connections and the interpreter's own fds — leave headroom
    cap = max(1000, int(nofile) - 4096)
    notes = []
    ladder = []
    for n in (1000, 5000, 20000):
        if n > cap:
            notes.append(f"rung {n} clamped to {cap} (ulimit -n {nofile})")
            n = cap
        if n not in ladder:
            ladder.append(n)
    cores = os.cpu_count() or 1
    if cores < 2:
        notes.append(f"host has {cores} core(s): threaded baseline run "
                     f"at 1k only; p99 numbers measure GIL contention "
                     f"as much as the fabric")

    def proc_status(pid):
        rss = threads = None
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1])          # kB
                elif line.startswith("Threads:"):
                    threads = int(line.split()[1])
        return rss, threads

    def spawn_router(replica_addr, reactor):
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
               "DMLC_SERVE_REACTOR": "1" if reactor else "0"}
        proc = subprocess.Popen(
            [sys.executable, "-m", "dmlc_core_tpu.serving.fleet.router",
             f"replicas={replica_addr}", "host=127.0.0.1", "port=0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, bufsize=1)
        line = proc.stdout.readline()
        if not line.startswith("routing on "):
            proc.kill()
            raise RuntimeError(f"router subprocess died: {line!r}")
        host, port = line.split()[-1].rsplit(":", 1)
        return proc, host, int(port)

    def open_idle(host, port, n):
        conns, failed = [], 0
        for i in range(n):
            try:
                s = socket.create_connection((host, port), timeout=10)
                s.setblocking(False)
                conns.append(s)
            except OSError:
                failed += 1
            if i % 256 == 255:
                time.sleep(0.02)        # let accept batches drain
        return conns, failed

    def sample_still_open(conns, sample=128):
        """A held connection shows EAGAIN, not EOF — spot-check."""
        if not conns:
            return 0, 0
        step = max(1, len(conns) // sample)
        ok = checked = 0
        for s in conns[::step]:
            checked += 1
            try:
                if s.recv(1) != b"":
                    ok += 1             # stray data still means open
            except (BlockingIOError, InterruptedError):
                ok += 1
            except OSError:
                pass
        return ok, checked

    engine = InferenceEngine(model, params, postprocess="sigmoid")
    srv = PredictionServer(engine, warmup=True, metrics_port=0).start()
    addr = f"{srv.host}:{srv.port}"
    # the live subset is a LIGHT closed loop — the C10k shape is
    # thousands of parked connections and a handful of live ones.  A
    # saturating load here would measure how the OS scheduler shares
    # one core between three processes, not the fabric (a single loop
    # thread gets a smaller CFS share than 1000 parked-but-runnable
    # conn threads).  Best-of-3 per rung bounds co-tenant noise, same
    # discipline as --timeline.
    live_requests = min(requests, 800)
    out = {}

    def live_load(host, port):
        return min((run_load(host, port, requests=live_requests,
                             features=features, concurrency=2,
                             pipeline_depth=2) for _ in range(3)),
                   key=lambda r: r["latency_ms"]["p99"])

    # warm the bench process itself (client paths, jax dispatch caches)
    # against the bare replica so the first rung isn't the one paying it
    run_load(srv.host, srv.port, requests=live_requests,
             features=features, concurrency=2, pipeline_depth=2)

    def hold(reactor, n):
        """Spawn a router, warm it, park n idle connections on it, and
        measure the cost — returns the live-load-ready handle."""
        proc, host, port = spawn_router(addr, reactor)
        # pay one-time costs (imports, backend link, first frames)
        # before the RSS baseline so the delta is the connections'
        run_load(host, port, requests=200, features=features,
                 concurrency=2, pipeline_depth=4)
        time.sleep(0.5)
        rss0, thr0 = proc_status(proc.pid)
        t0 = time.monotonic()
        conns, failed = open_idle(host, port, n)
        # threaded mode needs the per-connection threads actually
        # spawned before Threads: means anything
        deadline = time.monotonic() + 30
        while not reactor and time.monotonic() < deadline:
            if proc_status(proc.pid)[1] >= thr0 + len(conns) - 8:
                break
            time.sleep(0.2)
        time.sleep(1.0)
        return {"mode": "reactor" if reactor else "threaded",
                "proc": proc, "host": host, "port": port, "conns": conns,
                "failed": failed, "rss0": rss0,
                "connect_wall_s": round(time.monotonic() - t0, 3)}

    def finish(h, n, live):
        rss1, thr1 = proc_status(h["proc"].pid)
        ok_s, checked = sample_still_open(h["conns"])
        held = int(len(h["conns"]) * ok_s / max(1, checked))
        rep = {
            "mode": h["mode"], "target_conns": n,
            "idle_conns_held": held, "connect_failed": h["failed"],
            "connect_wall_s": h["connect_wall_s"],
            "rss_kb_base": h["rss0"], "rss_kb_loaded": rss1,
            "mem_per_conn_kb": round((rss1 - h["rss0"]) / max(1, held), 2),
            "resident_threads": thr1,
            "live_qps": live["qps"],
            "live_latency_ms": live["latency_ms"],
            "live_ok": live["ok"], "live_rejected": live["rejected"],
        }
        out[f"{h['mode']}_{n}"] = rep
        log(f"{h['mode']}_{n}: held={held}/{n} "
            f"mem/conn={rep['mem_per_conn_kb']:.1f}kB "
            f"threads={thr1} live_p99="
            f"{live['latency_ms']['p99']:.2f}ms")

    def release(h):
        for s in h["conns"]:
            try:
                s.close()
            except OSError:
                pass
        h["proc"].kill()
        h["proc"].wait()

    try:
        # the 1k comparison rung: both fabrics alive AT THE SAME TIME,
        # live reps interleaved — back-to-back arms on a busy host bias
        # whichever runs later (the box quiets as caches warm), and this
        # pair is the p99-parity acceptance number.  The threaded
        # baseline stops at 1k: one thread (and its stack) per held
        # connection — higher rungs would just be slower proof.
        h_r = hold(True, 1000)
        h_t = hold(False, 1000)
        try:
            reps_r, reps_t = [], []
            for _ in range(3):
                reps_r.append(run_load(h_r["host"], h_r["port"],
                                       requests=live_requests,
                                       features=features, concurrency=2,
                                       pipeline_depth=2))
                reps_t.append(run_load(h_t["host"], h_t["port"],
                                       requests=live_requests,
                                       features=features, concurrency=2,
                                       pipeline_depth=2))
            p99 = lambda r: r["latency_ms"]["p99"]  # noqa: E731
            finish(h_r, 1000, min(reps_r, key=p99))
            finish(h_t, 1000, min(reps_t, key=p99))
        finally:
            release(h_r)
            release(h_t)
        # the ladder proper: reactor only, one rung at a time
        for n in ladder[1:]:
            h = hold(True, n)
            try:
                finish(h, n, live_load(h["host"], h["port"]))
            finally:
                release(h)
    finally:
        srv.stop()

    top = f"reactor_{ladder[-1]}"
    headlines = {
        "idle_conns_held": out[top]["idle_conns_held"],
        "mem_per_conn_kb": out[top]["mem_per_conn_kb"],
        "resident_threads": out[top]["resident_threads"],
        "threaded_mem_per_conn_kb": out["threaded_1000"]["mem_per_conn_kb"],
        "threaded_resident_threads": out["threaded_1000"]["resident_threads"],
        "live_p99_ms_reactor_1k":
            out["reactor_1000"]["live_latency_ms"]["p99"],
        "live_p99_ms_threaded_1k":
            out["threaded_1000"]["live_latency_ms"]["p99"],
        "mem_ratio_threaded_over_reactor": round(
            out["threaded_1000"]["mem_per_conn_kb"]
            / max(out[top]["mem_per_conn_kb"], 1e-9), 2),
        "host_cores": cores, "nofile_ulimit": int(nofile),
    }
    log(f"c10k: reactor holds {headlines['idle_conns_held']} conns at "
        f"{headlines['mem_per_conn_kb']:.1f}kB/conn on "
        f"{headlines['resident_threads']} threads; threaded costs "
        f"{headlines['threaded_mem_per_conn_kb']:.1f}kB/conn "
        f"({headlines['mem_ratio_threaded_over_reactor']:.0f}x) on "
        f"{headlines['threaded_resident_threads']} threads at 1k")
    return out, headlines, notes


def ha_bench(model, params, *, features: int):
    """The control-plane HA sweep: one SIGKILL drill per journaled
    singleton (the dispatcher's equivalent lives in bench_suite's
    ``dispatcher_failover_s``).  Returns scenarios + headline numbers."""
    out = {"registry": _registry_failover(model, params,
                                         features=features)}
    log(f"registry failover: {out['registry']['failover_s']:.3f}s")
    out["tracker"] = _tracker_failover()
    log(f"tracker failover: {out['tracker']['failover_s']:.3f}s")
    headlines = {
        "registry_failover_s": out["registry"]["failover_s"],
        "tracker_failover_s": out["tracker"]["failover_s"],
    }
    return out, headlines


def diagnose_bench():
    """``--diagnose``: time a full diagnosis pass over a worst-case
    evidence set — a full 2048-event wide-event ring, a 300-series ×
    300-point history store, and a few thousand span records — the
    r20 acceptance surface (``diagnose_wall_ms``, gated lower-better)."""
    import random

    from dmlc_core_tpu.telemetry import trace as teltrace
    from dmlc_core_tpu.telemetry.diagnose import DiagnosisEngine
    from dmlc_core_tpu.telemetry.timeseries import HistoryStore
    from dmlc_core_tpu.telemetry.wide_events import wide_event, wide_log

    rng = random.Random(20)

    # full ring: 7/8 healthy traffic spread over 3 replicas, 1/8 slow
    # and errored on one — the differencer has real work to do
    wide_log.reset(capacity=2048)
    replicas = ["10.0.0.1:7011", "10.0.0.2:7012", "10.0.0.3:7013"]
    for i in range(2048):
        bad = i % 8 == 0
        wide_event("serving.route",
                   model="bench", replica=replicas[0] if bad
                   else replicas[i % 3],
                   req_id=i, rows=8, nnz=64,
                   outcome="DEADLINE_EXCEEDED" if bad else "OK",
                   attempts=1,
                   dur_ms=rng.uniform(20.0, 30.0) if bad
                   else rng.uniform(0.5, 2.0))
    # events are stamped at emit time — close the window after them
    now = time.time()

    # 300 series × 300 points at 1 s cadence; one series deviates
    # 40 points before the breach onset so lead/lag scans end-to-end
    state = {"t": 0}

    def snap():
        t = state["t"]
        out = {}
        for s in range(300):
            v = 10.0 + (s % 7) + 0.1 * ((t + s) % 5)
            if s == 7 and t >= 220:
                v += 50.0          # the leading suspect
            out[f"bench.s{s}"] = {"type": "gauge", "value": v}
        return out

    store = HistoryStore(snapshot_fn=snap, tiers=[(1.0, 300)])
    base = now - 300.0
    for t in range(300):
        state["t"] = t
        store.sample_once(now=base + t)

    # a few thousand live span records for the critical-path analyzer
    for i in range(2000):
        with teltrace.span(f"bench.op{i % 16}"):
            pass

    engine = DiagnosisEngine(history=store)
    breach = {"rule": "bench.s3:max", "metric": "bench.s3",
              "series": "bench.s3", "severity": "page",
              "window_s": 60.0, "value": 1.0, "max": 0.5}
    scenarios = {}
    walls = []
    for run in range(5):
        t0 = time.perf_counter()
        doc = engine.run(until=now, breach=breach)
        walls.append((time.perf_counter() - t0) * 1e3)
    walls.sort()
    scenarios["diagnose"] = {
        "runs": len(walls), "wall_ms": [round(w, 3) for w in walls],
        "suspects": len(doc["suspects"]),
        "series_scanned": doc["analyzers"]["timeline"]["series_scanned"],
        "events": doc["analyzers"]["wide_events"]["events"],
    }
    wide_log.reset()
    headlines = {"diagnose_wall_ms": round(walls[len(walls) // 2], 3)}
    return scenarios, headlines


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from dmlc_core_tpu.models.cli import MODEL_REGISTRY, TrainParams
    from dmlc_core_tpu.serving import (InferenceEngine, PredictClient,
                                       PredictionServer, run_load)
    from dmlc_core_tpu.utils.metrics import metrics

    argv = sys.argv[1:]
    router_mode = "--router" in argv
    if router_mode:
        argv.remove("--router")
    timeline_mode = "--timeline" in argv
    if timeline_mode:
        argv.remove("--timeline")
    trace_mode = "--trace-overhead" in argv
    if trace_mode:
        argv.remove("--trace-overhead")
    ha_mode = "--ha" in argv
    if ha_mode:
        argv.remove("--ha")
    c10k_mode = "--c10k" in argv
    if c10k_mode:
        argv.remove("--c10k")
    diagnose_mode = "--diagnose" in argv
    if diagnose_mode:
        argv.remove("--diagnose")
    telemetry_prefix = os.environ.get("DMLC_TELEMETRY_OUT")
    if "--telemetry-out" in argv:
        i = argv.index("--telemetry-out")
        telemetry_prefix = argv[i + 1]
        del argv[i:i + 2]

    if diagnose_mode:
        # needs no model — dispatch before the jax build below
        report = {"bench": "diagnose",
                  "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                  "backend": jax.default_backend(), "scenarios": {}}
        scenarios, headlines = diagnose_bench()
        report["scenarios"] = scenarios
        report.update(headlines)
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    requests = int(os.environ.get("DMLC_SERVE_REQUESTS", "2000"))
    features = int(os.environ.get("DMLC_SERVE_FEATURES", str(1 << 16)))
    model_name = os.environ.get("DMLC_SERVE_MODEL", "fm")
    dim = int(os.environ.get("DMLC_SERVE_DIM", "16"))

    p = TrainParams()
    p.init({"data": "bench", "model": model_name,
            "features": str(features), "dim": str(dim)})
    model = MODEL_REGISTRY[p.model](p)
    params = model.init(jax.random.PRNGKey(0))

    report = {
        "bench": ("router" if router_mode
                  else "timeline" if timeline_mode
                  else "trace" if trace_mode
                  else "ha" if ha_mode
                  else "c10k" if c10k_mode else "serving"),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(), "model": model_name,
        "features": features, "dim": dim, "requests": requests,
        "scenarios": {},
    }

    if c10k_mode:
        scenarios, headlines, notes = c10k_bench(model, params,
                                                 requests=requests,
                                                 features=features)
        report["scenarios"] = scenarios
        report.update(headlines)
        report["notes"] = notes
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    if ha_mode:
        scenarios, headlines = ha_bench(model, params, features=features)
        report["scenarios"] = scenarios
        report.update(headlines)
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    if router_mode:
        scenarios, headlines = router_bench(model, params,
                                            requests=requests,
                                            features=features)
        report["scenarios"] = scenarios
        report.update(headlines)
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    def scenario(name, *, max_queue=256, arm_flight=False,
                 arm_timeline=False, engine_kw=None, **load_kw):
        metrics.reset()
        monitor = None
        sampler = None
        flight_dir = None
        if arm_timeline:
            # time-machine sampler over the live registry at 4x the
            # default cadence — measuring the snapshot+extract cost the
            # sampler adds per tick, amplified to show above noise
            from dmlc_core_tpu.telemetry.timeseries import HistoryStore
            sampler = HistoryStore().start(interval_s=0.25)
        if arm_flight:
            # full observability layer on: armed flight recorder + an SLO
            # monitor ticking fast (rule bound high enough to never fire —
            # measuring evaluation cost, not dump cost)
            import tempfile

            from dmlc_core_tpu.telemetry import flight as _flight
            from dmlc_core_tpu.telemetry.anomaly import (SloMonitor,
                                                         parse_slo_spec)
            flight_dir = tempfile.mkdtemp(prefix="bench_flight_")
            _flight.flight_recorder.arm(flight_dir)
            monitor = SloMonitor(
                parse_slo_spec("serving.latency_s:field=p99:max=1000s"),
                interval_s=0.5).start()
        engine = InferenceEngine(model, params, postprocess="sigmoid",
                                 **(engine_kw or {}))
        srv = PredictionServer(engine, max_queue=max_queue,
                               warmup=True).start()
        t0 = time.monotonic()
        try:
            rep = run_load(srv.host, srv.port, requests=requests,
                           features=features, **load_kw)
        finally:
            srv.stop()
            if monitor is not None:
                monitor.stop()
            if sampler is not None:
                sampler.stop()
            if arm_flight:
                from dmlc_core_tpu.telemetry import flight as _flight
                _flight.flight_recorder.disarm()
        rep["compile_count"] = engine.compile_count
        rep["warmup_plus_load_s"] = time.monotonic() - t0
        snap = metrics.snapshot()
        rep["server_latency_ms"] = {
            k: snap["serving.latency_s"][k] * 1e3
            for k in ("p50", "p95", "p99", "mean")}
        rep["batch_occupancy"] = snap["serving.batcher.occupancy"]["value"]
        # FLOP-basis padding tax per request (padded: bucket nnz over true
        # nnz; ragged: 1.0 by construction) — the number the ragged mode
        # exists to retire
        pad = snap.get("serving.engine.padding_ratio")
        rep["padding_ratio"] = pad["mean"] if pad else None
        # the whole registry rides in the artifact so observability data
        # (queue depths, retry counters, latency quantiles) is diffable
        # across rounds without re-running the bench
        rep["registry"] = snap
        # resilience counters: how much retry/reconnect/shed machinery the
        # scenario actually exercised (zero on a healthy run except the
        # overload scenario's sheds)
        rep["resilience"] = {
            k: v["value"] for k, v in sorted(snap.items())
            if k.startswith(("retry.", "circuit.", "faults."))
            or k in ("serving.server.shed", "serving.client.reconnects")}
        report["scenarios"][name] = rep
        log(f"{name}: qps={rep['qps']:.0f} "
            f"p50={rep['latency_ms']['p50']:.2f}ms "
            f"p99={rep['latency_ms']['p99']:.2f}ms ok={rep['ok']} "
            f"shed={rep['overload']}")

    if timeline_mode:
        # sampler overhead: alternated identical runs, time machine off
        # vs sampling at 4 Hz; the acceptance bar is < 1% on QPS.  One
        # run's qps swings ±5% with co-tenant load — far above the 1%
        # signal — so each arm keeps its best of 3 (max over reps bounds
        # one-sided noise; a real sampler cost would depress every rep)
        reps = 3
        for r in range(reps):
            scenario(f"sampler_off_rep{r}", concurrency=1,
                     pipeline_depth=32)
            scenario(f"sampler_on_rep{r}", concurrency=1,
                     pipeline_depth=32, arm_timeline=True)
        for arm in ("sampler_off", "sampler_on"):
            best = max((report["scenarios"].pop(f"{arm}_rep{r}")
                        for r in range(reps)), key=lambda s: s["qps"])
            report["scenarios"][arm] = best
        off = report["scenarios"]["sampler_off"]
        on = report["scenarios"]["sampler_on"]
        off_qps, on_qps = off["qps"], on["qps"]
        report["timeline_sampler_qps_overhead_pct"] = (
            (off_qps - on_qps) / off_qps * 100.0 if off_qps > 0 else 0.0)
        off_p50 = off["latency_ms"]["p50"]
        on_p50 = on["latency_ms"]["p50"]
        report["timeline_sampler_p50_overhead"] = (
            (on_p50 - off_p50) / off_p50 if off_p50 > 0 else 0.0)
        # the gate key: 1 while the sampler stays under 1% of QPS — a
        # later round flipping to 0 is a 100% drop on a higher-better
        # key, which check_regression fails
        report["sampler_budget_ok"] = (
            1.0 if report["timeline_sampler_qps_overhead_pct"] < 1.0
            else 0.0)
        log(f"timeline sampler overhead: qps "
            f"{off_qps:.0f} -> {on_qps:.0f} "
            f"({report['timeline_sampler_qps_overhead_pct']:+.2f}%), p50 "
            f"{off_p50:.3f} -> {on_p50:.3f}ms "
            f"({report['timeline_sampler_p50_overhead'] * 100:+.2f}%)")
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    if trace_mode:
        # tracing overhead: three identical pipelined loads over one
        # connection — untraced wire (ids 0/0, the server opens no
        # span), traced with every span recorded (today's default), and
        # traced through the tail sampler at its production defaults
        # (1% floor, adaptive slow keep) — the configuration this round
        # argues a fleet should run.  Same best-of-3 discipline as
        # --timeline: one run's qps swings with co-tenant noise far
        # above the 1% signal; a real tracing cost depresses every rep.
        import numpy as np

        from dmlc_core_tpu.serving.client import _gen_request
        from dmlc_core_tpu.telemetry import sampling as telsampling
        from dmlc_core_tpu.telemetry import trace as teltrace

        depth = 32
        # sub-1% discrimination needs a longer measured window than the
        # default request count gives — stretch unless the caller already
        # asked for more
        requests = max(requests, 6000)
        report["requests"] = requests
        rng = np.random.default_rng(0)
        canned = [_gen_request(rng, 4, 32, features)
                  for _ in range(min(requests, 512))]

        def trace_run(name, *, traced, floor=None):
            metrics.reset()
            teltrace.recorder.clear()
            if floor is not None:
                telsampling.install(telsampling.TailSampler(floor=floor))
            engine = InferenceEngine(model, params, postprocess="sigmoid")
            srv = PredictionServer(engine, warmup=True).start()
            ok = 0
            try:
                with PredictClient(srv.host, srv.port) as client:
                    inflight = []
                    t0 = time.monotonic()
                    for i in range(requests):
                        if len(inflight) >= depth:
                            inflight.pop(0).result(timeout=60.0)
                            ok += 1
                        ids, vals, row_ptr = canned[i % len(canned)]
                        if traced:
                            # the span ends at submit-return; its context
                            # already rode the wire header, so the server
                            # and engine spans join the trace and the
                            # sampler sees the whole group
                            with teltrace.span("serving.client.predict",
                                               rows=len(row_ptr) - 1):
                                inflight.append(
                                    client.submit(ids, vals, row_ptr))
                        else:
                            inflight.append(
                                client.submit(ids, vals, row_ptr))
                    while inflight:
                        inflight.pop(0).result(timeout=60.0)
                        ok += 1
                    wall = max(time.monotonic() - t0, 1e-9)
            finally:
                srv.stop()
                if floor is not None:
                    telsampling.get_sampler().flush()
                    telsampling.uninstall()
            rep = {"requests": requests, "ok": ok, "wall_s": wall,
                   "qps": ok / wall, "traced": traced,
                   "sampler_floor": floor,
                   "spans_in_ring": len(teltrace.recorder.snapshot())}
            if floor is not None:
                snap = metrics.snapshot()
                rep["sampling"] = {
                    k: v["value"] for k, v in sorted(snap.items())
                    if k.startswith("telemetry.sampling.")}
            report["scenarios"][name] = rep
            log(f"{name}: qps={rep['qps']:.0f} ok={ok} "
                f"ring={rep['spans_in_ring']}")

        reps = 3
        for r in range(reps):
            trace_run(f"untraced_rep{r}", traced=False)
            trace_run(f"traced_all_rep{r}", traced=True)
            trace_run(f"kept_all_rep{r}", traced=True, floor=1.0)
            trace_run(f"traced_tail_rep{r}", traced=True, floor=0.01)
        for arm in ("untraced", "traced_all", "kept_all", "traced_tail"):
            best = max((report["scenarios"].pop(f"{arm}_rep{r}")
                        for r in range(reps)), key=lambda s: s["qps"])
            report["scenarios"][arm] = best
        base = report["scenarios"]["untraced"]["qps"]
        all_q = report["scenarios"]["traced_all"]["qps"]
        kept_q = report["scenarios"]["kept_all"]["qps"]
        tail_q = report["scenarios"]["traced_tail"]["qps"]
        # layer 1 (informational): what instrumenting every request with
        # pure-Python spans costs at microbench request rates
        report["trace_all_qps_overhead_pct"] = (
            (base - all_q) / base * 100.0 if base > 0 else 0.0)
        # layer 2 (informational): the sampler machinery itself —
        # buffer/decide/verdict on every trace, with a floor of 1.0 so
        # every trace is still kept (same ring traffic as layer 1)
        report["trace_sampler_qps_overhead_pct"] = (
            (all_q - kept_q) / all_q * 100.0 if all_q > 0 else 0.0)
        # layer 3, the budgeted number: what tail-DROPPING costs against
        # the same machinery keeping everything.  Dropping must never
        # cost more than keeping — negative is the expectation, since a
        # dropped trace skips the ring entirely
        report["trace_tail_qps_overhead_pct"] = (
            (kept_q - tail_q) / kept_q * 100.0 if kept_q > 0 else 0.0)
        # the gate key: 1 while tail-sampling stays under 1% of the
        # keep-everything configuration — a later round flipping to 0 is
        # a 100% drop on a higher-better key, which check_regression
        # fails
        report["trace_budget_ok"] = (
            1.0 if report["trace_tail_qps_overhead_pct"] < 1.0 else 0.0)
        log(f"trace overhead: untraced {base:.0f} qps, traced "
            f"{all_q:.0f} ({report['trace_all_qps_overhead_pct']:+.2f}%), "
            f"sampler@1.0 {kept_q:.0f} "
            f"({report['trace_sampler_qps_overhead_pct']:+.2f}%), "
            f"tail@0.01 {tail_q:.0f} "
            f"({report['trace_tail_qps_overhead_pct']:+.2f}% vs keep-all)")
        blob = json.dumps(report, indent=2)
        print(blob)
        if argv:
            with open(argv[0], "w") as f:
                f.write(blob + "\n")
            log(f"wrote {argv[0]}")
        return 0

    scenario("single", concurrency=1, pipeline_depth=1)
    scenario("pipelined", concurrency=1, pipeline_depth=32)
    scenario("concurrent", concurrency=4, pipeline_depth=16)
    # same capacity-point load through the ragged engine (ISSUE 6):
    # 3-tier capacity ladder + runtime nnz_used instead of the 2-D bucket
    # grid — compare qps at equal p99 and padding_ratio against
    # "concurrent" above
    scenario("concurrent_ragged", concurrency=4, pipeline_depth=16,
             engine_kw={"ragged": True})
    scenario("overload", concurrency=8, pipeline_depth=32, max_queue=16)
    # flight-recorder overhead: back-to-back identical runs, recorder off
    # vs armed (+SLO monitor at 2Hz); the acceptance bar is <2% on p50
    scenario("recorder_off", concurrency=1, pipeline_depth=32)
    scenario("recorder_on", concurrency=1, pipeline_depth=32,
             arm_flight=True)
    off_p50 = report["scenarios"]["recorder_off"]["latency_ms"]["p50"]
    on_p50 = report["scenarios"]["recorder_on"]["latency_ms"]["p50"]
    report["flight_recorder_p50_overhead"] = (
        (on_p50 - off_p50) / off_p50 if off_p50 > 0 else 0.0)
    log(f"flight recorder p50 overhead: "
        f"{report['flight_recorder_p50_overhead'] * 100:+.2f}% "
        f"({off_p50:.3f}ms -> {on_p50:.3f}ms)")

    ov = report["scenarios"]["overload"]
    report["overload_shed_fraction"] = (
        ov["overload"] / max(1, ov["ok"] + ov["overload"]))
    # headline numbers: the concurrent scenario is the capacity point
    cc = report["scenarios"]["concurrent"]
    report["qps"] = cc["qps"]
    report["latency_ms"] = cc["latency_ms"]
    # ragged-vs-bucket at the capacity point: the ISSUE 6 headline pair —
    # qps at equal (load, p99 budget) plus the padding ratio each engine
    # paid and how many programs it had to compile to serve the sweep
    cr = report["scenarios"]["concurrent_ragged"]
    report["ragged_vs_padded"] = {
        "qps_padded": cc["qps"], "qps_ragged": cr["qps"],
        "p99_ms_padded": cc["latency_ms"]["p99"],
        "p99_ms_ragged": cr["latency_ms"]["p99"],
        "padding_ratio_padded": cc["padding_ratio"],
        "padding_ratio_ragged": cr["padding_ratio"],
        "compiles_padded": cc["compile_count"],
        "compiles_ragged": cr["compile_count"],
    }
    log(f"ragged vs padded: qps {cc['qps']:.0f} -> {cr['qps']:.0f}, "
        f"p99 {cc['latency_ms']['p99']:.2f} -> "
        f"{cr['latency_ms']['p99']:.2f}ms, padding_ratio "
        f"{cc['padding_ratio']:.2f} -> {cr['padding_ratio']:.2f}, "
        f"compiles {cc['compile_count']} -> {cr['compile_count']}")

    if telemetry_prefix:
        # one short SYNCHRONOUS predict sequence: run_load drives async
        # submits (untraced by design), but predict() opens the client
        # span, so these requests give the trace artifact correlated
        # client → server → engine spans
        from dmlc_core_tpu import telemetry
        engine = InferenceEngine(model, params, postprocess="sigmoid")
        srv = PredictionServer(engine, warmup=True).start()
        try:
            with PredictClient(srv.host, srv.port) as client:
                import numpy as np
                rng = np.random.default_rng(0)
                for _ in range(8):
                    n = int(rng.integers(4, 32))
                    client.predict(rng.integers(0, features, n, np.int32),
                                   rng.random(n, np.float32))
        finally:
            srv.stop()
        paths = telemetry.dump_artifacts(telemetry_prefix)
        log(f"telemetry artifacts: {paths['metrics']} {paths['trace']}")

    blob = json.dumps(report, indent=2)
    print(blob)
    if argv:
        with open(argv[0], "w") as f:
            f.write(blob + "\n")
        log(f"wrote {argv[0]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
