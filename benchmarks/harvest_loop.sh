#!/bin/bash
# Poll for an axon tunnel grant all round: run harvest_run.sh until it
# completes with artifacts, retrying on rc 9 (grant lost / never landed).
# The single-tenant claim can queue for a long time behind other tenants,
# so losing one attempt is normal — the loop IS the strategy (docs/perf.md).
#
# Stop condition: /tmp/harvest_stop exists, or all five artifacts landed.
set -u
cd "$(dirname "$0")/.."
# shorter probe budget in loop mode: the loop IS the retry, so cheap
# frequent attempts beat one long wait (a flickering tunnel re-grant is
# easier to catch at ~10-min cadence than ~21-min)
export DMLC_TPU_PROBE_S="${DMLC_TPU_PROBE_S:-240}"
while [ ! -f /tmp/harvest_stop ]; do
    bash benchmarks/harvest_run.sh
    rc=$?
    if [ -s /tmp/bench_suite_tpu.json ] && [ -s /tmp/bench_tpu.json ]; then
        echo "harvest complete (rc=$rc)" >>/tmp/harvest_loop.log
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) harvest attempt rc=$rc — retrying in 60s" \
        >>/tmp/harvest_loop.log
    sleep 60
done
echo "stopped by /tmp/harvest_stop" >>/tmp/harvest_loop.log
