"""Collect on-chip harvest artifacts from /tmp into the repo and digest them.

The TPU tunnel is single-tenant and claims are scarce (see
docs/data.md + bench.py); a background loop polls for a grant and, when one
lands, writes artifacts to /tmp.  This script snapshots them into the repo
with round-stamped names and prints a digest: headline numbers, the config
probe outcome, link characteristics from tpu_diag, and a recommended default
(put_threads / wire_compact / batch size) backed by the measurements.

Usage: python benchmarks/harvest_commit.py [round_tag]   (default r03)
"""

from __future__ import annotations

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARTIFACTS = {
    "/tmp/bench_tpu.json": "BENCH_tpu_{tag}.json",
    "/tmp/bench_tpu_3x.json": "BENCH_tpu_3x_{tag}.json",
    "/tmp/tpu_diag.json": "TPU_DIAG_{tag}.json",
    "/tmp/tpu_micro.json": "TPU_MICRO_{tag}.json",
    "/tmp/bench_suite_tpu.json": "BENCH_suite_{tag}.json",
}


def _load(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def _merge_suite(old: dict, new: dict) -> dict:
    """Per-config union of two suite artifacts: a grant can die mid-suite,
    so artifacts from different windows cover different configs.  A fresher
    measured entry beats an older one; a measured entry NEVER loses to an
    error/skip entry (a later short window must not erase an earlier
    window's real numbers)."""
    if not isinstance(old.get("results"), list):
        return new
    if not isinstance(new.get("results"), list):
        # unparseable/mid-rewrite source: keep the old artifact untouched
        # and tell the caller nothing new landed
        return old
    merged: dict[str, dict] = {r["metric"]: r for r in old["results"]
                               if isinstance(r, dict) and "metric" in r}
    order = list(merged)
    for r in new["results"]:
        if not (isinstance(r, dict) and "metric" in r):
            continue
        prev = merged.get(r["metric"])
        if prev is not None and "error" in r and "error" not in prev:
            continue
        if r["metric"] not in merged:
            order.append(r["metric"])
        merged[r["metric"]] = r
    results = [merged[m] for m in order]
    # same platform-collapse rule as bench_suite.platform_of (keep in sync)
    plats = sorted({r["platform"] for r in results if "platform" in r})
    platform = "tpu" if "tpu" in plats else "+".join(plats) or "none"
    # extra top-level keys (e.g. provenance notes) survive the merge;
    # fresher values win on collision
    extras = {k: v for d in (old, new) for k, v in d.items()
              if k not in ("platform", "results")}
    return {**extras, "platform": platform, "results": results}


def main() -> int:
    tag = sys.argv[1] if len(sys.argv) > 1 else "r03"
    found = {}
    for src, dst_t in ARTIFACTS.items():
        if os.path.exists(src) and os.path.getsize(src) > 2:
            dst = os.path.join(REPO, dst_t.format(tag=tag))
            if dst_t.startswith("BENCH_suite") and os.path.exists(dst):
                fresh = _load(src)
                data = _merge_suite(_load(dst), fresh)
                with open(dst, "w") as f:
                    json.dump(data, f, indent=1)
                found[os.path.basename(dst)] = data
                if isinstance(fresh.get("results"), list):
                    print(f"merged {src} -> {os.path.basename(dst)}")
                else:
                    print(f"SOURCE UNPARSEABLE {src} "
                          f"({fresh.get('error')}) — kept existing "
                          f"{os.path.basename(dst)} unchanged")
                continue
            shutil.copyfile(src, dst)
            found[os.path.basename(dst)] = _load(src)
            print(f"copied {src} -> {os.path.basename(dst)}")
    if not found:
        print("no artifacts found in /tmp — harvest hasn't landed")
        return 1

    print("\n=== digest ===")
    b = found.get(f"BENCH_tpu_{tag}.json")
    if b and "value" in b:
        print(f"headline: {b['value']} MB/s = {b.get('vs_baseline')}x "
              f"baseline on {b.get('platform')} "
              f"(pt={b.get('put_threads')}, compact={b.get('wire_compact')}, "
              f"runs={b.get('runs')})")
    b3 = found.get(f"BENCH_tpu_3x_{tag}.json")
    if b3 and "value" in b3:
        print(f"3x batch:  {b3['value']} MB/s = {b3.get('vs_baseline')}x "
              f"(pt={b3.get('put_threads')}, compact={b3.get('wire_compact')})")
    d = found.get(f"TPU_DIAG_{tag}.json")
    if d and "put_bw" in d:
        bw16 = next((r for r in d["put_bw"] if r.get("mb") == 16), None)
        bw64 = next((r for r in d["put_bw"] if r.get("mb") == 64), None)
        print("link:      " + " ".join(
            f"{r['mb']}MB:{r['mbps']}MB/s" for r in d["put_bw"]))
        print("streams:   " + " ".join(
            f"k={r['streams']}:{r['agg_mbps']}MB/s" for r in d["put_streams"]))
        drift = d.get("put_drift", {}).get("drift_ratio")
        print(f"drift:     last/first quartile = {drift}")
        if bw16 and bw64 and bw64["mbps"] > 1.5 * bw16["mbps"]:
            print("→ per-put overhead dominates: raise DMLC_BENCH_ROWS")
        ks = d.get("put_streams", [])
        if len(ks) >= 2 and ks[-1]["agg_mbps"] > 1.5 * ks[0]["agg_mbps"]:
            print("→ streams scale: keep put_threads probing / raise default")
        up = d.get("unpack", {})
        if "v2" in up and "v3" in up:
            print(f"unpack:    v2 {up['v2']} | v3 {up['v3']}")
    m = found.get(f"TPU_MICRO_{tag}.json")
    if m:
        eb = m.get("embed_bag_pallas_vs_xla", {})
        if eb:
            print("pallas:    " + " ".join(
                f"K={k}:xla {v['xla_us']}us/pallas "
                f"{v['pallas_us'] if v['pallas_us'] is not None else 'FAIL'}"
                f"{'us' if v['pallas_us'] is not None else ''}"
                for k, v in eb.items()))
            wins = [k for k, v in eb.items()
                    if v["pallas_us"] is not None
                    and v["pallas_us"] < v["xla_us"]]
            if wins:
                print(f"→ pallas wins at K∈{{{','.join(wins)}}}: consider "
                      "flipping the _pallas_profitable default from "
                      "measurement")
            elif all(v["pallas_us"] is None for v in eb.values()):
                print("→ pallas never lowered on hardware: keep XLA default")
        sp = m.get("sp_1dev", {})
        pp = m.get("pp_1dev", {})
        if sp or pp:
            print(f"sp/pp 1dev: ring {sp.get('ring_us')}us "
                  f"ulysses {sp.get('ulysses_us')}us "
                  f"gpipe {pp.get('us')}us"
                  + (f" (sp err: {[v for k, v in sp.items() if 'error' in k]})"
                     if any('error' in k for k in sp) else ""))
    s = found.get(f"BENCH_suite_{tag}.json")
    if s and "results" in s:
        cpu_left = [r["metric"] for r in s["results"]
                    if r.get("platform") == "cpu"]
        print(f"suite:     {len(s['results'])} configs on "
              f"{s.get('platform')}; cpu-platform entries: {cpu_left or 'none'}")
    return 0


if __name__ == "__main__":
    main()
