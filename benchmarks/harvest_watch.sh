#!/bin/bash
# Companion to harvest_loop.sh: when a completed harvest lands (root bench
# + suite artifacts in /tmp), snapshot them into the repo with
# harvest_commit.py and commit.  Artifact-only commits — no code.
set -u
cd "$(dirname "$0")/.."
while [ ! -f /tmp/harvest_stop ]; do
    if [ -s /tmp/bench_tpu.json ] && [ -s /tmp/bench_suite_tpu.json ]; then
        python benchmarks/harvest_commit.py r03 >>/tmp/harvest_watch.log 2>&1
        git add BENCH_tpu_r03.json BENCH_tpu_3x_r03.json TPU_DIAG_r03.json \
                TPU_MICRO_r03.json BENCH_suite_r03.json 2>/dev/null
        git commit -q -m "On-chip harvest artifacts (late tunnel re-grant)" \
            >>/tmp/harvest_watch.log 2>&1
        echo "$(date -u +%H:%M:%S) committed harvest artifacts" \
            >>/tmp/harvest_watch.log
        exit 0
    fi
    sleep 120
done
