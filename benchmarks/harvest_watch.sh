#!/bin/bash
# Companion to harvest_loop.sh: when a completed harvest lands (root bench
# + suite artifacts in /tmp), snapshot them into the repo with
# harvest_commit.py and commit.  Artifact-only commits — no code.
# Usage: harvest_watch.sh [round_tag]   (default r04)
set -u
TAG="${1:-r04}"
cd "$(dirname "$0")/.."
while [ ! -f /tmp/harvest_stop ]; do
    if [ -s /tmp/bench_tpu.json ] && [ -s /tmp/bench_suite_tpu.json ]; then
        python benchmarks/harvest_commit.py "$TAG" >>/tmp/harvest_watch.log 2>&1
        git add "BENCH_tpu_${TAG}.json" "BENCH_tpu_3x_${TAG}.json" \
                "TPU_DIAG_${TAG}.json" "TPU_MICRO_${TAG}.json" \
                "BENCH_suite_${TAG}.json" 2>/dev/null
        git commit -q -m "On-chip harvest artifacts (${TAG} granted window)" \
            >>/tmp/harvest_watch.log 2>&1
        echo "$(date -u +%H:%M:%S) committed harvest artifacts" \
            >>/tmp/harvest_watch.log
        exit 0
    fi
    sleep 120
done
